"""Prototype: row-flat fused KV pool [P*2*ps, Hkv, D] decode kernel.

Page p: rows [p*2ps, p*2ps+ps) = K, [p*2ps+ps, (p+1)*2ps) = V. Every DMA is a
plain row-range slice (rank 3), scratch stays rank 4 — the rank >= 5 scratch
of the earlier fused prototypes is what made Mosaic slow.

Usage: python tools/proto_flatfused.py [parity|perf CONFIG]
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

_NEG_INF = -1e30


def _kernel_ff(
    page_tables_ref,  # [B, max_pages] SMEM
    lengths_ref,  # [B] SMEM
    q_ref,  # [group, Hq, D] VMEM
    kv_hbm,  # [P*2*ps, Hkv, D] HBM row-flat fused pool
    out_ref,  # [group, Hq, D] VMEM
    kv_scratch,  # [2, group*C*2*ps, Hkv, D] VMEM
    sems,  # [2, group] DMA
    *, page_size: int, chunk: int, group: int,
):
    ps = page_size
    rows_page = 2 * ps
    C = chunk
    span = C * rows_page
    P = kv_hbm.shape[0] // rows_page
    g0 = pl.program_id(0) * group
    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = kv_hbm.shape[1]
    G = Hq // Hkv

    lengths = [lengths_ref[g0 + j] for j in range(group)]
    n_pages = [jnp.maximum(1, pl.cdiv(lengths[j], ps)) for j in range(group)]
    n_chunks = [pl.cdiv(n_pages[j], C) for j in range(group)]
    max_chunks = n_chunks[0]
    for j in range(1, group):
        max_chunks = jnp.maximum(max_chunks, n_chunks[j])

    qs = [q_ref[j].reshape(Hkv, G, D) for j in range(group)]
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    def chunk_plan(j, c):
        first = page_tables_ref[g0 + j, c * C]
        ok = first + C <= P
        for t in range(1, C):
            idx = c * C + t
            ok &= (idx >= n_pages[j]) | (page_tables_ref[g0 + j, idx] == first + t)
        return first, ok

    def sweep(slot, c, do):
        for j in range(group):
            @pl.when(c < n_chunks[j])
            def _(j=j):
                if C == 1:
                    cp = pltpu.make_async_copy(
                        kv_hbm.at[pl.ds(page_tables_ref[g0 + j, c] * rows_page, rows_page)],
                        kv_scratch.at[slot, pl.ds(j * span, rows_page)],
                        sems.at[slot, j],
                    )
                    cp.start() if do == "start" else cp.wait()
                else:
                    first, ok = chunk_plan(j, c)

                    @pl.when(ok)
                    def _():
                        cp = pltpu.make_async_copy(
                            kv_hbm.at[pl.ds(first * rows_page, span)],
                            kv_scratch.at[slot, pl.ds(j * span, span)],
                            sems.at[slot, j],
                        )
                        cp.start() if do == "start" else cp.wait()

                    @pl.when(~ok)
                    def _():
                        for t in range(C):
                            @pl.when(c * C + t < n_pages[j])
                            def _(t=t):
                                cp = pltpu.make_async_copy(
                                    kv_hbm.at[pl.ds(
                                        page_tables_ref[g0 + j, c * C + t] * rows_page,
                                        rows_page,
                                    )],
                                    kv_scratch.at[slot, pl.ds((j * C + t) * rows_page, rows_page)],
                                    sems.at[slot, j],
                                )
                                cp.start() if do == "start" else cp.wait()

    sweep(0, 0, "start")

    def body(c, carry):
        m, l, acc = carry  # [group, Hkv, G], ..., [group, Hkv, G, D]
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < max_chunks)
        def _():
            sweep(jax.lax.rem(c + 1, 2), c + 1, "start")

        sweep(slot, c, "wait")

        ms, ls, accs = [], [], []
        for j in range(group):
            new_m, new_l, new_acc = m[j], l[j], acc[j]
            for t in range(C):  # per-page flash update (static unroll)
                base = (j * C + t) * rows_page
                k_pg = kv_scratch[slot, base : base + ps]  # [ps, Hkv, D]
                v_pg = kv_scratch[slot, base + ps : base + rows_page]
                kt = jnp.transpose(k_pg, (1, 0, 2))  # [Hkv, ps, D]
                vt = jnp.transpose(v_pg, (1, 0, 2))
                pidx = (c * C + t) * ps
                idx = pidx + jax.lax.broadcasted_iota(jnp.int32, (1, 1, ps), 2)
                vidx = pidx + jax.lax.broadcasted_iota(jnp.int32, (1, ps, 1), 1)
                scores = jax.lax.dot_general(
                    qs[j], kt, (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                ) * scale
                scores = jnp.where(idx < lengths[j], scores, _NEG_INF)
                vt_m = jnp.where(vidx < lengths[j], vt, 0)
                chunk_max = jnp.max(scores, axis=-1)
                m2 = jnp.maximum(new_m, chunk_max)
                corr = jnp.exp(new_m - m2)
                probs = jnp.exp(scores - m2[..., None])
                new_l = new_l * corr + jnp.sum(probs, axis=-1)
                new_acc = new_acc * corr[..., None] + jax.lax.dot_general(
                    probs.astype(kt.dtype), vt_m, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )
                new_m = m2
            ms.append(new_m)
            ls.append(new_l)
            accs.append(new_acc)
        if group == 1:
            return ms[0][None], ls[0][None], accs[0][None]
        return jnp.stack(ms), jnp.stack(ls), jnp.stack(accs)

    m0 = jnp.full((group, Hkv, G), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((group, Hkv, G), jnp.float32)
    acc0 = jnp.zeros((group, Hkv, G, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, max_chunks, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    out_ref[...] = out.reshape(group, Hq, D).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret", "group", "chunk"))
def flatfused(q, kv_pool, page_tables, positions, page_size, interpret=False, group=1, chunk=2):
    B, Hq, D = q.shape
    R, Hkv, _ = kv_pool.shape
    lengths = positions.astype(jnp.int32) + 1
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B // group,),
        in_specs=[
            pl.BlockSpec((group, Hq, D), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((group, Hq, D), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, group * chunk * 2 * page_size, Hkv, D), kv_pool.dtype),
            pltpu.SemaphoreType.DMA((2, group)),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(_kernel_ff, page_size=page_size, chunk=chunk, group=group),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    return kernel(page_tables.astype(jnp.int32), lengths, q, kv_pool)


def to_flat(k_pages, v_pages):
    """[P, ps, Hkv, D] x2 -> [P*2ps, Hkv, D] row-flat fused."""
    P, ps, Hkv, D = k_pages.shape
    kv = jnp.concatenate([k_pages, v_pages], axis=1)  # [P, 2ps, Hkv, D]
    return kv.reshape(P * 2 * ps, Hkv, D)


def parity():
    from dynamo_tpu.ops.attention import paged_decode_attention

    rng = np.random.default_rng(1)
    B, Hq, Hkv, D, PS, P, MP = 8, 16, 8, 128, 32, 64, 8
    k = jnp.asarray(rng.standard_normal((P, PS, Hkv, D)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((P, PS, Hkv, D)) * 0.3, jnp.float32)
    kv = to_flat(k, v)
    q = jnp.asarray(rng.standard_normal((B, Hq, D)) * 0.3, jnp.float32)
    for mode in ["contig", "scatter", "mixed"]:
        pt = np.zeros((B, MP), np.int32)
        lengths = rng.integers(1, PS * MP, B)
        for b in range(B):
            n = -(-int(lengths[b]) // PS)
            if mode == "contig":
                start = rng.integers(1, P - MP)
                pt[b, :n] = start + np.arange(n)
            elif mode == "scatter":
                pt[b, :n] = rng.choice(np.arange(1, P), n, replace=False)
            else:
                half = n // 2
                start = rng.integers(1, P - MP)
                pt[b, :half] = start + np.arange(half)
                pt[b, half:n] = rng.choice(np.arange(1, P), n - half, replace=False)
        positions = jnp.asarray(lengths - 1, jnp.int32)
        ptj = jnp.asarray(pt)
        ref = paged_decode_attention(q, k, v, ptj, positions)
        for g, c in [(1, 1), (1, 2), (1, 4), (2, 2), (4, 1), (4, 2)]:
            out = flatfused(q, kv, ptj, positions, PS, interpret=True, group=g, chunk=c)
            err = float(jnp.max(jnp.abs(out - ref)))
            status = "OK " if err < 1e-3 else "FAIL"
            print(f"{mode:8s} g={g} c={c}: max_err {err:.2e} {status}", flush=True)


def perf(config):
    g, c = map(int, config.split(","))
    B, PS, Hq, Hkv, D, L = 64, 128, 16, 8, 128, 24
    PAGES = 224
    rng = np.random.default_rng(0)
    LP = L * PAGES
    q0 = jnp.asarray(rng.standard_normal((B, Hq, D)) * 0.1, jnp.bfloat16)
    pt = np.zeros((B, 8), np.int32)
    nxt = 1
    for b in range(B):
        for i in range(3):
            pt[b, i] = nxt
            nxt += 1
    ptj = jnp.asarray(pt)
    offsets = jnp.arange(L, dtype=jnp.int32) * PAGES
    pos0 = jnp.full(B, 255, jnp.int32)
    kvp = jnp.asarray(
        rng.standard_normal((LP * 2 * PS, Hkv, D)) * 0.1, jnp.bfloat16
    )

    def kern_harness(num_steps):
        def fn(q, s, pool):
            def step(h, _):
                def layer(hh, off):
                    o = flatfused(hh, pool, off + ptj, pos0, PS, group=g, chunk=c)
                    return (hh + 0.0001 * o).astype(hh.dtype), ()
                h2, _ = jax.lax.scan(layer, h, offsets)
                return h2, ()
            qf, _ = jax.lax.scan(step, q * s, None, length=num_steps)
            return qf
        return jax.jit(fn)

    import itertools
    cnt = itertools.count()

    def best_wall(jf, reps=4):
        t0 = time.perf_counter()
        np.asarray(jax.device_get(jf(q0, jnp.bfloat16(1.0), kvp)))
        print(f"  compile+first: {time.perf_counter()-t0:.1f}s", flush=True)
        best = float("inf")
        for _ in range(reps):
            s = jnp.bfloat16(1.0 + 0.0001 * next(cnt))
            t0 = time.perf_counter()
            np.asarray(jax.device_get(jf(q0, s, kvp)))
            best = min(best, time.perf_counter() - t0)
        return best

    tA = best_wall(kern_harness(8))
    tB = best_wall(kern_harness(64))
    print(f"flatfused g={g} c={c}: N8 {tA*1e3:.1f}ms N64 {tB*1e3:.1f}ms -> {(tB-tA)/56*1e3:6.3f} ms/step", flush=True)


# ---- M1: perseq kernel verbatim, single fused DMA per page ----
def _kernel_m1(
    page_tables_ref, lengths_ref,
    q_ref,      # [1, Hq, D]
    kv_hbm,     # [P*2ps, Hkv, D] row-flat fused
    out_ref,    # [1, Hq, D]
    kv_scratch, # [2, 2*ps, Hkv, D]
    sems,       # [2]
    *, page_size: int,
):
    b = pl.program_id(0)
    ps = page_size
    rows_page = 2 * ps
    length = lengths_ref[b]
    n_pages = jnp.maximum(1, pl.cdiv(length, ps))

    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = kv_hbm.shape[1]
    G = Hq // Hkv

    q = q_ref[0].reshape(Hkv, G, D)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    def dma(slot, i):
        return pltpu.make_async_copy(
            kv_hbm.at[pl.ds(page_tables_ref[b, i] * rows_page, rows_page)],
            kv_scratch.at[slot],
            sems.at[slot],
        )

    dma(0, 0).start()

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)
        next_slot = jax.lax.rem(i + 1, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            dma(next_slot, i + 1).start()

        dma(slot, i).wait()

        k_page = kv_scratch[slot, :ps]
        v_page = kv_scratch[slot, ps:]
        kt = jnp.transpose(k_page, (1, 0, 2))
        vt = jnp.transpose(v_page, (1, 0, 2))

        scores = jax.lax.dot_general(
            q, kt, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * scale
        idx = i * ps + jax.lax.broadcasted_iota(jnp.int32, (1, 1, ps), 2)
        vidx = i * ps + jax.lax.broadcasted_iota(jnp.int32, (1, ps, 1), 1)
        scores = jnp.where(idx < length, scores, _NEG_INF)
        vt = jnp.where(vidx < length, vt, 0)

        chunk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[..., None])
        new_l = l * corr + jnp.sum(probs, axis=-1)
        chunk_out = jax.lax.dot_general(
            probs.astype(kt.dtype), vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        new_acc = acc * corr[..., None] + chunk_out
        return new_m, new_l, new_acc

    m0 = jnp.full((Hkv, G), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, G), jnp.float32)
    acc0 = jnp.zeros((Hkv, G, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_pages, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    out_ref[0] = out.reshape(Hq, D).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def m1(q, kv_pool, page_tables, positions, page_size, interpret=False):
    B, Hq, D = q.shape
    R, Hkv, _ = kv_pool.shape
    lengths = positions.astype(jnp.int32) + 1
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, 2 * page_size, Hkv, D), kv_pool.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(_kernel_m1, page_size=page_size),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    return kernel(page_tables.astype(jnp.int32), lengths, q, kv_pool)


def perf_m1():
    B, PS, Hq, Hkv, D, L = 64, 128, 16, 8, 128, 24
    PAGES = 224
    rng = np.random.default_rng(0)
    LP = L * PAGES
    q0 = jnp.asarray(rng.standard_normal((B, Hq, D)) * 0.1, jnp.bfloat16)
    pt = np.zeros((B, 8), np.int32)
    nxt = 1
    for b in range(B):
        for i in range(3):
            pt[b, i] = nxt
            nxt += 1
    ptj = jnp.asarray(pt)
    offsets = jnp.arange(L, dtype=jnp.int32) * PAGES
    pos0 = jnp.full(B, 255, jnp.int32)
    kvp = jnp.asarray(rng.standard_normal((LP * 2 * PS, Hkv, D)) * 0.1, jnp.bfloat16)

    def kern_harness(num_steps):
        def fn(q, s, pool):
            def step(h, _):
                def layer(hh, off):
                    o = m1(hh, pool, off + ptj, pos0, PS)
                    return (hh + 0.0001 * o).astype(hh.dtype), ()
                h2, _ = jax.lax.scan(layer, h, offsets)
                return h2, ()
            qf, _ = jax.lax.scan(step, q * s, None, length=num_steps)
            return qf
        return jax.jit(fn)

    import itertools
    cnt = itertools.count()

    def best_wall(jf, reps=4):
        t0 = time.perf_counter()
        np.asarray(jax.device_get(jf(q0, jnp.bfloat16(1.0), kvp)))
        print(f"  compile+first: {time.perf_counter()-t0:.1f}s", flush=True)
        best = float("inf")
        for _ in range(reps):
            s = jnp.bfloat16(1.0 + 0.0001 * next(cnt))
            t0 = time.perf_counter()
            np.asarray(jax.device_get(jf(q0, s, kvp)))
            best = min(best, time.perf_counter() - t0)
        return best

    tA = best_wall(kern_harness(8))
    tB = best_wall(kern_harness(64))
    print(f"m1: N8 {tA*1e3:.1f}ms N64 {tB*1e3:.1f}ms -> {(tB-tA)/56*1e3:6.3f} ms/step", flush=True)


if __name__ == "__main__":
    if sys.argv[1] == "parity":
        parity()
    elif sys.argv[1] == "m1":
        perf_m1()
    else:
        perf(sys.argv[2])
