"""Synthesize a fully HF-format Llama checkpoint directory.

Zero-egress environments have no real weights to download, but the SERVING
stack doesn't care about weight values — loading, tokenization, chat
templating, sharding, and throughput behave identically for a random
checkpoint of the same geometry. This builds one end to end:

  config.json           — LlamaForCausalLM at the requested geometry
  model.safetensors     — random-normal weights in HF tensor names/layouts
  tokenizer.json        — a REAL byte-level BPE tokenizer trained in-process
  tokenizer_config.json — chat template + special tokens

Default geometry matches TinyLlama-1.1B (2048 hidden, 22 layers, 32 q / 4 kv
heads, 32000 vocab) so on-chip numbers are comparable to published 1.1B-class
serving results.

Usage: python tools/make_hf_checkpoint.py OUTDIR [--tiny] [--seed N]
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

import numpy as np

TINYLLAMA_GEOMETRY = dict(
    hidden_size=2048,
    intermediate_size=5632,
    num_hidden_layers=22,
    num_attention_heads=32,
    num_key_value_heads=4,
    vocab_size=32000,
)

TINY_GEOMETRY = dict(
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    vocab_size=512,
)

CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "{% if message['role'] == 'system' %}<|system|>\n{{ message['content'] }}</s>\n"
    "{% elif message['role'] == 'user' %}<|user|>\n{{ message['content'] }}</s>\n"
    "{% elif message['role'] == 'assistant' %}<|assistant|>\n{{ message['content'] }}</s>\n"
    "{% endif %}{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>\n{% endif %}"
)


def _train_tokenizer(out: Path, vocab_size: int) -> None:
    """A genuine byte-level BPE tokenizer trained on synthetic text — real
    enough that AutoTokenizer loads it and merges/offsets all behave."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    rng = np.random.default_rng(0)
    words = ["".join(rng.choice(list("abcdefghijklmnopqrstuvwxyz"), size=rng.integers(2, 9)))
             for _ in range(4000)]

    def corpus():
        for _ in range(2000):
            yield " ".join(rng.choice(words, size=rng.integers(4, 30)))

    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=["<s>", "</s>", "<unk>", "<|system|>", "<|user|>", "<|assistant|>"],
        show_progress=False,
    )
    tok.train_from_iterator(corpus(), trainer)
    tok.save(str(out / "tokenizer.json"))
    (out / "tokenizer_config.json").write_text(json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "bos_token": "<s>",
        "eos_token": "</s>",
        "unk_token": "<unk>",
        "chat_template": CHAT_TEMPLATE,
        "model_max_length": 2048,
    }, indent=1))
    (out / "special_tokens_map.json").write_text(json.dumps({
        "bos_token": "<s>", "eos_token": "</s>", "unk_token": "<unk>",
    }))


def make_checkpoint(out_dir: str, geometry: dict | None = None, seed: int = 0) -> Path:
    from safetensors.numpy import save_file

    g = dict(TINYLLAMA_GEOMETRY)
    g.update(geometry or {})
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    head_dim = g["hidden_size"] // g["num_attention_heads"]
    config = {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "hidden_act": "silu",
        "bos_token_id": 1,
        "eos_token_id": 2,
        "max_position_embeddings": 2048,
        "rms_norm_eps": 1e-5,
        "rope_theta": 10000.0,
        "tie_word_embeddings": False,
        "torch_dtype": "bfloat16",
        "head_dim": head_dim,
        **g,
    }
    (out / "config.json").write_text(json.dumps(config, indent=1))

    rng = np.random.default_rng(seed)
    D, I, V = g["hidden_size"], g["intermediate_size"], g["vocab_size"]
    Hq, Hkv = g["num_attention_heads"], g["num_key_value_heads"]

    def w(*shape, scale=0.02):
        return (rng.standard_normal(shape, dtype=np.float32) * scale).astype(np.float16)

    tensors = {
        "model.embed_tokens.weight": w(V, D),
        "model.norm.weight": np.ones(D, np.float16),
        "lm_head.weight": w(V, D),
    }
    for l in range(g["num_hidden_layers"]):
        pre = f"model.layers.{l}."
        tensors[pre + "input_layernorm.weight"] = np.ones(D, np.float16)
        tensors[pre + "post_attention_layernorm.weight"] = np.ones(D, np.float16)
        tensors[pre + "self_attn.q_proj.weight"] = w(Hq * head_dim, D)
        tensors[pre + "self_attn.k_proj.weight"] = w(Hkv * head_dim, D)
        tensors[pre + "self_attn.v_proj.weight"] = w(Hkv * head_dim, D)
        tensors[pre + "self_attn.o_proj.weight"] = w(D, Hq * head_dim)
        tensors[pre + "mlp.gate_proj.weight"] = w(I, D)
        tensors[pre + "mlp.up_proj.weight"] = w(I, D)
        tensors[pre + "mlp.down_proj.weight"] = w(D, I)
    save_file(tensors, str(out / "model.safetensors"))

    _train_tokenizer(out, g["vocab_size"])
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("out_dir")
    ap.add_argument("--tiny", action="store_true", help="tiny geometry for tests")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    out = make_checkpoint(args.out_dir, TINY_GEOMETRY if args.tiny else None, seed=args.seed)
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
