"""Microbenchmark: paged decode attention kernel variants on the real chip.

Headline bench geometry (bench.py): B=64, Hq=16, Hkv=8, D=128, ps=128,
24-layer flat pool (224 pages/layer), context ~256 tokens (2 pages/seq).

Timing method: chain N kernel calls inside one jitted lax.scan (output q feeds
the next call), so per-call time excludes the tunneled-PJRT dispatch RTT.

Usage: python tools/profile_attn.py [B] [ps] [ctx]
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

B = int(sys.argv[1]) if len(sys.argv) > 1 else 64
PS = int(sys.argv[2]) if len(sys.argv) > 2 else 128
CTX = int(sys.argv[3]) if len(sys.argv) > 3 else 256
Hq, Hkv, D = 16, 8, 128
L = 24
PAGES_PER_LAYER = 224
MAX_PAGES = 8  # max_model_len 1024 / ps 128
N_ITERS = 32


def timed(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best / N_ITERS


def main():
    rng = np.random.default_rng(0)
    LP = L * PAGES_PER_LAYER
    k_pages = jnp.asarray(rng.standard_normal((LP, PS, Hkv, D)) * 0.1, jnp.bfloat16)
    v_pages = jnp.asarray(rng.standard_normal((LP, PS, Hkv, D)) * 0.1, jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((B, Hq, D)) * 0.1, jnp.bfloat16)
    n_pages_per_seq = -(-CTX // PS)
    # sequential allocation, like the page allocator's steady state
    pt = np.zeros((B, MAX_PAGES), np.int32)
    nxt = 1
    for b in range(B):
        for i in range(n_pages_per_seq):
            pt[b, i] = nxt
            nxt += 1
    page_tables = jnp.asarray(pt)
    positions = jnp.full(B, CTX - 1, jnp.int32)

    from dynamo_tpu.ops.pallas import paged_attention as pa

    variants = {
        "perseq": pa.paged_decode_attention_pallas,
        "chunked": pa.paged_decode_attention_pallas_chunked,
        "grouped": pa.paged_decode_attention_pallas_grouped,
    }
    if hasattr(pa, "paged_decode_attention_pallas_fused"):
        variants["fused"] = pa.paged_decode_attention_pallas_fused

    results = {}
    for name, kern in variants.items():
        @jax.jit
        def loop(q0, kp, vp, ptab, pos, kern=kern):
            def body(qc, _):
                o = kern(qc, kp, vp, ptab, pos)
                return o, ()
            qf, _ = jax.lax.scan(body, q0, None, length=N_ITERS)
            return qf

        try:
            t = timed(loop, q, k_pages, v_pages, page_tables, positions)
            results[name] = t
            # per decode STEP (x L layers) attention cost
            print(f"{name:10s}: {t*1e6:8.1f} us/call -> {t*L*1e3:6.2f} ms/step (x{L} layers)", flush=True)
        except Exception as e:
            print(f"{name:10s}: FAILED {type(e).__name__}: {str(e)[:200]}", flush=True)

    # roofline context: KV bytes actually needed per call
    kv_bytes = B * n_pages_per_seq * PS * Hkv * D * 2 * 2
    print(f"\nKV traffic/call: {kv_bytes/1e6:.1f} MB -> at 819 GB/s: {kv_bytes/819e9*1e6:.1f} us")
    print(f"DMA issues/call (perseq): {B * n_pages_per_seq * 2}")

    # matmul reference: one [B,2048]x[2048,5632] (the MLP gate shape) per call
    w = jnp.asarray(rng.standard_normal((2048, 5632)) * 0.02, jnp.bfloat16)
    h = jnp.asarray(rng.standard_normal((B, 2048)) * 0.1, jnp.bfloat16)

    @jax.jit
    def mm_loop(h0, w0):
        def body(hc, _):
            o = hc @ w0
            return (o @ w0.T * 1e-3).astype(jnp.bfloat16), ()
        hf, _ = jax.lax.scan(body, h0, None, length=N_ITERS)
        return hf

    t = timed(mm_loop, h, w)
    mm_bytes = 2048 * 5632 * 2 * 2
    print(f"matmul pair [B,2048]x[2048,5632]x2: {t*1e6:.1f} us/iter "
          f"(weight bytes {mm_bytes/1e6:.0f} MB -> floor {mm_bytes/819e9*1e6:.1f} us)")


if __name__ == "__main__":
    main()
