"""Microbenchmark: paged decode attention kernel variants on the real chip.

Headline bench geometry (bench.py): B=64, Hq=16, Hkv=8, D=128, ps=128,
24-layer flat pool (224 pages/layer), context ~256 tokens (2 pages/seq).

Timing method: chain kernel calls inside one jitted lax.scan (output q feeds
the next call) at TWO scan lengths and difference the walls: per-call =
(t_long - t_short) / (N_long - N_short). The r5 session measured a ~100 ms
per-dispatch tunnel RTT that a single wall/N division does NOT cancel —
every variant read ~3.1 ms/call (= RTT/32) while the live engine did whole
24-layer steps in 10 ms; only the two-length difference isolates execution.

Usage: python tools/profile_attn.py [B] [ps] [ctx]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

B = int(sys.argv[1]) if len(sys.argv) > 1 else 64
PS = int(sys.argv[2]) if len(sys.argv) > 2 else 128
CTX = int(sys.argv[3]) if len(sys.argv) > 3 else 256
Hq, Hkv, D = 16, 8, 128
L = 24
PAGES_PER_LAYER = 224
MAX_PAGES = 8  # max_model_len 1024 / ps 128
N_SHORT = 16
N_LONG = 144


def _sync(out):
    # np.asarray of one element forces completion (block_until_ready can
    # return early on the axon platform)
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])


def _wall(fn, *args):
    _sync(fn(*args))  # compile
    best = 1e9
    for _ in range(4):
        t0 = time.perf_counter()
        _sync(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def timed(make_loop, *args):
    """Per-call execution time with the dispatch RTT cancelled: difference
    the walls of two scan lengths."""
    t_short = _wall(make_loop(N_SHORT), *args)
    t_long = _wall(make_loop(N_LONG), *args)
    return max(t_long - t_short, 1e-9) / (N_LONG - N_SHORT)


def _null_kernel(
    page_tables_ref, lengths_ref, q_ref, k_hbm, v_hbm, out_ref,
    k_scratch, v_scratch, sems, *, page_size: int,
):
    """Null hypothesis: perseq's exact grid + 2-page double-buffered DMA
    stream with NO attention math — isolates the irreducible DMA cost."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = pl.program_id(0)
    length = lengths_ref[b]
    n_pages = jnp.maximum(1, pl.cdiv(length, page_size))

    def k_dma(slot, i):
        return pltpu.make_async_copy(
            k_hbm.at[page_tables_ref[b, i]], k_scratch.at[slot], sems.at[slot, 0]
        )

    def v_dma(slot, i):
        return pltpu.make_async_copy(
            v_hbm.at[page_tables_ref[b, i]], v_scratch.at[slot], sems.at[slot, 1]
        )

    k_dma(0, 0).start()
    v_dma(0, 0).start()

    def body(i, acc):
        slot = jax.lax.rem(i, 2)
        next_slot = jax.lax.rem(i + 1, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            k_dma(next_slot, i + 1).start()
            v_dma(next_slot, i + 1).start()

        k_dma(slot, i).wait()
        v_dma(slot, i).wait()
        # consume one lane per page so the waits can't be elided; no matmuls,
        # no softmax, no casts
        return acc + k_scratch[slot, 0].astype(jnp.float32) + v_scratch[slot, 0].astype(jnp.float32)

    Hkv, D = k_hbm.shape[2], k_hbm.shape[3]
    acc = jax.lax.fori_loop(0, n_pages, body, jnp.zeros((Hkv, D), jnp.float32))
    out_ref[0] = jnp.broadcast_to(
        acc[:1] * 1e-6, out_ref.shape[1:]
    ).astype(out_ref.dtype)


def paged_decode_dmaonly(q, k_pages, v_pages, page_tables, positions):
    import functools as ft

    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Hq, D = q.shape
    P, ps, Hkv, _ = k_pages.shape
    lengths = positions.astype(jnp.int32) + 1
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, ps, Hkv, D), k_pages.dtype),
            pltpu.VMEM((2, ps, Hkv, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = pl.pallas_call(
        ft.partial(_null_kernel, page_size=ps),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        grid_spec=grid_spec,
    )
    return kernel(page_tables.astype(jnp.int32), lengths, q, k_pages, v_pages)


def _perseq_variant_kernel(
    page_tables_ref, lengths_ref, q_ref, k_hbm, v_hbm, out_ref,
    k_scratch, v_scratch, sems, *, page_size: int, cast_f32: bool):
    """perseq with the two per-page VPU costs toggled: the f32 casts of the
    whole K/V page and the [ps,Hkv,D]->[Hkv,ps,D] relayout."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _NEG_INF = -1e30
    b = pl.program_id(0)
    length = lengths_ref[b]
    n_pages = jnp.maximum(1, pl.cdiv(length, page_size))

    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = k_hbm.shape[2]
    G = Hq // Hkv

    q = q_ref[0].reshape(Hkv, G, D)
    if cast_f32:
        q = q.astype(jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    def k_dma(slot, i):
        return pltpu.make_async_copy(
            k_hbm.at[page_tables_ref[b, i]], k_scratch.at[slot], sems.at[slot, 0]
        )

    def v_dma(slot, i):
        return pltpu.make_async_copy(
            v_hbm.at[page_tables_ref[b, i]], v_scratch.at[slot], sems.at[slot, 1]
        )

    k_dma(0, 0).start()
    v_dma(0, 0).start()

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)
        next_slot = jax.lax.rem(i + 1, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            k_dma(next_slot, i + 1).start()
            v_dma(next_slot, i + 1).start()

        k_dma(slot, i).wait()
        v_dma(slot, i).wait()

        k_page = k_scratch[slot]  # [ps, Hkv, D]
        v_page = v_scratch[slot]
        if cast_f32:
            k_page = k_page.astype(jnp.float32)
            v_page = v_page.astype(jnp.float32)
        kt = jnp.transpose(k_page, (1, 0, 2))  # [Hkv, ps, D]
        vt = jnp.transpose(v_page, (1, 0, 2))
        scores = jax.lax.dot_general(
            q, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale

        idx = i * page_size + jax.lax.broadcasted_iota(jnp.int32, (1, 1, page_size), 2)
        scores = jnp.where(idx < length, scores, _NEG_INF)

        chunk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[..., None])  # [Hkv, G, ps] f32
        new_l = l * corr + jnp.sum(probs, axis=-1)
        chunk_out = jax.lax.dot_general(
            probs if cast_f32 else probs.astype(vt.dtype), vt,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        new_acc = acc * corr[..., None] + chunk_out
        return new_m, new_l, new_acc

    m0 = jnp.full((Hkv, G), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, G), jnp.float32)
    acc0 = jnp.zeros((Hkv, G, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_pages, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-20)[..., None]
    out_ref[0] = out.reshape(Hq, D).astype(out_ref.dtype)


def make_perseq_variant(cast_f32: bool):
    import functools as ft

    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def run(q, k_pages, v_pages, page_tables, positions):
        B, Hq, D = q.shape
        P, ps, Hkv, _ = k_pages.shape
        lengths = positions.astype(jnp.int32) + 1
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, ps, Hkv, D), k_pages.dtype),
                pltpu.VMEM((2, ps, Hkv, D), v_pages.dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        )
        kernel = pl.pallas_call(
            ft.partial(_perseq_variant_kernel, page_size=ps, cast_f32=cast_f32),
            out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
            grid_spec=grid_spec,
        )
        return kernel(page_tables.astype(jnp.int32), lengths, q, k_pages, v_pages)

    return run


def main():
    rng = np.random.default_rng(0)
    LP = L * PAGES_PER_LAYER
    k_pages = jnp.asarray(rng.standard_normal((LP, PS, Hkv, D)) * 0.1, jnp.bfloat16)
    v_pages = jnp.asarray(rng.standard_normal((LP, PS, Hkv, D)) * 0.1, jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((B, Hq, D)) * 0.1, jnp.bfloat16)
    n_pages_per_seq = -(-CTX // PS)
    # sequential allocation, like the page allocator's steady state
    pt = np.zeros((B, MAX_PAGES), np.int32)
    nxt = 1
    for b in range(B):
        for i in range(n_pages_per_seq):
            pt[b, i] = nxt
            nxt += 1
    page_tables = jnp.asarray(pt)
    positions = jnp.full(B, CTX - 1, jnp.int32)

    from dynamo_tpu.ops.pallas import paged_attention as pa

    # _nt (no-transpose via dot_general batch dims ((0,),(1,))) variants were
    # measured Mosaic-ILLEGAL (remote_compile 500: tpu.matmul requires leading
    # batch dims) — deleted after the r5 A/B; the transpose stays.
    variants = {
        "perseq": pa.paged_decode_attention_pallas,
        "dmaonly": paged_decode_dmaonly,
        "perseq_bf16": make_perseq_variant(cast_f32=False),
        "chunked": pa.paged_decode_attention_pallas_chunked,
        "grouped": pa.paged_decode_attention_pallas_grouped,
    }
    # production cross-program-prefetch kernel (r5 default for GQA decode)
    variants["lookahead"] = pa.paged_decode_attention_pallas_lookahead
    if hasattr(pa, "paged_decode_attention_pallas_fused"):
        variants["fused"] = pa.paged_decode_attention_pallas_fused

    # numerics gate: every variant must agree with perseq before its timing
    # is taken seriously (dmaonly is exempt — it computes garbage by design)
    ref = np.asarray(
        variants["perseq"](q, k_pages, v_pages, page_tables, positions),
        np.float32,
    )
    bad = set()
    for name, kern in variants.items():
        if name in ("perseq", "dmaonly"):
            continue
        try:
            out = np.asarray(kern(q, k_pages, v_pages, page_tables, positions), np.float32)
            err = float(np.max(np.abs(out - ref)))
            print(f"{name:14s}: max|diff vs perseq| = {err:.4f}", flush=True)
            if err > 0.05:
                bad.add(name)
        except Exception as e:
            print(f"{name:14s}: NUMERICS FAILED {type(e).__name__}: {str(e)[:160]}", flush=True)
            bad.add(name)

    results = {}
    for name, kern in variants.items():
        if name in bad:
            print(f"{name:10s}: SKIPPED (failed numerics gate)", flush=True)
            continue
        def make_loop(n, kern=kern):
            @jax.jit
            def loop(q0, kp, vp, ptab, pos):
                def body(qc, _):
                    o = kern(qc, kp, vp, ptab, pos)
                    return o, ()
                qf, _ = jax.lax.scan(body, q0, None, length=n)
                return qf
            return loop

        try:
            t = timed(make_loop, q, k_pages, v_pages, page_tables, positions)
            results[name] = t
            # per decode STEP (x L layers) attention cost
            print(f"{name:10s}: {t*1e6:8.1f} us/call -> {t*L*1e3:6.2f} ms/step (x{L} layers)", flush=True)
        except Exception as e:
            print(f"{name:10s}: FAILED {type(e).__name__}: {str(e)[:200]}", flush=True)

    # roofline context: KV bytes actually needed per call
    kv_bytes = B * n_pages_per_seq * PS * Hkv * D * 2 * 2
    print(f"\nKV traffic/call: {kv_bytes/1e6:.1f} MB -> at 819 GB/s: {kv_bytes/819e9*1e6:.1f} us")
    print(f"DMA issues/call (perseq): {B * n_pages_per_seq * 2}")

    # matmul reference: one [B,2048]x[2048,5632] (the MLP gate shape) per call
    w = jnp.asarray(rng.standard_normal((2048, 5632)) * 0.02, jnp.bfloat16)
    h = jnp.asarray(rng.standard_normal((B, 2048)) * 0.1, jnp.bfloat16)

    def make_mm_loop(n):
        @jax.jit
        def mm_loop(h0, w0):
            def body(hc, _):
                o = hc @ w0
                return (o @ w0.T * 1e-3).astype(jnp.bfloat16), ()
            hf, _ = jax.lax.scan(body, h0, None, length=n)
            return hf
        return mm_loop

    t = timed(make_mm_loop, h, w)
    mm_bytes = 2048 * 5632 * 2 * 2
    print(f"matmul pair [B,2048]x[2048,5632]x2: {t*1e6:.1f} us/iter "
          f"(weight bytes {mm_bytes/1e6:.0f} MB -> floor {mm_bytes/819e9*1e6:.1f} us)")


if __name__ == "__main__":
    main()
