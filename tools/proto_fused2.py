"""Prototype: fused-KV pool with K/V folded into the page-row axis
([P, 2*ps, Hkv, D]) — DMA ranks stay identical to the proven split kernels.
A/B on chip against the split perseq baseline in an in-situ-style harness.

Usage: python tools/proto_fused2.py
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")
from dynamo_tpu.ops.pallas import paged_attention as pa

_NEG_INF = -1e30

B, PS, CTX, Hq, Hkv, D, L = 64, 128, 256, 16, 8, 128, 24
PAGES = 224


def _kernel_f2(
    page_tables_ref, lengths_ref,
    q_ref,      # [group, Hq, D]
    kv_hbm,     # [P, 2*ps, Hkv, D]
    out_ref,    # [group, Hq, D]
    kv_scratch, # [2, group, C, 2*ps, Hkv, D]
    sems,       # [2, group]
    *, page_size: int, chunk: int, group: int,
):
    P = kv_hbm.shape[0]
    g0 = pl.program_id(0) * group
    Hq_, D_ = q_ref.shape[1], q_ref.shape[2]
    Hkv_ = kv_hbm.shape[2]
    G = Hq_ // Hkv_
    C = chunk
    N = C * page_size

    lengths = [lengths_ref[g0 + j] for j in range(group)]
    n_pages = [jnp.maximum(1, pl.cdiv(lengths[j], page_size)) for j in range(group)]
    n_chunks = [pl.cdiv(n_pages[j], C) for j in range(group)]
    max_chunks = n_chunks[0]
    for j in range(1, group):
        max_chunks = jnp.maximum(max_chunks, n_chunks[j])

    qs = [q_ref[j].reshape(Hkv_, G, D_) for j in range(group)]
    scale = 1.0 / jnp.sqrt(jnp.float32(D_))

    def chunk_plan(j, c):
        first = page_tables_ref[g0 + j, c * C]
        ok = first + C <= P
        for t in range(1, C):
            idx = c * C + t
            ok &= (idx >= n_pages[j]) | (page_tables_ref[g0 + j, idx] == first + t)
        return first, ok

    def sweep(slot, c, do):
        for j in range(group):
            @pl.when(c < n_chunks[j])
            def _(j=j):
                if C == 1:
                    cp = pltpu.make_async_copy(
                        kv_hbm.at[page_tables_ref[g0 + j, c]],
                        kv_scratch.at[slot, j, 0],
                        sems.at[slot, j],
                    )
                    cp.start() if do == "start" else cp.wait()
                else:
                    first, ok = chunk_plan(j, c)

                    @pl.when(ok)
                    def _():
                        cp = pltpu.make_async_copy(
                            kv_hbm.at[pl.ds(first, C)],
                            kv_scratch.at[slot, j],
                            sems.at[slot, j],
                        )
                        cp.start() if do == "start" else cp.wait()

                    @pl.when(~ok)
                    def _():
                        for t in range(C):
                            @pl.when(c * C + t < n_pages[j])
                            def _(t=t):
                                cp = pltpu.make_async_copy(
                                    kv_hbm.at[page_tables_ref[g0 + j, c * C + t]],
                                    kv_scratch.at[slot, j, t],
                                    sems.at[slot, j],
                                )
                                cp.start() if do == "start" else cp.wait()

    sweep(0, 0, "start")

    def body(c, carry):
        m, l, acc = carry
        slot = jax.lax.rem(c, 2)
        next_slot = jax.lax.rem(c + 1, 2)

        @pl.when(c + 1 < max_chunks)
        def _():
            sweep(next_slot, c + 1, "start")

        sweep(slot, c, "wait")

        ps = page_size
        idx = c * N + jax.lax.broadcasted_iota(jnp.int32, (1, 1, N), 2)
        vidx = c * N + jax.lax.broadcasted_iota(jnp.int32, (1, N, 1), 1)
        ms, ls, accs = [], [], []
        for j in range(group):
            blk = kv_scratch[slot, j]  # [C, 2ps, Hkv, D]
            k = blk[:, :ps].reshape(N, Hkv_, D_)
            v = blk[:, ps:].reshape(N, Hkv_, D_)
            kt = jnp.transpose(k, (1, 0, 2))
            vt = jnp.transpose(v, (1, 0, 2))
            scores = jax.lax.dot_general(
                qs[j], kt, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ) * scale
            scores = jnp.where(idx < lengths[j], scores, _NEG_INF)
            vt_m = jnp.where(vidx < lengths[j], vt, 0)
            chunk_max = jnp.max(scores, axis=-1)
            new_m = jnp.maximum(m[j], chunk_max)
            corr = jnp.exp(m[j] - new_m)
            probs = jnp.exp(scores - new_m[..., None])
            new_l = l[j] * corr + jnp.sum(probs, axis=-1)
            chunk_out = jax.lax.dot_general(
                probs.astype(kt.dtype), vt_m, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            ms.append(new_m)
            ls.append(new_l)
            accs.append(acc[j] * corr[..., None] + chunk_out)
        if group == 1:
            return ms[0][None], ls[0][None], accs[0][None]
        return jnp.stack(ms), jnp.stack(ls), jnp.stack(accs)

    m0 = jnp.full((group, Hkv_, G), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((group, Hkv_, G), jnp.float32)
    acc0 = jnp.zeros((group, Hkv_, G, D_), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, max_chunks, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    out_ref[...] = out.reshape(group, Hq_, D_).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "group", "chunk"))
def fused2(q, kv_pages, page_tables, positions, interpret=False, group=1, chunk=1):
    B_, Hq_, D_ = q.shape
    P, ps2, Hkv_, _ = kv_pages.shape
    ps = ps2 // 2
    lengths = positions.astype(jnp.int32) + 1
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B_ // group,),
        in_specs=[
            pl.BlockSpec((group, Hq_, D_), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((group, Hq_, D_), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, group, chunk, ps2, Hkv_, D_), kv_pages.dtype),
            pltpu.SemaphoreType.DMA((2, group)),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(_kernel_f2, page_size=ps, chunk=chunk, group=group),
        out_shape=jax.ShapeDtypeStruct((B_, Hq_, D_), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    return kernel(page_tables.astype(jnp.int32), lengths, q, kv_pages)


def main():
    rng = np.random.default_rng(0)
    LP = L * PAGES
    q0 = jnp.asarray(rng.standard_normal((B, Hq, D)) * 0.1, jnp.bfloat16)
    pt = np.zeros((B, 8), np.int32)
    nxt = 1
    for b in range(B):
        for i in range(3):
            pt[b, i] = nxt
            nxt += 1
    ptj = jnp.asarray(pt)
    offsets = jnp.arange(L, dtype=jnp.int32) * PAGES
    pos0 = jnp.full(B, CTX - 1, jnp.int32)

    # correctness first (interpret, CPU-friendly shapes reuse the chip shapes)
    from dynamo_tpu.ops.attention import paged_decode_attention

    kk = jnp.asarray(rng.standard_normal((40, PS, Hkv, D)) * 0.3, jnp.bfloat16)
    vv = jnp.asarray(rng.standard_normal((40, PS, Hkv, D)) * 0.3, jnp.bfloat16)
    kv2 = jnp.concatenate([kk, vv], axis=1)  # [P, 2ps, Hkv, D]
    qq = jnp.asarray(rng.standard_normal((8, Hq, D)) * 0.3, jnp.bfloat16)
    pts = np.zeros((8, 8), np.int32)
    lens = rng.integers(1, PS * 6, 8)
    for b in range(8):
        n = -(-int(lens[b]) // PS)
        if b % 2:
            pts[b, :n] = 1 + b * 4 + np.arange(n)  # contiguous
        else:
            pts[b, :n] = rng.choice(np.arange(1, 40), n, replace=False)
    posn = jnp.asarray(lens - 1, jnp.int32)
    ref = paged_decode_attention(qq, kk, vv, jnp.asarray(pts), posn)
    for g, c in [(1, 1), (1, 2), (2, 2), (1, 4)]:
        out = fused2(qq, kv2, jnp.asarray(pts), posn, interpret=False, group=g, chunk=c)
        err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))))
        print(f"parity g={g} c={c}: {err:.2e}", flush=True)

    def harness(num_steps, g, c):
        def fn(kvpool, q, pos):
            def step(carry, _):
                kvp, qq_, p = carry
                def layer(carry2, off):
                    kvp2, h = carry2
                    phys = off + ptj[jnp.arange(B), p // PS]
                    rows = h.reshape(B, Hq, D)[:, :Hkv] * 0.01
                    kvp2 = kvp2.at[phys, p % PS].set(rows)
                    kvp2 = kvp2.at[phys, PS + p % PS].set(rows)
                    o = fused2(h, kvp2, off + ptj, p, group=g, chunk=c)
                    return (kvp2, (h + 0.0001 * o).astype(h.dtype)), ()
                (kvp, qq_), _ = jax.lax.scan(layer, (kvp, qq_), offsets)
                return (kvp, qq_, p + 1), ()
            (kvpool, q, pos), _ = jax.lax.scan(step, (kvpool, q, pos), None, length=num_steps)
            return q, kvpool
        return jax.jit(fn, donate_argnums=(0,))

    def best_wall(fn, reps=4):
        fn()
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    for g, c in [(1, 1), (1, 2), (2, 1), (2, 2), (1, 4)]:
        st = {"kv": jnp.zeros((LP, 2 * PS, Hkv, D), jnp.bfloat16)}
        try:
            f8 = harness(8, g, c)
            f64 = harness(64, g, c)
            def run_f(jf):
                out, st["kv"] = jf(st["kv"], q0, pos0)
                return np.asarray(jax.device_get(out))
            tA = best_wall(lambda: run_f(f8))
            tB = best_wall(lambda: run_f(f64))
            print(f"fused2 g={g} c={c}: {(tB-tA)/56*1e3:6.3f} ms/step", flush=True)
        except Exception as e:
            print(f"fused2 g={g} c={c}: FAILED {type(e).__name__} {str(e)[:150]}", flush=True)
        del st


if __name__ == "__main__":
    main()
