"""Summarize a DYNTPU_TRACE capture: per-stage latency attribution table.

A capture is JSONL (one Chrome trace event per line — what DYNTPU_TRACE=<path>
appends) or a ``{"traceEvents": [...]}`` document (what the HTTP service's
``/trace`` endpoint returns). Multiple files merge onto one timeline (each
serving process writes its own capture; spans share trace ids).

    python tools/trace_view.py trace.jsonl [more.jsonl ...]
        [--trace-id ID]        only spans of one request's stitched timeline
        [--per-trace]          also print a per-trace breakdown (slowest first)
        [--perfetto out.json]  write a Perfetto/chrome://tracing-loadable file

The per-stage table answers the attribution question directly: for each span
name (engine.queue_wait, engine.prefill, engine.decode.window, rpc.push.*,
disagg.kv_*, http.request, ...), count / total / mean / p50 / p95 / max ms.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_events(paths: list[str]) -> list[dict]:
    events: list[dict] = []
    for path in paths:
        with open(path) as f:
            text = f.read()
        try:
            # whole-document forms: {"traceEvents": [...]} or a bare array
            doc = json.loads(text)
            events.extend(doc.get("traceEvents", []) if isinstance(doc, dict) else doc)
            continue
        except json.JSONDecodeError:
            pass  # JSONL capture: one event per line
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"warning: skipping malformed line in {path}", file=sys.stderr)
    return [e for e in events if e.get("ph") == "X" and "dur" in e]


def _pct(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def stage_table(events: list[dict]) -> list[tuple]:
    """[(name, count, total_ms, mean_ms, p50_ms, p95_ms, max_ms)] by total desc."""
    by_name: dict[str, list[float]] = {}
    for e in events:
        by_name.setdefault(e.get("name", "?"), []).append(e["dur"] / 1e3)
    rows = []
    for name, durs in by_name.items():
        durs.sort()
        total = sum(durs)
        rows.append((
            name, len(durs), total, total / len(durs),
            _pct(durs, 0.5), _pct(durs, 0.95), durs[-1],
        ))
    rows.sort(key=lambda r: -r[2])
    return rows


def print_table(rows: list[tuple], out=sys.stdout) -> None:
    if not rows:
        print("no spans", file=out)
        return
    w = max(len(r[0]) for r in rows)
    hdr = f"{'span':<{w}}  {'count':>6}  {'total_ms':>10}  {'mean_ms':>8}  {'p50_ms':>8}  {'p95_ms':>8}  {'max_ms':>8}"
    print(hdr, file=out)
    print("-" * len(hdr), file=out)
    for name, n, total, mean, p50, p95, mx in rows:
        print(
            f"{name:<{w}}  {n:>6}  {total:>10.1f}  {mean:>8.2f}  {p50:>8.2f}  {p95:>8.2f}  {mx:>8.2f}",
            file=out,
        )


def per_trace_rows(events: list[dict]) -> list[tuple]:
    """[(trace_id, span_count, wall_ms, hops)] slowest wall first. wall is the
    envelope (last end - first start) of the trace's spans across processes."""
    by_trace: dict[str, list[dict]] = {}
    for e in events:
        tid = (e.get("args") or {}).get("trace_id") or "?"
        by_trace.setdefault(tid, []).append(e)
    rows = []
    for tid, evs in by_trace.items():
        start = min(e["ts"] for e in evs)
        end = max(e["ts"] + e["dur"] for e in evs)
        hops = len({e.get("pid") for e in evs})
        rows.append((tid, len(evs), (end - start) / 1e3, hops))
    rows.sort(key=lambda r: -r[2])
    return rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("captures", nargs="+", help="JSONL capture(s) or /trace JSON dump(s)")
    p.add_argument("--trace-id", help="filter to one request's stitched timeline")
    p.add_argument("--per-trace", action="store_true", help="per-trace wall breakdown")
    p.add_argument("--perfetto", metavar="OUT", help="write a Perfetto-loadable JSON file")
    args = p.parse_args(argv)

    events = load_events(args.captures)
    if args.trace_id:
        events = [
            e for e in events
            if (e.get("args") or {}).get("trace_id") == args.trace_id
        ]
    if not events:
        print("no matching spans", file=sys.stderr)
        return 1

    print(f"{len(events)} spans, "
          f"{len({(e.get('args') or {}).get('trace_id') for e in events})} traces, "
          f"{len({e.get('pid') for e in events})} processes\n")
    print_table(stage_table(events))

    if args.per_trace:
        print("\nper-trace wall (slowest first):")
        for tid, n, wall, hops in per_trace_rows(events)[:20]:
            print(f"  {tid}  spans={n} processes={hops} wall={wall:.1f}ms")

    if args.perfetto:
        with open(args.perfetto, "w") as f:
            json.dump({"displayTimeUnit": "ms", "traceEvents": events}, f)
        print(f"\nwrote {args.perfetto} (load in https://ui.perfetto.dev or chrome://tracing)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
