"""Decompose packed-prefill dispatch cost on the real chip.

The bench docstring carried a standing claim — "~10 ms fixed cost per packed
prefill call, roughly flat from 128 to 512 rows" — inferred from section
walls, never measured directly. This tool states and falsifies it with three
independent measurements (tunneled-PJRT safe, same RTT-cancelling tricks as
tools/profile_decode.py and tools/profile_attn.py):

  1. Two-width differencing through the PRODUCTION path: call-count
     differenced walls of runner.prefill_chunk_batch at the 128- and
     512-row buckets fit cost(rows) = fixed + slope*rows, so ``fixed_ms``
     is the rows->0 extrapolation and ``per_row_us`` the marginal row cost.
     Donated kv + an advancing sample key defeat executable/result caching.
  2. Direct stage timings of the SAME call split the fixed cost:
     pack_prefill_lanes (host prep, pure numpy), jnp.asarray staging (H2D),
     and the dispatch-return wall (async return, no sync); the remainder vs
     the steady-state per-call cost is device execution residue.
  3. Null-kernel A/B (methodology ported from tools/profile_attn.py): chain
     paged_prefill_attention_pallas vs paged_prefill_dmaonly inside one
     jitted lax.scan at TWO lengths and difference the walls. The dmaonly
     arm keeps the exact grid + double-buffered page-DMA stream but does no
     math, so its time is the irreducible DMA floor and the difference is
     pure attention compute.

On non-TPU platforms the kernel A/B runs in interpret mode at toy geometry
(smoke only — the printed platform tag says so); the runner-path numbers are
real wall time on whatever platform is active.

Usage: python tools/profile_prefill.py [batch] [page_size] [model_id]
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")
import bench  # noqa: E402  (repo-root bench config = single source of truth)

M_SHORT, M_LONG = 2, 8  # runner-path call counts (differenced)
ROWS_A, ROWS_B = 128, 512  # prefill buckets measured (both in bench_config)


def best_wall(fn, reps=3):
    fn()  # compile / warm caches
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.models.registry import load_model

    bench._probe_pallas()
    B = int(sys.argv[1]) if len(sys.argv) > 1 else bench.HEADLINE[0]
    PS = int(sys.argv[2]) if len(sys.argv) > 2 else bench.HEADLINE[1]
    model_id = sys.argv[3] if len(sys.argv) > 3 else None
    cfg = bench.bench_config(B, PS, model_id=model_id)
    model, params = load_model(cfg.model_id)
    runner = ModelRunner(cfg, model, params)
    platform = jax.devices()[0].platform

    rng = np.random.default_rng(0)
    V = model.config.vocab_size
    pages_b = -(-ROWS_B // cfg.page_size)
    if 1 + pages_b > cfg.num_pages:
        raise SystemExit(f"pool too small: need {1 + pages_b} pages")
    # same table length for both widths so the table bucket (and thus the
    # packed-int geometry other than the row bucket) is identical — the
    # difference isolates the rows term
    page_table = 1 + np.arange(pages_b, dtype=np.int32)
    greedy = SamplingParams()  # temperature 0

    def lane(rows):
        tokens = rng.integers(1, V, size=rows, dtype=np.int32)
        # final chunk of a rows-long prompt: samples a token (device output
        # materially depends on the full forward) and writes the slot-0
        # feedback entry
        return (tokens, 0, page_table, 0, greedy, (), True)

    lanes = {rows: [lane(rows)] for rows in (ROWS_A, ROWS_B)}

    # ---- 1. two-width differencing through the production path ----
    def run_calls(m, rows):
        toks = None
        for _ in range(m):
            # donated kv_cache + advancing sample key: the tunnel cannot
            # serve a cached result, every call really executes
            toks = runner.prefill_chunk_batch(lanes[rows], N=1)
        return int(np.asarray(toks)[0])  # sync once, after the burst

    per_call = {}
    for rows in (ROWS_A, ROWS_B):
        t_short = best_wall(lambda r=rows: run_calls(M_SHORT, r))
        t_long = best_wall(lambda r=rows: run_calls(M_LONG, r))
        per_call[rows] = max(t_long - t_short, 1e-9) / (M_LONG - M_SHORT)

    slope = (per_call[ROWS_B] - per_call[ROWS_A]) / (ROWS_B - ROWS_A)
    fixed_s = per_call[ROWS_A] - slope * ROWS_A

    # ---- 2. direct stage split at the wide bucket ----
    host_prep_s = best_wall(lambda: runner.pack_prefill_lanes(lanes[ROWS_B], 1))
    ints, flts, _, _ = runner.pack_prefill_lanes(lanes[ROWS_B], 1)
    h2d_s = best_wall(
        lambda: jax.block_until_ready((jnp.asarray(ints), jnp.asarray(flts)))
    )
    # async-return wall: host prep + H2D + trace/dispatch, NO device wait
    return_s = best_wall(lambda: runner.prefill_chunk_batch(lanes[ROWS_B], N=1))
    dispatch_s = max(0.0, return_s - host_prep_s - h2d_s)
    device_residue_s = max(0.0, per_call[ROWS_B] - return_s)

    # ---- 3. null-kernel A/B: real attention vs DMA-only ----
    from dynamo_tpu.ops.pallas.prefill_attention import (
        paged_prefill_attention_pallas,
        paged_prefill_dmaonly,
    )

    mc = model.config
    if platform == "tpu":
        T, CTX, ps = 512, 3072, PS
        Hq, Hkv, D = mc.num_heads, getattr(mc, "num_kv_heads", mc.num_heads), mc.head_dim
        block_q, interp = 128, False
        n_s, n_l = 4, 24
    else:
        # interpret-mode smoke: proves the harness runs, not the chip
        T, CTX, ps = 16, 32, 8
        Hq, Hkv, D = 4, 2, 8
        block_q, interp = 8, True
        n_s, n_l = 2, 5
    n_pages = -(-CTX // ps)
    kq = jnp.asarray(rng.standard_normal((T, Hq, D)) * 0.1, jnp.bfloat16)
    k_pages = jnp.asarray(rng.standard_normal((n_pages + 2, ps, Hkv, D)) * 0.1, jnp.bfloat16)
    v_pages = jnp.asarray(rng.standard_normal((n_pages + 2, ps, Hkv, D)) * 0.1, jnp.bfloat16)
    pt = jnp.asarray(1 + np.arange(n_pages, dtype=np.int32) % (n_pages + 1))
    # the LAST chunk of a CTX-long prefill: deepest causal context per row
    pos = jnp.asarray(CTX - T + np.arange(T, dtype=np.int32))

    def make_loop(kern, n):
        @jax.jit
        def loop(q0, kp, vp, ptab, p):
            def body(qc, _):
                o = kern(qc, kp, vp, ptab, p)
                return o.astype(q0.dtype), ()
            qf, _ = jax.lax.scan(body, q0, None, length=n)
            return qf
        return loop

    def timed(kern):
        # dmaonly mirrors the basic (non-lookahead) dispatcher branch, so
        # the main arm pins lookahead=False for a like-for-like grid
        def call(q, kp, vp, ptab, p, kern=kern):
            if kern is paged_prefill_attention_pallas:
                return kern(q, kp, vp, ptab, p, block_q=block_q,
                            interpret=interp, lookahead=False)
            return kern(q, kp, vp, ptab, p, block_q=block_q, interpret=interp)

        def wall(n):
            loop = make_loop(call, n)
            return best_wall(
                lambda: np.asarray(loop(kq, k_pages, v_pages, pt, pos).ravel()[:1])
            )

        return max(wall(n_l) - wall(n_s), 1e-9) / (n_l - n_s)

    attn_s = timed(paged_prefill_attention_pallas)
    dma_s = timed(paged_prefill_dmaonly)

    # ---- roofline: the SHARED estimator (utils/step_anatomy.py), the same
    # arithmetic dynamo_engine_prefill_roofline_fraction prices live ----
    from dynamo_tpu.utils.step_anatomy import roofline_for_runner

    roof = roofline_for_runner(runner, cfg)
    floor_s = roof.prefill_floor_seconds(ROWS_B) if roof is not None else None

    L = getattr(mc, "num_layers", 1)
    out = {
        "platform": platform,
        "B": B, "page_size": PS, "model": cfg.model_id.split(":")[0],
        "per_call_ms": {r: round(per_call[r] * 1e3, 3) for r in per_call},
        "fixed_ms": round(fixed_s * 1e3, 3),  # rows->0 extrapolation
        "per_row_us": round(slope * 1e6, 3),
        "fixed_split_ms": {
            "host_prep": round(host_prep_s * 1e3, 3),
            "h2d_staging": round(h2d_s * 1e3, 3),
            "dispatch": round(dispatch_s * 1e3, 3),
            "device_residue": round(device_residue_s * 1e3, 3),
        },
        "attn_kernel_ab": {
            "geometry": f"T={T} ctx={CTX} Hq={Hq} Hkv={Hkv} D={D} ps={ps}"
                        + (" INTERPRET-SMOKE" if interp else ""),
            "attn_us_per_layer": round(attn_s * 1e6, 1),
            "dma_floor_us_per_layer": round(dma_s * 1e6, 1),
            "attn_minus_dma_us": round((attn_s - dma_s) * 1e6, 1),
            "per_chunk_ms_x_layers": round(attn_s * L * 1e3, 3),
        },
    }
    if floor_s is not None:
        out["roofline"] = {
            "floor_ms_512rows": round(floor_s * 1e3, 3),
            "pct_of_roofline": round(100 * floor_s / per_call[ROWS_B], 1),
            "param_count": roof.param_count,
            "mxu_flops_s": roof.mxu_flops,
        }
    print(out)


if __name__ == "__main__":
    main()
