#!/usr/bin/env bash
# Lint gate, wired next to the tier-1 test command (ROADMAP.md):
#
#   bash tools/lint.sh
#
# Runs ruff with the minimal repo config from pyproject.toml ([tool.ruff]:
# syntax errors, comparison/f-string misuse, undefined names). The hermetic
# CI image has no egress, so when ruff isn't installed the gate degrades to
# a byte-compile pass — syntax rot is still caught, and installing ruff
# upgrades the gate with no script change.
set -euo pipefail
cd "$(dirname "$0")/.."

# metrics self-check: import and validate every Prometheus exposition
# surface without a cluster (promtool-style conformance; no egress needed),
# plus DECLARED_METRIC_FAMILIES == the rendered family set (the runtime half
# of the metric-conformance contract graftlint checks statically below)
JAX_PLATFORMS=cpu python -m dynamo_tpu.utils.prometheus --check

# graftlint: JAX/asyncio-aware static analysis gating the hot path (pure
# stdlib AST — runs on the no-egress image with a bare interpreter). First
# the detectors prove themselves against their seeded fixtures, then the
# repo scan must come back with zero unsuppressed findings.
python -m tools.graftlint --self-check
python -m tools.graftlint

# bench regression gate self-check: the compare tool must flag a synthetic
# regression and pass an identical pair (pure stdlib, no cluster)
python tools/bench_compare.py --self-check

if command -v ruff >/dev/null 2>&1; then
    exec ruff check dynamo_tpu tests tools bench.py
fi
if python -c "import ruff" >/dev/null 2>&1; then
    exec python -m ruff check dynamo_tpu tests tools bench.py
fi
echo "lint: ruff unavailable (no-egress image); falling back to the" \
     "compileall syntax gate" >&2
exec python -m compileall -q dynamo_tpu tests tools bench.py
