"""Prototype: vectorized-group paged decode kernel.

The shipped ``grouped`` kernel amortizes per-grid-program overhead by packing
g sequences per program but pays for it with a Python-unrolled per-sequence
compute body (g small matmuls + g flash updates per page step). This variant
keeps the g-sequence DMA batching and VECTORIZES the compute: one
[g*Hkv, G, ps] batched dot_general per page step, masks/flash state carried
as [g, Hkv, G(,D)] arrays. If the unroll (not the DMA pattern) is what made
``grouped`` lose to ``perseq`` (4.3 vs 12.1 ms/step in the round-4 A/B), this
should close the gap AND cut program count B -> B/g.

Usage: python tools/proto_gvec.py [parity|perf G]
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

_NEG_INF = -1e30


def _kernel_gvec(
    page_tables_ref,  # [B, max_pages] SMEM
    lengths_ref,  # [B] SMEM
    q_ref,  # [g, Hq, D] VMEM
    k_hbm,  # [P, ps, Hkv, D] HBM
    v_hbm,  # [P, ps, Hkv, D] HBM
    out_ref,  # [g, Hq, D] VMEM
    k_scratch,  # [2, g, ps, Hkv, D] VMEM
    v_scratch,  # [2, g, ps, Hkv, D] VMEM
    sems,  # [2, g, 2] DMA
    *,
    page_size: int,
    group: int,
):
    g0 = pl.program_id(0) * group
    ps = page_size
    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = k_hbm.shape[2]
    G = Hq // Hkv
    g = group

    lengths = [lengths_ref[g0 + j] for j in range(g)]
    n_pages = [jnp.maximum(1, pl.cdiv(lengths[j], ps)) for j in range(g)]
    max_n = n_pages[0]
    for j in range(1, g):
        max_n = jnp.maximum(max_n, n_pages[j])
    # [g] vector of lengths for the vectorized masks
    len_vec = jnp.stack(lengths)

    # q: [g, Hq, D] -> [g, Hkv, G, D] (split a middle dim; minor dim intact)
    q = q_ref[...].reshape(g, Hkv, G, D)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    def dma(slot, j, i, which):
        hbm, scratch = (k_hbm, k_scratch) if which == 0 else (v_hbm, v_scratch)
        return pltpu.make_async_copy(
            hbm.at[page_tables_ref[g0 + j, i]],
            scratch.at[slot, j],
            sems.at[slot, j, which],
        )

    def start_all(slot, i):
        for j in range(g):  # static unroll of DMA issue only
            @pl.when(i < n_pages[j])
            def _(j=j):
                dma(slot, j, i, 0).start()
                dma(slot, j, i, 1).start()

    def wait_all(slot, i):
        for j in range(g):
            @pl.when(i < n_pages[j])
            def _(j=j):
                dma(slot, j, i, 0).wait()
                dma(slot, j, i, 1).wait()

    start_all(0, 0)

    def body(i, carry):
        m, l, acc = carry  # [g, Hkv, G], [g, Hkv, G], [g, Hkv, G, D]
        slot = jax.lax.rem(i, 2)
        next_slot = jax.lax.rem(i + 1, 2)

        @pl.when(i + 1 < max_n)
        def _():
            start_all(next_slot, i + 1)

        wait_all(slot, i)

        # [g, ps, Hkv, D] -> [g, Hkv, ps, D]: one middle-dim transpose,
        # NO shape casts (Mosaic rejects merged-dim casts on TPU)
        kt = jnp.transpose(k_scratch[slot], (0, 2, 1, 3))
        vt = jnp.transpose(v_scratch[slot], (0, 2, 1, 3))

        # ONE two-batch-dim contraction: [g, Hkv, G, ps]
        scores = jax.lax.dot_general(
            q, kt, (((3,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32,
        ) * scale
        idx = i * ps + jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, ps), 3)
        valid = idx < len_vec[:, None, None, None]
        scores = jnp.where(valid, scores, _NEG_INF)

        chunk_max = jnp.max(scores, axis=-1)  # [g, Hkv, G]
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[..., None])  # [g, Hkv, G, ps]
        new_l = l * corr + jnp.sum(probs, axis=-1)
        # zero V rows past the length (stale/uninitialized VMEM must not
        # poison acc via 0 * NaN)
        vidx = i * ps + jax.lax.broadcasted_iota(jnp.int32, (1, 1, ps, 1), 2)
        vmask = vidx < len_vec[:, None, None, None]
        vt = jnp.where(vmask, vt, 0)
        # [g, Hkv, G, D] = [g, Hkv, G, ps] x [g, Hkv, ps, D]
        chunk_out = jax.lax.dot_general(
            probs.astype(kt.dtype), vt,
            (((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32,
        )
        new_acc = acc * corr[..., None] + chunk_out
        return new_m, new_l, new_acc

    m0 = jnp.full((g, Hkv, G), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((g, Hkv, G), jnp.float32)
    acc0 = jnp.zeros((g, Hkv, G, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, max_n, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-20)[..., None]
    out_ref[...] = out.reshape(g, Hq, D).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "group"))
def gvec(q, k_pages, v_pages, page_tables, positions, interpret=False, group=8):
    B, Hq, D = q.shape
    P, ps, Hkv, _ = k_pages.shape
    lengths = positions.astype(jnp.int32) + 1
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B // group,),
        in_specs=[
            pl.BlockSpec((group, Hq, D), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((group, Hq, D), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, group, ps, Hkv, D), k_pages.dtype),
            pltpu.VMEM((2, group, ps, Hkv, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, group, 2)),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(_kernel_gvec, page_size=ps, group=group),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    return kernel(page_tables.astype(jnp.int32), lengths, q, k_pages, v_pages)


def parity():
    from dynamo_tpu.ops.attention import paged_decode_attention

    rng = np.random.default_rng(1)
    B, Hq, Hkv, D, PS, P, MP = 8, 16, 8, 128, 32, 64, 8
    k = jnp.asarray(rng.standard_normal((P, PS, Hkv, D)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((P, PS, Hkv, D)) * 0.3, jnp.float32)
    q = jnp.asarray(rng.standard_normal((B, Hq, D)) * 0.3, jnp.float32)
    for mode in ["contig", "scatter"]:
        pt = np.zeros((B, MP), np.int32)
        lengths = rng.integers(1, PS * MP, B)
        for b in range(B):
            n = -(-int(lengths[b]) // PS)
            if mode == "contig":
                start = rng.integers(1, P - MP)
                pt[b, :n] = start + np.arange(n)
            else:
                pt[b, :n] = rng.choice(np.arange(1, P), n, replace=False)
        positions = jnp.asarray(lengths - 1, jnp.int32)
        ptj = jnp.asarray(pt)
        ref = paged_decode_attention(q, k, v, ptj, positions)
        for g in (2, 4, 8):
            out = gvec(q, k, v, ptj, positions, interpret=True, group=g)
            err = float(jnp.max(jnp.abs(out - ref)))
            status = "OK " if err < 1e-3 else "FAIL"
            print(f"{mode:8s} g={g}: max_err {err:.2e} {status}", flush=True)


def perf(g):
    import itertools

    B, PS, Hq, Hkv, D, L = 64, 128, 16, 8, 128, 24
    PAGES = 224
    rng = np.random.default_rng(0)
    LP = L * PAGES
    q0 = jnp.asarray(rng.standard_normal((B, Hq, D)) * 0.1, jnp.bfloat16)
    pt = np.zeros((B, 8), np.int32)
    nxt = 1
    for b in range(B):
        for i in range(3):
            pt[b, i] = nxt
            nxt += 1
    ptj = jnp.asarray(pt)
    offsets = jnp.arange(L, dtype=jnp.int32) * PAGES
    pos0 = jnp.full(B, 255, jnp.int32)
    kp = jnp.asarray(rng.standard_normal((LP, PS, Hkv, D)) * 0.1, jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((LP, PS, Hkv, D)) * 0.1, jnp.bfloat16)

    def harness(num_steps):
        def fn(q, s, kpp, vpp):
            def step(h, _):
                def layer(hh, off):
                    o = gvec(hh, kpp, vpp, off + ptj, pos0, group=g)
                    return (hh + 0.0001 * o).astype(hh.dtype), ()
                h2, _ = jax.lax.scan(layer, h, offsets)
                return h2, ()
            qf, _ = jax.lax.scan(step, q * s, None, length=num_steps)
            return qf
        return jax.jit(fn)

    cnt = itertools.count()

    def best_wall(jf, reps=4):
        t0 = time.perf_counter()
        np.asarray(jax.device_get(jf(q0, jnp.bfloat16(1.0), kp, vp)))
        print(f"  compile+first: {time.perf_counter()-t0:.1f}s", flush=True)
        best = float("inf")
        for _ in range(reps):
            s = jnp.bfloat16(1.0 + 0.0001 * next(cnt))
            t0 = time.perf_counter()
            np.asarray(jax.device_get(jf(q0, s, kp, vp)))
            best = min(best, time.perf_counter() - t0)
        return best

    tA = best_wall(harness(8))
    tB = best_wall(harness(64))
    print(f"gvec g={g}: N8 {tA*1e3:.1f}ms N64 {tB*1e3:.1f}ms -> {(tB-tA)/56*1e3:6.3f} ms/step", flush=True)


if __name__ == "__main__":
    if sys.argv[1] == "parity":
        parity()
    else:
        perf(int(sys.argv[2]))
