#!/usr/bin/env python3
"""dynotop: live fleet dashboard over the metrics component's /cluster/status.

    python tools/dynotop.py --url http://127.0.0.1:9091
    python tools/dynotop.py --url http://127.0.0.1:9091 --once   # one snapshot

Renders one row per worker: health state, heartbeat/staleness, slot and KV
page occupancy, waiting queue, HBM, compile churn, and SLO state — the
operator view of the signals the router/planner consume machine-side.
No third-party deps (urllib + optional curses), so it runs on a bare TPU VM.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

STATE_GLYPH = {
    "ready": "●", "degraded": "◐", "starting": "○", "draining": "◌",
    "migrating": "◎", "dead": "✗", "unknown": "?",
}


def fetch_status(url: str, timeout: float = 2.0) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/cluster/status", timeout=timeout) as r:
        return json.loads(r.read().decode())


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return "?"


def _slo_cell(slo: dict | None) -> str:
    if not slo or not slo.get("metrics"):
        return "-"
    worst = None
    for name, s in slo["metrics"].items():
        if s.get("target_ms") is None:
            continue
        b = s.get("error_budget", 1.0)
        if worst is None or b < worst[1]:
            worst = (name, b)
    if worst is None:
        return "untargeted"
    name, budget = worst
    flag = "OK" if budget > 0 else "BLOWN"
    return f"{name} budget {budget:+.2f} {flag}"


def _format_event(ev: dict) -> str:
    """One recent-events pane line: wall clock, worker, kind, ids, detail."""
    wall = ev.get("wall")
    clock = time.strftime("%H:%M:%S", time.localtime(wall)) if wall else "--:--:--"
    rid = ev.get("request_id", "")
    detail = ev.get("detail") or {}
    kv = " ".join(f"{k}={v}" for k, v in list(detail.items())[:4])
    tenant = ev.get("tenant", "")
    tag = f" [{tenant}]" if tenant else ""
    return (
        f"{clock} {str(ev.get('worker_id', '?')):<10} "
        f"{ev.get('kind', '?'):<26} {rid:<14}{tag} {kv}".rstrip()
    )


def render_status(doc: dict, events_rows: int = 8, events_offset: int = 0) -> str:
    """Pure renderer: /cluster/status JSON -> the dashboard text (testable
    without a cluster; curses and plain mode both draw this).
    ``events_rows``/``events_offset`` size and scroll the recent-events pane
    (offset counts lines back from the newest event)."""
    s = doc.get("summary", {})
    lines = [
        f"dynotop — {doc.get('namespace')}/{doc.get('component')}  "
        f"workers={s.get('workers', 0)} servable={s.get('servable', 0)} "
        f"stale={s.get('stale', 0)} unservable={s.get('unservable', 0)}  "
        f"scrape={doc.get('scrape_interval_s', '?')}s",
        "",
    ]
    header = (
        f"{'WORKER':<12} {'STATE':<10} {'HB':>6} {'SEEN':>6} {'MISS':>4} "
        f"{'SLOTS':>7} {'KV%':>6} {'KVMEM':>11} {'PREFIX':>9} {'RADIX':>7} "
        f"{'SPEC':>10} {'LORA':>11} {'TIER':>9} {'GOODPUT':>9} {'MIG':>7} "
        f"{'QOS':>9} {'EVT':>8} {'COST':>13} {'STEP':>11} {'ROOF':>5} {'PREFILL':>15} {'WAIT':>5} "
        f"{'HBM':>9} {'CMPL':>5}  SLO"
    )
    # router radix-index health (router broadcast via /cluster/status):
    # per-worker indexed-block counts feed the RADIX column; the fleet
    # totals (nodes vs cap, evictions, lookup hit rate) print as a footer
    radix = doc.get("router_radix") or {}
    radix_per_worker = radix.get("per_worker") or {}
    lines.append(header)
    lines.append("-" * len(header))
    for w in doc.get("workers", []):
        health = w.get("health") or {}
        state = health.get("state", "unknown")
        glyph = STATE_GLYPH.get(state, "?")
        kv = w.get("kv_metrics") or {}
        res = w.get("resources") or {}
        slots = f"{kv.get('request_active_slots', 0)}/{kv.get('request_total_slots', 0)}"
        kv_pct = 100.0 * kv.get("kv_active_blocks", 0) / max(1, kv.get("kv_total_blocks", 1))
        # KV pool bytes at the worker's ACTUAL cache dtype (resource gauges
        # carry kv_pool_bytes_*/kv_cache_dtype since the int8 KV cache —
        # the old render assumed bf16 and over-reported int8 workers 2x);
        # workers predating the gauges show "-"
        kv_used = res.get("kv_pool_bytes_used")
        if kv_used is None and res.get("kv_page_bytes"):
            kv_used = res.get("kv_pages_used", 0) * res["kv_page_bytes"]
        dt = str(res.get("kv_cache_dtype", "") or "")
        kv_mem = (
            f"{_fmt_bytes(kv_used)}:{dt[:4]}" if kv_used is not None and dt
            else (_fmt_bytes(kv_used) if kv_used is not None else "-")
        )
        # prefix-cache effectiveness, local vs remote: % of queried blocks
        # served by this worker's own cache vs pulled off fleet peers
        # (prefix_fetch_* counters ride resource_snapshot since the
        # fleet-wide prefix cache; older workers show "-")
        q = res.get("prefix_cache_query_blocks", 0)
        if q:
            lpct = 100.0 * res.get("prefix_cache_hit_blocks", 0) / q
            rpct = 100.0 * res.get("prefix_fetch_blocks", 0) / q
            prefix = f"{lpct:.0f}/{rpct:.0f}%"
        else:
            prefix = "-"
        # speculative decoding: proposer kind + acceptance rate (what the
        # verify passes actually keep), riding resource_snapshot's
        # spec_proposer / spec_acceptance_rate; non-spec workers show "-"
        kind = res.get("spec_proposer")
        if kind:
            spec = f"{str(kind)[:5]} {100.0 * res.get('spec_acceptance_rate', 0):.0f}%"
        else:
            spec = "-"
        # multi-LoRA: resident/capacity device slots + the hottest adapter
        # by admitted sequences (lora_* resource gauges; base-only workers
        # show "-")
        if res.get("lora_capacity"):
            hot = str(res.get("lora_hot", "") or "")[:6]
            lora = f"{res.get('lora_resident', 0)}/{res['lora_capacity']}"
            if hot:
                lora = f"{lora} {hot}"
        else:
            lora = "-"
        # KV tier ladder below HBM (engine/offload.py + engine/kv_store.py
        # via resource_snapshot): host-resident and disk-resident block
        # counts, with disk restore fallbacks flagged; workers without an
        # offload tier (or predating the plane) show "-"
        if res.get("offload_capacity_blocks"):
            tier = f"{res.get('offload_blocks_resident', 0)}h"
            if res.get("disk_budget_bytes") is not None:
                tier = f"{tier}/{res.get('disk_blocks_resident', 0)}d"
                if res.get("disk_io_errors"):
                    tier = f"{tier}!{res['disk_io_errors']}"
        else:
            tier = "-"
        # goodput: windowed fraction of finished requests meeting their
        # TTFT/ITL-p99 budgets (utils/goodput.py via worker stats); workers
        # with an empty window (or predating the plane) show "-"
        gp = w.get("goodput") or {}
        if gp.get("goodput") is not None:
            goodput = f"{100.0 * gp['goodput']:.0f}% ({gp.get('requests', 0)})"
        else:
            goodput = "-"
        # live migration (disagg/migrate.py via resource_snapshot): handoffs
        # OUT of this worker / adoptions IN, with failed handoffs flagged;
        # workers predating the plane (or with no migrations) show "-"
        m_out = res.get("migration_out")
        m_in = res.get("migration_in")
        if m_out or m_in or res.get("migration_out_failed"):
            mig = f"{m_out or 0}>{m_in or 0}"
            if res.get("migration_out_failed"):
                mig = f"{mig}!{res['migration_out_failed']}"
        else:
            mig = "-"
        # multi-tenant QoS (utils/qos.py via resource_snapshot): running
        # lanes per priority class (c/s/b) with cumulative shed count
        # flagged; workers predating the plane (or with QoS disabled and no
        # activity) show "-"
        qos_res = res.get("qos") or {}
        running = qos_res.get("running") or {}
        # per-class SLO state (utils/slo.py priority-keyed series): a class
        # letter gains "*" when any of its targeted metrics blew its error
        # budget — one glance says WHICH class is hurting, not just that
        # the aggregate is
        prio_slo = (w.get("slo") or {}).get("priorities") or {}

        def _blown(cls: str) -> str:
            states = prio_slo.get(cls) or {}
            return "*" if any(
                s.get("target_ms") is not None
                and s.get("error_budget", 1.0) <= 0
                for s in states.values()
            ) else ""

        if qos_res:
            qos = "/".join(
                f"{running.get(c, 0)}{c[0]}{_blown(c)}"
                for c in ("critical", "standard", "batch")
            )
            if qos_res.get("sheds"):
                qos = f"{qos}!{qos_res['sheds']}"
        else:
            qos = "-"
        # flight recorder (utils/events.py via worker stats): lifetime events
        # journaled, with pinned forensic captures flagged; workers predating
        # the plane show "-"
        ev = w.get("events") or {}
        if ev.get("emitted") is not None:
            evt = str(ev["emitted"])
            if ev.get("captures"):
                evt = f"{evt}!{ev['captures']}p"
        else:
            evt = "-"
        # cost attribution (utils/metering.py via worker stats): attributed
        # device-seconds total + the hottest tenant by device burn; workers
        # predating the metering plane (or with it off) show "-"
        costs = w.get("costs") or {}
        if costs.get("device_s_total") is not None:
            cost = f"{costs['device_s_total']:.1f}s"
            top = str(costs.get("top_tenant", "") or "")[:6]
            if top:
                cost = f"{cost} {top}"
        else:
            cost = "-"
        # step anatomy (utils/step_anatomy.py via resource_snapshot): STEP =
        # host-side fraction of attributed engine time + the decode-window
        # dispatch cadence p50; ROOF = HBM floor over measured decode seconds
        # (the r5 "69.8% of roofline" number, live). Pre-plane workers: "-"
        anat = res.get("step_anatomy") or {}
        step = "-"
        if anat.get("host_frac") is not None:
            step = f"h{100.0 * anat['host_frac']:.0f}%"
            gap = anat.get("dispatch_gap_ms_p50")
            if gap is not None:
                step = f"{step} {gap:.1f}ms"
        roof = (
            f"{100.0 * anat['roofline_frac']:.0f}%"
            if anat.get("roofline_frac") is not None else "-"
        )
        # PREFILL: host-side fraction of prefill dispatch time + the
        # rows-amortized per-call fixed cost + the prefill roofline fraction
        # (max(MXU-FLOP, bytes) floor over measured — see
        # tools/profile_prefill.py for the offline decomposition). Workers
        # predating the prefill plane (r19) show "-"
        prefill = "-"
        if anat.get("prefill_host_frac") is not None:
            prefill = f"h{100.0 * anat['prefill_host_frac']:.0f}%"
            fx = anat.get("prefill_fixed_ms")
            if fx is not None:
                prefill = f"{prefill} {fx:.1f}ms"
            pr = anat.get("prefill_roofline_frac")
            if pr is not None:
                prefill = f"{prefill} {100.0 * pr:.0f}%"
        # RADIX: blocks this worker has indexed in the router's radix tree
        # (its advertised prefix-cache footprint); "-" until the router has
        # broadcast index health
        radix_cell = radix_per_worker.get(str(w.get("worker_id", "")), None)
        radix_cell = str(radix_cell) if radix_cell is not None else "-"
        hb = health.get("heartbeat_age_s")
        stale_mark = " STALE" if w.get("stale") else ""
        lines.append(
            f"{w.get('worker_id', '?'):<12} {glyph} {state:<8} "
            f"{(f'{hb:.1f}s' if hb is not None else '-'):>6} "
            f"{w.get('last_seen_s', 0):>5.1f}s {w.get('missed_scrapes', 0):>4} "
            f"{slots:>7} {kv_pct:>5.1f}% {kv_mem:>11} {prefix:>9} "
            f"{radix_cell:>7} {spec:>10} "
            f"{lora:>11} {tier:>9} {goodput:>9} {mig:>7} {qos:>9} {evt:>8} "
            f"{cost:>13} {step:>11} "
            f"{roof:>5} {prefill:>15} {kv.get('num_requests_waiting', 0):>5} "
            f"{_fmt_bytes(res.get('hbm_bytes_in_use', 0)):>9} "
            f"{res.get('xla_compiles', 0):>5}  {_slo_cell(w.get('slo'))}"
            f"{stale_mark}"
        )
    if not doc.get("workers"):
        lines.append("(no workers reporting)")
    hit = doc.get("kv_hit_rate") or {}
    if hit.get("isl_blocks"):
        pct = 100.0 * hit.get("overlap_blocks", 0) / hit["isl_blocks"]
        lines.append("")
        lines.append(f"router prefix-cache hit rate: {pct:.1f}% "
                     f"({hit.get('overlap_blocks', 0)}/{hit['isl_blocks']} blocks)")
    if radix:
        cap = radix.get("max_nodes")
        cap_s = f"/{cap}" if cap else " (unbounded)"
        lookups = radix.get("lookups_total", 0)
        hitpct = (
            f", lookup hit {100.0 * radix.get('hits_total', 0) / lookups:.1f}%"
            if lookups else ""
        )
        lines.append(
            f"router radix index: {radix.get('nodes', 0)}{cap_s} nodes "
            f"({_fmt_bytes(radix.get('bytes', 0))}, "
            f"{radix.get('shards', 1)} shard(s)), "
            f"evictions {radix.get('evictions_total', 0)}{hitpct}"
        )
    # recent-events pane: the fleet timeline (merged per-worker flight
    # recorder tails riding /cluster/status), newest last; j/k scroll it in
    # curses mode
    recent = doc.get("recent_events") or []
    if recent and events_rows > 0:
        total = len(recent)
        offset = max(0, min(events_offset, total - events_rows))
        end = total - offset
        window = recent[max(0, end - events_rows):end]
        lines.append("")
        pos = "" if offset == 0 else f" (scrolled {offset} back)"
        lines.append(
            f"recent events — {total} merged, newest last{pos} (j/k scroll):"
        )
        for ev in window:
            lines.append("  " + _format_event(ev))
    return "\n".join(lines)


def _plain_loop(url: str, interval: float) -> None:
    while True:
        try:
            doc = fetch_status(url)
            out = render_status(doc)
        except Exception as e:
            out = f"dynotop: fetch failed: {e}"
        print("\x1b[2J\x1b[H" + out, flush=True)
        time.sleep(interval)


def _curses_loop(url: str, interval: float) -> None:
    import curses

    def body(stdscr):
        curses.curs_set(0)
        stdscr.timeout(int(interval * 1000))
        offset = 0
        while True:
            try:
                doc = fetch_status(url)
                maxy, _ = stdscr.getmaxyx()
                # the pane gets whatever vertical room the worker table
                # leaves (floor 4 rows so it never vanishes entirely)
                rows = max(4, maxy - len(doc.get("workers", ())) - 10)
                text = render_status(doc, events_rows=rows, events_offset=offset)
            except Exception as e:
                text = f"dynotop: fetch failed: {e}"
            stdscr.erase()
            maxy, maxx = stdscr.getmaxyx()
            for i, line in enumerate(text.splitlines()[: maxy - 1]):
                stdscr.addnstr(i, 0, line, maxx - 1)
            stdscr.refresh()
            ch = stdscr.getch()
            if ch in (ord("q"), 27):
                return
            if ch in (ord("j"), curses.KEY_DOWN):
                offset = max(0, offset - 1)
            elif ch in (ord("k"), curses.KEY_UP):
                offset += 1
            elif ch in (ord("g"),):
                offset = 0

    curses.wrapper(body)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--url", default="http://127.0.0.1:9091",
                   help="metrics component base URL (serves /cluster/status)")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--once", action="store_true", help="print one snapshot and exit")
    p.add_argument("--plain", action="store_true",
                   help="plain-text refresh loop instead of curses")
    args = p.parse_args(argv)

    if args.once:
        try:
            print(render_status(fetch_status(args.url)))
            return 0
        except Exception as e:
            print(f"dynotop: fetch failed: {e}", file=sys.stderr)
            return 1
    if args.plain or not sys.stdout.isatty():
        _plain_loop(args.url, args.interval)
        return 0
    try:
        _curses_loop(args.url, args.interval)
    except ImportError:
        _plain_loop(args.url, args.interval)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
