"""Decompose decode-window time on the real chip.

Methodology (tunneled-PJRT safe):
  - Per-step cost = (t(window of 64 steps) - t(window of 8 steps)) / 56 —
    the tunnel RTT (~75-100 ms/dispatch) cancels in the difference.
  - Every timed call materializes its (small) token output to host AND
    mutates donated device state, so the tunnel's executable/result caching
    cannot short-circuit the run (block_until_ready alone can be served from
    a cache when inputs are unchanged — measured on this rig).

Reports, per decode step at the bench config (1.3B llama-shaped):
  window   — full dispatch_decode_window (model + sampling + feedback)
  model    — scan of model.decode alone (argmax feedback, donated kv)
  attention (separate: tools/profile_attn.py)

Usage: python tools/profile_decode.py [batch] [page_size]
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")
import bench  # noqa: E402  (repo-root bench config = single source of truth)


def main():
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.models.registry import load_model

    bench._probe_pallas()
    B = int(sys.argv[1]) if len(sys.argv) > 1 else bench.HEADLINE[0]
    PS = int(sys.argv[2]) if len(sys.argv) > 2 else bench.HEADLINE[1]
    cfg = bench.bench_config(B, PS)
    model, params = load_model(cfg.model_id)
    runner = ModelRunner(cfg, model, params)
    ctx = bench.PROMPT_LEN + bench.DECODE_TOKENS // 2

    pages_per_seq = -(-ctx // cfg.page_size)
    pt = np.zeros((B, cfg.max_pages_per_seq), np.int32)
    npp = pages_per_seq + 1  # room for the 64-step window's growth
    if 1 + B * npp > cfg.num_pages:
        raise SystemExit(f"pool too small: need {1 + B * npp} pages, have {cfg.num_pages}")
    for i in range(B):
        pt[i, :npp] = 1 + i * npp + np.arange(npp)
    positions = np.full(B, ctx, np.int32)
    active = np.ones(B, bool)
    limits = np.full(B, npp * PS - 2, np.int32)
    temps = np.zeros(B, np.float32)
    top_ks = np.zeros(B, np.int32)
    top_ps = np.ones(B, np.float32)

    def best_wall(fn, reps=4):
        fn()  # compile
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    # ---- full window through the runner (greedy, like the bench) ----
    def window(num_steps):
        toks = runner.dispatch_decode_window(
            positions, pt, active, limits, temps, top_ks, top_ps, num_steps
        )
        return np.asarray(jax.device_get(toks))

    tA = best_wall(lambda: window(8))
    tB = best_wall(lambda: window(64))
    per_window = (tB - tA) / 56

    # ---- model.decode alone, argmax feedback, donated kv/state ----
    pt_j = jnp.asarray(pt)
    act = jnp.asarray(active)

    def model_only_impl(params, kv, toks0, pos0, *, num_steps):
        def body(carry, _):
            kv_, toks, pos = carry
            logits, kv_ = model.decode(params, kv_, toks, pos, pt_j, act)
            toks = jnp.argmax(logits, -1).astype(jnp.int32)
            return (kv_, toks, pos + 1), toks

        (kv, _, _), ys = jax.lax.scan(body, (kv, toks0, pos0), None, length=num_steps)
        return ys, kv

    jits = {
        n: jax.jit(
            lambda p, kv, t, q, n=n: model_only_impl(p, kv, t, q, num_steps=n),
            donate_argnums=(1,),
        )
        for n in (8, 64)
    }

    def model_only(num_steps):
        ys, runner.kv_cache = jits[num_steps](
            runner.params, runner.kv_cache, jnp.zeros(B, jnp.int32),
            jnp.asarray(positions),
        )
        return np.asarray(jax.device_get(ys))

    tA = best_wall(lambda: model_only(8))
    tB = best_wall(lambda: model_only(64))
    per_model = (tB - tA) / 56

    # bytes-moved floor from the SHARED estimator (utils/step_anatomy.py) —
    # the same arithmetic the live dynamo_engine_roofline_fraction gauge and
    # the bench step_anatomy section use, so this one-off tool and the
    # standing plane can never disagree on what "the roofline" means
    from dynamo_tpu.utils.step_anatomy import roofline_for_runner

    roof = roofline_for_runner(runner, cfg)
    if roof is None:
        raise SystemExit("runner/model cannot price the roofline")
    live_pages = B * pages_per_seq
    floor = roof.step_floor_seconds(live_pages)
    out = {
        "B": B, "page_size": PS, "ctx": ctx,
        "per_step_ms": {
            "window": round(per_window * 1e3, 3),
            "model_only": round(per_model * 1e3, 3),
            "sampling_and_feedback": round((per_window - per_model) * 1e3, 3),
        },
        "window_tok_s": round(B / per_window, 1),
        "hbm_floor_ms": round(floor * 1e3, 3),
        "pct_of_roofline": round(100 * floor / per_window, 1),
        "param_bytes": roof.param_bytes,
        "kv_bytes_per_step": live_pages * roof.page_bytes,
        "hbm_bw_bytes_s": roof.hbm_bw,
    }
    print(out)


if __name__ == "__main__":
    main()
