"""Decompose decode-window time on the real chip.

Times, per decode step at the bench config (1.3B llama-shaped; batch and
page size come from bench.bench_config() — check the printed B):
  window   — full dispatch_decode_window (model + sampling + feedback)
  model    — scan of model.decode alone (argmax feedback, no sampler)
  sampler  — scan of sample_tokens alone on [B, V] logits
  matmul   — weight-streaming floor: one scan step touching all params

Usage: python tools/profile_decode.py  (on the default/TPU backend)
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")
import bench  # noqa: E402  (repo-root bench config = single source of truth)


def timed(fn, n=3):
    import jax

    fn()  # compile
    best = float("inf")
    for _ in range(n):
        t0 = time.monotonic()
        jax.block_until_ready(fn())
        best = min(best, time.monotonic() - t0)
    return best


def main():
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.engine.sampling import sample_tokens
    from dynamo_tpu.models.registry import load_model

    bench._probe_pallas()
    cfg = bench.bench_config()
    K = cfg.decode_steps
    B = cfg.max_seqs
    model, params = load_model(cfg.model_id)
    runner = ModelRunner(cfg, model, params)
    V = model.config.vocab_size
    ctx = bench.PROMPT_LEN + bench.DECODE_TOKENS // 2

    pages_per_seq = -(-ctx // cfg.page_size)
    pt = np.zeros((B, cfg.max_pages_per_seq), np.int32)
    for i in range(B):
        pt[i, :pages_per_seq] = 1 + i * pages_per_seq + np.arange(pages_per_seq)
    positions = np.full(B, ctx, np.int32)
    active = np.ones(B, bool)
    limits = np.full(B, ctx + K, np.int32)
    temps = np.zeros(B, np.float32)
    top_ks = np.zeros(B, np.int32)
    top_ps = np.ones(B, np.float32)

    # ---- full window through the runner (greedy, like the bench) ----
    def window():
        out = runner.dispatch_decode_window(
            positions, pt, active, limits, temps, top_ks, top_ps, K
        )
        return out

    t_window = timed(window)

    # ---- model.decode alone, argmax feedback ----
    pt_j = jnp.asarray(pt)
    pos0 = jnp.asarray(positions)
    act = jnp.asarray(active)

    def model_only(params, kv, toks0):
        def body(carry, _):
            toks, pos = carry
            logits, _kv = model.decode(params, kv, toks, pos, pt_j, act)
            toks = jnp.argmax(logits, -1).astype(jnp.int32)
            return (toks, pos + 1), ()

        (toks, _), _ = jax.lax.scan(body, (toks0, pos0), None, length=K)
        return toks

    model_jit = jax.jit(model_only)
    toks0 = jnp.zeros(B, jnp.int32)
    t_model = timed(lambda: model_jit(runner.params, runner.kv_cache, toks0))

    # ---- sampler alone (greedy path, same trace as the bench) ----
    logits = jnp.asarray(np.random.default_rng(0).normal(size=(B, V)), jnp.float32)

    def sampler_only(logits, key):
        def body(key, _):
            key, sub = jax.random.split(key)
            toks = sample_tokens(
                logits, sub,
                jnp.zeros(B, jnp.float32), jnp.zeros(B, jnp.int32),
                jnp.ones(B, jnp.float32), min_p=jnp.zeros(B, jnp.float32),
            )
            return key, toks

        _, toks = jax.lax.scan(body, key, None, length=K)
        return toks

    sampler_jit = jax.jit(sampler_only)
    t_sampler = timed(lambda: sampler_jit(logits, jax.random.key(0)))

    # ---- weight-streaming floor: dot every param against a vector ----
    flat = jax.tree_util.tree_leaves(runner.params)
    total_bytes = sum(l.size * l.dtype.itemsize for l in flat)

    def touch(params, x):
        def body(acc, _):
            s = acc
            for l in jax.tree_util.tree_leaves(params):
                s = s + jnp.sum(l.reshape(-1, l.shape[-1]).astype(jnp.bfloat16) @ x[: l.shape[-1]])
            return s, ()

        s, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), None, length=K)
        return s

    x = jnp.ones((8192, 1), jnp.bfloat16)
    touch_jit = jax.jit(touch)
    t_touch = timed(lambda: touch_jit(runner.params, x))

    ms = lambda t: round(t / K * 1e3, 3)
    out = {
        "per_step_ms": {
            "window": ms(t_window),
            "model_only": ms(t_model),
            "sampler_only": ms(t_sampler),
            "weight_touch_floor": ms(t_touch),
        },
        "window_tok_s": round(B * K / t_window, 1),
        "param_bytes": total_bytes,
        "hbm_roofline_steps_s": round(819e9 / total_bytes, 1),
        "K": K,
        "B": B,
        "ctx": ctx,
    }
    print(out)


if __name__ == "__main__":
    main()
