"""graftlint CLI: the PR gate.

    python -m tools.graftlint                # scan the hot-path surface
    python -m tools.graftlint --self-check   # detectors vs seeded fixtures
    python -m tools.graftlint path/to.py     # scoped scan
    python -m tools.graftlint --write-baseline   # acknowledge current debt

Exit codes mirror tools/bench_compare.py: 0 = clean, 1 = unsuppressed
findings (or a failed self-check), 2 = usage/internal error. tools/lint.sh
runs ``--self-check`` then the full scan between the prometheus conformance
check and ruff, so a broken detector fails the gate as loudly as a broken
hot path.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tools.graftlint.core import (
    Finding,
    ScanContext,
    SourceFile,
    apply_baseline,
    iter_python_files,
    load_baseline,
    write_baseline,
)
from tools.graftlint.detectors import ALL_DETECTORS

#: what the repo gate scans: the package plus the tooling the tier-1 suite
#: shells out to. Tests are deliberately out of scope — they block, sync and
#: fake metrics on purpose.
DEFAULT_SCAN_ROOTS = ("dynamo_tpu", "tools", "bench.py")

DEFAULT_BASELINE = "tools/graftlint/baseline.json"


def run_scan(
    paths: list[Path], root: Path, force_hot: bool = False
) -> tuple[list[Finding], list[str]]:
    """(findings, parse errors). Findings include suppressed/baselined ones;
    callers partition by status."""
    ctx = ScanContext(root=root, force_hot=force_hot)
    files: list[SourceFile] = []
    errors: list[str] = []
    for f in iter_python_files(paths, root):
        try:
            files.append(SourceFile.load(f, root))
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(f"{f}: {e}")
    findings: list[Finding] = []
    detectors = [cls() for cls in ALL_DETECTORS]
    for sf in files:
        for det in detectors:
            findings.extend(det.scan(sf, ctx))
    for det in detectors:
        findings.extend(det.finalize(files, ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, errors


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="JAX/asyncio-aware static analysis gating the hot path; "
        "see ARCHITECTURE.md 'The lint gate' for the detector catalogue.",
    )
    p.add_argument(
        "paths",
        nargs="*",
        help=f"files/dirs to scan (default: {' '.join(DEFAULT_SCAN_ROOTS)})",
    )
    p.add_argument(
        "--root",
        default=".",
        help="repo root for relative paths and the metric declaration module",
    )
    p.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="acknowledged-debt baseline file (relative to --root)",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline (report acknowledged debt as live findings)",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current unsuppressed findings to the baseline and exit 0",
    )
    p.add_argument(
        "--force-hot",
        action="store_true",
        help="treat every scanned file as hot-path (fixture/debug use)",
    )
    p.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also list suppressed/baselined findings",
    )
    p.add_argument("--quiet", action="store_true", help="summary line only")
    p.add_argument(
        "--self-check",
        action="store_true",
        help="verify every detector against its seeded positive/negative "
        "fixtures (the lint-gate wiring)",
    )
    args = p.parse_args(argv)

    if args.self_check:
        from tools.graftlint.selfcheck import self_check

        problems = self_check()
        for prob in problems:
            print(f"FAIL graftlint self-check: {prob}")
        if not problems:
            print("ok: graftlint self-check passed (6 detectors)")
        return 1 if problems else 0

    root = Path(args.root).resolve()
    if args.paths:
        paths = [Path(p) if Path(p).is_absolute() else root / p for p in args.paths]
    else:
        paths = [root / p for p in DEFAULT_SCAN_ROOTS]
    paths = [p for p in paths if p.exists()]
    if not paths:
        print("graftlint: nothing to scan", file=sys.stderr)
        return 2

    try:
        findings, errors = run_scan(paths, root, force_hot=args.force_hot)
    except Exception as e:  # a crashed detector must fail the gate loudly
        print(f"graftlint: internal error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2
    for err in errors:
        print(f"graftlint: parse error: {err}", file=sys.stderr)

    baseline_path = root / args.baseline
    if not args.no_baseline:
        apply_baseline(findings, load_baseline(baseline_path))

    active = [f for f in findings if not f.suppressed and not f.baselined]
    suppressed = [f for f in findings if f.suppressed]
    baselined = [f for f in findings if f.baselined]

    if args.write_baseline:
        write_baseline(baseline_path, active)
        print(f"graftlint: wrote {len(active)} finding(s) to {baseline_path}")
        return 0

    if not args.quiet:
        for f in active:
            print(f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f"{f.render()}  [suppressed: {f.suppress_reason}]")
            for f in baselined:
                print(f"{f.render()}  [baselined]")
    print(
        f"graftlint: {len(active)} finding(s), {len(suppressed)} suppressed, "
        f"{len(baselined)} baselined"
        + (f", {len(errors)} parse error(s)" if errors else "")
    )
    if errors:
        return 2
    return 1 if active else 0


if __name__ == "__main__":
    raise SystemExit(main())
