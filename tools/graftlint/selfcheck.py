"""graftlint --self-check: detectors vs their seeded fixtures.

Mirrors ``tools/bench_compare.py --self-check``: before the repo scan runs,
every detector must (a) catch exactly the seeded violations in its POSITIVE
fixture, (b) stay silent on its NEGATIVE fixture — which includes annotated
violations, so the suppression machinery is exercised too — and (c) never
bleed findings into another detector's fixture. A detector that rots fails
the lint gate itself, not silently stops finding bugs.

Each fixture's first line declares its contract:

    # graftlint-fixture: <rule> expect=<N>

Fixtures are scanned standalone with ``force_hot`` (hot-path scoping is the
repo scan's business) and without the baseline.
"""

from __future__ import annotations

import re
from pathlib import Path

from tools.graftlint.cli import run_scan

FIXTURES_DIR = Path(__file__).parent / "fixtures"

_HEADER_RE = re.compile(r"#\s*graftlint-fixture:\s*(\S+)\s+expect=(\d+)")


def self_check() -> list[str]:
    """Problem list (empty = every detector healthy)."""
    problems: list[str] = []
    fixtures = sorted(FIXTURES_DIR.glob("*.py"))
    if len(fixtures) < 10:
        problems.append(
            f"expected >=10 fixtures (pos+neg per detector), found {len(fixtures)}"
        )
    seen_rules: set[str] = set()
    for fixture in fixtures:
        header = fixture.read_text().splitlines()[0]
        m = _HEADER_RE.search(header)
        if not m:
            problems.append(f"{fixture.name}: missing graftlint-fixture header")
            continue
        rule, expect = m.group(1), int(m.group(2))
        seen_rules.add(rule)
        findings, errors = run_scan([fixture], root=FIXTURES_DIR, force_hot=True)
        for err in errors:
            problems.append(f"{fixture.name}: parse error: {err}")
        active = [f for f in findings if not f.suppressed]
        mine = [f for f in active if f.rule == rule]
        others = [f for f in active if f.rule != rule]
        if len(mine) != expect:
            lines = ", ".join(str(f.line) for f in mine) or "none"
            problems.append(
                f"{fixture.name}: expected {expect} {rule} finding(s), got "
                f"{len(mine)} (lines: {lines})"
            )
        if others:
            problems.append(
                f"{fixture.name}: {len(others)} finding(s) bled in from other "
                f"detectors: {[f.rule for f in others]}"
            )
    missing = {
        "host-sync",
        "use-after-donation",
        "recompile-hazard",
        "async-blocking",
        "metric-conformance",
        "event-conformance",
    } - seen_rules
    if missing:
        problems.append(f"no fixtures cover rule(s): {sorted(missing)}")
    return problems
