"""``python -m tools.graftlint`` — see cli.py for flags and exit codes."""

from tools.graftlint.cli import main

raise SystemExit(main())
