"""graftlint: JAX/asyncio-aware static analysis that gates the hot path.

The runtime planes (``utils/step_anatomy.py``, ``utils/compile_monitor.py``,
``utils/slo.py``) *price* host syncs, recompile storms and event-loop stalls
after they cost milliseconds; graftlint makes the same hazard classes
machine-checked on every PR, before they ship. Stdlib-only (ast + json + re)
so the no-egress CI image runs it with a bare interpreter; wired into
``tools/lint.sh`` between the prometheus conformance check and ruff.

    python -m tools.graftlint               # repo scan (exit 1 on findings)
    python -m tools.graftlint --self-check  # detectors vs seeded fixtures

See ``tools/graftlint/detectors/__init__.py`` for the catalogue and
ARCHITECTURE.md ("The lint gate") for how this relates to the runtime
measurement planes.
"""

from tools.graftlint.cli import main, run_scan
from tools.graftlint.core import Finding, ScanContext, SourceFile

__all__ = ["Finding", "ScanContext", "SourceFile", "main", "run_scan"]
