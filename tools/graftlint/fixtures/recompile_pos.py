# graftlint-fixture: recompile-hazard expect=3
"""Seeded POSITIVE fixture: a static_argnames typo (signature drift) plus the
literal-at-traced-position retraces it causes."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("mp", "widht"))  # [1] typo drift
def decode(x, table, mp=8, width=16):
    return jnp.sum(x) + mp + width


def drive(x, table):
    a = decode(x, 3.0, 4)  # [2] scalar literal at non-static `table`
    b = decode(x, table, 4, width=32)  # [3] `width` is traced (typo!) + literal
    return a, b
