# graftlint-fixture: metric-conformance expect=0
"""Seeded NEGATIVE fixture: exact references, underscore-boundary prefix
references (the engine's dynamo_slo_* -> dynamo_engine_slo_* rename idiom),
and an annotated non-metric string."""

DECLARED_METRIC_FAMILIES = (
    "dynamo_fixture_requests_total",
    "dynamo_fixture_latency_seconds",
    "dynamo_fixture_goodput_ratio",
)


def render():
    fams = ["dynamo_fixture_requests_total"]  # exact reference
    prefix = "dynamo_fixture_latency_"  # trailing-underscore prefix reference
    rename = "dynamo_fixture_goodput"  # boundary prefix reference
    label = "dynamo_fixture_k8s_label"  # graftlint: metric-ok k8s selector
    return fams, prefix, rename, label
