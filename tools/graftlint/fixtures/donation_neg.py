# graftlint-fixture: use-after-donation expect=0
"""Seeded NEGATIVE fixture: the immediate-rebind idiom is safe, and an
annotated deliberate exception suppresses."""
import jax


def _step_impl(state, x):
    return state * x


class Runner:
    def __init__(self):
        self._step = jax.jit(_step_impl, donate_argnums=(0,))

    def run(self, state, xs):
        for x in xs:
            state = self._step(state, x)  # rebound each iteration: safe
        return state

    def peek(self, state, x):
        out = self._step(state, x)
        shape = state.shape  # graftlint: donation-ok fixture: metadata only
        return out, shape
