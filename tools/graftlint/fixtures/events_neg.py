# graftlint-fixture: event-conformance expect=0
"""Seeded NEGATIVE fixture: declared-kind emits, non-kind-shaped emit
arguments (free-text signal APIs), and an annotated collision."""

DECLARED_EVENT_KINDS = (
    "fixture.admitted",
    "fixture.preempted",
)


class _Journal:
    def emit(self, kind, **detail):
        return kind


def instrument(journal: _Journal, signals: _Journal):
    journal.emit("fixture.admitted")  # exact reference
    journal.emit("fixture.preempted", generated=7)  # exact reference
    signals.emit("plain text, not a kind")  # no taxonomy shape: skipped
    signals.emit("topic.changed")  # graftlint: event-ok pubsub topic, not a journal kind
