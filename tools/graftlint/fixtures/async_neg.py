# graftlint-fixture: async-blocking expect=0
"""Seeded NEGATIVE fixture: awaited sleeps, asyncio.Lock, sync I/O in a sync
helper, and an annotated bounded block."""
import asyncio
import time


def snapshot(path):
    with open(path) as f:  # sync def: runs wherever the caller put it
        return f.read()


class Poller:
    def __init__(self):
        self._lock = asyncio.Lock()

    async def tick(self, path):
        await asyncio.sleep(0.1)
        async with self._lock:  # async lock across await: correct idiom
            await asyncio.sleep(0)
        time.sleep(0)  # graftlint: blocking-ok fixture: documented bounded spin
        return snapshot
