# graftlint-fixture: use-after-donation expect=2
"""Seeded POSITIVE fixture: both use-after-donation shapes."""
import jax


def _step_impl(state, x):
    return state + x


class Runner:
    def __init__(self):
        self._step = jax.jit(_step_impl, donate_argnums=(0,))

    def run(self, state, x):
        out = self._step(state, x)
        stale = state.shape  # [1] donated `state` referenced after the call
        return out, stale

    def loop(self, state, xs):
        acc = []
        for x in xs:
            acc.append(self._step(state, x))  # [2] re-donated every iteration
        return acc
