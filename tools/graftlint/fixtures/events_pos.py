# graftlint-fixture: event-conformance expect=2
"""Seeded POSITIVE fixture: one undeclared emitting kind literal, one
declared kind nobody emits. Scanned standalone, so this module carries its
own declaration surface."""

DECLARED_EVENT_KINDS = (
    "fixture.admitted",
    "fixture.orphan_kind",  # [1] declared, never emitted
)


class _Journal:
    def emit(self, kind, **detail):
        return kind


def instrument(journal: _Journal):
    journal.emit("fixture.admitted", slot=3)  # declared: fine
    journal.emit("fixture.rogue_kind", slot=4)  # [2] undeclared kind
