# graftlint-fixture: host-sync expect=0
"""Seeded NEGATIVE fixture: host-side staging must NOT flag, and annotated
reconcile points must suppress (with a reason)."""
import jax
import jax.numpy as jnp
import numpy as np


def reconcile(runner, token_list, out_dev):
    ids = np.asarray(token_list, np.int32)  # host->device staging: fine
    toks = np.asarray(out_dev)  # graftlint: sync-ok priced reconcile point
    depth = int(len(token_list))  # host int: fine
    return ids, toks, depth


def warmup(x):
    out = jnp.exp(x)
    jax.block_until_ready(out)  # graftlint: sync-ok warmup compile gate
    return out
