# graftlint-fixture: host-sync expect=5
"""Seeded POSITIVE fixture: every host-sync shape the detector must catch.

Never imported — parsed only (the self-check runs the detector over this
file with --force-hot and asserts exactly the seeded finding count)."""
import jax
import jax.numpy as jnp
import numpy as np


def hot_loop(runner, table):
    logits = jnp.dot(table, table)  # taints `logits` as a device value
    toks_dev = runner.dispatch(table)
    a = float(logits[0])  # [1] float() coercion of a device value
    b = int(jnp.argmax(logits))  # [2] int() of a direct jnp call result
    host = np.asarray(toks_dev)  # [3] np.asarray on a *_dev handle
    n = logits.sum().item()  # [4] .item() round trip
    jax.block_until_ready(logits)  # [5] explicit blocking sync
    return a, b, host, n
