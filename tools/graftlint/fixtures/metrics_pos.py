# graftlint-fixture: metric-conformance expect=2
"""Seeded POSITIVE fixture: one undeclared emitting literal, one declared
family nobody emits. Scanned standalone, so this module carries its own
declaration surface."""

DECLARED_METRIC_FAMILIES = (
    "dynamo_fixture_requests_total",
    "dynamo_fixture_orphan_seconds",  # [1] declared, never emitted
)


def render():
    out = []
    out.append(("dynamo_fixture_requests_total", 1))  # declared: fine
    out.append(("dynamo_fixture_rogue_total", 2))  # [2] undeclared family
    return out
