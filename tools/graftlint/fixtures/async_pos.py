# graftlint-fixture: async-blocking expect=3
"""Seeded POSITIVE fixture: blocking sleep, sync file I/O, and an await
while holding a sync threading.Lock."""
import asyncio
import threading
import time


class Poller:
    def __init__(self):
        self._lock = threading.Lock()

    async def tick(self, path):
        time.sleep(0.5)  # [1] stalls the event loop
        with open(path) as f:  # [2] sync file I/O on the loop
            data = f.read()
        with self._lock:  # [3] lock held across a suspension point
            await asyncio.sleep(0)
        return data
