# graftlint-fixture: recompile-hazard expect=0
"""Seeded NEGATIVE fixture: literals at static positions are fine; an
annotated deliberate constant-fold suppresses."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("mp",))
def decode(x, table, mp=8):
    return jnp.sum(x) * mp + jnp.sum(table)


def drive(x, table):
    good = decode(x, table, 16)  # 16 binds static `mp`: one variant, fine
    bias = decode(x, 0.5, mp=4)  # graftlint: recompile-ok constant table folds
    return good, bias
