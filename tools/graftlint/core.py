"""graftlint core: source model, findings, suppressions, baseline.

The runtime planes (``utils/step_anatomy.py``, ``utils/compile_monitor.py``)
discover host syncs and recompile storms *after* they cost milliseconds;
graftlint makes the same hazard classes machine-checked before merge. This
module is the rule-agnostic substrate: parsed source files with a per-line
suppression index, the Finding record every detector emits, and the baseline
(acknowledged-debt) bookkeeping. Pure stdlib — the no-egress CI image runs it
with nothing but a Python interpreter.

Suppression syntax (one hazard class per token, reason REQUIRED):

    np.asarray(toks_dev)  # graftlint: sync-ok priced reconcile point

A suppression on the flagged line, the line above it, or any line of a
multi-line expression covers that expression. A suppression without a reason
does not suppress — it becomes its own finding, so the allowlist stays
self-documenting.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

#: rule id -> suppression token (``# graftlint: <token>-ok <reason>``)
SUPPRESS_TOKENS = {
    "host-sync": "sync",
    "use-after-donation": "donation",
    "recompile-hazard": "recompile",
    "async-blocking": "blocking",
    "metric-conformance": "metric",
    "event-conformance": "event",
}

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(sync|donation|recompile|blocking|metric|event)-ok"
    r"(?:[ \t]+(\S.*?))?\s*$"
)


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str
    func: str = "<module>"  # enclosing function qualname-ish, for fingerprints
    suppressed: bool = False
    suppress_reason: str = ""
    baselined: bool = False

    @property
    def fingerprint(self) -> str:
        # line numbers drift with every edit; (rule, file, function, message)
        # survives unrelated churn, which is what a baseline entry needs
        return f"{self.rule}|{self.path}|{self.func}|{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class SourceFile:
    path: str
    abspath: Path
    text: str
    tree: ast.AST
    lines: list[str]
    #: 1-based line -> (token, reason) for every graftlint suppression comment
    suppressions: dict[int, tuple[str, str]] = field(default_factory=dict)
    parents: dict[int, ast.AST] = field(default_factory=dict)

    @classmethod
    def load(cls, abspath: Path, root: Path) -> "SourceFile":
        text = abspath.read_text()
        tree = ast.parse(text, filename=str(abspath))
        sf = cls(
            path=abspath.relative_to(root).as_posix(),
            abspath=abspath,
            text=text,
            tree=tree,
            lines=text.splitlines(),
        )
        for lineno, line in enumerate(sf.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if m:
                sf.suppressions[lineno] = (m.group(1), (m.group(2) or "").strip())
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                sf.parents[id(child)] = node
        return sf

    def suppression_for(self, rule: str, node: ast.AST) -> tuple[bool, str]:
        """(suppressed, reason) for ``rule`` at ``node``: the token may sit on
        the line above the expression or on any of its own lines."""
        token = SUPPRESS_TOKENS[rule]
        first = getattr(node, "lineno", 1)
        last = getattr(node, "end_lineno", first) or first
        for lineno in range(first - 1, last + 1):
            entry = self.suppressions.get(lineno)
            if entry and entry[0] == token:
                return True, entry[1]
        return False, ""

    def stmt_of(self, node: ast.AST) -> ast.stmt:
        """Smallest statement containing ``node`` (node itself if a stmt)."""
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parents.get(id(cur))
        return cur if cur is not None else node


@dataclass
class ScanContext:
    """Shared scan state handed to every detector."""

    root: Path
    #: treat every scanned file as hot-path (the self-check fixtures opt in;
    #: the repo scan scopes host-sync to HOT_DIRS)
    force_hot: bool = False


def make_finding(
    sf: SourceFile, rule: str, node: ast.AST, message: str, func: str = "<module>"
) -> list[Finding]:
    """One finding at ``node``, honoring suppressions. A suppression with an
    empty reason yields a replacement finding instead of silence."""
    suppressed, reason = sf.suppression_for(rule, node)
    f = Finding(
        rule=rule,
        path=sf.path,
        line=getattr(node, "lineno", 1),
        message=message,
        func=func,
        suppressed=suppressed,
        suppress_reason=reason,
    )
    if suppressed and not reason:
        return [
            Finding(
                rule=rule,
                path=sf.path,
                line=f.line,
                message=f"suppression without a reason (was: {message})",
                func=func,
            )
        ]
    return [f]


def enclosing_func(sf: SourceFile, node: ast.AST) -> str:
    parts: list[str] = []
    cur = sf.parents.get(id(node))
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            parts.append(cur.name)
        cur = sf.parents.get(id(cur))
    return ".".join(reversed(parts)) or "<module>"


# ---------------- file walking ----------------

EXCLUDE_DIR_NAMES = {"__pycache__", ".git", "fixtures"}


def iter_python_files(paths: list[Path], root: Path) -> list[Path]:
    """Every .py under ``paths`` (files or directories), excluding pycache and
    the graftlint fixtures tree (seeded violations must not fail the repo
    scan)."""
    out: list[Path] = []
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if any(part in EXCLUDE_DIR_NAMES for part in f.parts):
                    continue
                out.append(f)
    seen: set[Path] = set()
    uniq = []
    for f in out:
        if f not in seen:
            seen.add(f)
            uniq.append(f)
    return uniq


# ---------------- baseline ----------------


def load_baseline(path: Path) -> set[str]:
    """Fingerprint set from the acknowledged-debt baseline file. Missing file
    = empty baseline (the gate starts strict)."""
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return {e["fingerprint"] for e in data.get("findings", [])}


def write_baseline(path: Path, findings: list[Finding]) -> None:
    data = {
        "_comment": (
            "graftlint acknowledged-debt baseline: findings listed here are "
            "reported as 'baselined' and do not fail the gate. Fingerprints "
            "are (rule|path|function|message) — stable across line drift. "
            "Regenerate with: python -m tools.graftlint --write-baseline"
        ),
        "findings": [
            {"fingerprint": f.fingerprint, "line": f.line}
            for f in sorted(findings, key=lambda f: f.fingerprint)
        ],
    }
    path.write_text(json.dumps(data, indent=2) + "\n")


def apply_baseline(findings: list[Finding], baseline: set[str]) -> None:
    for f in findings:
        if not f.suppressed and f.fingerprint in baseline:
            f.baselined = True
