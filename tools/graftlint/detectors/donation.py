"""Detector 2: use-after-donation.

``donate_argnums`` hands a buffer to XLA for in-place reuse — referencing the
Python binding afterwards reads a deleted buffer and raises (or worse, on some
backends, silently reads garbage). The runtime discovers this as a crash in
the engine loop; statically it is a dataflow check:

    self.kv_cache = self._prefill(self.params, self.slot_state, self.kv_cache)
    #                 donate_argnums=(1, 2): slot_state donated, NOT rebound
    x = self.slot_state  # <- use-after-donation

Two shapes are flagged, both scoped to a single function body (linear,
lineno-ordered — branch-sensitive dataflow is out of scope for a lint):

  1. a donated Name/Attribute is loaded after the jit call without being
     rebound in between (the call statement's own assignment targets count
     as an immediate rebind);
  2. the call sits in a loop and the donated binding is never rebound inside
     that loop body — the next iteration re-donates a consumed buffer.

Deliberate exceptions (e.g. a buffer provably dead afterwards that the
scheduler re-creates) carry ``# graftlint: donation-ok <reason>``.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import (
    Finding,
    ScanContext,
    SourceFile,
    enclosing_func,
    make_finding,
)
from tools.graftlint.jitspec import JitSpec, collect_jit_specs

RULE = "use-after-donation"


def _assign_target_keys(stmt: ast.stmt) -> set[str]:
    """Unparse keys of every simple binding target in ``stmt``."""
    out: set[str] = set()

    def add(t: ast.AST) -> None:
        if isinstance(t, (ast.Name, ast.Attribute)):
            try:
                out.add(ast.unparse(t))
            except Exception:
                pass
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                add(e)
        elif isinstance(t, ast.Starred):
            add(t.value)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            add(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        add(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        add(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                add(item.optional_vars)
    return out


def _donated_args(call: ast.Call, spec: JitSpec) -> list[ast.AST]:
    out: list[ast.AST] = []
    for i in spec.donated_positions():
        if i < len(call.args) and not isinstance(call.args[i], ast.Starred):
            out.append(call.args[i])
    donate_kw = set(spec.donate_names)
    if spec.params is not None:
        donate_kw |= {
            spec.params[i] for i in spec.donate_nums if i < len(spec.params)
        }
    for kw in call.keywords:
        if kw.arg in donate_kw:
            out.append(kw.value)
    return out


class DonationDetector:
    rule = RULE

    def scan(self, sf: SourceFile, ctx: ScanContext) -> list[Finding]:
        specs = collect_jit_specs(sf.tree)
        if not specs:
            return []
        findings: list[Finding] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._scan_function(sf, node, specs))
        return findings

    def finalize(self, files: list[SourceFile], ctx: ScanContext) -> list[Finding]:
        return []

    # ---- per-function linear dataflow ----

    def _scan_function(
        self, sf: SourceFile, fn: ast.AST, specs: dict[str, JitSpec]
    ) -> list[Finding]:
        findings: list[Finding] = []
        calls: list[tuple[ast.Call, JitSpec]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                try:
                    key = ast.unparse(node.func)
                except Exception:
                    continue
                spec = specs.get(key)
                if spec is not None and (spec.donate_nums or spec.donate_names):
                    calls.append((node, spec))
        if not calls:
            return findings

        # precompute, in source order: every load and every rebind of every
        # Name/Attribute key in this function
        loads: dict[str, list[ast.AST]] = {}
        rebinds: dict[str, list[int]] = {}
        for node in ast.walk(fn):
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                try:
                    loads.setdefault(ast.unparse(node), []).append(node)
                except Exception:
                    pass
            if isinstance(node, ast.stmt):
                for key in _assign_target_keys(node):
                    rebinds.setdefault(key, []).append(node.lineno)

        for call, spec in calls:
            stmt = sf.stmt_of(call)
            stmt_targets = _assign_target_keys(stmt)
            call_end = stmt.end_lineno or stmt.lineno
            for arg in _donated_args(call, spec):
                if not isinstance(arg, (ast.Name, ast.Attribute)):
                    continue
                try:
                    key = ast.unparse(arg)
                except Exception:
                    continue
                if key in stmt_targets:
                    continue  # result immediately rebinds the donated name
                qual = enclosing_func(sf, call)
                # shape 1: later load without an intervening rebind
                for use in loads.get(key, []):
                    if use.lineno <= call_end:
                        continue
                    if any(
                        call_end < rl <= use.lineno
                        for rl in rebinds.get(key, [])
                    ):
                        continue
                    findings.extend(
                        make_finding(
                            sf,
                            RULE,
                            use,
                            f"`{key}` donated to `{spec.key}` (line "
                            f"{call.lineno}, donate_argnums/argnames) is "
                            "referenced after the call — the buffer is gone",
                            qual,
                        )
                    )
                    break  # one finding per donated arg is enough
                else:
                    # shape 2: re-donation on the next loop iteration
                    loop = self._enclosing_loop(sf, stmt, fn)
                    if loop is not None and not any(
                        loop.lineno <= rl <= (loop.end_lineno or loop.lineno)
                        for rl in rebinds.get(key, [])
                    ):
                        findings.extend(
                            make_finding(
                                sf,
                                RULE,
                                call,
                                f"`{key}` is donated to `{spec.key}` inside a "
                                "loop but never rebound in the loop body — "
                                "the next iteration donates a consumed buffer",
                                qual,
                            )
                        )
        return findings

    def _enclosing_loop(
        self, sf: SourceFile, stmt: ast.stmt, fn: ast.AST
    ) -> ast.stmt | None:
        cur = sf.parents.get(id(stmt))
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return cur
            cur = sf.parents.get(id(cur))
        return None
