"""Detector 4: blocking calls and lock misuse inside ``async def``.

One ``time.sleep`` in a coroutine stalls the whole event loop — every
in-flight request's TTFT absorbs it, which is exactly the failure mode the
SLO plane (``utils/slo.py``) can see but not attribute. Flagged inside
``async def`` bodies (nested *sync* defs are skipped — they run wherever the
caller schedules them, e.g. ``run_in_executor``):

  - ``time.sleep`` (resolved through import aliasing)
  - ``subprocess.run/call/check_call/check_output/Popen/getoutput``,
    ``os.system``/``os.popen``
  - ``requests.*`` / ``urllib.request.urlopen`` / sync ``httpx`` verbs
  - ``socket.create_connection`` / ``socket.getaddrinfo`` (blocking DNS)
  - sync file I/O: builtin ``open(...)`` and the pathlib surface
    (``.open/.read_text/.write_text/.read_bytes/.write_bytes``)
  - ``await`` while holding a *sync* ``threading.Lock`` (a ``with <lock>:``
    block whose body awaits): the lock is held across a suspension point, so
    any thread contending on it — e.g. the engine loop — deadlocks against
    the event loop.

Intentional blocking (tiny bounded reads at startup, etc.) carries
``# graftlint: blocking-ok <reason>``.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import (
    Finding,
    ScanContext,
    SourceFile,
    enclosing_func,
    make_finding,
)

RULE = "async-blocking"

#: canonical dotted call -> why it blocks
_BLOCKING_CALLS = {
    "time.sleep": "sleeps the whole event loop — use `await asyncio.sleep`",
    "subprocess.run": "blocks on the child — use `asyncio.create_subprocess_exec`",
    "subprocess.call": "blocks on the child — use `asyncio.create_subprocess_exec`",
    "subprocess.check_call": "blocks on the child process",
    "subprocess.check_output": "blocks on the child process",
    "subprocess.getoutput": "blocks on the child process",
    "subprocess.Popen": "spawns a child the loop then waits on synchronously",
    "os.system": "blocks on a shell",
    "os.popen": "blocks on a shell",
    "urllib.request.urlopen": "sync HTTP — use aiohttp",
    "socket.create_connection": "sync connect — use loop.sock_connect/aiohttp",
    "socket.getaddrinfo": "blocking DNS — use loop.getaddrinfo",
}

_BLOCKING_ROOT_MODULES = {"requests": "sync HTTP — use aiohttp"}

_SYNC_IO_METHODS = {"read_text", "write_text", "read_bytes", "write_bytes"}


class _ImportMap(ast.NodeVisitor):
    """local name -> canonical dotted module path."""

    def __init__(self) -> None:
        self.names: dict[str, str] = {}

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.names[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module:
            for a in node.names:
                self.names[a.asname or a.name] = f"{node.module}.{a.name}"


def _canonical_call(func: ast.AST, imports: dict[str, str]) -> str | None:
    """Dotted canonical name of a call target, through import aliases."""
    parts: list[str] = []
    cur = func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    root = imports.get(cur.id, cur.id)
    return ".".join([root] + list(reversed(parts)))


class _AsyncVisitor(ast.NodeVisitor):
    def __init__(self, sf: SourceFile, imports: dict[str, str]) -> None:
        self.sf = sf
        self.imports = imports
        self.findings: list[Finding] = []
        self.async_depth = 0

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self.async_depth += 1
        self.generic_visit(node)
        self.async_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a nested sync def does not run on the loop by construction; its
        # body is the caller's problem (run_in_executor / thread target)
        saved, self.async_depth = self.async_depth, 0
        self.generic_visit(node)
        self.async_depth = saved

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved, self.async_depth = self.async_depth, 0
        self.generic_visit(node)
        self.async_depth = saved

    def _flag(self, node: ast.AST, message: str) -> None:
        self.findings.extend(
            make_finding(self.sf, RULE, node, message, enclosing_func(self.sf, node))
        )

    def visit_Call(self, node: ast.Call) -> None:
        if self.async_depth > 0:
            canon = _canonical_call(node.func, self.imports)
            if canon is not None:
                why = _BLOCKING_CALLS.get(canon)
                if why is None:
                    root = canon.split(".")[0]
                    if root in _BLOCKING_ROOT_MODULES and "." in canon:
                        why = _BLOCKING_ROOT_MODULES[root]
                if why is not None:
                    self._flag(node, f"blocking `{canon}` inside async def: {why}")
                elif canon == "open":
                    self._flag(
                        node,
                        "sync file I/O (builtin open) inside async def blocks "
                        "the event loop",
                    )
            if isinstance(node.func, ast.Attribute) and node.func.attr in (
                _SYNC_IO_METHODS | {"open"}
            ):
                self._flag(
                    node,
                    f"sync file I/O (.{node.func.attr}) inside async def "
                    "blocks the event loop",
                )
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        if self.async_depth > 0:
            lockish = None
            for item in node.items:
                try:
                    expr = ast.unparse(item.context_expr)
                except Exception:
                    continue
                seg = expr.split("(")[0].split(".")[-1]
                if "lock" in seg.lower():
                    lockish = expr
                    break
            if lockish is not None and self._has_await(node):
                self._flag(
                    node,
                    f"`await` while holding sync lock `{lockish}` — the lock "
                    "is held across a suspension point; use asyncio.Lock or "
                    "release before awaiting",
                )
        self.generic_visit(node)

    def _has_await(self, node: ast.With) -> bool:
        def walk(n: ast.AST) -> bool:
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # a nested def's awaits are not under this lock
                if isinstance(child, ast.Await) or walk(child):
                    return True
            return False

        return any(isinstance(s, ast.Await) or walk(s) for s in node.body)


class AsyncHazardDetector:
    rule = RULE

    def scan(self, sf: SourceFile, ctx: ScanContext) -> list[Finding]:
        imp = _ImportMap()
        imp.visit(sf.tree)
        v = _AsyncVisitor(sf, imp.names)
        v.visit(sf.tree)
        return v.findings

    def finalize(self, files: list[SourceFile], ctx: ScanContext) -> list[Finding]:
        return []
