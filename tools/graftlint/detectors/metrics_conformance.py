"""Detector 5: dynamo_* metric-name conformance.

Every ``dynamo_*`` family this system exposes is declared once, in
``dynamo_tpu/utils/prometheus.py`` (``DECLARED_METRIC_FAMILIES``), the same
module whose ``--check`` renders every exposition surface. This detector is
the *static* half of that contract:

  - every ``dynamo_*`` string literal at an emitting site must be a declared
    family, or an underscore-boundary prefix of one (the engine renames
    ``dynamo_slo_*`` -> ``dynamo_engine_slo_*`` via prefix literals like
    ``"dynamo_slo"`` / ``"dynamo_goodput_"`` — those are references to every
    family they cover);
  - vice versa, every declared family must be reachable from some literal in
    the scanned code (exact or via such a prefix) — a family nobody emits is
    exposition-test drift waiting to happen.

The runtime half lives in ``python -m dynamo_tpu.utils.prometheus --check``,
which asserts the *rendered* family set equals the declared set — so the
declaration list is pinned from both sides and the exposition tests can never
drift from the emitting sites.

Docstrings are skipped (prose mentions are not emitting sites). Non-metric
strings that happen to match (k8s label keys etc.) carry
``# graftlint: metric-ok <reason>``; the vice-versa direction only runs when
the declaring module is part of the scan.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass

from tools.graftlint.core import (
    Finding,
    ScanContext,
    SourceFile,
    enclosing_func,
    make_finding,
)

RULE = "metric-conformance"

DECLARATION_NAME = "DECLARED_METRIC_FAMILIES"
DECLARING_MODULE = "dynamo_tpu/utils/prometheus.py"

#: a family name or boundary-prefix reference ("dynamo_slo" is the SloTracker
#: render prefix covering dynamo_slo_*), no trailing underscore
_FULL_RE = re.compile(r"^dynamo_[a-z0-9]+(?:_[a-z0-9]+)*$")
#: an explicit prefix reference ("dynamo_goodput_", "dynamo_engine_context_")
_PREFIX_RE = re.compile(r"^dynamo_[a-z0-9_]*_$")


@dataclass
class _Literal:
    sf: SourceFile
    node: ast.Constant
    value: str


def _docstring_nodes(tree: ast.AST) -> set[int]:
    """ids of Constant nodes that are module/class/function docstrings."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)):
            body = node.body
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                out.add(id(body[0].value))
    return out


def _find_declaration(tree: ast.AST) -> tuple[list[tuple[str, ast.Constant]], set[int]]:
    """(declared (name, node) pairs, ids of every Constant inside the
    declaration assignment) — declaration literals are not usages."""
    declared: list[tuple[str, ast.Constant]] = []
    decl_ids: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            if not any(
                isinstance(t, ast.Name) and t.id == DECLARATION_NAME for t in targets
            ):
                continue
            if node.value is None:
                continue
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant):
                    decl_ids.add(id(sub))
                    if isinstance(sub.value, str) and sub.value.startswith("dynamo_"):
                        declared.append((sub.value, sub))
    return declared, decl_ids


class MetricsConformanceDetector:
    """Whole-scan detector: literals are collected per file, cross-checked in
    finalize (both directions need the full file set)."""

    rule = RULE

    def scan(self, sf: SourceFile, ctx: ScanContext) -> list[Finding]:
        return []

    def finalize(self, files: list[SourceFile], ctx: ScanContext) -> list[Finding]:
        findings: list[Finding] = []
        declared: dict[str, tuple[SourceFile, ast.Constant]] = {}
        declaring_file_scanned = False
        usages: list[_Literal] = []

        for sf in files:
            decl_pairs, decl_ids = _find_declaration(sf.tree)
            if decl_pairs:
                declaring_file_scanned = True
            for name, node in decl_pairs:
                declared.setdefault(name, (sf, node))
            doc_ids = _docstring_nodes(sf.tree)
            for node in ast.walk(sf.tree):
                if (
                    isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and node.value.startswith("dynamo_")
                    and id(node) not in decl_ids
                    and id(node) not in doc_ids
                    and (_FULL_RE.match(node.value) or _PREFIX_RE.match(node.value))
                    and not node.value.startswith("dynamo_tpu")
                ):
                    usages.append(_Literal(sf, node, node.value))

        names = set(declared)

        def covered_by(lit: str) -> set[str]:
            """Declared families a literal refers to (exact or prefix)."""
            if lit.endswith("_"):
                return {d for d in names if d.startswith(lit)}
            if lit in names:
                return {lit}
            return {d for d in names if d.startswith(lit + "_")}

        referenced: set[str] = set()
        for use in usages:
            hits = covered_by(use.value)
            if hits:
                referenced |= hits
            elif names:  # with no declaration in scope, skip direction 1
                kind = "prefix" if use.value.endswith("_") else "family"
                findings.extend(
                    make_finding(
                        use.sf,
                        RULE,
                        use.node,
                        f"metric {kind} literal {use.value!r} matches no "
                        f"declared dynamo_* family — declare it in "
                        f"{DECLARATION_NAME} (utils/prometheus.py) or mark "
                        "it metric-ok if it is not a metric",
                        enclosing_func(use.sf, use.node),
                    )
                )

        # vice versa: only meaningful when the declaring module was scanned
        if declaring_file_scanned:
            for name in sorted(names - referenced):
                sf, node = declared[name]
                findings.extend(
                    make_finding(
                        sf,
                        RULE,
                        node,
                        f"declared metric family {name!r} is never referenced "
                        "by any emitting site in the scanned code — dead "
                        "declaration or missing emitter",
                        DECLARATION_NAME,
                    )
                )
        return findings
