"""Detector 6: flight-recorder event-kind conformance.

Every lifecycle event the flight recorder may journal is declared once, in
``dynamo_tpu/utils/events.py`` (``DECLARED_EVENT_KINDS``) — the same tuple
``emit()`` enforces at runtime (ValueError on an unknown kind). This detector
is the *static* half of that contract, the exact mirror of
metric-conformance:

  - every ``*.emit("<kind>")`` string-literal kind at an emitting site must
    be a declared kind — a typo'd kind would otherwise only surface as a
    runtime ValueError on the one code path that emits it;
  - vice versa, every declared kind must have at least one emitting literal
    in the scanned code — a kind nobody emits is dashboard/forensics drift
    waiting to happen.

Only dotted ``<plane>.<decision>`` literals in the first positional argument
of an ``.emit(...)`` call are considered (other emit-like APIs with free-text
arguments don't look like kinds); non-event strings that still collide carry
``# graftlint: event-ok <reason>``. The vice-versa direction only runs when
the declaring module is part of the scan.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass

from tools.graftlint.core import (
    Finding,
    ScanContext,
    SourceFile,
    enclosing_func,
    make_finding,
)

RULE = "event-conformance"

DECLARATION_NAME = "DECLARED_EVENT_KINDS"
DECLARING_MODULE = "dynamo_tpu/utils/events.py"

#: the taxonomy shape: ``<plane>.<decision>`` (one dot, snake_case halves)
_KIND_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*$")


@dataclass
class _Literal:
    sf: SourceFile
    node: ast.Constant
    value: str


def _find_declaration(tree: ast.AST) -> tuple[list[tuple[str, ast.Constant]], set[int]]:
    """(declared (kind, node) pairs, ids of every Constant inside the
    declaration assignment) — declaration literals are not emitting sites."""
    declared: list[tuple[str, ast.Constant]] = []
    decl_ids: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            if not any(
                isinstance(t, ast.Name) and t.id == DECLARATION_NAME for t in targets
            ):
                continue
            if node.value is None:
                continue
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant):
                    decl_ids.add(id(sub))
                    if isinstance(sub.value, str) and _KIND_RE.match(sub.value):
                        declared.append((sub.value, sub))
    return declared, decl_ids


def _emit_literals(tree: ast.AST, decl_ids: set[int]) -> list[ast.Constant]:
    """First-positional string literals of ``<anything>.emit(...)`` calls
    that look like event kinds."""
    out: list[ast.Constant] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr != "emit" or not node.args:
            continue
        # the kind argument is usually one literal, but decision sites pick
        # between kinds inline ('prefix_fetch.timeout' if timed_out else
        # 'prefix_fetch.fallback') — every literal inside the argument is an
        # emitting reference
        for arg in ast.walk(node.args[0]):
            if (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and id(arg) not in decl_ids
                and _KIND_RE.match(arg.value)
            ):
                out.append(arg)
    return out


class EventConformanceDetector:
    """Whole-scan detector: literals are collected per file, cross-checked in
    finalize (the vice-versa direction needs the full file set)."""

    rule = RULE

    def scan(self, sf: SourceFile, ctx: ScanContext) -> list[Finding]:
        return []

    def finalize(self, files: list[SourceFile], ctx: ScanContext) -> list[Finding]:
        findings: list[Finding] = []
        declared: dict[str, tuple[SourceFile, ast.Constant]] = {}
        declaring_file_scanned = False
        usages: list[_Literal] = []

        for sf in files:
            decl_pairs, decl_ids = _find_declaration(sf.tree)
            if decl_pairs:
                declaring_file_scanned = True
            for kind, node in decl_pairs:
                declared.setdefault(kind, (sf, node))
            for node in _emit_literals(sf.tree, decl_ids):
                usages.append(_Literal(sf, node, node.value))

        kinds = set(declared)
        referenced: set[str] = set()
        for use in usages:
            if use.value in kinds:
                referenced.add(use.value)
            elif kinds:  # with no declaration in scope, skip direction 1
                findings.extend(
                    make_finding(
                        use.sf,
                        RULE,
                        use.node,
                        f"event kind literal {use.value!r} is not in "
                        f"{DECLARATION_NAME} (utils/events.py) — emit() would "
                        "raise ValueError at runtime; declare the kind or "
                        "mark the call event-ok if it is not a journal emit",
                        enclosing_func(use.sf, use.node),
                    )
                )

        # vice versa: only meaningful when the declaring module was scanned
        if declaring_file_scanned:
            for kind in sorted(kinds - referenced):
                sf, node = declared[kind]
                findings.extend(
                    make_finding(
                        sf,
                        RULE,
                        node,
                        f"declared event kind {kind!r} is emitted by no site "
                        "in the scanned code — dead declaration or missing "
                        "instrumentation",
                        DECLARATION_NAME,
                    )
                )
        return findings
