"""Detector 1: host-sync-in-hot-path.

The r5 judge decomposition puts ~30% of every decode step in host overhead;
``utils/step_anatomy.py`` prices that cost at runtime, but a new ``.item()``
or ``np.asarray(device_value)`` only shows up after it ships. This detector
flags host-synchronizing operations inside the modules tagged hot (engine/,
spec/, lora/, quant/, ops/):

  - ``x.item()`` — always a device->host round trip on an Array
  - ``jax.block_until_ready(...)`` / ``x.block_until_ready()``
  - ``jax.device_get(...)``
  - ``np.asarray(x)`` / ``np.array(x)`` where ``x`` is a *device* value
  - ``float(x)`` / ``int(x)`` / ``bool(x)`` coercions of a device value

"Device value" is resolved by a codebase-tuned intra-function taint: direct
``jnp.*``/``jax.*``/``lax.*`` call results, names assigned from them, the
``*_dev`` naming convention the scheduler uses for in-flight device handles
(``toks_dev``, ``out_dev``), and ``.dev`` attributes (the pipelined-window
handle). Host-side ``np.asarray(token_id_list)`` staging therefore does NOT
flag — only materializations that can stall the engine loop do.

Deliberate reconcile points (the ones step_anatomy already prices) carry
``# graftlint: sync-ok <reason>``.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import (
    Finding,
    ScanContext,
    SourceFile,
    enclosing_func,
    make_finding,
)

RULE = "host-sync"

#: modules whose engine-loop code must stay on the roofline
HOT_DIRS = (
    "dynamo_tpu/engine/",
    "dynamo_tpu/spec/",
    "dynamo_tpu/lora/",
    "dynamo_tpu/quant/",
    "dynamo_tpu/ops/",
)

_DEVICE_ROOTS = {"jnp", "lax"}
#: jax.* namespaces that produce device values. Allowlist, not blocklist:
#: jax.devices()/jax.tree.map()/jax.jit() return device handles, host trees
#: and callables — tainting them flags mesh construction
#: (np.array(jax.devices())) and similar host-side plumbing
_JAX_DEVICE_ATTRS = {"device_put", "numpy", "random", "nn", "lax", "eval_shape"}

_NP_ROOTS = {"np", "numpy"}
_NP_SYNC_FNS = {"asarray", "array"}
_COERCIONS = {"float", "int", "bool"}


def _attr_root(node: ast.AST) -> str | None:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def is_device_expr(node: ast.AST, tainted: set[str]) -> bool:
    if isinstance(node, ast.Name):
        return node.id in tainted or node.id.endswith("_dev")
    if isinstance(node, ast.Attribute):
        if node.attr == "dev" or node.attr.endswith("_dev"):
            return True
        return is_device_expr(node.value, tainted)
    if isinstance(node, ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute):
            root = _attr_root(func)
            if root in _DEVICE_ROOTS:
                return True
            if root == "jax":
                # jax.<x>.<y>(...): first attr segment after the root decides
                seg = func
                while isinstance(seg.value, ast.Attribute):
                    seg = seg.value
                return seg.attr in _JAX_DEVICE_ATTRS
            # method on a device value stays on device (x.astype(...), x.sum())
            return is_device_expr(func.value, tainted)
        if isinstance(func, ast.Name):
            return func.id in tainted
        return False
    if isinstance(node, ast.Subscript):
        return is_device_expr(node.value, tainted)
    if isinstance(node, (ast.BinOp,)):
        return is_device_expr(node.left, tainted) or is_device_expr(node.right, tainted)
    if isinstance(node, ast.UnaryOp):
        return is_device_expr(node.operand, tainted)
    if isinstance(node, ast.IfExp):
        return is_device_expr(node.body, tainted) or is_device_expr(node.orelse, tainted)
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, sf: SourceFile) -> None:
        self.sf = sf
        self.findings: list[Finding] = []
        self.taint_stack: list[set[str]] = [set()]

    @property
    def tainted(self) -> set[str]:
        return self.taint_stack[-1]

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.taint_stack.append(set())
        self.generic_visit(node)
        self.taint_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def _flag(self, node: ast.AST, message: str) -> None:
        self.findings.extend(
            make_finding(self.sf, RULE, node, message, enclosing_func(self.sf, node))
        )

    def _taint_targets(self, targets: list[ast.AST]) -> None:
        for t in targets:
            if isinstance(t, ast.Name):
                self.tainted.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                self._taint_targets(list(t.elts))

    def visit_Assign(self, node: ast.Assign) -> None:
        if is_device_expr(node.value, self.tainted):
            self._taint_targets(list(node.targets))
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None and is_device_expr(node.value, self.tainted):
            self._taint_targets([node.target])
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr == "item" and not node.args:
                self._flag(
                    node,
                    f"`{ast.unparse(node)}`: .item() forces a device->host sync "
                    "in a hot module",
                )
            elif func.attr == "block_until_ready":
                self._flag(
                    node,
                    "block_until_ready blocks the engine loop on device work "
                    "in a hot module",
                )
            elif func.attr == "device_get" and _attr_root(func) == "jax":
                self._flag(
                    node,
                    "jax.device_get materializes device values on host in a "
                    "hot module",
                )
            elif (
                func.attr in _NP_SYNC_FNS
                and _attr_root(func) in _NP_ROOTS
                and node.args
                and is_device_expr(node.args[0], self.tainted)
            ):
                self._flag(
                    node,
                    f"np.{func.attr}() on a device value transfers it to host "
                    "in a hot module",
                )
        elif (
            isinstance(func, ast.Name)
            and func.id in _COERCIONS
            and len(node.args) == 1
            and is_device_expr(node.args[0], self.tainted)
        ):
            self._flag(
                node,
                f"{func.id}() coercion of a device value forces a host sync "
                "in a hot module",
            )
        self.generic_visit(node)


class HostSyncDetector:
    rule = RULE

    def scan(self, sf: SourceFile, ctx: ScanContext) -> list[Finding]:
        if not ctx.force_hot and not sf.path.startswith(HOT_DIRS):
            return []
        v = _Visitor(sf)
        v.visit(sf.tree)
        return v.findings

    def finalize(self, files: list[SourceFile], ctx: ScanContext) -> list[Finding]:
        return []
