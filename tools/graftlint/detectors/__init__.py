"""graftlint detector registry.

Six detectors, each owning one hazard class the runtime planes only see
after it costs milliseconds (step_anatomy / compile_monitor / slo) or a
conformance test fails (prometheus exposition):

  host-sync           .item()/coercions/np.asarray/block_until_ready on
                      device values inside hot modules
  use-after-donation  donated buffers referenced after the jit call
  recompile-hazard    literal args at non-static jit positions; static/donate
                      specs that drifted from the wrapped signature
  async-blocking      blocking calls in async def; await under a sync lock
  metric-conformance  dynamo_* literals <-> DECLARED_METRIC_FAMILIES
  event-conformance   .emit("<kind>") literals <-> DECLARED_EVENT_KINDS
"""

from tools.graftlint.detectors.async_hazards import AsyncHazardDetector
from tools.graftlint.detectors.donation import DonationDetector
from tools.graftlint.detectors.event_conformance import EventConformanceDetector
from tools.graftlint.detectors.host_sync import HostSyncDetector
from tools.graftlint.detectors.metrics_conformance import MetricsConformanceDetector
from tools.graftlint.detectors.recompile import RecompileDetector

ALL_DETECTORS = (
    HostSyncDetector,
    DonationDetector,
    RecompileDetector,
    AsyncHazardDetector,
    MetricsConformanceDetector,
    EventConformanceDetector,
)

RULES = tuple(d.rule for d in ALL_DETECTORS)

__all__ = ["ALL_DETECTORS", "RULES"]
