"""Detector 3: recompile hazards at jit call sites.

``utils/compile_monitor.py`` counts cache growth at runtime
(``dynamo_engine_xla_compiles_total``) — a recompile storm shows up as a
counter after it already burned seconds of serving time. The static
complement flags the two call-shape mistakes that cause silent retraces:

  1. literal Python scalars / f-strings / dict/list/set displays passed at
     NON-static positions of a jit'd callable. Scalars weak-type the trace
     (a second call site with an array retraces), strings are outright trace
     errors unless static, and display literals rebuild a fresh pytree
     structure per call site. The fix is almost always ``static_argnames`` or
     a prebuilt ``jnp.asarray`` staged once.
  2. ``static_argnames``/``donate_argnames`` entries that do not name a
     parameter of the wrapped function, and ``static_argnums``/
     ``donate_argnums`` past the end of its positional signature — the
     classic drift bug after a signature refactor: the intended-static arg
     silently becomes traced and every distinct value compiles a variant.

Intentional cases (e.g. a literal 0 seed traced on purpose) carry
``# graftlint: recompile-ok <reason>``.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import (
    Finding,
    ScanContext,
    SourceFile,
    enclosing_func,
    make_finding,
)
from tools.graftlint.jitspec import collect_jit_specs

RULE = "recompile-hazard"


def _literal_kind(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant):
        if node.value is None:
            return None  # None is an empty pytree leaf slot — harmless
        if isinstance(node.value, bool):
            return "bool literal"
        if isinstance(node.value, (int, float, complex)):
            return "scalar literal"
        if isinstance(node.value, str):
            return "string literal"
        return None
    if isinstance(node, ast.JoinedStr):
        return "f-string"
    if isinstance(node, ast.Dict):
        return "dict display"
    if isinstance(node, (ast.List, ast.Set)):
        return f"{type(node).__name__.lower()} display"
    if isinstance(node, ast.UnaryOp) and isinstance(node.operand, ast.Constant):
        return _literal_kind(node.operand)
    return None


class RecompileDetector:
    rule = RULE

    def scan(self, sf: SourceFile, ctx: ScanContext) -> list[Finding]:
        specs = collect_jit_specs(sf.tree)
        if not specs:
            return []
        findings: list[Finding] = []

        # signature validation at the wrapper site
        for spec in specs.values():
            if spec.fn is None or spec.params is None or spec.has_varargs:
                continue
            valid = set(spec.params) | set(spec.kwonly)
            qual = enclosing_func(sf, spec.site)
            for label, names in (
                ("static_argnames", spec.static_names),
                ("donate_argnames", spec.donate_names),
            ):
                for name in sorted(names - valid):
                    findings.extend(
                        make_finding(
                            sf,
                            RULE,
                            spec.site,
                            f"{label} entry {name!r} on `{spec.key}` does not "
                            f"match the wrapped signature of "
                            f"`{spec.fn.name}` — the argument is silently "
                            "traced and every distinct value recompiles",
                            qual,
                        )
                    )
            for label, nums in (
                ("static_argnums", spec.static_nums),
                ("donate_argnums", spec.donate_nums),
            ):
                for i in sorted(nums):
                    if i >= len(spec.params):
                        findings.extend(
                            make_finding(
                                sf,
                                RULE,
                                spec.site,
                                f"{label} index {i} on `{spec.key}` is past "
                                f"the wrapped signature of `{spec.fn.name}` "
                                f"({len(spec.params)} positional params)",
                                qual,
                            )
                        )

        # literal arguments at non-static positions of known jit callables
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            try:
                key = ast.unparse(node.func)
            except Exception:
                continue
            spec = specs.get(key)
            if spec is None or spec.site is node:
                continue
            qual = enclosing_func(sf, node)
            for i, arg in enumerate(node.args):
                if spec.is_static_pos(i):
                    continue
                kind = _literal_kind(arg)
                if kind is not None:
                    where = (
                        f"param `{spec.params[i]}`"
                        if spec.params is not None and i < len(spec.params)
                        else f"position {i}"
                    )
                    findings.extend(
                        make_finding(
                            sf,
                            RULE,
                            arg,
                            f"{kind} passed to jit'd `{spec.key}` at "
                            f"non-static {where} — weak-typed retrace/"
                            "per-call-site variant; make it static or stage "
                            "an array once",
                            qual,
                        )
                    )
            for kw in node.keywords:
                if kw.arg is None or spec.is_static_kw(kw.arg):
                    continue
                kind = _literal_kind(kw.value)
                if kind is not None:
                    findings.extend(
                        make_finding(
                            sf,
                            RULE,
                            kw.value,
                            f"{kind} passed to jit'd `{spec.key}` at "
                            f"non-static keyword `{kw.arg}` — make it static "
                            "or stage an array once",
                            qual,
                        )
                    )
        return findings

    def finalize(self, files: list[SourceFile], ctx: ScanContext) -> list[Finding]:
        return []
