"""Static model of the module's jit-wrapped callables.

The donation and recompile detectors both need the same facts about every
jit call site: which bound name is a jit'd callable, which argument positions
are donated, which are static, and (when the wrapped function is defined in
the same module) its parameter list. This codebase binds jit three ways:

    self._prefill = monitored_jit("prefill", self._prefill_impl,
                                  donate_argnums=(1, 2), static_argnames=("mp",))
    self._lora_write = jax.jit(_lora_write_impl, donate_argnums=(0,))

    @functools.partial(jax.jit, static_argnames=("interpret",))
    def paged_attention(...): ...

All three are collected. Resolution is intentionally same-module-only: a
wrapper around an imported function still yields a spec (donation/static sets
from the wrapper kwargs), just without a parameter list, so positional static
mapping and signature validation degrade gracefully instead of guessing.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field


@dataclass
class JitSpec:
    key: str  # bound-name unparse at call sites ("self._prefill", "fn")
    site: ast.AST  # where the wrapper was declared
    fn: ast.FunctionDef | None = None  # wrapped def, when resolved
    params: list[str] | None = None  # positional params as seen by callers
    kwonly: list[str] = field(default_factory=list)
    has_varargs: bool = False
    donate_nums: set[int] = field(default_factory=set)
    donate_names: set[str] = field(default_factory=set)
    static_nums: set[int] = field(default_factory=set)
    static_names: set[str] = field(default_factory=set)

    def is_static_pos(self, i: int) -> bool:
        if i in self.static_nums:
            return True
        return (
            self.params is not None
            and i < len(self.params)
            and self.params[i] in self.static_names
        )

    def is_static_kw(self, name: str) -> bool:
        if name in self.static_names:
            return True
        if self.params is not None and name in self.params:
            return self.params.index(name) in self.static_nums
        return False

    def donated_positions(self) -> set[int]:
        out = set(self.donate_nums)
        if self.params is not None:
            out |= {self.params.index(n) for n in self.donate_names if n in self.params}
        return out


_WRAPPER_TAILS = ("jit",)  # jax.jit, jit, compile_monitor-monitored variants
_NAMED_WRAPPERS = {"monitored_jit", "_mjit"}  # (name, fn, **jit_kwargs)


def _int_tuple(node: ast.AST) -> set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        }
    return set()


def _str_tuple(node: ast.AST) -> set[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        }
    return set()


def _jit_kwargs(call: ast.Call) -> dict[str, set]:
    out: dict[str, set] = {}
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "static_argnums"):
            out[kw.arg] = _int_tuple(kw.value)
        elif kw.arg in ("donate_argnames", "static_argnames"):
            out[kw.arg] = _str_tuple(kw.value)
    return out


def _is_jit_func(func: ast.AST) -> bool:
    s = _unparse(func)
    return s is not None and (
        s == "jit" or s.endswith(".jit") or s.split(".")[-1] in _NAMED_WRAPPERS
        or s in _NAMED_WRAPPERS
    )


def _unparse(node: ast.AST | None) -> str | None:
    if node is None:
        return None
    try:
        return ast.unparse(node)
    except Exception:
        return None


def _wrapped_expr(call: ast.Call) -> ast.AST | None:
    """The function being wrapped. jit-likes take it at args[0]; the named
    monitored wrappers exist in both (label, fn, ...) and (fn, label, ...)
    orders across this codebase, so for those the first non-Constant arg is
    the function."""
    s = _unparse(call.func) or ""
    if s in _NAMED_WRAPPERS or s.split(".")[-1] in _NAMED_WRAPPERS:
        for a in call.args:
            if not isinstance(a, ast.Constant):
                return a
        return None
    return call.args[0] if call.args else None


def _params_of(fn: ast.FunctionDef, drop_self: bool) -> tuple[list[str], list[str], bool]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
    if drop_self and names and names[0] in ("self", "cls"):
        names = names[1:]
    # kwonly params are addressable by static_argnames too; callers index
    # positionally only over ``names``
    kwonly = [p.arg for p in a.kwonlyargs]
    return names, kwonly, a.vararg is not None or a.kwarg is not None


class _DefIndex(ast.NodeVisitor):
    """function defs by module-level name and by (class, method) name."""

    def __init__(self) -> None:
        self.module_fns: dict[str, ast.FunctionDef] = {}
        self.methods: dict[str, ast.FunctionDef] = {}  # any-class method index
        self.local_fns: dict[str, ast.FunctionDef] = {}  # nested defs too

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.local_fns.setdefault(node.name, node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]


def _index_defs(tree: ast.AST) -> _DefIndex:
    idx = _DefIndex()
    for node in ast.walk(tree):
        if isinstance(node, ast.Module):
            for ch in node.body:
                if isinstance(ch, ast.FunctionDef):
                    idx.module_fns[ch.name] = ch
        elif isinstance(node, ast.ClassDef):
            for ch in node.body:
                if isinstance(ch, ast.FunctionDef):
                    idx.methods[ch.name] = ch
    idx.visit(tree)
    return idx


def _resolve_fn(expr: ast.AST | None, idx: _DefIndex) -> tuple[ast.FunctionDef | None, bool]:
    """(def node, drop_self) for the wrapped-function expression."""
    if isinstance(expr, ast.Name):
        fn = idx.module_fns.get(expr.id) or idx.local_fns.get(expr.id)
        return fn, False
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        if expr.value.id in ("self", "cls"):
            return idx.methods.get(expr.attr), True
        return None, False
    return None, False


def _spec_from_wrapper(key: str, call: ast.Call, site: ast.AST, idx: _DefIndex) -> JitSpec:
    # peel nested wrappers — `_mjit("prefill", jax.jit(fn, donate_argnums=...))`
    # carries the jit config on the INNER call — merging kwargs outermost-wins
    kw: dict[str, set] = {}
    wrapped = call
    depth = 0
    while (
        isinstance(wrapped, ast.Call) and _is_jit_func(wrapped.func) and depth < 4
    ):
        for k, v in _jit_kwargs(wrapped).items():
            kw.setdefault(k, v)
        wrapped = _wrapped_expr(wrapped)
        depth += 1
    spec = JitSpec(
        key=key,
        site=site,
        donate_nums=kw.get("donate_argnums", set()),
        donate_names=kw.get("donate_argnames", set()),
        static_nums=kw.get("static_argnums", set()),
        static_names=kw.get("static_argnames", set()),
    )
    fn, drop_self = _resolve_fn(wrapped, idx)
    if fn is not None:
        spec.fn = fn
        spec.params, spec.kwonly, spec.has_varargs = _params_of(fn, drop_self)
    return spec


def collect_jit_specs(tree: ast.AST) -> dict[str, JitSpec]:
    """Every jit-wrapped callable bound to a name in this module."""
    idx = _index_defs(tree)
    specs: dict[str, JitSpec] = {}

    for node in ast.walk(tree):
        # form 1/2: <target> = jit-wrapper(fn, **kw)
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            val = node.value
            if isinstance(val, ast.Call) and _is_jit_func(val.func):
                key = _unparse(node.targets[0])
                if key:
                    specs[key] = _spec_from_wrapper(key, val, node, idx)
        # form 3: @functools.partial(jax.jit, **kw) / bare @jax.jit decorator
        elif isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                call = None
                if isinstance(dec, ast.Call):
                    fname = _unparse(dec.func) or ""
                    if fname.split(".")[-1] == "partial" and dec.args and _is_jit_func(dec.args[0]):
                        call = dec
                    elif _is_jit_func(dec.func):
                        call = dec
                if call is not None:
                    kw = _jit_kwargs(call)
                    params, kwonly, varargs = _params_of(node, drop_self=False)
                    specs[node.name] = JitSpec(
                        key=node.name,
                        site=node,
                        fn=node,
                        params=params,
                        kwonly=kwonly,
                        has_varargs=varargs,
                        donate_nums=kw.get("donate_argnums", set()),
                        donate_names=kw.get("donate_argnames", set()),
                        static_nums=kw.get("static_argnums", set()),
                        static_names=kw.get("static_argnames", set()),
                    )
    return specs
