#!/usr/bin/env python3
"""bench_compare: diff two BENCH_*.json artifacts and gate on regressions.

The bench trajectory (BENCH_r01..r0N) had no automated regression gate: a
round could silently lose 20% of headline throughput and nothing but a human
reading two JSON files would notice. This tool compares named summary keys
between an OLD and NEW artifact, flags any key that moved past its tolerance
in the *bad* direction, and exits nonzero on regression — wire it between a
bench run and the artifact commit, or across rounds:

    python tools/bench_compare.py BENCH_r06.json BENCH_r07.json
    python tools/bench_compare.py old.json new.json \
        --key headline_tok_s:0.10 --key step_anatomy.host_frac:0.05:lower

Artifacts are accepted in either shape: the bench's own stdout line
({"metric", "value", "summary": {...}}) or the driver's round record
({"parsed": {...}, ...}). Keys are dotted paths into the summary (numeric
components index into lists, e.g. ``replay.bursty.0`` = that scenario's
goodput column). Keys missing from EITHER artifact are reported and skipped
— sections come and go between rounds; absence is not a regression (pass
``--strict`` to make it one).

``--self-check`` runs the tool against built-in synthetic artifacts (a clean
identical pair must pass, an injected regression must fail) — the lint-gate
wiring, so the gate can't itself rot.
"""

from __future__ import annotations

import argparse
import json
from dataclasses import dataclass
from typing import Optional

#: default per-key tolerance: relative move in the bad direction that flags
DEFAULT_TOL = 0.15

#: (summary path, direction, tolerance): the standing cross-round gate set.
#: direction "higher" = bigger is better (throughput, ratios, goodput);
#: "lower" = smaller is better (TTFT, host overhead fraction).
DEFAULT_KEYS: tuple = (
    ("headline_tok_s", "higher", DEFAULT_TOL),
    ("continuity_bs8_tok_s", "higher", DEFAULT_TOL),
    ("ref_workload_isl3k_osl150.tok_s", "higher", DEFAULT_TOL),
    ("ref_workload_isl3k_osl150.ttft_p50_ms", "lower", DEFAULT_TOL),
    ("http_serving.http_over_engine_ratio", "higher", DEFAULT_TOL),
    ("mla_decode_tok_s", "higher", DEFAULT_TOL),
    ("moe_decode_tok_s", "higher", DEFAULT_TOL),
    ("parity_quant_int8.speedup", "higher", DEFAULT_TOL),
    ("prefill_kv_int8.ttft_ratio", "lower", DEFAULT_TOL),
    ("spec_ngram.speedup", "higher", DEFAULT_TOL),
    ("multi_lora.mixed_tok_s_ratio", "higher", DEFAULT_TOL),
    ("fleet_prefix.ttft_ratio_bf16", "lower", DEFAULT_TOL),
    ("long_context.ttft_ms_64k", "lower", DEFAULT_TOL),
    ("disagg_stream.ttft_ratio", "lower", DEFAULT_TOL),
    # step anatomy (r7+): host overhead must not creep back up, and the
    # roofline fraction must not fall (the fused-decode before/after gate)
    ("step_anatomy.host_frac", "lower", DEFAULT_TOL),
    ("step_anatomy.roofline_frac", "higher", DEFAULT_TOL),
    # live migration (r8+): token parity is binary (any drop is a break),
    # the client-visible pause must not balloon, and migrating must keep
    # beating kill+recompute on goodput
    ("migration.parity", "higher", 0.001),
    ("migration.pause_ms_p99", "lower", 0.5),
    ("migration.goodput_delta", "higher", 1.0),
    # multi-tenant QoS (r8+): the isolation ratio must not creep toward 1
    # (B's ITL under burst, QoS on vs off), the token budget must keep
    # biting on the burst arm, and critical goodput under burst must hold
    ("qos.tenant_b_itl_ratio", "lower", 0.5),
    ("qos.shed_fraction", "higher", 0.5),
    ("qos.critical_goodput", "higher", 0.1),
    # flight recorder (r16+): the journal's hot-path cost must stay a
    # rounding error of a decode step, and the forensic read must stay
    # interactive (generous tolerances: both are timer-noise-prone on
    # shared CPU-smoke machines)
    ("events.emit_frac", "lower", 1.0),
    ("events.rec_ms", "lower", 1.0),
    # router index under prefix churn (r17+): lookup p99 must stay flat
    # (generous tolerance — single-digit-microsecond timers on shared
    # CPU-smoke machines), the bounded index must not outgrow its cap
    # (resident count is the contract), and the hot-working-set hit ratio
    # must hold
    ("router_scale.lookup_p99_ms", "lower", 1.0),
    ("router_scale.resident_nodes", "lower", 0.10),
    ("router_scale.hot_hit_ratio", "higher", 0.05),
    # third KV tier (r18+): disk-restore resume must keep beating the
    # recompute arm, the resumed continuation must stay token-identical
    # (binary — any drop is a break), and the disk-resident footprint
    # after the standard churn must not balloon
    ("kv_tiers.resume_ttft_ratio", "lower", DEFAULT_TOL),
    ("kv_tiers.restore_parity", "higher", 0.001),
    ("kv_tiers.disk_resident_bytes", "lower", DEFAULT_TOL),
    # prefill anatomy (r19+): the pipelined arm's per-call fixed cost and
    # TTFT must not creep back up, and the dispatch count must not balloon
    # (fewer, larger packed calls is the whole attack). Generous
    # tolerances — all three are timer-noise-prone on CPU-smoke machines
    ("prefill_anatomy.fixed_ms", "lower", 1.0),
    ("prefill_anatomy.dispatches", "lower", 0.5),
    ("prefill_anatomy.ttft_p50_ms", "lower", 1.0),
    # cost attribution (r20+): the worst conservation residual across both
    # planes must stay a rounding error (the identities are by-construction
    # exact; any growth means an unmetered seam crept in), and the metering
    # hot-path's per-step price must stay a rounding error of a decode step
    # (generous tolerance — timer-noise-prone on shared CPU-smoke machines)
    ("metering.err", "lower", 1.0),
    ("metering.frac", "lower", 1.0),
    # replay goodput columns (aliased arrays; index 0 = goodput)
    ("replay.bursty.0", "higher", DEFAULT_TOL),
    ("replay.lctx.0", "higher", DEFAULT_TOL),
    ("replay.lora.0", "higher", DEFAULT_TOL),
    ("replay.spec.0", "higher", DEFAULT_TOL),
)


@dataclass
class KeyResult:
    path: str
    old: Optional[float]
    new: Optional[float]
    direction: str
    tolerance: float
    status: str  # ok | regression | missing

    def line(self) -> str:
        def f(v):
            return "absent" if v is None else f"{v:g}"

        arrow = {"ok": "  ", "regression": "✗ ", "missing": "? "}[self.status]
        return (
            f"{arrow}{self.path}: {f(self.old)} -> {f(self.new)} "
            f"({self.direction} better, tol {self.tolerance:.0%}) {self.status}"
        )


def extract_summary(artifact: dict) -> dict:
    """Summary dict from either artifact shape (bench line or driver
    record); an artifact with no summary compares as all-absent."""
    if not isinstance(artifact, dict):
        return {}
    if isinstance(artifact.get("summary"), dict):
        return artifact["summary"]
    parsed = artifact.get("parsed")
    if isinstance(parsed, dict) and isinstance(parsed.get("summary"), dict):
        return parsed["summary"]
    return {}


def lookup(summary: dict, path: str) -> Optional[float]:
    """Resolve a dotted path; numeric components index lists. None for any
    miss or a non-numeric leaf."""
    cur = summary
    for part in path.split("."):
        if isinstance(cur, dict):
            if part not in cur:
                return None
            cur = cur[part]
        elif isinstance(cur, (list, tuple)):
            try:
                cur = cur[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def compare_one(
    old: dict, new: dict, path: str, direction: str, tolerance: float
) -> KeyResult:
    a, b = lookup(old, path), lookup(new, path)
    if a is None or b is None:
        return KeyResult(path, a, b, direction, tolerance, "missing")
    if direction == "lower":
        bad = b > a * (1.0 + tolerance) + 1e-12
    else:
        bad = b < a * (1.0 - tolerance) - 1e-12
    return KeyResult(path, a, b, direction, tolerance,
                     "regression" if bad else "ok")


def compare(old: dict, new: dict, keys=DEFAULT_KEYS) -> list[KeyResult]:
    o, n = extract_summary(old), extract_summary(new)
    return [compare_one(o, n, path, direction, tol)
            for path, direction, tol in keys]


def parse_key_spec(spec: str, default_tol: float) -> tuple:
    """``path[:tol[:direction]]`` -> (path, direction, tol)."""
    parts = spec.split(":")
    path = parts[0]
    tol = float(parts[1]) if len(parts) > 1 and parts[1] else default_tol
    direction = parts[2] if len(parts) > 2 and parts[2] else "higher"
    if direction not in ("higher", "lower"):
        raise ValueError(f"direction must be higher|lower, got {direction!r}")
    return (path, direction, tol)


def _synthetic(headline: float = 6000.0, host_frac: float = 0.30) -> dict:
    """A minimal bench-line-shaped artifact for the self-check."""
    return {
        "metric": "engine_decode_throughput_llama1.3b_bf16",
        "value": headline,
        "summary": {
            "headline_tok_s": headline,
            "continuity_bs8_tok_s": headline / 4.5,
            "step_anatomy": {"host_frac": host_frac, "roofline_frac": 0.7},
            "replay": {"bursty": [0.98, 2600, 140, 33.6]},
        },
    }


def self_check() -> list[str]:
    """Built-in conformance of the gate itself: identical artifacts must
    pass; an injected throughput drop and a host-overhead creep must each
    flag. Returns problems (empty = healthy)."""
    problems = []
    clean = compare(_synthetic(), _synthetic())
    if any(r.status == "regression" for r in clean):
        problems.append("identical artifacts flagged a regression")
    worse = compare(_synthetic(), _synthetic(headline=4000.0))
    if not any(r.status == "regression" and r.path == "headline_tok_s"
               for r in worse):
        problems.append("33% headline drop not flagged")
    crept = compare(_synthetic(), _synthetic(host_frac=0.45))
    if not any(r.status == "regression" and r.path == "step_anatomy.host_frac"
               for r in crept):
        problems.append("host_frac creep (lower-better key) not flagged")
    better = compare(_synthetic(headline=4000.0), _synthetic(headline=6000.0))
    if any(r.status == "regression" for r in better):
        problems.append("an improvement was flagged as a regression")
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="diff two BENCH_*.json artifacts; exit 1 on regression"
    )
    p.add_argument("old", nargs="?", help="baseline artifact path")
    p.add_argument("new", nargs="?", help="candidate artifact path")
    p.add_argument("--key", action="append", default=[],
                   metavar="PATH[:TOL[:higher|lower]]",
                   help="summary key to gate (replaces the default set; "
                        "repeatable)")
    p.add_argument("--tol", type=float, default=DEFAULT_TOL,
                   help="default relative tolerance for --key specs")
    p.add_argument("--strict", action="store_true",
                   help="treat keys missing from either artifact as failures")
    p.add_argument("--quiet", action="store_true",
                   help="print regressions only")
    p.add_argument("--self-check", action="store_true",
                   help="validate the gate against built-in synthetic "
                        "artifacts (the lint-gate wiring)")
    args = p.parse_args(argv)

    if args.self_check:
        problems = self_check()
        for prob in problems:
            print(f"FAIL bench_compare self-check: {prob}")
        if not problems:
            print("ok: bench_compare self-check passed")
        return 1 if problems else 0

    if not args.old or not args.new:
        p.error("OLD and NEW artifact paths are required (or --self-check)")
    with open(args.old) as f:
        old = json.load(f)
    with open(args.new) as f:
        new = json.load(f)
    keys = (
        tuple(parse_key_spec(s, args.tol) for s in args.key)
        if args.key else DEFAULT_KEYS
    )
    results = compare(old, new, keys)
    regressions = [r for r in results if r.status == "regression"]
    missing = [r for r in results if r.status == "missing"]
    for r in results:
        if args.quiet and r.status == "ok":
            continue
        print(r.line())
    compared = len(results) - len(missing)
    print(f"compared {compared}/{len(results)} keys: "
          f"{len(regressions)} regression(s), {len(missing)} missing")
    if regressions:
        return 1
    if args.strict and missing:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
