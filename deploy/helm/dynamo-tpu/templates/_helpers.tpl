{{- define "dynamo-tpu.cplaneAddress" -}}
{{- if .Values.cplane.enabled -}}
{{ .Release.Name }}-cplane:{{ .Values.cplane.port }}
{{- else -}}
{{ required "cplane.address is required when cplane.enabled=false" .Values.cplane.address }}
{{- end -}}
{{- end }}

{{- define "dynamo-tpu.labels" -}}
app.kubernetes.io/part-of: {{ .Release.Name }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end }}

{{- define "dynamo-tpu.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag }}
{{- end }}
