"""Benchmark: serving throughput on the real TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures end-to-end engine decode throughput (output tokens/sec/chip) through
the full serving stack — async engine, continuous batching scheduler, paged KV
cache, fused sampling — on a 1.3B-parameter Llama-shaped model (bf16) that
fits a single v5e chip alongside its KV cache.

vs_baseline: the reference publishes no absolute numbers (BASELINE.json
published = {}), so the ratio is against PARITY_TARGET_TOK_S, a
roofline-derived parity bar for this config on v5e: weights ~2.5 GiB bf16,
v5e HBM BW 819 GB/s -> ~330 weight-bound steps/s ceiling; at batch 8 a
well-tuned serving stack should clear ~1000 out tok/s/chip.

Run-to-run variance: the tunneled PJRT link drifts; identical code measured
2900-6400 tok/s on the headline config across sessions, with occasional
multi-second stalls mid-run (every section moves proportionally — compare
the continuity config against r01_value_bs8 to separate environment drift
from real regressions). Sections therefore prefer DETERMINISTIC signals
(recompute token counts, restored-block counts) priced at in-section
measured rates over raw wall medians wherever a ratio is the deliverable.

Round-5 decomposition (two-length RTT-cancelling chained scans — a single
wall/N division leaves the ~100 ms tunnel RTT in every number; see
tools/profile_attn.py): decode-only ~8 ms/step wall vs 7.7 model vs the
5.05 ms weight+KV HBM floor; the lookahead paged-attention kernel (cross-
program DMA prefetch) runs AT the measured DMA floor (78.9 us/call vs the
null kernel's 92.1 — full A/B record in ops/pallas/paged_attention.py), and
the prefill phase (~20% of a round) rides the packed trace. The old "~10 ms
fixed per packed call" claim was inferred from section walls; round 19's
tools/profile_prefill.py measures it directly — two-width differencing
through the production path splits the per-call cost into a rows->0 fixed
intercept plus a per-row slope, the stage timings split the fixed part into
host-prep / H2D staging / dispatch / device residue, and a null-kernel A/B
(paged_prefill_dmaonly) separates attention compute from its DMA floor.
CPU-smoke of that split (tiny model): fixed ~3.9 ms with dispatch-return
dominating — rerun on the chip for the real numbers; lanes still pack to a
1024-row budget, and prefill_pipeline_depth (default 2) dispatch-aheads
packed calls so the fixed cost overlaps device time (bench section
prefill_anatomy proves parity + fewer forced stalls). The headline config
batches 64 sequences so weight reads amortize; bs=8 is kept as a secondary
round-over-round continuity metric.
"""

from __future__ import annotations

import asyncio
import json
import math
import time

import numpy as np

PARITY_TARGET_TOK_S = 1000.0

PROMPT_LEN = 128
DECODE_TOKENS = 128

# (batch, page_size): headline serving config + round-1-comparable config
HEADLINE = (64, 128)
CONTINUITY = (8, 16)
# round-1 measured continuity value (bs8): the fixed round-over-round anchor
R01_VALUE_BS8 = 1341.84


def bench_config(batch: int = 64, page_size: int = 64, model_id: str | None = None):
    from dynamo_tpu.engine.config import EngineConfig

    return EngineConfig(
        model_id=model_id or json_model_id(),
        page_size=page_size,
        num_pages=max(1024 * 16 // page_size, batch * 28 * 16 // page_size),
        max_seqs=batch,
        max_model_len=1024,
        prefill_buckets=(128, 256, 512),
        tp=1,
        # swept on v5e: decode_steps x pipeline_depth over {16,32,64} x {2,3,4}
        # all within ~3% - dispatch latency is hidden; 32x3 best (re-confirmed
        # r5 at lookahead-kernel speeds: 32x3 7527 > 16x4 7512 > 64x3 7437)
        decode_steps=32,
        pipeline_depth=3,
    )


def json_model_id() -> str:
    # ~1.3B params: llama-shaped (GQA 4:1), bf16
    cfg = {
        "vocab_size": 32000,
        "hidden_size": 2048,
        "intermediate_size": 5632,
        "num_layers": 24,
        "num_heads": 16,
        "num_kv_heads": 8,
        "head_dim": 128,
        "dtype": "bf16",
    }
    return "tiny:" + json.dumps(cfg)


def quant_model_id() -> str:
    """The headline llama-1.3b geometry served weight-only int8: identical
    shapes/seed to json_model_id(), so the two engines hold the SAME random
    weights before quantization and the int8-vs-bf16 comparison isolates the
    quantization itself."""
    cfg = json.loads(json_model_id().split(":", 1)[1])
    cfg["quantize"] = "int8_wo"
    return "tiny:" + json.dumps(cfg)


def mla_model_id() -> str:
    """DeepSeek-MLA geometry at ~1.3B (bf16, single v5e): real MLA head
    shapes (kv_lora_rank 512, rope 64, nope/v 128 — DeepSeek-V2 values,
    reference: the vLLM patch's deepseek_v2.py), MLP kept dense
    (first_k_dense_replace = num_layers) so the section isolates the MLA
    decode kernel; MoE is priced by moe_decode below."""
    cfg = {
        "vocab_size": 32000, "hidden_size": 2048, "intermediate_size": 5632,
        "num_layers": 24, "num_heads": 16, "q_lora_rank": None,
        "kv_lora_rank": 512, "qk_nope_head_dim": 128, "qk_rope_head_dim": 64,
        "v_head_dim": 128, "first_k_dense_replace": 24,
        "n_routed_experts": 4, "num_experts_per_tok": 2, "n_shared_experts": 1,
        "moe_intermediate_size": 32, "dtype": "bf16",
    }
    return "tiny-mla:" + json.dumps(cfg)


def moe_model_id() -> str:
    """Mixtral geometry scaled to ~2.3B total / top-2-of-8 routing (bf16):
    per-step active weights ~ attention + 2/8 of expert banks, but at serving
    batch sizes nearly every expert is hit, so the decode roofline reads the
    full expert banks each step."""
    cfg = {
        "vocab_size": 32000, "hidden_size": 1024, "intermediate_size": 3584,
        "num_layers": 12, "num_heads": 8, "num_kv_heads": 4, "head_dim": 128,
        "num_experts": 8, "num_experts_per_tok": 2, "moe_capacity_factor": 2.0,
        "dtype": "bf16",
    }
    return "tiny-moe:" + json.dumps(cfg)


def _probe_pallas(page_size: int = 64) -> None:
    """Try the Pallas decode kernel on tiny shapes; fall back to the pure-XLA
    path for the whole bench if it fails on this platform."""
    import os

    if os.environ.get("DYNTPU_PALLAS") is not None:
        return
    try:
        import jax.numpy as jnp
        from dynamo_tpu.ops.attention import (
            dispatch_paged_decode_attention,
            dispatch_paged_prefill_attention,
            use_pallas_decode,
        )

        if not use_pallas_decode(128, 8):
            return
        # probe with the bench model's exact head config (16 q / 8 kv, D=128)
        out = dispatch_paged_decode_attention(
            jnp.zeros((8, 16, 128), jnp.bfloat16),
            jnp.zeros((4, page_size, 8, 128), jnp.bfloat16),
            jnp.zeros((4, page_size, 8, 128), jnp.bfloat16),
            jnp.zeros((8, 2), jnp.int32),
            jnp.zeros(8, jnp.int32),
        )
        out.block_until_ready()
        out = dispatch_paged_prefill_attention(
            jnp.zeros((128, 16, 128), jnp.bfloat16),
            jnp.zeros((4, page_size, 8, 128), jnp.bfloat16),
            jnp.zeros((4, page_size, 8, 128), jnp.bfloat16),
            jnp.zeros(2, jnp.int32),
            jnp.arange(128, dtype=jnp.int32),
        )
        out.block_until_ready()
    except Exception as e:  # kernel unsupported here: use the XLA reference path
        import sys

        print(f"pallas probe failed ({type(e).__name__}); DYNTPU_PALLAS=0", file=sys.stderr, flush=True)
        os.environ["DYNTPU_PALLAS"] = "0"


async def run_config(
    batch: int,
    page_size: int,
    rounds: int = 3,
    prompt_len: int = PROMPT_LEN,
    decode_tokens: int = DECODE_TOKENS,
    max_model_len: int = 1024,
    model_id: str | None = None,
    vocab: int = 31000,
) -> dict:
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    cfg = bench_config(batch, page_size, model_id=model_id)
    if max_model_len != cfg.max_model_len:
        import dataclasses

        need_pages = batch * (-(-(prompt_len + decode_tokens) // page_size) + 4)
        cfg = dataclasses.replace(
            cfg,
            max_model_len=max_model_len,
            num_pages=max(cfg.num_pages, need_pages),
            # 1024 cap: long prompts run as chunked prefill; a 2048-token
            # bucket compile is heavy enough to flake the remote compiler
            prefill_buckets=(128, 256, 512, 1024),
        )
    engine = AsyncJaxEngine(cfg)
    await engine.start()

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, vocab, prompt_len).tolist() for _ in range(batch)]
    best = None
    round_tok_s = []

    async def one(i: int, warmup: bool, rnd: int = 0):
        req = EngineRequest(
            request_id=f"{'w' if warmup else 'b'}{rnd}-{i}",
            token_ids=prompts[i] if not warmup else rng.integers(1, vocab, prompt_len).tolist(),
            sampling=SamplingParams(
                temperature=0.0,
                max_tokens=8 if warmup else decode_tokens,
                ignore_eos=True,
            ),
        )
        n = 0
        ttft = None
        t0 = time.monotonic()
        async for out in engine.generate(req):
            if out.token is not None:
                if ttft is None:
                    ttft = time.monotonic() - t0
                n += 1
        return n, ttft

    try:
        # warmup: compile prefill buckets + decode, then one full-length pass
        # so the page allocator reaches its steady-state churn pattern (the
        # first measured round otherwise under-reports while the pool
        # fills/evicts)
        await asyncio.gather(*[one(i, warmup=True) for i in range(batch)])
        for i in range(batch):
            prompts[i] = rng.integers(1, vocab, prompt_len).tolist()
        await asyncio.gather(*[one(i, warmup=False, rnd=99) for i in range(batch)])

        # best of N measured rounds (fresh prompts each round so the prefix
        # cache never helps): the tunneled PJRT link adds multi-ms jitter per
        # round trip, so a single round under-reports sustained throughput
        for rnd in range(rounds):
            for i in range(batch):
                prompts[i] = rng.integers(1, vocab, prompt_len).tolist()
            t0 = time.monotonic()
            results = await asyncio.gather(*[one(i, warmup=False, rnd=rnd) for i in range(batch)])
            elapsed = time.monotonic() - t0
            total_tokens = sum(n for n, _ in results)
            ttfts = [t for _, t in results if t is not None]
            round_tok_s.append(round(total_tokens / elapsed, 2))
            if best is None or total_tokens / elapsed > best[0]:
                best = (total_tokens / elapsed, total_tokens, elapsed, ttfts)
        # per-stage latency attribution (engine StageStats, cumulative over
        # warmup + all rounds): lets a round's artifact answer whether TTFT
        # sits in queue wait, prefill dispatch, or device sync without a
        # re-run under DYNTPU_TRACE
        stage = engine.stage_snapshot()
    finally:
        # a cancelled/timed-out section must still release the engine (HBM,
        # device buffers) before the next section starts its own
        await engine.shutdown()
    tok_s, total_tokens, elapsed, ttfts = best
    return {
        "tok_s": round(tok_s, 2),
        "total_output_tokens": total_tokens,
        "elapsed_s": round(elapsed, 3),
        "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 1),
        "batch": batch,
        "page_size": page_size,
        "prompt_len": prompt_len,
        "decode_tokens": decode_tokens,
        "rounds": round_tok_s,
        "stage_breakdown": stage,
    }


async def _request(eng, rid, prompt, max_tokens=8, holder="", holder_blocks=0):
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    req = EngineRequest(
        request_id=rid, token_ids=list(prompt),
        sampling=SamplingParams(temperature=0.0, max_tokens=max_tokens, ignore_eos=True),
        kv_holder_addr=holder, kv_holder_blocks=holder_blocks,
    )
    t0 = time.monotonic()
    ttft, toks, cached = None, [], 0
    async for out in eng.generate(req):
        if out.token is not None and ttft is None:
            ttft = time.monotonic() - t0
        if out.token is not None:
            toks.append(out.token)
        cached = max(cached, out.cached_tokens)
    if ttft is None:
        raise RuntimeError(f"bench request {rid} yielded no tokens")
    return toks, ttft, cached


def _parity_config(**over):
    from dynamo_tpu.engine.config import EngineConfig

    d = dict(
        model_id=json_model_id(), page_size=64, num_pages=384, max_seqs=4,
        max_model_len=4096, prefill_buckets=(512, 1024, 2048),
        decode_steps=8, pipeline_depth=2,
    )
    d.update(over)
    return EngineConfig(**d)


async def run_routing_parity(n_workers=2, sessions=4, turns=3, plen=3072) -> dict:
    """BASELINE.md parity checkpoint: KV-aware routing vs random on
    prefix-heavy multi-turn traffic across two colocated engines.

    Reports both wall TTFT (compressed on this testbed by the ~100 ms tunnel
    RTT floor every request pays) and recomputed prefill tokens — the actual
    TTFT driver the reference's 3x claim comes from."""
    import gc
    import random

    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.llm.kv_router.indexer import KvIndexer, RouterEvent

    async def workload(kv_aware: bool):
        indexer = KvIndexer(kv_block_size=64)
        engines = []
        try:
            for i in range(n_workers):
                sink = (lambda wid: (
                    lambda ev: indexer.apply_event(RouterEvent(worker_id=wid, event=ev))
                ))(i)
                eng = AsyncJaxEngine(_parity_config(), kv_event_sink=sink)
                await eng.start()
                engines.append(eng)
            rng = random.Random(7)
            rr = np.random.default_rng(3)
            # prompts long enough that a full recompute (~plen tokens of
            # prefill chip time) clears the tunnel's run-to-run wall noise
            # (~±35 ms between arms measured in r4) — at 1536 the signal
            # drowned in it
            hist = {s: rr.integers(1, 31000, plen).tolist() for s in range(sessions)}
            seed_ttfts = []
            for s in range(sessions):
                _, st, _ = await _request(engines[s % n_workers], f"seed{kv_aware}-{s}", hist[s])
                seed_ttfts.append(st)
            seed_ttft = float(np.median(seed_ttfts))
            # RTT floor: a fully-cached re-send's prefill is one cache-hit
            # chunk, so its wall TTFT is ~pure dispatch/tunnel round trip.
            # Subtracting it from measured TTFTs yields the in-situ numbers
            # the reference's 3x claim compares (its testbed has no ~100 ms
            # per-request RTT; ours does and it floors every wall number).
            rtts = []
            for k in range(3):
                _, rtt, _ = await _request(engines[0], f"rtt{kv_aware}-{k}", hist[0])
                rtts.append(rtt)
            rtt_floor = float(np.median(rtts))
            ttfts, recompute = [], 0
            for t in range(turns):
                for s in range(sessions):
                    prompt = hist[s]
                    if kv_aware:
                        scores = indexer.find_matches_for_request(prompt).scores
                        wid = max(scores, key=scores.get) if scores else rng.randrange(n_workers)
                    else:
                        wid = rng.randrange(n_workers)
                    toks, ttft, cached = await _request(engines[wid], f"{kv_aware}r{t}-{s}", prompt)
                    ttfts.append(ttft)
                    recompute += len(prompt) - cached
                    hist[s] = (prompt + toks + [11 + t])[:3600]
        finally:
            for e in engines:
                try:
                    await e.shutdown()
                except Exception:
                    import traceback

                    traceback.print_exc()
            engines.clear()
            gc.collect()
        return float(np.median(ttfts)), recompute, rtt_floor, seed_ttft

    t_kv, rc_kv, rtt_kv, seed_kv = await workload(True)
    t_rand, rc_rand, rtt_rand, seed_rand = await workload(False)
    # Two views of the same claim:
    #   measured — wall TTFT medians minus ONE common dispatch floor (the
    #     smaller probe; per-arm floors inject tunnel drift into the ratio).
    #     On this rig the tunnel drifts tens of ms BETWEEN arms run-to-run,
    #     so this view is noisy at the ~50 ms recompute scale.
    #   derived — the deterministic recomputed-token counts priced at the
    #     per-token prefill rate measured in-section from the seeding
    #     requests (fresh full prefills). Recompute counts are exact and
    #     repeatable; this is the drift-free apples-to-apples number for the
    #     reference's zero-RTT testbed claim.
    eps = 2e-3
    rtt = min(rtt_kv, rtt_rand)
    ins_kv = max(t_kv - rtt, eps)
    ins_rand = max(t_rand - rtt, eps)
    n_req = sessions * turns
    rate = max(min(seed_kv, seed_rand) - rtt, eps) / plen  # s per prefill token
    der_kv = rc_kv / n_req * rate
    der_rand = rc_rand / n_req * rate
    return {
        "ttft_kv_aware_ms": round(t_kv * 1e3, 1),
        "ttft_random_ms": round(t_rand * 1e3, 1),
        "ttft_ratio": round(t_rand / t_kv, 2),
        "rtt_floor_ms": {"kv": round(rtt_kv * 1e3, 1), "random": round(rtt_rand * 1e3, 1)},
        "ttft_insitu_kv_aware_ms": round(ins_kv * 1e3, 1),
        "ttft_insitu_random_ms": round(ins_rand * 1e3, 1),
        "ttft_insitu_ratio_measured": round(ins_rand / ins_kv, 2),
        "recomputed_prefill_tokens_kv_aware": rc_kv,
        "recomputed_prefill_tokens_random": rc_rand,
        "recompute_ratio": round(rc_rand / max(1, rc_kv), 1),
        "prefill_rate_us_per_token": round(rate * 1e6, 1),
        "ttft_derived_kv_aware_ms": round(der_kv * 1e3, 1),
        "ttft_derived_random_ms": round(der_rand * 1e3, 1),
        # denominator floored at one KV block's prefill so a perfect cache
        # (rc_kv ~ 0) can't divide by ~0
        "ttft_insitu_ratio_derived": round(der_rand / max(der_kv, rate * 64), 2),
        "target": "ttft_insitu_ratio_derived >= 3 (BASELINE.md: reference claims 3x TTFT)",
        "note": (
            "derived = deterministic recompute counts x in-section measured "
            "prefill rate (drift-free); measured = wall medians minus the "
            "common dispatch floor (noisy at this scale on the tunnel)"
        ),
    }


def _measure_restore(eng) -> dict:
    """Measure the two restore-path components this rig CAN time:

      scatter (measured): block bytes already device-resident -> jitted
        scatter into the donated pool. Amortized over a batch to cancel the
        ~100 ms dispatch RTT. This is the on-chip half of any restore.
      tunnel (measured): the same batch with host-resident bytes — the wall
        path on THIS rig (PJRT tunnel). Explains the raw wall TTFT numbers.

    The host-DRAM->HBM transfer of a real TPU-VM cannot be produced here, so
    the projection prices that leg at an ASSUMED 10 GB/s and labels it."""
    import time as _time

    import jax.numpy as jnp

    one = eng.runner.extract_pages(np.asarray([1], np.int32))
    axis = getattr(eng.runner.model, "wire_n_axis", 2)
    nbytes_block = one.nbytes

    def batch(n):
        data = np.concatenate([one] * n, axis=axis)
        ids = np.arange(1, n + 1, dtype=np.int32)
        return ids, data

    def timed(ids, data, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = _time.monotonic()
            eng.runner.inject_pages(ids, data)
            # np.asarray forces completion (block_until_ready lies on axon)
            np.asarray(eng.runner.kv_cache["k"][1, 0, :1])
            best = min(best, _time.monotonic() - t0)
        return best

    ids1, d1 = batch(1)
    ids16, d16 = batch(16)
    # host-resident bytes: the tunnel path (what this rig's wall TTFT pays)
    t1 = timed(ids1, d1)
    t16 = timed(ids16, d16)
    tunnel_bw = 15 * nbytes_block / max(t16 - t1, 1e-6)
    # device-staged bytes: the same scatter with no host->device transfer —
    # the measured on-chip floor of the restore path
    d1_dev, d16_dev = jnp.asarray(d1), jnp.asarray(d16)
    np.asarray(d16_dev[..., :1, :, :1])  # staging paid outside the timing
    s1 = timed(ids1, d1_dev)
    s16 = timed(ids16, d16_dev)
    staged_bw = 15 * nbytes_block / max(s16 - s1, 1e-6)
    return {
        "block_wire_bytes": int(nbytes_block),
        "tunnel_bw_MBps_measured": round(tunnel_bw / 1e6, 1),
        "scatter_bw_GBps_measured_device_staged": round(staged_bw / 1e9, 2),
        "scatter_s_per_block_measured": max((s16 - s1) / 15, 1e-9),
    }


async def run_offload_parity(sessions=3, plen=512) -> dict:
    """BASELINE.md parity checkpoint: host-DRAM KV offload on multi-turn
    revisit traffic, device pool sized so revisits need the host tier.

    On this testbed host<->device block movement rides the PJRT tunnel
    (~13 MB/s vs local PCIe on a real TPU-VM), so wall TTFT is reported but
    the honest signal is restored-vs-recomputed prefix tokens."""
    import dataclasses
    import gc

    from dynamo_tpu.engine.engine import AsyncJaxEngine

    # (64, 512): the 48-token dispatch-floor probe must land in a SMALL
    # bucket — with 512 as the only bucket the probe itself paid a full
    # 512-row prefill, and subtracting it erased the very recompute cost
    # being measured (r4 post-mortem: recompute_ms came out 2.7 ms when a
    # 512-token prefill actually costs ~15 ms)
    base_cfg = _parity_config(
        num_pages=20, max_seqs=2, max_model_len=1024, prefill_buckets=(64, 512)
    )

    async def workload(host_blocks: int):
        eng = AsyncJaxEngine(
            dataclasses.replace(base_cfg, host_cache_blocks=host_blocks)
        )
        await eng.start()
        try:
            rr = np.random.default_rng(5)
            prompts = {s: rr.integers(1, 31000, plen).tolist() for s in range(sessions)}
            for s in range(sessions):
                await _request(eng, f"h{host_blocks}-v1-{s}", prompts[s])
            # dispatch-floor probe: a 1-page prompt's TTFT is ~one tunnel
            # round trip + one small prefill chunk (device pool is too small
            # to keep revisit prompts cached, so a full-cache-hit probe isn't
            # constructible here; the short chunk's compute is ~1 ms)
            rtts = []
            for k in range(3):
                _, rtt, _ = await _request(
                    eng, f"h{host_blocks}-rtt-{k}", prompts[0][:48]
                )
                rtts.append(rtt)
            rtt_floor = float(np.median(rtts))
            # measured recompute cost of one plen-token prefill: M concurrent
            # FRESH prompts serialize on the chip, so (wall - rtt)/M amortizes
            # the dispatch floor away (same technique as the disagg section's
            # wp). The revisit TTFT medians below can't give this number —
            # the device pool retains the most recent sessions' blocks, so
            # the median revisit is often a cache hit, not a recompute.
            Mf = 4
            fresh = [rr.integers(1, 31000, plen).tolist() for _ in range(Mf)]
            t0 = time.monotonic()
            await asyncio.gather(*[
                _request(eng, f"h{host_blocks}-fresh-{j}", fresh[j], max_tokens=1)
                for j in range(Mf)
            ])
            recompute_s = max(0.0, (time.monotonic() - t0) - rtt_floor) / Mf
            ttfts, cacheds = [], []
            for s in range(sessions):
                _, ttft, cached = await _request(eng, f"h{host_blocks}-v2-{s}", prompts[s])
                ttfts.append(ttft)
                cacheds.append(cached)
            loads = eng.offload.loads if eng.offload else 0
            restore = _measure_restore(eng) if host_blocks else None
        finally:
            await eng.shutdown()
            del eng
            gc.collect()
        return (float(np.median(ttfts)), int(np.sum(cacheds)), loads, rtt_floor,
                recompute_s, restore)


    t_on, cached_on, loads, rtt_on, _, restore = await workload(256)
    t_off, cached_off, _, rtt_off, recompute_s, _ = await workload(0)
    eps = 2e-3
    # in-situ revisit TTFTs with the dispatch floor excluded
    ins_on = max(t_on - rtt_on, eps)
    ins_off = max(t_off - rtt_off, eps)
    # Hardware projection for the restore path: on this rig the host tier's
    # block loads ride the PJRT tunnel (bandwidth MEASURED in-section above),
    # which buries the restore under transfer time; on a real TPU-VM the same
    # loads are local host-DRAM -> HBM copies. The projection's two legs are
    # labeled by provenance: the on-chip scatter is MEASURED (device-staged
    # bytes, amortized batch), the host-DRAM transfer is ASSUMED at 10 GB/s
    # (not producible on this rig).
    mcfg = json.loads(base_cfg.model_id.split(":", 1)[1])
    block_bytes = (
        base_cfg.page_size * mcfg["num_kv_heads"] * mcfg["head_dim"] * 2 * 2
        * mcfg["num_layers"]
    )
    loads_per_revisit = loads / max(1, sessions)
    transfer_s = loads_per_revisit * block_bytes / 10e9
    scatter_s = loads_per_revisit * (
        restore["scatter_s_per_block_measured"] if restore else 0.0
    )
    restore_s_projected = transfer_s + scatter_s
    projected_ratio = recompute_s / max(restore_s_projected, eps)
    return {
        "ttft_offload_ms": round(t_on * 1e3, 1),
        "ttft_no_offload_ms": round(t_off * 1e3, 1),
        "rtt_floor_ms": {"offload": round(rtt_on * 1e3, 1), "none": round(rtt_off * 1e3, 1)},
        "ttft_insitu_offload_ms": round(ins_on * 1e3, 1),
        "ttft_insitu_no_offload_ms": round(ins_off * 1e3, 1),
        "revisit_tokens_restored_with_offload": cached_on,
        "revisit_tokens_restored_without": cached_off,
        "host_block_loads": loads,
        "restore_path_measured": restore,
        "projection": {
            "block_bytes": block_bytes,
            "loads_per_revisit": round(loads_per_revisit, 1),
            "transfer_ms_at_10GBps_assumed": round(transfer_s * 1e3, 2),
            "scatter_ms_measured": round(scatter_s * 1e3, 2),
            "restore_ms_projected": round(restore_s_projected * 1e3, 2),
            "recompute_ms_measured": round(recompute_s * 1e3, 1),
            "ttft_ratio_projected": round(projected_ratio, 2),
            "restore_bw_source": "scatter=measured(device-staged); transfer=assumed(10GB/s); wall=tunnel(measured)",
        },
        "target": "ttft_ratio_projected >= 1.4 (BASELINE.md: reference claims 1.4x TTFT)",
        "note": (
            "wall TTFT with offload is tunnel-transfer-bound on this rig "
            "(tunnel bw measured in restore_path_measured); the projection "
            "combines the MEASURED on-chip scatter cost with an ASSUMED "
            "10 GB/s TPU-VM host-DRAM transfer leg, against the measured "
            "recompute prefill time"
        ),
    }


async def run_kv_tiers(sessions=3, plen=512, fillers=6) -> dict:
    """Third KV tier (engine/kv_store.py): disk-backed cold-session resume.

    Multi-turn sessions generate, then PARK while filler traffic churns the
    HBM pool and a deliberately small host tier — demoting the parked
    sessions' blocks host -> disk. The resume turn revisits the parked
    prompts: the tiered arm restores from disk through the FETCHING_KV
    deferred-admission path, the control arm (no off-device tiers)
    recomputes the prefill. Headline is the resume-TTFT ratio
    (tiered/recompute, lower is better), exact greedy parity between the
    arms, and the disk byte cap held under churn.

    Both arms run an int8 KV cache so the disk tier's int8 wire format is a
    bit-exact roundtrip — parity is exact, not approximate. CPU smoke on
    this rig (platform tag rides the artifact); both arms pay the same
    dispatch floor, so the wall ratio is honest."""
    import dataclasses
    import gc

    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.kv_store import DiskKvStore, _block_disk_nbytes, disk_block_bytes

    base_cfg = _parity_config(
        num_pages=20, max_seqs=2, max_model_len=1024, prefill_buckets=(64, 512),
        kv_cache_dtype="int8",
    )
    mcfg = json.loads(base_cfg.model_id.split(":", 1)[1])
    blk = disk_block_bytes(
        base_cfg.page_size, mcfg["num_kv_heads"], mcfg["head_dim"],
        mcfg["num_layers"],
    )
    # generous budget for the resume arms (parked sessions + filler churn
    # both fit: the cap-under-churn proof runs store-level below where the
    # eviction victim choice can't race the resume measurement)
    disk_budget = blk * (sessions + fillers + 2) * (plen // base_cfg.page_size + 1)

    async def workload(tiered: bool):
        cfg = dataclasses.replace(
            base_cfg,
            host_cache_blocks=8 if tiered else 0,
            disk_cache_bytes=disk_budget if tiered else 0,
        )
        eng = AsyncJaxEngine(cfg)
        await eng.start()
        try:
            rr = np.random.default_rng(11)
            prompts = {s: rr.integers(1, 31000, plen).tolist() for s in range(sessions)}
            turn1 = {}
            for s in range(sessions):
                toks, _, _ = await _request(eng, f"kt{int(tiered)}-v1-{s}", prompts[s])
                turn1[s] = toks
            # park: filler churn evicts the parked sessions from HBM and
            # (tiered arm) pushes their host copies down to disk
            for j in range(fillers):
                filler = rr.integers(1, 31000, plen).tolist()
                await _request(eng, f"kt{int(tiered)}-fill-{j}", filler, max_tokens=1)
            # resume: the same conversations come back cold
            ttfts, cacheds, turn2 = [], [], {}
            for s in range(sessions):
                toks, ttft, cached = await _request(
                    eng, f"kt{int(tiered)}-v2-{s}", prompts[s]
                )
                ttfts.append(ttft)
                cacheds.append(cached)
                turn2[s] = toks
            snap = eng.resource_snapshot()
        finally:
            await eng.shutdown()
            del eng
            gc.collect()
        return (float(np.median(ttfts)), int(np.sum(cacheds)), turn1, turn2, snap)

    t_tier, cached_tier, t1_tier, t2_tier, snap = await workload(True)
    t_rec, cached_rec, _, t2_rec, _ = await workload(False)
    if not snap.get("disk_restore_hits"):
        raise RuntimeError(
            f"tiered arm never took the disk restore path (snapshot: "
            f"spills={snap.get('disk_spills')} restores={snap.get('disk_restores')} "
            f"fallbacks={snap.get('disk_restore_fallbacks')})"
        )
    if snap.get("disk_bytes_resident", 0) > snap.get("disk_budget_bytes", 0):
        raise RuntimeError("disk tier over budget after churn")
    # exact greedy parity: the resumed continuation must match both the
    # recompute arm AND the never-parked turn-1 output (same prompt, greedy)
    parity = sum(
        1 for s in t2_tier
        if t2_tier[s] == t2_rec.get(s) and t2_tier[s] == t1_tier.get(s)
    ) / max(1, len(t2_tier))
    # cap-under-churn proof at the store level: a 4-block budget churned
    # with 16 distinct blocks must hold the cap and actually evict
    rr = np.random.default_rng(23)
    shape = (4, 2, 2, base_cfg.page_size, 16)
    probe = rr.standard_normal(shape).astype(np.float32)
    probe_bytes = _block_disk_nbytes(probe)
    store = DiskKvStore(budget_bytes=4 * probe_bytes, page_axis=2,
                        block_bytes=probe_bytes)
    max_resident = 0
    try:
        for h in range(16):
            store.spill(h + 1, rr.standard_normal(shape).astype(np.float32))
            max_resident = max(max_resident, store.bytes_resident)
        churn_drops = store.drops
        store.flush()
    finally:
        store.close()
    if max_resident > 4 * probe_bytes:
        raise RuntimeError("store-level churn exceeded the disk byte cap")
    if churn_drops < 12:
        raise RuntimeError(f"store-level churn under-evicted ({churn_drops} drops)")
    return {
        "resume_ttft_tiered_ms": round(t_tier * 1e3, 1),
        "resume_ttft_recompute_ms": round(t_rec * 1e3, 1),
        "resume_ttft_ratio": round(t_tier / max(t_rec, 1e-9), 3),
        "resume_tokens_restored_tiered": cached_tier,
        "resume_tokens_restored_recompute": cached_rec,
        "restore_parity": parity,
        "disk": {
            "spills": snap.get("disk_spills"),
            "restores": snap.get("disk_restores"),
            "restore_hits": snap.get("disk_restore_hits"),
            "restore_fallbacks": snap.get("disk_restore_fallbacks"),
            "restore_tokens": snap.get("disk_restore_tokens"),
            "io_errors": snap.get("disk_io_errors"),
            "blocks_resident": snap.get("disk_blocks_resident"),
            "bytes_resident": snap.get("disk_bytes_resident"),
            "budget_bytes": snap.get("disk_budget_bytes"),
        },
        "cap_under_churn": {
            "budget_bytes": 4 * probe_bytes,
            "max_resident_bytes": max_resident,
            "drops": churn_drops,
        },
        "target": "resume_ttft_ratio < 1.0 (disk restore beats recompute)",
        "note": (
            "tiered arm: 8-block host tier + disk; sessions park while "
            "filler traffic demotes their blocks host -> disk, then resume "
            "through the FETCHING_KV restore path. int8 KV cache in both "
            "arms -> the disk wire format roundtrips bit-exact and parity "
            "is exact"
        ),
    }


async def run_disagg_parity(
    clients: int = 18, n_requests: int = 24, plen: int = 3072, osl: int = 150,
    batch: int = 12, page_size: int = 128,
) -> dict:
    """BASELINE.md parity checkpoint #1: disaggregated prefill/decode vs
    aggregated throughput per chip, reference workload shape (3K ISL/150 OSL;
    reference claim: +30 percent per GPU single-node, docs/architecture.md:57-61).

    Three measurements, all on the one real chip:
      measured_aggregated   — one engine, continuous closed-loop traffic
                              (prefill/decode interference included)
      measured_disagg_1chip — REAL two-worker disagg (prefill worker + decode
                              worker + broker, ICI in-process KV handoff) on
                              the same chip. Both workers share the chip, so
                              this proves the path and prices the KV-transfer
                              overhead — it cannot show the specialization
                              win (that needs >= 2 chips).
      projected_disagg      — the specialization arithmetic with every term
                              measured: per-request prefill chip-time Wp
                              (prefill-only), per-request decode chip-time cd
                              (decode-only), so a disagg pool split costs
                              Wp + cd chip-seconds per request with no
                              interference. ratio_projected = that throughput
                              vs measured_aggregated — the falsifiable analogue
                              of the reference's >= 1.3x single-host claim.
    """
    import gc
    import time as _time

    from dynamo_tpu.cplane.broker import Broker
    from dynamo_tpu.disagg.decode_worker import DisaggDecodeEngine
    from dynamo_tpu.disagg.prefill_worker import PrefillWorker
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.llm.disagg_router import DisaggregatedRouter, DisaggRouterConf
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    pages_per_seq = -(-(plen + osl) // page_size) + 2
    # HBM budget (r5 post-mortem: the r4-sized section OOM'd at batch=16 —
    # decode pool 6.2 GB + prefill pool 2.3 GB + 2x 2.5 GB weights left no
    # slack, and a mid-section RESOURCE_EXHAUSTED poisons the process's
    # allocator so every LATER section dies at init; batch=12 keeps the
    # two-worker phase near 11 GB of the 16 GB chip)
    decode_cfg = _parity_config(
        page_size=page_size, max_seqs=batch, max_model_len=4096,
        num_pages=(batch + 2) * pages_per_seq + 8,
        prefill_buckets=(512, 1024), decode_steps=32, pipeline_depth=3,
    )
    rng = np.random.default_rng(11)
    M = 6  # prefill-cost sample size
    prompts = [
        rng.integers(1, 31000, plen).tolist()
        for _ in range(n_requests + M + batch + 1)
    ]
    wp_prompts = prompts[n_requests : n_requests + M]
    cd_prompts = prompts[n_requests + M : n_requests + M + batch]
    warm_prompt = prompts[-1]

    async def continuous(eng, tag: str) -> dict:
        """Closed-loop with `clients` in flight until n_requests finish."""
        done = []
        ttfts = []
        next_i = 0
        t0 = _time.monotonic()

        async def client():
            nonlocal next_i
            while next_i < n_requests:
                i = next_i
                next_i += 1
                toks, ttft, _ = await _request(
                    eng, f"{tag}-{i}", prompts[i], max_tokens=osl
                )
                done.append(len(toks))
                ttfts.append(ttft)

        await asyncio.gather(*[client() for _ in range(clients)])
        elapsed = _time.monotonic() - t0
        return {
            "tok_s": round(sum(done) / elapsed, 2),
            "requests": len(done),
            "elapsed_s": round(elapsed, 2),
            "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 1),
        }

    # ---- aggregated: one engine, continuous traffic ----
    agg = AsyncJaxEngine(decode_cfg)
    await agg.start()
    try:
        # warmup: compile prefill buckets + window variants
        await _request(agg, "warm-agg", warm_prompt, max_tokens=4)
        agg_res = await continuous(agg, "agg")

        # ---- component costs on the same engine/executables ----
        # Wp: M concurrent fresh 1-token requests; the chip serializes their
        # prefill chunks, so wall/M ~ per-request prefill chip-time (the
        # ~0.1 s dispatch RTT amortizes over M)
        t0 = _time.monotonic()
        await asyncio.gather(*[
            _request(agg, f"wp-{j}", wp_prompts[j], max_tokens=1)
            for j in range(M)
        ])
        wp = (_time.monotonic() - t0) / M
        # cd: decode chip-time per request. Round 1 on fresh prompts warms the
        # prefix cache; later rounds re-send the SAME prompts, so their
        # prefill is a cache hit (last token only) and each round is pure
        # batched decode. Best of 2 measured rounds: a single round is
        # exposed to multi-second tunnel stalls (r4 saw cd drift 0.21 -> 0.95
        # s/req between whole-bench runs).
        await asyncio.gather(*[
            _request(agg, f"cdw-{j}", cd_prompts[j], max_tokens=osl)
            for j in range(batch)
        ])
        cd = float("inf")
        cache_hits = 0
        for rnd in range(2):
            t0 = _time.monotonic()
            res2 = await asyncio.gather(*[
                _request(agg, f"cd{rnd}-{j}", cd_prompts[j], max_tokens=osl)
                for j in range(batch)
            ])
            cd = min(cd, (_time.monotonic() - t0) / batch)
            cache_hits = max(cache_hits, sum(c for _, _, c in res2))
    finally:
        await agg.shutdown()
        del agg
        gc.collect()

    # ---- real two-worker disagg on the one chip ----
    # teardown stack: anything successfully started gets torn down even when
    # a later setup step or the measurement itself dies
    cleanups = []
    try:
        broker = Broker()
        port = await broker.start()
        cleanups.append(broker.stop)
        addr = f"127.0.0.1:{port}"
        decode_rt = DistributedRuntime(cplane_address=addr)
        await decode_rt.connect()
        cleanups.append(decode_rt._shutdown_hook)
        prefill_rt = DistributedRuntime(cplane_address=addr)
        await prefill_rt.connect()
        cleanups.append(prefill_rt._shutdown_hook)
        decode_inner = AsyncJaxEngine(decode_cfg)
        await decode_inner.start()
        cleanups.append(decode_inner.shutdown)
        prefill_engine = AsyncJaxEngine(_parity_config(
            page_size=page_size, max_seqs=4, max_model_len=4096,
            num_pages=6 * pages_per_seq + 8,
            prefill_buckets=(512, 1024), decode_steps=8, pipeline_depth=2,
        ))
        await prefill_engine.start()
        cleanups.append(prefill_engine.shutdown)
        router = DisaggregatedRouter(
            "bench", conf=DisaggRouterConf(max_local_prefill_length=256)
        )
        decode = DisaggDecodeEngine(
            decode_inner, decode_rt, "bench", "decoder", "bench", disagg_router=router
        )
        await decode.start()
        cleanups.append(decode.shutdown)
        pw = PrefillWorker(prefill_engine, prefill_rt, "bench", "bench")
        await pw.start()
        cleanups.append(pw.stop)

        await _request(decode, "warm-dis", warm_prompt, max_tokens=4)
        dis_res = await continuous(decode, "dis")
        remote = decode.remote_prefills
    finally:
        for stop in reversed(cleanups):
            try:
                await stop()
            except Exception:
                # keep tearing the rest down, but leave a trace: a silently
                # leaked engine/broker corrupts every later section
                import traceback

                traceback.print_exc()
        # belt: a cancelled request can race its ICI-transfer cleanup; a
        # parked device array is ~hundreds of MB of HBM the next sections need
        from dynamo_tpu.disagg import ici as _ici

        dropped = _ici.drain_all()
        if dropped:
            import sys as _sys

            print(f"[bench] disagg teardown dropped {dropped} parked ICI transfers",
                  file=_sys.stderr, flush=True)
    gc.collect()

    projected = osl / (wp + cd)
    # marginal prefill cost actually observed in the aggregated mix: the agg
    # round's wall minus what its tokens would take at the pure-decode rate.
    # On this dispatch-latency-bound testbed prefill chunks slot into the
    # decode pipeline's dispatch gaps nearly free — the isolated wp above is
    # therefore an UPPER bound on prefill cost and ratio_projected a lower
    # bound on the pool-split ratio.
    decode_only_s = agg_res["requests"] * cd
    marginal_prefill = max(0.0, agg_res["elapsed_s"] - decode_only_s) / max(1, agg_res["requests"])
    return {
        "workload": {"isl": plen, "osl": osl, "clients": clients, "requests": n_requests},
        "measured_aggregated": agg_res,
        "measured_disagg_1chip": {**dis_res, "remote_prefills": remote},
        "ratio_measured_1chip": round(dis_res["tok_s"] / agg_res["tok_s"], 3),
        "components": {
            "prefill_chip_s_per_req_isolated": round(wp, 3),
            "prefill_s_per_req_marginal_in_mix": round(marginal_prefill, 3),
            "decode_chip_s_per_req": round(cd, 3),
            "cd_round_cache_hit_tokens": cache_hits,
        },
        "projected_disagg_tok_s_per_chip": round(projected, 1),
        "ratio_projected": round(projected / agg_res["tok_s"], 3),
        "target": ">= 1.3 single host (reference docs/architecture.md:57-61)",
        "note": (
            "one chip hosts both workers, so measured_disagg_1chip proves the "
            "path + prices KV handoff but cannot show the specialization win; "
            "ratio_projected uses measured per-stage chip-times for an "
            "interference-free pool split. r5 conclusion: the aggregated "
            "engine overlaps prefill into decode so well that the MARGINAL "
            "prefill cost in the mix is below the isolated cost "
            "(prefill_s_per_req_marginal_in_mix < _isolated), which puts the "
            "pool-split projection BELOW 1 — for this single-model 3K/150 "
            "workload on this engine, disaggregation has no interference "
            "left to remove, and the reference's +30% (whose engines pay "
            "real prefill/decode interference) does not transfer. The "
            "disagg machinery's value here is structural (pool pressure, "
            "heterogeneous pools, cross-host scaling), and the MECHANISM is "
            "demonstrated in CI "
            "(tests/test_disagg.py::test_disagg_pool_specialization_counters): "
            "with a prefill worker joined, the decode engine's local prefill "
            "rows collapse to ~0 (remote_prefills == all long prompts) with "
            "token-exact outputs and no added page-pressure events"
        ),
    }


async def run_disagg_stream(
    n_requests: int = 5, plen: int = 2600, osl: int = 24, page_size: int = 128,
) -> dict:
    """Streamed (chunk-pipelined, multi-lane) vs monolithic KV transfer on the
    cross-process socket path, long multi-chunk prompts.

    ici.is_local is forced off so the bulk KV really rides the TCP data plane
    (same-process workers would otherwise take the device handoff). Both arms
    run the identical two-worker fleet; only the prefill engine's kv_stream
    flag differs. Reports per-arm TTFT, exact token parity between arms, and
    the measured compute/transfer overlap fraction from the prefill worker's
    counters — the pipelining win the v2 wire protocol exists for (on this
    single-host loopback the transfer leg is cheap, so the TTFT delta is a
    lower bound on what a real DCN hop would recover)."""
    import gc
    import time as _time  # noqa: F401 — parity with sibling sections

    from dynamo_tpu.cplane.broker import Broker
    from dynamo_tpu.disagg import ici as _ici
    from dynamo_tpu.disagg.decode_worker import DisaggDecodeEngine
    from dynamo_tpu.disagg.prefill_worker import PrefillWorker
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.llm.disagg_router import DisaggregatedRouter, DisaggRouterConf
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    rng = np.random.default_rng(17)
    prompts = [rng.integers(1, 31000, plen).tolist() for _ in range(n_requests)]
    warm_prompt = rng.integers(1, 31000, plen).tolist()
    pages_per_seq = -(-(plen + osl) // page_size) + 2
    orig_is_local = _ici.is_local
    _ici.is_local = lambda worker_id: False  # force the socket data plane
    arms: dict[str, dict] = {}
    try:
        for arm, stream in (("monolithic", False), ("streamed", True)):
            cleanups = []
            try:
                broker = Broker()
                port = await broker.start()
                cleanups.append(broker.stop)
                addr = f"127.0.0.1:{port}"
                decode_rt = DistributedRuntime(cplane_address=addr)
                await decode_rt.connect()
                cleanups.append(decode_rt._shutdown_hook)
                prefill_rt = DistributedRuntime(cplane_address=addr)
                await prefill_rt.connect()
                cleanups.append(prefill_rt._shutdown_hook)
                decode_inner = AsyncJaxEngine(_parity_config(
                    page_size=page_size, max_seqs=4, max_model_len=4096,
                    num_pages=6 * pages_per_seq + 8,
                    prefill_buckets=(512, 1024), decode_steps=16,
                    pipeline_depth=2,
                ))
                await decode_inner.start()
                cleanups.append(decode_inner.shutdown)
                prefill_engine = AsyncJaxEngine(_parity_config(
                    page_size=page_size, max_seqs=4, max_model_len=4096,
                    num_pages=6 * pages_per_seq + 8,
                    prefill_buckets=(512, 1024), decode_steps=8,
                    pipeline_depth=2, kv_stream=stream, kv_stream_lanes=2,
                ))
                await prefill_engine.start()
                cleanups.append(prefill_engine.shutdown)
                router = DisaggregatedRouter(
                    "bench", conf=DisaggRouterConf(max_local_prefill_length=256)
                )
                decode = DisaggDecodeEngine(
                    decode_inner, decode_rt, "bstream", "decoder", "bench",
                    disagg_router=router,
                )
                await decode.start()
                cleanups.append(decode.shutdown)
                pw = PrefillWorker(prefill_engine, prefill_rt, "bstream", "bench")
                await pw.start()
                cleanups.append(pw.stop)

                await _request(decode, f"warm-{arm}", warm_prompt, max_tokens=2)
                ttfts, tokens = [], []
                # sequential requests: the TTFT signal must not mix queueing
                for i, p in enumerate(prompts):
                    toks, ttft, _ = await _request(
                        decode, f"{arm}-{i}", p, max_tokens=osl
                    )
                    ttfts.append(ttft)
                    tokens.append(toks)
                arms[arm] = {
                    "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 1),
                    "ttft_mean_ms": round(float(np.mean(ttfts)) * 1e3, 1),
                    "remote_prefills": decode.remote_prefills,
                    "parts_scattered": decode.parts_scattered,
                    "stream_parts": pw.stream_parts,
                    "stream_bytes": pw.stream_bytes,
                    "stream_send_s": round(pw.stream_send_s, 4),
                    "stream_overlap_s": round(pw.stream_overlap_s, 4),
                    "_tokens": tokens,
                }
            finally:
                for stop in reversed(cleanups):
                    try:
                        await stop()
                    except Exception:
                        import traceback

                        traceback.print_exc()
                dropped = _ici.drain_all()
                if dropped:
                    import sys as _sys

                    print(f"[bench] disagg_stream teardown dropped {dropped} "
                          "parked ICI transfers", file=_sys.stderr, flush=True)
            gc.collect()
    finally:
        _ici.is_local = orig_is_local

    parity = arms["streamed"].pop("_tokens") == arms["monolithic"].pop("_tokens")
    send_s = arms["streamed"]["stream_send_s"]
    overlap_fraction = (
        round(arms["streamed"]["stream_overlap_s"] / send_s, 3) if send_s else 0.0
    )
    return {
        "workload": {
            "isl": plen, "osl": osl, "requests": n_requests,
            "chunks_per_prompt": -(-plen // 1024), "lanes": 2,
        },
        "monolithic": arms["monolithic"],
        "streamed": arms["streamed"],
        "token_parity": parity,
        "overlap_fraction": overlap_fraction,
        "ttft_ratio_streamed_over_monolithic": round(
            arms["streamed"]["ttft_p50_ms"]
            / max(arms["monolithic"]["ttft_p50_ms"], 1e-9), 3,
        ),
        "target": (
            "token_parity exact; overlap_fraction > 0; streamed TTFT <= "
            "monolithic on multi-chunk prompts (ratio <= 1.0)"
        ),
    }


async def run_fleet_prefix(sessions: int = 3, osl: int = 8) -> dict:
    """Fleet-wide prefix cache: cross-worker KV pull vs full recompute on a
    shared-system-prompt workload (the millions-of-users chat shape: many
    sessions share a long system prompt, the router can't always land them
    on the worker that already holds it).

    Three engines per KV dtype: a HOLDER seeded with every session's shared
    prefix (and serving a KvPullServer), a HIT engine whose requests carry
    the holder as kv_holder (admission pulls the prefix over the wire —
    FETCHING_KV), and a COLD engine running the identical requests with no
    holder (full prefix recompute). Reports the cross-worker-hit vs
    recompute TTFT ratio (< 1.0 is the win), the fleet recompute-token
    ratio, pulled bytes at the ACTUAL wire KV dtype (int8 payloads are half
    the bf16 bytes), and exact token parity between the arms.

    On CPU (no TPU in the build container) the section scales the geometry
    down; parity and the recompute-ratio are exact either way, the driver's
    TPU run prices the TTFT ratio at serving geometry."""
    import gc

    import jax

    from dynamo_tpu.disagg.prefix_fetch import KvPullServer, PrefixFetchClient
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        geom = {
            "vocab_size": 512, "hidden_size": 512, "intermediate_size": 1024,
            "num_layers": 4, "num_heads": 4, "num_kv_heads": 2,
            "head_dim": 128, "dtype": "f32",
        }
        base_id = "tiny:" + json.dumps(geom)
        page_size, shared_len, tail_len, vocab = 16, 448, 31, 500
        prefill_buckets = (64, 128, 256, 512)
        max_model_len = 1024
    else:
        base_id = json_model_id()
        page_size, shared_len, tail_len, vocab = 64, 1536, 127, 31000
        prefill_buckets = (512, 1024, 2048)
        max_model_len = 4096

    ps = page_size
    prefix_blocks = shared_len // ps
    plen = shared_len + tail_len
    pages_per_seq = -(-(plen + osl) // ps) + 2
    num_pages = (sessions + 4) * pages_per_seq + 8

    rng = np.random.default_rng(41)
    # one shared system prompt per session (warm session included), so every
    # measured request is a genuine first-placement miss that must pull
    all_prompts = [
        rng.integers(1, vocab, shared_len).tolist()
        + rng.integers(1, vocab, tail_len).tolist()
        for _ in range(sessions + 1)
    ]
    warm_prompt, prompts = all_prompts[0], all_prompts[1:]

    results: dict[str, dict] = {}
    for dtype in (None, "int8"):
        label = dtype or "bf16"

        def cfg():
            return EngineConfig(
                model_id=base_id, page_size=ps, num_pages=num_pages,
                max_seqs=4, max_model_len=max_model_len,
                prefill_buckets=prefill_buckets, decode_steps=4,
                pipeline_depth=2, kv_cache_dtype=dtype,
                prefix_fetch_timeout_s=60.0,
            )

        cleanups = []
        try:
            holder = AsyncJaxEngine(cfg())
            await holder.start()
            cleanups.append(holder.shutdown)
            hit_eng = AsyncJaxEngine(cfg())
            await hit_eng.start()
            cleanups.append(hit_eng.shutdown)
            cold_eng = AsyncJaxEngine(cfg())
            await cold_eng.start()
            cleanups.append(cold_eng.shutdown)
            srv = await KvPullServer(holder, host="127.0.0.1").start()
            cleanups.append(srv.stop)
            fetcher = PrefixFetchClient(asyncio.get_running_loop(), timeout_s=60.0)
            hit_eng.attach_prefix_fetch(fetcher)

            # fleet state: the holder computed (and cached) every session's
            # shared prefix
            for i, p in enumerate(all_prompts):
                await _request(holder, f"seed-{label}-{i}", p, max_tokens=2)
            # warm both serving arms on the warm session: compiles prefill
            # buckets, decode windows, and the fetch-scatter executables out
            # of the measurement (the warm hit request exercises a real pull)
            await _request(hit_eng, f"warm-hit-{label}", warm_prompt,
                           max_tokens=2, holder=srv.address,
                           holder_blocks=prefix_blocks)
            await _request(cold_eng, f"warm-cold-{label}", warm_prompt, max_tokens=2)

            hit_ttfts, hit_tokens, hit_recompute = [], [], 0
            for i, p in enumerate(prompts):
                toks, ttft, cached = await _request(
                    hit_eng, f"hit-{label}-{i}", p, max_tokens=osl,
                    holder=srv.address, holder_blocks=prefix_blocks,
                )
                hit_ttfts.append(ttft)
                hit_tokens.append(toks)
                hit_recompute += plen - cached
            cold_ttfts, cold_tokens, cold_recompute = [], [], 0
            for i, p in enumerate(prompts):
                toks, ttft, cached = await _request(
                    cold_eng, f"cold-{label}-{i}", p, max_tokens=osl,
                )
                cold_ttfts.append(ttft)
                cold_tokens.append(toks)
                cold_recompute += plen - cached

            sched = hit_eng.scheduler
            results[label] = {
                "ttft_hit_p50_ms": round(float(np.percentile(hit_ttfts, 50)) * 1e3, 1),
                "ttft_recompute_p50_ms": round(
                    float(np.percentile(cold_ttfts, 50)) * 1e3, 1
                ),
                "ttft_ratio_hit_over_recompute": round(
                    float(np.percentile(hit_ttfts, 50))
                    / max(float(np.percentile(cold_ttfts, 50)), 1e-9), 3
                ),
                "token_parity": hit_tokens == cold_tokens,
                "prefix_fetch_hits": sched.prefix_fetch_hits,
                "prefix_fetch_fallbacks": sched.prefix_fetch_fallbacks,
                "pulled_blocks": sched.prefix_fetch_blocks,
                # at the ACTUAL wire KV dtype: int8 payloads are half the
                # bf16 bytes (scale planes ride part headers, uncounted)
                "pulled_bytes": sched.prefix_fetch_bytes,
                "recompute_tokens_hit_arm": hit_recompute,
                "recompute_tokens_cold_arm": cold_recompute,
                "recompute_ratio": round(
                    hit_recompute / max(1, cold_recompute), 4
                ),
                "served_blocks": dict(srv.served_blocks),
            }
        finally:
            for stop in reversed(cleanups):
                try:
                    await stop()
                except Exception:
                    import traceback

                    traceback.print_exc()
            gc.collect()

    assert results["bf16"]["token_parity"], "cross-worker pull broke token parity"
    assert results["int8"]["token_parity"], "int8 cross-worker pull broke parity"
    return {
        "cpu_smoke": on_cpu,
        "workload": {
            "sessions": sessions, "shared_prefix_len": shared_len,
            "prompt_len": plen, "osl": osl, "page_size": ps,
            "prefix_blocks": prefix_blocks,
        },
        "bf16": results["bf16"],
        "int8": results["int8"],
        "wire_bytes_ratio_int8_over_bf16": round(
            results["int8"]["pulled_bytes"]
            / max(1, results["bf16"]["pulled_bytes"]), 3
        ),
        "target": (
            "token parity exact both dtypes; hit-arm TTFT ratio < 1.0; "
            "recompute_ratio ~= tail/plen (the fleet stops recomputing "
            "shared prefixes); int8 wire bytes = itemsize ratio (0.5x vs "
            "bf16 on TPU, 0.25x vs the f32 CPU-smoke geometry)"
        ),
    }


async def run_migration(sessions: int = 3, osl: int = 24) -> dict:
    """Live sequence migration vs kill+resume (the round-14 tentpole):
    migrated-vs-killed request outcome on identical mid-decode interrupts.

    Three engines: a BASELINE serving each prompt uninterrupted (the parity
    reference and the no-interrupt gap distribution), a SOURCE + DEST pair
    for the migrated arm (requests start on SOURCE, migrate mid-decode over
    the seq_handoff pull dataplane, finish on DEST with the stream relayed),
    and a kill+resume arm on SOURCE (cancel at the same point + preempt-
    style resume — today's alternative). Reports exact token parity for the
    migrated arm, the client-visible pause p99 (freeze -> first continuation
    token), tokens salvaged by the KV pull, and the goodput delta between
    the arms under a shared per-token ITL budget.

    On CPU (no TPU in the build container) the section scales the geometry
    down; parity and the salvage counters are exact either way, the
    driver's TPU run prices pause/goodput at serving geometry."""
    import gc

    import jax

    from dynamo_tpu.disagg.prefix_fetch import KvPullServer, PrefixFetchClient
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest
    from dynamo_tpu.utils.goodput import RequestOutcome, outcome_meets

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        geom = {
            "vocab_size": 512, "hidden_size": 256, "intermediate_size": 512,
            "num_layers": 2, "num_heads": 4, "num_kv_heads": 2,
            "head_dim": 64, "dtype": "f32",
        }
        base_id = "tiny:" + json.dumps(geom)
        page_size, plen, vocab = 16, 96, 500
        prefill_buckets = (32, 64, 128)
        max_model_len = 256
    else:
        base_id = json_model_id()
        page_size, plen, vocab = 64, 1536, 31000
        prefill_buckets = (512, 1024, 2048)
        max_model_len = 4096

    half = osl // 2
    pages_per_seq = -(-(plen + osl) // page_size) + 2
    num_pages = (sessions + 2) * pages_per_seq + 8

    def cfg():
        return EngineConfig(
            model_id=base_id, page_size=page_size, num_pages=num_pages,
            max_seqs=4, max_model_len=max_model_len,
            prefill_buckets=prefill_buckets, decode_steps=2,
            pipeline_depth=2, migration_timeout_s=60.0,
            # pre-compile every prefill-bucket/window variant: a cold XLA
            # compile landing inside one measured handoff would otherwise
            # dominate the pause percentiles (the warm migration below still
            # covers the handoff-only executables like the part scatter)
            warmup=True,
        )

    rng = np.random.default_rng(47)
    mig_prompts = [rng.integers(1, vocab, plen).tolist() for _ in range(sessions)]
    kill_prompts = [rng.integers(1, vocab, plen).tolist() for _ in range(sessions)]

    def req_for(rid, prompt, max_tokens=osl):
        return EngineRequest(
            request_id=rid, token_ids=list(prompt),
            sampling=SamplingParams(
                temperature=0.0, max_tokens=max_tokens, ignore_eos=True
            ),
        )

    async def collect(eng, req, stop_after=None):
        """(tokens, arrival walls). stop_after=n breaks the stream after n
        tokens (the kill arm's client walking through a worker death)."""
        toks, walls = [], []
        async for out in eng.generate(req):
            if out.token is not None:
                toks.append(out.token)
                walls.append(time.monotonic())
            if stop_after is not None and len(toks) >= stop_after:
                break
            if out.finished:
                break
        return toks, walls

    async def wait_generated(eng, rid, n, timeout=120.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            seq = next(
                (s for s in eng.scheduler.slots
                 if s is not None and s.req.request_id == rid), None,
            )
            if seq is not None and len(seq.generated) >= n:
                return True
            await asyncio.sleep(0.005)
        return False

    cleanups = []
    try:
        baseline = AsyncJaxEngine(cfg())
        await baseline.start()
        cleanups.append(baseline.shutdown)
        source = AsyncJaxEngine(cfg())
        await source.start()
        cleanups.append(source.shutdown)
        dest = AsyncJaxEngine(cfg())
        await dest.start()
        cleanups.append(dest.shutdown)
        srv = await KvPullServer(source, host="127.0.0.1").start()
        cleanups.append(srv.stop)
        source.kv_pull_server = srv
        dest.attach_prefix_fetch(
            PrefixFetchClient(asyncio.get_running_loop(), timeout_s=60.0)
        )

        # baseline arm: uninterrupted runs = the parity reference + the
        # undisturbed per-token gap distribution (warm run compiles first)
        await collect(baseline, req_for("warm-base", mig_prompts[0], 4))
        expected, base_gaps = [], []
        for i, p in enumerate(mig_prompts):
            toks, walls = await collect(baseline, req_for(f"base-{i}", p))
            expected.append(toks)
            base_gaps.extend(np.diff(walls).tolist())

        # migrated arm: start on SOURCE, freeze+handoff at `half` tokens,
        # finish on DEST with the stream relayed through the source. Warm
        # the WHOLE handoff path first (manifest, seq_handoff pull, scatter,
        # adoption prefill executables) with a throwaway migration so the
        # measured pauses price the handoff, not cold XLA compiles.
        warm_prompt = rng.integers(1, vocab, plen).tolist()
        wt = asyncio.ensure_future(collect(source, req_for("warm-mig", warm_prompt)))
        if await wait_generated(source, "warm-mig", half):
            await source.migrate_out("warm-mig", dest.adopt_migrated)
        await wt
        mig_tokens, mig_pauses, mig_gap_series = [], [], []
        for i, p in enumerate(mig_prompts):
            rid = f"mig-{i}"
            task = asyncio.ensure_future(collect(source, req_for(rid, p)))
            assert await wait_generated(source, rid, half), "migration arm stalled"
            res = await source.migrate_out(rid, dest.adopt_migrated)
            assert res["status"] == "ok", f"handoff failed: {res}"
            toks, walls = await task
            mig_tokens.append(toks)
            mig_pauses.append(res["pause_s"])
            mig_gap_series.append(np.diff(walls).tolist())

        # kill arm: the worker DIES at the same point — the client's retry
        # lands on the peer with the history as its prompt and NO KV to
        # pull (the dead worker's pages are gone), so the whole history
        # re-prefills cold. This is the outcome migration must beat; a
        # same-worker resume would instead model preemption (its local
        # prefix cache recovers the blocks, which a dead worker cannot).
        kill_gap_series, kill_pauses = [], []
        for i, p in enumerate(kill_prompts):
            rid = f"kill-{i}"
            got, walls = await collect(source, req_for(rid, p), stop_after=half)
            rest, walls2 = await collect(
                dest, req_for(f"{rid}-retry", list(p) + got, osl - len(got))
            )
            kill_pauses.append(walls2[0] - walls[-1] if walls2 else 0.0)
            kill_gap_series.append(
                np.diff(walls).tolist()
                + ([walls2[0] - walls[-1]] if walls2 else [])
                + np.diff(walls2).tolist()
            )

        # shared per-token ITL budget: generous over the undisturbed gap
        # distribution, so only the interrupt stall can miss it
        itl_budget = max(
            float(np.percentile(base_gaps, 95)) * 3.0 if base_gaps else 0.05,
            0.05,
        )

        def arm_goodput(series):
            met = 0
            for gaps in series:
                out = RequestOutcome(
                    "x", itl_s=tuple(gaps), output_tokens=len(gaps) + 1,
                )
                met += 1 if outcome_meets(out, None, itl_budget) else 0
            return met / max(1, len(series))

        gp_mig = arm_goodput(mig_gap_series)
        gp_kill = arm_goodput(kill_gap_series)
        parity = sum(
            1 for got, want in zip(mig_tokens, expected) if got == want
        ) / max(1, sessions)
        dsched = dest.scheduler
        assert parity == 1.0, (
            f"migration broke token parity: {mig_tokens} != {expected}"
        )
        assert dsched.migration_in_pulled >= 1, "no handoff pull landed"
        return {
            "cpu_smoke": on_cpu,
            "workload": {"sessions": sessions, "prompt_len": plen,
                         "osl": osl, "migrate_at": half,
                         "page_size": page_size},
            "parity": parity,
            "pause_ms_p50": round(float(np.percentile(mig_pauses, 50)) * 1e3, 1),
            "pause_ms_p99": round(float(np.percentile(mig_pauses, 99)) * 1e3, 1),
            "kill_pause_ms_p99": round(
                float(np.percentile(kill_pauses, 99)) * 1e3, 1
            ),
            "tokens_salvaged": dsched.migration_tokens_salvaged,
            "migrations_pulled": dsched.migration_in_pulled,
            "migrations_recomputed": dsched.migration_in_recomputed,
            "itl_budget_ms": round(itl_budget * 1e3, 1),
            "goodput_migrated": round(gp_mig, 4),
            "goodput_killed": round(gp_kill, 4),
            "goodput_delta": round(gp_mig - gp_kill, 4),
            "target": (
                "parity exact; pause p99 under the kill+resume stall; "
                "goodput_delta >= 0 (migrating a sequence must beat killing "
                "it); salvaged tokens ~= sessions * committed history"
            ),
        }
    finally:
        for stop in reversed(cleanups):
            try:
                await stop()
            except Exception:
                import traceback

                traceback.print_exc()
        gc.collect()


async def run_qos() -> dict:
    """Multi-tenant QoS isolation experiment (utils/qos.py): tenant A bursts
    batch-class traffic with long outputs through ONE engine while tenant B
    runs a steady critical-class stream — with QoS on vs off on the same
    trace.

    QoS on: B rides the critical lane (admission order, victim ordering
    prefers batch lanes, a waiting critical request evicts a batch lane) and
    A's burst is charged against a per-tenant token budget (the frontend
    bucket semantics, replayed at the trace's own timestamps — shed requests
    never reach the engine, exactly like the 429 path). QoS off: classes are
    ignored (FIFO admission, recency-only victims) and nothing sheds — A's
    page-pressure churn preempts B mid-stream.

    Headline: tenant B's per-request ITL-p99 stays within budget with QoS on
    while the off arm violates it; shed_fraction says how much of A's burst
    the budget refused; critical_goodput (B under burst, QoS on) must hold
    the no-burst baseline. The engine asserts B was NEVER a preemption
    victim in the on arm."""
    import gc

    import jax

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.loadgen import compile_trace, load_scenario
    from dynamo_tpu.loadgen.replay import replay_engine
    from dynamo_tpu.utils.goodput import percentile
    from dynamo_tpu.utils.qos import AdmissionController, QosPolicy

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        model_id = "tiny"
        n_a, n_b, speed = 12, 6, 2.0
        # budgets sized to separate window-scale gaps (~2 ms measured) from
        # preempt+requeue stalls (~0.5 s+) on the CPU tiny engine
        ttft_budget_ms, itl_budget_ms = 30000.0, 250.0
        # pages sized so three LONG tenant-A lanes cannot coexist: A's decode
        # growth (osl 96 on a 32-token prompt) forces preemption churn — the
        # noisy-neighbor pathology the off arm must exhibit against B
        eng_kw = dict(
            page_size=4, num_pages=64, max_seqs=3, max_model_len=256,
            prefill_buckets=(16, 32, 64), decode_steps=2, pipeline_depth=1,
            prefill_batches_per_step=1, qos_preempt_wait_ms=50.0,
        )
        a_scale = dict(isl_mean=32, isl_max=64, osl_dist="fixed", osl_mean=96,
                       osl_max=96, vocab=256, rate_rps=24.0, burst_factor=6.0,
                       num_requests=n_a, slo_ttft_ms=ttft_budget_ms,
                       slo_itl_ms=itl_budget_ms)
        # B outputs long enough that a mid-stream preemption (the off arm's
        # failure mode) lands INSIDE the ITL series, spaced so at most two B
        # lanes overlap (three critical lanes alone would exhaust the pool
        # and force critical-on-critical preemption even with QoS on)
        b_scale = dict(isl_mean=12, isl_max=24, osl_dist="fixed", osl_mean=48,
                       osl_max=48, vocab=256, rate_rps=0.8, num_requests=n_b,
                       slo_ttft_ms=ttft_budget_ms, slo_itl_ms=itl_budget_ms)
        # A's budget: ~2 requests' worth of burst, then ~1 per 6 s — most of
        # the burst must shed so the bucket actually bites
        budget_spec = "tenant-a=20:300"
    else:
        model_id = json_model_id()
        n_a, n_b, speed = 32, 16, 1.0
        ttft_budget_ms, itl_budget_ms = 2000.0, 200.0
        eng_kw = dict(
            page_size=16, num_pages=2048, max_seqs=8, max_model_len=2048,
            prefill_buckets=(128, 256, 512), decode_steps=8, pipeline_depth=2,
            prefill_batches_per_step=2, qos_preempt_wait_ms=100.0,
        )
        a_scale = dict(isl_mean=256, isl_max=1024, osl_dist="fixed",
                       osl_mean=256, osl_max=256, vocab=31000, rate_rps=32.0,
                       burst_factor=6.0, num_requests=n_a,
                       slo_ttft_ms=ttft_budget_ms, slo_itl_ms=itl_budget_ms)
        b_scale = dict(isl_mean=64, isl_max=256, osl_dist="fixed", osl_mean=48,
                       osl_max=48, vocab=31000, rate_rps=4.0, num_requests=n_b,
                       slo_ttft_ms=ttft_budget_ms, slo_itl_ms=itl_budget_ms)
        budget_spec = "tenant-a=2000:8192"

    spec_a = load_scenario("bursty_chat", seed=5).replace(
        name="qos_burst_a", tenants=("tenant-a",), **a_scale)
    spec_b = load_scenario("bursty_chat", seed=6).replace(
        name="qos_steady_b", arrival="poisson", tenants=("tenant-b",),
        **b_scale)
    trace_a, trace_b = compile_trace(spec_a), compile_trace(spec_b)
    merged = sorted(trace_a + trace_b, key=lambda tr: tr.at_s)

    def stamp_priority(req, tr):
        req.priority = "critical" if tr.tenant == "tenant-b" else "batch"

    # frontend-bucket admission replayed at the trace's own timestamps (a
    # virtual clock makes the shed set deterministic): shed requests never
    # reach the engine — on the wire they'd be structured retriable 429s
    clock = {"t": 0.0}
    ctl = AdmissionController(
        QosPolicy.from_specs(budget_spec, "tenant-a=batch,tenant-b=critical"),
        clock=lambda: clock["t"],
    )
    admitted_trace, shed = [], 0
    for tr in merged:
        clock["t"] = tr.at_s
        if tr.tenant == "tenant-a":
            d = ctl.admit(tr.tenant, "batch", len(tr.token_ids) + tr.max_tokens)
            if not d.admitted:
                shed += 1
                continue
        else:
            ctl.admit(tr.tenant, "critical", len(tr.token_ids) + tr.max_tokens)
        admitted_trace.append(tr)
    shed_fraction = shed / max(1, len(trace_a))

    def tenant_stats(report, tenant):
        outs = [o for o in report["outcomes"] if o.get("tenant") == tenant]
        itl_p99s = [o["itl_p99_ms"] for o in outs if o.get("itl_p99_ms") is not None]
        met = sum(
            1 for o in outs
            if not o.get("error")
            and (o.get("ttft_ms") is not None and o["ttft_ms"] <= ttft_budget_ms)
            and (o.get("itl_p99_ms") is None or o["itl_p99_ms"] <= itl_budget_ms)
        )
        return {
            "requests": len(outs),
            "errors": sum(1 for o in outs if o.get("error")),
            "itl_p99_ms": percentile(itl_p99s, 99),
            "ttft_p99_ms": percentile(
                [o["ttft_ms"] for o in outs if o.get("ttft_ms") is not None], 99
            ),
            "goodput": round(met / len(outs), 4) if outs else None,
        }

    async def arm(qos_on: bool, trace, hook):
        eng = AsyncJaxEngine(EngineConfig(model_id=model_id, qos=qos_on, **eng_kw))
        try:
            await eng.start()
            # warm BOTH tenants' shapes (prefill buckets/lane counts) so a
            # cold XLA compile can't masquerade as an ITL stall mid-arm
            for wspec in (spec_a.replace(seed=98, num_requests=3),
                          spec_b.replace(seed=99, num_requests=3)):
                await replay_engine(
                    eng, compile_trace(wspec), spec=wspec, speed=100.0,
                )
            # warm traffic ran at class "standard": its preemptions must not
            # pollute the measured arm's enforcement audit
            sched = eng.scheduler
            sched.qos_preempted.clear()
            sched.qos_sheds = sched.qos_shed_migrations = 0
            sched.preempt_count = 0
            report = await replay_engine(
                eng, trace, spec=spec_b, speed=speed, request_hook=hook,
            )
            sched = eng.scheduler
            report["engine_qos"] = {
                "preempted": dict(sched.qos_preempted),
                "sheds": sched.qos_sheds,
                "preempt_count": sched.preempt_count,
            }
            return report
        finally:
            await eng.shutdown()
            gc.collect()

    rep_on = await arm(True, admitted_trace, stamp_priority)
    rep_off = await arm(False, merged, None)
    # no-burst baseline: tenant B alone on a QoS engine — the bar
    # critical-class goodput under burst must hold
    rep_base = await arm(True, trace_b, stamp_priority)

    b_on = tenant_stats(rep_on, "tenant-b")
    b_off = tenant_stats(rep_off, "tenant-b")
    b_base = tenant_stats(rep_base, "tenant-b")
    for rep in (rep_on, rep_off, rep_base):
        rep.pop("outcomes", None)

    # enforcement audit: with QoS on, tenant B (critical) was NEVER a
    # preemption victim — batch lanes paid for all of A's page pressure
    assert rep_on["engine_qos"]["preempted"].get("critical", 0) == 0, (
        rep_on["engine_qos"],
    )
    assert shed_fraction > 0.0, "A's burst never hit the token budget"
    assert b_on["errors"] == 0 and b_base["errors"] == 0
    # the isolation headline: B within its ITL budget with QoS on, and the
    # SAME trace without QoS blowing it (the off arm's preempt churn hits B)
    assert b_on["itl_p99_ms"] is not None and \
        b_on["itl_p99_ms"] <= itl_budget_ms, (b_on, itl_budget_ms)
    assert b_off["itl_p99_ms"] is not None and \
        b_off["itl_p99_ms"] > itl_budget_ms, (b_off, itl_budget_ms)

    return {
        "cpu_smoke": on_cpu,
        "platform": jax.devices()[0].platform,
        "ttft_budget_ms": ttft_budget_ms,
        "itl_budget_ms": itl_budget_ms,
        "tenant_b_on": b_on,
        "tenant_b_off": b_off,
        "tenant_b_baseline": b_base,
        "tenant_b_itl_ratio": (
            round(b_on["itl_p99_ms"] / b_off["itl_p99_ms"], 4)
            if b_on["itl_p99_ms"] and b_off["itl_p99_ms"] else None
        ),
        "b_within_budget_on": bool(
            b_on["itl_p99_ms"] is not None
            and b_on["itl_p99_ms"] <= itl_budget_ms
        ),
        "b_violates_off": bool(
            b_off["itl_p99_ms"] is not None
            and b_off["itl_p99_ms"] > itl_budget_ms
        ),
        "shed_fraction": round(shed_fraction, 4),
        "sheds": shed,
        "critical_goodput": b_on["goodput"],
        "baseline_goodput": b_base["goodput"],
        "admission": ctl.snapshot(),
        "engine_qos_on": rep_on["engine_qos"],
        "engine_qos_off": rep_off["engine_qos"],
    }


async def run_long_context(osl: int = 32) -> dict:
    """Long-context serving (round-8 tentpole): 16K/64K-token prompts
    end-to-end through the page-table width ladder + depth-aware chunked
    prefill, reporting TTFT, decode tok/s, and the KV page high-watermark
    (the PR 5 ``kv_pages_peak`` gauge) per depth — plus EXACT token parity
    between the ladder and the dense-table path on the deepest prompt, and
    a short-prompt ladder-vs-dense TTFT ratio (the no-regression guard).

    On CPU (no TPU in the build container) the geometry scales down 16x,
    exactly like fleet_prefix: "16k"/"64k" become 1K/4K-token prompts on
    the tiny-json model and prefill_flat_depth scales with them so the
    depth-aware chunk shrinking genuinely engages; parity and the gauge
    plumbing are exact either way, and the driver's TPU run prices the
    real depths."""
    import gc

    import jax

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        geom = {
            "vocab_size": 512, "hidden_size": 512, "intermediate_size": 1024,
            "num_layers": 4, "num_heads": 4, "num_kv_heads": 2,
            "head_dim": 128, "dtype": "f32",
        }
        base_id = "tiny:" + json.dumps(geom)
        page_size, vocab = 16, 500
        depths = {"16k": 1024, "64k": 4096}  # 16x scale-down
        short_len, max_model_len = 256, 8192
        prefill_buckets = (128, 256, 512)
        flat_depth = 1024  # scaled with the depths: shrinking engages at "64k"
    else:
        base_id = json_model_id()
        page_size, vocab = 64, 31000
        depths = {"16k": 16384, "64k": 65536}
        short_len, max_model_len = 2048, 131072
        prefill_buckets = (512, 1024, 2048)
        flat_depth = 8192
    mp = max_model_len // page_size  # dense table width
    num_pages = (
        depths["64k"] // page_size + 4 * (short_len // page_size) + 64
    )

    def cfg(**over):
        return EngineConfig(
            model_id=base_id, page_size=page_size, num_pages=num_pages,
            max_seqs=2, max_model_len=max_model_len,
            prefill_buckets=prefill_buckets, prefill_flat_depth=flat_depth,
            decode_steps=4, pipeline_depth=2, **over,
        )

    rng = np.random.default_rng(17)
    prompts = {
        label: rng.integers(1, vocab, depth).tolist()
        for label, depth in depths.items()
    }
    short_prompt = rng.integers(1, vocab, short_len).tolist()

    async def timed(eng, rid, prompt):
        t0 = time.monotonic()
        toks, ttft, _ = await _request(eng, rid, prompt, max_tokens=osl)
        total = time.monotonic() - t0
        decode_s = max(total - ttft, 1e-9)
        return toks, ttft, (len(toks) - 1) / decode_s

    out: dict = {"cpu_smoke": on_cpu, "scale": {
        "depths_tokens": dict(depths), "short_len": short_len,
        "page_size": page_size, "dense_table_width": mp,
    }}
    cleanups = []
    try:
        ladder = AsyncJaxEngine(cfg())
        await ladder.start()
        cleanups.append(ladder.shutdown)
        dense = AsyncJaxEngine(cfg(page_table_buckets=(mp,)))
        await dense.start()
        cleanups.append(dense.shutdown)
        out["table_buckets"] = list(ladder.config.table_buckets)

        # warm both arms: the short-prompt buckets + decode window, and ONE
        # deep prompt each so the wide-table/deep-chunk executables compile
        # out of the measured TTFT (fresh random prompts — no prefix reuse
        # between warm and measured requests)
        warm_deep = rng.integers(1, vocab, depths["64k"]).tolist()
        await _request(ladder, "warm-l", short_prompt, max_tokens=2)
        await _request(dense, "warm-d", short_prompt, max_tokens=2)
        await _request(ladder, "warm-l-deep", warm_deep, max_tokens=2)
        await _request(dense, "warm-d-deep", warm_deep, max_tokens=2)

        deep_tokens: dict[str, list] = {}
        for label in depths:
            toks, ttft, tok_s = await timed(ladder, f"lc-{label}", prompts[label])
            deep_tokens[label] = toks
            snap = ladder.resource_snapshot()
            out[label] = {
                "ttft_ms": round(ttft * 1e3, 1),
                "decode_tok_s": round(tok_s, 1),
                "kv_pages_peak": snap["kv_pages_peak"],
                "kv_pages_total": snap["kv_pages_total"],
                "table_dispatches": dict(snap["context_table_dispatches"]),
                "chunk_dispatches": dict(snap["context_chunk_dispatches"]),
            }

        # dense arm serves the DEEPEST prompt for the acceptance parity:
        # the ladder must be byte-identical to the dense-table path
        toks_dense, ttft_dense, _ = await timed(dense, "lc-64k-dense", prompts["64k"])
        out["64k"]["ttft_dense_ms"] = round(ttft_dense * 1e3, 1)
        out["parity_64k_ladder_vs_dense"] = deep_tokens["64k"] == toks_dense

        # short-prompt no-regression: the ladder's narrow tables must not be
        # slower than the dense path on <= 2K-scale traffic (both engines
        # warm; p50 of a few repeats to damp scheduling noise)
        lt, dt = [], []
        for i in range(5):
            _, t, _ = await _request(ladder, f"short-l{i}", short_prompt, max_tokens=8)
            lt.append(t)
            _, t, _ = await _request(dense, f"short-d{i}", short_prompt, max_tokens=8)
            dt.append(t)
        out["short_ttft_ladder_ms"] = round(float(np.percentile(lt, 50)) * 1e3, 1)
        out["short_ttft_dense_ms"] = round(float(np.percentile(dt, 50)) * 1e3, 1)
        out["short_ttft_ratio_ladder_over_dense"] = round(
            float(np.percentile(lt, 50)) / max(float(np.percentile(dt, 50)), 1e-9), 3
        )
    finally:
        for stop in reversed(cleanups):
            try:
                await stop()
            except Exception:
                import traceback

                traceback.print_exc()
        gc.collect()

    assert out["parity_64k_ladder_vs_dense"], \
        "page-table ladder broke token parity on the 64K prompt"
    out["target"] = (
        "64k serves end-to-end with EXACT ladder-vs-dense parity; deep TTFT "
        "scales sub-linearly vs dense (narrow tables + flat chunks); "
        "short-prompt ratio ~<= 1.0 (no regression); kv_pages_peak tracks "
        "the deep prompt's working set"
    )
    return out


async def run_quant_int8_parity(decode_tokens: int = 72) -> dict:
    """Weight-only int8 vs bf16 on the headline llama-1.3b config: decode
    throughput (the weight-bound roofline argument — int8 weights halve the
    HBM stream every decode step reads) plus numeric parity on greedy
    decoding.

    Throughput legs run the full run_config harness back-to-back in the same
    process so tunnel drift hits both. Parity runs model-level on the SAME
    random weights (same tiny seed — quantization is the only delta):

      teacher-forced agreement — the bf16 model free-runs a greedy chain,
        then the int8 model replays the SAME fed tokens and we compare each
        step's argmax. This is the well-defined per-step metric: this
        config's weights are random, so logit top-2 gaps are near-degenerate
        and a single flip in a free-running chain compounds into total
        divergence. CPU calibration at this geometry: raw per-step agreement
        ~0.82, every flip on a bf16 top-2 margin well under the logit std —
        so the asserted pair is raw agreement >= 0.7 AND "agree or near-tie"
        >= 0.95 (a step counts as near-tie when bf16's own margin between
        its choice and int8's choice is < 0.5, i.e. quantization only flips
        decisions bf16 held by under half a logit-std; real checkpoints'
        confident distributions agree far more often).
      max_abs_logit_delta — prefill last-token logits, bf16 vs int8, plus
        the delta normalized by the bf16 logit std (CPU-calibrated at ~0.22
        for this geometry/seed)."""
    import gc

    # ---- throughput: bf16 leg then int8 leg, same harness/shapes ----
    bf16 = await run_config(*HEADLINE, rounds=2)
    int8 = await run_config(*HEADLINE, rounds=2, model_id=quant_model_id())
    speedup = int8["tok_s"] / bf16["tok_s"] if bf16["tok_s"] else None

    # ---- model-level parity on identical pre-quantization weights ----
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.registry import load_model

    rng = np.random.default_rng(23)
    probe = rng.integers(1, 31000, PROMPT_LEN)
    positions = np.arange(PROMPT_LEN, dtype=np.int32)
    # pages from 1 (page 0 is the allocator's trash-page convention); enough
    # pages to cover prompt + decode_tokens
    n_pages = -(-(PROMPT_LEN + decode_tokens) // 64) + 1
    page_table = np.arange(1, n_pages + 1, dtype=np.int32)

    def greedy_chain(model_id: str, forced: list | None = None):
        """Free-running greedy argmax chain (forced=None), or the per-step
        argmax while replaying ``forced`` as the fed tokens (teacher-forced).
        Returns (argmaxes [decode_tokens], per-step logits [decode_tokens, V]
        — step 0 is the prefill's last-token logits)."""
        model, params = load_model(model_id)
        kv = model.init_kv_cache(n_pages + 2, 64)
        pts = np.zeros((1, n_pages + 2), np.int32)
        pts[0, : len(page_table)] = page_table
        logits, kv = jax.jit(model.prefill)(
            params, kv, jnp.asarray(probe, jnp.int32), jnp.asarray(positions),
            jnp.asarray(page_table), jnp.ones(PROMPT_LEN, bool),
            jnp.asarray(PROMPT_LEN - 1),
        )
        all_logits = [np.asarray(jax.device_get(logits), np.float32)]
        decode = jax.jit(model.decode)
        out = [int(all_logits[0].argmax())]
        feed = out[0] if forced is None else forced[0]
        for i in range(decode_tokens - 1):
            logits, kv = decode(
                params, kv, jnp.asarray([feed], jnp.int32),
                jnp.asarray([PROMPT_LEN + i], jnp.int32), jnp.asarray(pts),
                jnp.asarray([True]),
            )
            row = np.asarray(jax.device_get(logits), np.float32)[0]
            all_logits.append(row)
            tok = int(row.argmax())
            out.append(tok)
            feed = tok if forced is None else forced[i + 1]
        return out, np.stack(all_logits)

    ref_chain, l_bf16 = greedy_chain(json_model_id())
    tf_chain, l_int8 = greedy_chain(quant_model_id(), forced=ref_chain)
    # teacher forcing => both models saw IDENTICAL context each step, so the
    # per-step bf16 margin between its own choice and int8's choice measures
    # how strongly held every flipped decision was
    agree = [int(a == b) for a, b in zip(ref_chain, tf_chain)]
    flip_margins = [
        float(l_bf16[i, ref_chain[i]] - l_bf16[i, tf_chain[i]])
        for i in range(decode_tokens)
        if ref_chain[i] != tf_chain[i]
    ]
    NEAR_TIE = 0.5  # bf16 margins under this count as quantization-noise ties
    agree_or_tie = [
        int(a == b or float(l_bf16[i, a] - l_bf16[i, b]) < NEAR_TIE)
        for i, (a, b) in enumerate(zip(ref_chain, tf_chain))
    ]
    n_eval = min(64, decode_tokens)
    agree_64 = sum(agree[:n_eval]) / n_eval
    agree_or_tie_64 = sum(agree_or_tie[:n_eval]) / n_eval
    first_div = next((i for i, ok in enumerate(agree) if not ok), decode_tokens)
    max_delta = float(np.max(np.abs(l_bf16[0] - l_int8[0])))
    logit_std = float(np.std(l_bf16[0]))
    gc.collect()

    return {
        "tok_s_bf16": bf16["tok_s"],
        "tok_s_int8": int8["tok_s"],
        "speedup_int8_over_bf16": round(speedup, 3) if speedup else None,
        "rounds": {"bf16": bf16["rounds"], "int8": int8["rounds"]},
        "ttft_p50_ms": {"bf16": bf16["ttft_p50_ms"], "int8": int8["ttft_p50_ms"]},
        "greedy_decode_tokens": decode_tokens,
        "teacher_forced_agreement_64": round(agree_64, 4),
        "teacher_forced_agree_or_near_tie_64": round(agree_or_tie_64, 4),
        "flip_bf16_margins": [round(m, 4) for m in flip_margins],
        "free_run_first_divergence": first_div,
        "max_abs_logit_delta": round(max_delta, 4),
        "logit_std_bf16": round(logit_std, 4),
        "max_abs_logit_delta_over_std": round(max_delta / max(logit_std, 1e-9), 4),
        "weights_note": (
            "per-output-channel symmetric int8 on wq/wk/wv/wo/gate/up/down; "
            "embed/lm_head/norms stay bf16 — quantized weight bytes ~0.5x of "
            "the layer-stack stream the decode roofline reads; random weights "
            "=> near-degenerate logit top-2 gaps (CPU-calibrated raw "
            "agreement ~0.82), so the asserted pair is raw agreement plus "
            "agree-or-near-tie (flips only on bf16 margins < 0.5)"
        ),
        "target": (
            "speedup >= 1.25; over 64 teacher-forced steps: raw agreement "
            ">= 0.7 AND agree-or-near-tie(0.5) >= 0.95; "
            "max_abs_logit_delta_over_std <= 0.35"
        ),
        "pass": {
            "speedup": bool(speedup and speedup >= 1.25),
            "greedy_agreement": bool(agree_64 >= 0.7 and agree_or_tie_64 >= 0.95),
            "logit_delta": bool(max_delta / max(logit_std, 1e-9) <= 0.35),
        },
    }


def kv_int8_model_id(base: str | None = None) -> str:
    """A tiny:{...} model id with the int8 KV cache turned on — identical
    shapes/seed to its base, so the int8-vs-bf16 KV comparison isolates the
    cache quantization itself (the weight-int8 section's trick, applied to
    the cache)."""
    base = base or json_model_id()
    fam, js = base.split(":", 1)
    cfg = json.loads(js)
    cfg["kv_cache_dtype"] = "int8"
    return fam + ":" + json.dumps(cfg)


async def run_prefill_kv_int8(decode_tokens: int = 64) -> dict:
    """Int8 KV cache vs bf16 KV on the prefill-bound reference workload
    shape (3K ISL / 150 OSL — the config that has been flat for three judge
    rounds): TTFT p50 + tok/s with the cache as the only delta, the
    page-capacity ratio at an equal HBM budget (the ~2x claim, computed from
    the real per-page byte cost including scale planes), and teacher-forced
    greedy agreement over 64 steps (the acceptance bar: >= 0.9 — KV
    quantization error is per-row absmax/127, far gentler than weight
    quantization, so flips only happen on near-degenerate margins).

    On CPU (no TPU in the build container) the section scales the geometry
    down and forces DYNTPU_PALLAS=1 so the int8 decode + lookahead-prefill
    kernels execute in interpret mode — the smoke proves the whole
    config -> engine -> kernel path, the driver's TPU run prices it."""
    import gc
    import os

    import jax

    from dynamo_tpu.quant.kv import pages_for_hbm_budget

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        # interpret-mode kernels at a CPU-tractable geometry; D=128 keeps
        # the non-folded flash kernels (incl. the lookahead prefill) engaged
        geom = {
            "vocab_size": 512, "hidden_size": 256, "intermediate_size": 512,
            "num_layers": 2, "num_heads": 2, "num_kv_heads": 2,
            "head_dim": 128, "dtype": "f32",
        }
        base_id = "tiny:" + json.dumps(geom)
        run_kw = dict(
            rounds=1, prompt_len=192, decode_tokens=8, max_model_len=512,
            vocab=500,
        )
        batch, page_size = 2, 16
        tf_steps = min(decode_tokens, 16)  # interpret decode is slow
        tf_prompt = 64
        prev_pallas = os.environ.get("DYNTPU_PALLAS")
        os.environ["DYNTPU_PALLAS"] = "1"
    else:
        geom = json.loads(json_model_id().split(":", 1)[1])
        base_id = json_model_id()
        run_kw = dict(
            rounds=2, prompt_len=3072, decode_tokens=150, max_model_len=4096,
        )
        batch, page_size = 16, 128
        tf_steps = decode_tokens
        tf_prompt = PROMPT_LEN
        prev_pallas = None
    int8_id = kv_int8_model_id(base_id)

    try:
        # ---- throughput/TTFT: bf16-KV leg then int8-KV leg, same harness
        # shapes back-to-back so tunnel drift hits both ----
        bf16 = await run_config(batch, page_size, model_id=base_id, **run_kw)
        int8 = await run_config(batch, page_size, model_id=int8_id, **run_kw)
        speedup = int8["tok_s"] / bf16["tok_s"] if bf16["tok_s"] else None
        ttft_ratio = (
            int8["ttft_p50_ms"] / bf16["ttft_p50_ms"]
            if bf16["ttft_p50_ms"]
            else None
        )

        # ---- page capacity at an equal HBM budget (deterministic
        # arithmetic from the real per-page cost incl. int8 scale planes;
        # page 0 is the allocator's reserved trash page either way) ----
        budget = 1 << 30  # 1 GiB nominal; the RATIO is budget-independent
        cap_args = (
            page_size, geom["num_kv_heads"], geom["head_dim"],
            geom["num_layers"],
        )
        pages_bf16 = pages_for_hbm_budget(budget, *cap_args, None)
        pages_int8 = pages_for_hbm_budget(budget, *cap_args, "int8")
        capacity_ratio = pages_int8 / max(1, pages_bf16)

        # ---- greedy-agreement parity: teacher-forced per-step argmax with
        # the int8 cache replaying the bf16 chain's fed tokens ----
        import jax.numpy as jnp

        from dynamo_tpu.models.registry import load_model

        rng = np.random.default_rng(23)
        probe = rng.integers(1, run_kw["vocab"] if "vocab" in run_kw else 31000, tf_prompt)
        positions = np.arange(tf_prompt, dtype=np.int32)
        tf_ps = 64 if not on_cpu else 16
        n_pages = -(-(tf_prompt + tf_steps) // tf_ps) + 1
        page_table = np.arange(1, n_pages + 1, dtype=np.int32)

        def greedy_chain(model_id: str, forced=None):
            model, params = load_model(model_id)
            kv = model.init_kv_cache(n_pages + 2, tf_ps)
            pts = np.zeros((1, n_pages + 2), np.int32)
            pts[0, : len(page_table)] = page_table
            logits, kv = jax.jit(model.prefill)(
                params, kv, jnp.asarray(probe, jnp.int32), jnp.asarray(positions),
                jnp.asarray(page_table), jnp.ones(tf_prompt, bool),
                jnp.asarray(tf_prompt - 1),
            )
            all_logits = [np.asarray(jax.device_get(logits), np.float32)]
            decode = jax.jit(model.decode)
            out = [int(all_logits[0].argmax())]
            feed = out[0] if forced is None else forced[0]
            for i in range(tf_steps - 1):
                logits, kv = decode(
                    params, kv, jnp.asarray([feed], jnp.int32),
                    jnp.asarray([tf_prompt + i], jnp.int32), jnp.asarray(pts),
                    jnp.asarray([True]),
                )
                row = np.asarray(jax.device_get(logits), np.float32)[0]
                all_logits.append(row)
                tok = int(row.argmax())
                out.append(tok)
                feed = tok if forced is None else forced[i + 1]
            return out, np.stack(all_logits)

        ref_chain, l_bf16 = greedy_chain(base_id)
        tf_chain, l_int8 = greedy_chain(int8_id, forced=ref_chain)
        agree = sum(int(a == b) for a, b in zip(ref_chain, tf_chain)) / len(ref_chain)
        max_delta = float(np.max(np.abs(l_bf16[0] - l_int8[0])))
        logit_std = float(np.std(l_bf16[0]))
    finally:
        if prev_pallas is None:
            os.environ.pop("DYNTPU_PALLAS", None)
        else:
            os.environ["DYNTPU_PALLAS"] = prev_pallas
        gc.collect()

    return {
        "kv_cache_dtype": "int8",
        "cpu_smoke": on_cpu,
        "workload": {
            "batch": batch, "page_size": page_size,
            "prompt_len": run_kw["prompt_len"],
            "decode_tokens": run_kw["decode_tokens"],
        },
        "tok_s_bf16_kv": bf16["tok_s"],
        "tok_s_int8_kv": int8["tok_s"],
        "speedup_int8_over_bf16_kv": round(speedup, 3) if speedup else None,
        "ttft_p50_ms": {"bf16": bf16["ttft_p50_ms"], "int8": int8["ttft_p50_ms"]},
        "ttft_ratio_int8_over_bf16": round(ttft_ratio, 3) if ttft_ratio else None,
        "stage_breakdown": {"bf16": bf16.get("stage_breakdown"),
                            "int8": int8.get("stage_breakdown")},
        "page_capacity_equal_hbm": {
            "budget_bytes": budget,
            "pages_bf16": pages_bf16,
            "pages_int8": pages_int8,
            "ratio": round(capacity_ratio, 3),
        },
        "teacher_forced_steps": tf_steps,
        "teacher_forced_agreement": round(agree, 4),
        "max_abs_logit_delta": round(max_delta, 4),
        "logit_std_bf16_kv": round(logit_std, 4),
        "target": (
            "greedy agreement >= 0.9 over the teacher-forced steps; "
            "capacity ratio ~2x (1.94 at ps=128 after scale planes); on TPU "
            "the prefill-bound TTFT should finally move (halved context "
            "stream + lookahead-prefetch flash prefill)"
        ),
        "pass": {
            "greedy_agreement": bool(agree >= 0.9),
            "page_capacity_2x": bool(capacity_ratio >= 1.8),
        },
    }


async def run_spec_ngram(
    batch: int = 8, page_size: int = 64, prompt_len: int = 192,
    decode_tokens: int = 128, model_id: str | None = None,
) -> dict:
    """Speculative decoding (prompt-lookup ngram:4 + batched multi-token
    verification, dynamo_tpu/spec/) vs the classic fused-window decode path
    on a repetition-heavy workload.

    Workload: each prompt tiles a short random pattern, so the n-gram
    proposer finds its suffixes immediately and greedy decoding on this
    model's random weights settles into short loops — the regime speculative
    decoding exists for (code, quoting, multi-turn chat). Both legs run the
    SAME prompts greedy on the SAME tiny seed, so the parity check is exact
    token equality per request; the speedup is decode throughput spec/base.
    Acceptance counters come from the engine's StageStats (the same numbers
    /metrics exports as dynamo_spec_proposed_total / _accepted_total)."""
    import dataclasses

    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    cfg = bench_config(batch, page_size, model_id=model_id)
    need_pages = batch * (-(-(prompt_len + decode_tokens) // page_size) + 4)
    cfg = dataclasses.replace(cfg, num_pages=max(cfg.num_pages, need_pages))
    rng = np.random.default_rng(7)
    prompts = []
    for _ in range(batch):
        pattern = rng.integers(1, 31000, 24)
        prompts.append(np.tile(pattern, -(-prompt_len // 24))[:prompt_len].tolist())

    async def leg(speculative: str | None):
        eng = AsyncJaxEngine(dataclasses.replace(cfg, speculative=speculative))
        await eng.start()

        async def one(i: int, rnd: int):
            req = EngineRequest(
                request_id=f"s{speculative or 'base'}-{rnd}-{i}",
                token_ids=list(prompts[i]),
                sampling=SamplingParams(
                    temperature=0.0, max_tokens=decode_tokens, ignore_eos=True
                ),
            )
            toks = []
            async for out in eng.generate(req):
                if out.token is not None:
                    toks.append(out.token)
            return toks

        try:
            await asyncio.gather(*[one(i, 0) for i in range(batch)])  # warmup
            best = None
            streams = None
            for rnd in (1, 2):
                t0 = time.monotonic()
                results = await asyncio.gather(*[one(i, rnd) for i in range(batch)])
                elapsed = time.monotonic() - t0
                total = sum(len(t) for t in results)
                if best is None or total / elapsed > best:
                    best = total / elapsed
                    streams = results
            stage = eng.stage_snapshot()
        finally:
            await eng.shutdown()
        return round(best, 2), streams, stage

    # k=8 on the bench: verify rounds are synchronous, so tokens-per-round is
    # what amortizes both the weight stream and the per-round dispatch+sync;
    # at this workload's ~0.95+ acceptance a round advances ~8 tokens/slot
    base_tok_s, base_streams, _ = await leg(None)
    spec_tok_s, spec_streams, stage = await leg("ngram:8")
    parity = sum(
        int(a == b) for a, b in zip(base_streams, spec_streams)
    ) / max(1, batch)
    speedup = spec_tok_s / base_tok_s if base_tok_s else None
    proposed = stage.get("spec_proposed", 0)
    accepted = stage.get("spec_accepted", 0)
    return {
        "tok_s_spec": spec_tok_s,
        "tok_s_base": base_tok_s,
        "speedup_spec_over_base": round(speedup, 3) if speedup else None,
        "greedy_parity": round(parity, 4),
        "spec_proposed": proposed,
        "spec_accepted": accepted,
        "acceptance_rate": round(accepted / max(1, proposed), 4),
        "spec_rounds": stage.get("spec_rounds", 0),
        "spec_emitted": stage.get("spec_emitted", 0),
        "speculative": "ngram:8",
        "batch": batch,
        "prompt_len": prompt_len,
        "decode_tokens": decode_tokens,
        "workload_note": (
            "tiled 24-token patterns (prompt-lookup's native regime); both "
            "legs greedy on identical prompts/weights so parity is exact "
            "token equality per request"
        ),
        "target": "speedup >= 1.3 on this workload; greedy_parity == 1.0",
        "pass": {
            "speedup": bool(speedup and speedup >= 1.3),
            "greedy_parity": parity == 1.0,
        },
    }


async def run_spec_draft(osl: int | None = None) -> dict:
    """Draft-model speculation vs n-gram vs the classic decode path on a
    NON-repetitive workload — the regime n-gram acceptance collapses in and
    the draft-model proposer exists for (Leviathan/Chen: a small draft
    recovers multi-token rounds on arbitrary text).

    Prompts are pure random token streams (no tiling), so prompt-lookup
    finds no suffix match while the draft model keeps proposing. The draft
    IS the target model here (the only honestly-available draft in a
    synthetic-weights bench), which makes two things exact: greedy token
    parity vs the classic engine (asserted per request) and ~full
    acceptance of every proposed token. It also means the draft leg runs
    the target twice per round — on equal-size models wall-clock CANNOT
    beat classic by construction, so the gates are parity + acceptance +
    draft-pages-visible; the TPU run with a 5-10x smaller draft is where
    the tok/s win appears, and the three tok/s legs reported here price
    the dispatch overhead that win must clear.

    On CPU (no TPU in the build container) the section scales the geometry
    down like fleet_prefix does."""
    import dataclasses

    import jax

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        geom = {
            "vocab_size": 512, "hidden_size": 512, "intermediate_size": 1024,
            "num_layers": 4, "num_heads": 4, "num_kv_heads": 2,
            "head_dim": 128, "dtype": "f32",
        }
        base_id = "tiny:" + json.dumps(geom)
        batch, page_size, prompt_len, vocab = 6, 16, 128, 500
        decode_tokens = osl or 64
        prefill_buckets = (64, 128, 256)
    else:
        base_id = json_model_id()
        batch, page_size, prompt_len, vocab = 8, 64, 192, 31000
        decode_tokens = osl or 128
        prefill_buckets = (128, 256, 512)
    K = 4
    pages_per_seq = -(-(prompt_len + decode_tokens + K + 1) // page_size) + 2
    num_pages = batch * pages_per_seq + 8

    rng = np.random.default_rng(17)
    # pure random streams: no token pair repeats by construction of the draw
    # (vocab >> prompt_len), so n-gram's longest-suffix match comes up empty
    prompts = [rng.integers(1, vocab, prompt_len).tolist() for _ in range(batch)]

    def cfg(speculative):
        return EngineConfig(
            model_id=base_id, page_size=page_size, num_pages=num_pages,
            max_seqs=batch, max_model_len=prompt_len + decode_tokens + 2 * K,
            prefill_buckets=prefill_buckets, decode_steps=8, pipeline_depth=2,
            speculative=speculative,
        )

    async def leg(speculative: str | None):
        eng = AsyncJaxEngine(cfg(speculative))
        await eng.start()

        async def one(i: int, rnd: int):
            req = EngineRequest(
                request_id=f"d{(speculative or 'base').split(':')[0]}-{rnd}-{i}",
                token_ids=list(prompts[i]),
                sampling=SamplingParams(
                    temperature=0.0, max_tokens=decode_tokens, ignore_eos=True
                ),
            )
            toks = []
            async for out in eng.generate(req):
                if out.token is not None:
                    toks.append(out.token)
            return toks

        try:
            await asyncio.gather(*[one(i, 0) for i in range(batch)])  # warmup
            best, streams = None, None
            for rnd in (1, 2):
                t0 = time.monotonic()
                results = await asyncio.gather(*[one(i, rnd) for i in range(batch)])
                elapsed = time.monotonic() - t0
                total = sum(len(t) for t in results)
                if best is None or total / elapsed > best:
                    best = total / elapsed
                    streams = results
            stage = eng.stage_snapshot()
            snap = eng.resource_snapshot()
        finally:
            await eng.shutdown()
        return round(best, 2), streams, stage, snap

    base_tok_s, base_streams, _, _ = await leg(None)
    ngram_tok_s, ngram_streams, ngram_stage, _ = await leg(f"ngram:{K}")
    draft_spec = f"draft:{base_id}:{K}"
    draft_tok_s, draft_streams, draft_stage, draft_snap = await leg(draft_spec)

    parity = sum(
        int(a == b) for a, b in zip(base_streams, draft_streams)
    ) / max(1, batch)
    ngram_parity = sum(
        int(a == b) for a, b in zip(base_streams, ngram_streams)
    ) / max(1, batch)

    def rate(stage):
        return stage.get("spec_accepted", 0) / max(1, stage.get("spec_proposed", 0))

    draft_rate, ngram_rate = rate(draft_stage), rate(ngram_stage)
    assert parity == 1.0, "draft==target greedy must be token-identical"
    assert draft_rate > ngram_rate, (
        f"draft acceptance {draft_rate} must beat n-gram's {ngram_rate} on "
        "non-repetitive text"
    )
    assert draft_snap.get("spec_draft_pages_total", 0) > 0, (
        "draft KV pages must be visible in resource_snapshot()"
    )
    return {
        "tok_s_draft": draft_tok_s,
        "tok_s_ngram": ngram_tok_s,
        "tok_s_classic": base_tok_s,
        "speedup_draft_over_classic": round(draft_tok_s / base_tok_s, 3),
        "speedup_ngram_over_classic": round(ngram_tok_s / base_tok_s, 3),
        "acceptance_rate_draft": round(draft_rate, 4),
        "acceptance_rate_ngram": round(ngram_rate, 4),
        "greedy_parity_draft": round(parity, 4),
        "greedy_parity_ngram": round(ngram_parity, 4),
        "spec_proposed_draft": draft_stage.get("spec_proposed", 0),
        "spec_accepted_draft": draft_stage.get("spec_accepted", 0),
        "spec_proposed_ngram": ngram_stage.get("spec_proposed", 0),
        "spec_draft_calls": draft_stage.get("spec_draft_calls", 0),
        "spec_draft_dispatch_s": draft_stage.get("spec_draft_s", 0.0),
        "spec_draft_prefills": draft_stage.get("spec_draft_prefills", 0),
        "draft_pages_total": draft_snap.get("spec_draft_pages_total", 0),
        "draft_model": "== target (exact-parity smoke; TPU uses a smaller draft)",
        "k": K,
        "batch": batch,
        "prompt_len": prompt_len,
        "decode_tokens": decode_tokens,
        "workload_note": (
            "pure random token streams — prompt-lookup finds no match "
            "(acceptance ~0) while the draft model proposes every round"
        ),
        "target": (
            "greedy_parity_draft == 1.0; acceptance_rate_draft > "
            "acceptance_rate_ngram; draft pages visible. tok/s legs price "
            "dispatch overhead: a same-size draft can't beat classic on "
            "wall clock (runs the target twice) — the TPU win needs a "
            "5-10x smaller draft"
        ),
        "pass": {
            "greedy_parity": parity == 1.0,
            "draft_acceptance_above_ngram": bool(draft_rate > ngram_rate),
            "draft_pages_visible": bool(
                draft_snap.get("spec_draft_pages_total", 0) > 0
            ),
        },
    }


async def run_multi_lora(M: int = 4, osl: int = 32) -> dict:
    """Multi-LoRA multiplexing: M fine-tunes of one base model served from
    ONE engine via gathered adapter kernels (Punica/S-LoRA BGMV shape).

    Three arms:
      - base engine, no adapters: the throughput reference at the same
        batch shape
      - lora engine, mixed batch: the B concurrent requests round-robin
        across M adapters — each decode window is ONE gathered dispatch
        (per-slot adapter ids gathered on device), not M per-adapter calls
      - parity: every request re-served ALONE on a fresh identical engine
        must be token-identical to its mixed-batch output (greedy)

    Plus an eviction arm: M adapters through M//2 device slots, proving the
    LRU hot-swap path churns without breaking determinism. Acceptance:
    mixed_tok_s_ratio >= 0.85 of base on the same shape (recorded, gated on
    TPU where the ratio is meaningful; CPU smoke records the measured value).

    On CPU (no TPU in the build container) the section scales the geometry
    down; parity/evictions are exact either way."""
    import gc

    import jax

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        geom = {
            "vocab_size": 512, "hidden_size": 256, "intermediate_size": 512,
            "num_layers": 4, "num_heads": 4, "num_kv_heads": 2,
            "head_dim": 64, "dtype": "f32",
        }
        base_id = "tiny:" + json.dumps(geom)
        page_size, plen, vocab, rank = 16, 96, 500, 8
        prefill_buckets = (64, 128)
    else:
        base_id = json_model_id()
        page_size, plen, vocab, rank = 64, 512, 31000, 16
        prefill_buckets = (128, 256, 512)

    B = 8
    adapters = tuple(f"a{i}=random:{100 + i}" for i in range(M))
    num_pages = (B + 2) * (-(-(plen + osl) // page_size) + 2) + 8

    def cfg(**over):
        d = dict(
            model_id=base_id, page_size=page_size, num_pages=num_pages,
            max_seqs=B, max_model_len=2048, prefill_buckets=prefill_buckets,
            decode_steps=8, pipeline_depth=2,
        )
        d.update(over)
        return EngineConfig(**d)

    rng = np.random.default_rng(43)
    prompts = [rng.integers(1, vocab, plen).tolist() for _ in range(B)]
    lane_lora = [f"a{i % M}" for i in range(B)]

    async def one(eng, rid, prompt, lora):
        from dynamo_tpu.engine.sampling import SamplingParams
        from dynamo_tpu.engine.scheduler import EngineRequest

        req = EngineRequest(
            request_id=rid, token_ids=list(prompt),
            sampling=SamplingParams(temperature=0.0, max_tokens=osl, ignore_eos=True),
            lora_name=lora,
        )
        toks = []
        async for out in eng.generate(req):
            if out.token is not None:
                toks.append(out.token)
        return toks

    async def throughput(eng, tag, loras):
        # warmup round (compiles + allocator steady state), then 2 measured
        await asyncio.gather(*[
            one(eng, f"w-{tag}-{i}", rng.integers(1, vocab, plen).tolist(), loras[i])
            for i in range(B)
        ])
        best, toks_last = 0.0, None
        for rnd in range(2):
            fresh = [rng.integers(1, vocab, plen).tolist() for _ in range(B)]
            use = prompts if rnd == 1 else fresh  # final round = parity prompts
            t0 = time.monotonic()
            results = await asyncio.gather(*[
                one(eng, f"{tag}-{rnd}-{i}", use[i], loras[i]) for i in range(B)
            ])
            dt = time.monotonic() - t0
            best = max(best, sum(len(t) for t in results) / dt)
            toks_last = results
        return best, toks_last

    cleanups = []
    try:
        base_eng = AsyncJaxEngine(cfg())
        await base_eng.start()
        cleanups.append(base_eng.shutdown)
        tok_s_base, _ = await throughput(base_eng, "base", [""] * B)

        lora_eng = AsyncJaxEngine(cfg(
            lora_adapters=adapters, max_loras=M, lora_rank=rank
        ))
        await lora_eng.start()
        cleanups.append(lora_eng.shutdown)
        tok_s_mixed, mixed_toks = await throughput(lora_eng, "mixed", lane_lora)
        lora_snap = lora_eng.resource_snapshot()

        # parity: each request alone on a FRESH identical engine (no shared
        # prefix cache / device state with the mixed run)
        alone_eng = AsyncJaxEngine(cfg(
            lora_adapters=adapters, max_loras=M, lora_rank=rank
        ))
        await alone_eng.start()
        cleanups.append(alone_eng.shutdown)
        parity = True
        for i in range(B):
            alone = await one(alone_eng, f"alone-{i}", prompts[i], lane_lora[i])
            parity = parity and alone == mixed_toks[i]

        # eviction/hot-swap arm: M adapters through M//2 slots, two passes —
        # the second pass's reloads must reproduce the first pass exactly
        evict_eng = AsyncJaxEngine(cfg(
            lora_adapters=adapters, max_loras=max(1, M // 2), lora_rank=rank
        ))
        await evict_eng.start()
        cleanups.append(evict_eng.shutdown)
        churn_prompt = prompts[0]
        first_pass = {}
        for name in [f"a{i}" for i in range(M)]:
            first_pass[name] = await one(evict_eng, f"e1-{name}", churn_prompt, name)
        swap_coherent = True
        for name in [f"a{i}" for i in range(M)]:
            again = await one(evict_eng, f"e2-{name}", churn_prompt, name)
            swap_coherent = swap_coherent and again == first_pass[name]
        evictions = evict_eng.runner.lora_store.evictions
    finally:
        for stop in reversed(cleanups):
            try:
                await stop()
            except Exception:
                import traceback

                traceback.print_exc()
        gc.collect()

    assert parity, "mixed-adapter batch diverged from single-adapter serving"
    assert swap_coherent, "LRU hot-swap changed a reloaded adapter's output"
    assert evictions > 0, "eviction arm never churned a slot"
    ratio = round(tok_s_mixed / max(tok_s_base, 1e-9), 3)
    if not on_cpu:
        assert ratio >= 0.85, f"mixed-adapter throughput ratio {ratio} < 0.85"
    return {
        "cpu_smoke": on_cpu,
        "workload": {
            "adapters": M, "batch": B, "prompt_len": plen, "osl": osl,
            "lora_rank": rank, "page_size": page_size,
        },
        "tok_s_base": round(tok_s_base, 2),
        "tok_s_mixed": round(tok_s_mixed, 2),
        "mixed_tok_s_ratio": ratio,
        "parity_mixed_vs_alone": parity,
        "hot_swap_coherent": swap_coherent,
        "resident_evictions": evictions,
        "lora_loads": lora_snap.get("lora_loads"),
        "lora_resident": lora_snap.get("lora_resident"),
        "target": (
            "parity exact; hot-swap coherent; evictions > 0; mixed 4-adapter "
            "decode >= 0.85x base throughput at the same batch shape (ONE "
            "gathered dispatch per window — gated on TPU, recorded on the "
            "CPU smoke)"
        ),
    }


async def run_http_serving(batch: int = 32, page_size: int = 64) -> dict:
    """HTTP-level serving numbers through /v1/chat/completions — the
    reference's published numbers are serving-stack numbers, not engine-loop
    numbers (reference: docs/architecture.md:57-87).

    Serves a full HF-FORMAT checkpoint (TinyLlama-1.1B geometry: config.json
    + safetensors + a genuine trained BPE tokenizer with chat template; the
    weight VALUES are synthetic — no real weights are reachable zero-egress,
    and throughput is independent of them)."""
    import gc
    import os
    import sys
    import time as _time

    import aiohttp

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tools.make_hf_checkpoint import make_checkpoint

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.frontends.pipeline import build_pipeline
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    ckpt = "/tmp/dyntpu_ckpt_tinyllama_1b"
    if not os.path.exists(os.path.join(ckpt, "model.safetensors")):
        make_checkpoint(ckpt)

    card = ModelDeploymentCard.from_local_path(ckpt, name="tinyllama-1.1b-synth")
    engine = AsyncJaxEngine(EngineConfig.for_model(
        ckpt, page_size=page_size, num_pages=max(320, batch * 20 * 16 // page_size),
        max_seqs=batch, max_model_len=1024, prefill_buckets=(128, 256, 512),
        decode_steps=32, pipeline_depth=3,
        # pre-compile every decode-window + (packed-)prefill trace variant:
        # a cold XLA compile mid-HTTP-traffic stalls past client timeouts on
        # this tunneled platform (r3 post-mortem)
        warmup=True,
    ))
    await engine.start()

    rng = np.random.default_rng(17)

    # engine-loop leg runner: the SAME engine and workload shape with the
    # HTTP/preprocessor/detokenizer/SSE stack removed — the serving-overhead
    # denominator. Cross-session comparisons are useless here (the tunnel
    # drifts 2x run-to-run); only a same-process ratio is meaningful.
    # 304 tokens = the measured tokenized length of this section's chat
    # prompts, so both legs hit the same prefill bucket/packing shape.
    async def engine_round(rnd: int):
        fresh = [rng.integers(1, 30000, 304).tolist() for _ in range(batch)]
        t0 = _time.monotonic()
        res = await asyncio.gather(*[
            _request(engine, f"eng-{rnd}-{i}", fresh[i], max_tokens=DECODE_TOKENS)
            for i in range(batch)
        ])
        tok_s = batch * DECODE_TOKENS / (_time.monotonic() - t0)
        return tok_s, [t for _, t, _ in res]

    # symmetric warmup (r4 post-mortem: the engine leg measured BELOW the
    # HTTP leg — ratio 1.105 > 1 — because it ran first, straight out of
    # 8-token warmups, paying the allocator's fill/evict transient that
    # run_config's full-length warmup pass exists to absorb):
    #   1. both legs get an 8-token compile warmup
    #   2. both legs get one full-length warmup round (allocator steady state)
    #   3. measured rounds ALTERNATE engine/HTTP so tunnel drift between legs
    #      cancels instead of biasing whichever leg ran last
    await asyncio.gather(*[
        _request(engine, f"eng-w-{i}", rng.integers(1, 30000, 304).tolist(), max_tokens=8)
        for i in range(batch)
    ])
    await engine_round(99)  # engine full-length warmup

    svc = HttpService(host="127.0.0.1", port=0)
    svc.manager.add(build_pipeline(engine, card))
    port = await svc.start()
    base = f"http://127.0.0.1:{port}/v1"

    words = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf", "hotel"]

    async def one(session, i, rnd, max_tokens=DECODE_TOKENS):
        body = {
            "model": "tinyllama-1.1b-synth",
            "messages": [{
                "role": "user",
                "content": " ".join(words[(i + j + rnd) % len(words)] for j in range(96)) + f" q{rnd}-{i}",
            }],
            "max_tokens": max_tokens,
            "temperature": 0.0,
            "stream": True,
            "ext": {"ignore_eos": True},
        }
        t0 = _time.monotonic()
        ttft = None
        async with session.post(f"{base}/chat/completions", json=body) as r:
            r.raise_for_status()
            async for line in r.content:
                # first delta chunk of ANY kind: the service now emits the
                # role chunk at first-token time, so this is true first-token
                # TTFT — comparable to the engine leg's (first CONTENT can
                # lag several tokens while byte fragments stabilize)
                if line.startswith(b"data:") and b'"delta"' in line:
                    if ttft is None:
                        ttft = _time.monotonic() - t0
        if ttft is None:
            ttft = _time.monotonic() - t0  # stream completed with no delta
        # ignore_eos + max_tokens => the engine generated exactly max_tokens
        # (SSE delta count undercounts: multi-token BPE merges coalesce)
        return max_tokens, ttft

    async def http_round(session, rnd):
        t0 = _time.monotonic()
        results = await asyncio.gather(*[one(session, i, rnd) for i in range(batch)])
        elapsed = _time.monotonic() - t0
        toks = sum(n for n, _ in results)
        return toks / elapsed, elapsed, [t for _, t in results if t is not None]

    eng_rounds, http_rounds = [], []
    try:
        # no total timeout (aiohttp default 300 s aborted r3's whole bench):
        # per-request pacing is the sock_read gap between stream chunks, sized
        # far above worst-case engine stalls; the section-level timeout in
        # run() is the real backstop
        client_timeout = aiohttp.ClientTimeout(
            total=None, sock_connect=60, sock_read=600
        )
        async with aiohttp.ClientSession(timeout=client_timeout) as session:
            # HTTP leg warmups: compile (8 tok) + one full-length round, so
            # both legs enter their measured rounds in the same engine state
            await asyncio.gather(*[one(session, i, 0, max_tokens=8) for i in range(batch)])
            await http_round(session, 98)
            # measured rounds alternate legs (tunnel drift cancels)
            for rnd in (1, 2):
                eng_rounds.append(await engine_round(rnd))
                http_rounds.append(await http_round(session, rnd))
    finally:
        # a failed round must not leak the engine's HBM into the parity
        # sections that start their own engines next
        await svc.stop()
        await engine.shutdown()
        gc.collect()
    eng_best, eng_ttfts = max(eng_rounds, key=lambda r: r[0])
    tok_s, elapsed, ttfts = max(http_rounds, key=lambda r: r[0])
    return {
        "model": "TinyLlama-1.1B geometry (synthetic HF checkpoint)",
        "endpoint": "/v1/chat/completions (stream)",
        "tok_s": round(tok_s, 2),
        "engine_loop_tok_s": round(eng_best, 2),
        "http_over_engine_ratio": round(tok_s / eng_best, 3) if eng_best else None,
        "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 1),
        "engine_ttft_p50_ms": round(float(np.percentile(eng_ttfts, 50)) * 1e3, 1),
        "rounds": {
            "engine_tok_s": [round(r[0], 1) for r in eng_rounds],
            "http_tok_s": [round(r[0], 1) for r in http_rounds],
        },
        "batch": batch,
        "decode_tokens": DECODE_TOKENS,
        "elapsed_s": round(elapsed, 3),
        "target": "http_over_engine_ratio in (0.8, 1.0] (same process, same "
                  "shapes, symmetric warmup, alternating measured rounds)",
    }


async def run_replay() -> dict:
    """Trace-replay bench spine (dynamo_tpu/loadgen): seeded scenario traces
    replayed against in-process engines, producing per-scenario
    goodput/TTFT-p99/ITL-p99/tok_s — one arm per post-r05 subsystem:

      bursty_chat            base engine (the chat shape)
      int8_kv                bursty chat on an int8 KV cache
      long_context_sessions  shared-prefix sessions (table ladder / prefix cache)
      lora_churn             zipf hot/cold adapters over multiple tenants
      spec_draft             bursty chat under draft-model speculation
      fleet_prefix           session prefixes pulled from a peer holder
      mm_vl                  Qwen2-VL image requests (first perf numbers)

    On CPU (no TPU in the build container) geometry and budgets scale down —
    numbers are labeled cpu_smoke; the driver's TPU run prices the same
    scenarios at serving geometry. Every arm records the replay report's
    goodput verdict against the scenario's (platform-scaled) SLO budgets."""
    import gc

    import jax

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.loadgen import compile_trace, load_scenario
    from dynamo_tpu.loadgen.replay import ReplayMetrics, replay_engine
    from dynamo_tpu.utils.goodput import GoodputTracker

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        base_id = "tiny"  # registry tiny (64-hidden f32): CPU-fast
        n, speed = 12, 2.0
        # CPU smoke budgets: generous enough that the verdict measures the
        # serving stack, not the absence of a TPU
        budgets = {"slo_ttft_ms": 30000.0, "slo_itl_ms": 5000.0}
        eng_kw = dict(
            page_size=4, num_pages=1024, max_seqs=4, max_model_len=640,
            prefill_buckets=(16, 32, 64, 128, 256), decode_steps=4,
            pipeline_depth=2,
        )
        scale = dict(
            isl_mean=24, isl_max=96, osl_dist="fixed", osl_mean=8, osl_max=8,
            rate_rps=8.0, vocab=256, **budgets,
        )
        lctx_scale = dict(
            shared_prefix_len=128, isl_mean=32, isl_max=96, osl_dist="fixed",
            osl_mean=8, osl_max=8, vocab=256, **budgets,
        )
    else:
        base_id = json_model_id()
        n, speed = 48, 1.0
        budgets = {"slo_ttft_ms": 2000.0, "slo_itl_ms": 100.0}
        eng_kw = dict(
            page_size=16, num_pages=8192, max_seqs=16, max_model_len=2048,
            prefill_buckets=(128, 256, 512), decode_steps=16,
            pipeline_depth=3,
        )
        scale = dict(isl_mean=128, isl_max=512, osl_mean=48, osl_max=128,
                     rate_rps=16.0, vocab=31000, **budgets)
        lctx_scale = dict(shared_prefix_len=512, isl_mean=128, isl_max=512,
                          osl_mean=32, osl_max=64, vocab=31000, **budgets)

    lora_names = ("a1", "a2", "a3", "a4", "a5", "a6")
    arms = [
        # (scenario key, spec, engine-config overrides, model id)
        ("bursty_chat",
         load_scenario("bursty_chat", num_requests=n).replace(**scale),
         {}, base_id),
        ("int8_kv",
         load_scenario("bursty_chat", num_requests=n, seed=1).replace(
             name="int8_kv", **scale),
         {"kv_cache_dtype": "int8"}, base_id),
        ("long_context_sessions",
         load_scenario("long_context_sessions", num_requests=max(8, n // 2))
         .replace(**lctx_scale),
         {}, base_id),
        ("lora_churn",
         load_scenario("lora_churn", num_requests=n).replace(
             adapters=lora_names, **scale),
         {"lora_adapters": lora_names, "max_loras": 4, "lora_rank": 4},
         base_id),
        ("spec_draft",
         load_scenario("bursty_chat", num_requests=max(8, n // 2), seed=2)
         .replace(name="spec_draft", **scale),
         {"speculative": f"draft:{base_id}:2"}, base_id),
        ("mm_vl",
         load_scenario("mm_vl", num_requests=max(6, n // 4)).replace(
             vocab=250, image_hw=(16, 16), **budgets),
         {"max_model_len": 640}, "tiny-vl"),
    ]

    out: dict = {
        "cpu_smoke": on_cpu,
        "platform": jax.devices()[0].platform,
        "speed": speed,
        "budgets": budgets,
        "scenarios": {},
    }
    goodput = GoodputTracker()
    for key, spec, over, model_id in arms:
        eng = AsyncJaxEngine(EngineConfig(model_id=model_id, **{**eng_kw, **over}))
        try:
            await eng.start()
            # warm the executables out of the measurement (a cold XLA compile
            # inside the replay would blow every budget on its own)
            warm = compile_trace(spec.replace(seed=spec.seed + 97,
                                              num_requests=2, images=spec.images))
            await replay_engine(eng, warm, spec=spec, speed=100.0)
            report = await replay_engine(
                eng, compile_trace(spec), spec=spec, speed=speed,
                goodput=goodput, metrics=ReplayMetrics(),
            )
            report.pop("outcomes", None)
            report["engine_stage"] = eng.stage_snapshot()
            out["scenarios"][key] = report
        finally:
            await eng.shutdown()
            gc.collect()

    # fleet_prefix arm: a holder engine computes (and serves) every session's
    # shared prefix; the replay engine's requests carry the holder hint, so
    # admission PULLS the prefix over the dataplane instead of recomputing
    from dynamo_tpu.disagg.prefix_fetch import KvPullServer, PrefixFetchClient

    spec = load_scenario(
        "long_context_sessions", num_requests=max(8, n // 2), seed=3,
    ).replace(name="fleet_prefix", **lctx_scale)
    trace = compile_trace(spec)
    ps = eng_kw["page_size"]
    prefix_blocks = spec.shared_prefix_len // ps
    cfg = dict(eng_kw, prefix_fetch_timeout_s=60.0)
    cleanups = []
    try:
        holder = AsyncJaxEngine(EngineConfig(model_id=base_id, **cfg))
        await holder.start()
        cleanups.append(holder.shutdown)
        puller = AsyncJaxEngine(EngineConfig(model_id=base_id, **cfg))
        await puller.start()
        cleanups.append(puller.shutdown)
        srv = await KvPullServer(holder, host="127.0.0.1").start()
        cleanups.append(srv.stop)
        fetcher = PrefixFetchClient(asyncio.get_running_loop(), timeout_s=60.0)
        puller.attach_prefix_fetch(fetcher)
        # seed the holder's cache with each session's shared prefix
        seen = set()
        for tr in trace:
            if tr.session not in seen:
                seen.add(tr.session)
                await _request(holder, f"seed-{tr.session}",
                               tr.token_ids[: spec.shared_prefix_len],
                               max_tokens=2)

        def attach_holder(req, tr):
            req.kv_holder_addr = srv.address
            req.kv_holder_blocks = prefix_blocks

        warm = compile_trace(spec.replace(seed=spec.seed + 97, num_requests=2))
        await replay_engine(puller, warm, spec=spec, speed=100.0,
                            request_hook=attach_holder)
        report = await replay_engine(
            puller, trace, spec=spec, speed=speed, goodput=goodput,
            metrics=ReplayMetrics(), request_hook=attach_holder,
        )
        report.pop("outcomes", None)
        sched = puller.scheduler
        report["prefix_fetch"] = {
            "hits": sched.prefix_fetch_hits,
            "fallbacks": sched.prefix_fetch_fallbacks,
            "pulled_blocks": sched.prefix_fetch_blocks,
            "pulled_bytes": sched.prefix_fetch_bytes,
        }
        out["scenarios"]["fleet_prefix"] = report
        assert sched.prefix_fetch_hits > 0, "fleet_prefix replay never pulled"
    finally:
        for stop in reversed(cleanups):
            try:
                await stop()
            except Exception:
                import traceback

                traceback.print_exc()
        gc.collect()

    out["overall_goodput"] = goodput.snapshot()["goodput"]
    # every scenario must have produced the acceptance keys
    for key, rep in out["scenarios"].items():
        for field in ("goodput", "ttft_p99_ms", "tok_s"):
            assert rep.get(field) is not None, f"replay.{key}.{field} missing"
    return out


async def run_step_anatomy() -> dict:
    """Step-anatomy plane (utils/step_anatomy.py): price the host-overhead
    fraction and the live roofline fraction across three serving arms —
    plain decode, draft-model speculation, and multi-LoRA — from the
    per-dispatch phase attribution the scheduler now records on every step.

    The r5 decomposition ("decode at 69.8% of the 5.05 ms floor, ~30% of
    every step host overhead") was a one-off tools/profile_decode.py run;
    this section re-derives the same two numbers from the standing plane so
    every future round (and the item-3 fused-decode work) has a before/after
    in the artifact. Consistency gate: the anatomy's device_wait seconds
    must equal the scheduler's reconcile_wait_s counter (same measurement
    site), so host_frac = 1 - reconcile_wait/total is checkable from
    StageStats alone."""
    import gc

    import jax

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        base_id = "tiny"
        n, plen, osl = 8, 48, 32
        eng_kw = dict(
            page_size=4, num_pages=1024, max_seqs=8, max_model_len=256,
            prefill_buckets=(16, 32, 64), decode_steps=4, pipeline_depth=2,
        )
        vocab = 256
    else:
        base_id = json_model_id()
        n, plen, osl = 16, PROMPT_LEN, DECODE_TOKENS
        eng_kw = dict(
            page_size=64, num_pages=4096, max_seqs=16, max_model_len=1024,
            prefill_buckets=(128, 256), decode_steps=32, pipeline_depth=3,
        )
        vocab = 31000

    lora_names = ("a1", "a2", "a3")
    arms = [
        ("decode", {}, ()),
        ("spec_draft", {"speculative": f"draft:{base_id}:2"}, ()),
        ("multi_lora",
         {"lora_adapters": lora_names, "max_loras": 2, "lora_rank": 4},
         lora_names),
    ]

    async def one(eng, rid, prompt, lora_name=""):
        req = EngineRequest(
            request_id=rid, token_ids=list(prompt),
            sampling=SamplingParams(
                temperature=0.0, max_tokens=osl, ignore_eos=True
            ),
            lora_name=lora_name,
        )
        async for _ in eng.generate(req):
            pass

    out: dict = {"cpu_smoke": on_cpu, "platform": jax.devices()[0].platform}
    rng = np.random.default_rng(7)
    for key, over, adapters in arms:
        eng = AsyncJaxEngine(EngineConfig(model_id=base_id, **{**eng_kw, **over}))
        try:
            await eng.start()
            # warm the executables (and the LoRA host loads) out of the
            # measured anatomy, then reset the counters so the recorded
            # phases cover steady-state serving only
            await asyncio.gather(*[
                one(eng, f"w-{i}", rng.integers(1, vocab, plen).tolist(),
                    lora_name=adapters[i % len(adapters)] if adapters else "")
                for i in range(min(4, n))
            ])
            from dynamo_tpu.utils.step_anatomy import StepAnatomy

            sched = eng.scheduler
            sched.anatomy = StepAnatomy(roofline=sched.anatomy.roofline)
            store = getattr(eng.runner, "lora_store", None)
            if store is not None:
                store.anatomy = sched.anatomy
            base_wait = sched.stage.reconcile_wait_s
            t0 = time.monotonic()
            await asyncio.gather(*[
                one(eng, f"m-{i}", rng.integers(1, vocab, plen).tolist(),
                    lora_name=adapters[i % len(adapters)] if adapters else "")
                for i in range(n)
            ])
            wall = time.monotonic() - t0
            snap = sched.anatomy.snapshot()
            wait_s = sum(
                v for k, v in snap["phase_seconds"].items()
                if k.startswith("device_wait.")
            )
            total_s = sum(snap["phase_seconds"].values())
            stage_wait = sched.stage.reconcile_wait_s - base_wait
            arm = {
                "host_frac": snap["host_frac"],
                "decode_host_frac": snap["decode_host_frac"],
                "roofline_frac": snap["roofline_frac"],
                "dispatch_gap_ms_p50": snap["dispatch_gap_ms_p50"],
                "dispatches": snap["dispatches"],
                "phase_seconds": snap["phase_seconds"],
                "attributed_s": round(total_s, 4),
                "wall_s": round(wall, 4),
                "device_wait_s": round(wait_s, 4),
                "stage_reconcile_wait_s": round(stage_wait, 4),
                "output_tokens": n * osl,
            }
            # acceptance: the anatomy's device_wait and StageStats'
            # reconcile_wait_s are the SAME measurement (one site feeds
            # both), so host_frac is auditable from the stage counters
            spec_wait = sum(
                v for k, v in snap["phase_seconds"].items()
                if k in ("device_wait.spec_draft", "device_wait.spec_verify")
            )
            assert abs((wait_s - spec_wait) - stage_wait) <= max(
                0.05, 0.05 * max(wait_s, stage_wait)
            ), f"{key}: anatomy device_wait {wait_s} (spec {spec_wait}) " \
               f"disagrees with reconcile_wait_s {stage_wait}"
            assert arm["host_frac"] is not None
            if key == "decode":
                assert arm["roofline_frac"] is not None
                assert snap["dispatches"].get("decode_window", 0) >= 2
            if key == "spec_draft":
                assert snap["dispatches"].get("spec_verify", 0) >= 1
                assert snap["dispatches"].get("spec_draft", 0) >= 1
            if key == "multi_lora":
                assert snap["dispatches"].get("lora_slot_load", 0) >= 1
            out[key] = arm
        finally:
            await eng.shutdown()
            gc.collect()
    return out


async def run_prefill_anatomy() -> dict:
    """Prefill anatomy (the dispatch-cost attack): the same ref-shaped burst
    through two engines that differ ONLY in ``prefill_pipeline_depth`` —
    1 = strict reconcile-per-packed-call (the old mixed-regime behavior),
    2 = dispatch-ahead. Acceptance, asserted here: exact greedy token parity
    between the arms (the knob must not touch numerics), and strictly fewer
    forced blocking reconciles (``stage.prefill_stalls``) in the pipelined
    arm. The artifact also records the standing plane's measured per-call
    fixed cost (``prefill_fixed_ms``, the rows-amortized host_prep+dispatch
    seconds) and roofline fraction, so the tools/profile_prefill.py
    decomposition has a live counterpart every round."""
    import gc

    import jax

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        base_id = "tiny"
        # 12 x 48-token prompts against 64-row buckets at 2 lanes: each
        # burst is ~6 packed calls back-to-back, so the depth-1 arm pays a
        # forced stall per call while depth-2 overlaps them
        n, plen, osl = 12, 48, 16
        eng_kw = dict(
            page_size=4, num_pages=1024, max_seqs=16, max_model_len=256,
            prefill_buckets=(16, 32, 64), prefill_lanes=2,
            decode_steps=4, pipeline_depth=2,
        )
        vocab = 256
    else:
        # the reference-shaped workload (ISL 3072 / OSL 150): each prompt
        # is 6 chunked 512-row calls, the regime the ~10 ms per-call fixed
        # cost dominates
        base_id = json_model_id()
        n, plen, osl = 8, 3072, 150
        eng_kw = dict(
            page_size=64, num_pages=1024, max_seqs=8, max_model_len=4096,
            prefill_buckets=(128, 256, 512), prefill_lanes=4,
            decode_steps=32, pipeline_depth=3,
        )
        vocab = 31000

    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, vocab, plen).tolist() for _ in range(n)]

    async def one(eng, rid, prompt, toks_out, ttfts):
        req = EngineRequest(
            request_id=rid, token_ids=list(prompt),
            sampling=SamplingParams(
                temperature=0.0, max_tokens=osl, ignore_eos=True
            ),
        )
        t0 = time.monotonic()
        first = None
        toks_out[rid] = []
        async for out in eng.generate(req):
            if out.token is not None:
                if first is None:
                    first = time.monotonic() - t0
                toks_out[rid].append(out.token)
        if first is not None:
            ttfts.append(first)

    out: dict = {"cpu_smoke": on_cpu, "platform": jax.devices()[0].platform}
    arm_tokens: dict[int, dict] = {}
    for depth in (1, 2):
        eng = AsyncJaxEngine(EngineConfig(
            model_id=base_id, prefill_pipeline_depth=depth, **eng_kw
        ))
        try:
            await eng.start()
            toks: dict = {}
            ttfts: list = []
            # warm the executables out of the measured counters
            await asyncio.gather(*[
                one(eng, f"w-{i}", prompts[i], toks, ttfts)
                for i in range(min(4, n))
            ])
            sched = eng.scheduler
            from dynamo_tpu.utils.step_anatomy import StepAnatomy

            sched.anatomy = StepAnatomy(roofline=sched.anatomy.roofline)
            base_stalls = sched.stage.prefill_stalls
            base_calls = sched.stage.prefill_calls
            base_waits = sched.stage.reconcile_waits
            toks, ttfts = {}, []
            t0 = time.monotonic()
            await asyncio.gather(*[
                one(eng, i, prompts[i], toks, ttfts) for i in range(n)
            ])
            wall = time.monotonic() - t0
            snap = sched.anatomy.snapshot()
            arm_tokens[depth] = toks
            out[f"depth{depth}"] = {
                "prefill_stalls": sched.stage.prefill_stalls - base_stalls,
                "prefill_calls": sched.stage.prefill_calls - base_calls,
                "reconcile_waits": sched.stage.reconcile_waits - base_waits,
                "prefill_fixed_ms": snap["prefill_fixed_ms"],
                "prefill_host_frac": snap["prefill_host_frac"],
                "prefill_roofline_frac": snap["prefill_roofline_frac"],
                "ttft_p50_ms": round(float(np.median(ttfts)) * 1e3, 1),
                "wall_s": round(wall, 4),
                "output_tokens": sum(len(v) for v in toks.values()),
            }
        finally:
            await eng.shutdown()
            gc.collect()

    d1, d2 = out["depth1"], out["depth2"]
    # acceptance 1: the knob is a scheduling change only — greedy tokens
    # must match token-for-token across the arms
    assert set(arm_tokens[1]) == set(arm_tokens[2])
    mismatch = [r for r in arm_tokens[1] if arm_tokens[1][r] != arm_tokens[2][r]]
    assert not mismatch, f"greedy parity broke for requests {mismatch}"
    out["greedy_parity"] = "exact"
    # acceptance 2: dispatch-ahead must strictly cut the forced blocking
    # reconciles the depth-1 contract pays per packed call
    assert d1["prefill_stalls"] > 0, "depth-1 arm recorded no prefill stalls"
    assert d2["prefill_stalls"] < d1["prefill_stalls"], (
        f"pipelined arm did not reduce stalls: "
        f"{d2['prefill_stalls']} vs {d1['prefill_stalls']}"
    )
    # both arms price the standing prefill plane
    assert d2["prefill_fixed_ms"] is not None
    assert d2["prefill_roofline_frac"] is not None
    out["stall_delta"] = d1["prefill_stalls"] - d2["prefill_stalls"]
    return out


async def run_events() -> dict:
    """Flight-recorder overhead (observability tentpole): the journal must be
    effectively free on the hot path, so price one emit() against the MEASURED
    decode step wall on this platform and assert the fraction stays under 1%.
    Also price the forensic read side — timeline() reconstruction against a
    full 4096-event ring with a loaded capture set — since /debug/requests
    runs on the serving event loop."""
    import jax

    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest
    from dynamo_tpu.utils.events import CAPACITY, EventJournal

    from tests.test_engine import tiny_engine_config  # CPU-smoke config

    on_cpu = jax.devices()[0].platform == "cpu"
    osl = 32
    if on_cpu:
        eng = AsyncJaxEngine(tiny_engine_config(decode_steps=4, pipeline_depth=2))
        prompt = list(range(1, 33))
    else:
        eng = AsyncJaxEngine(bench_config(8, 64))
        prompt = np.random.default_rng(7).integers(1, 31000, 256).tolist()

    # ---- decode step wall: bs=1 so tokens map 1:1 to model steps; measure
    # first-token..last-token (decode only, prefill excluded). Also count the
    # journal events the run ACTUALLY emitted for the measured request — the
    # journal's hot-path contract is a handful of emits per request, not per
    # token, so the per-step overhead is (emits/request) amortized over the
    # request's decode steps.
    from dynamo_tpu.utils import events as events_mod

    async def one(rid):
        req = EngineRequest(
            request_id=rid, token_ids=list(prompt),
            sampling=SamplingParams(temperature=0.0, max_tokens=osl,
                                    ignore_eos=True),
        )
        stamps = []
        async for out in eng.generate(req):
            if out.token is not None:
                stamps.append(time.perf_counter())
        return stamps

    try:
        await eng.start()
        await one("warm")  # executables out of the measurement
        stamps = await one("measured")
    finally:
        await eng.shutdown()
    assert len(stamps) == osl
    step_wall_s = (stamps[-1] - stamps[0]) / (osl - 1)
    emits_per_request = len(events_mod.JOURNAL.events_for("measured"))
    assert emits_per_request >= 3  # enqueued/admitted/first_token/finished

    # ---- emit cost: a dedicated journal (same code path as the global one),
    # realistic payload, mean over enough rounds to dominate timer noise
    j = EventJournal()
    n_emit = 20000
    t0 = time.perf_counter()
    for i in range(n_emit):
        j.emit("sched.admitted", request_id="bench-r%d" % (i % 64),
               tenant="bench", priority="standard", slot=i % 8, tokens=256)
    emit_s = (time.perf_counter() - t0) / n_emit

    # ---- forensic reconstruction: full ring + loaded capture set, read the
    # way /debug/requests/{id} does (pinned chain wins over ring scan)
    full = EventJournal()
    n_req = 256
    for i in range(CAPACITY):
        full.emit("request.first_token", request_id="r%d" % (i % n_req))
    for i in range(32):
        full.pin("r%d" % i, "ttft_over_budget")
    reads = 200
    t0 = time.perf_counter()
    for i in range(reads):
        tl = full.timeline("r%d" % (i % n_req))
        assert tl["found"]
    reconstruct_ms = (time.perf_counter() - t0) / reads * 1e3

    # the request's whole journal cost amortized over its decode steps, as a
    # fraction of one measured step: the honest per-step price at the REAL
    # emit rate (the planes emit on lifecycle decisions, not per token)
    overhead_frac = (emit_s * emits_per_request / osl) / step_wall_s
    out = {
        "cpu_smoke": on_cpu,
        "decode_step_wall_ms": round(step_wall_s * 1e3, 4),
        "emit_us": round(emit_s * 1e6, 3),
        "emits_per_request": emits_per_request,
        "emit_overhead_frac": round(overhead_frac, 6),
        "journal_events": CAPACITY,
        "reconstruct_ms": round(reconstruct_ms, 4),
    }
    # acceptance: the journal costs <1% of decode step wall at the measured
    # emit rate — even against the CPU-smoke toy model's sub-ms steps
    assert overhead_frac < 0.01, out
    # the forensic read must be interactive-debugging cheap (it runs on the
    # serving loop); 50 ms is generous even for CPU-smoke machines
    assert reconstruct_ms < 50.0, out
    return out


async def run_metering() -> dict:
    """Cost-attribution plane (observability tentpole): drive a real engine
    with two tagged tenants and check BOTH conservation identities on the
    live ledger — attributed device-seconds vs the step-anatomy wall totals,
    and per-tier summed KV byte-seconds vs the occupancy integrals. Then
    price the hot-path writes (one on_phase split, one KV edge pair) against
    the MEASURED decode step wall and assert the metering plane costs <1%
    of a step, same contract as the flight recorder."""
    import jax

    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest
    from dynamo_tpu.utils.metering import MeterLedger
    from dynamo_tpu.utils.step_anatomy import StepRecord

    from tests.test_engine import tiny_engine_config  # CPU-smoke config

    on_cpu = jax.devices()[0].platform == "cpu"
    osl = 32
    if on_cpu:
        eng = AsyncJaxEngine(tiny_engine_config(decode_steps=4, pipeline_depth=2))
        prompt = list(range(1, 33))
    else:
        eng = AsyncJaxEngine(bench_config(8, 64))
        prompt = np.random.default_rng(11).integers(1, 31000, 256).tolist()

    async def one(rid, tenant):
        req = EngineRequest(
            request_id=rid, token_ids=list(prompt), tenant=tenant,
            sampling=SamplingParams(temperature=0.0, max_tokens=osl,
                                    ignore_eos=True),
        )
        stamps = []
        async for out in eng.generate(req):
            if out.token is not None:
                stamps.append(time.perf_counter())
        return stamps

    try:
        await eng.start()
        await one("warm", "bench-a")  # executables out of the measurement
        stamps = await one("measured", "bench-a")
        # a concurrent two-tenant pair so the split path (multi-row bills,
        # shared decode windows) is what conservation is checked against
        await asyncio.gather(one("m2", "bench-a"), one("m3", "bench-b"))
        cons = eng.meter.conservation(anatomy=eng.scheduler.anatomy)
        snap = eng.meter.snapshot()
        anat = eng.scheduler.anatomy
        with anat._lock:
            d_steps = anat.steps_total.get("decode_window", 0)
            d_calls = anat.dispatch_counts.get("decode_window", 0)
        steps_per_dispatch = max(1.0, d_steps / max(1, d_calls))
    finally:
        await eng.shutdown()
    assert len(stamps) == osl
    step_wall_s = (stamps[-1] - stamps[0]) / (osl - 1)

    # ---- hot-path price: a dedicated ledger (same code path), a billed
    # two-row record, mean over enough rounds to dominate timer noise
    led = MeterLedger()
    rec = StepRecord(seq=1, ts=0.0, kind="decode_window", bill=[
        ("bench-r1", "bench-a", "", "standard", 3.0),
        ("bench-r2", "bench-b", "", "standard", 1.0),
    ])
    n = 20000
    # best-of-3 with a warmup pass: the first repeat absorbs dict sizing
    # and bytecode-cache first-touch; min strips scheduler noise so the
    # price reflects the steady state the contract is about
    on_phase_s = math.inf
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            led.on_phase(rec, "device_wait", 1e-4)
        on_phase_s = min(on_phase_s, (time.perf_counter() - t0) / n)
    kv_acq_s = math.inf
    kv_rel_s = math.inf
    for r in range(3):
        t0 = time.perf_counter()
        for i in range(n):
            led.kv_acquire("hbm", (r, i), 4096, ("bench-a", "bench-r1"))
        kv_acq_s = min(kv_acq_s, (time.perf_counter() - t0) / n)
        t0 = time.perf_counter()
        for i in range(n):
            led.kv_release("hbm", (r, i))
        kv_rel_s = min(kv_rel_s, (time.perf_counter() - t0) / n)

    # per MODEL STEP: 4 phase splits per decode dispatch amortized over the
    # dispatch's steps, plus ~1/page_size acquire edges per sequence-step (a
    # fresh page every page_size generated tokens). The matching releases
    # land in the end-of-life free batch, not inside a decode step, so the
    # steady-state step pays only the acquire half (release price reported)
    page_size = eng.config.page_size
    per_step_s = (4.0 * on_phase_s) / steps_per_dispatch + kv_acq_s / page_size
    overhead_frac = per_step_s / step_wall_s
    out = {
        "cpu_smoke": on_cpu,
        "decode_step_wall_ms": round(step_wall_s * 1e3, 4),
        "on_phase_us": round(on_phase_s * 1e6, 3),
        "kv_acquire_us": round(kv_acq_s * 1e6, 3),
        "kv_release_us": round(kv_rel_s * 1e6, 3),
        "overhead_frac": round(overhead_frac, 6),
        "device_rel_err": cons["device"]["rel_err"],
        "kv_rel_err": {t: cons["kv"][t]["rel_err"] for t in cons["kv"]},
        "device_s_total": snap["device_s_total"],
        "tenants_metered": sorted(t for t in snap["tenants"] if t),
    }
    # acceptance: both identities hold on the LIVE ledger (by-construction
    # exact; tolerance covers float summation order), and the metering
    # plane prices under 1% of a measured decode step
    assert cons["device"]["rel_err"] < 1e-6, out
    for tier, side in cons["kv"].items():
        assert side["rel_err"] < 1e-6, (tier, out)
    assert {"bench-a", "bench-b"} <= set(snap["tenants"]), out
    assert overhead_frac < 0.01, out
    return out


async def run_router_scale() -> dict:
    """Router radix index under internet-scale distinct-prefix churn: the
    bounded/sharded index (PR 17) vs the unbounded baseline.

    Pure-CPU, pure-index — no engine. Both arms store a HOT working set
    (depth-4 prefix chains) and then churn distinct single-block prefixes
    through the index, re-touching the hot set as they go; the bounded arm
    churns >1M distinct prefixes against a 75k-node cap, the unbounded arm a
    smaller volume (an unbounded 1M-node Python tree is ~0.5 GB — the
    monotonic-growth checkpoints prove the leak without paying for it).
    Acceptance, asserted here: resident nodes hold the cap under churn while
    the unbounded baseline only grows; the hot-set hit ratio stays within 5%
    of unbounded; hot-lookup p99 stays flat (the per-shard dict walk does
    not price the resident count)."""
    import random

    from dynamo_tpu.llm.kv_events import KvCacheEvent, StoredBlock
    from dynamo_tpu.llm.kv_router.indexer import KvIndexer, RouterEvent

    CAP = 75_000
    SHARDS = 4
    HOT = 2_000          # hot prefix lines, each a depth-4 chain
    HOT_DEPTH = 4
    BOUNDED_CHURN = 1_050_000
    UNBOUNDED_CHURN = 200_000
    PROBES = 10_000
    rng = random.Random(20817)

    def hot_seq(j: int) -> list:
        return [(1 << 40) + j * HOT_DEPTH + d for d in range(HOT_DEPTH)]

    async def arm(churn: int, **kw) -> dict:
        idx = KvIndexer(kv_block_size=16, use_native=False, **kw)
        for j in range(HOT):
            seq = hot_seq(j)
            idx.apply_event(RouterEvent(1, KvCacheEvent.stored(
                None, [StoredBlock((1 << 50) + h, h) for h in seq])))
        checkpoints = []
        for i in range(churn):
            idx.apply_event(RouterEvent(1, KvCacheEvent.stored(
                None, [StoredBlock((1 << 51) + i, i)])))
            if i % 8 == 0:
                # keep the hot working set recently-hit, the way real
                # traffic does — LRU only protects what gets walked
                idx.find_matches(hot_seq((i // 8) % HOT))
            if i % 50_000 == 0:
                checkpoints.append(idx.radix_stats()["nodes"])
                await asyncio.sleep(0)  # keep the section cancellable
        # hot-set hit ratio: matched blocks over expected across every line
        matched = sum(
            idx.find_matches(hot_seq(j)).scores.get(1, 0) for j in range(HOT)
        )
        hot_ratio = matched / float(HOT * HOT_DEPTH)
        # lookup latency over a hit/miss mix (misses = absent prefixes)
        times_ns = []
        for k in range(PROBES):
            seq = hot_seq(rng.randrange(HOT)) if k % 2 == 0 else [(1 << 45) + k]
            t0 = time.perf_counter_ns()
            idx.find_matches(seq)
            times_ns.append(time.perf_counter_ns() - t0)
        times_ns.sort()
        s = idx.radix_stats()
        return {
            "churn": churn,
            "resident_nodes": s["nodes"],
            "resident_bytes": s["bytes"],
            "cap_nodes": s["max_nodes"],
            "shards": s["shards"],
            "evictions": s["evictions_total"],
            "hot_hit_ratio": round(hot_ratio, 4),
            "lookup_p50_ms": round(times_ns[len(times_ns) // 2] / 1e6, 5),
            "lookup_p99_ms": round(times_ns[(len(times_ns) * 99) // 100] / 1e6, 5),
            "node_checkpoints": checkpoints,
        }

    unbounded = await arm(UNBOUNDED_CHURN)
    bounded = await arm(BOUNDED_CHURN, max_nodes=CAP, num_shards=SHARDS)
    # the unbounded baseline only ever grows (the pre-PR-17 behavior this
    # section exists to price): every churn checkpoint is >= the last
    cps = unbounded["node_checkpoints"]
    assert all(b >= a for a, b in zip(cps, cps[1:])), cps
    # the bounded index holds its cap under >1M distinct-prefix churn
    assert bounded["resident_nodes"] <= CAP, bounded
    assert bounded["evictions"] > 0, bounded
    # hot-working-set hit ratio within 5% of unbounded (LRU keeps what the
    # traffic actually walks)
    assert bounded["hot_hit_ratio"] >= unbounded["hot_hit_ratio"] - 0.05, (
        bounded, unbounded)
    # lookup p99 must not price the resident count (generous bound: shared
    # CPU-smoke timers are noisy at single-digit microseconds)
    assert bounded["lookup_p99_ms"] <= unbounded["lookup_p99_ms"] * 3.0 + 0.2, (
        bounded, unbounded)
    return {
        "bounded": bounded,
        "unbounded": unbounded,
        # the gated headline keys (bench_compare router_scale.*)
        "resident_nodes": bounded["resident_nodes"],
        "hot_hit_ratio": bounded["hot_hit_ratio"],
        "lookup_p50_ms": bounded["lookup_p50_ms"],
        "lookup_p99_ms": bounded["lookup_p99_ms"],
    }


#: filled section-by-section so a crash in section N never erases sections
#: 1..N-1 — __main__ prints whatever landed here even on a fatal error
DETAIL: dict = {}
ERRORS: dict = {}


async def _section(name: str, thunk, timeout_s: float) -> None:
    """Run one bench section with its own timeout and error isolation.

    A section that times out is cancelled; every section's engines shut down
    in finally blocks, so the next section starts clean. The failure lands in
    ERRORS[name] and the bench carries on — a crash in one section must never
    zero the whole artifact (r3 post-mortem: one aiohttp timeout discarded 10
    minutes of measured results)."""
    import gc
    import os
    import sys
    import traceback

    wanted = {
        s.strip()
        for s in os.environ.get("DYNTPU_BENCH_SECTIONS", "").split(",")
        if s.strip()
    }
    if wanted and name not in wanted:
        print(f"[bench] section {name} skipped (DYNTPU_BENCH_SECTIONS)",
              file=sys.stderr, flush=True)
        return

    t0 = time.monotonic()
    try:
        DETAIL[name] = await asyncio.wait_for(thunk(), timeout_s)
        print(f"[bench] section {name} ok in {time.monotonic()-t0:.0f}s",
              file=sys.stderr, flush=True)
    except Exception as e:
        tb = traceback.format_exc(limit=8)
        ERRORS[name] = {
            "error": f"{type(e).__name__}: {e}",
            "elapsed_s": round(time.monotonic() - t0, 1),
            "traceback_tail": tb[-1500:],
        }
        print(f"[bench] section {name} FAILED after {time.monotonic()-t0:.0f}s: "
              f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
    finally:
        gc.collect()


async def run() -> dict:
    import os

    import jax

    # the artifact must say what it measured on (CPU smoke numbers are
    # labeled; the driver's TPU run carries the priced numbers)
    DETAIL["platform"] = jax.devices()[0].platform
    _probe_pallas(HEADLINE[1])
    await _section("headline_bs%d_ps%d" % HEADLINE,
                   lambda: run_config(*HEADLINE), 1500)
    await _section("continuity_bs%d_ps%d" % CONTINUITY,
                   lambda: run_config(*CONTINUITY), 900)
    DETAIL.update({
        "prompt_len": PROMPT_LEN,
        "decode_tokens": DECODE_TOKENS,
        "devices": 1,
        "r01_value_bs8": R01_VALUE_BS8,
    })
    if os.environ.get("DYNTPU_BENCH_PARITY", "1") != "0":
        # the reference's tracked workload shape (BASELINE.md: 3K ISL /
        # 150 OSL serving configs)
        await _section("ref_workload_isl3k_osl150", lambda: run_config(
            16, 128, rounds=2, prompt_len=3072, decode_tokens=150,
            max_model_len=4096,
        ), 1500)
        await _section("http_serving", run_http_serving, 2400)
        # on-chip decode numbers for the non-Llama families (the vLLM patch
        # exists substantially for DeepSeek MLA — SURVEY.md §2.4)

        async def mla():
            return {
                **await run_config(32, 128, rounds=3, model_id=mla_model_id()),
                "roofline_note": (
                    "~1.3B dense-MLP MLA geometry (kv_lora 512/rope 64): "
                    "weights ~2.6 GB bf16 -> ~315 weight-bound steps/s "
                    "(3.15 ms/step floor); latent cache is 1.25 KB/token vs "
                    "4 KB for the GQA headline (the MLA win). r5 measured "
                    "decomposition (RTT-cancelled window chains, bs32 "
                    "ctx192): window 5.5 ms/step (5.8k tok/s capability), "
                    "model-only 4.85 — the 1.7 ms over the weight floor is "
                    "the absorbed-attention einsums + latent kernel, and the "
                    "section wall adds prefill amortization on top"
                ),
            }

        async def moe():
            return {
                **await run_config(32, 128, rounds=3, model_id=moe_model_id()),
                "roofline_note": (
                    "~2.3B Mixtral-geometry top-2/8: at bs32 nearly every "
                    "expert is active each step -> full ~2.3 GB read -> ~355 "
                    "steps/s weight-bound ceiling"
                ),
            }

        await _section("mla_decode", mla, 1500)
        await _section("moe_decode", moe, 1500)
        # speculative decoding vs classic decode on a repetition-heavy
        # workload: speedup + exact greedy parity + acceptance counters
        await _section("spec_ngram", run_spec_ngram, 1800)
        # draft-model speculation vs n-gram vs classic on a NON-repetitive
        # workload (exact greedy parity draft==target; acceptance must beat
        # n-gram's where prompt-lookup collapses)
        await _section("spec_draft", run_spec_draft, 1800)
        # multi-LoRA multiplexing: M fine-tunes in one mixed batch through
        # the gathered adapter kernels vs the base engine at the same shape,
        # with exact mixed-vs-alone parity and the LRU eviction arm (the
        # round-10 tentpole)
        await _section("multi_lora", run_multi_lora, 1800)
        # weight-only int8 vs bf16 on the headline config: throughput ratio +
        # greedy/logit parity (the round-6 tentpole)
        await _section("parity_quant_int8", run_quant_int8_parity, 2400)
        # int8 KV cache vs bf16 KV on the prefill-bound ref-workload shape:
        # TTFT/tok_s, ~2x page capacity at equal HBM, greedy parity (the
        # round-7 tentpole; composes with the int8 weights above)
        await _section("prefill_kv_int8", run_prefill_kv_int8, 2400)
        await _section("parity_disagg", run_disagg_parity, 2400)
        # streamed vs monolithic KV transfer on the socket path: TTFT on
        # multi-chunk prompts, token parity, compute/transfer overlap
        await _section("disagg_stream", run_disagg_stream, 1800)
        await _section("parity_kv_routing", run_routing_parity, 1500)
        # fleet-wide prefix cache: cross-worker KV pull vs recompute on a
        # shared-system-prompt workload (exact parity + TTFT ratio)
        await _section("fleet_prefix", run_fleet_prefix, 1800)
        # live migration: migrated-vs-killed mid-decode interrupts (exact
        # parity, client-visible pause p99, tokens salvaged, goodput delta)
        await _section("migration", run_migration, 1800)
        # multi-tenant QoS: tenant-A burst vs tenant-B steady through one
        # engine, QoS on/off — B's ITL-p99 must hold its budget under the
        # burst (priority scheduling + token-budget shed), off arm violates
        await _section("qos", run_qos, 1800)
        # long-context serving: 16K/64K TTFT + tok/s + KV high-watermark
        # through the page-table ladder, exact parity vs the dense path,
        # short-prompt no-regression ratio (CPU smoke scales down 16x)
        await _section("long_context", run_long_context, 2400)
        await _section("parity_host_offload", run_offload_parity, 1200)
        # third KV tier: disk-backed cold-session resume — parked sessions
        # demote host -> disk, resume restores through FETCHING_KV; TTFT
        # vs the recompute arm + exact greedy parity + byte cap under churn
        await _section("kv_tiers", run_kv_tiers, 1800)
    # trace-replay spine (ROADMAP item 2): seeded scenarios re-price the
    # post-r05 subsystems in goodput/TTFT-p99/ITL-p99 terms per scenario
    await _section("replay", run_replay, 2400)
    # step-anatomy plane (r7 tentpole): host-overhead + roofline fractions
    # from the standing per-dispatch attribution, across decode/spec/LoRA
    await _section("step_anatomy", run_step_anatomy, 1500)
    # prefill anatomy (r19 tentpole): depth-1 vs dispatch-ahead packed
    # prefill on the ref-shaped burst — exact greedy parity + strictly
    # fewer forced stalls asserted; fixed-cost + roofline from the plane
    await _section("prefill_anatomy", run_prefill_anatomy, 1500)
    # flight recorder: emit cost vs the measured decode step wall (<1%
    # asserted) + forensic timeline-reconstruction latency
    await _section("events", run_events, 900)
    # cost attribution: both conservation identities on a live two-tenant
    # engine ledger + the metering hot-path priced against the measured
    # decode step wall (<1% asserted inside)
    await _section("metering", run_metering, 900)
    # router index under >1M distinct-prefix churn: bounded/sharded vs
    # unbounded (pure CPU; resident cap + hot-hit ratio asserted inside)
    await _section("router_scale", run_router_scale, 900)
    return _result()


#: summary-line aliases for the replay scenarios (tail-budget compression);
#: bench_detail.json keeps the full names
_REPLAY_ALIASES = {
    "bursty_chat": "bursty",
    "int8_kv": "int8",
    "long_context_sessions": "lctx",
    "lora_churn": "lora",
    "spec_draft": "spec",
    "fleet_prefix": "fleet",
    "mm_vl": "mm",
}


def _get(d: dict | None, *path, default=None):
    cur = d
    for p in path:
        if not isinstance(cur, dict) or p not in cur:
            return default
        cur = cur[p]
    return cur


def _summary(errors: dict) -> dict:
    """The compact (<1.5 KB) per-section key numbers for the round artifact.

    The driver records only the TAIL of stdout, so the LAST printed line must
    be self-contained: headline, continuity, ref workload, http ratio, mla/moe,
    and all three parity ratios — measured AND derived, labeled — plus a
    compact errors map (r4 post-mortem: the full-detail line was truncated and
    the artifact lost its own headline)."""
    head = DETAIL.get("headline_bs%d_ps%d" % HEADLINE)
    cont = DETAIL.get("continuity_bs%d_ps%d" % CONTINUITY)
    refw = DETAIL.get("ref_workload_isl3k_osl150")
    http = DETAIL.get("http_serving")
    mla = DETAIL.get("mla_decode")
    moe = DETAIL.get("moe_decode")
    dis = DETAIL.get("parity_disagg")
    dstream = DETAIL.get("disagg_stream")
    rout = DETAIL.get("parity_kv_routing")
    fleet = DETAIL.get("fleet_prefix")
    mig = DETAIL.get("migration")
    qos = DETAIL.get("qos")
    lctx = DETAIL.get("long_context")
    off = DETAIL.get("parity_host_offload")
    ktier = DETAIL.get("kv_tiers")
    quant = DETAIL.get("parity_quant_int8")
    kvq = DETAIL.get("prefill_kv_int8")
    spec = DETAIL.get("spec_ngram")
    sdraft = DETAIL.get("spec_draft")
    mlora = DETAIL.get("multi_lora")
    replay = DETAIL.get("replay")
    sanat = DETAIL.get("step_anatomy")
    panat = DETAIL.get("prefill_anatomy")
    evts = DETAIL.get("events")
    mtr = DETAIL.get("metering")
    rscale = DETAIL.get("router_scale")
    # per-scenario acceptance keys (replay.{scenario}.{goodput,ttft_p99_ms,
    # itl_p99_ms,tok_s}); wall/lag/stage detail rides bench_detail.json
    replay_summary = None
    if replay:
        # compact aliased-array form against the driver's hard 2000-char
        # stdout-tail cap (BENCH_r02..r05 all recorded exactly 2000):
        # replay_cols names the columns, _REPLAY_ALIASES maps the keys; the
        # full named-key reports (replay.{scenario}.{goodput,ttft_p99_ms,
        # itl_p99_ms,tok_s} + wall/lag/stage breakdowns) ride
        # bench_detail.json under their full scenario names
        def ims(v):  # integer ms: sub-ms precision is noise at p99
            return round(v) if isinstance(v, float) else v

        replay_summary = {
            _REPLAY_ALIASES.get(sc, sc): [
                _get(rep, "goodput"),
                ims(_get(rep, "ttft_p99_ms")),
                ims(_get(rep, "itl_p99_ms")),
                ims(_get(rep, "tok_s")),
            ]
            for sc, rep in sorted(replay.get("scenarios", {}).items())
        }
    return {
        "platform": DETAIL.get("platform"),
        "headline_tok_s": _get(head, "tok_s"),
        # r01_value_bs8 (the fixed continuity anchor) moved to
        # bench_detail.json — it is a code constant, not a measurement, and
        # the summary line's truncation budget needs the bytes
        "continuity_bs8_tok_s": _get(cont, "tok_s"),
        "ref_workload_isl3k_osl150": {
            "tok_s": _get(refw, "tok_s"), "ttft_p50_ms": _get(refw, "ttft_p50_ms"),
            # stages (the per-stage engine seconds kept here to chase the
            # flat-TTFT attribution) moved to bench_detail.json: r19's
            # prefill_anatomy keys below ARE that attribution now (the fixed
            # cost was per-dispatch, and the pipelined arm's TTFT is gated),
            # and the summary-line truncation budget needed the bytes
        },
        "http_serving": {
            # ttft_p50_ms and tok_s moved to bench_detail.json (summary-line
            # truncation budget — tok_s went with the kv_tiers keys; the
            # gated ratio carries the signal)
            "http_over_engine_ratio": _get(http, "http_over_engine_ratio"),
        },
        "mla_decode_tok_s": _get(mla, "tok_s"),
        "moe_decode_tok_s": _get(moe, "tok_s"),
        "parity_quant_int8": {
            # tok_s_int8/tok_s_bf16, teacher_forced_agreement_64,
            # max_abs_logit_delta + agree_or_near_tie_64 all moved to
            # bench_detail.json (summary-line truncation budget; the section
            # asserts agreement itself and the gated speedup carries the
            # signal)
            "speedup": _get(quant, "speedup_int8_over_bf16"),
        },
        "prefill_kv_int8": {
            # kv_cache_dtype + both raw tok/s legs ride bench_detail.json
            # (summary-line truncation budget; the ratios + agreement gate
            # carry the signal)
            # teacher_forced_agreement also rides bench_detail.json
            # (truncation budget; the section asserts it itself)
            "ttft_ratio": _get(kvq, "ttft_ratio_int8_over_bf16"),
            "page_capacity_ratio": _get(kvq, "page_capacity_equal_hbm", "ratio"),
        },
        "spec_ngram": {
            # tok_s_spec/tok_s_base live in bench_detail.json (the speedup
            # ratio carries them; summary-line truncation budget)
            "speedup": _get(spec, "speedup_spec_over_base"),
            "acceptance_rate": _get(spec, "acceptance_rate"),
            # raw proposed/accepted counters + greedy_parity live in
            # bench_detail.json (summary-line truncation budget; the section
            # asserts parity itself and the rate carries the signal)
        },
        # draft-model speculation on NON-repetitive text: acceptance is the
        # headline signal (the draft proposes where n-gram can't; a
        # same-size CPU-smoke draft can't win wall clock by construction).
        # tok_s legs, speedups, raw counters, and the draft-pool gauges all
        # ride bench_detail.json under spec_draft.
        "spec_draft": {
            "accept_draft": _get(sdraft, "acceptance_rate_draft"),
            # accept_ngram (the control arm) and greedy_parity moved to
            # bench_detail.json (truncation budget; the section asserts
            # parity itself and the draft acceptance is the gated signal)
        },
        # M=4 adapters mixed-batch vs base at the same shape: the throughput
        # ratio + exact mixed-vs-alone parity + LRU churn proof (raw tok/s
        # legs and load/residency gauges ride bench_detail.json)
        "multi_lora": {
            "mixed_tok_s_ratio": _get(mlora, "mixed_tok_s_ratio"),
            # parity_mixed_vs_alone + resident_evictions moved to
            # bench_detail.json (truncation budget; both are asserted
            # inside the section and the gated ratio carries the signal)
        },
        "parity_disagg": {
            "ratio_measured_1chip": _get(dis, "ratio_measured_1chip"),
            "ratio_projected": _get(dis, "ratio_projected"),
        },
        "disagg_stream": {
            # streamed/monolithic raw TTFTs + token_parity live in
            # bench_detail.json (the section asserts parity itself — a break
            # fails the section; the ratio + overlap carry the signal)
            "ttft_ratio": _get(dstream, "ttft_ratio_streamed_over_monolithic"),
            "overlap_fraction": _get(dstream, "overlap_fraction"),
        },
        "parity_kv_routing": {
            # ratio_derived moved to bench_detail.json (truncation budget;
            # the measured in-situ ratio is the meaningful one)
            "ratio_measured": _get(rout, "ttft_insitu_ratio_measured"),
        },
        "fleet_prefix": {
            "ttft_ratio_bf16": _get(fleet, "bf16", "ttft_ratio_hit_over_recompute"),
            # ttft_ratio_int8 + wire_bytes_ratio_int8 moved to
            # bench_detail.json (summary-line truncation budget needed the
            # bytes for the migration keys; the bf16 ratio is the gated one)
        },
        # live migration: exact-parity flag, client-visible pause p99, and
        # the migrated-minus-killed goodput delta (salvage counters, kill
        # pause, and the budget ride bench_detail.json)
        "migration": {
            "parity": _get(mig, "parity"),
            "pause_ms_p99": _get(mig, "pause_ms_p99"),
            "goodput_delta": _get(mig, "goodput_delta"),
        },
        # multi-tenant QoS isolation: B's ITL-p99 on/off ratio under the A
        # burst, the fraction of A's burst the token budget shed, and
        # critical-class goodput under burst (per-tenant breakdowns, budget
        # values, and the engine enforcement audit ride bench_detail.json)
        "qos": {
            "tenant_b_itl_ratio": _get(qos, "tenant_b_itl_ratio"),
            "shed_fraction": _get(qos, "shed_fraction"),
            "critical_goodput": _get(qos, "critical_goodput"),
        },
        # 16K/64K TTFT + KV high-watermark (acceptance keys; tok/s and the
        # dispatch histograms ride bench_detail.json)
        "long_context": {
            "ttft_ms_64k": _get(lctx, "64k", "ttft_ms"),
            # ttft_ms_16k, kv_peak_64k, tok_s_64k and parity_64k moved to
            # bench_detail.json (truncation budget; the section asserts
            # parity itself and the gated 64k TTFT carries the signal)
            "short_ratio": _get(lctx, "short_ttft_ratio_ladder_over_dense"),
        },
        # restore_bw_source moved to bench_detail.json (truncation budget)
        "parity_host_offload": {
            "ratio_projected": _get(off, "projection", "ttft_ratio_projected"),
        },
        # third KV tier, cold-session resume: disk-restore TTFT over the
        # recompute arm (lower is better), exact greedy parity, and the
        # disk-resident footprint after churn (raw TTFT legs, restore
        # counters, and the cap-under-churn proof ride bench_detail.json)
        "kv_tiers": {
            "resume_ttft_ratio": _get(ktier, "resume_ttft_ratio"),
            "restore_parity": _get(ktier, "restore_parity"),
            "disk_resident_bytes": _get(ktier, "disk", "bytes_resident"),
        },
        # step anatomy (decode arm): host-overhead fraction of engine time,
        # HBM-floor fraction of measured decode seconds, and the decode
        # window dispatch cadence — the item-3 fused-decode before/after
        # numbers (per-arm spec/LoRA breakdowns ride bench_detail.json)
        # dispatch_gap_ms_p50 moved to bench_detail.json (truncation
        # budget; the gated host_frac/roofline_frac carry the signal)
        "step_anatomy": {
            "host_frac": _get(sanat, "decode", "host_frac"),
            "roofline_frac": _get(sanat, "decode", "roofline_frac"),
        },
        # prefill anatomy (pipelined arm): measured per-call fixed cost from
        # the standing plane, dispatch count, and TTFT p50 — the r19
        # dispatch-cost before/after keys. Parity + stall deltas are
        # asserted inside the section; per-arm detail rides bench_detail.json
        "prefill_anatomy": {
            "fixed_ms": _get(panat, "depth2", "prefill_fixed_ms"),
            "dispatches": _get(panat, "depth2", "prefill_calls"),
            "ttft_p50_ms": _get(panat, "depth2", "ttft_p50_ms"),
        },
        # flight recorder: the journal's per-step cost fraction at the
        # measured emit rate (the section asserts <1% itself) and the
        # forensic timeline-reconstruction latency against a full ring.
        # Short keys for the truncation budget — the full-named report
        # (emit_us, decode_step_wall_ms, emits_per_request,
        # emit_overhead_frac, reconstruct_ms) rides bench_detail.json
        "events": {
            "emit_frac": _get(evts, "emit_overhead_frac"),
            "rec_ms": _get(evts, "reconstruct_ms"),
        },
        # cost attribution: the WORST conservation residual across both
        # planes (device vs anatomy, per-tier byte-seconds — each asserted
        # <1e-6 inside the section) + the metering hot-path's per-step
        # price fraction (asserted <1% inside). Short keys for the
        # truncation budget — per-plane residuals, on_phase/kv-edge
        # prices, and the per-tenant rollup ride bench_detail.json
        "metering": {
            "err": max(
                (v for v in [
                    _get(mtr, "device_rel_err"),
                    *(_get(mtr, "kv_rel_err") or {}).values(),
                ] if v is not None),
                default=None,
            ),
            "frac": _get(mtr, "overhead_frac"),
        },
        # router index under >1M distinct-prefix churn (bounded arm): the
        # gated resident-cap / hot-hit / lookup-latency keys (per-arm
        # detail incl. the unbounded baseline rides bench_detail.json)
        "router_scale": {
            "lookup_p99_ms": _get(rscale, "lookup_p99_ms"),
            "resident_nodes": _get(rscale, "resident_nodes"),
            "hot_hit_ratio": _get(rscale, "hot_hit_ratio"),
        },
        # the trace-replay spine: goodput under per-scenario SLO budgets,
        # columns per replay_cols (budgets + cpu_smoke flag + full named
        # reports in bench_detail.json)
        "replay_cols": "goodput,ttft_p99_ms,itl_p99_ms,tok_s"
        if replay_summary else None,
        "replay": replay_summary,
        # 120-char cap per error: a raw XLA error repr is routinely thousands
        # of chars and would re-trigger the very tail truncation this summary
        # exists to survive (full text lands in bench_detail.json)
        "errors": {k: v.get("error", "?")[:120] for k, v in errors.items()} or None,
    }


def _result(extra_errors: dict | None = None) -> dict:
    """Assemble the compact one-line artifact from whatever sections landed.

    Full per-section detail goes to bench_detail.json next to this script;
    stdout carries only `value` + the compact summary so the driver's tail
    truncation can never eat the round's own numbers."""
    import os

    head = DETAIL.get("headline_bs%d_ps%d" % HEADLINE)
    value = head["tok_s"] if head else 0.0
    errors = {**ERRORS, **(extra_errors or {})}
    detail_path = os.environ.get("DYNTPU_BENCH_DETAIL") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_detail.json")
    try:
        # temp + rename: a mid-write failure must not leave a truncated file
        # where post-mortem tooling expects the previous run's detail
        tmp = detail_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"detail": DETAIL, "errors": errors}, f, indent=1, default=str)
        os.replace(tmp, detail_path)
    except (OSError, TypeError, ValueError):
        # a non-serializable value in DETAIL must not destroy the artifact
        # line itself — the summary carries plain floats and serializes fine
        detail_path = None
    out = {
        "metric": "engine_decode_throughput_llama1.3b_bf16",
        "value": value,
        "unit": "out_tok/s/chip",
        "vs_baseline": round(value / PARITY_TARGET_TOK_S, 3),
        "summary": _summary(errors),
        "detail_file": detail_path,
    }
    return out


if __name__ == "__main__":
    import os
    import sys

    # persistent XLA compilation cache (verified working through the axon
    # remote compiler): the bench starts 10+ engine instances with identical
    # geometries — without this every instance re-pays ~25 s per executable
    # over the tunnel; with it, instance N>1 deserializes from disk
    from dynamo_tpu.utils.xla_cache import enable_compilation_cache

    enable_compilation_cache()

    try:
        result = asyncio.run(run())
    except BaseException as e:  # even a fatal crash must emit the sections that finished
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            label = "interrupted"
        else:
            label = f"{type(e).__name__}: {e}"
        result = _result(extra_errors={"__run__": {"error": label}})
        print(json.dumps(result, separators=(",", ":")))
        sys.exit(0 if result["value"] else 1)
    # compact separators: the driver keeps only the last 2000 chars of
    # stdout, and the default ", " formatting alone costs ~200 chars on a
    # full summary line
    print(json.dumps(result, separators=(",", ":")))
