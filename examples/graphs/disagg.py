"""Disaggregated serving graph: Frontend -> Processor -> DecodeWorker, with
PrefillWorkers consuming the remote-prefill queue.

The analogue of the reference's disagg graph (reference: examples/llm/graphs/
disagg.py). Launch:

    python -m dynamo_tpu.sdk.serve examples.graphs.disagg:Frontend -f examples/configs/disagg.yaml
"""

from __future__ import annotations

from dynamo_tpu.sdk import async_on_start, depends, service
from dynamo_tpu.frontends.pipeline import card_for_model
from dynamo_tpu.launch._run_impl import engine_config_for
from examples.graphs.agg import Frontend as AggFrontend, Processor as AggProcessor, _Args


@service(namespace="dynamo", component="backend", resources={"tpu": 1})
class DecodeWorker:
    """Decode-side engine with conditional remote prefill."""

    @async_on_start
    async def boot(self):
        from dynamo_tpu.components.worker import WorkerService

        cfg = self.config
        model = cfg.get("model", "tiny")
        card = card_for_model(model, cfg.get("max_model_len"))
        engine_cfg = engine_config_for(_Args({"model": model, **cfg}))
        self.worker = WorkerService(
            self.runtime, "dynamo", "backend", card, engine_cfg,
            enable_disagg_decode=True, register=False,
        )
        await self.worker.start()

    async def on_shutdown(self):
        await self.worker.stop()


@service(namespace="dynamo", component="prefill", resources={"tpu": 1})
class PrefillWorker:
    """Prefill-side engine consuming the remote-prefill work queue."""

    @async_on_start
    async def boot(self):
        from dynamo_tpu.disagg.prefill_worker import PrefillWorker as PW
        from dynamo_tpu.engine.engine import AsyncJaxEngine

        cfg = self.config
        model = cfg.get("model", "tiny")
        engine_cfg = engine_config_for(_Args({"model": model, **cfg}))
        # prefill-only role: background warmup would compile decode-window
        # variants this engine never dispatches, stalling its prefill work;
        # its prefill traces compile lazily on the first few requests
        import dataclasses

        engine_cfg = dataclasses.replace(engine_cfg, warmup=False)
        self.engine = AsyncJaxEngine(engine_cfg)
        await self.engine.start()
        card = card_for_model(model, cfg.get("max_model_len"))
        self.pw = PW(self.engine, self.runtime, "dynamo", card.display_name)
        await self.pw.start()

    async def on_shutdown(self):
        await self.pw.stop()
        await self.engine.shutdown()


@service(namespace="dynamo", component="processor")
class Processor(AggProcessor):
    worker = depends(DecodeWorker)


@service(namespace="dynamo", component="frontend")
class Frontend(AggFrontend):
    processor = depends(Processor)
    prefill = depends(PrefillWorker)
