"""Aggregated serving graph: Frontend -> Processor -> TpuWorker.

The analogue of the reference's agg graph (reference: examples/llm/graphs/
agg.py + examples/llm/components/). Launch:

    python -m dynamo_tpu.sdk.serve examples.graphs.agg:Frontend -f examples/configs/agg.yaml
"""

from __future__ import annotations

from dynamo_tpu.sdk import async_on_start, depends, service
from dynamo_tpu.frontends.pipeline import card_for_model
from dynamo_tpu.launch._run_impl import engine_config_for


class _Args:
    def __init__(self, d):
        self.__dict__.update(d)

    def __getattr__(self, k):
        return None


@service(namespace="dynamo", component="backend", resources={"tpu": 1})
class TpuWorker:
    """JAX engine worker (tokens in -> detokenized stream out)."""

    @async_on_start
    async def boot(self):
        from dynamo_tpu.components.worker import WorkerService

        cfg = self.config
        model = cfg.get("model", "tiny")
        card = card_for_model(model, cfg.get("max_model_len"))
        engine_cfg = engine_config_for(_Args({"model": model, **cfg}))
        self.worker = WorkerService(
            self.runtime, "dynamo", "backend", card, engine_cfg, register=False
        )
        await self.worker.start()

    async def on_shutdown(self):
        await self.worker.stop()


@service(namespace="dynamo", component="processor")
class Processor:
    """KV-aware routing tier."""

    worker = depends(TpuWorker)

    @async_on_start
    async def boot(self):
        from dynamo_tpu.components.processor import ProcessorService

        cfg = self.config
        self.processor = ProcessorService(
            self.runtime,
            "dynamo",
            worker_component="backend",
            kv_block_size=cfg.get("kv_block_size", 4),
            routing=cfg.get("routing", "kv"),
        )
        await self.processor.start()

    async def on_shutdown(self):
        await self.processor.stop()


@service(namespace="dynamo", component="frontend")
class Frontend:
    """OpenAI HTTP frontend with model discovery."""

    processor = depends(Processor)

    @async_on_start
    async def boot(self):
        from dynamo_tpu.components.frontend import FrontendService
        from dynamo_tpu.llm.model_registry import ModelEntry, register_model

        cfg = self.config
        model = cfg.get("model", "tiny")
        card = card_for_model(model, cfg.get("max_model_len"))
        card.display_name = cfg.get("served_model_name", card.display_name)
        entry = ModelEntry(
            name=card.display_name,
            endpoint="dyn://dynamo.processor.generate",
            model_type="chat",
            card=card,
        )
        await register_model(self.runtime.cplane, entry)
        self.frontend = FrontendService(
            self.runtime, host=cfg.get("host", "0.0.0.0"), port=cfg.get("port", 8080)
        )
        port = await self.frontend.start()
        print(f"frontend listening on :{port}", flush=True)

    async def on_shutdown(self):
        await self.frontend.stop()
