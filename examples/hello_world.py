"""Three-stage SDK pipeline: Frontend -> Middle -> Backend.

The analogue of the reference hello_world example (reference: examples/
hello_world/hello_world.py:20-80) — demonstrates @service/@endpoint/depends
streaming composition without any model.

    python -m dynamo_tpu.sdk.serve examples.hello_world:Frontend
    curl localhost:8099/generate?text=world
"""

from __future__ import annotations


from aiohttp import web

from dynamo_tpu.sdk import async_on_start, depends, endpoint, service


@service(namespace="hello", component="backend")
class Backend:
    @endpoint
    async def generate(self, text: str):
        for word in f"hello {text}!".split():
            yield word


@service(namespace="hello", component="middle")
class Middle:
    backend = depends(Backend)

    @endpoint
    async def generate(self, text: str):
        stream = await self.backend.stream(text.upper())
        async for word in stream:
            yield f"[{word}]"


@service(namespace="hello", component="frontend")
class Frontend:
    middle = depends(Middle)

    @async_on_start
    async def boot(self):
        app = web.Application()
        app.router.add_get("/generate", self._handle)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", int(self.config.get("port", 8099)))
        await site.start()
        self._runner = runner
        print("hello_world frontend on :8099", flush=True)

    async def _handle(self, request: web.Request) -> web.Response:
        text = request.query.get("text", "world")
        stream = await self.middle.stream(text)
        words = [w async for w in stream]
        return web.json_response({"result": " ".join(words)})

    async def on_shutdown(self):
        await self._runner.cleanup()
