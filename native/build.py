"""Build the native shared library (g++) with on-disk caching.

Called lazily on first import of the native bindings; rebuilds when sources
change (mtime)."""

from __future__ import annotations

import subprocess
from pathlib import Path

NATIVE_DIR = Path(__file__).parent
SRC = NATIVE_DIR / "src"

LIBS = {
    "libdynamo_tpu_native.so": [SRC / "radix_tree.cc"],
    # engine-embeddable C ABI for KV event publication (llm_capi.cc docstring)
    "libdynamo_tpu_llm.so": [SRC / "llm_capi.cc"],
}


def _build_one(out: Path, sources: list[Path], force: bool) -> Path:
    if not force and out.exists():
        newest_src = max(s.stat().st_mtime for s in sources)
        if out.stat().st_mtime >= newest_src:
            return out
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        *[str(s) for s in sources],
        "-o", str(out),
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    return out


def build(force: bool = False) -> Path:
    """Build all native libs; returns the radix-tree library path (primary)."""
    outs = [
        _build_one(NATIVE_DIR / name, sources, force) for name, sources in LIBS.items()
    ]
    return outs[0]


def build_llm_capi(force: bool = False) -> Path:
    return _build_one(NATIVE_DIR / "libdynamo_tpu_llm.so", LIBS["libdynamo_tpu_llm.so"], force)


if __name__ == "__main__":
    for name, sources in LIBS.items():
        print(_build_one(NATIVE_DIR / name, sources, force=True))
