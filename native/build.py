"""Build the native shared library (g++) with on-disk caching.

Called lazily on first import of the native bindings; rebuilds when sources
change (mtime)."""

from __future__ import annotations

import subprocess
from pathlib import Path

NATIVE_DIR = Path(__file__).parent
SRC = NATIVE_DIR / "src"
OUT = NATIVE_DIR / "libdynamo_tpu_native.so"

SOURCES = [SRC / "radix_tree.cc"]


def build(force: bool = False) -> Path:
    if not force and OUT.exists():
        newest_src = max(s.stat().st_mtime for s in SOURCES)
        if OUT.stat().st_mtime >= newest_src:
            return OUT
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        *[str(s) for s in SOURCES],
        "-o", str(OUT),
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    return OUT


if __name__ == "__main__":
    print(build(force=True))
