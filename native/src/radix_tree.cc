// Native radix/prefix tree for KV-aware routing — the hot lookup path of the
// router (C++ analogue of the reference's Rust indexer,
// reference: lib/llm/src/kv_router/indexer.rs:187-560).
//
// Exposed as a C ABI consumed from Python via ctypes
// (dynamo_tpu/llm/kv_router/native_indexer.py). All hashes are precomputed
// u64s (xxh3, computed by the caller); the tree itself is hash-keyed:
//   - children keyed by tokens_hash (unchained local chunk hash)
//   - per-worker lookup table block_hash -> node for O(1) event attachment
//
// Single-threaded by contract: the owning Python side calls from one event
// loop (concurrency-by-isolation, same as the reference's dedicated runtime).

#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Node {
  std::unordered_map<uint64_t, Node*> children;  // tokens_hash -> child
  std::unordered_set<int64_t> workers;
};

struct Tree {
  Node root;
  // worker -> block_hash -> node
  std::unordered_map<int64_t, std::unordered_map<uint64_t, Node*>> lookup;
  std::deque<Node> arena;  // stable addresses; nodes are never freed until reset

  Node* alloc() {
    arena.emplace_back();
    return &arena.back();
  }
};

}  // namespace

extern "C" {

void* rtree_new() { return new Tree(); }

void rtree_free(void* h) { delete static_cast<Tree*>(h); }

// Stored event: attach a chain of blocks for `worker` under `parent`
// (parent_hash valid iff has_parent != 0; otherwise the root).
void rtree_apply_stored(void* h, int64_t worker, uint64_t parent_hash,
                        int has_parent, int64_t n, const uint64_t* block_hashes,
                        const uint64_t* tokens_hashes) {
  Tree* t = static_cast<Tree*>(h);
  auto& wl = t->lookup[worker];
  Node* parent = &t->root;
  if (has_parent) {
    auto it = wl.find(parent_hash);
    if (it != wl.end()) parent = it->second;
  }
  for (int64_t i = 0; i < n; i++) {
    Node*& child = parent->children[tokens_hashes[i]];
    if (child == nullptr) child = t->alloc();
    child->workers.insert(worker);
    wl[block_hashes[i]] = child;
    parent = child;
  }
}

void rtree_apply_removed(void* h, int64_t worker, int64_t n,
                         const uint64_t* block_hashes) {
  Tree* t = static_cast<Tree*>(h);
  auto wit = t->lookup.find(worker);
  if (wit == t->lookup.end()) return;
  auto& wl = wit->second;
  for (int64_t i = 0; i < n; i++) {
    auto it = wl.find(block_hashes[i]);
    if (it != wl.end()) {
      it->second->workers.erase(worker);
      wl.erase(it);
    }
  }
}

void rtree_remove_worker(void* h, int64_t worker) {
  Tree* t = static_cast<Tree*>(h);
  auto wit = t->lookup.find(worker);
  if (wit == t->lookup.end()) return;
  for (auto& [bh, node] : wit->second) node->workers.erase(worker);
  t->lookup.erase(wit);
}

// Walk the tree along tokens_hashes accumulating per-worker matched-block
// counts. Writes up to max_out (worker, score) pairs; returns the count, or
// -1 if max_out was too small.
int64_t rtree_find_matches(void* h, int64_t n, const uint64_t* tokens_hashes,
                           int early_exit, int64_t* out_workers,
                           int64_t* out_scores, int64_t max_out) {
  Tree* t = static_cast<Tree*>(h);
  std::unordered_map<int64_t, int64_t> scores;
  Node* current = &t->root;
  for (int64_t i = 0; i < n; i++) {
    auto it = current->children.find(tokens_hashes[i]);
    if (it == current->children.end()) break;
    Node* node = it->second;
    for (int64_t w : node->workers) scores[w] += 1;
    if (early_exit && node->workers.size() == 1) break;
    current = node;
  }
  if (static_cast<int64_t>(scores.size()) > max_out) return -1;
  int64_t k = 0;
  for (auto& [w, s] : scores) {
    out_workers[k] = w;
    out_scores[k] = s;
    k++;
  }
  return k;
}

void rtree_stats(void* h, int64_t* out_nodes, int64_t* out_workers) {
  Tree* t = static_cast<Tree*>(h);
  *out_nodes = static_cast<int64_t>(t->arena.size());
  *out_workers = static_cast<int64_t>(t->lookup.size());
}

}  // extern "C"
