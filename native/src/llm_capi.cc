// C ABI for engine-embedded KV event publication — the analogue of the
// reference's libdynamo_llm C FFI (reference: lib/bindings/c/src/lib.rs:52-318:
// dynamo_llm_init / dynamo_kv_event_publish_stored / _removed / _shutdown).
//
// A foreign engine process (any language) loads this library, calls init with
// the control-plane address + its worker identity, and publishes KV cache
// events straight onto the `{ns}|{comp}.kv_events` subject that KV routers
// subscribe to. Self-contained: speaks the broker's wire protocol (4-byte BE
// length prefix + msgpack) with a built-in minimal msgpack encoder/decoder —
// no external dependencies.
//
// Block identities are the caller-computed u64 hashes (chained block_hash +
// unchained tokens_hash, xxh3 seed 1337 — see dynamo_tpu/llm/tokens.py).

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

// ---------------- minimal msgpack writer ----------------

struct Packer {
  std::vector<uint8_t> buf;

  void u8(uint8_t b) { buf.push_back(b); }
  void raw(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf.insert(buf.end(), b, b + n);
  }
  void be16(uint16_t v) { uint16_t x = htons(v); raw(&x, 2); }
  void be32(uint32_t v) { uint32_t x = htonl(v); raw(&x, 4); }
  void be64(uint64_t v) {
    for (int i = 7; i >= 0; i--) u8(static_cast<uint8_t>(v >> (8 * i)));
  }

  void pack_nil() { u8(0xc0); }
  void pack_uint(uint64_t v) {
    if (v < 0x80) u8(static_cast<uint8_t>(v));
    else if (v <= 0xff) { u8(0xcc); u8(static_cast<uint8_t>(v)); }
    else if (v <= 0xffff) { u8(0xcd); be16(static_cast<uint16_t>(v)); }
    else if (v <= 0xffffffffULL) { u8(0xce); be32(static_cast<uint32_t>(v)); }
    else { u8(0xcf); be64(v); }
  }
  void pack_int(int64_t v) {
    if (v >= 0) { pack_uint(static_cast<uint64_t>(v)); return; }
    if (v >= -32) { u8(static_cast<uint8_t>(v)); return; }
    u8(0xd3); be64(static_cast<uint64_t>(v));
  }
  void pack_str(const std::string& s) {
    size_t n = s.size();
    if (n < 32) u8(0xa0 | static_cast<uint8_t>(n));
    else if (n <= 0xff) { u8(0xd9); u8(static_cast<uint8_t>(n)); }
    else { u8(0xda); be16(static_cast<uint16_t>(n)); }
    raw(s.data(), n);
  }
  void pack_map(uint32_t n) {
    if (n < 16) u8(0x80 | static_cast<uint8_t>(n));
    else { u8(0xde); be16(static_cast<uint16_t>(n)); }
  }
  void pack_array(uint32_t n) {
    if (n < 16) u8(0x90 | static_cast<uint8_t>(n));
    else { u8(0xdc); be16(static_cast<uint16_t>(n)); }
  }
};

// ---------------- minimal msgpack skipper (for replies) ----------------
// We only need to consume reply frames; a full decoder is unnecessary.

// ---------------- client state ----------------

struct Client {
  int fd = -1;
  std::string subject;
  int64_t worker_id = 0;
  uint64_t next_rid = 1;
  std::mutex mu;
};

Client* g_client = nullptr;
std::mutex g_init_mu;

int send_frame(Client* c, const Packer& p) {
  uint32_t len = htonl(static_cast<uint32_t>(p.buf.size()));
  uint8_t header[4];
  std::memcpy(header, &len, 4);
  if (::send(c->fd, header, 4, MSG_NOSIGNAL) != 4) return -1;
  size_t off = 0;
  while (off < p.buf.size()) {
    ssize_t n = ::send(c->fd, p.buf.data() + off, p.buf.size() - off, MSG_NOSIGNAL);
    if (n <= 0) return -1;
    off += static_cast<size_t>(n);
  }
  return 0;
}

int read_exact(int fd, uint8_t* out, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t r = ::recv(fd, out + off, n - off, 0);
    if (r <= 0) return -1;
    off += static_cast<size_t>(r);
  }
  return 0;
}

// Consume one reply frame (we send strictly sequentially, so the next frame
// is our ack; watch events are not subscribed on this connection).
int consume_reply(Client* c) {
  uint8_t header[4];
  if (read_exact(c->fd, header, 4) != 0) return -1;
  uint32_t len;
  std::memcpy(&len, header, 4);
  len = ntohl(len);
  if (len > (64u << 20)) return -1;
  std::vector<uint8_t> payload(len);
  return read_exact(c->fd, payload.data(), len);
}

int request(Client* c, const Packer& p) {
  std::lock_guard<std::mutex> lock(c->mu);
  if (send_frame(c, p) != 0) return -1;
  return consume_reply(c);
}

void pack_event_header(Packer& p, Client* c, const char* extra_key_count_note) {
  (void)extra_key_count_note;
  p.pack_map(5);
  p.pack_str("op"); p.pack_str("publish");
  p.pack_str("rid"); p.pack_uint(c->next_rid++);
  p.pack_str("subject"); p.pack_str(c->subject);
  p.pack_str("reply"); p.pack_nil();
  p.pack_str("payload");
}

}  // namespace

extern "C" {

// Returns 0 on success. cplane_addr: "host:port".
int dynamo_tpu_llm_init(const char* cplane_addr, const char* ns,
                        const char* component, int64_t worker_id,
                        uint32_t kv_block_size) {
  (void)kv_block_size;
  std::lock_guard<std::mutex> lock(g_init_mu);
  if (g_client != nullptr) return 0;

  std::string addr(cplane_addr);
  auto colon = addr.rfind(':');
  if (colon == std::string::npos) return -1;
  std::string host = addr.substr(0, colon);
  std::string port = addr.substr(colon + 1);

  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0) return -2;
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0 || ::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    freeaddrinfo(res);
    if (fd >= 0) ::close(fd);
    return -3;
  }
  freeaddrinfo(res);

  Client* c = new Client();
  c->fd = fd;
  c->worker_id = worker_id;
  c->subject = std::string(ns) + "|" + component + ".kv_events";
  g_client = c;
  return 0;
}

int dynamo_tpu_llm_kv_event_publish_stored(uint64_t event_id,
                                           uint64_t parent_hash, int has_parent,
                                           int64_t num_blocks,
                                           const uint64_t* block_hashes,
                                           const uint64_t* tokens_hashes) {
  Client* c = g_client;
  if (c == nullptr) return -1;
  Packer p;
  pack_event_header(p, c, nullptr);
  // payload = RouterEvent wire format (dynamo_tpu/llm/kv_router/indexer.py)
  p.pack_map(2);
  p.pack_str("worker_id"); p.pack_int(c->worker_id);
  p.pack_str("event");
  p.pack_map(2);
  p.pack_str("event_id"); p.pack_uint(event_id);
  p.pack_str("stored");
  p.pack_map(2);
  p.pack_str("parent_hash");
  if (has_parent) p.pack_uint(parent_hash); else p.pack_nil();
  p.pack_str("blocks");
  p.pack_array(static_cast<uint32_t>(num_blocks));
  for (int64_t i = 0; i < num_blocks; i++) {
    p.pack_map(2);
    p.pack_str("block_hash"); p.pack_uint(block_hashes[i]);
    p.pack_str("tokens_hash"); p.pack_uint(tokens_hashes[i]);
  }
  return request(c, p);
}

int dynamo_tpu_llm_kv_event_publish_removed(uint64_t event_id,
                                            const uint64_t* block_hashes,
                                            int64_t num_blocks) {
  Client* c = g_client;
  if (c == nullptr) return -1;
  Packer p;
  pack_event_header(p, c, nullptr);
  p.pack_map(2);
  p.pack_str("worker_id"); p.pack_int(c->worker_id);
  p.pack_str("event");
  p.pack_map(2);
  p.pack_str("event_id"); p.pack_uint(event_id);
  p.pack_str("removed");
  p.pack_map(1);
  p.pack_str("block_hashes");
  p.pack_array(static_cast<uint32_t>(num_blocks));
  for (int64_t i = 0; i < num_blocks; i++) p.pack_uint(block_hashes[i]);
  return request(c, p);
}

int dynamo_tpu_llm_shutdown() {
  std::lock_guard<std::mutex> lock(g_init_mu);
  if (g_client != nullptr) {
    ::close(g_client->fd);
    delete g_client;
    g_client = nullptr;
  }
  return 0;
}

}  // extern "C"
