"""DeepSeek MLA correctness: paged absorbed-attention prefill/decode vs a naive
dense transformer that materializes per-head K/V from the latents (the
standard, non-absorbed formulation). Token-exactness through the engine proves
the weight-folding math and the latent page pool.

Also checks the headline property: the latent cache is an order of magnitude
smaller per token than an equivalent full-KV cache.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.deepseek import DeepseekConfig, DeepseekModel
from dynamo_tpu.ops.moe import moe_block
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.rotary import apply_rope


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow

PAGE_SIZE = 4
NUM_PAGES = 16
PROMPT = np.array([5, 9, 2, 77, 31, 8, 100], dtype=np.int32)
PAGE_TABLE = np.array([3, 5, 7, 0, 0, 0, 0, 0], dtype=np.int32)


@pytest.fixture(scope="module")
def setup():
    cfg = DeepseekConfig.tiny_mla()
    model = DeepseekModel(cfg)
    params = model.init_params(jax.random.key(1))
    return cfg, model, params


def naive_forward(cfg, params, tokens):
    """Dense MLA with explicit K/V expansion: k_h = [W_kb_h c ; k_rope],
    v_h = W_vb_h c, then standard multi-head causal attention."""
    T = len(tokens)
    pos = jnp.arange(T)
    h = params["embed"][jnp.array(tokens)].astype(cfg.dtype)
    dn, dr, dv, dc = (
        cfg.qk_nope_head_dim,
        cfg.qk_rope_head_dim,
        cfg.v_head_dim,
        cfg.kv_lora_rank,
    )
    H = cfg.num_heads

    def layer(h, lp, moe):
        x = rms_norm(h, lp["input_norm"], cfg.rms_norm_eps)
        q = (x @ lp["w_q"]).reshape(T, H, dn + dr)
        q_nope, q_rope = q[..., :dn], q[..., dn:]
        q_rope = apply_rope(q_rope, pos, cfg.rope_theta)

        ckv = x @ lp["w_dkv"]
        latent = rms_norm(ckv[:, :dc], lp["kv_norm"], cfg.rms_norm_eps)
        k_rope = apply_rope(ckv[:, None, dc:], pos, cfg.rope_theta)[:, 0]

        # materialize per-head K/V from the latent (non-absorbed)
        k_nope = jnp.einsum("sc,chn->shn", latent, lp["w_kb"])  # [S, H, dn]
        v = jnp.einsum("sc,chv->shv", latent, lp["w_vb"])  # [S, H, dv]
        k = jnp.concatenate(
            [k_nope, jnp.broadcast_to(k_rope[:, None, :], (T, H, dr))], axis=-1
        )
        qf = jnp.concatenate([q_nope, q_rope], axis=-1)  # [T, H, dn+dr]

        s = jnp.einsum("thd,shd->hts", qf.astype(jnp.float32), k.astype(jnp.float32))
        s = s / np.sqrt(dn + dr)
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None], s, -1e30)
        a = jnp.einsum(
            "hts,shv->thv", jax.nn.softmax(s, -1), v.astype(jnp.float32)
        ).astype(cfg.dtype)
        h = h + a.reshape(T, -1) @ lp["wo"]

        x = rms_norm(h, lp["post_norm"], cfg.rms_norm_eps)
        if moe:
            shared = (
                jax.nn.silu(x @ lp["shared_gate"]) * (x @ lp["shared_up"])
            ) @ lp["shared_down"]
            routed = moe_block(
                x,
                lp["router"],
                lp["w_gate"],
                lp["w_up"],
                lp["w_down"],
                num_experts_per_tok=cfg.num_experts_per_tok,
                capacity_factor=cfg.moe_capacity_factor,
                renormalize=cfg.norm_topk_prob,
            )
            h = h + shared + cfg.routed_scaling_factor * routed
        else:
            h = h + (jax.nn.silu(x @ lp["gate"]) * (x @ lp["up"])) @ lp["down"]
        return h

    Ld = cfg.first_k_dense_replace
    for l in range(Ld):
        h = layer(h, jax.tree.map(lambda x: x[l], params["dense_layers"]), False)
    for l in range(cfg.num_layers - Ld):
        h = layer(h, jax.tree.map(lambda x: x[l], params["moe_layers"]), True)
    x = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    return jnp.einsum("td,vd->tv", x.astype(jnp.float32), params["lm_head"].astype(jnp.float32))


def test_prefill_matches_naive(setup):
    cfg, model, params = setup
    ref = naive_forward(cfg, params, PROMPT)[-1]
    Tn, T_pad = len(PROMPT), 8
    tokens = np.zeros(T_pad, np.int32)
    tokens[:Tn] = PROMPT
    positions = np.arange(T_pad, dtype=np.int32)
    kv = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits, _ = model.prefill(
        params, kv, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref), atol=2e-4)


def test_prefill_then_decode_matches_full_prefill(setup):
    cfg, model, params = setup
    Tn, T_pad = len(PROMPT), 8
    tokens = np.zeros(T_pad, np.int32)
    tokens[:Tn] = PROMPT
    positions = np.arange(T_pad, dtype=np.int32)

    kv1 = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits_a, kv1 = model.prefill(
        params, kv1, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )

    kv2 = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits_b, kv2 = model.prefill(
        params, kv2, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < 3), jnp.array(2),
    )
    pts = np.zeros((2, 8), np.int32)
    pts[0] = PAGE_TABLE
    for i in range(3, Tn):
        logits_dec, kv2 = model.decode(
            params, kv2,
            jnp.array([PROMPT[i], 0], jnp.int32),
            jnp.array([i, 0], jnp.int32),
            jnp.array(pts),
            jnp.array([True, False]),
        )
        logits_b = logits_dec[0]
    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b), atol=2e-4)

    owned = np.asarray(PAGE_TABLE[:2])
    flat = (owned[None, :] + np.arange(cfg.num_layers)[:, None] * NUM_PAGES).ravel()
    np.testing.assert_allclose(
        np.asarray(kv1["ckv"][flat]), np.asarray(kv2["ckv"][flat]), atol=2e-4
    )


def test_engine_serves_mla_model():
    """Full engine stack (paged allocator, pipelined decode windows, prefix
    cache) over the MLA model."""
    import asyncio

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    async def body():
        eng = AsyncJaxEngine(
            EngineConfig(
                model_id="tiny-mla",
                page_size=4,
                num_pages=32,
                max_seqs=2,
                max_model_len=64,
                prefill_buckets=(16,),
            )
        )
        await eng.start()
        req = EngineRequest(
            request_id="mla1",
            token_ids=list(PROMPT),
            sampling=SamplingParams(temperature=0.0, max_tokens=8),
        )
        toks = []
        async for out in eng.generate(req):
            if out.token is not None:
                toks.append(out.token)
        # greedy continuation must match teacher-forced naive logits argmax
        cfg = DeepseekConfig.tiny_mla()
        model = DeepseekModel(cfg)
        params = model.init_params(jax.random.key(0))
        seq = list(PROMPT)
        want = []
        for _ in range(8):
            lg = naive_forward(cfg, params, np.asarray(seq, np.int32))[-1]
            nxt = int(jnp.argmax(lg))
            want.append(nxt)
            seq.append(nxt)
        await eng.shutdown()
        return toks, want

    toks, want = asyncio.run(body())
    assert toks == want, f"engine {toks} != naive {want}"


def test_latent_cache_is_small(setup):
    """The MLA pool is ~an order of magnitude smaller than an equivalent
    full-KV cache with the same head geometry."""
    cfg, model, _ = setup
    latent_row = cfg.latent_dim  # per token
    full_row = 2 * cfg.num_heads * (cfg.qk_nope_head_dim + cfg.qk_rope_head_dim)
    assert latent_row * 3 < full_row


def test_tp_sharded_prefill_matches(setup):
    """Same prefill under a tp=2 mesh (head-sharded up-projections, replicated
    latent cache) must produce identical logits."""
    from jax.sharding import Mesh

    cfg, model, params = setup
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("tp",))
    params_sh = jax.device_put(params, model.param_shardings(mesh))
    kv = jax.device_put(
        model.init_kv_cache(NUM_PAGES, PAGE_SIZE), model.kv_cache_sharding(mesh)
    )
    Tn, T_pad = len(PROMPT), 8
    tokens = np.zeros(T_pad, np.int32)
    tokens[:Tn] = PROMPT
    positions = np.arange(T_pad, dtype=np.int32)
    logits_sh, _ = jax.jit(model.prefill)(
        params_sh, kv, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )
    ref = naive_forward(cfg, params, PROMPT)[-1]
    np.testing.assert_allclose(np.asarray(logits_sh), np.asarray(ref), atol=2e-4)


def test_unsupported_hf_features_raise():
    base = {
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 48,
        "num_hidden_layers": 2, "num_attention_heads": 4,
    }
    with pytest.raises(ValueError, match="sigmoid"):
        DeepseekConfig.from_hf_config({**base, "scoring_func": "sigmoid"})
    with pytest.raises(ValueError, match="group-limited"):
        DeepseekConfig.from_hf_config({**base, "topk_method": "group_limited_greedy"})
    with pytest.raises(ValueError, match="rope_scaling"):
        DeepseekConfig.from_hf_config(
            {**base, "rope_scaling": {"type": "yarn", "factor": 40}}
        )


def test_unrenormalized_topk_routing():
    """renormalize=False (DeepSeek default) takes top-k probs from the full
    softmax; renormalize=True (Mixtral) softmaxes over the selected k."""
    from dynamo_tpu.ops.moe import topk_routing

    logits = jnp.array([[2.0, 1.0, 0.0, -1.0]])
    w_full, idx = topk_routing(logits, 2, renormalize=False)
    probs = np.asarray(jax.nn.softmax(logits[0]))
    np.testing.assert_allclose(np.asarray(w_full[0]), probs[[0, 1]], rtol=1e-6)
    assert np.asarray(w_full[0]).sum() < 1.0  # not renormalized
    w_renorm, _ = topk_routing(logits, 2, renormalize=True)
    np.testing.assert_allclose(np.asarray(w_renorm[0]).sum(), 1.0, rtol=1e-6)


def test_pallas_mla_kernel_matches_reference():
    """The Pallas latent-page kernel (interpret mode) vs the pure-JAX absorbed
    attention, across lengths straddling page boundaries."""
    from dynamo_tpu.ops.pallas.mla_attention import paged_mla_decode_attention_pallas

    rng = np.random.default_rng(5)
    B, H, dc, dr, ps, P, mp = 3, 4, 32, 8, 4, 16, 6
    latent = dc + dr
    q_cat = jnp.asarray(rng.standard_normal((B, H, latent)), jnp.float32)
    pages = jnp.asarray(rng.standard_normal((P, ps, latent)), jnp.float32)
    pt = np.zeros((B, mp), np.int32)
    for b in range(B):
        pt[b] = rng.choice(np.arange(1, P), size=mp, replace=False)
    positions = jnp.asarray([3, 9, 14], jnp.int32)

    got = paged_mla_decode_attention_pallas(
        q_cat, pages, jnp.asarray(pt), positions, d_c=dc, interpret=True
    )

    # reference: gather, dot over latent, causal mask, softmax, weighted latents
    for b in range(B):
        ctx = np.asarray(pages)[pt[b]].reshape(mp * ps, latent)
        scores = np.asarray(q_cat)[b] @ ctx.T  # [H, S]
        mask = np.arange(mp * ps) <= int(positions[b])
        scores = np.where(mask[None], scores, -1e30)
        probs = np.exp(scores - scores.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        want = probs @ ctx[:, :dc]  # [H, dc]
        np.testing.assert_allclose(np.asarray(got[b]), want, atol=2e-5)


def test_engine_mla_pallas_token_parity(monkeypatch):
    """tiny-mla engine with the kernel forced on (interpret on CPU) generates
    the same greedy tokens as the pure-XLA path."""
    import asyncio

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    def run():
        async def body():
            eng = AsyncJaxEngine(
                EngineConfig(
                    model_id="tiny-mla", page_size=4, num_pages=32, max_seqs=2,
                    max_model_len=64, prefill_buckets=(16,),
                )
            )
            await eng.start()
            toks = []
            async for out in eng.generate(
                EngineRequest(
                    request_id="pk",
                    token_ids=list(PROMPT),
                    sampling=SamplingParams(temperature=0.0, max_tokens=8),
                )
            ):
                if out.token is not None:
                    toks.append(out.token)
            await eng.shutdown()
            return toks

        return asyncio.run(body())

    monkeypatch.setenv("DYNTPU_PALLAS", "1")
    got = run()
    monkeypatch.setenv("DYNTPU_PALLAS", "0")
    ref = run()
    assert got == ref, f"pallas MLA {got} != xla {ref}"


def test_mla_pallas_tp2_shard_map(monkeypatch):
    """tp=2 MLA decode with the kernel forced on: runs under shard_map
    (head-sharded) and matches the unsharded XLA reference logits."""
    from jax.sharding import Mesh

    monkeypatch.setenv("DYNTPU_PALLAS", "1")
    cfg = DeepseekConfig.tiny_mla()
    model = DeepseekModel(cfg)
    params = model.init_params(jax.random.key(2))
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("tp",))
    model.attn_mesh = mesh
    params_sh = jax.device_put(params, model.param_shardings(mesh))
    kv = jax.device_put(
        model.init_kv_cache(NUM_PAGES, PAGE_SIZE), model.kv_cache_sharding(mesh)
    )
    # seed some context via prefill, then one decode step through the kernel
    Tn, T_pad = len(PROMPT), 8
    tokens = np.zeros(T_pad, np.int32)
    tokens[:Tn] = PROMPT
    positions = np.arange(T_pad, dtype=np.int32)
    _, kv = jax.jit(model.prefill)(
        params_sh, kv, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )
    pts = np.zeros((2, 8), np.int32)
    pts[0] = PAGE_TABLE
    logits_sh, _ = jax.jit(model.decode)(
        params_sh, kv,
        jnp.array([PROMPT[-1], 0], jnp.int32),
        jnp.array([Tn - 1, 0], jnp.int32),
        jnp.array(pts),
        jnp.array([True, False]),
    )

    monkeypatch.setenv("DYNTPU_PALLAS", "0")
    ref_model = DeepseekModel(cfg)
    kv_ref = ref_model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    _, kv_ref = ref_model.prefill(
        params, kv_ref, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )
    logits_ref, _ = ref_model.decode(
        params, kv_ref,
        jnp.array([PROMPT[-1], 0], jnp.int32),
        jnp.array([Tn - 1, 0], jnp.int32),
        jnp.array(pts),
        jnp.array([True, False]),
    )
    np.testing.assert_allclose(
        np.asarray(logits_sh[0]), np.asarray(logits_ref[0]), atol=2e-4
    )


def test_pallas_mla_prefill_kernel_matches_reference():
    """Chunked-prefill latent flash kernel (interpret) vs the absorbed XLA
    reference, incl. a cached-prefix chunk and 2 query blocks."""
    import numpy as np
    from dynamo_tpu.ops.pallas.mla_attention import paged_mla_prefill_attention_pallas

    rng = np.random.default_rng(0)
    H, dc, dr = 4, 32, 8
    latent = dc + dr
    latent_pad = 128  # lane-aligned physical row
    P, ps, max_pages = 64, 4, 48
    pages = np.zeros((P, ps, latent_pad), np.float32)
    pages[:, :, :latent] = rng.standard_normal((P, ps, latent))
    pt = rng.choice(np.arange(1, P), size=max_pages, replace=False).astype(np.int32)

    for T, start in [(128, 0), (128, 37), (256, 0)]:
        q_cat = np.zeros((T, H, latent_pad), np.float32)
        q_cat[:, :, :latent] = rng.standard_normal((T, H, latent))
        positions = (start + np.arange(T)).astype(np.int32)

        # dense reference in latent space
        ctx = pages[pt].reshape(max_pages * ps, latent_pad)
        scores = np.einsum("thc,sc->hts", q_cat, ctx)
        mask = np.arange(max_pages * ps)[None, :] <= positions[:, None]
        scores = np.where(mask[None], scores, -1e30)
        probs = np.exp(scores - scores.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        ref = np.einsum("hts,sc->thc", probs, ctx[:, :dc])

        got = paged_mla_prefill_attention_pallas(
            jnp.asarray(q_cat), jnp.asarray(pages), jnp.asarray(pt),
            jnp.asarray(positions), d_c=dc, interpret=True,
        )
        np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-5, atol=2e-5)


def test_engine_mla_prefill_pallas_token_parity(monkeypatch):
    """Engine greedy tokens with the MLA kernels forced on (prefill chunk 128,
    interpret on CPU) == kernels off."""
    import asyncio
    import numpy as np
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    def cfg():
        return EngineConfig(
            model_id="tiny-mla",
            page_size=4,
            num_pages=128,
            max_seqs=2,
            max_model_len=256,
            prefill_buckets=(128,),
        )

    prompt = np.random.default_rng(3).integers(1, 250, 70).tolist()

    def run():
        async def body():
            eng = AsyncJaxEngine(cfg())
            await eng.start()
            req = EngineRequest(
                request_id="mlapf",
                token_ids=list(prompt),
                sampling=SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True),
            )
            toks = []
            async for out in eng.generate(req):
                if out.token is not None:
                    toks.append(out.token)
            await eng.shutdown()
            return toks

        return asyncio.run(body())

    monkeypatch.setenv("DYNTPU_PALLAS", "0")
    ref = run()
    monkeypatch.setenv("DYNTPU_PALLAS", "1")
    got = run()
    assert got == ref


def test_pallas_mla_lookahead_tail_path(monkeypatch):
    """Lengths deep past the prefetch window W (the tail double-buffer path
    long-context decodes hit in production) + ragged short sequences and odd
    B for parity alternation — vs the same numpy reference (review r5).
    Lookahead is opt-in for MLA (classic won the on-chip A/B), so force it
    here to keep the kernel covered."""
    from dynamo_tpu.ops.pallas.mla_attention import (
        _mla_lookahead_window,
        paged_mla_decode_attention_pallas,
    )

    monkeypatch.setenv("DYNTPU_DECODE_KERNEL", "lookahead")

    rng = np.random.default_rng(9)
    B, H, dc, dr, ps, P, mp = 5, 4, 32, 8, 4, 96, 14
    latent = dc + dr
    W = _mla_lookahead_window(ps, latent, 4)
    assert 1 <= W <= 4
    assert mp > W  # the tail path really engages
    q_cat = jnp.asarray(rng.standard_normal((B, H, latent)), jnp.float32)
    pages = jnp.asarray(rng.standard_normal((P, ps, latent)), jnp.float32)
    pt = np.zeros((B, mp), np.int32)
    pool = list(range(1, P))
    rng.shuffle(pool)
    for b in range(B):
        pt[b] = pool[b * mp:(b + 1) * mp]
    # 1 token; W pages exactly; W pages + 1 token; 14-page tail; 1 page
    positions = jnp.asarray(
        [0, W * ps - 1, W * ps, mp * ps - 2, ps - 1], jnp.int32
    )

    got = paged_mla_decode_attention_pallas(
        q_cat, pages, jnp.asarray(pt), positions, d_c=dc, interpret=True
    )
    for b in range(B):
        ctx = np.asarray(pages)[pt[b]].reshape(mp * ps, latent)
        scores = np.asarray(q_cat)[b] @ ctx.T
        mask = np.arange(mp * ps) <= int(positions[b])
        scores = np.where(mask[None], scores, -1e30)
        probs = np.exp(scores - scores.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        want = probs @ ctx[:, :dc]
        np.testing.assert_allclose(np.asarray(got[b]), want, atol=2e-5)
