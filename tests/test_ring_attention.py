"""Ring attention vs dense causal attention on the virtual device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.ops.ring_attention import ring_attention


def dense_causal(q, k, v):
    Hq = q.shape[1]
    g = Hq // k.shape[1]
    kr = jnp.repeat(k, g, axis=1)
    vr = jnp.repeat(v, g, axis=1)
    T = q.shape[0]
    s = jnp.einsum("thd,shd->hts", q.astype(jnp.float32), kr.astype(jnp.float32))
    s = s / np.sqrt(q.shape[-1])
    s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("hts,shd->thd", p, vr.astype(jnp.float32))
    return out.astype(q.dtype)


@pytest.mark.parametrize("sp,Hq,Hkv", [(4, 4, 4), (8, 4, 2), (2, 8, 8)])
def test_ring_matches_dense(sp, Hq, Hkv):
    devices = np.array(jax.devices()[:sp])
    mesh = Mesh(devices, ("sp",))
    T, D = 8 * sp, 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((T, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((T, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((T, Hkv, D)), jnp.float32)

    expected = dense_causal(q, k, v)

    sharding = NamedSharding(mesh, P("sp"))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    got = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_ring_attention_jit_compiles_once_per_shape():
    devices = np.array(jax.devices()[:4])
    mesh = Mesh(devices, ("sp",))
    T, H, D = 32, 4, 16
    sharding = NamedSharding(mesh, P("sp"))
    x = jax.device_put(jnp.ones((T, H, D), jnp.float32), sharding)
    fn = jax.jit(lambda a: ring_attention(a, a, a, mesh))
    out1 = fn(x)
    out2 = fn(x * 2)
    assert out1.shape == (T, H, D)
    assert out2.shape == (T, H, D)
