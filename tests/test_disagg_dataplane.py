"""KV data-plane failure paths + the v2 streamed wire protocol, over loopback
sockets only — no engines, no device code, so this file rides the fast tier.

Covers the ISSUE-4 satellite list: bad-nonce rejection, duplicate-payload
drop, abandon() followed by a late payload, client reconnect after a server
restart, multi-part reassembly (out-of-order lanes), and a missing tail part
timing out — plus the checksum-mismatch isolation fix and the deterministic
chunk->part plan the streamed prefill export uses."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.disagg.dataplane import (
    KvDataPlaneClient,
    KvDataPlaneServer,
    stream_part_plan,
)


def _arr(n: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.normal(size=(2, n, 4)).astype(np.float32)


async def _fleet(lanes: int = 1):
    server = await KvDataPlaneServer(host="127.0.0.1").start()
    client = KvDataPlaneClient(lanes=lanes)
    return server, client


def test_monolithic_roundtrip():
    async def body():
        server, client = await _fleet()
        try:
            token = server.expect("r1")
            payload = _arr(3)
            await client.send(server.address, "r1", payload, token=token)
            got = await server.receive("r1", timeout=5)
            np.testing.assert_array_equal(got, payload)
            assert server.received == 1
            assert server.parts_received == 1
            assert server.bytes_received == payload.nbytes
            assert client.sent == 1
            assert client.bytes_sent == payload.nbytes
        finally:
            await client.close()
            await server.stop()

    asyncio.run(body())


def test_bad_nonce_rejected_then_good_payload_lands():
    async def body():
        server, client = await _fleet()
        try:
            token = server.expect("r1")
            payload = _arr(2)
            await client.send(server.address, "r1", payload, token="forged")
            for _ in range(100):
                if server.rejected:
                    break
                await asyncio.sleep(0.01)
            # the rejected frame must count AND must not poison the transfer:
            # the legitimate sender's payload still lands afterwards
            assert server.rejected == 1
            assert server.received == 0
            await client.send(server.address, "r1", payload, token=token)
            got = await server.receive("r1", timeout=5)
            np.testing.assert_array_equal(got, payload)
            assert server.received == 1
        finally:
            await client.close()
            await server.stop()

    asyncio.run(body())


def test_duplicate_part_dropped():
    async def body():
        server, client = await _fleet()
        try:
            token = server.expect("r2")
            p0, p1 = _arr(2, seed=1), _arr(3, seed=2)
            await client.send_part(server.address, "r2", p0, token=token,
                                   part_seq=0, part_total=2, page_from=0,
                                   page_to=2, cat_axis=1)
            # duplicate of part 0 (a redelivered/retried frame)
            await client.send_part(server.address, "r2", p0, token=token,
                                   part_seq=0, part_total=2, page_from=0,
                                   page_to=2, cat_axis=1)
            await client.send_part(server.address, "r2", p1, token=token,
                                   part_seq=1, part_total=2, page_from=2,
                                   page_to=5, cat_axis=1)
            got = await server.receive("r2", timeout=5)
            np.testing.assert_array_equal(got, np.concatenate([p0, p1], axis=1))
            assert server.dropped == 1
            assert server.received == 1
            assert server.parts_received == 2  # the duplicate never counted
        finally:
            await client.close()
            await server.stop()

    asyncio.run(body())


def test_abandon_then_late_payload_dropped():
    async def body():
        server, client = await _fleet()
        try:
            token = server.expect("r3")
            server.abandon("r3")
            await client.send(server.address, "r3", _arr(2), token=token)
            for _ in range(50):
                if server.dropped:
                    break
                await asyncio.sleep(0.01)
            assert server.dropped == 1
            assert server.received == 0
        finally:
            await client.close()
            await server.stop()

    asyncio.run(body())


def test_client_reconnects_after_server_restart():
    async def body():
        server, client = await _fleet()
        port = server.port
        token = server.expect("warm")
        await client.send(server.address, "warm", _arr(1), token=token)
        await server.receive("warm", timeout=5)
        await server.stop()
        # same port, fresh server: the pooled socket is now stale
        server2 = await KvDataPlaneServer(host="127.0.0.1").start(port=port)
        try:
            await asyncio.sleep(0.2)  # let the FIN reach the pooled reader
            token2 = server2.expect("r4")
            payload = _arr(4)
            await client.send(server2.address, "r4", payload, token=token2)
            got = await server2.receive("r4", timeout=5)
            np.testing.assert_array_equal(got, payload)
        finally:
            await client.close()
            await server2.stop()

    asyncio.run(body())


def test_multipart_reassembly_out_of_order_lanes():
    async def body():
        server, client = await _fleet(lanes=3)
        try:
            token = server.expect("r5")
            parts = [_arr(2, seed=i) for i in range(3)]
            # arrival order scrambled across the 3 lanes: 2, 0, 1
            for seq in (2, 0, 1):
                await client.send_part(
                    server.address, "r5", parts[seq], token=token,
                    part_seq=seq, part_total=3,
                    page_from=2 * seq, page_to=2 * seq + 2, cat_axis=1,
                )
            got = await server.receive("r5", timeout=5)
            np.testing.assert_array_equal(got, np.concatenate(parts, axis=1))
            assert server.received == 1
            assert server.parts_received == 3
            # all three lanes actually opened
            assert len(client._conns) == 3
        finally:
            await client.close()
            await server.stop()

    asyncio.run(body())


def test_missing_tail_part_times_out():
    async def body():
        server, client = await _fleet()
        try:
            token = server.expect("r6")
            await client.send_part(server.address, "r6", _arr(2), token=token,
                                   part_seq=0, part_total=2, page_from=0,
                                   page_to=2, cat_axis=1)
            with pytest.raises(asyncio.TimeoutError):
                await server.receive("r6", timeout=0.3)
            assert server.parts_received == 1
            assert server.received == 0
        finally:
            await client.close()
            await server.stop()

    asyncio.run(body())


def test_checksum_mismatch_kills_one_transfer_not_the_connection():
    import msgpack
    import struct

    async def body():
        server = await KvDataPlaneServer(host="127.0.0.1").start()
        try:
            token_bad = server.expect("corrupt")
            token_good = server.expect("clean")
            reader, writer = await asyncio.open_connection("127.0.0.1", server.port)
            bad_payload = _arr(2)
            raw = np.ascontiguousarray(bad_payload).view(np.uint8).reshape(-1)
            header = msgpack.packb({
                "request_id": "corrupt", "shape": list(bad_payload.shape),
                "dtype": str(bad_payload.dtype), "xxh3": 12345,  # wrong
                "token": token_bad,
            })
            writer.write(struct.pack("<I", len(header)))
            writer.write(header)
            writer.write(raw.tobytes())
            await writer.drain()
            # the SAME connection must keep working for an unrelated transfer
            client = KvDataPlaneClient()
            good = _arr(3, seed=7)
            await client.send(server.address, "clean", good, token=token_good)
            got = await server.receive("clean", timeout=5)
            np.testing.assert_array_equal(got, good)
            assert server.checksum_failures == 1
            # the corrupt transfer failed fast instead of timing out
            with pytest.raises(RuntimeError, match="checksum"):
                await server.receive("corrupt", timeout=5)
            writer.close()
            await client.close()
        finally:
            await server.stop()

    asyncio.run(body())


def test_incremental_consumer_and_late_attach_flush():
    async def body():
        server, client = await _fleet(lanes=2)
        try:
            token = server.expect("r7")
            parts = [_arr(2, seed=i + 10) for i in range(3)]
            # part 0 arrives BEFORE the consumer attaches: it parks
            await client.send_part(server.address, "r7", parts[0], token=token,
                                   part_seq=0, part_total=3, page_from=0,
                                   page_to=2, cat_axis=1)
            for _ in range(100):
                if server.parts_received:
                    break
                await asyncio.sleep(0.01)
            seen = []
            server.set_consumer("r7", lambda part: seen.append(part))
            assert [p.seq for p in seen] == [0]  # parked part flushed
            for seq in (2, 1):
                await client.send_part(
                    server.address, "r7", parts[seq], token=token,
                    part_seq=seq, part_total=3,
                    page_from=2 * seq, page_to=2 * seq + 2, cat_axis=1,
                )
            # consumer mode: receive() is only the completion gate
            assert await server.receive("r7", timeout=5) is None
            assert sorted(p.seq for p in seen) == [0, 1, 2]
            for p in seen:
                np.testing.assert_array_equal(p.data, parts[p.seq])
            assert [(p.page_from, p.page_to) for p in sorted(seen, key=lambda p: p.seq)] == \
                [(0, 2), (2, 4), (4, 6)]
        finally:
            await client.close()
            await server.stop()

    asyncio.run(body())


def test_metrics_exposition_conformant():
    from dynamo_tpu.utils.prometheus import check_exposition

    async def body():
        server, client = await _fleet(lanes=2)
        try:
            token = server.expect("m1")
            await client.send(server.address, "m1", _arr(2), token=token)
            await server.receive("m1", timeout=5)
            text = server.render_metrics() + client.render_metrics()
            assert "dynamo_kv_stream_parts_received_total 1" in text
            assert "dynamo_kv_stream_lanes 2" in text
            check_exposition(text)
        finally:
            await client.close()
            await server.stop()

    asyncio.run(body())


def test_stream_part_plan_shapes():
    # no cache, 3 chunks of 8 over 20 tokens, page_size 4 -> parts at each
    # chunk boundary's full pages, tail part closes the ragged last page
    assert stream_part_plan(0, 0, 20, 4, 8) == [(0, 2), (2, 4), (4, 5)]
    # prefill-side prefix cache: cached pages ship immediately as one part
    assert stream_part_plan(0, 8, 20, 4, 8) == [(0, 2), (2, 4), (4, 5)]
    # decode-side shared prefix (skip_leading): pages below start_page never ship
    assert stream_part_plan(2, 0, 20, 4, 8) == [(2, 4), (4, 5)]
    # cache beyond the skip: leading cached part starts at start_page
    assert stream_part_plan(1, 8, 20, 4, 8) == [(1, 2), (2, 4), (4, 5)]
    # single chunk -> single part
    assert stream_part_plan(0, 0, 8, 4, 32) == [(0, 2)]
    # fully covered by the decode side's shared prefix -> nothing to send
    assert stream_part_plan(5, 0, 20, 4, 8) == []
    # non-page-aligned cache (cached_len = prompt_len - 1 style): the
    # partially-cached page ships with the chunk that finalizes it
    assert stream_part_plan(0, 7, 20, 4, 8) == [(0, 1), (1, 3), (3, 5)]


def test_prefill_result_kv_parts_wire_roundtrip():
    from dynamo_tpu.llm.remote_prefill import PrefillResult

    r = PrefillResult(
        request_id="x", first_token=5, prompt_len=20, skip_leading_tokens=0,
        kv_shape=(), kv_dtype="", kv_bytes=b"", kv_mode="socket", kv_parts=3,
    )
    rt = PrefillResult.from_wire(r.to_wire())
    assert rt.kv_parts == 3 and rt.kv_mode == "socket"
    # pre-v2 senders omit the field entirely
    legacy = r.to_wire()
    legacy.pop("kv_parts")
    assert PrefillResult.from_wire(legacy).kv_parts == 0
