"""Control-plane broker: KV/lease/watch/pubsub/queue semantics.

Mirrors the reference's binding tests that exercise real etcd+nats
(reference: lib/bindings/python/tests/test_kv_bindings.py fixture pattern) —
here the broker runs in-process.
"""

import asyncio

import pytest

from dynamo_tpu.cplane.broker import Broker
from dynamo_tpu.cplane.client import CplaneClient


def run(coro):
    return asyncio.run(coro)


async def with_broker(fn):
    broker = Broker()
    port = await broker.start()
    clients = []

    async def client():
        c = CplaneClient(f"127.0.0.1:{port}")
        await c.connect()
        clients.append(c)
        return c

    try:
        return await fn(client)
    finally:
        for c in clients:
            await c.close()
        await broker.stop()


def test_kv_put_get_prefix_delete():
    async def body(client):
        c = await client()
        await c.kv_put("ns/a/1", b"v1")
        await c.kv_put("ns/a/2", b"v2")
        await c.kv_put("ns/b/1", b"v3")
        assert await c.kv_get("ns/a/1") == b"v1"
        assert await c.kv_get("nope") is None
        items = await c.kv_get_prefix("ns/a/")
        assert [(i.key, i.value) for i in items] == [("ns/a/1", b"v1"), ("ns/a/2", b"v2")]
        assert await c.kv_delete("ns/a/1")
        assert not await c.kv_delete("ns/a/1")

    run(with_broker(body))


def test_kv_create_if_absent():
    async def body(client):
        c = await client()
        assert await c.kv_create("k", b"1")
        assert not await c.kv_create("k", b"2")
        assert await c.kv_get("k") == b"1"

    run(with_broker(body))


def test_watch_sees_puts_and_deletes():
    async def body(client):
        c1, c2 = await client(), await client()
        await c1.kv_put("w/initial", b"x")
        watcher = await c2.kv_get_and_watch_prefix("w/")
        assert [i.key for i in watcher.initial] == ["w/initial"]
        await c1.kv_put("w/new", b"y")
        await c1.kv_delete("w/initial")
        events = []
        async for ev in watcher.events():
            events.append(ev)
            if len(events) == 2:
                break
        assert (events[0].kind, events[0].key, events[0].value) == ("put", "w/new", b"y")
        assert (events[1].kind, events[1].key) == ("delete", "w/initial")

    run(with_broker(body))


def test_lease_keys_vanish_on_disconnect():
    async def body(client):
        c1, c2 = await client(), await client()
        lease = await c2.lease_create(ttl=5.0)
        await c2.kv_put("inst/ep:1", b"me", lease_id=lease.lease_id)
        assert await c1.kv_get("inst/ep:1") == b"me"

        watcher = await c1.kv_get_and_watch_prefix("inst/")
        await c2.close()  # process death => lease release => key delete
        ev = await asyncio.wait_for(watcher._queue.get(), 3)
        assert ev.kind == "delete" and ev.key == "inst/ep:1"
        assert await c1.kv_get("inst/ep:1") is None

    run(with_broker(body))


def test_lease_ttl_expiry():
    async def body(client):
        c1, c2 = await client(), await client()
        lease = await c2.lease_create(ttl=0.6)
        lease._task.cancel()  # stop keepalives -> ttl expiry in the broker
        await c2.kv_put("ttl/k", b"v", lease_id=lease.lease_id)
        assert await c1.kv_get("ttl/k") == b"v"
        await asyncio.sleep(1.5)
        assert await c1.kv_get("ttl/k") is None

    run(with_broker(body))


def test_lease_hijack_rejected():
    """A peer that learned a lease id (they're broadcast to every watcher)
    must not be able to revoke it or keep it alive — only the owning
    connection or the holder of the create-time secret may."""

    async def body(client):
        owner, attacker = await client(), await client()
        lease = await owner.lease_create(ttl=5.0)
        await owner.kv_put("sec/ep:1", b"me", lease_id=lease.lease_id)

        # bare-id revoke from another connection: rejected, key survives
        with pytest.raises(Exception, match="not owned"):
            await attacker._request(
                {"op": "lease_revoke", "lease_id": lease.lease_id}
            )
        assert await attacker.kv_get("sec/ep:1") == b"me"

        # bare-id keepalive from another connection: rejected too
        with pytest.raises(Exception, match="not owned"):
            await attacker._request(
                {"op": "lease_keepalive", "lease_id": lease.lease_id}
            )

        # a keepalive carrying the create-time secret from a NEW connection is
        # the owner moving: accepted, and the lease rebinds to that connection
        await attacker._request(
            {"op": "lease_keepalive", "lease_id": lease.lease_id,
             "secret": lease.secret}
        )
        # rebind back to the owner connection (same secret path)
        await owner._request(
            {"op": "lease_keepalive", "lease_id": lease.lease_id,
             "secret": lease.secret}
        )

        # the owner itself can still revoke (owning conn, secret attached)
        await lease.revoke()
        assert await attacker.kv_get("sec/ep:1") is None

    run(with_broker(body))


def test_pubsub_and_request_reply():
    async def body(client):
        c1, c2 = await client(), await client()
        got = asyncio.Queue()

        def handler(msg):
            got.put_nowait(msg)

        await c2.subscribe("events.test", handler)
        n = await c1.publish("events.test", {"x": 1})
        assert n == 1
        msg = await asyncio.wait_for(got.get(), 2)
        assert msg["payload"] == {"x": 1}

        # request/reply: responder echoes on the reply subject
        async def responder(msg):
            await c2.publish(msg["reply"], {"echo": msg["payload"]})

        def responder_cb(msg):
            asyncio.ensure_future(responder(msg))

        await c2.subscribe("svc.echo", responder_cb)
        result = await c1.request_subject("svc.echo", "hello", timeout=2)
        assert result == {"echo": "hello"}

        with pytest.raises(ConnectionError):
            await c1.request_subject("svc.missing", "x", timeout=1)

    run(with_broker(body))


def test_queue_push_pull_ack_nack():
    async def body(client):
        c1, c2 = await client(), await client()
        await c1.queue_push("q1", {"job": 1})
        m = await c2.queue_pull("q1", timeout=2)
        assert m.payload == {"job": 1}
        # nack requeues at the front
        await c2.queue_nack("q1", m.msg_id)
        m2 = await c2.queue_pull("q1", timeout=2)
        assert m2.payload == {"job": 1}
        await c2.queue_ack("q1", m2.msg_id)
        assert await c1.queue_depth("q1") == 0

    run(with_broker(body))


def test_queue_blocking_pull_and_redelivery_on_consumer_death():
    async def body(client):
        c1, c2, c3 = await client(), await client(), await client()
        pull_task = asyncio.ensure_future(c2.queue_pull("jobs"))
        await asyncio.sleep(0.05)
        await c1.queue_push("jobs", "work")
        m = await asyncio.wait_for(pull_task, 2)
        assert m.payload == "work"
        # consumer dies without ack -> message redelivered to another consumer
        await c2.close()
        m2 = await asyncio.wait_for(c3.queue_pull("jobs"), 2)
        assert m2.payload == "work"

    run(with_broker(body))


def test_queue_fifo_across_consumers():
    async def body(client):
        c = await client()
        for i in range(5):
            await c.queue_push("fifo", i)
        got = [(await c.queue_pull("fifo")).payload for _ in range(5)]
        assert got == [0, 1, 2, 3, 4]

    run(with_broker(body))


def test_serving_tolerates_control_plane_latency():
    """The reference's mock-network latency-model slot: a slow control plane
    (injected per-op delay) must not break endpoint serving — requests still
    complete, just slower."""
    import time

    from dynamo_tpu.cplane.broker import Broker
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    async def body():
        broker = Broker(latency=(0.02, 0.005))
        port = await broker.start()
        drt = DistributedRuntime(cplane_address=f"127.0.0.1:{port}")
        await drt.connect()
        served = None
        try:
            async def echo(req):
                yield {"echo": req}

            served = await drt.namespace("lat").component("c").endpoint("run").serve_endpoint(echo)
            client = await drt.endpoint_client("dyn://lat.c.run")
            await client.wait_for_instances(timeout=30)
            t0 = time.monotonic()
            outs = []
            async for out in await client.random({"n": 1}):
                outs.append(out)
            assert outs[0]["echo"] == {"n": 1}
            # latency is actually injected: mean - 3*jitter lower bound keeps
            # the gaussian sample assertion deterministic in practice
            assert time.monotonic() - t0 >= 0.02 - 3 * 0.005
        finally:
            if served is not None:
                await served.stop()
            await drt._shutdown_hook()
            await broker.stop()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(body(), 60))
