"""Deploy plane: CRD validation, reconciler manifests/diff, API server CRUD.

Mirrors the reference's operator resource unit tests and api-server
integration suite with fixture storage (reference:
deploy/dynamo/operator/internal/controller_common/resource_test.go,
deploy/dynamo/api-server/tests/integration/api_test.go).
"""

import asyncio
import json

import pytest

from dynamo_tpu.deploy import DeploymentSpec, ServiceSpec, Autoscaling, render_manifests, reconcile
from dynamo_tpu.deploy.crd import SpecError
from dynamo_tpu.deploy.api_server import DeployApiServer, FileDeploymentStore


def sample_spec(**over) -> DeploymentSpec:
    d = dict(
        name="llama-agg",
        image="dynamo-tpu:v1",
        services=[
            ServiceSpec(
                name="frontend",
                command=["python", "-m", "dynamo_tpu.components.frontend"],
                port=8080,
                autoscaling=Autoscaling(min_replicas=1, max_replicas=4, metric="inflight_requests", target=32),
            ),
            ServiceSpec(
                name="worker",
                command=["python", "-m", "dynamo_tpu.launch.run", "run", "/models/llama", "--out", "jax"],
                tpu_chips=4,
                config={"tp": 4, "num_pages": 4096},
            ),
        ],
    )
    d.update(over)
    return DeploymentSpec(**d)


# ---------------- CRD ----------------


def test_spec_roundtrip_and_validation():
    spec = sample_spec()
    spec.validate()
    again = DeploymentSpec.from_dict(spec.to_dict())
    assert again == spec

    with pytest.raises(SpecError):
        DeploymentSpec(name="Bad_Name", services=[ServiceSpec(name="x")]).validate()
    with pytest.raises(SpecError):
        DeploymentSpec(name="ok", services=[]).validate()
    with pytest.raises(SpecError):
        DeploymentSpec(
            name="ok", services=[ServiceSpec(name="a"), ServiceSpec(name="a")]
        ).validate()
    with pytest.raises(SpecError):
        ServiceSpec(name="w", autoscaling=Autoscaling(min_replicas=3, max_replicas=1)).validate()


def test_spec_from_yaml():
    yaml_text = """
name: demo
image: dynamo-tpu:v2
services:
  - name: frontend
    port: 8080
    command: [python, -m, dynamo_tpu.components.frontend]
  - name: worker
    tpu_chips: 8
    hosts_per_slice: 2
"""
    spec = DeploymentSpec.from_yaml(yaml_text)
    assert spec.image == "dynamo-tpu:v2"
    assert spec.services[1].hosts_per_slice == 2


# ---------------- reconciler ----------------


def test_render_manifests_shapes():
    objs = render_manifests(sample_spec())
    kinds = [(o["kind"], o["metadata"]["name"]) for o in objs]
    # managed cplane (Deployment+Service), frontend (Deployment+Service+HPA), worker (Deployment)
    assert ("Deployment", "llama-agg-cplane") in kinds
    assert ("Service", "llama-agg-cplane") in kinds
    assert ("Deployment", "llama-agg-frontend") in kinds
    assert ("Service", "llama-agg-frontend") in kinds
    assert ("HorizontalPodAutoscaler", "llama-agg-frontend") in kinds
    assert ("Deployment", "llama-agg-worker") in kinds

    worker = next(o for o in objs if o["metadata"]["name"] == "llama-agg-worker")
    ctr = worker["spec"]["template"]["spec"]["containers"][0]
    assert ctr["resources"]["limits"]["google.com/tpu"] == "4"
    env = {e["name"]: e.get("value") for e in ctr["env"]}
    assert env["DYNTPU_CPLANE"] == "llama-agg-cplane:4222"
    assert json.loads(env["DYNTPU_SERVICE_CONFIG"]) == {"worker": {"tp": 4, "num_pages": 4096}}

    hpa = next(o for o in objs if o["kind"] == "HorizontalPodAutoscaler")
    assert hpa["spec"]["metrics"][0]["pods"]["metric"]["name"] == "llm_http_service_inflight_requests"


def test_render_external_cplane_skips_managed_broker():
    spec = sample_spec(cplane="nats.infra:4222")
    objs = render_manifests(spec)
    assert not any("cplane" in o["metadata"]["name"] for o in objs)
    worker = next(o for o in objs if o["metadata"]["name"] == "llama-agg-worker")
    env = {e["name"]: e.get("value") for e in worker["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["DYNTPU_CPLANE"] == "nats.infra:4222"


def test_render_multihost_statefulset():
    spec = DeploymentSpec(
        name="mh",
        services=[
            ServiceSpec(
                name="worker", tpu_chips=4, hosts_per_slice=2, replicas=3, port=8080
            )
        ],
    )
    objs = render_manifests(spec)
    # one StatefulSet per slice replica: pod ordinals stay in
    # [0, hosts_per_slice) so DYNTPU_PROCESS_ID < DYNTPU_NUM_PROCESSES, and
    # each slice forms its mesh against its own pod-0 coordinator
    stss = [o for o in objs if o["kind"] == "StatefulSet"]
    assert [s["metadata"]["name"] for s in stss] == [
        "mh-worker-s0", "mh-worker-s1", "mh-worker-s2"
    ]
    for i, sts in enumerate(stss):
        assert sts["spec"]["replicas"] == 2  # hosts_per_slice
        env = {e["name"]: e for e in sts["spec"]["template"]["spec"]["containers"][0]["env"]}
        assert env["DYNTPU_NUM_PROCESSES"]["value"] == "2"
        assert env["DYNTPU_COORDINATOR"]["value"].startswith(f"mh-worker-s{i}-0.mh-worker-s{i}.")
        assert "DYNTPU_PROCESS_ID" in env
        headless = next(
            o for o in objs
            if o["kind"] == "Service" and o["metadata"]["name"] == f"mh-worker-s{i}"
        )
        assert headless["spec"]["clusterIP"] == "None"
    # the serving port is exposed by a cross-slice ClusterIP service
    port_svc = next(
        o for o in objs if o["kind"] == "Service" and o["metadata"]["name"] == "mh-worker"
    )
    assert port_svc["spec"]["ports"] == [{"port": 8080, "targetPort": 8080}]
    assert port_svc["spec"]["selector"]["dynamo-tpu/component"] == "worker"

    # autoscaling cannot own a multihost slice's scale — rejected at
    # validate() time so the API server 422s instead of 500ing on render
    bad = ServiceSpec(
        name="w",
        hosts_per_slice=2,
        autoscaling=Autoscaling(min_replicas=1, max_replicas=2),
    )
    with pytest.raises(SpecError):
        bad.validate()
    with pytest.raises(SpecError):
        render_manifests(DeploymentSpec(name="mh2", services=[bad]))


def test_hpa_owned_deployment_omits_replicas():
    objs = render_manifests(sample_spec())
    frontend = next(
        o for o in objs
        if o["kind"] == "Deployment" and o["metadata"]["name"] == "llama-agg-frontend"
    )
    # the HPA owns the scale; pinning replicas would reset it on every apply
    assert "replicas" not in frontend["spec"]
    worker = next(
        o for o in objs
        if o["kind"] == "Deployment" and o["metadata"]["name"] == "llama-agg-worker"
    )
    assert worker["spec"]["replicas"] == 1


def test_reconcile_diff():
    spec = sample_spec()
    desired = render_manifests(spec)

    # empty cluster: everything is created
    actions = reconcile(spec, live=[])
    assert len(actions["create"]) == len(desired)
    assert not actions["update"] and not actions["delete"]

    # live == desired: no-op
    actions = reconcile(spec, live=[json.loads(json.dumps(o)) for o in desired])
    assert not actions["create"] and not actions["update"] and not actions["delete"]
    assert len(actions["unchanged"]) == len(desired)

    # env change -> update; dropped service -> delete; foreign objects ignored
    spec2 = sample_spec()
    spec2.services[0].env = {"LOG": "debug"}
    spec2.services = spec2.services[:1]
    foreign = {"kind": "Deployment", "metadata": {"name": "other", "namespace": "default", "labels": {}}}
    # part-of alone (a shared label other tools also set) must NOT mark an
    # object as ours — only part-of + managed-by together do
    part_of_only = {
        "kind": "Ingress",
        "metadata": {
            "name": "helm-ingress",
            "namespace": "default",
            "labels": {"app.kubernetes.io/part-of": "llama-agg"},
        },
    }
    actions = reconcile(spec2, live=desired + [foreign, part_of_only])
    updated = {o["metadata"]["name"] for o in actions["update"]}
    deleted = {o["metadata"]["name"] for o in actions["delete"]}
    assert "llama-agg-frontend" in updated
    assert "llama-agg-worker" in deleted
    assert "other" not in deleted
    assert "helm-ingress" not in deleted


# ---------------- API server ----------------


async def _json(client_fn, method, url, body=None):
    import aiohttp

    async with aiohttp.ClientSession() as s:
        async with s.request(method, url, json=body) as resp:
            return resp.status, await resp.json()


def test_api_server_crud(tmp_path):
    async def run():
        server = DeployApiServer(FileDeploymentStore(tmp_path / "db.json"))
        port = await server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            spec = sample_spec().to_dict()

            status, body = await _json(None, "POST", f"{base}/api/v1/deployments", spec)
            assert (status, body["revision"]) == (201, 1)

            status, _ = await _json(None, "POST", f"{base}/api/v1/deployments", spec)
            assert status == 409  # duplicate

            status, body = await _json(None, "GET", f"{base}/api/v1/deployments/llama-agg")
            assert status == 200 and body["spec"]["image"] == "dynamo-tpu:v1"

            spec["image"] = "dynamo-tpu:v2"
            status, body = await _json(None, "PUT", f"{base}/api/v1/deployments/llama-agg", spec)
            assert (status, body["revision"]) == (200, 2)

            status, body = await _json(None, "GET", f"{base}/api/v1/deployments/llama-agg/revisions")
            assert [r["revision"] for r in body["revisions"]] == [2, 1]

            status, body = await _json(
                None, "POST", f"{base}/api/v1/deployments/llama-agg/rollback/1"
            )
            assert (status, body["revision"], body["rolled_back_to"]) == (200, 3, 1)
            status, body = await _json(None, "GET", f"{base}/api/v1/deployments/llama-agg")
            assert body["spec"]["image"] == "dynamo-tpu:v1"

            status, body = await _json(None, "GET", f"{base}/api/v1/deployments/llama-agg/manifests")
            assert status == 200 and any(m["kind"] == "Deployment" for m in body["manifests"])

            # invalid spec -> 422
            status, _ = await _json(None, "POST", f"{base}/api/v1/deployments", {"name": "x"})
            assert status == 422

            status, body = await _json(None, "DELETE", f"{base}/api/v1/deployments/llama-agg")
            assert status == 200
            status, _ = await _json(None, "GET", f"{base}/api/v1/deployments/llama-agg")
            assert status == 404
        finally:
            await server.stop()

    asyncio.run(run())


def test_file_store_persists(tmp_path):
    path = tmp_path / "db.json"
    store = FileDeploymentStore(path)
    store.put("a", {"name": "a"})
    store.put("a", {"name": "a", "v": 2})
    store2 = FileDeploymentStore(path)
    assert store2.head("a")["revision"] == 2
    assert [r["revision"] for r in store2.revisions("a")] == [1, 2]


def test_sqlite_store_durable_across_restart(tmp_path):
    from dynamo_tpu.deploy.api_server import SqliteDeploymentStore

    path = tmp_path / "deploy.db"
    store = SqliteDeploymentStore(path)
    store.put("a", {"name": "a"})
    store.put("a", {"name": "a", "v": 2})
    store.put("b", {"name": "b"})
    store.set_status("a", {"converged": True, "observed_revision": 2})
    store.delete("b")
    store.close()

    store2 = SqliteDeploymentStore(path)
    assert store2.list() == ["a"]
    assert store2.head("a")["revision"] == 2
    assert store2.head("a")["spec"]["v"] == 2
    assert [r["revision"] for r in store2.revisions("a")] == [1, 2]
    assert store2.get_status("a")["converged"] is True
    # revisions keep counting after the restart (no id reuse)
    assert store2.put("a", {"name": "a", "v": 3})["revision"] == 3
    store2.close()


def test_api_server_on_sqlite_store(tmp_path):
    """The full CRUD surface over the durable store, then a fresh server on
    the same DB sees the state (the reference's Postgres-backed behavior)."""
    from dynamo_tpu.deploy.api_server import SqliteDeploymentStore

    path = tmp_path / "deploy.db"

    async def run():
        server = DeployApiServer(SqliteDeploymentStore(path))
        port = await server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            spec = sample_spec().to_dict()
            status, body = await _json(None, "POST", f"{base}/api/v1/deployments", spec)
            assert (status, body["revision"]) == (201, 1)
            spec["image"] = "dynamo-tpu:v2"
            status, body = await _json(None, "PUT", f"{base}/api/v1/deployments/llama-agg", spec)
            assert (status, body["revision"]) == (200, 2)
        finally:
            await server.stop()
            server.store.close()

        server2 = DeployApiServer(SqliteDeploymentStore(path))
        port = await server2.start()
        base = f"http://127.0.0.1:{port}"
        try:
            status, body = await _json(None, "GET", f"{base}/api/v1/deployments/llama-agg")
            assert status == 200 and body["spec"]["image"] == "dynamo-tpu:v2"
            status, body = await _json(None, "GET", f"{base}/api/v1/deployments/llama-agg/revisions")
            assert [r["revision"] for r in body["revisions"]] == [2, 1]
        finally:
            await server2.stop()
            server2.store.close()

    asyncio.run(run())


# ---------------- controller loop (watch -> converge -> drift) ----------------


def test_controller_converges_and_repairs_drift():
    from dynamo_tpu.deploy.api_server import DeploymentStore
    from dynamo_tpu.deploy.controller import DeployController, FakeCluster

    async def run():
        store = DeploymentStore()
        cluster = FakeCluster()
        ctrl = DeployController(store, cluster, interval=3600)  # manual ticks

        # watch -> converge: new deployment materializes every object
        store.put("llama-agg", sample_spec().to_dict())
        summary = await ctrl.converge_once()
        assert summary["llama-agg"]["created"] > 0
        n_objects = len(cluster.objects)
        assert n_objects > 0
        assert store.get_status("llama-agg")["converged"] is False  # had work

        # steady state: second pass is a no-op
        summary = await ctrl.converge_once()
        assert summary["llama-agg"]["converged"] is True
        assert len(cluster.objects) == n_objects

        # drift 1: a worker Deployment deleted out from under the controller
        key = ("Deployment", "default", "llama-agg-worker")
        assert key in cluster.objects
        del cluster.objects[key]
        summary = await ctrl.converge_once()
        assert summary["llama-agg"]["created"] == 1
        assert key in cluster.objects

        # drift 2: replicas mutated out-of-band converge back to desired
        cluster.objects[key]["spec"]["replicas"] = 17
        summary = await ctrl.converge_once()
        assert summary["llama-agg"]["updated"] == 1
        assert cluster.objects[key]["spec"]["replicas"] == 1

        # unmanaged objects in the namespace are never touched
        stranger = {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "other", "namespace": "default", "labels": {}},
            "spec": {"replicas": 3},
        }
        cluster.objects[("Deployment", "default", "other")] = stranger
        await ctrl.converge_once()
        assert cluster.objects[("Deployment", "default", "other")]["spec"]["replicas"] == 3

        # spec update: scale the worker; converge applies exactly that change
        spec2 = sample_spec()
        spec2.services[1].replicas = 3
        store.put("llama-agg", spec2.to_dict())
        summary = await ctrl.converge_once()
        assert summary["llama-agg"]["updated"] >= 1
        assert cluster.objects[key]["spec"]["replicas"] == 3
        assert store.get_status("llama-agg")["observed_revision"] == 2

        # deployment removed from the store: objects garbage-collected,
        # the stranger survives
        store.delete("llama-agg")
        summary = await ctrl.converge_once()
        assert summary["llama-agg"] == {"garbage_collected": True}
        remaining = [k for k in cluster.objects if k[2].startswith("llama-agg")]
        assert remaining == []
        assert ("Deployment", "default", "other") in cluster.objects

    asyncio.new_event_loop().run_until_complete(run())


def test_controller_rollback_mid_flight_and_api_status(tmp_path):
    """Rollback through the API while the controller loop is live: the
    cluster converges back to revision 1's content and /status reports it."""
    import aiohttp

    from dynamo_tpu.deploy.api_server import DeploymentStore
    from dynamo_tpu.deploy.controller import DeployController, FakeCluster

    async def run():
        store = DeploymentStore()
        cluster = FakeCluster()
        ctrl = await DeployController(store, cluster, interval=0.1).start()
        server = DeployApiServer(store, controller=ctrl)
        port = await server.start()
        base = f"http://127.0.0.1:{port}/api/v1"
        key = ("Deployment", "default", "llama-agg-worker")

        async def wait_converged(rev, timeout=10.0):
            deadline = asyncio.get_running_loop().time() + timeout
            while asyncio.get_running_loop().time() < deadline:
                async with aiohttp.ClientSession() as s:
                    async with s.get(f"{base}/deployments/llama-agg/status") as r:
                        body = await r.json()
                st = body.get("status") or {}
                if st.get("observed_revision") == rev and st.get("converged"):
                    return st
                await asyncio.sleep(0.05)
            raise TimeoutError(f"never converged to rev {rev}")

        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/deployments", json=sample_spec().to_dict()) as r:
                    assert r.status == 201
            await wait_converged(1)
            assert cluster.objects[key]["spec"]["replicas"] == 1

            spec2 = sample_spec()
            spec2.services[1].replicas = 5
            async with aiohttp.ClientSession() as s:
                async with s.put(f"{base}/deployments/llama-agg", json=spec2.to_dict()) as r:
                    assert r.status == 200
            await wait_converged(2)
            assert cluster.objects[key]["spec"]["replicas"] == 5

            # rollback mid-flight -> revision 3 with revision 1's spec
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/deployments/llama-agg/rollback/1") as r:
                    assert r.status == 200
            st = await wait_converged(3)
            assert cluster.objects[key]["spec"]["replicas"] == 1
            assert st["converged"] is True
        finally:
            await server.stop()
            await ctrl.stop()

    asyncio.new_event_loop().run_until_complete(run())


def test_controller_restart_gcs_deployments_deleted_while_down():
    """A deployment deleted while the controller was down must still be
    garbage-collected: ownership labels, not in-process memory, drive GC."""
    from dynamo_tpu.deploy.api_server import DeploymentStore
    from dynamo_tpu.deploy.controller import DeployController, FakeCluster

    async def run():
        store = DeploymentStore()
        cluster = FakeCluster()
        ctrl1 = DeployController(store, cluster, interval=3600)
        store.put("llama-agg", sample_spec().to_dict())
        store.put("other-dep", sample_spec(name="other-dep").to_dict())
        await ctrl1.converge_once()
        assert any(k[2].startswith("llama-agg") for k in cluster.objects)

        # controller dies; deployment deleted while it is down
        store.delete("llama-agg")
        ctrl2 = DeployController(store, cluster, interval=3600)  # fresh memory
        await ctrl2.converge_once()
        assert not any(k[2].startswith("llama-agg") for k in cluster.objects)
        assert any(k[2].startswith("other-dep") for k in cluster.objects)

    asyncio.new_event_loop().run_until_complete(run())


def test_restart_gc_sweeps_foreign_namespaces():
    """A deployment in a NON-default namespace deleted while the controller
    was down must still be garbage-collected after restart: the cluster-wide
    managed-by label listing discovers its namespace even though no store
    head or in-process state names it (ADVICE r2)."""
    from dynamo_tpu.deploy.controller import DeployController, FakeCluster, MANAGED_BY
    from dynamo_tpu.deploy.api_server import DeploymentStore

    async def run():
        cluster = FakeCluster()
        # orphan left behind in namespace "prod" by a dead deployment
        cluster.objects[("Deployment", "prod", "ghost-worker")] = {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {
                "name": "ghost-worker", "namespace": "prod",
                "labels": {
                    "app.kubernetes.io/managed-by": MANAGED_BY,
                    "app.kubernetes.io/part-of": "ghost",
                },
            },
            "spec": {"replicas": 1},
        }
        # fresh controller: empty store, empty in-process state
        ctrl = DeployController(DeploymentStore(), cluster, interval=3600)
        await ctrl.converge_once()
        assert ("Deployment", "prod", "ghost-worker") not in cluster.objects
        assert ("Deployment", "prod", "ghost-worker") in cluster.deleted

    asyncio.run(run())


def test_image_build_flow(tmp_path):
    """The DynamoNimRequest slot end-to-end: `dynamo-tpu build` emits a
    Containerfile into the artifact, POST /api/v1/builds renders the
    in-cluster kaniko Job, and the controller applies it and tracks the
    build to completion — durable across an API-server restart."""
    import asyncio as _asyncio

    from dynamo_tpu.deploy.api_server import SqliteDeploymentStore
    from dynamo_tpu.deploy.controller import DeployController, FakeCluster
    from dynamo_tpu.sdk.build import build_artifact

    out = build_artifact("examples.hello_world:Frontend", str(tmp_path / "art"))
    cf = (out / "Containerfile").read_text()
    assert "FROM python" in cf and "Containerfile" not in cf.split("FROM")[0]
    assert (out / "deployment.yaml").exists()

    path = tmp_path / "deploy.db"

    async def run():
        store = SqliteDeploymentStore(path)
        cluster = FakeCluster()
        server = DeployApiServer(store)
        port = await server.start()
        base = f"http://127.0.0.1:{port}"
        controller = DeployController(store, cluster, interval=30.0)
        try:
            status, body = await _json(None, "POST", f"{base}/api/v1/builds", {
                "name": "hello", "image": "registry/hello:v1",
                "context": f"dir://{out}",
            })
            assert (status, body["phase"]) == (201, "pending")

            await controller.converge_once()
            status, rec = await _json(None, "GET", f"{base}/api/v1/builds/hello")
            assert status == 200
            assert rec["phase"] in ("building", "complete")
            # the rendered Job reached the cluster
            jobs = [o for o in await cluster.list_objects("default") if o["kind"] == "Job"]
            assert jobs and jobs[0]["metadata"]["name"] == "hello-image-build"
            assert any("registry/hello:v1" in a for a in
                       jobs[0]["spec"]["template"]["spec"]["containers"][0]["args"])

            await controller.converge_once()
            _, rec = await _json(None, "GET", f"{base}/api/v1/builds/hello")
            assert rec["phase"] == "complete"

            status, listing = await _json(None, "GET", f"{base}/api/v1/builds")
            assert [b["name"] for b in listing["builds"]] == ["hello"]
        finally:
            await server.stop()
            store.close()

        # restart: the build record (incl. completion) survives
        store2 = SqliteDeploymentStore(path)
        try:
            assert store2.get_build("hello")["phase"] == "complete"
        finally:
            store2.close()

    _asyncio.run(run())


def test_build_conflict_and_namespace_validation(tmp_path):
    """ADVICE r4: re-POSTing an in-flight/complete build must 409 (not
    silently reset to pending and re-apply the Job); a failed build MAY be
    replaced; namespace gets the same DNS-1123 gate as name."""
    from dynamo_tpu.deploy.api_server import DeploymentStore

    async def run():
        store = DeploymentStore()
        server = DeployApiServer(store)
        port = await server.start()
        base = f"http://{'127.0.0.1'}:{port}"
        try:
            body = {"name": "b1", "image": "r/i:v1", "context": "dir:///tmp/x"}
            status, _ = await _json(None, "POST", f"{base}/api/v1/builds", body)
            assert status == 201
            # duplicate over a pending build -> 409, record untouched
            status, resp = await _json(None, "POST", f"{base}/api/v1/builds", body)
            assert status == 409 and "exists" in resp["error"]
            assert store.get_build("b1")["phase"] == "pending"
            # a FAILED build may be re-posted (retry path)
            store.put_build("b1", {**store.get_build("b1"), "phase": "failed"})
            status, _ = await _json(None, "POST", f"{base}/api/v1/builds", body)
            assert status == 201
            assert store.get_build("b1")["phase"] == "pending"
            # 52+-char name rejected: Job name adds "-image-build" (+12)
            # and must stay under k8s' 63-char limit
            status, resp = await _json(None, "POST", f"{base}/api/v1/builds", {
                "name": "x" * 52, "image": "r/i:v1", "context": "dir:///tmp/x",
            })
            assert status == 422
            # bad namespace rejected up front (it rides into kubectl apply)
            status, resp = await _json(None, "POST", f"{base}/api/v1/builds", {
                "name": "b2", "image": "r/i:v1", "context": "dir:///tmp/x",
                "namespace": "Bad_NS",
            })
            assert status == 422 and "namespace" in resp["error"]
        finally:
            await server.stop()

    asyncio.run(run())


def test_file_store_persists_builds(tmp_path):
    """ADVICE r4: build records written through a FileDeploymentStore must
    survive a restart (they used to inherit the no-op flush and vanish)."""
    path = tmp_path / "store.json"
    store = FileDeploymentStore(path)
    store.put(sample_spec().name, sample_spec().to_dict())
    store.put_build("bld", {"name": "bld", "phase": "building", "job": {}})
    store2 = FileDeploymentStore(path)
    assert store2.get_build("bld")["phase"] == "building"
    assert store2.head("llama-agg") is not None
    # pre-builds files (bare revisions map) still load
    legacy = tmp_path / "legacy.json"
    legacy.write_text(json.dumps({"old": [{"revision": 1, "created_at": 0.0, "spec": {}}]}))
    store3 = FileDeploymentStore(legacy)
    assert store3.head("old")["revision"] == 1


def test_vanished_build_job_reapplied_then_failed():
    """ADVICE r4: a 'building' record whose Job object disappeared (TTL GC /
    out-of-band delete) must not wedge: after the grace period the controller
    re-applies the Job, and after max_reapplies it marks the build failed."""
    import time as _time

    from dynamo_tpu.deploy.api_server import DeploymentStore
    from dynamo_tpu.deploy.controller import DeployController, FakeCluster

    async def run():
        store = DeploymentStore()
        cluster = FakeCluster()
        ctrl = DeployController(store, cluster, interval=3600,
                                build_job_grace_s=0.0, build_job_max_reapplies=2)
        job = {
            "apiVersion": "batch/v1", "kind": "Job",
            "metadata": {"name": "b-image-build", "namespace": "default",
                         "labels": {}},
        }
        store.put_build("b", {
            "name": "b", "image": "r/i:v1", "context": "dir:///x",
            "namespace": "default", "phase": "building", "job": job,
            "job_applied_at": _time.time() - 10,
        })

        class VanishingCluster(FakeCluster):
            async def apply(self, obj):
                # record the apply but never retain the Job (simulates GC)
                self.applied.append(self._key(obj))

        ctrl.cluster = VanishingCluster()
        await ctrl.converge_once()
        rec = store.get_build("b")
        assert rec["phase"] == "building" and rec["job_reapplies"] == 1
        rec["job_applied_at"] = _time.time() - 10
        store.put_build("b", rec)
        await ctrl.converge_once()
        assert store.get_build("b")["job_reapplies"] == 2
        rec = store.get_build("b")
        rec["job_applied_at"] = _time.time() - 10
        store.put_build("b", rec)
        await ctrl.converge_once()
        assert store.get_build("b")["phase"] == "failed"
        assert "disappeared" in store.get_build("b")["failure"]

    asyncio.run(run())


def test_completed_build_may_be_replaced():
    """A terminal 'complete' build may be re-POSTed (rebuild workflow) — only
    pending/building conflict."""
    from dynamo_tpu.deploy.api_server import DeploymentStore

    async def run():
        store = DeploymentStore()
        server = DeployApiServer(store)
        port = await server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            body = {"name": "c1", "image": "r/i:v1", "context": "dir:///tmp/x"}
            status, _ = await _json(None, "POST", f"{base}/api/v1/builds", body)
            assert status == 201
            store.put_build("c1", {**store.get_build("c1"), "phase": "complete"})
            status, _ = await _json(None, "POST", f"{base}/api/v1/builds",
                                    {**body, "image": "r/i:v2"})
            assert status == 201
            assert store.get_build("c1")["image"] == "r/i:v2"
        finally:
            await server.stop()

    asyncio.run(run())


def test_permanently_failing_reapply_reaches_failed():
    """A re-apply that RAISES every pass (namespace gone) must still burn
    through max_reapplies and fail, not retry forever (review r5)."""
    import time as _time

    from dynamo_tpu.deploy.api_server import DeploymentStore
    from dynamo_tpu.deploy.controller import DeployController, FakeCluster

    class BrokenCluster(FakeCluster):
        async def apply(self, obj):
            raise RuntimeError("namespace gone")

    async def run():
        store = DeploymentStore()
        ctrl = DeployController(store, BrokenCluster(), interval=3600,
                                build_job_grace_s=0.0, build_job_max_reapplies=1)
        job = {"apiVersion": "batch/v1", "kind": "Job",
               "metadata": {"name": "p-image-build", "namespace": "gone", "labels": {}}}
        store.put_build("p", {
            "name": "p", "image": "r/i:v1", "context": "dir:///x",
            "namespace": "gone", "phase": "building", "job": job,
            "job_applied_at": _time.time() - 10,
        })
        await ctrl.converge_once()
        assert store.get_build("p")["job_reapplies"] == 1
        rec = store.get_build("p")
        rec["job_applied_at"] = _time.time() - 10
        store.put_build("p", rec)
        await ctrl.converge_once()
        assert store.get_build("p")["phase"] == "failed"

    asyncio.run(run())


def test_registry_routes_clusters_targets_components(tmp_path):
    """The reference API server's cluster / deployment-target / component
    routes (api-server/api/routes/{cluster,deployment_target,
    dynamo_component}.go): CRUD + conflict/validation + sqlite durability."""
    from dynamo_tpu.deploy.api_server import SqliteDeploymentStore

    path = tmp_path / "reg.db"

    async def run():
        store = SqliteDeploymentStore(path)
        server = DeployApiServer(store)
        port = await server.start()
        base = f"http://127.0.0.1:{port}/api/v1"
        try:
            # clusters: implicit default + registered
            status, body = await _json(None, "POST", f"{base}/clusters",
                                       {"name": "edge-1", "accelerator": "tpu-v5e"})
            assert status == 201
            status, _ = await _json(None, "POST", f"{base}/clusters", {"name": "edge-1"})
            assert status == 409
            status, _ = await _json(None, "POST", f"{base}/clusters", {"name": "default"})
            assert status == 409  # implicit
            status, _ = await _json(None, "POST", f"{base}/clusters", {"name": "Bad_Name"})
            assert status == 422
            status, body = await _json(None, "GET", f"{base}/clusters")
            assert [c["name"] for c in body["clusters"]] == ["default", "edge-1"]
            status, body = await _json(None, "GET", f"{base}/clusters/edge-1")
            assert (status, body["accelerator"]) == (200, "tpu-v5e")
            # the implicit default the list advertises is GETtable too, and
            # refuses deletion with the same 'implicit' answer as create
            status, body = await _json(None, "GET", f"{base}/clusters/default")
            assert (status, body["name"]) == (200, "default")
            status, _ = await _json(None, "DELETE", f"{base}/clusters/default")
            assert status == 409

            # deployment targets
            status, _ = await _json(None, "POST", f"{base}/deployment-targets",
                                    {"name": "prod-a", "cluster": "edge-1",
                                     "namespace": "prod"})
            assert status == 201
            status, body = await _json(None, "GET", f"{base}/deployment-targets")
            assert body["deployment-targets"][0]["cluster"] == "edge-1"

            # components: versioned registry
            status, _ = await _json(None, "POST", f"{base}/components",
                                    {"name": "frontend", "version": "1.0",
                                     "image": "reg/frontend:1.0"})
            assert status == 201
            status, _ = await _json(None, "POST", f"{base}/components",
                                    {"name": "frontend", "version": "1.0"})
            assert status == 409
            status, _ = await _json(None, "POST", f"{base}/components",
                                    {"name": "frontend", "version": "1.1",
                                     "image": "reg/frontend:1.1"})
            assert status == 201
            # natural version order: backfilling 1.0.5 after 1.1 must not
            # downgrade latest, and 1.10 sorts above 1.9, not below
            status, _ = await _json(None, "POST", f"{base}/components",
                                    {"name": "frontend", "version": "1.0.5"})
            assert status == 201
            status, body = await _json(None, "GET", f"{base}/components")
            assert body["components"][0]["latest"] == "1.1"
            assert body["components"][0]["versions"] == ["1.0", "1.0.5", "1.1"]
            # malformed component names are rejected, not stored unreachable
            status, _ = await _json(None, "POST", f"{base}/components",
                                    {"name": "Bad Name", "version": "1"})
            assert status == 422
            status, body = await _json(None, "GET", f"{base}/components/frontend")
            assert body["versions"]["1.0"]["image"] == "reg/frontend:1.0"

            # delete
            status, _ = await _json(None, "DELETE", f"{base}/deployment-targets/prod-a")
            assert status == 200
            status, _ = await _json(None, "GET", f"{base}/deployment-targets/prod-a")
            assert status == 404
        finally:
            await server.stop()
            store.close()

        # durability across restart
        store2 = SqliteDeploymentStore(path)
        try:
            assert store2.get_item("clusters", "edge-1")["accelerator"] == "tpu-v5e"
            assert store2.get_item("components", "frontend")["latest"] == "1.1"
            assert store2.get_item("deployment_targets", "prod-a") is None
        finally:
            store2.close()

    asyncio.run(run())
