"""Live sequence migration (disagg/migrate.py): drain, rebalance, and
survive worker loss without killing requests.

Correctness bar: a sequence migrated mid-decode must finish with tokens
byte-identical to an unmigrated run (greedy AND seeded sampling, including
spec-draft and LoRA-bound lanes), and every arm of the failure ladder —
handoff pull timeout (injected part drop), corrupt parts, destination death
before/after the first continuation token, source death after the manifest,
double-migration races — must degrade to recompute/local-resume with
identical final output: no request error, no hang past the deadline belts.
The chaos arms drive the seeded DYNTPU_FAULT_DATAPLANE knobs instead of
real socket blackholes.
"""

import asyncio
import time

import pytest

from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest, StepOutput

PROMPT = [5, 9, 2, 77, 31, 8, 100, 42, 17, 3, 60, 61,
          7, 13, 19, 23, 29, 37, 41, 43, 47, 53, 59, 67]


def _req(rid, prompt=PROMPT, n=32, temp=0.0, seed=None, lora=""):
    return EngineRequest(
        request_id=rid, token_ids=list(prompt),
        sampling=SamplingParams(temperature=temp, max_tokens=n, seed=seed,
                                ignore_eos=True),
        lora_name=lora,
    )


def _engine(**over):
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    from tests.test_engine import tiny_engine_config

    defaults = dict(decode_steps=2, pipeline_depth=1, num_pages=96)
    defaults.update(over)
    return AsyncJaxEngine(tiny_engine_config(**defaults))


async def _collect(engine, req):
    toks, finish = [], None
    async for out in engine.generate(req):
        if out.token is not None:
            toks.append(out.token)
        if out.finished:
            finish = out.finish_reason
    return toks, finish


async def _wait_generated(eng, rid, n, timeout=60.0):
    """Poll until the sequence has materialized >= n tokens (mid-decode)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        seq = next((s for s in eng.scheduler.slots
                    if s is not None and s.req.request_id == rid), None)
        if seq is not None and not seq.finished and len(seq.generated) >= n:
            return True
        await asyncio.sleep(0.005)
    return False


async def _wire_pair(src, dst, timeout_s=30.0):
    """Attach a pull server to src and a fetch client to dst (the handoff
    dataplane); returns the server for cleanup."""
    from dynamo_tpu.disagg.prefix_fetch import KvPullServer, PrefixFetchClient

    srv = await KvPullServer(src, host="127.0.0.1").start()
    src.kv_pull_server = srv
    dst.attach_prefix_fetch(
        PrefixFetchClient(asyncio.get_running_loop(), timeout_s=timeout_s)
    )
    return srv


# ---------------- manifest (fast, no engine) ----------------


def test_manifest_roundtrip_and_request_arithmetic():
    import dataclasses

    from dynamo_tpu.disagg.migrate import SequenceManifest

    m = SequenceManifest(
        request_id="r1",
        prompt_tokens=[1, 2, 3, 4],
        generated=[10, 11, 12],
        sampling=dataclasses.asdict(
            SamplingParams(temperature=0.7, max_tokens=16, min_tokens=5,
                           seed=42, ignore_eos=True)
        ),
        eos_token_ids=[0],
        lora_name="a1",
        penalty_output_from=4,
        tenant="t1", scenario="bursty_chat",
        source_addr="127.0.0.1:4040", kv_blocks=6, age_s=1.5,
    )
    # wire + msgpack byte-stability
    m2 = SequenceManifest.from_wire(m.to_wire())
    assert m2 == m
    assert SequenceManifest.unpack(m.pack()) == m
    assert m.pack() == SequenceManifest.unpack(m.pack()).pack()
    assert len(m.pack()) < 1024  # "small msgpack manifest"

    req = m.to_engine_request(now=100.0)
    assert req.token_ids == [1, 2, 3, 4, 10, 11, 12]
    assert req.sampling.max_tokens == 13  # 16 - 3 already streamed
    assert req.sampling.min_tokens == 2  # 5 - 3
    assert req.sampling.seed == 42 and req.sampling.temperature == 0.7
    assert req.kv_handoff_seq == "r1"
    assert req.kv_holder_addr == "127.0.0.1:4040" and req.kv_holder_blocks == 6
    assert req.lora_name == "a1" and req.tenant == "t1"
    assert req.penalty_output_from == 4
    assert req.enqueue_ts == pytest.approx(98.5)

    # resume after a failed handoff that relayed 2 destination tokens
    res = m.to_resume_request([20, 21], now=50.0)
    assert res.token_ids == [1, 2, 3, 4, 10, 11, 12, 20, 21]
    assert res.sampling.max_tokens == 11  # 16 - 5 delivered
    assert res.kv_handoff_seq == "" and res.kv_holder_addr == ""
    # back-dated by age_s: resume must bill from the ORIGINAL submission
    assert res.enqueue_ts == pytest.approx(48.5)


# ---------------- fault knobs (fast) ----------------


def test_fault_plan_parsing_and_determinism(monkeypatch):
    from dynamo_tpu.disagg import faults

    plan = faults.FaultPlan("seq_handoff=drop-part,push=delay-ms:50", seed=3)
    assert plan.should_drop("seq_handoff")
    assert not plan.should_drop("push")
    assert plan.delay_s("push") == pytest.approx(0.05)
    assert plan.delay_s("seq_handoff") == 0.0
    assert not plan.should_corrupt("seq_handoff")

    # '*' fans a rule to every kind
    allp = faults.FaultPlan("*=corrupt-checksum")
    for kind in faults.FAULT_KINDS:
        assert allp.should_corrupt(kind)

    # probabilistic drops are seeded: same seed => same decision sequence
    a = faults.FaultPlan("push=drop-part:0.5", seed=9)
    b = faults.FaultPlan("push=drop-part:0.5", seed=9)
    seq_a = [a.should_drop("push") for _ in range(32)]
    seq_b = [b.should_drop("push") for _ in range(32)]
    assert seq_a == seq_b
    assert any(seq_a) and not all(seq_a)

    with pytest.raises(ValueError):
        faults.FaultPlan("bogus-kind=drop-part")
    with pytest.raises(ValueError):
        faults.FaultPlan("push=explode")
    with pytest.raises(ValueError):
        faults.FaultPlan("push=delay-ms")  # delay needs its arg

    # env resolution: unset => None, set => parsed + cached
    monkeypatch.delenv(faults.ENV_SPEC, raising=False)
    assert faults.active_plan() is None
    monkeypatch.setenv(faults.ENV_SPEC, "prefix_fetch=drop-part")
    p1 = faults.active_plan()
    assert p1 is not None and p1.should_drop("prefix_fetch")
    assert faults.active_plan() is p1  # cached by (spec, seed)


# ---------------- reconnect backoff (fast) ----------------


def test_dataplane_reconnect_backoff_with_jitter(monkeypatch):
    """A refused destination retries MAX_ATTEMPTS times with growing,
    jittered, bounded delays — and the reconnect counter + exposition
    family record it."""
    from dynamo_tpu.disagg import dataplane
    from dynamo_tpu.disagg.dataplane import KvDataPlaneClient

    sleeps = []

    async def body():
        real_sleep = asyncio.sleep

        async def spy_sleep(delay):
            sleeps.append(delay)
            await real_sleep(0)

        monkeypatch.setattr(dataplane.asyncio, "sleep", spy_sleep)
        client = KvDataPlaneClient(lanes=1)
        import numpy as np

        with pytest.raises(OSError):
            await client.send("127.0.0.1:9", "r1", np.zeros(4, np.float32))
        assert client.reconnects == client.MAX_ATTEMPTS - 1
        assert len(sleeps) == client.MAX_ATTEMPTS - 1
        for i, d in enumerate(sleeps):
            base = min(client.BACKOFF_MAX_S, client.BACKOFF_BASE_S * (1 << i))
            assert base * 0.5 <= d <= base  # jittered into [0.5, 1.0]x
        text = client.render_metrics()
        assert "dynamo_kv_stream_reconnects_total 2" in text
        from dynamo_tpu.utils.prometheus import check_exposition

        assert check_exposition(text) == []

    asyncio.run(body())


# ---------------- planner rebalance policy (fast) ----------------


def test_planner_rebalance_policy_sustain_and_cooldown():
    from dynamo_tpu.components.planner import Planner, RebalancePolicy

    planner = Planner(rebalance_policy=RebalancePolicy(
        occupancy_hot=0.8, occupancy_cold=0.5, goodput_floor=0.9,
        sustain=2, cooldown_s=30.0,
    ))

    def workers(hot_occ=0.9, cold_occ=0.2, hot_gp=None, **over):
        hot = {"worker_id": "aa", "occupancy": hot_occ, "goodput": hot_gp,
               "servable": True, "migration": True}
        cold = {"worker_id": "bb", "occupancy": cold_occ, "goodput": 1.0,
                "servable": True, "migration": True}
        hot.update(over.get("hot", {}))
        cold.update(over.get("cold", {}))
        return [hot, cold]

    # sustained-signal gating: the first observation never fires
    assert planner.rebalance(workers(), now=0.0) is None
    d = planner.rebalance(workers(), now=1.0)
    assert d is not None and d.source == "aa" and d.target == "bb"
    assert "occupancy" in d.reason

    # cooldown: an immediate re-trigger is suppressed
    assert planner.rebalance(workers(), now=2.0) is None
    assert planner.rebalance(workers(), now=3.0) is None

    # after cooldown the signal must sustain again
    planner2 = Planner(rebalance_policy=RebalancePolicy(sustain=1, cooldown_s=0.0))
    # goodput burn below the floor triggers even under the occupancy bar
    d2 = planner2.rebalance(workers(hot_occ=0.7, cold_occ=0.3, hot_gp=0.5), now=100.0)
    assert d2 is not None and "goodput" in d2.reason
    # balanced pool: no decision, and the sustain counter resets
    assert planner2.rebalance(workers(hot_occ=0.5, cold_occ=0.45), now=101.0) is None
    # non-migratable or unservable peers are never targets
    ws = workers()
    ws[1]["migration"] = False
    assert planner2.rebalance(ws, now=102.0) is None
    ws = workers()
    ws[1]["servable"] = False
    assert planner2.rebalance(ws, now=103.0) is None


# ---------------- health + router pruning (fast) ----------------


def test_migrating_health_state_is_unservable():
    from dynamo_tpu.utils.health import (
        STATES,
        UNSERVABLE_STATES,
        HealthMonitor,
        is_snapshot_servable,
    )

    assert "migrating" in STATES
    assert "migrating" in UNSERVABLE_STATES
    assert not is_snapshot_servable({"state": "migrating"})
    hm = HealthMonitor("w")
    hm.set_state("ready", "up")
    hm.set_state("draining", "drain")
    hm.set_state("migrating", "handing off")
    assert not hm.is_servable()
    hm.set_state("draining", "pass complete")
    hm.set_state("dead", "gone")
    assert hm.state == "dead"


def test_router_prunes_radix_for_unservable_workers():
    """The radix/fleet caches follow the sequence: a worker that reports
    draining/migrating stops being a prefix holder on the next scrape
    round, without waiting for its instance key to disappear."""
    import time as _time

    from dynamo_tpu.llm.kv_events import KvCacheEvent, StoredBlock
    from dynamo_tpu.llm.kv_router.indexer import RouterEvent
    from dynamo_tpu.llm.kv_router.metrics_aggregator import WorkerView
    from dynamo_tpu.llm.kv_router.router import KvRouter
    from dynamo_tpu.llm.tokens import compute_block_hash_for_seq

    class _Drt:
        cplane = None

    router = KvRouter(_Drt(), "ns", "backend", kv_block_size=4)
    prompt = list(range(1, 13))
    hashes = compute_block_hash_for_seq(prompt, 4)
    blocks, parent = [], None
    for th in hashes:
        bh = th ^ 0xA
        blocks.append(StoredBlock(block_hash=bh, tokens_hash=th, parent_hash=parent))
        parent = bh
    router._on_kv_event({"payload": RouterEvent(
        worker_id=0xA, event=KvCacheEvent.stored(parent_hash=None, blocks=blocks),
    ).to_wire()})
    assert router._find_overlap(prompt).scores.get(0xA) == 3

    view = WorkerView(
        0xA,
        data={"health": {"state": "migrating", "heartbeat_age_s": 0.01}},
        last_seen=_time.monotonic(),
    )
    router.aggregator._workers[0xA] = view
    router._on_loads([])  # the scrape-round hook
    assert router._find_overlap(prompt).scores.get(0xA) is None
    assert 0xA in router._pruned_unservable
    # back to ready: eligible again (blocks re-advertise via kv events)
    view.data["health"]["state"] = "ready"
    router._on_loads([])
    assert 0xA not in router._pruned_unservable


# ---------------- frontend 503 (fast, aiohttp) ----------------


CHAT_BODY = {
    "model": "tiny",
    "messages": [{"role": "user", "content": "hello"}],
    "max_tokens": 4,
}


def test_frontend_retriable_503_while_draining_without_migration():
    """A draining backend with migration disabled answers 503 + Retry-After
    on BOTH the unary and the stream path — and the stream path gets plain
    JSON, never SSE bytes."""
    import aiohttp

    from dynamo_tpu.llm.http.service import HttpService, ModelPipeline

    class _Backend:
        def availability(self):
            return {
                "servable": False, "retriable": True,
                "reason": "engine is draining and live migration is disabled",
                "retry_after_s": 7,
            }

        async def generate(self, pre):  # pragma: no cover - must not be hit
            raise AssertionError("draining backend must not be asked to generate")
            yield

    async def body():
        service = HttpService(host="127.0.0.1", port=0)
        service.manager.add(ModelPipeline("tiny", None, _Backend(), "both"))
        port = await service.start()
        url = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                # unary
                async with s.post(f"{url}/v1/chat/completions", json=CHAT_BODY) as r:
                    assert r.status == 503
                    assert r.headers.get("Retry-After") == "7"
                    assert r.content_type == "application/json"
                    doc = await r.json()
                    assert doc["error"]["code"] == "model_draining"
                # stream=true: still a pre-SSE JSON 503
                async with s.post(
                    f"{url}/v1/chat/completions",
                    json={**CHAT_BODY, "stream": True},
                ) as r:
                    assert r.status == 503
                    assert r.headers.get("Retry-After") == "7"
                    assert r.content_type == "application/json"
                    raw = await r.read()
                    assert not raw.startswith(b"data:")
                    import json as _json

                    assert _json.loads(raw)["error"]["code"] == "model_draining"
        finally:
            await service.stop()

    asyncio.run(body())


def test_backend_availability_draining_vs_migration():
    from dynamo_tpu.llm.backend import Backend
    from dynamo_tpu.utils.health import HealthMonitor

    class _Cfg:
        migration = False

    class _Eng:
        health = HealthMonitor("t")
        config = _Cfg()

    b = Backend(_Eng(), tokenizer=None)
    _Eng.health.set_state("ready", "up")
    assert b.availability()["servable"]
    _Eng.health.set_state("draining", "drain")
    a = b.availability()
    assert not a["servable"] and a["retriable"] and a["retry_after_s"] > 0
    # with migration enabled the engine keeps serving through its drain
    _Cfg.migration = True
    assert b.availability()["servable"]


# ---------------- metrics surfaces (fast) ----------------


def test_migration_metric_families_render_conformantly():
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.page_table import PageAllocator
    from dynamo_tpu.engine.scheduler import Scheduler
    from dynamo_tpu.utils.prometheus import check_exposition

    cfg = EngineConfig(model_id="tiny", page_size=4, num_pages=8, max_seqs=2,
                       prefill_buckets=(16,))
    eng = AsyncJaxEngine(cfg)
    eng.allocator = PageAllocator(cfg.num_pages, cfg.page_size)
    eng.scheduler = Scheduler(cfg, None, eng.allocator)
    eng.runner = None
    eng.scheduler.migration_out = 3
    eng.scheduler.migration_out_failed = 1
    eng.scheduler.migration_in_pulled = 2
    eng.scheduler.migration_in_recomputed = 1
    eng.scheduler.migration_tokens_salvaged = 40
    eng.migration_pause_hist.observe(0.03)
    text = eng.render_stage_metrics()
    assert check_exposition(text) == []
    assert 'dynamo_migration_requests_total{result="ok",role="out"} 3' in text
    assert 'dynamo_migration_requests_total{result="failed",role="out"} 1' in text
    assert 'dynamo_migration_requests_total{result="pulled",role="in"} 2' in text
    assert "dynamo_migration_tokens_salvaged_total 40" in text
    assert "dynamo_migration_pause_seconds_bucket" in text
    snap = eng.resource_snapshot()
    assert snap["migration_out"] == 3
    assert snap["migration_tokens_salvaged"] == 40


def test_dynotop_migration_column():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "dynotop", Path(__file__).resolve().parent.parent / "tools" / "dynotop.py"
    )
    dynotop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(dynotop)

    doc = {
        "namespace": "ns", "component": "backend", "summary": {"workers": 1},
        "workers": [{
            "worker_id": "ab", "last_seen_s": 0.1, "missed_scrapes": 0,
            "health": {"state": "migrating", "heartbeat_age_s": 0.01},
            "kv_metrics": {"request_active_slots": 1, "request_total_slots": 4,
                           "kv_active_blocks": 1, "kv_total_blocks": 10},
            "resources": {"migration_out": 3, "migration_in": 1,
                          "migration_out_failed": 1},
        }],
    }
    text = dynotop.render_status(doc)
    assert "MIG" in text
    assert "3>1!1" in text  # out>in with failed flag
    assert "migrating" in text
    doc["workers"][0]["resources"] = {}
    assert "3>1" not in dynotop.render_status(doc)  # pre-plane workers: "-"


# ---------------- two-engine loopback: migrate mid-decode ----------------


@pytest.mark.parametrize(
    "temp,seed", [(0.0, None), (0.8, 11)], ids=["greedy", "seeded"]
)
def test_migrate_mid_decode_token_parity(temp, seed):
    """The acceptance bar: a sequence migrated mid-decode finishes with
    tokens byte-identical to an unmigrated run, the committed KV arrives
    over the seq_handoff pull (no recompute), and the source frees its
    slot without emitting a finish of its own."""

    async def body():
        base = _engine()
        await base.start()
        src = _engine()
        await src.start()
        dst = _engine()
        await dst.start()
        srv = None
        try:
            srv = await _wire_pair(src, dst)
            expected, finish = await _collect(
                base, _req("b1", temp=temp, seed=seed)
            )
            assert finish == "length" and len(expected) == 32
            # warm the destination's executables so the pause measures the
            # handoff, not a cold XLA compile
            await _collect(dst, _req("warm", n=4))

            task = asyncio.ensure_future(
                _collect(src, _req("m1", temp=temp, seed=seed))
            )
            assert await _wait_generated(src, "m1", 8)
            res = await src.migrate_out("m1", dst.adopt_migrated)
            assert res["status"] == "ok", res
            assert res["kv_blocks"] >= 6  # committed history shipped
            got, finish = await task
            assert finish == "length"
            assert got == expected, f"migrated {got} != baseline {expected}"

            ssched, dsched = src.scheduler, dst.scheduler
            assert ssched.migration_out == 1
            assert ssched.migration_out_failed == 0
            assert ssched.num_running == 0  # source slot + pages released
            assert src.allocator.active_pages == 0
            assert dsched.migration_in == 1
            assert dsched.migration_in_pulled == 1  # KV pulled, not recomputed
            assert dsched.migration_in_recomputed == 0
            assert dsched.migration_tokens_salvaged > 0
            assert srv.handoffs_served == 1
            assert src.migration_pause_hist.count == 1
        finally:
            if srv is not None:
                await srv.stop()
            await base.shutdown()
            await src.shutdown()
            await dst.shutdown()

    asyncio.run(body())


@pytest.mark.slow
def test_migrate_spec_draft_lane_token_parity():
    """A draft-model speculative sequence migrates mid-decode: the
    destination rebuilds the draft cache from the authoritative history at
    its first spec round and the continuation stays token-identical."""

    async def body():
        over = dict(speculative="draft:tiny:2", num_pages=128)
        base = _engine(**over)
        await base.start()
        src = _engine(**over)
        await src.start()
        dst = _engine(**over)
        await dst.start()
        srv = None
        try:
            srv = await _wire_pair(src, dst)
            expected, _ = await _collect(base, _req("b1", n=24))
            await _collect(dst, _req("warm", n=4))
            task = asyncio.ensure_future(_collect(src, _req("m1", n=24)))
            assert await _wait_generated(src, "m1", 6)
            res = await src.migrate_out("m1", dst.adopt_migrated)
            assert res["status"] == "ok", res
            got, finish = await task
            assert finish == "length"
            assert got == expected, f"spec-draft migrated {got} != {expected}"
            assert dst.scheduler.migration_in_pulled == 1
        finally:
            if srv is not None:
                await srv.stop()
            await base.shutdown()
            await src.shutdown()
            await dst.shutdown()

    asyncio.run(body())


@pytest.mark.slow
def test_migrate_lora_lane_token_parity():
    """A LoRA-bound sequence migrates: the manifest carries the adapter
    binding, the destination pins its own slot at admission, and the salted
    block identity lines up so the handoff pull still lands."""

    async def body():
        over = dict(lora_adapters=("a1",), max_loras=2, num_pages=128)
        base = _engine(**over)
        await base.start()
        src = _engine(**over)
        await src.start()
        dst = _engine(**over)
        await dst.start()
        srv = None
        try:
            srv = await _wire_pair(src, dst)
            expected, _ = await _collect(base, _req("b1", n=24, lora="a1"))
            expected_base, _ = await _collect(base, _req("b2", n=24))
            assert expected != expected_base  # the adapter actually bites
            await _collect(dst, _req("warm", n=4, lora="a1"))
            task = asyncio.ensure_future(_collect(src, _req("m1", n=24, lora="a1")))
            assert await _wait_generated(src, "m1", 6)
            res = await src.migrate_out("m1", dst.adopt_migrated)
            assert res["status"] == "ok", res
            got, finish = await task
            assert finish == "length"
            assert got == expected, f"LoRA migrated {got} != {expected}"
            assert dst.scheduler.migration_in_pulled == 1
        finally:
            if srv is not None:
                await srv.stop()
            await base.shutdown()
            await src.shutdown()
            await dst.shutdown()

    asyncio.run(body())


# ---------------- failure ladder ----------------


def test_failure_ladder_pull_faults_degrade_to_recompute(monkeypatch):
    """Injected handoff-pull faults (part drop => timeout; corrupt
    checksum => integrity reject) both degrade the ADOPTION to chunked
    recompute from history — final tokens identical, no request error, no
    hang past the deadline belt."""

    async def body():
        base = _engine()
        await base.start()
        src = _engine()
        await src.start()
        dst = _engine(migration_timeout_s=1.0)
        await dst.start()
        srv = None
        try:
            srv = await _wire_pair(src, dst, timeout_s=30.0)
            await _collect(dst, _req("warm", n=4))

            arms = [
                ("seq_handoff=drop-part", "timeout"),
                ("seq_handoff=corrupt-checksum", "error"),
            ]
            for i, (fault, _expected_mode) in enumerate(arms):
                prompt = [(i * 131 + j * 7) % 400 + 1 for j in range(24)]
                expected, _ = await _collect(base, _req(f"b{i}", prompt, n=24))
                monkeypatch.setenv("DYNTPU_FAULT_DATAPLANE", fault)
                try:
                    rid = f"m{i}"
                    task = asyncio.ensure_future(
                        _collect(src, _req(rid, prompt, n=24))
                    )
                    assert await _wait_generated(src, rid, 6)
                    t0 = time.monotonic()
                    res = await src.migrate_out(rid, dst.adopt_migrated)
                    # the handoff itself still succeeds — only the KV pull
                    # degraded to recompute on the destination
                    assert res["status"] == "ok", (fault, res)
                    got, finish = await task
                    assert finish == "length"
                    assert got == expected, (fault, got, expected)
                    assert time.monotonic() - t0 < 30.0  # belt held
                finally:
                    monkeypatch.delenv("DYNTPU_FAULT_DATAPLANE", raising=False)
            dsched = dst.scheduler
            assert dsched.migration_in == 2
            assert dsched.migration_in_recomputed == 2
            assert dsched.migration_in_pulled == 0
        finally:
            if srv is not None:
                await srv.stop()
            await base.shutdown()
            await src.shutdown()
            await dst.shutdown()

    asyncio.run(body())


def test_failure_ladder_dest_death_and_double_migration():
    """Destination dies before the first continuation token -> the source
    un-freezes and finishes locally; destination dies mid-stream -> the
    source resumes from history + relayed tokens; a concurrent second
    migrate_out of the same sequence is refused. Tokens identical in every
    arm."""

    async def body():
        base = _engine()
        await base.start()
        src = _engine()
        await src.start()
        srv = None
        try:
            # arm 1: adopter dies before yielding anything
            expected, _ = await _collect(base, _req("b1"))

            async def dead_adopter(manifest):
                raise ConnectionError("destination gone")
                yield  # pragma: no cover

            task = asyncio.ensure_future(_collect(src, _req("m1")))
            assert await _wait_generated(src, "m1", 8)
            res = await src.migrate_out("m1", dead_adopter)
            assert res["status"] == "failed"
            got, finish = await task
            assert finish == "length" and got == expected
            assert src.scheduler.migration_out_failed == 1
            assert src.scheduler.migration_out == 0

            # arm 2: adopter yields 2 continuation tokens, then dies — the
            # relayed tokens must NOT be re-emitted by the local resume
            prompt2 = [(j * 13 + 5) % 400 + 1 for j in range(24)]
            expected2, _ = await _collect(base, _req("b2", prompt2))

            dst = _engine()
            await dst.start()
            srv = await _wire_pair(src, dst)

            async def flaky_adopter(manifest):
                n = 0
                async for out in dst.adopt_migrated(manifest):
                    yield out
                    n += 1 if out.token is not None else 0
                    if n >= 2:
                        raise ConnectionError("destination crashed mid-stream")

            task = asyncio.ensure_future(_collect(src, _req("m2", prompt2)))
            assert await _wait_generated(src, "m2", 8)
            res = await src.migrate_out("m2", flaky_adopter)
            assert res["status"] == "resumed"
            assert res["tokens_relayed"] == 2
            got2, finish2 = await task
            assert finish2 == "length"
            assert got2 == expected2, f"resumed {got2} != baseline {expected2}"
            assert src.scheduler.migration_out_failed == 2

            # arm 3: double-migration race — two concurrent migrate_out
            # calls; exactly one snapshot wins, the other is skipped
            prompt3 = [(j * 29 + 3) % 400 + 1 for j in range(24)]
            expected3, _ = await _collect(base, _req("b3", prompt3))
            await _collect(dst, _req("warm", n=4))
            task = asyncio.ensure_future(_collect(src, _req("m3", prompt3)))
            assert await _wait_generated(src, "m3", 8)
            r1, r2 = await asyncio.gather(
                src.migrate_out("m3", dst.adopt_migrated),
                src.migrate_out("m3", dst.adopt_migrated),
            )
            statuses = sorted([r1["status"], r2["status"]])
            assert statuses == ["ok", "skipped"], (r1, r2)
            got3, finish3 = await task
            assert finish3 == "length" and got3 == expected3
        finally:
            if srv is not None:
                await srv.stop()
                await dst.shutdown()
            await base.shutdown()
            await src.shutdown()

    asyncio.run(body())


def test_failure_ladder_source_death_after_manifest():
    """The source vanishes right after shipping the manifest (pull server
    down): the destination's seq_handoff pull fails fast and the adoption
    recomputes the whole history — the continuation completes with the
    exact baseline tokens."""

    async def body():
        base = _engine()
        await base.start()
        src = _engine()
        await src.start()
        dst = _engine(migration_timeout_s=2.0)
        await dst.start()
        srv = None
        try:
            srv = await _wire_pair(src, dst)
            expected, _ = await _collect(base, _req("b1"))
            await _collect(dst, _req("warm", n=4))

            task = asyncio.ensure_future(_collect(src, _req("m1")))
            assert await _wait_generated(src, "m1", 8)
            manifest = await src.run_on_engine(
                lambda: src.sync_snapshot_for_migration("m1")
            )
            assert manifest is not None and manifest.kv_blocks > 0
            k = len(manifest.generated)
            # the source dies: its pull server goes away mid-handoff
            await srv.stop()
            srv = None
            cont = [
                out async for out in dst.adopt_migrated(manifest)
            ]
            cont_toks = [o.token for o in cont if o.token is not None]
            assert cont_toks == expected[k:], "recompute continuation diverged"
            assert cont[-1].finished and cont[-1].finish_reason == "length"
            assert dst.scheduler.migration_in_recomputed == 1
            assert dst.scheduler.migration_in_pulled == 0
            # local cleanup: the frozen source sequence resumes on abort
            await src.run_on_engine(lambda: src.sync_abort_migration("m1"))
            got, finish = await task
            assert finish == "length" and got == expected
        finally:
            if srv is not None:
                await srv.stop()
            await base.shutdown()
            await src.shutdown()
            await dst.shutdown()

    asyncio.run(body())


# ---------------- rolling restart under replay load ----------------


@pytest.mark.slow
def test_rolling_restart_replay_goodput():
    """bursty_chat replay against a worker that drains mid-run: every live
    sequence migrates to the peer, the streams keep flowing, zero request
    errors — and goodput stays within budget of the no-restart baseline."""
    from dynamo_tpu.loadgen.scenarios import load_scenario
    from dynamo_tpu.loadgen.trace import compile_trace

    async def body():
        from dynamo_tpu.loadgen.replay import replay_engine

        spec = load_scenario("bursty_chat", num_requests=8).replace(
            isl_max=48, osl_dist="fixed", osl_mean=12, osl_max=12,
            rate_rps=24.0, slo_ttft_ms=60000.0, slo_itl_ms=60000.0,
        )
        trace = compile_trace(spec)

        base = _engine(max_seqs=4, num_pages=192, max_model_len=128)
        await base.start()
        src = _engine(max_seqs=4, num_pages=192, max_model_len=128)
        await src.start()
        dst = _engine(max_seqs=4, num_pages=192, max_model_len=128)
        await dst.start()
        srv = None
        try:
            srv = await _wire_pair(src, dst)
            warm = compile_trace(spec.replace(seed=99, num_requests=2))
            await replay_engine(base, warm, spec=spec, speed=100.0)
            await replay_engine(src, warm, spec=spec, speed=100.0)
            await replay_engine(dst, warm, spec=spec, speed=100.0)

            baseline = await replay_engine(base, trace, spec=spec, speed=4.0)
            assert baseline["errors"] == 0

            # rolling restart: a drainer migrates every mid-decode sequence
            # off the source while the replay keeps submitting to it
            stop = asyncio.Event()

            async def drainer():
                while not stop.is_set():
                    rids = [
                        s.req.request_id for s in src.scheduler.slots
                        if s is not None and not s.finished and not s.migrating
                        and s.prefill_pos is None and len(s.generated) >= 4
                    ]
                    for rid in rids:
                        await src.migrate_out(rid, dst.adopt_migrated)
                    await asyncio.sleep(0.02)

            drain_task = asyncio.ensure_future(drainer())
            try:
                restarted = await replay_engine(src, trace, spec=spec, speed=4.0)
            finally:
                stop.set()
                await drain_task
            assert restarted["errors"] == 0, restarted
            assert src.scheduler.migration_out >= 1  # sequences really moved
            assert dst.scheduler.migration_in >= 1
            # goodput within budget of the uninterrupted baseline (one
            # request's worth of slack on the 8-request CPU smoke)
            assert restarted["goodput"] >= baseline["goodput"] - 0.125, (
                restarted["goodput"], baseline["goodput"],
            )
        finally:
            if srv is not None:
                await srv.stop()
            await base.shutdown()
            await src.shutdown()
            await dst.shutdown()

    asyncio.run(body())


# ---------------- multimodal rejection ----------------


def test_multimodal_sequence_migration_rejected():
    """A VL sequence is REJECTED with a structured error instead of silently
    migrating without its vision context: mm_embeds do not ride the manifest
    (the destination would re-prefill the virtual token ids with no image
    behind them and produce garbage). The rejection happens before the
    sequence is frozen, so it keeps decoding locally to completion."""
    import numpy as np

    from dynamo_tpu.llm.multimodal import (
        ImageInput, image_content_hash, patchify, virtual_token_ids,
    )

    def mm_req(engine, rid, img, n=96):
        cfg = engine.model.config
        patches, rows, cols, grid = patchify(
            img, cfg.vision.patch_size, cfg.vision.spatial_merge_size
        )
        n_tok = patches.shape[0] // cfg.vision.spatial_merge_size**2
        chash = image_content_hash(img)
        toks = [1, 2] + virtual_token_ids(chash, n_tok, cfg.vocab_size) + [3]
        im = ImageInput(
            offset=2, patches=patches, rows=rows, cols=cols, grid=grid,
            num_tokens=n_tok, content_hash=chash,
        )
        return EngineRequest(
            request_id=rid, token_ids=toks,
            sampling=SamplingParams(temperature=0.0, max_tokens=n,
                                    ignore_eos=True),
            images=[im],
        )

    async def body():
        from dynamo_tpu.engine.config import EngineConfig
        from dynamo_tpu.engine.engine import AsyncJaxEngine

        cfg = EngineConfig(
            model_id="tiny-vl", page_size=4, num_pages=128, max_seqs=4,
            max_model_len=256, prefill_buckets=(32, 64, 128),
        )
        src = AsyncJaxEngine(cfg)
        await src.start()
        try:
            img = np.random.default_rng(7).random((24, 16, 3)).astype(np.float32)
            expected, _ = await _collect(src, mm_req(src, "base", img))

            async def never_adopt(manifest):
                raise AssertionError("a multimodal sequence reached adoption")
                yield  # pragma: no cover

            task = asyncio.ensure_future(_collect(src, mm_req(src, "m1", img)))
            assert await _wait_generated(src, "m1", 6)
            res = await src.migrate_out("m1", never_adopt)
            assert res["status"] == "rejected"
            assert res["reason"] == "multimodal_sequence"
            assert "mm_embeds" in res["detail"]
            # not frozen: the sequence finishes locally, token-identical
            got, finish = await task
            assert finish == "length"
            assert got == expected
        finally:
            await src.shutdown()

    asyncio.run(body())
