"""PageAllocator: prefix cache, refcounting, LRU reuse, KV events."""

import pytest

from dynamo_tpu.engine.page_table import PageAllocator
from dynamo_tpu.llm.tokens import TokenSequence

PS = 4


def make(num_pages=8, events=None):
    sink = events.append if events is not None else None
    return PageAllocator(num_pages, PS, event_sink=sink)


def test_basic_allocation_and_free():
    a = make()
    cached, st = a.allocate_sequence("s1", list(range(10)))  # 3 pages
    assert cached == 0
    assert len(st.pages) == 3
    assert 0 not in st.pages  # null page never allocated
    assert a.active_pages == 3
    a.commit_prefilled("s1", 10)
    a.free_sequence("s1")
    assert a.active_pages == 0
    # 2 full blocks stay cached (reusable), 1 partial page freed
    assert a.free_pages == 7


def test_prefix_cache_hit_and_sharing():
    events = []
    a = make(events=events)
    prompt = list(range(8))  # 2 full blocks
    a.allocate_sequence("s1", prompt + [99, 98])
    a.commit_prefilled("s1", 10)
    stored = [e for e in events if e.kind == "stored"]
    assert len(stored) == 2  # two full blocks registered

    # second sequence with the same 8-token prefix
    cached, st2 = a.allocate_sequence("s2", prompt + [55, 44, 33, 22, 11])
    assert cached == 8
    st1 = a._seqs["s1"]
    assert st2.pages[:2] == st1.pages[:2]  # physical sharing
    assert a._refcount[st1.pages[0]] == 2

    a.free_sequence("s1")
    # shared pages still referenced by s2
    assert a._refcount[st2.pages[0]] == 1
    a.free_sequence("s2")


def test_full_prompt_cache_hit_leaves_one_block_to_prefill():
    a = make()
    prompt = list(range(8))
    a.allocate_sequence("s1", prompt)
    a.commit_prefilled("s1", 8)
    a.free_sequence("s1")
    cached, st = a.allocate_sequence("s2", prompt)
    assert cached == 4  # not 8: last block must be prefilled for logits


def test_lru_eviction_emits_removed():
    events = []
    a = make(num_pages=6, events=events)  # 5 usable pages
    a.allocate_sequence("s1", list(range(8)))  # 2 pages, both full blocks
    a.commit_prefilled("s1", 8)
    a.free_sequence("s1")  # both pages now reusable
    assert a.free_pages == 5

    # allocating 5 pages forces reclaim of the cached blocks (LRU order);
    # the batched reclaim may coalesce them into one removed event, so the
    # contract is the set of advertised hashes, not the event count
    a.allocate_sequence("s2", list(range(100, 120)))  # 5 pages
    removed_hashes = [
        h for e in events if e.kind == "removed" for h in e.block_hashes
    ]
    assert len(removed_hashes) == 2
    assert a.free_pages == 0

    with pytest.raises(MemoryError):
        a.allocate_sequence("s3", [1, 2, 3, 4])


def test_decode_block_completion_registers_one_token_late():
    events = []
    a = make(events=events)
    a.allocate_sequence("s1", [1, 2, 3])  # partial block
    a.commit_prefilled("s1", 3)
    assert not [e for e in events if e.kind == "stored"]
    # completing block 0 must NOT register it yet: the block's last row's
    # KV is only written once token 4 is FED, which the appearance of token
    # 5 proves — registering at fill time advertised a block whose final
    # position read garbage to any sequence extending past it
    a.append_token("s1", 4)
    assert not [e for e in events if e.kind == "stored"]
    a.append_token("s1", 5)
    stored = [e for e in events if e.kind == "stored"]
    assert len(stored) == 1
    ts = TokenSequence([1, 2, 3, 4], PS)
    assert stored[0].blocks[0].block_hash == ts.blocks[0].sequence_hash


def test_ensure_capacity_grows_and_fails():
    a = make(num_pages=4)  # 3 usable
    a.allocate_sequence("s1", [1, 2, 3, 4])
    assert a.ensure_capacity("s1", 12)  # 3 pages
    assert not a.ensure_capacity("s1", 13)  # would need a 4th


def test_oom_rollback_restores_state():
    a = make(num_pages=4)
    with pytest.raises(MemoryError):
        a.allocate_sequence("big", list(range(100)))
    assert a.free_pages == 3
    assert "big" not in a._seqs
