"""SDK: decorators/config/graph discovery + a full `serve` supervisor run of
the aggregated graph (subprocess-per-service), hit over HTTP.

Mirrors the reference SDK tests + dynamo serve flow (reference: deploy/dynamo/
sdk/src/dynamo/sdk/tests/, cli/serving.py)."""

import json
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from dynamo_tpu.sdk.config import ServiceConfig
from dynamo_tpu.sdk.decorators import async_on_start, endpoint, service
from dynamo_tpu.sdk.dependency import depends
from dynamo_tpu.sdk.serve import discover_graph


def test_decorators_and_graph_discovery():
    @service(namespace="t", component="a")
    class A:
        @endpoint
        async def gen(self, req):
            yield req

        @async_on_start
        async def boot(self):
            pass

    @service(namespace="t", component="b")
    class B:
        a = depends(A)

    @service(namespace="t", component="c")
    class C:
        b = depends(B)
        a = depends(A)

    assert A.__dynamo_service__.component == "a"
    assert "gen" in A.__dynamo_endpoints__
    assert A.__dynamo_on_start__ == ["boot"]
    assert discover_graph(C) == [A, B, C]

    # subclass keeps inherited endpoints/hooks and can override depends
    @service(namespace="t", component="a2")
    class A2(A):
        pass

    assert "gen" in A2.__dynamo_endpoints__
    assert A2.__dynamo_on_start__ == ["boot"]


def test_service_config_layers(tmp_path):
    yaml_file = tmp_path / "conf.yaml"
    yaml_file.write_text("Worker:\n  model: llama\n  port: 8000\n")
    data = ServiceConfig.from_yaml_and_overrides(
        str(yaml_file), ["--Worker.port=9000", "--Frontend.host=0.0.0.0"]
    )
    assert data["Worker"]["model"] == "llama"
    assert data["Worker"]["port"] == 9000
    assert data["Frontend"]["host"] == "0.0.0.0"
    with pytest.raises(ValueError):
        ServiceConfig.from_yaml_and_overrides(None, ["badoverride"])


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_serve_supervisor_agg_graph(tmp_path):
    http_port = _free_port()
    cplane_port = _free_port()
    conf = tmp_path / "agg.yaml"
    conf.write_text(
        f"Frontend:\n  model: tiny\n  host: 127.0.0.1\n  port: {http_port}\n"
        "Processor:\n  routing: kv\n  kv_block_size: 4\n"
        "TpuWorker:\n  model: tiny\n"
    )
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "dynamo_tpu.sdk.serve",
            "examples.graphs.agg:Frontend",
            "-f", str(conf),
            "--cplane", f"127.0.0.1:{cplane_port}",
            "--no-restart",
        ],
        cwd="/root/repo",
    )
    try:
        body = json.dumps(
            {
                "model": "tiny",
                "messages": [{"role": "user", "content": "hello graph"}],
                "max_tokens": 4,
                "temperature": 0,
            }
        ).encode()
        deadline = time.time() + 120
        last_err = None
        while time.time() < deadline:
            if proc.poll() is not None:
                pytest.fail(f"supervisor died rc={proc.returncode}")
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{http_port}/v1/chat/completions",
                    data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    result = json.loads(resp.read())
                assert result["choices"][0]["finish_reason"] in ("stop", "length")
                assert result["usage"]["completion_tokens"] == 4
                return
            except Exception as e:  # noqa: PERF203 — polling until ready
                last_err = e
                time.sleep(1.0)
        pytest.fail(f"graph never became ready: {last_err}")
    finally:
        proc.terminate()
        try:
            proc.wait(15)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_build_mesh_axes():

    from dynamo_tpu.parallel.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(tp=2, dp=2, sp=1, ep=2))
    assert mesh.axis_names == ("dp", "pp", "sp", "ep", "tp")
    assert mesh.devices.shape == (2, 1, 1, 2, 2)
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(tp=16))


def test_resource_allocator_whole_chips():
    from dynamo_tpu.sdk.allocator import ResourceAllocator
    from dynamo_tpu.sdk.decorators import ServiceMeta

    alloc = ResourceAllocator(total_chips=4)
    meta = ServiceMeta(workers=2, resources={"tpu": 1})
    n, envs = alloc.get_worker_env(meta, {})
    assert n == 2
    assert envs[0]["TPU_VISIBLE_DEVICES"] == "0"
    assert envs[1]["TPU_VISIBLE_DEVICES"] == "1"
    # a second service gets the remaining chips, disjoint from the first
    n, envs = alloc.get_worker_env(ServiceMeta(workers=1, resources={"tpu": 2}), {})
    assert envs[0]["TPU_VISIBLE_DEVICES"] == "2,3"


def test_resource_allocator_fractional_shares_chip():
    from dynamo_tpu.sdk.allocator import ResourceAllocator
    from dynamo_tpu.sdk.decorators import ServiceMeta

    alloc = ResourceAllocator(total_chips=2)
    meta = ServiceMeta(workers=2, resources={"tpu": 0.5})
    _, envs = alloc.get_worker_env(meta, {})
    # both half-chip workers co-locate on chip 0
    assert envs[0]["TPU_VISIBLE_DEVICES"] == envs[1]["TPU_VISIBLE_DEVICES"] == "0"


def test_resource_allocator_cpu_service_pinned_off_tpu():
    from dynamo_tpu.sdk.allocator import ResourceAllocator
    from dynamo_tpu.sdk.decorators import ServiceMeta

    alloc = ResourceAllocator(total_chips=4)
    _, envs = alloc.get_worker_env(ServiceMeta(workers=1), {})
    assert envs[0] == {"JAX_PLATFORMS": "cpu"}
    # YAML config overrides meta resources/workers
    n, envs = alloc.get_worker_env(
        ServiceMeta(workers=1), {"workers": 3, "resources": {"tpu": 1}}
    )
    assert n == 3
    assert len({e["TPU_VISIBLE_DEVICES"] for e in envs}) == 3


def test_resource_allocator_overcommit_warns():
    import warnings as _w

    from dynamo_tpu.sdk.allocator import ResourceAllocator
    from dynamo_tpu.sdk.decorators import ServiceMeta

    alloc = ResourceAllocator(total_chips=1)
    with _w.catch_warnings(record=True) as caught:
        _w.simplefilter("always")
        _, envs = alloc.get_worker_env(ServiceMeta(workers=2, resources={"tpu": 1}), {})
    assert any(issubclass(c.category, ResourceWarning) for c in caught)
    assert len(envs) == 2
