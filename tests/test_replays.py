"""Replay-based protocol tests: recorded SSE streams decoded and aggregated
into stable snapshots (the reference's replay tier, reference: lib/llm/tests/
data/replays + tests/aggregators.rs insta snapshots)."""

import asyncio
import json
from pathlib import Path

from dynamo_tpu.llm.protocols.aggregator import aggregate_chat_stream
from dynamo_tpu.llm.protocols.sse import SseDecoder

DATA = Path(__file__).parent / "data" / "replays"


def replay_chunks(name: str):
    """Parse a recorded SSE byte stream into chunk dicts."""
    raw = (DATA / name).read_bytes()
    dec = SseDecoder()
    chunks = []
    for msg in dec.feed(raw):
        if msg.is_done:
            break
        if msg.data:
            chunks.append(json.loads(msg.data))
    return chunks


def test_recorded_stream_aggregates_to_snapshot():
    chunks = replay_chunks("chat_stream_basic.sse")

    async def gen():
        for c in chunks:
            yield c

    out = asyncio.run(aggregate_chat_stream(gen()))
    snapshot = json.loads((DATA / "chat_stream_basic.expected.json").read_text())
    assert out == snapshot


def test_recorded_stream_handles_comments_and_split_frames():
    raw = (DATA / "chat_stream_basic.sse").read_bytes()
    dec = SseDecoder()
    msgs = []
    # feed one byte at a time — decoder must be fully incremental
    for i in range(len(raw)):
        msgs.extend(dec.feed(raw[i : i + 1]))
    datas = [m for m in msgs if m.data and not m.is_done]
    comments = [c for m in msgs for c in m.comments]
    assert len(datas) == 4
    assert any("keepalive" in c for c in comments)
