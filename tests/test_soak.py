"""Bounded soak: many concurrent mixed requests with cancellations and page
pressure through the async engine (reference: lib/runtime/tests/soak.rs runs a
long-haul variant manually; this keeps a CI-sized slice of it)."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest


@pytest.fixture(scope="module")
def soak_engine():
    cfg = EngineConfig(
        model_id="tiny",
        page_size=4,
        num_pages=48,  # tight: forces admission waits + preemptions under load
        max_seqs=4,
        max_model_len=64,
        prefill_buckets=(8, 16, 32),
        decode_steps=4,
        pipeline_depth=2,
    )
    engine = AsyncJaxEngine(cfg)
    loop = asyncio.new_event_loop()
    loop.run_until_complete(engine.start())
    yield engine, loop
    loop.run_until_complete(engine.shutdown())
    loop.close()


def test_soak_mixed_load_with_cancels(soak_engine):
    """60 concurrent requests with mixed prompt/output lengths, a third
    cancelled mid-stream: everything terminates, no stuck streams, and the
    engine serves a clean request afterwards."""
    engine, loop = soak_engine
    rng = np.random.default_rng(0)

    async def one(i: int):
        prompt_len = int(rng.integers(3, 40))
        max_tokens = int(rng.integers(1, 24))
        cancel_after = int(rng.integers(1, 6)) if i % 3 == 0 else None
        req = EngineRequest(
            request_id=f"soak-{i}",
            token_ids=rng.integers(1, 250, prompt_len).tolist(),
            sampling=SamplingParams(
                temperature=float(rng.choice([0.0, 0.8])),
                max_tokens=max_tokens,
                ignore_eos=True,
            ),
        )
        got = 0
        finished = False
        async for out in engine.generate(req):
            if out.token is not None:
                got += 1
            if out.finished:
                finished = True
                assert out.finish_reason in ("length", "stop", "error")
            if cancel_after is not None and got >= cancel_after:
                break  # client walks away mid-stream -> engine must cancel
        if cancel_after is None:
            assert finished and got == max_tokens
        return got

    async def run_all():
        return await asyncio.gather(*[one(i) for i in range(60)])

    results = loop.run_until_complete(asyncio.wait_for(run_all(), timeout=600))
    assert len(results) == 60

    async def settle():
        # all slots/pages must drain back (cancels included)
        for _ in range(200):
            m = engine.metrics()
            if m.request_active_slots == 0 and m.num_requests_waiting == 0:
                return m
            await asyncio.sleep(0.05)
        return engine.metrics()

    m = loop.run_until_complete(settle())
    assert m.request_active_slots == 0
    assert m.num_requests_waiting == 0

    # engine still healthy: a clean greedy request completes exactly
    async def clean():
        req = EngineRequest(
            request_id="soak-final",
            token_ids=[5, 9, 2],
            sampling=SamplingParams(temperature=0.0, max_tokens=5, ignore_eos=True),
        )
        toks = [o.token async for o in engine.generate(req) if o.token is not None]
        return toks

    assert len(loop.run_until_complete(clean())) == 5
