"""Flight recorder (utils/events.py): journal semantics, forensic captures,
burn-rate alerting, fleet merge, and the conformance pins.

Tier-1, CPU, fast: everything here is pure-Python journal/tracker work plus
one scheduler built without a runner (the prometheus --check idiom). The
replay-driven e2e (shed + migrated request chains over a real socket) lives
in test_events_e2e.py under the slow marker.
"""

import ast
import json
from pathlib import Path

import pytest

from dynamo_tpu.utils import events as events_mod
from dynamo_tpu.utils.events import (
    CAPTURE_EVENTS,
    DECLARED_EVENT_KINDS,
    EventJournal,
    merge_recent,
)
from dynamo_tpu.utils.prometheus import check_exposition
from dynamo_tpu.utils.slo import SloTracker

ROOT = Path(__file__).resolve().parent.parent


# ---------------- journal semantics ----------------


def test_emit_assigns_causal_seq_and_bounded_ring():
    j = EventJournal(capacity=8)
    for i in range(20):
        j.emit("request.enqueued", request_id=f"r{i}")
    snap = j.snapshot(limit=100)
    assert snap["emitted"] == 20
    assert len(snap["recent"]) == 8  # ring bound, oldest evicted
    seqs = [e["seq"] for e in snap["recent"]]
    assert seqs == sorted(seqs) and seqs[-1] == 19


def test_undeclared_kind_raises():
    j = EventJournal()
    with pytest.raises(ValueError, match="undeclared event kind"):
        j.emit("sched.admited")  # typo must fail loudly, not journal garbage


def test_explicit_ids_win_over_ambient_context():
    from dynamo_tpu.runtime.context import new_context, use_context

    j = EventJournal()
    ctx = new_context(request_id="ambient-r")
    ctx.ensure_trace_id()
    with use_context(ctx):
        amb = j.emit("qos.admitted", tenant="t1")
        exp = j.emit("sched.admitted", request_id="explicit-r")
    assert amb.request_id == "ambient-r"
    assert amb.trace_id  # stamped from the context
    assert exp.request_id == "explicit-r"
    assert exp.trace_id == "explicit-r"  # falls back to the request id


def test_pin_survives_ring_eviction_and_is_idempotent():
    j = EventJournal(capacity=4, capture_capacity=2)
    j.emit("request.enqueued", request_id="slow-1")
    j.emit("request.first_token", request_id="slow-1")
    assert j.pin("slow-1", "ttft_over_budget") is True
    assert j.pin("slow-1", "error") is False  # first reason wins
    assert j.capture_reason("slow-1") == "ttft_over_budget"
    # flood the ring: the live entries evict, the capture does not
    for i in range(16):
        j.emit("request.enqueued", request_id=f"noise-{i}")
    tl = j.timeline("slow-1")
    assert tl["found"] and tl["pinned"] == "ttft_over_budget"
    assert [e["kind"] for e in tl["events"]] == [
        "request.enqueued", "request.first_token",
    ]
    # LRU bound: two more captures push the oldest out
    assert j.pin("noise-14", "error") and j.pin("noise-15", "error")
    assert j.capture_reason("slow-1") is None
    assert j.pinned_total == 3


def test_capture_is_bounded_per_request():
    j = EventJournal(capacity=2048)
    for _ in range(CAPTURE_EVENTS + 50):
        j.emit("request.first_token", request_id="chatty")
    j.pin("chatty", "itl_over_budget")
    for i in range(3000):  # evict the ring so only the capture answers
        j.emit("request.enqueued", request_id=f"n{i}")
    tl = j.timeline("chatty")
    assert len(tl["events"]) == CAPTURE_EVENTS


def test_timeline_durations_are_causal():
    t = {"now": 100.0}
    j = EventJournal(clock=lambda: t["now"])
    j.emit("request.enqueued", request_id="r1")
    t["now"] = 100.25
    j.emit("sched.admitted", request_id="r1", slot=0)
    t["now"] = 100.3
    j.emit("request.first_token", request_id="r1")
    tl = j.timeline("r1")
    assert [e["dt_ms"] for e in tl["events"]] == [0.0, 250.0, 50.0]
    assert tl["span_ms"] == 300.0
    assert tl["pinned"] is None
    assert j.timeline("ghost")["found"] is False


def test_merge_recent_orders_across_workers():
    a, b = EventJournal(), EventJournal()
    clock = {"now": 0.0}
    a._clock = b._clock = lambda: clock["now"]
    clock["now"] = 1.0
    a.emit("request.enqueued", request_id="ra")
    clock["now"] = 2.0
    b.emit("request.enqueued", request_id="rb")
    clock["now"] = 3.0
    a.emit("request.finished", request_id="ra")
    merged = merge_recent([
        ("worker-a", a.snapshot()), ("worker-b", b.snapshot()),
    ])
    assert [e["worker_id"] for e in merged] == ["worker-a", "worker-b", "worker-a"]
    assert merge_recent([("w", a.snapshot())], limit=1)[0]["kind"] == "request.finished"
    assert merge_recent([("w", None)]) == []  # workers predating the plane


def test_post_mortem_dump_jsonl(tmp_path, monkeypatch):
    monkeypatch.setenv(events_mod.POSTMORTEM_DIR_ENV, str(tmp_path))
    j = EventJournal()
    j.emit("request.enqueued", request_id="r1")
    j.emit("engine.crash", request_id="", error="Boom", step=7)
    path = j.dump_post_mortem("engine step failed: Boom")
    assert path is not None and path.startswith(str(tmp_path))
    lines = [json.loads(ln) for ln in Path(path).read_text().splitlines()]
    assert lines[0]["postmortem"].startswith("engine step failed")
    assert lines[0]["events"] == 2
    assert [ev["kind"] for ev in lines[1:]] == ["request.enqueued", "engine.crash"]
    # never-raises contract: an unwritable directory returns None
    assert j.dump_post_mortem("x", path="/nonexistent-dir/pm.jsonl") is None


def test_event_exposition_is_conformant():
    j = EventJournal()
    j.emit("qos.shed", request_id="r1", tenant="t")
    j.pin("r1", "shed")
    text = j.render_metrics()
    assert check_exposition(text) == []
    assert 'dynamo_event_emitted_total{kind="qos.shed"} 1' in text
    assert "dynamo_event_journal_size 1" in text
    assert "dynamo_event_captures_pinned_total 1" in text
    # an empty journal still renders every declared family (placeholders)
    empty = EventJournal().render_metrics()
    assert check_exposition(empty) == []
    for fam in ("dynamo_event_emitted_total", "dynamo_event_journal_size",
                "dynamo_event_captures_pinned_total"):
        assert f"# TYPE {fam}" in empty


def test_emit_records_exemplar_span_when_tracing(monkeypatch):
    from dynamo_tpu.utils import tracing

    monkeypatch.setattr(tracing, "enabled", lambda: True)
    recorded = []
    monkeypatch.setattr(
        tracing, "record_span",
        lambda name, *a, **kw: recorded.append((name, kw)),
    )
    j = EventJournal()
    ev = j.emit("sched.preempted", request_id="r9", generated=4)
    assert recorded and recorded[0][0] == "event.sched.preempted"
    assert recorded[0][1]["attrs"]["event_seq"] == ev.seq
    assert recorded[0][1]["trace_id"] == "r9"


# ---------------- conformance: static tuple vs runtime tuple ----------------


def test_static_event_declaration_matches_runtime_tuple():
    """The event-conformance detector's AST view of DECLARED_EVENT_KINDS must
    equal the tuple Python imports (same file, two readers) — the mirror of
    the metric-conformance cross-check."""
    from tools.graftlint.detectors.event_conformance import (
        DECLARING_MODULE,
        _find_declaration,
    )

    tree = ast.parse((ROOT / DECLARING_MODULE).read_text())
    declared, _ = _find_declaration(tree)
    assert {kind for kind, _ in declared} == set(DECLARED_EVENT_KINDS)
    assert len(DECLARED_EVENT_KINDS) == len(set(DECLARED_EVENT_KINDS))


def test_event_kind_typo_is_caught_statically(tmp_path):
    from tools.graftlint.cli import run_scan

    mod = tmp_path / "emitter.py"
    mod.write_text(
        "DECLARED_EVENT_KINDS = (\n"
        '    "demo.admitted",\n'
        ")\n\n\n"
        "def instrument(journal):\n"
        '    journal.emit("demo.admited")\n'  # transposed letters
    )
    findings, _ = run_scan([mod], root=tmp_path)
    msgs = [f.message for f in findings if not f.suppressed]
    assert any("demo.admited" in m for m in msgs), msgs
    assert any("emitted by no site" in m for m in msgs), msgs


# ---------------- burn-rate alerting (utils/slo.py) ----------------


def _burn_tracker(clk):
    return SloTracker(
        {"ttft": 0.1}, window_s=100.0, objective=0.9,
        clock=lambda: clk["now"], burn_threshold=2.0,
    )


def test_burn_rate_fires_on_sustained_violation_and_clears():
    clk = {"now": 1000.0}
    slo = _burn_tracker(clk)
    # sustained violations across the whole window: both windows burn hot
    for i in range(50):
        clk["now"] += 1.0
        slo.observe("ttft", 0.5)  # 5x the 100 ms target
    burn = slo.burn_snapshot()
    st = burn["metrics"]["ttft"]
    # violation ratio 1.0 against allowed 0.1 -> burn 10x in both windows
    assert st["short"] == pytest.approx(10.0)
    assert st["long"] == pytest.approx(10.0)
    assert st["alert"] is True
    assert burn["alerting"] == ["ttft"]
    assert burn["short_window_s"] == pytest.approx(20.0)  # 0.2 * window
    # recovery: fast samples push the SHORT window under threshold -> the
    # two-window rule clears even while the long window is still digesting
    for i in range(200):
        clk["now"] += 0.1
        slo.observe("ttft", 0.01)
    burn2 = slo.burn_snapshot()
    assert burn2["metrics"]["ttft"]["short"] < 2.0
    assert burn2["metrics"]["ttft"]["alert"] is False
    assert burn2["alerting"] == []


def test_burn_requires_both_windows():
    """A short burst alone must not page: the long window de-noises it."""
    clk = {"now": 0.0}
    slo = _burn_tracker(clk)
    # a long healthy history...
    for _ in range(80):
        clk["now"] += 1.0
        slo.observe("ttft", 0.01)
    # ...then a violent 10-sample burst inside the short window only
    for _ in range(10):
        clk["now"] += 0.5
        slo.observe("ttft", 0.9)
    burn = slo.burn_snapshot()
    st = burn["metrics"]["ttft"]
    assert st["short"] >= 2.0  # the burst dominates the short window
    assert st["long"] < 2.0  # diluted across the long window
    assert st["alert"] is False


def test_burn_exposition_and_snapshot_surface():
    clk = {"now": 0.0}
    slo = _burn_tracker(clk)
    for _ in range(20):
        clk["now"] += 1.0
        slo.observe("ttft", 0.5)
    text = slo.render_burn_metrics()
    assert check_exposition(text) == []
    assert 'dynamo_slo_burn_rate{metric="ttft",window="short"}' in text
    assert 'dynamo_slo_burn_rate{metric="ttft",window="long"}' in text
    assert 'dynamo_alert_state{alert="slo_burn_ttft"} 1' in text
    # the burn verdict rides snapshot() for worker stats -> planner
    snap = slo.snapshot()
    assert snap["burn"]["alerting"] == ["ttft"]
    # untargeted tracker: no burn block, placeholder exposition stays
    # conformant (families must render for the --check gate regardless)
    bare = SloTracker()
    assert "burn" not in bare.snapshot()
    bare_text = bare.render_burn_metrics()
    assert check_exposition(bare_text) == []
    assert "# TYPE dynamo_slo_burn_rate gauge" in bare_text
    assert "# TYPE dynamo_alert_state gauge" in bare_text


def test_slo_priority_class_series():
    """Satellite: observe(priority=) feeds a class-keyed series on the same
    families, surfaced in snapshot()['priorities'] and rendered with a
    priority label."""
    slo = SloTracker({"ttft": 0.1})
    slo.observe("ttft", 0.05, tenant="t-a", priority="critical")
    slo.observe("ttft", 0.3, priority="batch")
    snap = slo.snapshot()
    assert set(snap["priorities"]) == {"critical", "batch"}
    assert snap["priorities"]["batch"]["ttft"]["violations"] == 1
    assert snap["tenants"]["t-a"]["ttft"]["count"] == 1
    # the aggregate series sees every sample (breakdowns are views, not splits)
    assert snap["metrics"]["ttft"]["count"] == 2
    text = slo.render_metrics()
    assert check_exposition(text) == []
    assert 'priority="critical"' in text and 'priority="batch"' in text


def test_planner_rebalance_honors_burn_alert():
    """The planner consumes the burn verdict read-only: a hot worker whose
    burn-rate alert fires counts as burning even with healthy goodput."""
    from dynamo_tpu.components.planner import Planner, RebalancePolicy

    planner = Planner(rebalance_policy=RebalancePolicy(
        occupancy_hot=0.8, occupancy_cold=0.5, goodput_floor=0.9,
        sustain=1, cooldown_s=0.0,
    ))
    workers = [
        {"worker_id": "aa", "occupancy": 0.9, "goodput": 1.0,
         "servable": True, "migration": True, "burn_alert": True,
         "burn_alerting": ["ttft"]},
        {"worker_id": "bb", "occupancy": 0.2, "goodput": 1.0,
         "servable": True, "migration": True},
    ]
    d = planner.rebalance(workers, now=10.0)
    assert d is not None and d.source == "aa"
    assert "burn-rate alert ttft" in d.reason


# ---------------- satellite: preempt keeps the original queue clock --------


def test_preempt_requeue_preserves_original_enqueue_clock():
    """A preempted-and-requeued request must bill queue wait / TTFT /
    duration from its ORIGINAL submission, not the requeue instant."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.page_table import PageAllocator
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest, RunningSeq, Scheduler

    cfg = EngineConfig(model_id="tiny", page_size=4, num_pages=8, max_seqs=2,
                       prefill_buckets=(16,))
    alloc = PageAllocator(cfg.num_pages, cfg.page_size)
    sched = Scheduler(cfg, None, alloc)
    req = EngineRequest(
        request_id="pre-1", token_ids=[1, 2, 3, 4],
        sampling=SamplingParams(temperature=0.0, max_tokens=8),
        enqueue_ts=123.456, trace_id="tr-1", tenant="t-a", priority="standard",
    )
    _, st = alloc.allocate_sequence("pre-1", req.token_ids)
    seq = RunningSeq(req=req, slot=0, prompt_len=4, cached_len=0,
                     generated=[7, 8], page_table=st.pages)
    sched.slots[0] = seq
    sched._preempt(seq)
    requeued = sched.waiting[0]
    assert requeued.request_id == "pre-1"
    assert requeued.enqueue_ts == 123.456  # the original clock, not now()
    assert requeued.token_ids == [1, 2, 3, 4, 7, 8]
    assert requeued.tenant == "t-a" and requeued.priority == "standard"


def test_resume_request_backdates_enqueue_clock():
    """The migration twin of the preempt fix: to_resume_request back-dates
    by the manifest's recorded age so the destination's recompute path also
    bills from the original submission."""
    from dynamo_tpu.disagg.migrate import SequenceManifest

    man = SequenceManifest(
        request_id="m-1", prompt_tokens=[1, 2, 3], generated=[4],
        sampling={"temperature": 0.0, "max_tokens": 8}, age_s=2.5,
    )
    res = man.to_resume_request([], now=50.0)
    assert res.enqueue_ts == pytest.approx(47.5)
    eng = man.to_engine_request(now=50.0)
    assert eng.enqueue_ts == pytest.approx(47.5)
    # a degenerate clock never produces a negative timestamp
    assert man.to_resume_request([], now=1.0).enqueue_ts == 0.0


# ---------------- chaos breadcrumbs ----------------


def test_fault_injection_journals_breadcrumbs(monkeypatch):
    from dynamo_tpu.disagg import faults

    monkeypatch.setenv(faults.ENV_ADMISSION, "reject-rate:1.0")
    monkeypatch.setenv(faults.ENV_SEED, "3")
    before = events_mod.JOURNAL.snapshot()["counts"].get("fault.injected", 0)
    plan = faults.admission_plan()
    assert plan.should_reject() is True
    after = events_mod.JOURNAL.snapshot()["counts"].get("fault.injected", 0)
    assert after == before + 1


# ---------------- fleet timeline (components/metrics) ----------------


def _metrics_service_with_events():
    import time as _time

    from dynamo_tpu.components.metrics import MetricsService
    from dynamo_tpu.llm.kv_router.metrics_aggregator import WorkerView
    from dynamo_tpu.llm.kv_router.scheduler import WorkerLoad

    class _Drt:
        cplane = None

    svc = MetricsService(_Drt(), "ns", "backend")
    clock = {"now": 0.0}
    journals = []
    for wid, rid, tenant in ((0xA1, "r-a", "t1"), (0xB2, "r-b", "t2")):
        j = EventJournal(clock=lambda: clock["now"])
        clock["now"] += 1.0
        j.emit("request.enqueued", request_id=rid, tenant=tenant)
        clock["now"] += 1.0
        j.emit("qos.shed", request_id=rid, tenant=tenant, site="frontend")
        journals.append((wid, j))
        kv = {"request_active_slots": 1, "request_total_slots": 8,
              "kv_active_blocks": 1, "kv_total_blocks": 10}
        svc.aggregator._workers[wid] = WorkerView(
            wid,
            data={"kv_metrics": kv, "events": j.snapshot()},
            load=WorkerLoad.from_wire(wid, kv),
            last_seen=_time.monotonic(),
        )
    return svc


def test_cluster_events_merge_and_filters():
    svc = _metrics_service_with_events()
    merged = svc.cluster_events()
    assert len(merged) == 4
    # (wall, seq)-ordered across workers, each labeled with its worker
    assert [e["worker_id"] for e in merged] == ["a1", "a1", "b2", "b2"]
    walls = [e["wall"] for e in merged]
    assert walls == sorted(walls)
    # filters: kind is a startswith match (plane-level), tenant/request exact
    assert {e["kind"] for e in svc.cluster_events(kind="qos.")} == {"qos.shed"}
    assert all(e["tenant"] == "t2" for e in svc.cluster_events(tenant="t2"))
    by_req = svc.cluster_events(request_id="r-a")
    assert len(by_req) == 2 and all(e["request_id"] == "r-a" for e in by_req)
    assert svc.cluster_events(kind="migration.") == []
    assert len(svc.cluster_events(limit=1)) == 1


def test_cluster_status_carries_recent_events_and_worker_counts():
    svc = _metrics_service_with_events()
    doc = svc.cluster_status()
    assert [e["kind"] for e in doc["recent_events"][-2:]] == [
        "request.enqueued", "qos.shed",
    ]
    for w in doc["workers"]:
        assert w["events"]["emitted"] == 2
        assert w["events"]["counts"]["qos.shed"] == 1


# ---------------- dynotop rendering ----------------


def test_dynotop_evt_column_and_events_pane():
    from tools.dynotop import render_status

    doc = {
        "namespace": "ns", "component": "backend",
        "summary": {"workers": 1, "servable": 1, "stale": 0, "unservable": 0},
        "scrape_interval_s": 2.0,
        "workers": [{
            "worker_id": "a1", "stale": False,
            "health": {"state": "ready", "heartbeat_age_s": 0.1},
            "kv_metrics": {"request_active_slots": 1, "request_total_slots": 8,
                           "kv_active_blocks": 2, "kv_total_blocks": 10,
                           "num_requests_waiting": 0},
            "resources": {"qos": {"running": {"critical": 1}, "sheds": 2}},
            "events": {"emitted": 321, "captures": 3},
            "slo": {"priorities": {"critical": {
                "ttft": {"target_ms": 100.0, "error_budget": -0.5},
            }}},
        }],
        "recent_events": [
            {"wall": 1e9, "seq": 1, "kind": "sched.preempted", "worker_id": "a1",
             "request_id": "r-1", "detail": {"generated": 5}},
            {"wall": 1e9 + 1, "seq": 2, "kind": "qos.shed", "worker_id": "a1",
             "request_id": "r-2", "tenant": "t1", "detail": {"site": "frontend"}},
        ],
    }
    out = render_status(doc)
    assert "EVT" in out
    assert "321!3p" in out  # emitted count + pinned captures
    assert "1c*" in out  # critical class blew its error budget
    assert "recent events" in out
    assert "sched.preempted" in out and "qos.shed" in out and "[t1]" in out
    # scrolled view drops the newest line and says so
    scrolled = render_status(doc, events_rows=1, events_offset=1)
    assert "sched.preempted" in scrolled and "qos.shed" not in scrolled
    assert "scrolled 1 back" in scrolled
    # workers predating the plane render the placeholder, pane is absent
    doc["workers"][0].pop("events")
    doc.pop("recent_events")
    bare = render_status(doc)
    assert "recent events" not in bare
