"""Step-anatomy profiler (utils/step_anatomy.py): ring bounds, phase
attribution, roofline arithmetic vs hand-computed bytes (bf16 + int8 KV),
the /debug/steps payload, dynotop STEP/ROOF columns, exposition conformance
of the dynamo_step_* families, and the live scheduler integration (anatomy
device-wait agreeing with StageStats.reconcile_wait_s on the same run)."""

import asyncio
from types import SimpleNamespace

import numpy as np
import pytest

from dynamo_tpu.quant.kv import kv_page_bytes
from dynamo_tpu.utils.prometheus import _sample_surfaces, check_exposition
from dynamo_tpu.utils.step_anatomy import (
    KINDS,
    RooflineModel,
    StepAnatomy,
    roofline_for_runner,
)


# ---------------- ring + phase attribution ----------------


def test_ring_bounds_and_eviction():
    a = StepAnatomy(ring_size=8)
    for _ in range(20):
        a.begin("decode_window")
    assert len(a.ring) == 8  # bounded: eviction, not growth
    # cumulative counters survive eviction
    assert a.dispatch_counts["decode_window"] == 20
    recs = a.records(limit=4)
    assert [r["seq"] for r in recs] == [17, 18, 19, 20]  # newest last
    assert len(a.records(limit=100)) == 8
    # kind filter
    a.record("offload_drain", dispatch_s=0.001)
    assert [r["kind"] for r in a.records(kind="offload_drain")] == ["offload_drain"]


def test_phase_attribution_and_host_fraction():
    a = StepAnatomy()
    assert a.host_fraction() is None  # no data: no fake 1.0/0.0
    rec = a.begin("decode_window")
    a.add_phase(rec, "host_prep", 0.001)
    a.add_phase(rec, "dispatch", 0.002)
    a.add_phase(rec, "device_wait", 0.007)
    assert rec.total_s == pytest.approx(0.010)
    assert rec.host_s == pytest.approx(0.003)
    assert a.host_fraction() == pytest.approx(0.3)
    # a later reconcile mutates the SAME record (pipelined attribution)
    a.add_phase(rec, "reconcile", 0.002)
    assert a.host_fraction() == pytest.approx(5.0 / 12.0)
    d = rec.to_dict()
    assert d["dispatch_ms"] == pytest.approx(2.0)
    assert d["device_wait_ms"] == pytest.approx(7.0)
    # None-safe: an untracked entry still lands in the totals
    a.add_phase(None, "device_wait", 0.01)
    assert a.phase_seconds[("device_wait", "decode_window")] == pytest.approx(0.017)


def test_every_issue_kind_is_in_vocabulary():
    for kind in ("decode_window", "prefill_packed", "prefill_chunk",
                 "spec_draft", "spec_verify", "lora_slot_load",
                 "prefix_fetch_scatter", "offload_drain"):
        assert kind in KINDS


# ---------------- roofline arithmetic ----------------

_GEO = dict(page_size=16, num_kv_heads=8, head_dim=128, num_layers=24)


def test_roofline_bytes_bf16_hand_computed():
    # one page: K and V, all layers, page_size rows of Hkv*D bf16 values
    page = kv_page_bytes(_GEO["page_size"], _GEO["num_kv_heads"],
                         _GEO["head_dim"], _GEO["num_layers"], None, itemsize=2)
    assert page == 2 * 24 * 16 * (8 * 128 * 2)
    roof = RooflineModel(param_bytes=2_600_000_000, page_bytes=page,
                         page_size=16, hbm_bw=819e9)
    live = 64 * 28
    assert roof.step_floor_bytes(live) == 2_600_000_000 + live * page
    assert roof.step_floor_seconds(live) == pytest.approx(
        (2_600_000_000 + live * page) / 819e9
    )


def test_roofline_bytes_int8_hand_computed():
    page8 = kv_page_bytes(_GEO["page_size"], _GEO["num_kv_heads"],
                          _GEO["head_dim"], _GEO["num_layers"], "int8")
    # int8 rows: Hkv*D one-byte values + one f32 scale per row
    assert page8 == 2 * 24 * 16 * (8 * 128 * 1 + 4)
    page16 = kv_page_bytes(_GEO["page_size"], _GEO["num_kv_heads"],
                           _GEO["head_dim"], _GEO["num_layers"], None, itemsize=2)
    # the int8 floor is genuinely lower at the same occupancy (the estimator
    # must track the cache dtype, not assume bf16)
    live = 512
    f8 = RooflineModel(1_000, page8, 16, hbm_bw=1e9).step_floor_bytes(live)
    f16 = RooflineModel(1_000, page16, 16, hbm_bw=1e9).step_floor_bytes(live)
    assert f8 < f16
    assert f16 - f8 == live * (page16 - page8)


def test_roofline_for_runner_reads_actual_leaves():
    model = SimpleNamespace(
        config=None, kv_page_bytes=lambda ps: 4096 if ps == 4 else 0
    )
    runner = SimpleNamespace(
        model=model,
        params={"w": np.zeros((8, 4), np.float32), "b": np.zeros(4, np.int8)},
    )
    roof = roofline_for_runner(runner, SimpleNamespace(page_size=4))
    assert roof is not None
    assert roof.param_bytes == 8 * 4 * 4 + 4  # f32 + int8 leaves, as stored
    assert roof.page_bytes == 4096
    # runners that can't price pages degrade to None, never raise
    assert roofline_for_runner(SimpleNamespace(model=None, params=None),
                               SimpleNamespace(page_size=4)) is None


def test_roofline_fraction_and_dispatch_gap():
    roof = RooflineModel(param_bytes=1000, page_bytes=10, page_size=4,
                         hbm_bw=1000.0)
    a = StepAnatomy(roofline=roof)
    assert a.roofline_fraction() is None  # no priced dispatch yet
    rec = a.begin("decode_window", ts=1.0)
    a.add_phase(rec, "dispatch", 1.0)
    a.note_steps(rec, steps=2, floor_bytes=a.decode_floor_bytes(5, 2))
    # floor = (1000 + 5*10) * 2 steps = 2100 bytes / 1000 B/s = 2.1 s over
    # 1.0 s measured
    assert a.roofline_fraction() == pytest.approx(2.1)
    rec2 = a.begin("decode_window", ts=1.5)
    a.add_phase(rec2, "dispatch", 0.5)
    assert a.dispatch_gap_ms("decode_window") == pytest.approx(500.0)
    # other kinds don't pollute the decode cadence
    a.record("prefill_packed", dispatch_s=0.1, ts=1.25)
    assert a.dispatch_gap_ms("decode_window") == pytest.approx(500.0)
    assert a.dispatch_gap_ms("offload_drain") is None


def test_decode_floor_without_roofline_is_zero():
    a = StepAnatomy()
    assert a.decode_floor_bytes(100, 4) == 0
    assert a.roofline_fraction() is None


# ---------------- exposition conformance ----------------


def test_render_metrics_conformant_and_families_present():
    a = StepAnatomy(roofline=RooflineModel(1000, 10, 4, hbm_bw=1e9))
    rec = a.begin("decode_window")
    a.add_phase(rec, "dispatch", 0.002)
    a.add_phase(rec, "device_wait", 0.005)
    a.note_steps(rec, steps=4, floor_bytes=a.decode_floor_bytes(8, 4))
    a.record("lora_slot_load", dispatch_s=0.003)
    text = a.render_metrics()
    assert check_exposition(text) == []
    assert 'dynamo_step_seconds_total{kind="decode_window",phase="dispatch"}' in text
    assert 'dynamo_step_seconds_total{kind="decode_window",phase="device_wait"}' in text
    assert 'dynamo_step_dispatch_total{kind="lora_slot_load"} 1' in text
    assert "# TYPE dynamo_engine_roofline_fraction gauge" in text
    assert "# TYPE dynamo_step_host_fraction gauge" in text
    # empty tracker still renders conformant zero-sample families
    empty = StepAnatomy().render_metrics()
    assert check_exposition(empty) == []
    assert "dynamo_step_seconds_total" in empty
    # ...but never a fake roofline gauge
    assert "dynamo_engine_roofline_fraction" not in empty


def test_step_families_on_sample_surface():
    """The lint-gate surface list must carry the new families (acceptance:
    dynamo_step_* + dynamo_engine_roofline_fraction pass conformance via
    python -m dynamo_tpu.utils.prometheus --check)."""
    text = dict(_sample_surfaces())["engine.render_stage_metrics"]
    assert check_exposition(text) == []
    assert "# TYPE dynamo_step_seconds_total counter" in text
    assert "# TYPE dynamo_step_dispatch_total counter" in text
    assert "# TYPE dynamo_engine_roofline_fraction gauge" in text
    assert 'dynamo_step_dispatch_total{kind="lora_slot_load"}' in text


# ---------------- /debug/steps payload ----------------


def _bare_engine():
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.page_table import PageAllocator
    from dynamo_tpu.engine.scheduler import Scheduler

    cfg = EngineConfig(model_id="tiny", page_size=4, num_pages=8, max_seqs=2,
                       prefill_buckets=(16,))
    eng = AsyncJaxEngine(cfg)
    eng.allocator = PageAllocator(cfg.num_pages, cfg.page_size)
    eng.scheduler = Scheduler(cfg, None, eng.allocator)
    return eng


def test_debug_steps_payload_shape():
    eng = _bare_engine()
    # pre-data: well-formed and empty
    empty = eng.debug_steps()
    assert empty["records"] == [] and "summary" in empty
    a = eng.scheduler.anatomy
    for i in range(5):
        rec = a.begin("decode_window")
        a.add_phase(rec, "dispatch", 0.001 * (i + 1))
        a.note_steps(rec, steps=4, tokens=8, participants=2)
    a.record("prefill_packed", dispatch_s=0.004)
    doc = eng.debug_steps(limit=3)
    assert len(doc["records"]) == 3
    for r in doc["records"]:
        assert set(r) == {
            "seq", "ts", "kind", "host_prep_ms", "dispatch_ms",
            "device_wait_ms", "reconcile_ms", "steps", "tokens",
            "participants", "floor_bytes", "floor_ms",
        }
    # kind filter reaches through
    only = eng.debug_steps(kind="prefill_packed")
    assert {r["kind"] for r in only["records"]} == {"prefill_packed"}
    summary = doc["summary"]
    assert summary["dispatches"]["decode_window"] == 5
    assert summary["host_frac"] == 1.0  # no device_wait recorded
    # JSON-serializable end to end (the endpoint json_response contract)
    import json

    json.dumps(doc)


def test_debug_steps_http_endpoint():
    """The /debug/steps route serves the engine payload (and an empty shell
    when no engine is attached)."""
    from aiohttp.test_utils import TestClient, TestServer

    from dynamo_tpu.llm.http.service import HttpService

    eng = _bare_engine()
    a = eng.scheduler.anatomy
    rec = a.begin("decode_window")
    a.add_phase(rec, "dispatch", 0.002)

    async def run():
        svc = HttpService(step_source=eng.debug_steps)
        client = TestClient(TestServer(svc.app))
        await client.start_server()
        try:
            r = await client.get("/debug/steps?limit=10")
            assert r.status == 200
            doc = await r.json()
            assert doc["records"][-1]["kind"] == "decode_window"
            assert doc["summary"]["dispatches"]["decode_window"] == 1
            r2 = await client.get("/debug/steps?kind=prefill_packed")
            assert (await r2.json())["records"] == []
        finally:
            await client.close()

        bare = HttpService()
        client = TestClient(TestServer(bare.app))
        await client.start_server()
        try:
            r = await client.get("/debug/steps")
            assert await r.json() == {"records": [], "summary": {}}
        finally:
            await client.close()

    asyncio.run(run())


def test_resource_snapshot_carries_step_anatomy():
    eng = _bare_engine()
    a = eng.scheduler.anatomy
    rec = a.begin("decode_window")
    a.add_phase(rec, "dispatch", 0.002)
    snap = eng.resource_snapshot()
    assert "step_anatomy" in snap
    assert snap["step_anatomy"]["dispatches"]["decode_window"] == 1
    assert "host_frac" in snap["step_anatomy"]


# ---------------- dynotop columns ----------------


def _load_dynotop():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "dynotop_sa", Path(__file__).resolve().parent.parent / "tools" / "dynotop.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_dynotop_step_roof_columns():
    dynotop = _load_dynotop()
    doc = {
        "summary": {"workers": 1, "servable": 1, "stale": 0, "unservable": 0},
        "workers": [{
            "worker_id": "ab", "health": {"state": "ready", "heartbeat_age_s": 0.1},
            "kv_metrics": {"request_active_slots": 1, "request_total_slots": 8,
                           "kv_active_blocks": 2, "kv_total_blocks": 10,
                           "num_requests_waiting": 0},
            "resources": {"step_anatomy": {
                "host_frac": 0.312, "roofline_frac": 0.698,
                "dispatch_gap_ms_p50": 2.484,
                "prefill_host_frac": 0.974, "prefill_fixed_ms": 10.23,
                "prefill_roofline_frac": 0.63,
            }},
            "last_seen_s": 0.2, "missed_scrapes": 0,
        }],
    }
    text = dynotop.render_status(doc)
    assert "STEP" in text and "ROOF" in text and "PREFILL" in text
    assert "h31% 2.5ms" in text
    assert "70%" in text
    assert "h97% 10.2ms 63%" in text
    # workers predating the plane render "-" without crashing
    doc["workers"][0]["resources"] = {}
    text = dynotop.render_status(doc)
    assert "h31%" not in text and "70%" not in text
    assert "h97%" not in text


# ---------------- scheduler integration (tiny engine e2e) ----------------


@pytest.fixture(scope="module")
def served_engine():
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    cfg = EngineConfig(
        model_id="tiny", page_size=4, num_pages=256, max_seqs=4,
        max_model_len=160, prefill_buckets=(16, 32, 64), decode_steps=4,
        pipeline_depth=2,
    )
    eng = AsyncJaxEngine(cfg)
    loop = asyncio.new_event_loop()
    loop.run_until_complete(eng.start())
    yield eng, loop
    loop.run_until_complete(eng.shutdown())
    loop.close()


def test_live_engine_records_step_anatomy(served_engine):
    """Serving traffic populates the ring: decode windows + prefill kinds,
    priced floors, and device_wait agreeing with StageStats'
    reconcile_wait_s (the acceptance criterion's consistency check — both
    numbers come from the same measurement site)."""
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    eng, loop = served_engine
    rng = np.random.default_rng(0)

    async def one(i):
        req = EngineRequest(
            request_id=f"sa-{i}", token_ids=rng.integers(1, 200, 24).tolist(),
            sampling=SamplingParams(temperature=0.0, max_tokens=12,
                                    ignore_eos=True),
        )
        async for _ in eng.generate(req):
            pass

    async def run_all():
        await asyncio.gather(*[one(i) for i in range(4)])

    loop.run_until_complete(run_all())
    anatomy = eng.scheduler.anatomy
    snap = anatomy.snapshot()
    assert snap["dispatches"].get("decode_window", 0) >= 2
    assert snap["dispatches"].get("prefill_packed", 0) \
        + snap["dispatches"].get("prefill_chunk", 0) >= 1
    assert snap["steps"]["decode_window"] >= 4 * 12 // eng.config.decode_steps
    # the roofline estimator priced real floors off the tiny model's actual
    # geometry (param bytes > 0, page bytes from model.kv_page_bytes)
    assert anatomy.roofline is not None and anatomy.roofline.param_bytes > 0
    assert snap["floor_bytes_total"] > 0
    assert snap["host_frac"] is not None
    # consistency: anatomy's non-spec device_wait IS reconcile_wait_s (same
    # dt feeds both counters)
    wait = sum(v for k, v in snap["phase_seconds"].items()
               if k.startswith("device_wait."))
    assert wait == pytest.approx(eng.scheduler.stage.reconcile_wait_s, abs=1e-6)
    # /debug/steps sees the same traffic
    doc = eng.debug_steps(limit=256)
    assert any(r["kind"] == "decode_window" and r["tokens"] > 0
               for r in doc["records"])
    # and the engine exposition carries the families conformantly
    text = eng.render_stage_metrics()
    assert check_exposition(text) == []
    assert "dynamo_step_seconds_total" in text
