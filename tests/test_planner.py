"""Planner: scaling policy (pure) + service publishing desired replicas
(reference claims a Planner as capability #2 but ships none; ours is real)."""

import asyncio
import json

from dynamo_tpu.components.planner import Planner, PlannerService, PoolPolicy
from dynamo_tpu.llm.kv_router.scheduler import WorkerLoad


def load(active=0, total=8, kv=0, kv_total=100, waiting=0, wid=1):
    return WorkerLoad(
        worker_id=wid,
        request_active_slots=active,
        request_total_slots=total,
        kv_active_blocks=kv,
        kv_total_blocks=kv_total,
        num_requests_waiting=waiting,
    )


def mk_planner(sustain=2, cooldown=100.0):
    pol = PoolPolicy(min_replicas=1, max_replicas=4, sustain=sustain, cooldown_s=cooldown)
    return Planner(decode_policy=pol, prefill_policy=pol)


def test_scale_up_requires_sustained_pressure():
    p = mk_planner(sustain=3)
    hot = [load(active=8, wid=1)]  # slot pressure 1.0
    for t in range(2):
        d = p.observe(hot, 0, 1, 1, now=float(t))[0]
        assert not d.is_change  # not sustained yet
    d = p.observe(hot, 0, 1, 1, now=2.0)[0]
    assert d.is_change and d.desired == 2


def test_pressure_blip_resets_sustain():
    p = mk_planner(sustain=2)
    hot, idle = [load(active=8)], [load(active=4)]  # 1.0 vs 0.5 (dead zone)
    p.observe(hot, 0, 1, 1, now=0.0)
    p.observe(idle, 0, 1, 1, now=1.0)  # resets the streak
    d = p.observe(hot, 0, 1, 1, now=2.0)[0]
    assert not d.is_change


def test_cooldown_blocks_consecutive_changes():
    p = mk_planner(sustain=1, cooldown=60.0)
    hot = [load(active=8)]
    d = p.observe(hot, 0, 1, 1, now=0.0)[0]
    assert d.desired == 2
    d = p.observe(hot, 0, 2, 1, now=10.0)[0]  # inside cooldown
    assert not d.is_change
    d = p.observe(hot, 0, 2, 1, now=61.0)[0]  # cooldown expired
    assert d.desired == 3


def test_scale_down_and_min_bound():
    p = mk_planner(sustain=2, cooldown=0.0)
    idle = [load(active=0)]
    p.observe(idle, 0, 2, 1, now=0.0)
    d = p.observe(idle, 0, 2, 1, now=1.0)[0]
    assert d.desired == 1
    # at min: never below
    p2 = mk_planner(sustain=1, cooldown=0.0)
    d = p2.observe(idle, 0, 1, 1, now=0.0)[0]
    assert not d.is_change and d.desired == 1


def test_max_bound():
    p = mk_planner(sustain=1, cooldown=0.0)
    hot = [load(active=8)]
    d = p.observe(hot, 0, 4, 1, now=0.0)[0]
    assert not d.is_change and d.desired == 4


def test_kv_pressure_alone_triggers():
    p = mk_planner(sustain=1, cooldown=0.0)
    kv_hot = [load(active=1, kv=95)]  # kv 0.95, slots 0.125
    d = p.observe(kv_hot, 0, 1, 1, now=0.0)[0]
    assert d.desired == 2


def test_prefill_queue_scales_prefill_pool():
    p = mk_planner(sustain=2, cooldown=0.0)
    # queue 8 vs 1 replica * 4/worker -> pressure 1.0
    p.observe([], 8, 1, 1, now=0.0)
    d = p.observe([], 8, 1, 1, now=1.0)[1]
    assert d.component == "prefill-worker" and d.desired == 2
    # decode pool untouched (no loads -> pressure 0, but scale-down respects min)
    assert p.observe([], 8, 1, 2, now=2.0)[0].desired == 1


def test_planner_service_publishes_desired_replicas():
    from dynamo_tpu.cplane.broker import Broker
    from dynamo_tpu.llm.kv_router.publisher import KvMetricsPublisher
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    async def body():
        broker = Broker()
        port = await broker.start()
        addr = f"127.0.0.1:{port}"

        rt = DistributedRuntime(cplane_address=addr)
        await rt.connect()
        pub = KvMetricsPublisher(
            lambda: {
                "request_active_slots": 8,
                "request_total_slots": 8,
                "kv_active_blocks": 90,
                "kv_total_blocks": 100,
                "num_requests_waiting": 5,
            }
        )

        async def handler(req):
            yield {"ok": True}

        ep = rt.namespace("pl").component("worker").endpoint("generate")
        await ep.serve_endpoint(handler, metrics=pub.stats_handler)

        prt = DistributedRuntime(cplane_address=addr)
        await prt.connect()
        svc = PlannerService(
            prt, "pl",
            planner=Planner(
                decode_policy=PoolPolicy(sustain=2, cooldown_s=0.0, max_replicas=4),
                prefill_policy=PoolPolicy(sustain=2, cooldown_s=0.0, max_replicas=4),
            ),
        )
        try:
            await svc.step()
            decisions = await svc.step()  # sustained on 2nd observation
            decode = decisions[0]
            assert decode.desired == 2 and decode.current == 1

            kvs = await prt.cplane.kv_get_prefix("planner/pl/desired/")
            by_key = {item.key.rsplit("/", 1)[1]: json.loads(item.value) for item in kvs}
            assert by_key["worker"]["replicas"] == 2
            assert by_key["prefill-worker"]["replicas"] == 1
        finally:
            await rt._shutdown_hook()
            await prt._shutdown_hook()
            await broker.stop()

    asyncio.new_event_loop().run_until_complete(body())


def test_supervisor_applies_planner_scaling(monkeypatch):
    """The serve supervisor consumes the planner's desired-replica keys:
    scale-up spawns new replicas (chip envs reused round-robin), scale-down
    terminates the highest indices and the restart loop leaves them dead."""
    from dynamo_tpu.sdk.serve import Supervisor

    sup = Supervisor("m:X", {}, "127.0.0.1:1", planner_scaling=True, planner_poll_s=0.0)

    class Meta:
        namespace = "pl"
        component = "worker"

    cls = type("Worker", (), {})
    envs = [{"TPU_VISIBLE_DEVICES": "0"}, {"TPU_VISIBLE_DEVICES": "1"}]
    sup._class_info["Worker"] = (cls, Meta, envs)
    sup.desired["Worker"] = 2

    spawned = []
    monkeypatch.setattr(sup, "spawn", lambda c, i, env=None: spawned.append((i, env)))
    monkeypatch.setattr(
        sup, "_read_planner_desired", lambda: {"planner/pl/desired/worker": 4}
    )
    sup._apply_planner_scaling()
    assert sup.desired["Worker"] == 4
    # replicas 2,3 spawned; envs reused round-robin beyond the initial pool
    assert spawned == [(2, envs[0]), (3, envs[1])]

    class FakeProc:
        def __init__(self):
            self.terminated = False

        def poll(self):
            return None

        def terminate(self):
            self.terminated = True

    sup.children = {f"Worker-{i}": FakeProc() for i in range(4)}
    monkeypatch.setattr(
        sup, "_read_planner_desired", lambda: {"planner/pl/desired/worker": 1}
    )
    sup._last_planner_poll = 0.0
    sup._apply_planner_scaling()
    assert [sup.children[f"Worker-{i}"].terminated for i in range(4)] == [
        False, True, True, True,
    ]
