"""tools/bench_compare.py: the bench regression gate. Acceptance: nonzero
exit on a synthetic regression fixture, clean exit on identical artifacts,
both artifact shapes (bench line / driver record) accepted, missing keys
skipped (not regressions), direction + tolerance semantics honored."""

import importlib.util
import json
import sys
from pathlib import Path

import pytest

_spec = importlib.util.spec_from_file_location(
    "bench_compare", Path(__file__).resolve().parent.parent / "tools" / "bench_compare.py"
)
bench_compare = importlib.util.module_from_spec(_spec)
# dataclasses resolve their defining module through sys.modules at class
# creation time, so the module must be registered before exec
sys.modules["bench_compare"] = bench_compare
_spec.loader.exec_module(bench_compare)


def _artifact(headline=6000.0, host_frac=0.30, ttft_64k=57000.0):
    return {
        "metric": "engine_decode_throughput_llama1.3b_bf16",
        "value": headline,
        "summary": {
            "headline_tok_s": headline,
            "continuity_bs8_tok_s": round(headline / 4.5, 2),
            "long_context": {"ttft_ms_64k": ttft_64k},
            "step_anatomy": {"host_frac": host_frac, "roofline_frac": 0.7,
                             "dispatch_gap_ms_p50": 231.4},
            "replay": {"bursty": [0.98, 2600, 140, 33.6],
                       "lctx": [1.0, 1200, 105, 26.6],
                       "lora": [1.0, 1700, 6, 45.7],
                       "spec": [1.0, 1250, 165, 46.1]},
        },
    }


def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_identical_artifacts_exit_clean(tmp_path):
    old = _write(tmp_path, "old.json", _artifact())
    new = _write(tmp_path, "new.json", _artifact())
    assert bench_compare.main([old, new]) == 0


def test_synthetic_regression_exits_nonzero(tmp_path):
    """The acceptance fixture: a 1/3 headline drop must fail the gate."""
    old = _write(tmp_path, "old.json", _artifact())
    new = _write(tmp_path, "new.json", _artifact(headline=4000.0))
    assert bench_compare.main([old, new]) != 0


def test_lower_better_direction(tmp_path):
    # host overhead creeping UP is the regression for a lower-better key
    old = _write(tmp_path, "old.json", _artifact())
    worse = _write(tmp_path, "worse.json", _artifact(host_frac=0.45))
    assert bench_compare.main([old, worse]) != 0
    # and 64K TTFT regressing is caught through a nested path
    slow = _write(tmp_path, "slow.json", _artifact(ttft_64k=90000.0))
    assert bench_compare.main([old, slow]) != 0
    # improvement in a lower-better key passes
    better = _write(tmp_path, "better.json", _artifact(host_frac=0.20))
    assert bench_compare.main([old, better]) == 0


def test_driver_record_shape_accepted(tmp_path):
    """BENCH_rXX.json driver records nest the bench line under `parsed`."""
    old = _write(tmp_path, "old.json", {"n": 6, "parsed": _artifact()})
    new = _write(tmp_path, "new.json",
                 {"n": 7, "parsed": _artifact(headline=3000.0)})
    assert bench_compare.main([old, new]) != 0
    same = _write(tmp_path, "same.json", {"n": 7, "parsed": _artifact()})
    assert bench_compare.main([old, same]) == 0


def test_missing_keys_skip_unless_strict(tmp_path):
    """Sections come and go between rounds: absence is reported, not a
    regression — unless --strict."""
    old = _write(tmp_path, "old.json", _artifact())
    partial = _write(
        tmp_path, "partial.json",
        {"summary": {"headline_tok_s": 6000.0}},
    )
    assert bench_compare.main([old, partial]) == 0
    assert bench_compare.main([old, partial, "--strict"]) != 0


def test_explicit_keys_and_tolerance(tmp_path):
    old = _write(tmp_path, "old.json", _artifact())
    new = _write(tmp_path, "new.json", _artifact(headline=5500.0))
    # an 8.3% drop passes at 15% tolerance but fails at 5%
    assert bench_compare.main([old, new, "--key", "headline_tok_s:0.15"]) == 0
    assert bench_compare.main([old, new, "--key", "headline_tok_s:0.05"]) != 0


def test_lookup_paths_and_list_indexing():
    s = _artifact()["summary"]
    assert bench_compare.lookup(s, "headline_tok_s") == 6000.0
    assert bench_compare.lookup(s, "long_context.ttft_ms_64k") == 57000.0
    assert bench_compare.lookup(s, "replay.bursty.0") == 0.98
    assert bench_compare.lookup(s, "replay.bursty.9") is None
    assert bench_compare.lookup(s, "nope.deeper") is None
    assert bench_compare.lookup({"b": True}, "b") is None  # bool is not a metric


def test_parse_key_spec():
    assert bench_compare.parse_key_spec("a.b", 0.1) == ("a.b", "higher", 0.1)
    assert bench_compare.parse_key_spec("a:0.2:lower", 0.1) == ("a", "lower", 0.2)
    with pytest.raises(ValueError):
        bench_compare.parse_key_spec("a:0.2:sideways", 0.1)


def test_self_check_healthy():
    assert bench_compare.self_check() == []


def test_current_repo_artifact_parses():
    """The real BENCH_r06 driver record must be readable by the gate (its
    summary rides `parsed`), so cross-round comparison works on day one."""
    repo = Path(__file__).resolve().parent.parent
    r06 = repo / "BENCH_r06.json"
    if not r06.exists():
        pytest.skip("no BENCH_r06.json in repo root")
    doc = json.loads(r06.read_text())
    summary = bench_compare.extract_summary(doc)
    assert isinstance(summary, dict) and summary
