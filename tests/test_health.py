"""Fleet health plane units: HealthMonitor lifecycle + watchdog, SloTracker
percentiles/error budget, monitored-jit compile counting, aggregator aging,
and the dynotop renderer."""

import asyncio

import pytest

from dynamo_tpu.utils.compile_monitor import CompileMonitor, monitored_jit
from dynamo_tpu.utils.health import HealthMonitor, is_snapshot_servable
from dynamo_tpu.utils.prometheus import check_exposition
from dynamo_tpu.utils.slo import SloTracker


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------- HealthMonitor ----------------


def test_health_lifecycle_and_heartbeat():
    clock = FakeClock()
    hm = HealthMonitor("engine", clock=clock)
    assert hm.state == "starting"
    hm.set_state("ready", "init done")
    assert hm.state == "ready" and hm.is_servable()

    hm.beat()
    clock.advance(2.5)
    assert hm.heartbeat_age() == pytest.approx(2.5)
    snap = hm.snapshot()
    assert snap["state"] == "ready"
    assert snap["heartbeat_age_s"] == pytest.approx(2.5)
    assert snap["transitions"][-1]["to"] == "ready"

    hm.set_state("draining", "scale down")
    assert not hm.is_servable()
    hm.set_state("dead", "gone")
    # dead is terminal: later transitions are ignored
    hm.set_state("ready", "zombie")
    assert hm.state == "dead"


def test_health_watchdog_stuck_queue_and_recovery():
    clock = FakeClock()
    hm = HealthMonitor("engine", stuck_queue_s=10.0, no_progress_s=5.0, clock=clock)
    hm.set_state("ready", "")
    assert hm.check(oldest_waiting_age=3.0) is None
    assert hm.state == "ready"
    assert hm.check(oldest_waiting_age=11.0) == "stuck-queue"
    assert hm.state == "degraded"
    # alarm clears -> auto-recover to ready
    assert hm.check(oldest_waiting_age=0.0) is None
    assert hm.state == "ready"


def test_health_watchdog_no_progress():
    clock = FakeClock()
    hm = HealthMonitor("engine", stuck_queue_s=100.0, no_progress_s=5.0, clock=clock)
    hm.set_state("ready", "")
    hm.check(has_work=True, progress_marker=7)
    clock.advance(6.0)
    # marker frozen past the threshold while work exists -> degraded
    assert hm.check(has_work=True, progress_marker=7) == "no-progress"
    assert hm.state == "degraded"
    # progress resumes -> recovered
    assert hm.check(has_work=True, progress_marker=8) is None
    assert hm.state == "ready"
    # idle engines never alarm no matter how long the marker freezes
    clock.advance(100.0)
    assert hm.check(has_work=False, progress_marker=8) is None


def test_health_watchdog_never_overrides_draining():
    clock = FakeClock()
    hm = HealthMonitor("engine", stuck_queue_s=1.0, clock=clock)
    hm.set_state("draining", "scale down")
    hm.check(oldest_waiting_age=999.0)
    assert hm.state == "draining"


def test_health_exposition_conformant():
    hm = HealthMonitor("engine")
    hm.set_state("ready", "")
    text = hm.render_metrics()
    assert check_exposition(text) == []
    assert 'dynamo_health_state{component="engine",state="ready"} 1' in text
    assert 'state="dead"} 0' in text


def test_snapshot_servable_predicate():
    assert is_snapshot_servable(None)  # no health plane = servable
    assert is_snapshot_servable({"state": "ready"})
    assert is_snapshot_servable({"state": "degraded"})
    assert not is_snapshot_servable({"state": "draining"})
    assert not is_snapshot_servable({"state": "dead"})


# ---------------- SloTracker ----------------


def test_slo_percentiles_and_budget():
    clock = FakeClock()
    slo = SloTracker({"ttft": 0.5}, window_s=60.0, objective=0.9, clock=clock)
    # 8 good, 2 bad out of 10: violations == allowed (10%) -> budget 0.0
    for v in [0.1] * 8 + [0.9] * 2:
        slo.observe("ttft", v)
    s = slo.metric_state("ttft")
    assert s["count"] == 10 and s["violations"] == 2
    assert s["compliance"] == pytest.approx(0.8)
    assert s["error_budget"] == pytest.approx(-1.0)  # 2 violations, 1 allowed
    assert not s["ok"]
    assert s["p50_ms"] == pytest.approx(100.0)
    assert s["p99_ms"] == pytest.approx(900.0)

    # old samples fall out of the window
    clock.advance(120.0)
    slo.observe("ttft", 0.1)
    s = slo.metric_state("ttft")
    assert s["count"] == 1 and s["violations"] == 0 and s["ok"]
    # lifetime counters survive the pruning
    assert s["observed_total"] == 11 and s["violations_total"] == 2


def test_slo_untargeted_metric_never_violates():
    slo = SloTracker({})
    slo.observe("itl", 5.0)
    s = slo.metric_state("itl")
    assert s["ok"] and s["target_ms"] is None and s["error_budget"] == 1.0
    assert slo.snapshot()["ok"]


def test_slo_exposition_conformant():
    slo = SloTracker({"ttft": 0.2})
    for v in (0.05, 0.1, 0.4):
        slo.observe("ttft", v)
    text = slo.render_metrics()
    assert check_exposition(text) == []
    assert 'dynamo_slo_latency_seconds{metric="ttft",quantile="0.99"}' in text
    assert "dynamo_slo_error_budget_remaining" in text


def test_slo_env_targets(monkeypatch):
    from dynamo_tpu.utils.slo import targets_from_env

    monkeypatch.setenv("DYNTPU_SLO_TTFT_MS", "500")
    monkeypatch.setenv("DYNTPU_SLO_ITL_MS", "junk")  # ignored, not a crash
    t = targets_from_env({"itl": 25})
    assert t["ttft"] == pytest.approx(0.5)
    assert t["itl"] == pytest.approx(0.025)  # explicit override wins


# ---------------- monitored jit ----------------


def test_monitored_jit_counts_compiles():
    jax = pytest.importorskip("jax")
    import numpy as np

    mon = CompileMonitor()
    f = monitored_jit(jax.jit(lambda x: x + 1), "add", mon)
    f(np.zeros(3, np.float32))
    assert mon.compiles == 1 and mon.compile_s > 0
    f(np.zeros(3, np.float32))  # cache hit: no new compile
    assert mon.compiles == 1
    f(np.zeros(5, np.float32))  # new shape: recompile
    assert mon.compiles == 2
    snap = mon.snapshot()
    assert snap["per_label"] == {"add": 2}
    assert snap["last_label"] == "add"


def test_monitored_jit_passthrough_without_monitor():
    def fn(x):
        return x

    assert monitored_jit(fn, "x", None) is fn


# ---------------- aggregator aging ----------------


def _mk_aggregator(max_missed=2):
    from dynamo_tpu.llm.kv_router.metrics_aggregator import KvMetricsAggregator

    return KvMetricsAggregator(None, "ns", "backend", max_missed_scrapes=max_missed)


def _fake_scrape(agg, endpoints):
    """Drive one scrape round against injected endpoint stats (no cplane)."""
    import dynamo_tpu.llm.kv_router.metrics_aggregator as mod
    from dynamo_tpu.runtime.service import EndpointStats, ServiceSet

    async def fake_collect(cplane, ns, comp, timeout=0.0):
        return ServiceSet(endpoints=[
            EndpointStats(instance_id=i, endpoint="generate", subject="s", data=d)
            for i, d in endpoints
        ])

    orig = mod.collect_service_stats
    mod.collect_service_stats = fake_collect
    try:
        return asyncio.run(agg.scrape_once())
    finally:
        mod.collect_service_stats = orig


KV = {
    "request_active_slots": 1, "request_total_slots": 8,
    "kv_active_blocks": 5, "kv_total_blocks": 100,
}


def test_aggregator_ages_out_silent_workers():
    agg = _mk_aggregator(max_missed=2)
    loads = _fake_scrape(agg, [(1, {"kv_metrics": KV}), (2, {"kv_metrics": KV})])
    assert {w.worker_id for w in loads} == {1, 2}

    # worker 2 goes silent: stale immediately, aged out after max_missed
    _fake_scrape(agg, [(1, {"kv_metrics": KV})])
    views = {v.instance_id: v for v in agg.worker_views()}
    assert views[2].stale and views[2].missed_scrapes == 1
    assert {w.worker_id for w in agg.get_metrics()} == {1, 2}  # not aged yet
    _fake_scrape(agg, [(1, {"kv_metrics": KV})])
    _fake_scrape(agg, [(1, {"kv_metrics": KV})])
    assert {w.worker_id for w in agg.get_metrics()} == {1}
    assert [v.instance_id for v in agg.worker_views()] == [1]

    # a returning worker is fresh again
    _fake_scrape(agg, [(1, {"kv_metrics": KV}), (2, {"kv_metrics": KV})])
    assert {w.worker_id for w in agg.get_metrics()} == {1, 2}


def test_aggregator_excludes_draining_and_dead_immediately():
    agg = _mk_aggregator()
    _fake_scrape(agg, [
        (1, {"kv_metrics": KV, "health": {"state": "ready"}}),
        (2, {"kv_metrics": KV, "health": {"state": "draining"}}),
        (3, {"kv_metrics": KV, "health": {"state": "dead"}}),
    ])
    assert {w.worker_id for w in agg.get_metrics()} == {1}
    assert {i for i, _ in agg.get_raw()} == {1}
    # the status surface still SHOWS them
    assert [v.instance_id for v in agg.worker_views()] == [1, 2, 3]


def test_aggregator_last_seen_tracks_freshness():
    agg = _mk_aggregator()
    _fake_scrape(agg, [(7, {"kv_metrics": KV})])
    view = agg.worker_views()[0]
    assert view.age_s() < 1.0
    assert view.last_seen_wall > 0


# ---------------- dynotop renderer ----------------


def test_dynotop_render_status_pure():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "dynotop", Path(__file__).resolve().parent.parent / "tools" / "dynotop.py"
    )
    dynotop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(dynotop)

    doc = {
        "namespace": "ns", "component": "backend",
        "summary": {"workers": 2, "servable": 1, "stale": 1, "unservable": 1},
        "scrape_interval_s": 1.0,
        "kv_hit_rate": {"isl_blocks": 10, "overlap_blocks": 4},
        "workers": [
            {
                "worker_id": "ab", "last_seen_s": 0.2, "missed_scrapes": 0,
                "stale": False, "servable": True,
                "health": {"state": "ready", "heartbeat_age_s": 0.05},
                "kv_metrics": {"request_active_slots": 2, "request_total_slots": 8,
                               "kv_active_blocks": 50, "kv_total_blocks": 100,
                               "num_requests_waiting": 1},
                "resources": {"hbm_bytes_in_use": 2 * 1024**3, "xla_compiles": 12},
                "slo": {"metrics": {"ttft": {"target_ms": 500.0, "error_budget": 0.75}}},
            },
            {
                "worker_id": "cd", "last_seen_s": 9.5, "missed_scrapes": 3,
                "stale": True, "servable": False,
                "health": {"state": "dead"}, "kv_metrics": {}, "resources": {},
            },
        ],
    }
    text = dynotop.render_status(doc)
    assert "ab" in text and "cd" in text
    assert "ready" in text and "dead" in text
    assert "STALE" in text
    assert "50.0%" in text  # kv occupancy
    assert "2.0GB" in text
    assert "budget +0.75 OK" in text
    assert "hit rate: 40.0%" in text

    # empty fleet renders, not crashes
    empty = dynotop.render_status({"summary": {}, "workers": []})
    assert "no workers" in empty
