"""Fleet-wide prefix cache: cross-worker KV pull over the dataplane.

Correctness bar: a worker that pulls a peer's cached prefix instead of
recomputing it must produce TOKEN-IDENTICAL output (the injected KV equals
the locally-computed KV), and every failure mode — dead peer, black-holed
connection, holder death mid-stream, evicted blocks ("gone") — must degrade
to recompute, never to an error or a wedged admission queue.
"""

import asyncio
import time

import pytest

from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest

# 24 tokens -> 6 full blocks at page_size 4; the fetchable prefix caps at
# (24 - 1) // 4 = 5 blocks (the last token must prefill for logits)
PROMPT = [5, 9, 2, 77, 31, 8, 100, 42, 17, 3, 60, 61,
          7, 13, 19, 23, 29, 37, 41, 43, 47, 53, 59, 67]


def _req(rid, prompt, n=6, holder="", blocks=0):
    return EngineRequest(
        request_id=rid,
        token_ids=list(prompt),
        sampling=SamplingParams(temperature=0.0, max_tokens=n),
        kv_holder_addr=holder,
        kv_holder_blocks=blocks,
    )


async def _collect(engine, req):
    toks, finish, cached = [], None, 0
    async for out in engine.generate(req):
        if out.token is not None:
            toks.append(out.token)
        cached = max(cached, out.cached_tokens)
        if out.finished:
            finish = out.finish_reason
    return toks, finish, cached


def _engine(**over):
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    from tests.test_engine import tiny_engine_config

    return AsyncJaxEngine(tiny_engine_config(**over))


# ---------------- two-engine loopback: pull + token parity ----------------


@pytest.mark.parametrize("kv_dtype", [None, "int8"], ids=["bf16", "int8"])
def test_cross_worker_pull_token_parity(kv_dtype):
    """Worker B pulls worker A's prefix over the wire and must emit exactly
    the tokens A emits (greedy, same weights) while skipping the prefix
    recompute — with both the bf16 and the int8 KV cache (int8 pages ride
    the wire with their scale planes in the part headers)."""
    from dynamo_tpu.disagg.prefix_fetch import KvPullServer, PrefixFetchClient

    async def body():
        holder = _engine(kv_cache_dtype=kv_dtype)
        await holder.start()
        puller = _engine(kv_cache_dtype=kv_dtype)
        await puller.start()
        srv = None
        try:
            expected, finish, _ = await _collect(holder, _req("seed", PROMPT))
            assert finish == "length" and len(expected) == 6
            srv = await KvPullServer(holder, host="127.0.0.1").start()
            puller.attach_prefix_fetch(
                PrefixFetchClient(asyncio.get_running_loop(), timeout_s=30.0)
            )
            got, finish, cached = await _collect(
                puller, _req("pull", PROMPT, holder=srv.address, blocks=6)
            )
            assert got == expected, f"pulled {got} != recompute {expected}"
            assert finish == "length"
            sched = puller.scheduler
            assert sched.prefix_fetch_hits == 1
            assert sched.prefix_fetch_fallbacks == 0
            assert sched.prefix_fetch_blocks == 5  # capped at (24-1)//4
            assert sched.prefix_fetch_tokens == 20
            assert cached >= 20  # pulled prefix reported like a local hit
            assert srv.served == 1
            assert srv.served_blocks["hbm"] == 5
            assert srv.bytes_sent > 0
            res = puller.resource_snapshot()
            assert res["prefix_fetch_blocks"] == 5
            assert res["prefix_fetch_bytes"] == srv.bytes_sent
            # the pulled blocks registered locally: a repeat request is now a
            # plain local hit, no second fetch
            got2, _, cached2 = await _collect(
                puller, _req("pull2", PROMPT, holder=srv.address, blocks=6)
            )
            assert got2 == expected
            assert sched.prefix_fetch_hits == 1  # no new fetch
            assert cached2 >= 20
        finally:
            if srv is not None:
                await srv.stop()
            await holder.shutdown()
            await puller.shutdown()

    asyncio.run(body())


def test_cross_worker_pull_mixed_dtype_peers():
    """An int8 holder serving a bf16 puller still works end to end: the
    {"q","s"} wire block dequantizes into the bf16 cache at scatter time
    (scatter_pages_wire) — functional interop, no exact-parity claim across
    the dtype boundary."""
    from dynamo_tpu.disagg.prefix_fetch import KvPullServer, PrefixFetchClient

    async def body():
        holder = _engine(kv_cache_dtype="int8")
        await holder.start()
        puller = _engine()  # bf16 cache
        await puller.start()
        srv = None
        try:
            await _collect(holder, _req("seed", PROMPT))
            srv = await KvPullServer(holder, host="127.0.0.1").start()
            puller.attach_prefix_fetch(
                PrefixFetchClient(asyncio.get_running_loop(), timeout_s=30.0)
            )
            got, finish, cached = await _collect(
                puller, _req("pull", PROMPT, holder=srv.address, blocks=6)
            )
            assert finish == "length" and len(got) == 6
            assert puller.scheduler.prefix_fetch_hits == 1
            assert cached >= 20
        finally:
            if srv is not None:
                await srv.stop()
            await holder.shutdown()
            await puller.shutdown()

    asyncio.run(body())


# ---------------- failure ladder: everything degrades to recompute ----------------


def test_fetch_failures_degrade_to_recompute():
    """Dead peer, black-holed connection (timeout), holder death mid-fetch,
    and evicted blocks ("gone") all fall back to recompute — the request
    completes normally and admission never wedges."""
    from dynamo_tpu.disagg.prefix_fetch import KvPullServer, PrefixFetchClient

    async def body():
        puller = _engine(prefix_fetch_timeout_s=0.4)
        await puller.start()
        fetcher = PrefixFetchClient(asyncio.get_running_loop(), timeout_s=0.4)
        puller.attach_prefix_fetch(fetcher)
        sched = puller.scheduler

        def prompt(seed):
            return [(seed * 97 + i * 13) % 400 + 1 for i in range(24)]

        blackhole_conns = []

        async def _blackhole(reader, writer):
            blackhole_conns.append(writer)  # accept, never answer

        async def _die_mid_fetch(reader, writer):
            await reader.readexactly(4)  # start reading the request frame...
            writer.close()  # ...and die

        blackhole = await asyncio.start_server(_blackhole, "127.0.0.1", 0)
        killer = await asyncio.start_server(_die_mid_fetch, "127.0.0.1", 0)
        bh_port = blackhole.sockets[0].getsockname()[1]
        k_port = killer.sockets[0].getsockname()[1]
        try:
            # (a) connection refused: resolves as an error, fast
            toks, finish, _ = await _collect(
                puller, _req("dead", prompt(1), holder="127.0.0.1:9", blocks=6)
            )
            assert finish == "length" and len(toks) == 6
            assert sched.prefix_fetch_fallbacks == 1

            # (b) black hole: the fetch timeout bounds the stall
            t0 = time.monotonic()
            toks, finish, _ = await _collect(
                puller,
                _req("blackhole", prompt(2), holder=f"127.0.0.1:{bh_port}", blocks=6),
            )
            assert finish == "length" and len(toks) == 6
            assert sched.prefix_fetch_fallbacks == 2
            assert fetcher.results.get("timeout", 0) == 1
            assert time.monotonic() - t0 < 30.0

            # (c) holder dies mid-fetch: clean error, immediate fallback
            toks, finish, _ = await _collect(
                puller,
                _req("killer", prompt(3), holder=f"127.0.0.1:{k_port}", blocks=6),
            )
            assert finish == "length" and len(toks) == 6
            assert sched.prefix_fetch_fallbacks == 3

            # (d) holder alive but blocks not there: a clean "gone" response,
            # not a timeout (self-pull: our own pull server, blocks of a
            # prompt we never cached)
            srv = await KvPullServer(puller, host="127.0.0.1").start()
            try:
                toks, finish, _ = await _collect(
                    puller, _req("gone", prompt(4), holder=srv.address, blocks=6)
                )
                assert finish == "length" and len(toks) == 6
                assert sched.prefix_fetch_fallbacks == 4
                assert srv.gone == 1
                assert fetcher.results.get("gone", 0) == 1
            finally:
                await srv.stop()
            assert sched.prefix_fetch_hits == 0
        finally:
            blackhole.close()
            killer.close()
            for w in blackhole_conns:
                w.close()
            await puller.shutdown()

    asyncio.run(body())


# ---------------- eviction truthfulness ----------------


def test_eviction_publishes_removed_events():
    """Every block the allocator reclaims from the prefix cache (no host
    tier) must emit a `removed` event carrying the same block identity its
    `stored` event advertised — so no router ever points a fetch at a block
    the holder no longer has."""
    from dynamo_tpu.engine.page_table import PageAllocator

    events = []
    alloc = PageAllocator(num_pages=6, page_size=4, event_sink=events.append)
    alloc.allocate_sequence("a", list(range(1, 17)))  # 4 blocks
    alloc.commit_prefilled("a", 16)
    alloc.free_sequence("a")
    stored = [b.block_hash for e in events if e.kind == "stored" for b in e.blocks]
    assert len(stored) == 4
    # a second sequence forces reclaim of 3 reusable blocks (1 page was free)
    alloc.allocate_sequence("b", list(range(101, 117)))
    removed = [h for e in events if e.kind == "removed" for h in e.block_hashes]
    assert len(removed) == 3
    assert set(removed) <= set(stored)
    # advertised-minus-removed is exactly what the pull server can still find
    live = set(stored) - set(removed)
    assert live and all(alloc.cached_page(h) is not None for h in live)
    assert all(alloc.cached_page(h) is None for h in removed)


def test_offload_drop_publishes_removed_once_gone_from_all_tiers():
    """With a host tier, reclaiming a device block is NOT a removal (the
    block is still pullable from the host pool); only the host-LRU drop —
    the block leaving its last tier — emits `removed`."""
    from dynamo_tpu.engine.page_table import PageAllocator

    class _Runner:  # host-pool transfers without a device
        def extract_pages(self, ids):
            import numpy as np

            return np.zeros((1, 2, len(ids), 4, 1, 2), np.float32)

        def inject_pages_bucketed(self, ids, data, axis=None):
            pass

    from dynamo_tpu.engine.offload import HostKvPool

    events = []
    pool = HostKvPool(_Runner(), capacity_blocks=2)
    alloc = PageAllocator(num_pages=6, page_size=4,
                          event_sink=events.append, offload=pool)
    alloc.allocate_sequence("a", list(range(1, 17)))
    alloc.commit_prefilled("a", 16)
    alloc.free_sequence("a")
    alloc.allocate_sequence("b", list(range(101, 117)))
    removed = [h for e in events if e.kind == "removed" for h in e.block_hashes]
    # 3 device blocks were reclaimed; the first spilled to host and was then
    # LRU-dropped when the next two arrived (capacity 2) -> exactly 1 removal
    assert len(removed) == 1
    assert len(pool) == 2
    stored = [b.block_hash for e in events if e.kind == "stored" for b in e.blocks]
    assert set(removed) <= set(stored)


# ---------------- radix tree under churn ----------------


def test_radix_tree_remove_worker_and_expiration_under_churn():
    from dynamo_tpu.llm.kv_events import KvCacheEvent, StoredBlock
    from dynamo_tpu.llm.kv_router.indexer import RadixTree, RouterEvent

    def stored(worker, chain):
        blocks, parent = [], None
        for h in chain:
            blocks.append(StoredBlock(block_hash=h * 1000 + worker,
                                      tokens_hash=h, parent_hash=parent))
            parent = h * 1000 + worker
        return RouterEvent(worker_id=worker,
                           event=KvCacheEvent.stored(parent_hash=None, blocks=blocks))

    tree = RadixTree(expiration_duration=0.05)
    seq = [11, 22, 33]
    for w in (1, 3):
        tree.apply_event(stored(w, seq))
    tree.apply_event(stored(2, [11, 22, 99]))  # worker 2 diverges at depth 2

    scores = tree.find_matches(seq).scores
    assert scores == {1: 3, 2: 2, 3: 3}

    # churn: remove a worker entirely, then partially remove another's blocks
    tree.remove_worker(2)
    scores = tree.find_matches(seq).scores
    assert 2 not in scores and scores[1] == 3
    tree.apply_event(RouterEvent(
        worker_id=1, event=KvCacheEvent.removed([33 * 1000 + 1])
    ))
    scores = tree.find_matches(seq).scores
    assert scores == {1: 2, 3: 3}
    # re-advertise after re-store: worker 2 comes back
    tree.apply_event(stored(2, seq))
    assert tree.find_matches(seq).scores[2] == 3

    # frequency expiration: uses recorded now, decayed after the window
    freqs1 = tree.find_matches(seq).frequencies
    assert freqs1 and freqs1[0] >= 1
    time.sleep(0.06)
    freqs2 = tree.find_matches(seq).frequencies
    assert freqs2[0] <= freqs1[0]


# ---------------- router: one radix walk + remote-holder selection ----------------


def test_router_overlap_memo_and_remote_holder():
    """schedule/prefix_hit_tokens share ONE radix walk per prompt, and the
    remote-holder pick comes from the same OverlapScores."""
    import time as _time

    from dynamo_tpu.llm.kv_events import KvCacheEvent, StoredBlock
    from dynamo_tpu.llm.kv_router.indexer import RouterEvent
    from dynamo_tpu.llm.kv_router.metrics_aggregator import WorkerView
    from dynamo_tpu.llm.kv_router.router import KvRouter
    from dynamo_tpu.llm.tokens import compute_block_hash_for_seq

    class _Drt:
        cplane = None

    router = KvRouter(_Drt(), "ns", "backend", kv_block_size=4)
    prompt = list(range(1, 13))  # 3 blocks
    hashes = compute_block_hash_for_seq(prompt, 4)

    def stored(worker, n):
        blocks, parent = [], None
        for i, th in enumerate(hashes[:n]):
            bh = th ^ worker
            blocks.append(StoredBlock(block_hash=bh, tokens_hash=th, parent_hash=parent))
            parent = bh
        return {"payload": RouterEvent(
            worker_id=worker,
            event=KvCacheEvent.stored(parent_hash=None, blocks=blocks),
        ).to_wire()}

    router._on_kv_event(stored(0xA, 3))
    router._on_kv_event(stored(0xB, 1))

    calls = [0]
    orig = router.indexer.find_matches_for_request

    def counting(token_ids, early_exit=False, salt=0):
        calls[0] += 1
        return orig(token_ids, early_exit, salt=salt)

    router.indexer.find_matches_for_request = counting

    overlap = router._find_overlap(prompt)
    assert calls[0] == 1
    assert router._find_overlap(prompt) is overlap  # memo hit
    assert calls[0] == 1
    assert router.prefix_hit_tokens(prompt, 0xA) == 12
    assert calls[0] == 1  # satellite: no second radix walk

    holder = router.best_remote_holder(overlap, 0xB)
    assert holder == (0xA, 3)
    assert router.best_remote_holder(overlap, 0xA) is None  # B's 1 < A's 3 + margin

    # a new KV event invalidates the memo (the tree changed)
    router._on_kv_event(stored(0xB, 2))
    router._find_overlap(prompt)
    assert calls[0] == 2

    # pull_address comes from the stats broadcast of a servable worker
    router.aggregator._workers[0xA] = WorkerView(
        0xA,
        data={"kv_pull": {"address": "10.0.0.7:4040"},
              "health": {"state": "ready", "heartbeat_age_s": 0.01}},
        last_seen=_time.monotonic(),
    )
    assert router.pull_address(0xA) == "10.0.0.7:4040"
    assert router.pull_address(0xB) == ""  # unknown worker -> no address
    router.aggregator._workers[0xA].data["health"]["state"] = "draining"
    assert router.pull_address(0xA) == ""  # never fetch from a draining peer


# ---------------- dynotop prefix column ----------------


def test_dynotop_prefix_column_local_vs_remote():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "dynotop", Path(__file__).resolve().parent.parent / "tools" / "dynotop.py"
    )
    dynotop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(dynotop)

    doc = {
        "namespace": "ns", "component": "backend", "summary": {"workers": 1},
        "workers": [{
            "worker_id": "ab", "last_seen_s": 0.1, "missed_scrapes": 0,
            "health": {"state": "ready", "heartbeat_age_s": 0.01},
            "kv_metrics": {"request_active_slots": 1, "request_total_slots": 4,
                           "kv_active_blocks": 1, "kv_total_blocks": 10},
            "resources": {"prefix_cache_query_blocks": 10,
                          "prefix_cache_hit_blocks": 8,
                          "prefix_fetch_blocks": 2},
        }],
    }
    text = dynotop.render_status(doc)
    assert "PREFIX" in text
    assert "80/20%" in text  # local 8/10, remote 2/10
    # workers predating the counters render a dash, not a crash
    doc["workers"][0]["resources"] = {}
    assert "80/20%" not in dynotop.render_status(doc)


# ---------------- exposition ----------------


def test_prefix_fetch_exposition_families():
    from dynamo_tpu.disagg.prefix_fetch import KvPullServer, PrefixFetchClient
    from dynamo_tpu.utils.prometheus import check_exposition

    srv = KvPullServer(None)
    srv.served, srv.gone = 3, 1
    srv.served_blocks["host"] = 2
    text = srv.render_metrics()
    assert check_exposition(text) == []
    assert 'dynamo_prefix_fetch_served_total{result="hit"} 3' in text
    assert 'dynamo_prefix_fetch_served_blocks_total{tier="host"} 2' in text

    cl = PrefixFetchClient(None)
    cl.results["timeout"] = 2
    cl.fetch_seconds.observe(0.1)
    text = cl.render_metrics()
    assert check_exposition(text) == []
    assert 'dynamo_prefix_fetch_client_requests_total{result="timeout"} 2' in text
    assert "dynamo_prefix_fetch_client_seconds_bucket" in text
