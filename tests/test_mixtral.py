"""Mixtral MoE: paged forward vs a naive dense-dispatch reference + ep sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from dynamo_tpu.models.mixtral import MixtralConfig, MixtralModel
from dynamo_tpu.ops.moe import moe_block, topk_routing
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.rotary import apply_rope


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow

PAGE_SIZE = 4
NUM_PAGES = 16
PROMPT = np.array([5, 9, 2, 77, 31, 8, 100], dtype=np.int32)
PAGE_TABLE = np.array([3, 5, 7, 0, 0, 0, 0, 0], dtype=np.int32)


def naive_moe(hidden, router_w, w_gate, w_up, w_down, k):
    """Per-token loop over selected experts — the semantic reference."""
    T = hidden.shape[0]
    logits = hidden.astype(jnp.float32) @ router_w.astype(jnp.float32)
    weights, idx = topk_routing(logits, k)
    out = jnp.zeros_like(hidden, dtype=jnp.float32)
    for t in range(T):
        acc = jnp.zeros(hidden.shape[1], jnp.float32)
        for j in range(k):
            e = int(idx[t, j])
            x = hidden[t].astype(w_gate.dtype)
            g = jax.nn.silu(x @ w_gate[e]) * (x @ w_up[e])
            acc += float(weights[t, j]) * (g @ w_down[e]).astype(jnp.float32)
        out = out.at[t].set(acc)
    return out.astype(hidden.dtype)


def test_moe_block_matches_naive():
    rng = np.random.default_rng(0)
    T, D, F, E, K = 10, 16, 32, 4, 2
    h = jnp.asarray(rng.standard_normal((T, D)), jnp.float32)
    router = jnp.asarray(rng.standard_normal((D, E)), jnp.float32)
    wg = jnp.asarray(rng.standard_normal((E, D, F)) * 0.1, jnp.float32)
    wu = jnp.asarray(rng.standard_normal((E, D, F)) * 0.1, jnp.float32)
    wd = jnp.asarray(rng.standard_normal((E, F, D)) * 0.1, jnp.float32)
    expected = naive_moe(h, router, wg, wu, wd, K)
    got = moe_block(h, router, wg, wu, wd, K, capacity_factor=float(E))  # no drops
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-4)


def test_moe_capacity_drops_are_bounded():
    rng = np.random.default_rng(1)
    T, D, F, E, K = 32, 16, 32, 4, 2
    h = jnp.asarray(rng.standard_normal((T, D)), jnp.float32)
    router = jnp.zeros((D, E), jnp.float32)  # uniform router -> heavy collisions
    wg = jnp.asarray(rng.standard_normal((E, D, F)) * 0.1, jnp.float32)
    wu = jnp.asarray(rng.standard_normal((E, D, F)) * 0.1, jnp.float32)
    wd = jnp.asarray(rng.standard_normal((E, F, D)) * 0.1, jnp.float32)
    out = moe_block(h, router, wg, wu, wd, K, capacity_factor=0.5)
    assert out.shape == h.shape
    assert np.isfinite(np.asarray(out)).all()


@pytest.fixture(scope="module")
def setup():
    cfg = MixtralConfig.tiny_moe()
    model = MixtralModel(cfg)
    params = model.init_params(jax.random.key(0))
    return cfg, model, params


def naive_forward_moe(cfg, params, tokens):
    T = len(tokens)
    pos = jnp.arange(T)
    h = params["embed"][jnp.array(tokens)].astype(cfg.dtype)
    for l in range(cfg.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])
        x = rms_norm(h, lp["input_norm"], cfg.rms_norm_eps)
        q = apply_rope((x @ lp["wq"]).reshape(T, cfg.num_heads, cfg.head_dim), pos, cfg.rope_theta)
        k = apply_rope((x @ lp["wk"]).reshape(T, cfg.num_kv_heads, cfg.head_dim), pos, cfg.rope_theta)
        v = (x @ lp["wv"]).reshape(T, cfg.num_kv_heads, cfg.head_dim)
        g = cfg.num_heads // cfg.num_kv_heads
        kr = jnp.repeat(k, g, axis=1)
        vr = jnp.repeat(v, g, axis=1)
        s = jnp.einsum("thd,shd->hts", q.astype(jnp.float32), kr.astype(jnp.float32))
        s = s / np.sqrt(cfg.head_dim)
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None], s, -1e30)
        a = jnp.einsum("hts,shd->thd", jax.nn.softmax(s, -1), vr.astype(jnp.float32)).astype(cfg.dtype)
        h = h + a.reshape(T, -1) @ lp["wo"]
        x = rms_norm(h, lp["post_norm"], cfg.rms_norm_eps)
        h = h + naive_moe(x, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
                          cfg.num_experts_per_tok)
    x = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"] if cfg.tie_word_embeddings else params["lm_head"]
    return jnp.einsum("td,vd->tv", x.astype(jnp.float32), head.astype(jnp.float32))


def test_mixtral_paged_prefill_matches_naive(setup):
    cfg, model, params = setup
    ref = naive_forward_moe(cfg, params, PROMPT)[-1]
    Tn, T_pad = len(PROMPT), 8
    tokens = np.zeros(T_pad, np.int32)
    tokens[:Tn] = PROMPT
    positions = np.arange(T_pad, dtype=np.int32)
    kv = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits, _ = model.prefill(
        params, kv, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref), atol=2e-4)


def test_mixtral_ep_sharded_prefill(setup):
    """Experts sharded over ep=4 x tp=2 mesh produce identical logits."""
    cfg, model, params = setup
    devices = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devices, ("ep", "tp"))
    params_sh = jax.device_put(params, model.param_shardings(mesh))
    kv = jax.device_put(
        model.init_kv_cache(NUM_PAGES, PAGE_SIZE), model.kv_cache_sharding(mesh)
    )
    Tn, T_pad = len(PROMPT), 8
    tokens = np.zeros(T_pad, np.int32)
    tokens[:Tn] = PROMPT
    positions = np.arange(T_pad, dtype=np.int32)
    logits_sh, _ = jax.jit(model.prefill)(
        params_sh, kv, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )
    ref = naive_forward_moe(cfg, params, PROMPT)[-1]
    np.testing.assert_allclose(np.asarray(logits_sh), np.asarray(ref), atol=2e-4)


def test_mixtral_in_engine():
    """MixtralModel through the full async engine (registry dispatch)."""
    import asyncio

    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    from tests.test_engine import tiny_engine_config

    cfg = tiny_engine_config(model_id="tiny-moe")
    eng = AsyncJaxEngine(cfg)

    async def body():
        await eng.start()
        req = EngineRequest(
            request_id="m1",
            token_ids=[5, 9, 2, 77],
            sampling=SamplingParams(temperature=0.0, max_tokens=4),
        )
        toks = []
        async for out in eng.generate(req):
            if out.token is not None:
                toks.append(out.token)
        await eng.shutdown()
        return toks

    toks = asyncio.run(body())
    assert len(toks) == 4
