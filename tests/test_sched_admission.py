"""Admission-order unit tests (no JAX): the max_model_len rejection is pure
host work and must run BEFORE the per-step fairness-cap break, so an oversized
prompt at the queue head fails in the same scheduler step instead of stalling
behind the cap (ADVICE r5)."""

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.page_table import PageAllocator
from dynamo_tpu.engine.scheduler import EngineRequest, RunningSeq, Scheduler


class _StubRunner:
    """Just enough runner surface for Scheduler._admit's control flow."""

    packed_prefill_mode = False

    lora_store = None

    def write_token_slots(self, slots, tokens):  # pragma: no cover
        pass

    def set_slot_lora(self, slot, lora_slot):  # pragma: no cover
        pass


def _scheduler(max_model_len=64, cap=1):
    cfg = EngineConfig(
        model_id="tiny", page_size=4, num_pages=64, max_seqs=4,
        max_model_len=max_model_len, prefill_batches_per_step=cap,
    )
    alloc = PageAllocator(cfg.num_pages, cfg.page_size)
    return Scheduler(cfg, _StubRunner(), alloc)


def _occupy_decode_slot(sched):
    """A running decode sequence (prefill done) makes the fairness cap bind."""
    seq = RunningSeq(
        req=EngineRequest("running", [1, 2, 3]), slot=0, prompt_len=3,
        cached_len=0, prefill_pos=None,
    )
    sched.slots[0] = seq
    return seq


def test_oversized_prompt_rejected_before_fairness_cap(monkeypatch):
    sched = _scheduler(max_model_len=8, cap=1)
    _occupy_decode_slot(sched)

    # admission itself stubbed out: this test is about _admit's ORDERING, not
    # the prefill dispatch it triggers
    started = []

    def fake_start(req, slot, lora_slot=0):
        sched.slots[slot] = RunningSeq(
            req=req, slot=slot, prompt_len=len(req.token_ids), cached_len=0,
            prefill_pos=None,
        )
        started.append(req.request_id)

    monkeypatch.setattr(sched, "_start_sequence", fake_start)

    sched.add_request(EngineRequest("ok-1", [1] * 4))
    sched.add_request(EngineRequest("too-long", [1] * 99))  # > max_model_len
    sched.add_request(EngineRequest("ok-2", [1] * 4))

    outputs = sched._admit()

    # ok-1 consumed the per-step cap; the oversized request must STILL fail in
    # this same step (pure rejection, no chip work), leaving ok-2 to wait
    assert started == ["ok-1"]
    errors = [o for o in outputs if o.finish_reason == "error"]
    assert [o.request_id for o in errors] == ["too-long"]
    assert [r.request_id for r in sched.waiting] == ["ok-2"]


def test_oversized_rejection_does_not_consume_the_cap(monkeypatch):
    sched = _scheduler(max_model_len=8, cap=1)
    _occupy_decode_slot(sched)
    started = []

    def fake_start(req, slot, lora_slot=0):
        sched.slots[slot] = RunningSeq(
            req=req, slot=slot, prompt_len=len(req.token_ids), cached_len=0,
            prefill_pos=None,
        )
        started.append(req.request_id)

    monkeypatch.setattr(sched, "_start_sequence", fake_start)

    # oversized at the HEAD: rejected immediately, and the request behind it
    # still gets this step's one capped start
    sched.add_request(EngineRequest("too-long", [1] * 99))
    sched.add_request(EngineRequest("ok-1", [1] * 4))

    outputs = sched._admit()
    assert [o.request_id for o in outputs if o.finish_reason == "error"] == ["too-long"]
    assert started == ["ok-1"]
    assert not sched.waiting
