"""KV-aware routing effectiveness: on prefix-heavy traffic across two real
engines, routing by radix-tree overlap must recover ~all prefix tokens from
cache while random routing forfeits roughly half — the mechanism behind the
reference's 3x TTFT / 2x latency claim for KV-aware routing (reference:
docs/architecture.md:76-87, BASELINE.md parity checkpoint #2).
"""

import asyncio
import random

import pytest

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest
from dynamo_tpu.llm.kv_router.indexer import KvIndexer, RouterEvent

from tests.test_engine import _collect, tiny_engine_config

pytestmark = pytest.mark.slow

BS = 4  # kv block size == page size


def _mk_engines(n):
    engines = []
    indexer = KvIndexer(kv_block_size=BS)

    async def boot():
        for i in range(n):
            sink = (lambda wid: (
                lambda ev: indexer.apply_event(RouterEvent(worker_id=wid, event=ev))
            ))(i)
            eng = AsyncJaxEngine(
                tiny_engine_config(page_size=BS, num_pages=128, max_seqs=4),
                kv_event_sink=sink,
            )
            await eng.start()
            engines.append(eng)

    asyncio.run(boot())
    return engines, indexer


def _run_workload(engines, indexer, kv_aware: bool, sessions=4, turns=8) -> int:
    """Prefix-heavy multi-turn replay; returns total RECOMPUTED prefill tokens
    (the TTFT driver: tokens the chosen worker had to prefill because its
    cache lacked them)."""
    rng = random.Random(42)
    total_recompute = 0
    histories = {
        s: [100 + 31 * s + j for j in range(12)]  # distinct 3-block roots
        for s in range(sessions)
    }

    async def one(eng, rid, prompt):
        req = EngineRequest(
            request_id=rid,
            token_ids=list(prompt),
            sampling=SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True),
        )
        toks, _, cached = await _collect(eng, req)
        return toks, cached

    r = 0
    for turn in range(turns):
        for s in range(sessions):
            prompt = histories[s]
            if kv_aware:
                scores = indexer.find_matches_for_request(prompt).scores
                wid = max(scores, key=scores.get) if scores else rng.randrange(len(engines))
            else:
                wid = rng.randrange(len(engines))
            toks, cached = asyncio.run(one(engines[wid], f"{kv_aware}-{s}-{turn}", prompt))
            total_recompute += len(prompt) - cached
            # multi-turn growth: the answer + a new user turn extend the history
            histories[s] = prompt + toks + [7 + r % 90]
            r += 1
    return total_recompute


def test_kv_routing_beats_random_on_prefix_heavy_traffic():
    engines, indexer = _mk_engines(4)
    try:
        recompute_kv = _run_workload(engines, indexer, kv_aware=True)
    finally:
        for e in engines:
            asyncio.run(e.shutdown())

    engines2, indexer2 = _mk_engines(4)
    try:
        recompute_random = _run_workload(engines2, indexer2, kv_aware=False)
    finally:
        for e in engines2:
            asyncio.run(e.shutdown())

    # KV-aware pins every session to the worker holding its prefix, so only
    # genuinely-new tokens are prefilled; random routing lands each turn on a
    # worker whose cache is stale-or-empty for that session most of the time
    assert recompute_kv > 0
    assert recompute_random >= 2 * recompute_kv, (
        f"kv-aware recomputed {recompute_kv} prefill tokens, "
        f"random recomputed {recompute_random}"
    )
