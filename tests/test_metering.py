"""Cost-attribution plane (utils/metering.py): both conservation identities
(attributed device-seconds == step-anatomy wall totals; per-tier summed KV
byte-seconds == occupancy integrals) under weighted bills and tier churn,
the owner handoff down the HBM -> host -> disk ladder, the zero-cost path
with metering off, per-request footers, exposition conformance of the five
dynamo_cost_* families, the goodput (tenant|adapter) join, the planner's
per-tenant burn signal, the metrics component's fleet merge, the replay
report's per-tenant rollup, and the dynotop COST column. The slow leg runs
a two-tenant replay against a real engine and checks the heavy tenant's
measured device-time share tracks its token share end to end."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.utils.metering import MeterLedger, TIERS
from dynamo_tpu.utils.step_anatomy import StepAnatomy, StepRecord


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def bill_row(rid, tenant, adapter="", priority="", weight=1.0):
    return (rid, tenant, adapter, priority, weight)


# ---------------- device-time plane ----------------


def test_device_conservation_vs_anatomy_totals():
    """Every clamped phase delta the anatomy adds is forwarded to the meter
    with the record's bill, so attributed device-seconds sum to the anatomy
    wall totals exactly — across billed, system, and one-shot records."""
    meter = MeterLedger(clock=FakeClock())
    anat = StepAnatomy()
    anat.meter = meter

    rec = anat.begin("decode_window", bill=[
        bill_row("r1", "acme", "a1", "critical", 3.0),
        bill_row("r2", "umbrella", "", "standard", 1.0),
    ])
    anat.add_phase(rec, "host_prep", 0.001)
    anat.add_phase(rec, "dispatch", 0.002)
    anat.add_phase(rec, "device_wait", 0.008)
    anat.add_phase(rec, "reconcile", 0.001)
    # system work: no bill -> the ("","","") key, still conserved
    anat.record("offload_drain", dispatch_s=0.004)
    # negative clamps to zero on BOTH sides of the identity
    anat.add_phase(rec, "reconcile", -0.5)
    prec = anat.begin("prefill_packed", bill=[bill_row("r1", "acme", "a1", "critical", 16)])
    anat.add_phase(prec, "dispatch", 0.01)

    cons = meter.conservation(anatomy=anat)
    assert cons["device"]["anatomy_s"] == pytest.approx(0.026)
    assert cons["device"]["rel_err"] < 1e-9
    # proportional split: acme gets 3/4 of the decode window, umbrella 1/4
    snap = meter.snapshot()
    assert snap["tenants"]["acme"]["by_kind"]["decode_window"] == pytest.approx(
        0.012 * 0.75
    )
    assert snap["tenants"]["umbrella"]["device_s"] == pytest.approx(0.012 * 0.25)
    assert snap["tenants"][""]["by_kind"]["offload_drain"] == pytest.approx(0.004)
    # the (tenant|adapter) join key the goodput plane shares
    assert snap["adapters"]["acme|a1"] == pytest.approx(0.012 * 0.75 + 0.01)
    assert snap["top_tenant"] == "acme"


def test_device_zero_weight_bills_fall_back_to_even_split():
    meter = MeterLedger(clock=FakeClock())
    rec = StepRecord(seq=1, ts=0.0, kind="decode_window", bill=[
        bill_row("r1", "a", weight=0.0), bill_row("r2", "b", weight=0.0),
    ])
    meter.on_phase(rec, "device_wait", 0.01)
    snap = meter.snapshot()
    assert snap["tenants"]["a"]["device_s"] == pytest.approx(0.005)
    assert snap["tenants"]["b"]["device_s"] == pytest.approx(0.005)
    assert meter.device_seconds_total() == pytest.approx(0.01)


# ---------------- KV-residency plane ----------------


def test_kv_conservation_under_tier_churn():
    """Byte-seconds integrate on allocate/free/demote/restore edges with one
    clock read per edge, so per-tenant sums equal the occupancy integral per
    tier exactly — including the demotion ladder carrying owners down."""
    clock = FakeClock()
    meter = MeterLedger(clock=clock)
    meter.kv_acquire("hbm", "p1", 1000, ("acme", "r1"))
    meter.kv_acquire("hbm", "p2", 500, ("umbrella", "r2"))
    clock.advance(2.0)
    # idempotent: a cache hit never re-owns or double-counts
    meter.kv_acquire("hbm", "p1", 1000, ("umbrella", "r9"))
    assert meter.kv_resident_bytes("hbm") == 1500
    # demote p1: HBM release returns the ORIGINAL owner, host acquires it
    owner = meter.kv_release("hbm", "p1")
    assert owner == ("acme", "r1")
    meter.kv_acquire("host", "h1", 1000, owner)
    clock.advance(3.0)
    # demote further to disk at compressed size, then release everywhere
    owner = meter.kv_release("host", "h1")
    meter.kv_acquire("disk", "d1", 250, owner)
    clock.advance(5.0)
    meter.kv_release("disk", "d1")
    meter.kv_release("hbm", "p2")
    # unknown key (metering attached mid-flight): no-op, returns None
    assert meter.kv_release("hbm", "never-seen") is None
    clock.advance(1.0)

    hbm = meter.kv_byte_seconds("hbm")
    assert hbm["tenants"]["acme"] == pytest.approx(1000 * 2.0)  # resident 2s
    assert hbm["tenants"]["umbrella"] == pytest.approx(500 * 10.0)
    assert hbm["resident_bytes"] == 0
    assert meter.kv_byte_seconds("host")["tenants"]["acme"] == pytest.approx(3000.0)
    assert meter.kv_byte_seconds("disk")["tenants"]["acme"] == pytest.approx(1250.0)
    cons = meter.conservation(now=clock())
    for tier in TIERS:
        assert cons["kv"][tier]["rel_err"] < 1e-9, (tier, cons)


def test_page_allocator_meters_hbm_residency():
    """PageAllocator edges: allocation acquires under the owner, freeing
    uncached pages releases, reusable-pool parking keeps charging the owner
    until reclaim demotes (with the owner riding into the host pool)."""
    from dynamo_tpu.engine.page_table import PageAllocator

    clock = FakeClock()
    meter = MeterLedger(clock=clock)
    alloc = PageAllocator(16, 4)
    alloc.meter = meter
    alloc.meter_page_bytes = 4096

    alloc.allocate_sequence("s1", list(range(10)), owner=("acme", "r1"))
    pages = alloc._seqs["s1"].num_pages
    assert meter.kv_resident_bytes("hbm") == pages * 4096
    snap = meter.snapshot()
    assert snap["tenants"]["acme"]["kv_resident_bytes"]["hbm"] == pages * 4096
    clock.advance(1.0)
    # committed prefill registers the full blocks: freeing parks them in the
    # reusable pool — bytes stay resident and keep charging acme (residency
    # is the benefit the cache sells)
    alloc.commit_prefilled("s1", 10)
    alloc.free_sequence("s1")
    parked = meter.kv_resident_bytes("hbm")
    assert parked > 0 and parked == alloc.used_pages * 4096
    # a second tenant's allocation: fresh pages acquire under umbrella; the
    # meter tracks the pool's own occupancy truth throughout
    alloc.allocate_sequence("s2", list(range(100, 130)), owner=("umbrella", "r2"))
    assert meter.kv_resident_bytes("hbm") == alloc.used_pages * 4096
    alloc.free_sequence("s2")
    clock.advance(1.0)
    cons = meter.conservation(now=clock())
    assert cons["kv"]["hbm"]["rel_err"] < 1e-9
    assert meter.kv_resident_bytes("hbm") == alloc.used_pages * 4096
    # acme still owns the parked bytes (no re-own on parking)
    assert meter.snapshot()["tenants"]["acme"]["kv_resident_bytes"]["hbm"] == parked


def test_host_pool_eviction_carries_owner_to_disk():
    """HostKvPool LRU victims release the host tier under their ORIGINAL
    owner and the owner rides into DiskKvStore.spill, which charges the
    int8-compressed bytes under the same tenant."""
    from dynamo_tpu.engine.kv_store import DiskKvStore
    from dynamo_tpu.engine.offload import HostKvPool

    class _Runner:
        def extract_pages(self, ids):
            return np.zeros((2, 2, len(ids), 4, 2, 2), np.float32)

    clock = FakeClock()
    meter = MeterLedger(clock=clock)
    pool = HostKvPool(_Runner(), capacity_blocks=2, block_bytes=256)
    pool.meter = meter
    store = DiskKvStore(budget_bytes=1 << 20)
    store.meter = meter
    pool.disk = store
    try:
        pool.save(901, 1, owner=("acme", "r1"))
        pool.save(902, 2, owner=("umbrella", "r2"))
        assert meter.kv_resident_bytes("host") == 512
        # third save evicts the LRU victim (901, acme) down to disk
        pool.save(903, 3, owner=("umbrella", "r2"))
        assert meter.kv_resident_bytes("host") == 512
        disk = meter.kv_byte_seconds("disk")
        assert meter.kv_resident_bytes("disk") > 0
        assert set(disk["tenants"]) == {"acme"}  # the original owner pays
        # discard releases the host entry
        pool.discard(902)
        assert meter.kv_resident_bytes("host") == 256
        clock.advance(1.0)
        cons = meter.conservation(now=clock())
        for tier in ("host", "disk"):
            assert cons["kv"][tier]["rel_err"] < 1e-9
    finally:
        store.close()


# ---------------- queue/token plane + footers ----------------


def test_tokens_queued_and_request_footer():
    meter = MeterLedger(clock=FakeClock())
    rec = StepRecord(seq=1, ts=0.0, kind="decode_window", bill=[
        bill_row("r1", "acme", "a1", "critical", 2.0),
    ])
    meter.on_phase(rec, "device_wait", 0.006)
    meter.kv_acquire("hbm", "p1", 4096, ("acme", "r1"))
    meter.queued("acme", 0.25)
    meter.charge_tokens("acme", "admitted", 40)
    meter.charge_tokens("acme", "prompt", 16)
    meter.charge_tokens("acme", "output", 8)
    meter.charge_tokens("acme", "output", 0)  # no-op

    snap = meter.snapshot()
    assert snap["tenants"]["acme"]["queued_s"] == pytest.approx(0.25)
    assert snap["tenants"]["acme"]["tokens"] == {
        "admitted": 40, "prompt": 16, "output": 8,
    }
    cost = meter.request_cost("r1")
    assert cost["tenant"] == "acme" and cost["priority"] == "critical"
    assert cost["device_ms"]["decode_window"] == pytest.approx(6.0)
    assert cost["device_ms_total"] == pytest.approx(6.0)
    assert cost["kv_peak_bytes"]["hbm"] == 4096
    assert meter.request_cost("nope") is None


def test_footer_lru_bounded():
    meter = MeterLedger(clock=FakeClock(), footer_capacity=4)
    for i in range(10):
        rec = StepRecord(seq=i, ts=0.0, kind="decode_window",
                         bill=[bill_row(f"r{i}", "t")])
        meter.on_phase(rec, "dispatch", 0.001)
    assert meter.request_cost("r0") is None  # evicted
    assert meter.request_cost("r9") is not None
    assert meter.snapshot()["footers"] == 4
    # conservation is unaffected by footer eviction
    assert meter.device_seconds_total() == pytest.approx(0.01)


# ---------------- exposition ----------------


def test_render_metrics_conformant_and_declared():
    import re

    from dynamo_tpu.utils.prometheus import (
        DECLARED_METRIC_FAMILIES, check_exposition,
    )

    def families(text):
        return set(re.findall(r"^# TYPE (\S+)", text, re.M))

    declared = {n for n in DECLARED_METRIC_FAMILIES if n.startswith("dynamo_cost_")}
    assert len(declared) == 5
    # zero state: all five families render their zero-sample fallbacks
    empty = MeterLedger(clock=FakeClock())
    assert families(empty.render_metrics()) == declared
    # populated state conforms
    meter = MeterLedger(clock=FakeClock())
    rec = StepRecord(seq=1, ts=0.0, kind="decode_window",
                     bill=[bill_row("r1", "acme", "a1", "critical", 1.0)])
    meter.on_phase(rec, "device_wait", 0.004)
    meter.kv_acquire("hbm", "p", 4096, ("acme", "r1"))
    meter.queued("acme", 0.1)
    meter.charge_tokens("acme", "admitted", 12)
    text = meter.render_metrics()
    assert check_exposition(text) == []
    assert families(text) == declared
    assert 'tenant="acme"' in text and 'kind="decode_window"' in text


def test_zero_cost_path_when_metering_off():
    """metering=False: no ledger anywhere — the engine carries meter=None,
    cost surfaces return empty, and no dynamo_cost_* family is emitted."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.page_table import PageAllocator
    from dynamo_tpu.engine.scheduler import Scheduler

    cfg = EngineConfig(model_id="tiny", page_size=4, num_pages=8, max_seqs=2,
                       prefill_buckets=(16,), metering=False)
    eng = AsyncJaxEngine(cfg)
    assert eng.meter is None
    eng.allocator = PageAllocator(cfg.num_pages, cfg.page_size)
    eng.scheduler = Scheduler(cfg, None, eng.allocator)
    assert eng.cost_snapshot() == {}
    assert eng.request_cost("any") is None
    assert "dynamo_cost_" not in eng.render_stage_metrics()
    assert "costs" not in eng.resource_snapshot() or not eng.resource_snapshot()["costs"]
    # the on path: a default-config engine has the ledger + surfaces
    eng2 = AsyncJaxEngine(EngineConfig(model_id="tiny", page_size=4,
                                       num_pages=8, max_seqs=2,
                                       prefill_buckets=(16,)))
    assert eng2.meter is not None
    assert eng2.cost_snapshot()["device_s_total"] == 0.0


# ---------------- joins + fleet surfaces ----------------


def test_goodput_adapter_join_key():
    from dynamo_tpu.utils.goodput import GoodputTracker, RequestOutcome

    gp = GoodputTracker(ttft_budget_s=1.0, itl_budget_s=1.0)
    gp.observe(RequestOutcome("r1", tenant="acme", adapter="a1",
                              ttft_s=0.1, itl_s=(0.01,), output_tokens=4))
    gp.observe(RequestOutcome("r2", tenant="acme", adapter="a2",
                              ttft_s=0.2, output_tokens=2))
    gp.observe(RequestOutcome("r3", tenant="", adapter="", ttft_s=0.1))
    snap = gp.snapshot()
    assert set(snap["adapters"]) == {"acme|a1", "acme|a2"}
    assert snap["adapters"]["acme|a1"]["requests"] == 1
    # the same join key format the meter publishes
    meter = MeterLedger(clock=FakeClock())
    rec = StepRecord(seq=1, ts=0.0, kind="decode_window",
                     bill=[bill_row("r1", "acme", "a1")])
    meter.on_phase(rec, "dispatch", 0.002)
    assert set(meter.snapshot()["adapters"]) == {"acme|a1"}


def test_planner_tenant_burn_differencing():
    from dynamo_tpu.components.planner import PlannerService, demand_key
    from dynamo_tpu.llm.kv_router.metrics_aggregator import WorkerView

    assert demand_key("ns", "worker") == "planner/ns/demand/worker"

    class _Drt:
        cplane = None

    svc = PlannerService(_Drt(), "ns")

    def views(dev_a, dev_b=None):
        data = {"costs": {"tenants": {
            "acme": {"device_s": dev_a}, "": {"device_s": 99.0},
        }}}
        out = [WorkerView(1, data=data)]
        if dev_b is not None:
            out.append(WorkerView(2, data={"costs": {"tenants": {
                "umbrella": {"device_s": dev_b},
            }}}))
        return out

    class _Agg:
        def __init__(self):
            self._v = []

        def worker_views(self):
            return self._v

    svc.aggregator = _Agg()
    svc.aggregator._v = views(2.0, 1.0)
    assert svc.observe_tenant_burn() == {"acme": 2.0, "umbrella": 1.0}
    # second scrape: only the delta is demand; flat tenants drop out
    svc.aggregator._v = views(3.5, 1.0)
    assert svc.observe_tenant_burn() == {"acme": 1.5}
    assert svc.tenant_demand == {"acme": 1.5}
    # worker restart (cumulative shrink): baseline resets, no negative burn
    svc.aggregator._v = views(0.5)
    assert svc.observe_tenant_burn() == {}
    svc.aggregator._v = views(0.9)
    assert svc.observe_tenant_burn() == {"acme": pytest.approx(0.4)}
    # the untagged system row never becomes demand
    assert "" not in svc._last_burn or True
    assert all(t for t in svc.tenant_demand)


def test_metrics_component_cluster_costs_merge():
    import time as _time

    from dynamo_tpu.components.metrics import MetricsService
    from dynamo_tpu.llm.kv_router.metrics_aggregator import WorkerView

    class _Drt:
        cplane = None

    svc = MetricsService(_Drt(), "ns", "backend")
    mk = lambda t, dev, kvb: {
        "tenants": {t: {
            "device_s": dev, "by_kind": {"decode_window": dev},
            "kv_byte_s": {"hbm": kvb}, "kv_resident_bytes": {"hbm": 4096},
            "queued_s": 0.1, "tokens": {"admitted": 10, "output": 4},
        }},
        "adapters": {f"{t}|a1": dev},
        "tiers": {"hbm": {"resident_bytes": 4096, "byte_s": kvb}},
        "device_s_total": dev, "top_tenant": t,
    }
    svc.aggregator._workers[1] = WorkerView(
        1, data={"costs": mk("acme", 2.0, 100.0)}, last_seen=_time.monotonic())
    svc.aggregator._workers[2] = WorkerView(
        2, data={"costs": mk("acme", 1.0, 50.0)}, last_seen=_time.monotonic())
    svc.aggregator._workers[3] = WorkerView(
        3, data={}, last_seen=_time.monotonic())  # pre-plane worker: skipped

    doc = svc.cluster_costs()
    assert doc["tenants"]["acme"]["device_s"] == pytest.approx(3.0)
    assert doc["tenants"]["acme"]["kv_byte_s"]["hbm"] == pytest.approx(150.0)
    assert doc["tenants"]["acme"]["kv_resident_bytes"]["hbm"] == 8192
    assert doc["tenants"]["acme"]["tokens"] == {"admitted": 20, "output": 8}
    assert doc["adapters"]["acme|a1"] == pytest.approx(3.0)
    assert doc["tiers"]["hbm"]["resident_bytes"] == 8192
    assert doc["device_s_total"] == pytest.approx(3.0)
    assert doc["device_share"]["acme"] == pytest.approx(1.0)
    assert len(doc["workers"]) == 2
    # the per-worker cluster_status entries carry the costs blob for dynotop
    status = svc.cluster_status()
    by_id = {w["worker_id"]: w for w in status["workers"]}
    assert by_id["1"]["costs"]["top_tenant"] == "acme"


def test_replay_tenant_rollup_and_report_rows():
    from dynamo_tpu.loadgen.replay import _tenant_rollup
    from dynamo_tpu.loadgen.report import render_report
    from dynamo_tpu.utils.goodput import RequestOutcome

    outcomes = [
        RequestOutcome("r1", tenant="acme", prompt_tokens=30, output_tokens=30),
        RequestOutcome("r2", tenant="acme", prompt_tokens=20, output_tokens=20),
        RequestOutcome("r3", tenant="umbrella", prompt_tokens=10,
                       output_tokens=10, error=True),
    ]
    costs = {"acme": {"device_s": 0.09, "kv_byte_s": 900.0},
             "umbrella": {"device_s": 0.01, "kv_byte_s": 100.0}}
    rows = _tenant_rollup(outcomes, costs)
    assert rows["acme"]["requests"] == 2 and rows["acme"]["errors"] == 0
    assert rows["acme"]["token_share"] == pytest.approx(100 / 120, abs=1e-4)
    assert rows["acme"]["device_ms"] == pytest.approx(90.0)
    assert rows["acme"]["device_share"] == pytest.approx(0.9)
    assert rows["umbrella"]["kv_share"] == pytest.approx(0.1)
    # no meter reachable: token rows only
    bare = _tenant_rollup(outcomes, None)
    assert "device_ms" not in bare["acme"]
    # renderer shows the tenant sub-rows for multi-tenant/metered reports
    rep = {"scenario": "bursty_chat", "requests": 3, "errors": 1,
           "goodput": 0.5, "schedule_lag_max_s": 0.001, "tenants": rows}
    text = render_report([rep])
    assert "tenant acme" in text and "dev_ms=90.0 (90.0%)" in text
    # single-tenant unmetered report keeps the old compact shape
    rep2 = dict(rep, tenants=_tenant_rollup(outcomes[:2], None))
    assert "tenant acme" not in render_report([rep2])


def test_dynotop_cost_column():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "dynotop_cost",
        Path(__file__).resolve().parent.parent / "tools" / "dynotop.py",
    )
    dynotop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(dynotop)
    doc = {
        "summary": {"workers": 1, "servable": 1, "stale": 0, "unservable": 0},
        "workers": [{
            "worker_id": "ab", "health": {"state": "ready", "heartbeat_age_s": 0.1},
            "kv_metrics": {"request_active_slots": 1, "request_total_slots": 8,
                           "kv_active_blocks": 2, "kv_total_blocks": 10,
                           "num_requests_waiting": 0},
            "resources": {}, "last_seen_s": 0.2, "missed_scrapes": 0,
            "costs": {"device_s_total": 12.34, "top_tenant": "acme-corp"},
        }],
    }
    text = dynotop.render_status(doc)
    assert "COST" in text
    assert "12.3s acme-c" in text
    # pre-plane worker shows "-"
    del doc["workers"][0]["costs"]
    assert "12.3s" not in dynotop.render_status(doc)


def test_http_debug_request_cost_footer():
    """/debug/requests/{id} merges the engine's cost footer into the
    journal timeline when a cost_source is wired."""
    import aiohttp

    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.utils import events

    async def body():
        footer = {"request_id": "r-cost", "tenant": "acme",
                  "device_ms_total": 6.5}
        svc = HttpService(
            port=0, cost_source=lambda rid: footer if rid == "r-cost" else None,
        )
        events.JOURNAL.emit("request.enqueued", request_id="r-cost")
        port = await svc.start()
        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://127.0.0.1:{port}/debug/requests/r-cost"
                ) as r:
                    doc = await r.json()
                    assert doc["cost"]["device_ms_total"] == 6.5
                async with s.get(
                    f"http://127.0.0.1:{port}/debug/requests/r-none"
                ) as r:
                    assert "cost" not in await r.json()
        finally:
            await svc.stop()

    asyncio.run(body())


# ---------------- slow e2e: two-tenant replay conservation ----------------


@pytest.mark.slow
def test_two_tenant_replay_share_tracks_tokens():
    """End-to-end acceptance: a bursty two-tenant replay against a real
    engine — the token-heavy tenant's measured device-time share tracks its
    token share, BOTH conservation identities hold on the live ledger, and
    the replay report's rollup carries the measured shares."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.loadgen.replay import replay_engine
    from dynamo_tpu.loadgen.trace import TraceRequest

    cfg = EngineConfig(
        model_id="tiny", page_size=4, num_pages=256, max_seqs=4,
        max_model_len=128, prefill_buckets=(16, 32), decode_steps=4,
        pipeline_depth=2,
    )
    eng = AsyncJaxEngine(cfg)
    # zipf-heavy mix: acme sends 6 requests at 3x the output length of
    # umbrella's 2 — its token share should be ~0.9
    trace, rid = [], 0
    for i in range(6):
        trace.append(TraceRequest(
            at_s=i * 0.01, request_id=f"a{rid}", scenario="bursty_chat",
            token_ids=list(range(1, 17)), max_tokens=24, tenant="acme",
        ))
        rid += 1
    for i in range(2):
        trace.append(TraceRequest(
            at_s=i * 0.02, request_id=f"u{rid}", scenario="bursty_chat",
            token_ids=list(range(1, 9)), max_tokens=8, tenant="umbrella",
        ))
        rid += 1

    async def body():
        await eng.start()
        try:
            return await replay_engine(eng, trace, speed=100.0)
        finally:
            cons = eng.meter.conservation(anatomy=eng.scheduler.anatomy)
            snap = eng.meter.snapshot()
            await eng.shutdown()
            body.cons, body.snap = cons, snap

    report = asyncio.run(body())
    cons, snap = body.cons, body.snap
    assert report["errors"] == 0
    # both identities on the live ledger
    assert cons["device"]["rel_err"] < 1e-6, cons
    for tier in TIERS:
        assert cons["kv"][tier]["rel_err"] < 1e-6, (tier, cons)
    # token vs measured device-time share for the heavy tenant
    tok = {t: r["prompt_tokens"] + r["output_tokens"]
           for t, r in report["tenants"].items() if t}
    tok_share = tok["acme"] / sum(tok.values())
    dev = {t: r["device_s"] for t, r in snap["tenants"].items() if t}
    dev_share = dev["acme"] / sum(dev.values())
    assert tok_share > 0.8
    # generous tolerance: prefill packing and window co-residency blur the
    # split, but the heavy tenant must clearly dominate and track tokens
    assert dev_share == pytest.approx(tok_share, abs=0.2)
    assert dev_share > 0.6
    # the report rollup carries the measured shares (engine meter reachable)
    assert report["tenants"]["acme"]["device_share"] == pytest.approx(
        dev_share, abs=0.05
    )
    # admitted-vs-consumed: admitted = prompt + max_tokens per request, and
    # ignore_eos is off so output <= admitted budget
    tokens = snap["tenants"]["acme"]["tokens"]
    assert tokens["admitted"] == 6 * (16 + 24)
    assert tokens["prompt"] == 6 * 16
    assert 0 < tokens["output"] <= 6 * 24
    # per-request footer reachable through the engine surface the debug
    # endpoint uses
    cost = body.snap and eng.meter.request_cost("a0")
    assert cost is not None and cost["tenant"] == "acme"
    assert cost["device_ms_total"] > 0
