"""Disaggregated prefill/decode end-to-end: decode worker + prefill worker over
a live broker, KV blocks transferred over the TCP data plane.

Correctness bar: greedy generation through the disagg path must be token-exact
with a purely local engine (same weights), proving the injected KV equals the
locally-computed KV."""

import asyncio

import pytest

from dynamo_tpu.cplane.broker import Broker
from dynamo_tpu.disagg.decode_worker import DisaggDecodeEngine
from dynamo_tpu.disagg.prefill_worker import PrefillWorker
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest
from dynamo_tpu.llm.disagg_router import DisaggregatedRouter, DisaggRouterConf
from dynamo_tpu.runtime.distributed import DistributedRuntime

from tests.test_engine import tiny_engine_config


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow


async def collect(engine, req):
    toks = []
    finish = None
    async for out in engine.generate(req):
        if out.token is not None:
            toks.append(out.token)
        if out.finished:
            finish = out.finish_reason
    return toks, finish


def req_for(rid, prompt, n=6):
    return EngineRequest(
        request_id=rid,
        token_ids=list(prompt),
        sampling=SamplingParams(temperature=0.0, max_tokens=n),
    )


LONG_PROMPT = [5, 9, 2, 77, 31, 8, 100, 42, 17, 3, 60, 61]  # 12 tokens > threshold 6
SHORT_PROMPT = [5, 9, 2]


@pytest.mark.parametrize(
    "force_dcn,stream",
    [(False, True), (True, True), (True, False)],
    ids=["ici", "dcn-streamed", "dcn-monolithic"],
)
def test_disagg_matches_local(force_dcn, stream, monkeypatch):
    """force_dcn=False: same-process workers use the device (ICI) KV handoff.
    force_dcn=True: the decode engine looks remote, so KV rides the data
    plane — chunk-streamed v2 parts by default (stream=True), or the legacy
    monolithic single payload (stream=False); both must stay token-exact."""
    if force_dcn:
        from dynamo_tpu.disagg import ici

        monkeypatch.setattr(ici, "is_local", lambda worker_id: False)

    async def body():
        broker = Broker()
        port = await broker.start()
        addr = f"127.0.0.1:{port}"

        decode_rt = DistributedRuntime(cplane_address=addr)
        await decode_rt.connect()
        prefill_rt = DistributedRuntime(cplane_address=addr)
        await prefill_rt.connect()

        decode_inner = AsyncJaxEngine(tiny_engine_config())
        await decode_inner.start()
        prefill_engine = AsyncJaxEngine(tiny_engine_config(kv_stream=stream))
        await prefill_engine.start()
        local_engine = AsyncJaxEngine(tiny_engine_config())
        await local_engine.start()

        router = DisaggregatedRouter(
            "tiny", conf=DisaggRouterConf(max_local_prefill_length=6)
        )
        decode = DisaggDecodeEngine(
            decode_inner, decode_rt, "ns", "decoder", "tiny", disagg_router=router
        )
        await decode.start()
        prefill_worker = PrefillWorker(prefill_engine, prefill_rt, "ns", "tiny")
        await prefill_worker.start()

        try:
            from dynamo_tpu.disagg import ici

            transfers_before = ici.total_transfers()
            # long prompt -> remote prefill path
            expected, _ = await collect(local_engine, req_for("ref1", LONG_PROMPT))
            got, finish = await collect(decode, req_for("d1", LONG_PROMPT))
            assert got == expected, f"disagg {got} != local {expected}"
            assert finish == "length"
            assert decode.remote_prefills == 1
            assert prefill_worker.completed == 1
            # same-process workers take the device (ICI) handoff, and the
            # parked array is consumed on adoption; with the hub bypassed the
            # KV must have travelled as bytes instead
            if force_dcn:
                assert ici.total_transfers() == transfers_before
            else:
                assert ici.total_transfers() == transfers_before + 1
            assert ici.transfer_count() == 0
            if force_dcn and stream:
                # v2 streamed transfer actually ran: parts on the wire from
                # the prefill side, incremental scatters on the decode side
                assert prefill_worker.stream_parts >= 1
                assert prefill_worker.stream_requests == 1
                assert decode.parts_scattered >= 1
                assert decode.kv_server.parts_received >= 1
            elif force_dcn:
                assert prefill_worker.stream_parts == 0

            # short prompt stays local
            expected_s, _ = await collect(local_engine, req_for("ref2", SHORT_PROMPT))
            got_s, _ = await collect(decode, req_for("d2", SHORT_PROMPT))
            assert got_s == expected_s
            assert decode.local_prefills == 1

            # second long request: decode-side prefix cache now holds the
            # prompt blocks, so the disagg router sees a high prefix hit and
            # keeps it local
            got2, _ = await collect(decode, req_for("d3", LONG_PROMPT))
            assert got2 == expected
            assert decode.remote_prefills == 1  # unchanged: went local via cache
        finally:
            await prefill_worker.stop()
            await decode.shutdown()
            await prefill_engine.shutdown()
            await local_engine.shutdown()
            await decode_rt._shutdown_hook()
            await prefill_rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())


def test_disagg_streamed_multichunk_parity(monkeypatch):
    """A prompt spanning several prefill chunks exercises the real pipelined
    path: multiple parts per request (one per chunk boundary), striped across
    2 client lanes, scattered incrementally on the decode side — and the
    output must stay token-exact vs a purely local engine."""
    from dynamo_tpu.disagg import ici

    monkeypatch.setattr(ici, "is_local", lambda worker_id: False)
    # 44 tokens over (8,16) buckets -> chunks [0,16),[16,32),[32,44) -> 3
    # parts at page_size 4 (pages 0-4, 4-8, 8-11)
    prompt = [(7 * i + 3) % 90 + 1 for i in range(44)]

    async def body():
        broker = Broker()
        port = await broker.start()
        addr = f"127.0.0.1:{port}"
        decode_rt = DistributedRuntime(cplane_address=addr)
        await decode_rt.connect()
        prefill_rt = DistributedRuntime(cplane_address=addr)
        await prefill_rt.connect()

        cfg = dict(prefill_buckets=(8, 16), num_pages=128, max_model_len=64)
        decode_inner = AsyncJaxEngine(tiny_engine_config(**cfg))
        await decode_inner.start()
        prefill_engine = AsyncJaxEngine(
            tiny_engine_config(**cfg, kv_stream=True, kv_stream_lanes=2)
        )
        await prefill_engine.start()
        local_engine = AsyncJaxEngine(tiny_engine_config(**cfg))
        await local_engine.start()

        router = DisaggregatedRouter(
            "tiny", conf=DisaggRouterConf(max_local_prefill_length=6)
        )
        decode = DisaggDecodeEngine(
            decode_inner, decode_rt, "nsc", "decoder", "tiny", disagg_router=router
        )
        await decode.start()
        prefill_worker = PrefillWorker(prefill_engine, prefill_rt, "nsc", "tiny")
        await prefill_worker.start()

        try:
            expected, _ = await collect(local_engine, req_for("ref", prompt, n=6))
            got, _ = await collect(decode, req_for("d1", prompt, n=6))
            assert got == expected, f"streamed disagg {got} != local {expected}"
            assert decode.remote_prefills == 1
            # the multi-chunk prompt split into several parts, all scattered
            # before adoption; the client really striped across both lanes
            assert prefill_worker.stream_parts == 3
            assert decode.parts_scattered == 3
            assert decode.kv_server.parts_received == 3
            assert decode.kv_server.received == 1
            assert len(prefill_worker.kv_client._conns) == 2
            # the transfer actually moved wall-clock transfer time, and the
            # overlap accounting is bounded by it
            assert prefill_worker.stream_send_s >= 0.0
            assert 0.0 <= prefill_worker.stream_overlap_s <= (
                prefill_worker.stream_send_s + 1e-9
            )
        finally:
            await prefill_worker.stop()
            await decode.shutdown()
            await prefill_engine.shutdown()
            await local_engine.shutdown()
            await decode_rt._shutdown_hook()
            await prefill_rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())


@pytest.mark.parametrize("model_id", ["tiny-mla", "tiny-moe"])
def test_disagg_matches_local_mla_and_moe(model_id, monkeypatch):
    """The non-Llama cache layouts cross the disagg data plane byte-exact:
    DeepSeek MLA's latent wire format ([L, n, ps, latent_padded] — the vLLM
    patch's deepseek_v2.py section is why the reference patch exists) and
    Mixtral's k/v pools. Forced DCN so the KV travels as bytes, proving the
    wire serialization, not just the same-process device handoff."""
    from dynamo_tpu.disagg import ici

    monkeypatch.setattr(ici, "is_local", lambda worker_id: False)

    async def body():
        broker = Broker()
        port = await broker.start()
        addr = f"127.0.0.1:{port}"

        decode_rt = DistributedRuntime(cplane_address=addr)
        await decode_rt.connect()
        prefill_rt = DistributedRuntime(cplane_address=addr)
        await prefill_rt.connect()

        cfg = tiny_engine_config(model_id=model_id)
        decode_inner = AsyncJaxEngine(cfg)
        await decode_inner.start()
        prefill_engine = AsyncJaxEngine(cfg)
        await prefill_engine.start()
        local_engine = AsyncJaxEngine(cfg)
        await local_engine.start()

        router = DisaggregatedRouter(
            model_id, conf=DisaggRouterConf(max_local_prefill_length=6)
        )
        decode = DisaggDecodeEngine(
            decode_inner, decode_rt, "ns", "decoder", model_id, disagg_router=router
        )
        await decode.start()
        prefill_worker = PrefillWorker(prefill_engine, prefill_rt, "ns", model_id)
        await prefill_worker.start()

        try:
            expected, _ = await collect(local_engine, req_for("ref1", LONG_PROMPT))
            got, finish = await collect(decode, req_for("d1", LONG_PROMPT))
            assert got == expected, f"disagg {got} != local {expected}"
            assert finish == "length"
            assert decode.remote_prefills == 1
            assert prefill_worker.completed == 1
        finally:
            await prefill_worker.stop()
            await decode.shutdown()
            await prefill_engine.shutdown()
            await local_engine.shutdown()
            await decode_rt._shutdown_hook()
            await prefill_rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())


def test_disagg_pool_exhaustion_falls_back_to_local():
    """Remote-prefill allocation has no admission control (pages must exist
    before the prefill worker writes into them), so under page pressure the
    decode worker must fall back to the LOCAL path — whose scheduler queues
    the request until pages free — instead of failing the request with
    MemoryError (r4 bench post-mortem: this killed the disagg parity
    section and leaked HBM into every later section)."""
    prompt_a = list(range(5, 25))  # 20 tokens = 5 pages at ps=4
    prompt_b = list(range(40, 60))

    async def body():
        broker = Broker()
        port = await broker.start()
        addr = f"127.0.0.1:{port}"

        decode_rt = DistributedRuntime(cplane_address=addr)
        await decode_rt.connect()
        prefill_rt = DistributedRuntime(cplane_address=addr)
        await prefill_rt.connect()

        # pool of 8 pages (7 usable): ONE 5-page sequence fits, two do not
        tight = tiny_engine_config(num_pages=8, max_seqs=2, max_model_len=40)
        decode_inner = AsyncJaxEngine(tight)
        await decode_inner.start()
        prefill_engine = AsyncJaxEngine(tiny_engine_config())
        await prefill_engine.start()
        local_engine = AsyncJaxEngine(tiny_engine_config())
        await local_engine.start()

        router = DisaggregatedRouter(
            "tiny", conf=DisaggRouterConf(max_local_prefill_length=6)
        )
        decode = DisaggDecodeEngine(
            decode_inner, decode_rt, "ns", "decoder", "tiny", disagg_router=router
        )
        await decode.start()
        prefill_worker = PrefillWorker(prefill_engine, prefill_rt, "ns", "tiny")
        await prefill_worker.start()

        try:
            exp_a, _ = await collect(local_engine, req_for("ra", prompt_a))
            exp_b, _ = await collect(local_engine, req_for("rb", prompt_b))
            (got_a, _), (got_b, _) = await asyncio.gather(
                collect(decode, req_for("da", prompt_a)),
                collect(decode, req_for("db", prompt_b)),
            )
            assert got_a == exp_a and got_b == exp_b
            # at least one request had to take the local-fallback path
            assert decode.local_prefills >= 1
        finally:
            await prefill_worker.stop()
            await decode.shutdown()
            await prefill_engine.shutdown()
            await local_engine.shutdown()
            await decode_rt._shutdown_hook()
            await prefill_rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())


def test_disagg_tp_mismatch_prefill2_decode1():
    """Prefill worker at tp=2, decode worker at tp=1: the host-staged block
    transfer is layout-canonical, so differing mesh shardings reshard on
    placement — the analogue of the reference's tp_multiplier + kv_rearrange
    Triton path (reference: patch nixl.py _get_block_descs_ids, kv_rearrange.py)."""

    async def body():
        broker = Broker()
        port = await broker.start()
        addr = f"127.0.0.1:{port}"
        decode_rt = DistributedRuntime(cplane_address=addr)
        await decode_rt.connect()
        prefill_rt = DistributedRuntime(cplane_address=addr)
        await prefill_rt.connect()

        decode_inner = AsyncJaxEngine(tiny_engine_config(tp=1))
        await decode_inner.start()
        prefill_engine = AsyncJaxEngine(tiny_engine_config(tp=2))
        await prefill_engine.start()
        local_engine = AsyncJaxEngine(tiny_engine_config(tp=1))
        await local_engine.start()

        router = DisaggregatedRouter(
            "tiny", conf=DisaggRouterConf(max_local_prefill_length=6)
        )
        decode = DisaggDecodeEngine(
            decode_inner, decode_rt, "ns2", "decoder", "tiny", disagg_router=router
        )
        await decode.start()
        pw = PrefillWorker(prefill_engine, prefill_rt, "ns2", "tiny")
        await pw.start()
        try:
            expected, _ = await collect(local_engine, req_for("ref", LONG_PROMPT))
            got, _ = await collect(decode, req_for("d1", LONG_PROMPT))
            assert got == expected, f"tp-mismatch disagg {got} != local {expected}"
            assert decode.remote_prefills == 1
        finally:
            await pw.stop()
            await decode.shutdown()
            await prefill_engine.shutdown()
            await local_engine.shutdown()
            await decode_rt._shutdown_hook()
            await prefill_rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())


def test_disagg_router_decision_and_live_reload():
    async def body():
        broker = Broker()
        port = await broker.start()
        from dynamo_tpu.cplane.client import CplaneClient
        from dynamo_tpu.llm.disagg_router import config_key

        c = CplaneClient(f"127.0.0.1:{port}")
        await c.connect()
        router = DisaggregatedRouter(
            "m", conf=DisaggRouterConf(max_local_prefill_length=100), cplane=c
        )
        await router.start_watching()
        try:
            assert not router.prefill_remote(100, 0)
            assert router.prefill_remote(101, 0)
            assert not router.prefill_remote(150, 60)  # prefix hit reduces work
            assert not router.prefill_remote(500, 0, queue_depth=64)  # queue full

            # live threshold reload via control-plane put
            await c.kv_put(config_key("m"), b'{"max_local_prefill_length": 10}')
            for _ in range(50):
                if router.conf.max_local_prefill_length == 10:
                    break
                await asyncio.sleep(0.02)
            assert router.conf.max_local_prefill_length == 10
            assert router.prefill_remote(11, 0)
        finally:
            await router.stop()
            await c.close()
            await broker.stop()

    asyncio.run(body())


def test_disagg_cancellation_no_leaks():
    """Cancelling generate() mid-remote-prefill must leak neither decode-side
    pages nor parked ICI transfers, and the engine must keep serving."""

    async def body():
        broker = Broker()
        port = await broker.start()
        addr = f"127.0.0.1:{port}"
        decode_rt = DistributedRuntime(cplane_address=addr)
        await decode_rt.connect()
        prefill_rt = DistributedRuntime(cplane_address=addr)
        await prefill_rt.connect()

        decode_inner = AsyncJaxEngine(tiny_engine_config())
        await decode_inner.start()
        prefill_engine = AsyncJaxEngine(tiny_engine_config())
        await prefill_engine.start()

        router = DisaggregatedRouter(
            "tiny", conf=DisaggRouterConf(max_local_prefill_length=6)
        )
        decode = DisaggDecodeEngine(
            decode_inner, decode_rt, "ns", "decoder", "tiny", disagg_router=router
        )
        await decode.start()
        prefill_worker = PrefillWorker(prefill_engine, prefill_rt, "ns", "tiny")
        await prefill_worker.start()

        from dynamo_tpu.disagg import ici

        try:
            for delay in (0.0, 0.05, 0.3):
                task = asyncio.create_task(
                    collect(decode, req_for(f"c{delay}", LONG_PROMPT))
                )
                await asyncio.sleep(delay)
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, TimeoutError):
                    # wait_for's cancellation bookkeeping can surface either;
                    # a late cancel may even let the request complete normally
                    pass
                await asyncio.sleep(0.3)  # let cleanup + zombie reconcile run
                assert ici.transfer_count() == 0, "parked ICI transfer leaked"
                # decode-side sequence state must be fully released
                seqs = await decode_inner.run_on_engine(
                    lambda: list(decode_inner.allocator._seqs.keys())
                )
                assert not [s for s in seqs if s.startswith("c")], f"leaked seqs {seqs}"

            # engine still serves correctly after the cancellations
            expected, _ = await collect(decode, req_for("after", LONG_PROMPT))
            assert len(expected) == 6
        finally:
            await prefill_worker.stop()
            await decode.shutdown()
            await prefill_engine.shutdown()
            await decode_rt._shutdown_hook()
            await prefill_rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())


def test_disagg_survives_broker_restart(tmp_path):
    """Kill the broker under a live disagg deployment and restart it on the
    same port: the prefill consumer re-arms its pull, the decode worker's
    endpoints re-register, and a remote prefill completes token-exact —
    serving heals without restarting any worker."""
    async def body():
        persist = str(tmp_path / "broker.log")
        broker = Broker(persist_path=persist)
        port = await broker.start()
        live_broker = [broker]  # the currently-running broker (stops LAST)
        addr = f"127.0.0.1:{port}"

        decode_rt = DistributedRuntime(cplane_address=addr)
        await decode_rt.connect()
        prefill_rt = DistributedRuntime(cplane_address=addr)
        await prefill_rt.connect()
        for rt in (decode_rt, prefill_rt):
            rt.cplane.reconnect_window = 15.0
            rt.runtime.shutdown = lambda: None  # observe, don't die

        decode_inner = AsyncJaxEngine(tiny_engine_config())
        await decode_inner.start()
        prefill_engine = AsyncJaxEngine(tiny_engine_config())
        await prefill_engine.start()
        local_engine = AsyncJaxEngine(tiny_engine_config())
        await local_engine.start()

        router = DisaggregatedRouter(
            "tiny", conf=DisaggRouterConf(max_local_prefill_length=6)
        )
        decode = DisaggDecodeEngine(
            decode_inner, decode_rt, "nsr", "decoder", "tiny", disagg_router=router
        )
        await decode.start()
        prefill_worker = PrefillWorker(prefill_engine, prefill_rt, "nsr", "tiny")
        await prefill_worker.start()

        try:
            expected, _ = await collect(local_engine, req_for("ref", LONG_PROMPT))
            got, _ = await collect(decode, req_for("r1", LONG_PROMPT))
            assert got == expected
            assert decode.remote_prefills == 1

            # ---- broker dies and comes back on the same port ----
            await broker.stop()
            await asyncio.sleep(0.5)
            broker2 = Broker(port=port, persist_path=persist)
            await broker2.start()
            live_broker[0] = broker2

            # a FRESH long prompt (no cached prefix) must go remote again
            # once the session heals; allow time for reconnect + re-pull
            prompt2 = [p + 1 for p in LONG_PROMPT]
            expected2, _ = await collect(local_engine, req_for("ref2", prompt2))
            deadline = asyncio.get_running_loop().time() + 20
            got2 = None
            attempt = 0
            while asyncio.get_running_loop().time() < deadline:
                attempt += 1
                try:
                    got2, _ = await asyncio.wait_for(
                        collect(decode, req_for(f"r2-{attempt}", prompt2)), 10
                    )
                    break
                except Exception:
                    await asyncio.sleep(0.5)
            assert got2 == expected2, f"post-restart disagg {got2} != {expected2}"
            # >=: a timed-out-then-retried attempt may have completed too
            assert decode.remote_prefills >= 2
            assert prefill_worker.completed >= 2
        finally:
            await prefill_worker.stop()
            await decode.shutdown()
            await prefill_engine.shutdown()
            await local_engine.shutdown()
            await decode_rt._shutdown_hook()
            await prefill_rt._shutdown_hook()
            await live_broker[0].stop()

    asyncio.run(asyncio.wait_for(body(), 180))


def test_disagg_pool_specialization_counters():
    """Structural proof of the disagg mechanism on one host (VERDICT r4
    item 5): the single-chip bench can't see the specialization win in wall
    time, but the COUNTERS can — with a prefill worker joined, the decode
    engine's local prefill burden (prompt rows prefilled on its chip, the
    interference the reference's disagg removes) collapses to ~0 while
    output tokens stay exact, and its page-pressure events do not increase.
    Reference: docs/disagg_serving.md:14-100 (pool specialization)."""
    import numpy as np

    rng = np.random.default_rng(23)
    R = 6
    prompts = [rng.integers(1, 100, 16).tolist() for _ in range(R)]
    # pool sized so both arms run the same admission pattern (4 slots x 6
    # pages in flight) without tripping the pool-full local-prefill fallback
    # on the disagg side — the counters, not allocator luck, are the signal
    cfg = dict(page_size=4, num_pages=48, max_seqs=4, prefill_buckets=(8, 16, 32))

    async def run_aggregated():
        eng = AsyncJaxEngine(tiny_engine_config(**cfg))
        await eng.start()
        try:
            outs = await asyncio.gather(*[
                collect(eng, req_for(f"a{i}", prompts[i], n=8)) for i in range(R)
            ])
            sched = eng.scheduler
            return ([t for t, _ in outs], sched.local_prefill_rows,
                    sched.preempt_count + sched.pressure_drain_count)
        finally:
            await eng.shutdown()

    async def run_disagg():
        broker = Broker()
        port = await broker.start()
        addr = f"127.0.0.1:{port}"
        decode_rt = DistributedRuntime(cplane_address=addr)
        await decode_rt.connect()
        prefill_rt = DistributedRuntime(cplane_address=addr)
        await prefill_rt.connect()
        decode_inner = AsyncJaxEngine(tiny_engine_config(**cfg))
        await decode_inner.start()
        prefill_engine = AsyncJaxEngine(tiny_engine_config(**cfg))
        await prefill_engine.start()
        router = DisaggregatedRouter(
            "tiny", conf=DisaggRouterConf(max_local_prefill_length=4)
        )
        decode = DisaggDecodeEngine(
            decode_inner, decode_rt, "ns2", "decoder", "tiny", disagg_router=router
        )
        await decode.start()
        pw = PrefillWorker(prefill_engine, prefill_rt, "ns2", "tiny")
        await pw.start()
        try:
            outs = await asyncio.gather(*[
                collect(decode, req_for(f"d{i}", prompts[i], n=8)) for i in range(R)
            ])
            sched = decode_inner.scheduler
            return ([t for t, _ in outs], sched.local_prefill_rows,
                    sched.preempt_count + sched.pressure_drain_count,
                    decode.remote_prefills)
        finally:
            await pw.stop()
            await decode.shutdown()
            await prefill_engine.shutdown()
            await decode_rt._shutdown_hook()
            await prefill_rt._shutdown_hook()
            await broker.stop()

    agg_toks, agg_rows, agg_pressure = asyncio.run(run_aggregated())
    dis_toks, dis_rows, dis_pressure, remote = asyncio.run(run_disagg())

    # tokens exact through the disagg path (same weights, same prompts)
    assert dis_toks == agg_toks
    # aggregated paid every prompt row on the decode chip...
    assert agg_rows >= R * 16
    # ...the specialized decode pool pays (almost) none: prompts go remote
    assert remote == R
    assert dis_rows <= agg_rows * 0.2, (dis_rows, agg_rows)
    # and specialization must not ADD page-pressure events on the decode pool
    assert dis_pressure <= agg_pressure, (dis_pressure, agg_pressure)
