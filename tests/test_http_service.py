"""HTTP service E2E: OpenAI chat/completions over a real socket against the
tiny JAX engine (the reference's http-service test tier,
reference: lib/llm/tests/http-service.rs:35-465)."""

import asyncio
import json

import aiohttp
import pytest

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model
from dynamo_tpu.llm.echo import EchoEngine
from dynamo_tpu.llm.http.service import HttpService

from tests.test_engine import tiny_engine_config


@pytest.fixture(scope="module")
def server():
    """(loop, base_url, engine) — one loop for server + client calls."""
    loop = asyncio.new_event_loop()

    async def boot():
        engine = AsyncJaxEngine(tiny_engine_config())
        await engine.start()
        card = card_for_model("tiny")

        def extra_metrics() -> str:
            fm = engine.metrics()
            return "\n".join(f"llm_worker_{k} {v}" for k, v in fm.to_wire().items()) + "\n"

        service = HttpService(host="127.0.0.1", port=0, extra_metrics=extra_metrics)
        service.manager.add(build_pipeline(engine, card))

        echo_card = card_for_model("tiny")
        echo_card.display_name = "echo"
        service.manager.add(build_pipeline(EchoEngine(), echo_card))

        port = await service.start()
        return engine, service, f"http://127.0.0.1:{port}"

    engine, service, url = loop.run_until_complete(boot())
    yield loop, url, engine
    loop.run_until_complete(service.stop())
    loop.run_until_complete(engine.shutdown())
    loop.close()


def _post(loop, url, path, body):
    async def go():
        async with aiohttp.ClientSession() as s:
            async with s.post(url + path, json=body) as resp:
                return resp.status, await resp.json()

    return loop.run_until_complete(go())


def _get(loop, url, path):
    async def go():
        async with aiohttp.ClientSession() as s:
            async with s.get(url + path) as resp:
                return resp.status, await resp.text()

    return loop.run_until_complete(go())


CHAT_BODY = {
    "model": "tiny",
    "messages": [{"role": "user", "content": "hello"}],
    "max_tokens": 6,
    "temperature": 0,
}


def test_chat_unary(server):
    loop, url, _ = server
    status, body = _post(loop, url, "/v1/chat/completions", CHAT_BODY)
    assert status == 200
    assert body["object"] == "chat.completion"
    choice = body["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert isinstance(choice["message"]["content"], str)
    assert choice["finish_reason"] in ("stop", "length")
    assert body["usage"]["completion_tokens"] > 0


def test_chat_stream_matches_unary(server):
    loop, url, _ = server
    _, unary = _post(loop, url, "/v1/chat/completions", CHAT_BODY)

    async def stream():
        texts = []
        done = False
        async with aiohttp.ClientSession() as s:
            async with s.post(
                url + "/v1/chat/completions", json={**CHAT_BODY, "stream": True}
            ) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/event-stream")
                async for line in resp.content:
                    line = line.decode().strip()
                    if not line.startswith("data:"):
                        continue
                    data = line[5:].strip()
                    if data == "[DONE]":
                        done = True
                        break
                    chunk = json.loads(data)
                    delta = chunk["choices"][0]["delta"]
                    if delta.get("content"):
                        texts.append(delta["content"])
        return "".join(texts), done

    text, done = loop.run_until_complete(stream())
    assert done
    # greedy + same prompt => deterministic, stream text == unary content
    assert text == unary["choices"][0]["message"]["content"]


def test_completions_echo(server):
    loop, url, _ = server
    status, body = _post(
        loop, url, "/v1/completions",
        {"model": "echo", "prompt": "abcdef", "max_tokens": 100},
    )
    assert status == 200
    assert body["object"] == "text_completion"
    assert body["choices"][0]["text"] == "abcdef"


def test_model_not_found(server):
    loop, url, _ = server
    status, body = _post(loop, url, "/v1/chat/completions", {**CHAT_BODY, "model": "nope"})
    assert status == 404
    assert "error" in body


def test_bad_request(server):
    loop, url, _ = server
    status, body = _post(loop, url, "/v1/chat/completions", {"messages": []})
    assert status == 400


def test_context_length_exceeded_is_structured_400(server):
    """An over-long prompt is a CLIENT error: a structured 400 with the
    OpenAI error.code, not a 500 (the tiny card's context is 64 tokens)."""
    loop, url, _ = server
    status, body = _post(loop, url, "/v1/completions", {
        "model": "tiny", "prompt": list(range(1, 101)), "max_tokens": 4,
    })
    assert status == 400
    err = body["error"]
    assert err["type"] == "invalid_request_error"
    assert err["code"] == "context_length_exceeded"
    assert "context" in err["message"]


def test_context_length_exceeded_stream_mode_still_400(server):
    """stream=true must reject BEFORE any SSE bytes go out: a JSON 400 with
    the same structured code, never a 200 + mid-stream abort."""
    loop, url, _ = server
    status, body = _post(loop, url, "/v1/completions", {
        "model": "tiny", "prompt": list(range(1, 101)), "max_tokens": 4,
        "stream": True,
    })
    assert status == 400
    assert body["error"]["code"] == "context_length_exceeded"


def test_models_and_metrics(server):
    loop, url, _ = server
    status, text = _get(loop, url, "/v1/models")
    assert status == 200
    ids = [m["id"] for m in json.loads(text)["data"]]
    assert "tiny" in ids and "echo" in ids

    status, text = _get(loop, url, "/metrics")
    assert status == 200
    assert "llm_http_service_requests_total" in text
    assert 'model="tiny"' in text
    assert "llm_worker_request_total_slots" in text


def test_annotation_and_timing_events_in_stream(server):
    """ext.annotations ride the SSE stream as named events (reference:
    Annotated envelope); "timing" adds a per-request latency breakdown."""
    loop, url, _engine = server
    import aiohttp

    async def go():
        body = {
            "model": "tiny",
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 3,
            "temperature": 0.0,
            "stream": True,
            "ext": {"annotations": ["formatted_prompt", "token_ids", "timing"],
                    "ignore_eos": True},
        }
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{url}/v1/chat/completions", json=body) as resp:
                assert resp.status == 200
                return (await resp.read()).decode()

    text = loop.run_until_complete(go())
    events = {}
    lines = text.splitlines()
    for i, line in enumerate(lines):
        if line.startswith("event: "):
            events[line[7:]] = json.loads(lines[i + 1][6:])
    assert "formatted_prompt" in events
    assert isinstance(events["token_ids"], list) and events["token_ids"]
    timing = events["timing"]
    assert timing["output_tokens"] == 3
    assert timing["total_ms"] > 0
    assert timing["ttft_ms"] is None or timing["ttft_ms"] <= timing["total_ms"]

    # unary with annotations: response aggregates cleanly, no event leakage
    async def unary():
        body = {
            "model": "tiny",
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 2,
            "temperature": 0.0,
            "ext": {"annotations": ["timing"], "ignore_eos": True},
        }
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{url}/v1/chat/completions", json=body) as resp:
                return resp.status, await resp.json()

    status, out = loop.run_until_complete(unary())
    assert status == 200
    assert out["object"] == "chat.completion"
    assert out["id"] is not None


def test_completions_echo_param(server):
    loop, url, _engine = server
    status, out = _post(loop, url, "/v1/completions", {
        "model": "tiny", "prompt": "hello-prompt", "max_tokens": 3,
        "temperature": 0.0, "echo": True, "ext": {"ignore_eos": True},
    })
    assert status == 200
    text = out["choices"][0]["text"]
    assert text.startswith("hello-prompt")
    status, plain = _post(loop, url, "/v1/completions", {
        "model": "tiny", "prompt": "hello-prompt", "max_tokens": 3,
        "temperature": 0.0, "ext": {"ignore_eos": True},
    })
    assert text == "hello-prompt" + plain["choices"][0]["text"]


def test_completions_echo_with_logprobs_rejected(server):
    """OpenAI returns prompt-token logprobs for echo+logprobs; we don't compute
    prompt logprobs, so the combination is rejected explicitly rather than
    silently omitting them."""
    loop, url, _engine = server
    status, out = _post(loop, url, "/v1/completions", {
        "model": "tiny", "prompt": "hello", "max_tokens": 3,
        "echo": True, "logprobs": 2,
    })
    assert status == 400
    assert "echo" in out["error"]["message"]


def test_moe_serves_through_http():
    """A Mixtral-geometry MoE engine behind the full HTTP stack: unary chat
    and streamed SSE both produce tokens (the reference only reaches MoE
    models through engine adapters; here the native engine serves them)."""
    loop = asyncio.new_event_loop()

    async def boot():
        engine = AsyncJaxEngine(tiny_engine_config(model_id="tiny-moe"))
        await engine.start()
        card = card_for_model("tiny-moe")
        service = HttpService(host="127.0.0.1", port=0)
        service.manager.add(build_pipeline(engine, card))
        port = await service.start()
        return engine, service, f"http://127.0.0.1:{port}"

    engine, service, url = loop.run_until_complete(boot())
    try:
        body = {
            "model": card_for_model("tiny-moe").display_name,
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 6,
            "temperature": 0,
            "ext": {"ignore_eos": True},
        }
        status, out = _post(loop, url, "/v1/chat/completions", body)
        assert status == 200
        assert out["usage"]["completion_tokens"] == 6

        async def stream():
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    url + "/v1/chat/completions", json={**body, "stream": True}
                ) as resp:
                    assert resp.status == 200
                    raw = await resp.text()
            assert raw.rstrip().endswith("data: [DONE]")
            return raw

        raw = loop.run_until_complete(stream())
        assert '"finish_reason": "length"' in raw or '"finish_reason":"length"' in raw
    finally:
        loop.run_until_complete(service.stop())
        loop.run_until_complete(engine.shutdown())
        loop.close()
