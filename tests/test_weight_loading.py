"""HF checkpoint loading round-trips: synthesize an HF-style safetensors
checkpoint from randomly-initialized params via the inverse name/layout
mapping, load it through the registry, and require identical prefill logits.

This validates the name mapping, transposes, expert stacking, and the
kv_b_proj k-up/v-up split without needing real checkpoints (zero-egress env);
reference: launch/dynamo-run/src/hub.rs resolves HF repos, here local dirs.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from safetensors.numpy import save_file

from dynamo_tpu.models.registry import load_model

PROMPT = np.array([5, 9, 2, 77, 31, 8], dtype=np.int32)


def _prefill_logits(model, params, num_pages=16, page_size=4):
    kv = model.init_kv_cache(num_pages, page_size)
    T = len(PROMPT)
    pt = np.array([3, 5, 7, 0, 0, 0, 0, 0], np.int32)
    positions = np.arange(8, dtype=np.int32)
    tokens = np.zeros(8, np.int32)
    tokens[:T] = PROMPT
    logits, _ = model.prefill(
        params, kv, jnp.array(tokens), jnp.array(positions),
        jnp.array(pt), jnp.array(positions < T), jnp.array(T - 1),
    )
    return np.asarray(logits)


def _np(x):
    return np.asarray(x, np.float32)


def _T(x):
    # safetensors writes the raw buffer of non-contiguous views (silently
    # wrong for transposes) — always materialize the transpose
    return np.ascontiguousarray(_np(x).T)


def test_llama_checkpoint_roundtrip(tmp_path):
    hf_cfg = {
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": 128,
        "hidden_size": 32,
        "intermediate_size": 64,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "head_dim": 8,
        "rope_theta": 10000.0,
        "rms_norm_eps": 1e-5,
    }
    (tmp_path / "config.json").write_text(json.dumps(hf_cfg))

    from dynamo_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.from_hf_config(hf_cfg)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.key(7))

    tensors = {
        "model.embed_tokens.weight": _np(params["embed"]),
        "model.norm.weight": _np(params["final_norm"]),
        "lm_head.weight": _np(params["lm_head"]),
    }
    lw = params["layers"]
    for l in range(cfg.num_layers):
        pre = f"model.layers.{l}."
        tensors[pre + "input_layernorm.weight"] = _np(lw["input_norm"][l])
        tensors[pre + "self_attn.q_proj.weight"] = _T(lw["wq"][l])
        tensors[pre + "self_attn.k_proj.weight"] = _T(lw["wk"][l])
        tensors[pre + "self_attn.v_proj.weight"] = _T(lw["wv"][l])
        tensors[pre + "self_attn.o_proj.weight"] = _T(lw["wo"][l])
        tensors[pre + "post_attention_layernorm.weight"] = _np(lw["post_norm"][l])
        tensors[pre + "mlp.gate_proj.weight"] = _T(lw["gate"][l])
        tensors[pre + "mlp.up_proj.weight"] = _T(lw["up"][l])
        tensors[pre + "mlp.down_proj.weight"] = _T(lw["down"][l])
    save_file(tensors, str(tmp_path / "model.safetensors"))

    loaded_model, loaded_params = load_model(str(tmp_path))
    np.testing.assert_allclose(
        _prefill_logits(loaded_model, loaded_params),
        _prefill_logits(model, params),
        atol=1e-3,
    )


def test_mixtral_checkpoint_roundtrip(tmp_path):
    hf_cfg = {
        "architectures": ["MixtralForCausalLM"],
        "model_type": "mixtral",
        "vocab_size": 128,
        "hidden_size": 32,
        "intermediate_size": 48,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "head_dim": 8,
        "num_local_experts": 4,
        "num_experts_per_tok": 2,
    }
    (tmp_path / "config.json").write_text(json.dumps(hf_cfg))

    from dynamo_tpu.models.mixtral import MixtralConfig, MixtralModel

    cfg = MixtralConfig.from_hf_config(hf_cfg)
    # huge capacity => exact routing for the comparison
    from dataclasses import replace

    cfg = replace(cfg, moe_capacity_factor=8.0)
    model = MixtralModel(cfg)
    params = model.init_params(jax.random.key(8))

    tensors = {
        "model.embed_tokens.weight": _np(params["embed"]),
        "model.norm.weight": _np(params["final_norm"]),
        "lm_head.weight": _np(params["lm_head"]),
    }
    lw = params["layers"]
    for l in range(cfg.num_layers):
        pre = f"model.layers.{l}."
        tensors[pre + "input_layernorm.weight"] = _np(lw["input_norm"][l])
        tensors[pre + "self_attn.q_proj.weight"] = _T(lw["wq"][l])
        tensors[pre + "self_attn.k_proj.weight"] = _T(lw["wk"][l])
        tensors[pre + "self_attn.v_proj.weight"] = _T(lw["wv"][l])
        tensors[pre + "self_attn.o_proj.weight"] = _T(lw["wo"][l])
        tensors[pre + "post_attention_layernorm.weight"] = _np(lw["post_norm"][l])
        tensors[pre + "block_sparse_moe.gate.weight"] = _T(lw["router"][l])
        for e in range(cfg.num_experts):
            epre = pre + f"block_sparse_moe.experts.{e}."
            tensors[epre + "w1.weight"] = _T(lw["w_gate"][l, e])
            tensors[epre + "w3.weight"] = _T(lw["w_up"][l, e])
            tensors[epre + "w2.weight"] = _T(lw["w_down"][l, e])
    save_file(tensors, str(tmp_path / "model.safetensors"))

    loaded_model, loaded_params = load_model(str(tmp_path))
    object.__setattr__(loaded_model.config, "moe_capacity_factor", 8.0)
    np.testing.assert_allclose(
        _prefill_logits(loaded_model, loaded_params),
        _prefill_logits(model, params),
        atol=1e-3,
    )


def test_deepseek_checkpoint_roundtrip(tmp_path):
    hf_cfg = {
        "architectures": ["DeepseekV2ForCausalLM"],
        "model_type": "deepseek_v2",
        "vocab_size": 128,
        "hidden_size": 32,
        "intermediate_size": 48,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "q_lora_rank": 24,
        "kv_lora_rank": 16,
        "qk_nope_head_dim": 8,
        "qk_rope_head_dim": 4,
        "v_head_dim": 8,
        "n_routed_experts": 4,
        "num_experts_per_tok": 2,
        "n_shared_experts": 1,
        "moe_intermediate_size": 16,
        "first_k_dense_replace": 1,
    }
    (tmp_path / "config.json").write_text(json.dumps(hf_cfg))

    from dataclasses import replace

    from dynamo_tpu.models.deepseek import DeepseekConfig, DeepseekModel

    cfg = replace(DeepseekConfig.from_hf_config(hf_cfg), moe_capacity_factor=8.0)
    model = DeepseekModel(cfg)
    params = model.init_params(jax.random.key(9))

    tensors = {
        "model.embed_tokens.weight": _np(params["embed"]),
        "model.norm.weight": _np(params["final_norm"]),
        "lm_head.weight": _np(params["lm_head"]),
    }
    dn, dv, dc = cfg.qk_nope_head_dim, cfg.v_head_dim, cfg.kv_lora_rank
    H = cfg.num_heads
    Ld = cfg.first_k_dense_replace
    for l in range(cfg.num_layers):
        dense = l < Ld
        lw = params["dense_layers"] if dense else params["moe_layers"]
        gl = l if dense else l - Ld
        pre = f"model.layers.{l}."
        tensors[pre + "input_layernorm.weight"] = _np(lw["input_norm"][gl])
        tensors[pre + "self_attn.q_a_proj.weight"] = _T(lw["w_dq"][gl])
        tensors[pre + "self_attn.q_a_layernorm.weight"] = _np(lw["q_norm"][gl])
        tensors[pre + "self_attn.q_b_proj.weight"] = _T(lw["w_uq"][gl])
        tensors[pre + "self_attn.kv_a_proj_with_mqa.weight"] = _T(lw["w_dkv"][gl])
        tensors[pre + "self_attn.kv_a_layernorm.weight"] = _np(lw["kv_norm"][gl])
        # [dc, H, dn] + [dc, H, dv] -> HF kv_b_proj [H*(dn+dv), dc]
        kvb = np.concatenate([_np(lw["w_kb"][gl]), _np(lw["w_vb"][gl])], axis=-1)
        tensors[pre + "self_attn.kv_b_proj.weight"] = np.ascontiguousarray(kvb.reshape(dc, H * (dn + dv)).T)
        tensors[pre + "self_attn.o_proj.weight"] = _T(lw["wo"][gl])
        tensors[pre + "post_attention_layernorm.weight"] = _np(lw["post_norm"][gl])
        if dense:
            tensors[pre + "mlp.gate_proj.weight"] = _T(lw["gate"][gl])
            tensors[pre + "mlp.up_proj.weight"] = _T(lw["up"][gl])
            tensors[pre + "mlp.down_proj.weight"] = _T(lw["down"][gl])
        else:
            tensors[pre + "mlp.gate.weight"] = _T(lw["router"][gl])
            tensors[pre + "mlp.shared_experts.gate_proj.weight"] = _T(lw["shared_gate"][gl])
            tensors[pre + "mlp.shared_experts.up_proj.weight"] = _T(lw["shared_up"][gl])
            tensors[pre + "mlp.shared_experts.down_proj.weight"] = _T(lw["shared_down"][gl])
            for e in range(cfg.n_routed_experts):
                epre = pre + f"mlp.experts.{e}."
                tensors[epre + "gate_proj.weight"] = _T(lw["w_gate"][gl, e])
                tensors[epre + "up_proj.weight"] = _T(lw["w_up"][gl, e])
                tensors[epre + "down_proj.weight"] = _T(lw["w_down"][gl, e])
    save_file(tensors, str(tmp_path / "model.safetensors"))

    loaded_model, loaded_params = load_model(str(tmp_path))
    object.__setattr__(loaded_model.config, "moe_capacity_factor", 8.0)
    np.testing.assert_allclose(
        _prefill_logits(loaded_model, loaded_params),
        _prefill_logits(model, params),
        atol=1e-3,
    )
