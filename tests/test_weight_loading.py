"""HF checkpoint loading round-trips: synthesize an HF-style safetensors
checkpoint from randomly-initialized params via the inverse name/layout
mapping, load it through the registry, and require identical prefill logits.

This validates the name mapping, transposes, expert stacking, and the
kv_b_proj k-up/v-up split without needing real checkpoints (zero-egress env);
reference: launch/dynamo-run/src/hub.rs resolves HF repos, here local dirs.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from safetensors.numpy import save_file

from dynamo_tpu.models.registry import load_model


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow

PROMPT = np.array([5, 9, 2, 77, 31, 8], dtype=np.int32)


def _prefill_logits(model, params, num_pages=16, page_size=4):
    kv = model.init_kv_cache(num_pages, page_size)
    T = len(PROMPT)
    pt = np.array([3, 5, 7, 0, 0, 0, 0, 0], np.int32)
    positions = np.arange(8, dtype=np.int32)
    tokens = np.zeros(8, np.int32)
    tokens[:T] = PROMPT
    logits, _ = model.prefill(
        params, kv, jnp.array(tokens), jnp.array(positions),
        jnp.array(pt), jnp.array(positions < T), jnp.array(T - 1),
    )
    return np.asarray(logits)


def _np(x):
    return np.asarray(x, np.float32)


def _T(x):
    # safetensors writes the raw buffer of non-contiguous views (silently
    # wrong for transposes) — always materialize the transpose
    return np.ascontiguousarray(_np(x).T)


def test_llama_checkpoint_roundtrip(tmp_path):
    hf_cfg = {
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": 128,
        "hidden_size": 32,
        "intermediate_size": 64,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "head_dim": 8,
        "rope_theta": 10000.0,
        "rms_norm_eps": 1e-5,
    }
    (tmp_path / "config.json").write_text(json.dumps(hf_cfg))

    from dynamo_tpu.models.llama import LlamaConfig, LlamaModel

    cfg = LlamaConfig.from_hf_config(hf_cfg)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.key(7))

    tensors = {
        "model.embed_tokens.weight": _np(params["embed"]),
        "model.norm.weight": _np(params["final_norm"]),
        "lm_head.weight": _np(params["lm_head"]),
    }
    lw = params["layers"]
    for l in range(cfg.num_layers):
        pre = f"model.layers.{l}."
        tensors[pre + "input_layernorm.weight"] = _np(lw["input_norm"][l])
        tensors[pre + "self_attn.q_proj.weight"] = _T(lw["wq"][l])
        tensors[pre + "self_attn.k_proj.weight"] = _T(lw["wk"][l])
        tensors[pre + "self_attn.v_proj.weight"] = _T(lw["wv"][l])
        tensors[pre + "self_attn.o_proj.weight"] = _T(lw["wo"][l])
        tensors[pre + "post_attention_layernorm.weight"] = _np(lw["post_norm"][l])
        tensors[pre + "mlp.gate_proj.weight"] = _T(lw["gate"][l])
        tensors[pre + "mlp.up_proj.weight"] = _T(lw["up"][l])
        tensors[pre + "mlp.down_proj.weight"] = _T(lw["down"][l])
    save_file(tensors, str(tmp_path / "model.safetensors"))

    loaded_model, loaded_params = load_model(str(tmp_path))
    np.testing.assert_allclose(
        _prefill_logits(loaded_model, loaded_params),
        _prefill_logits(model, params),
        atol=1e-3,
    )


def test_mixtral_checkpoint_roundtrip(tmp_path):
    hf_cfg = {
        "architectures": ["MixtralForCausalLM"],
        "model_type": "mixtral",
        "vocab_size": 128,
        "hidden_size": 32,
        "intermediate_size": 48,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "head_dim": 8,
        "num_local_experts": 4,
        "num_experts_per_tok": 2,
    }
    (tmp_path / "config.json").write_text(json.dumps(hf_cfg))

    from dynamo_tpu.models.mixtral import MixtralConfig, MixtralModel

    cfg = MixtralConfig.from_hf_config(hf_cfg)
    # huge capacity => exact routing for the comparison
    from dataclasses import replace

    cfg = replace(cfg, moe_capacity_factor=8.0)
    model = MixtralModel(cfg)
    params = model.init_params(jax.random.key(8))

    tensors = {
        "model.embed_tokens.weight": _np(params["embed"]),
        "model.norm.weight": _np(params["final_norm"]),
        "lm_head.weight": _np(params["lm_head"]),
    }
    lw = params["layers"]
    for l in range(cfg.num_layers):
        pre = f"model.layers.{l}."
        tensors[pre + "input_layernorm.weight"] = _np(lw["input_norm"][l])
        tensors[pre + "self_attn.q_proj.weight"] = _T(lw["wq"][l])
        tensors[pre + "self_attn.k_proj.weight"] = _T(lw["wk"][l])
        tensors[pre + "self_attn.v_proj.weight"] = _T(lw["wv"][l])
        tensors[pre + "self_attn.o_proj.weight"] = _T(lw["wo"][l])
        tensors[pre + "post_attention_layernorm.weight"] = _np(lw["post_norm"][l])
        tensors[pre + "block_sparse_moe.gate.weight"] = _T(lw["router"][l])
        for e in range(cfg.num_experts):
            epre = pre + f"block_sparse_moe.experts.{e}."
            tensors[epre + "w1.weight"] = _T(lw["w_gate"][l, e])
            tensors[epre + "w3.weight"] = _T(lw["w_up"][l, e])
            tensors[epre + "w2.weight"] = _T(lw["w_down"][l, e])
    save_file(tensors, str(tmp_path / "model.safetensors"))

    loaded_model, loaded_params = load_model(str(tmp_path))
    object.__setattr__(loaded_model.config, "moe_capacity_factor", 8.0)
    np.testing.assert_allclose(
        _prefill_logits(loaded_model, loaded_params),
        _prefill_logits(model, params),
        atol=1e-3,
    )


def test_deepseek_checkpoint_roundtrip(tmp_path):
    hf_cfg = {
        "architectures": ["DeepseekV2ForCausalLM"],
        "model_type": "deepseek_v2",
        "vocab_size": 128,
        "hidden_size": 32,
        "intermediate_size": 48,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "q_lora_rank": 24,
        "kv_lora_rank": 16,
        "qk_nope_head_dim": 8,
        "qk_rope_head_dim": 4,
        "v_head_dim": 8,
        "n_routed_experts": 4,
        "num_experts_per_tok": 2,
        "n_shared_experts": 1,
        "moe_intermediate_size": 16,
        "first_k_dense_replace": 1,
    }
    (tmp_path / "config.json").write_text(json.dumps(hf_cfg))

    from dataclasses import replace

    from dynamo_tpu.models.deepseek import DeepseekConfig, DeepseekModel

    cfg = replace(DeepseekConfig.from_hf_config(hf_cfg), moe_capacity_factor=8.0)
    model = DeepseekModel(cfg)
    params = model.init_params(jax.random.key(9))

    tensors = {
        "model.embed_tokens.weight": _np(params["embed"]),
        "model.norm.weight": _np(params["final_norm"]),
        "lm_head.weight": _np(params["lm_head"]),
    }
    dn, dv, dc = cfg.qk_nope_head_dim, cfg.v_head_dim, cfg.kv_lora_rank
    H = cfg.num_heads
    Ld = cfg.first_k_dense_replace
    for l in range(cfg.num_layers):
        dense = l < Ld
        lw = params["dense_layers"] if dense else params["moe_layers"]
        gl = l if dense else l - Ld
        pre = f"model.layers.{l}."
        tensors[pre + "input_layernorm.weight"] = _np(lw["input_norm"][gl])
        tensors[pre + "self_attn.q_a_proj.weight"] = _T(lw["w_dq"][gl])
        tensors[pre + "self_attn.q_a_layernorm.weight"] = _np(lw["q_norm"][gl])
        tensors[pre + "self_attn.q_b_proj.weight"] = _T(lw["w_uq"][gl])
        tensors[pre + "self_attn.kv_a_proj_with_mqa.weight"] = _T(lw["w_dkv"][gl])
        tensors[pre + "self_attn.kv_a_layernorm.weight"] = _np(lw["kv_norm"][gl])
        # [dc, H, dn] + [dc, H, dv] -> HF kv_b_proj [H*(dn+dv), dc]
        kvb = np.concatenate([_np(lw["w_kb"][gl]), _np(lw["w_vb"][gl])], axis=-1)
        tensors[pre + "self_attn.kv_b_proj.weight"] = np.ascontiguousarray(kvb.reshape(dc, H * (dn + dv)).T)
        tensors[pre + "self_attn.o_proj.weight"] = _T(lw["wo"][gl])
        tensors[pre + "post_attention_layernorm.weight"] = _np(lw["post_norm"][gl])
        if dense:
            tensors[pre + "mlp.gate_proj.weight"] = _T(lw["gate"][gl])
            tensors[pre + "mlp.up_proj.weight"] = _T(lw["up"][gl])
            tensors[pre + "mlp.down_proj.weight"] = _T(lw["down"][gl])
        else:
            tensors[pre + "mlp.gate.weight"] = _T(lw["router"][gl])
            tensors[pre + "mlp.shared_experts.gate_proj.weight"] = _T(lw["shared_gate"][gl])
            tensors[pre + "mlp.shared_experts.up_proj.weight"] = _T(lw["shared_up"][gl])
            tensors[pre + "mlp.shared_experts.down_proj.weight"] = _T(lw["shared_down"][gl])
            for e in range(cfg.n_routed_experts):
                epre = pre + f"mlp.experts.{e}."
                tensors[epre + "gate_proj.weight"] = _T(lw["w_gate"][gl, e])
                tensors[epre + "up_proj.weight"] = _T(lw["w_up"][gl, e])
                tensors[epre + "down_proj.weight"] = _T(lw["w_down"][gl, e])
    save_file(tensors, str(tmp_path / "model.safetensors"))

    loaded_model, loaded_params = load_model(str(tmp_path))
    object.__setattr__(loaded_model.config, "moe_capacity_factor", 8.0)
    np.testing.assert_allclose(
        _prefill_logits(loaded_model, loaded_params),
        _prefill_logits(model, params),
        atol=1e-3,
    )


def test_qwen2_vl_checkpoint_roundtrip(tmp_path):
    """Text + vision towers: synthesize HF qwen2_vl names (conv3d patch embed,
    fused qkv, LayerNorm biases, merger MLP) and require identical mm logits."""
    hf_cfg = {
        "architectures": ["Qwen2VLForConditionalGeneration"],
        "model_type": "qwen2_vl",
        "vocab_size": 128,
        "hidden_size": 32,
        "intermediate_size": 64,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "head_dim": 8,
        "rope_theta": 10000.0,
        "rms_norm_eps": 1e-5,
        "attention_bias": True,
        "vision_config": {
            "patch_size": 4,
            "in_channels": 3,
            "spatial_merge_size": 2,
            "embed_dim": 16,
            "intermediate_size": 32,
            "depth": 2,
            "num_heads": 2,
        },
    }
    (tmp_path / "config.json").write_text(json.dumps(hf_cfg))

    from dynamo_tpu.models.qwen2_vl import Qwen2VLConfig, Qwen2VLModel

    cfg = Qwen2VLConfig.from_hf_config(hf_cfg)
    model = Qwen2VLModel(cfg)
    params = model.init_params(jax.random.key(11))
    # exercise nonzero biases/norm offsets (init is zeros/ones)
    params = jax.tree.map(
        lambda x: x + 0.01 * jax.random.normal(jax.random.key(1), x.shape, jnp.float32).astype(x.dtype)
        if x.ndim <= 2 else x,
        params,
    )

    vc = cfg.vision
    tensors = {
        "model.embed_tokens.weight": _np(params["embed"]),
        "model.norm.weight": _np(params["final_norm"]),
        "lm_head.weight": _np(params["lm_head"]),
    }
    lw = params["layers"]
    for l in range(cfg.num_layers):
        pre = f"model.layers.{l}."
        tensors[pre + "input_layernorm.weight"] = _np(lw["input_norm"][l])
        tensors[pre + "self_attn.q_proj.weight"] = _T(lw["wq"][l])
        tensors[pre + "self_attn.k_proj.weight"] = _T(lw["wk"][l])
        tensors[pre + "self_attn.v_proj.weight"] = _T(lw["wv"][l])
        tensors[pre + "self_attn.o_proj.weight"] = _T(lw["wo"][l])
        tensors[pre + "self_attn.q_proj.bias"] = _np(lw["bq"][l])
        tensors[pre + "self_attn.k_proj.bias"] = _np(lw["bk"][l])
        tensors[pre + "self_attn.v_proj.bias"] = _np(lw["bv"][l])
        tensors[pre + "post_attention_layernorm.weight"] = _np(lw["post_norm"][l])
        tensors[pre + "mlp.gate_proj.weight"] = _T(lw["gate"][l])
        tensors[pre + "mlp.up_proj.weight"] = _T(lw["up"][l])
        tensors[pre + "mlp.down_proj.weight"] = _T(lw["down"][l])

    vis = params["vision"]
    # our linear [C*ps*ps, D] -> HF conv3d [D, C, T=2, ps, ps]; the loader sums
    # the temporal taps so split the weight across two taps to prove that path
    pe = _np(vis["patch_embed"]).reshape(vc.patch_size, vc.patch_size, vc.in_channels, vc.hidden_size)
    conv = pe.transpose(3, 2, 0, 1)  # [D, C, ps, ps]
    tap = conv / 2.0
    tensors["visual.patch_embed.proj.weight"] = np.ascontiguousarray(
        np.stack([tap, tap], axis=2)
    )
    vl = vis["layers"]
    for l in range(vc.num_layers):
        pre = f"visual.blocks.{l}."
        tensors[pre + "norm1.weight"] = _np(vl["norm1"][l])
        tensors[pre + "norm1.bias"] = _np(vl["norm1_b"][l])
        tensors[pre + "attn.qkv.weight"] = _T(vl["wqkv"][l])
        tensors[pre + "attn.qkv.bias"] = _np(vl["bqkv"][l])
        tensors[pre + "attn.proj.weight"] = _T(vl["wo"][l])
        tensors[pre + "attn.proj.bias"] = _np(vl["bo"][l])
        tensors[pre + "norm2.weight"] = _np(vl["norm2"][l])
        tensors[pre + "norm2.bias"] = _np(vl["norm2_b"][l])
        tensors[pre + "mlp.fc1.weight"] = _T(vl["fc1"][l])
        tensors[pre + "mlp.fc1.bias"] = _np(vl["bfc1"][l])
        tensors[pre + "mlp.fc2.weight"] = _T(vl["fc2"][l])
        tensors[pre + "mlp.fc2.bias"] = _np(vl["bfc2"][l])
    tensors["visual.merger.ln_q.weight"] = _np(vis["merger_norm"])
    tensors["visual.merger.ln_q.bias"] = _np(vis["merger_norm_b"])
    tensors["visual.merger.mlp.0.weight"] = _T(vis["merger_fc1"])
    tensors["visual.merger.mlp.0.bias"] = _np(vis["merger_bfc1"])
    tensors["visual.merger.mlp.2.weight"] = _T(vis["merger_fc2"])
    tensors["visual.merger.mlp.2.bias"] = _np(vis["merger_bfc2"])

    save_file(tensors, str(tmp_path / "model.safetensors"))
    loaded_model, loaded_params = load_model(str(tmp_path))
    assert type(loaded_model).__name__ == "Qwen2VLModel"

    from dynamo_tpu.llm.multimodal import image_content_hash, patchify, virtual_token_ids

    img = np.random.default_rng(4).random((16, 16, 3)).astype(np.float32)
    patches, rows, cols, _ = patchify(img, vc.patch_size, vc.spatial_merge_size)
    n_img = patches.shape[0] // vc.spatial_merge_size**2

    def mm_logits(m, p):
        emb = m.encode_images(
            p, jnp.asarray(patches), jnp.asarray(rows), jnp.asarray(cols),
            jnp.ones(len(rows), bool),
        )
        toks = [5, 9] + virtual_token_ids(image_content_hash(img), n_img, cfg.vocab_size) + [2]
        T = len(toks)
        Tp = 64
        tokens = np.zeros(Tp, np.int32)
        tokens[:T] = toks
        embeds = np.zeros((Tp, cfg.hidden_size), np.float32)
        embeds[2 : 2 + n_img] = np.asarray(emb, np.float32)
        mask = np.zeros(Tp, bool)
        mask[2 : 2 + n_img] = True
        positions = np.arange(Tp, dtype=np.int32)
        kv = m.init_kv_cache(32, 4)
        logits, _ = m.prefill(
            p, kv, jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(np.arange(1, 17, dtype=np.int32)),
            jnp.asarray(positions < T), jnp.asarray(T - 1),
            input_embeds=jnp.asarray(embeds), embeds_mask=jnp.asarray(mask),
        )
        return np.asarray(logits)

    ref = mm_logits(model, params)
    got = mm_logits(loaded_model, loaded_params)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
