"""Flight-recorder E2E (satellite of the observability PR): a bursty replay
against the real HTTP service + tiny JAX engine with the seeded admission
fault knob, then per-request forensics over the wire.

Acceptance driven here:
  - ``/debug/requests/{id}`` for a SHED request (client-supplied
    x-request-id, rejected before the preprocessor ever stamps one) shows
    the qos.shed chain, pinned under reason "shed";
  - an SLO-violating completed request is AUTO-pinned by the scheduler
    (ttft budget set impossibly tight) and its capture reconstructs the
    complete causally-ordered lifecycle enqueued -> admitted -> first_token
    -> finished;
  - the two-window burn-rate alert FIRES on /metrics during the violating
    burst and CLEARS once healthy traffic dilutes the short window;
  - a migrated request's chain (freeze -> handoff -> adopted) is
    reconstructable through the same endpoint.

Slow tier: boots real engines and sockets.
"""

import asyncio
import json
import os

import aiohttp
import pytest

from dynamo_tpu.utils import events

from tests.test_migration import _collect, _engine, _req, _wait_generated, _wire_pair

pytestmark = pytest.mark.slow

#: impossibly tight TTFT budget (1 us): every completed request violates,
#: so the scheduler auto-pins each one and the frontend burn rate saturates
_TTFT_MS = "0.001"


@pytest.fixture(scope="module")
def server():
    """(loop, base_url, service, engine) — SLO env knobs set BEFORE boot so
    both the frontend tracker and the engine scheduler pick up the target."""
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model
    from dynamo_tpu.llm.http.service import HttpService

    from tests.test_engine import tiny_engine_config

    saved = os.environ.get("DYNTPU_SLO_TTFT_MS")
    os.environ["DYNTPU_SLO_TTFT_MS"] = _TTFT_MS
    loop = asyncio.new_event_loop()

    async def boot():
        engine = AsyncJaxEngine(tiny_engine_config())
        await engine.start()
        service = HttpService(host="127.0.0.1", port=0)
        service.manager.add(build_pipeline(engine, card_for_model("tiny")))
        port = await service.start()
        return engine, service, f"http://127.0.0.1:{port}"

    engine, service, url = loop.run_until_complete(boot())
    try:
        yield loop, url, service, engine
    finally:
        loop.run_until_complete(service.stop())
        loop.run_until_complete(engine.shutdown())
        loop.close()
        if saved is None:
            os.environ.pop("DYNTPU_SLO_TTFT_MS", None)
        else:
            os.environ["DYNTPU_SLO_TTFT_MS"] = saved


def _chat_body(max_tokens=4):
    return {
        "model": "tiny",
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": max_tokens,
        "temperature": 0,
    }


async def _post(url, path, body, headers=None):
    async with aiohttp.ClientSession() as s:
        async with s.post(url + path, json=body, headers=headers or {}) as resp:
            return resp.status, await resp.json()


async def _get_json(url, path):
    async with aiohttp.ClientSession() as s:
        async with s.get(url + path) as resp:
            return resp.status, json.loads(await resp.text())


async def _get_text(url, path):
    async with aiohttp.ClientSession() as s:
        async with s.get(url + path) as resp:
            return await resp.text()


def test_burst_replay_shed_chain_autopin_and_burn_alert(server, monkeypatch):
    loop, url, service, _engine_ = server
    # seeded admission chaos: a deterministic fraction of the burst sheds
    monkeypatch.setenv("DYNTPU_FAULT_ADMISSION", "reject-rate:0.4")
    monkeypatch.setenv("DYNTPU_FAULT_SEED", "7")

    async def burst():
        reqs = []
        for i in range(12):
            headers = {
                "x-request-id": f"replay-{i}",
                "x-tenant": "acme" if i % 2 else "globex",
            }
            reqs.append(_post(url, "/v1/chat/completions", _chat_body(), headers))
        return await asyncio.gather(*reqs)

    results = loop.run_until_complete(burst())
    statuses = [s for s, _ in results]
    shed_ids = [f"replay-{i}" for i, (s, _) in enumerate(results) if s == 429]
    ok = statuses.count(200)
    assert shed_ids and ok >= 3, statuses  # the seeded knob split the burst

    # ---- shed chain: the 429 happened before any engine involvement, yet
    # the client-supplied id reconstructs the decision over the wire
    status, tl = loop.run_until_complete(
        _get_json(url, f"/debug/requests/{shed_ids[0]}")
    )
    assert status == 200 and tl["found"], tl
    assert tl["pinned"] == "shed"
    kinds = [e["kind"] for e in tl["events"]]
    assert "qos.shed" in kinds
    shed_ev = tl["events"][kinds.index("qos.shed")]
    assert shed_ev["detail"]["site"] == "frontend"
    assert shed_ev["tenant"] in ("acme", "globex")

    # ---- auto-pin: every COMPLETED request blew the 1 us ttft budget, so
    # the scheduler pinned it; the capture reconstructs the full causally
    # ordered lifecycle (the acceptance criterion)
    pinned = [
        rid for rid in events.JOURNAL.captured_ids()
        if events.JOURNAL.capture_reason(rid) == "ttft_over_budget"
    ]
    assert pinned, events.JOURNAL.captured_ids()
    status, tl = loop.run_until_complete(_get_json(url, f"/debug/requests/{pinned[-1]}"))
    assert status == 200 and tl["found"] and tl["pinned"] == "ttft_over_budget"
    kinds = [e["kind"] for e in tl["events"]]
    for a, b in (
        ("request.enqueued", "sched.admitted"),
        ("sched.admitted", "request.first_token"),
        ("request.first_token", "request.finished"),
    ):
        assert kinds.index(a) < kinds.index(b), kinds
    seqs = [e["seq"] for e in tl["events"]]
    assert seqs == sorted(seqs)
    assert all(e["dt_ms"] >= 0.0 for e in tl["events"])
    assert tl["span_ms"] >= 0.0

    # ---- burn-rate alert: the burst's ttft observations are 100%
    # violations, so both windows burn far above threshold -> alert on the
    # frontend exposition
    text = loop.run_until_complete(_get_text(url, "/metrics"))
    assert 'dynamo_slo_burn_rate{metric="ttft",window="short"}' in text
    assert 'dynamo_alert_state{alert="slo_burn_ttft"} 1' in text

    # ---- and it CLEARS: healthy post-burst traffic dilutes the short
    # window below threshold (simulated by feeding the service's tracker
    # directly — real recovery is just many fast requests)
    for _ in range(2000):
        service.slo.observe("ttft", 0.0)
    text = loop.run_until_complete(_get_text(url, "/metrics"))
    assert 'dynamo_alert_state{alert="slo_burn_ttft"} 0' in text

    # shed/served split also reached the journal counters on /metrics
    assert "dynamo_event_emitted_total" in text
    assert "dynamo_event_captures_pinned_total" in text


def test_migrated_request_chain_over_debug_endpoint(server):
    """A live migration's freeze -> handoff -> adopted decision chain is
    reconstructable through the same forensics endpoint (the engines share
    the process-wide journal with the HTTP frontend)."""
    loop, url, _service, _eng = server

    async def migrate():
        src = _engine()
        await src.start()
        dst = _engine()
        await dst.start()
        srv = None
        try:
            srv = await _wire_pair(src, dst)
            await _collect(dst, _req("warm", n=4))
            task = asyncio.ensure_future(_collect(src, _req("mig-e2e")))
            assert await _wait_generated(src, "mig-e2e", 8)
            res = await src.migrate_out("mig-e2e", dst.adopt_migrated)
            assert res["status"] == "ok", res
            toks, finish = await task
            assert finish == "length" and len(toks) == 32
            return await _get_json(url, "/debug/requests/mig-e2e")
        finally:
            if srv is not None:
                await srv.stop()
            await src.shutdown()
            await dst.shutdown()

    status, tl = loop.run_until_complete(migrate())
    assert status == 200 and tl["found"], tl
    kinds = [e["kind"] for e in tl["events"]]
    # causal order: the source freezes, the destination adopts, and the
    # source's handoff record lands once the pause is measured (its end is
    # the destination's first continuation token — necessarily after adopt)
    for a, b in (
        ("migration.freeze", "migration.adopted"),
        ("migration.adopted", "migration.handoff"),
    ):
        assert kinds.index(a) < kinds.index(b), kinds
    # the adopted request finishes on the destination under the SAME id —
    # one request, one causal chain across two engines
    assert kinds.count("request.finished") >= 1
    freeze = tl["events"][kinds.index("migration.freeze")]
    assert freeze["detail"].get("generated", 0) >= 8
