"""Sampling parity features: penalties (presence/frequency/repetition), min_p,
per-request seeds, min_tokens (reference: lib/llm/src/protocols/common.rs
SamplingOptions; penalty semantics follow its vLLM engines)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.sampling import SamplingParams, apply_penalties, sample_tokens
from dynamo_tpu.engine.scheduler import EngineRequest


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow


# ---------------- pure sampler units ----------------


def test_apply_penalties_semantics():
    logits = jnp.array([[2.0, -1.0, 0.5, 3.0]])
    counts = jnp.array([[2, 0, 1, 0]], jnp.int32)
    seen = jnp.array([[True, True, True, False]])  # token 1 from the prompt
    out = apply_penalties(
        logits, counts, seen,
        presence=jnp.array([0.5]), frequency=jnp.array([0.25]), repetition=jnp.array([2.0]),
    )
    # vLLM order: repetition on raw logits first, then freq/presence subtract
    # token0: 2.0/2 (seen, positive) = 1.0, then -0.25*2 - 0.5 = 0.0
    # token1: -1.0*2 (seen, negative) = -2.0; no output counts
    # token2: 0.5/2 = 0.25, then -0.25 - 0.5 = -0.5
    # token3: unseen, no counts: untouched
    np.testing.assert_allclose(np.asarray(out[0]), [0.0, -2.0, -0.5, 3.0], atol=1e-6)


def test_min_p_filters_tail():
    # two strong tokens, long tail; min_p=0.5 must keep only the top token(s)
    logits = jnp.array([[10.0, 9.0] + [0.0] * 62])
    toks = set()
    for i in range(30):
        t = sample_tokens(
            logits, jax.random.key(i),
            jnp.array([1.0]), jnp.array([0], jnp.int32), jnp.array([1.0]),
            min_p=jnp.array([0.5]),
        )
        toks.add(int(t[0]))
    assert toks <= {0, 1}


def test_seeded_sampling_is_deterministic_and_batch_independent():
    V = 64
    logits_row = jax.random.normal(jax.random.key(9), (V,))

    def draw(slot, B, seed, key_int, pos=0):
        logits = jnp.tile(logits_row[None], (B, 1))
        toks = sample_tokens(
            logits, jax.random.key(key_int),
            jnp.full(B, 1.0), jnp.zeros(B, jnp.int32), jnp.ones(B, jnp.float32),
            min_p=jnp.zeros(B),
            seeds=jnp.full(B, 0, jnp.int32).at[slot].set(seed),
            positions=jnp.full(B, pos, jnp.int32),
        )
        return int(toks[slot])

    # same seed+position -> same token regardless of engine key or batch slot
    a = draw(slot=0, B=1, seed=1234, key_int=0)
    b = draw(slot=2, B=4, seed=1234, key_int=77)
    assert a == b
    # different position -> (almost surely) advances the stream
    c = [draw(slot=0, B=1, seed=1234, key_int=0, pos=p) for p in range(8)]
    assert len(set(c)) > 1


# ---------------- engine end-to-end ----------------


def _engine(**over):
    defaults = dict(
        model_id="tiny",
        page_size=4,
        num_pages=64,
        max_seqs=4,
        max_model_len=64,
        prefill_buckets=(8, 16, 32),
    )
    defaults.update(over)
    return AsyncJaxEngine(EngineConfig(**defaults))


async def _gen(engine, rid, prompt, sampling):
    req = EngineRequest(request_id=rid, token_ids=list(prompt), sampling=sampling)
    toks = []
    async for out in engine.generate(req):
        if out.token is not None:
            toks.append(out.token)
    return toks


def test_engine_repetition_penalty_breaks_loops():
    """Greedy tiny-model decoding loops on a few tokens; a strong repetition
    penalty must produce strictly more distinct tokens."""
    async def body():
        eng = _engine()
        await eng.start()
        prompt = [5, 9, 2, 77, 31]
        plain = await _gen(eng, "plain", prompt, SamplingParams(
            temperature=0.0, max_tokens=16, ignore_eos=True))
        pen = await _gen(eng, "pen", prompt, SamplingParams(
            temperature=0.0, max_tokens=16, ignore_eos=True, repetition_penalty=5.0))
        await eng.shutdown()
        return plain, pen

    plain, pen = asyncio.new_event_loop().run_until_complete(body())
    assert len(pen) == 16
    assert len(set(pen)) > len(set(plain))


def test_engine_seeded_requests_reproduce():
    async def body():
        eng = _engine()
        await eng.start()
        prompt = [3, 1, 4, 1, 5]
        sp = lambda: SamplingParams(temperature=1.0, max_tokens=10, ignore_eos=True, seed=42)
        a = await _gen(eng, "a", prompt, sp())
        b = await _gen(eng, "b", prompt, sp())
        other = await _gen(eng, "c", prompt, SamplingParams(
            temperature=1.0, max_tokens=10, ignore_eos=True, seed=43))
        await eng.shutdown()
        return a, b, other

    a, b, other = asyncio.new_event_loop().run_until_complete(body())
    assert a == b
    assert a != other  # different seed diverges (overwhelmingly likely)


def test_engine_min_tokens_suppresses_early_eos():
    async def body():
        eng = _engine()
        await eng.start()
        prompt = [5, 9, 2]
        # force immediate "EOS": make every token an eos token
        req = EngineRequest(
            request_id="mt",
            token_ids=prompt,
            sampling=SamplingParams(temperature=0.0, max_tokens=12, min_tokens=6),
            eos_token_ids=tuple(range(256)),
        )
        toks = []
        finish = None
        async for out in eng.generate(req):
            if out.token is not None:
                toks.append(out.token)
            if out.finished:
                finish = out.finish_reason
        await eng.shutdown()
        return toks, finish

    toks, finish = asyncio.new_event_loop().run_until_complete(body())
    assert finish == "stop"
    # vLLM semantics: min_tokens guarantees 6 non-stopping tokens; the first
    # EOS that may finish the stream is generation #7
    assert len(toks) == 7


def test_http_sampling_params_parse():
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest, ProtocolError
    from dynamo_tpu.llm.tokenizer import get_tokenizer

    pre = OpenAIPreprocessor(get_tokenizer("byte"), "tiny", max_model_len=256)
    req = ChatCompletionRequest.from_dict({
        "model": "tiny",
        "messages": [{"role": "user", "content": "hi"}],
        "presence_penalty": 0.5, "frequency_penalty": -0.25,
        "repetition_penalty": 1.3, "min_p": 0.1, "min_tokens": 4, "seed": 7,
    })
    p, _ = pre.preprocess_chat(req)
    s = p.sampling
    assert (s.presence_penalty, s.frequency_penalty) == (0.5, -0.25)
    assert s.repetition_penalty == 1.3 and s.min_p == 0.1
    assert s.min_tokens == 4 and s.seed == 7
    assert s.needs_penalties

    # wire roundtrip carries everything
    from dynamo_tpu.llm.protocols.common import PreprocessedRequest
    s2 = PreprocessedRequest.from_wire(p.to_wire()).sampling
    assert s2 == s

    with pytest.raises(ProtocolError):
        ChatCompletionRequest.from_dict({
            "model": "tiny", "messages": [{"role": "user", "content": "x"}],
            "presence_penalty": 3.0,
        })


def test_engine_min_tokens_greedy_emits_no_early_eos():
    """Device-side EOS masking: with greedy decoding whose argmax IS an EOS
    token, min_tokens must yield non-EOS content tokens until the threshold
    (not a stream of suppressed EOS ids)."""
    async def body():
        eng = _engine()
        await eng.start()
        # discover the natural greedy continuation; its first token becomes EOS
        probe = await _gen(eng, "probe", [5, 9, 2], SamplingParams(
            temperature=0.0, max_tokens=1, ignore_eos=True))
        eos = probe[0]
        req = EngineRequest(
            request_id="mask",
            token_ids=[5, 9, 2],
            sampling=SamplingParams(temperature=0.0, max_tokens=12, min_tokens=5),
            eos_token_ids=(eos,),
        )
        toks = []
        async for out in eng.generate(req):
            if out.token is not None:
                toks.append(out.token)
        await eng.shutdown()
        return eos, toks

    eos, toks = asyncio.new_event_loop().run_until_complete(body())
    # the min_tokens guaranteed tokens must not be the banned EOS id
    assert all(t != eos for t in toks[:5])


def test_engine_min_tokens_one_is_meaningful():
    """min_tokens=1 guarantees one non-EOS token even when the greedy argmax
    of the prompt IS an EOS id (vLLM parity; previously a no-op)."""
    async def body():
        eng = _engine()
        await eng.start()
        probe = await _gen(eng, "probe1", [5, 9, 2], SamplingParams(
            temperature=0.0, max_tokens=1, ignore_eos=True))
        eos = probe[0]
        req = EngineRequest(
            request_id="mt1",
            token_ids=[5, 9, 2],
            sampling=SamplingParams(temperature=0.0, max_tokens=8, min_tokens=1),
            eos_token_ids=(eos,),
        )
        toks = []
        async for out in eng.generate(req):
            if out.token is not None:
                toks.append(out.token)
        await eng.shutdown()
        return eos, toks

    eos, toks = asyncio.new_event_loop().run_until_complete(body())
    assert toks and toks[0] != eos


def test_engine_penalties_survive_preemption():
    """Frequency-penalty counts restore after preemption: a run that preempts
    mid-stream produces the same tokens as one that never preempts."""
    prompt = [5, 9, 2, 77]
    sp = lambda: SamplingParams(
        temperature=0.0, max_tokens=14, ignore_eos=True,
        frequency_penalty=0.9, presence_penalty=0.4,
    )

    async def run(num_pages):
        eng = _engine(num_pages=num_pages, max_seqs=2, decode_steps=2,
                      pipeline_depth=1, max_model_len=64)
        await eng.start()
        if num_pages < 64:
            # a second long-running request forces page pressure -> preemption
            bg = asyncio.create_task(_gen(eng, "bg", [1, 2, 3], SamplingParams(
                temperature=0.0, max_tokens=30, ignore_eos=True)))
            out = await _gen(eng, "fg", prompt, sp())
            await bg
        else:
            out = await _gen(eng, "fg", prompt, sp())
        await eng.shutdown()
        return out

    loop = asyncio.new_event_loop()
    ref = loop.run_until_complete(run(64))
    tight = loop.run_until_complete(run(18))
    loop.close()
    assert tight == ref


def test_fold_seed_out_of_range():
    from dynamo_tpu.engine.sampling import fold_seed

    # only None means unseeded; an explicit seed=0 is a real deterministic
    # seed (it used to fall through `if not seed` into the engine's shared
    # stream — tests/test_spec_decode.py holds the regression)
    assert fold_seed(None) == 0
    for s in (0, 3_000_000_000, -5, 2**63 - 1, -(2**31)):
        v = fold_seed(s)
        assert 0 < v < 2**31
    assert fold_seed(42) == fold_seed(42)


def test_engine_warmup_precompiles_trace_variants():
    """warmup=True pre-compiles the decode/prefill trace variants; serving a
    feature-bearing request afterwards must not change behavior (and a seeded
    run stays deterministic through the collapsed extras trace)."""
    async def body():
        eng = _engine(warmup=True)
        await eng.start()
        a = await _gen(eng, "w1", [5, 9, 2], SamplingParams(
            temperature=0.8, max_tokens=6, seed=42))
        b = await _gen(eng, "w2", [5, 9, 2], SamplingParams(
            temperature=0.8, max_tokens=6, seed=42))
        plain = await _gen(eng, "w3", [5, 9, 2], SamplingParams(
            temperature=0.0, max_tokens=6))
        await eng.shutdown()
        return a, b, plain

    a, b, plain = asyncio.new_event_loop().run_until_complete(body())
    assert a == b
    assert len(plain) == 6
