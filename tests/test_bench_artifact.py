"""The round artifact must be self-contained: the driver keeps only the tail
of bench stdout (~2000 chars), so the LAST line has to carry every section's
key number by itself (r4 post-mortem: the full-detail line was truncated and
BENCH_r04.json lost its own headline)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture()
def bench_mod():
    import bench

    saved_detail, saved_errors = dict(bench.DETAIL), dict(bench.ERRORS)
    bench.DETAIL.clear()
    bench.ERRORS.clear()
    yield bench
    bench.DETAIL.clear()
    bench.DETAIL.update(saved_detail)
    bench.ERRORS.clear()
    bench.ERRORS.update(saved_errors)


def _fill_representative(bench):
    """Populate DETAIL with r4-scale values (worst-case field widths)."""
    bench.DETAIL["headline_bs%d_ps%d" % bench.HEADLINE] = {
        "tok_s": 6354.12, "total_output_tokens": 8192, "elapsed_s": 1.289,
        "ttft_p50_ms": 171.4, "rounds": [6102.44, 6354.12, 6233.91],
    }
    bench.DETAIL["continuity_bs%d_ps%d" % bench.CONTINUITY] = {"tok_s": 1402.77}
    bench.DETAIL["ref_workload_isl3k_osl150"] = {
        "tok_s": 731.55, "ttft_p50_ms": 1893.2,
        "stage_breakdown": {
            "queue_wait_s": 12.3456, "queue_wait_n": 48, "prefill_s": 31.9071,
            "prefill_calls": 96, "prefill_rows": 147456,
            "decode_dispatch_s": 55.1203, "decode_windows": 240,
            "decode_steps": 7680, "reconcile_wait_s": 8.0042,
            "reconcile_waits": 120, "ttft_s": 90.8, "ttft_n": 48,
        },
    }
    bench.DETAIL["http_serving"] = {
        "tok_s": 3264.18, "engine_loop_tok_s": 3401.02,
        "http_over_engine_ratio": 0.96, "ttft_p50_ms": 287.3,
    }
    bench.DETAIL["mla_decode"] = {"tok_s": 4658.33}
    bench.DETAIL["moe_decode"] = {"tok_s": 5425.87}
    bench.DETAIL["parity_disagg"] = {
        "ratio_measured_1chip": 0.941, "ratio_projected": 1.387,
    }
    bench.DETAIL["parity_kv_routing"] = {
        "ttft_insitu_ratio_measured": 2.79, "ttft_insitu_ratio_derived": 16.14,
    }
    bench.DETAIL["parity_host_offload"] = {
        "projection": {"ttft_ratio_projected": 8.82, "restore_bw_source": "measured"},
    }
    bench.DETAIL["kv_tiers"] = {
        "resume_ttft_tiered_ms": 123.4, "resume_ttft_recompute_ms": 534.2,
        "resume_ttft_ratio": 0.231, "restore_parity": 1.0,
        "resume_tokens_restored_tiered": 1344,
        "disk": {"spills": 72, "restores": 21, "restore_hits": 3,
                 "restore_fallbacks": 0, "restore_tokens": 1344,
                 "io_errors": 0, "blocks_resident": 72,
                 "bytes_resident": 452984832, "budget_bytes": 905969664},
        "cap_under_churn": {"budget_bytes": 1048576,
                            "max_resident_bytes": 1048400, "drops": 12},
    }
    bench.DETAIL["long_context"] = {
        "16k": {"ttft_ms": 13956.5, "decode_tok_s": 123.4, "kv_pages_peak": 1088},
        "64k": {"ttft_ms": 57321.8, "decode_tok_s": 98.7, "kv_pages_peak": 4160},
        "parity_64k_ladder_vs_dense": True,
        "short_ttft_ratio_ladder_over_dense": 0.169,
    }
    bench.DETAIL["spec_draft"] = {
        "tok_s_draft": 4123.45, "tok_s_ngram": 3356.71, "tok_s_classic": 3310.02,
        "speedup_draft_over_classic": 1.246, "acceptance_rate_draft": 0.9873,
        "acceptance_rate_ngram": 0.0512, "greedy_parity_draft": 1.0,
    }
    bench.DETAIL["migration"] = {
        "parity": 1.0, "pause_ms_p99": 1234.5, "kill_pause_ms_p99": 4567.8,
        "goodput_delta": 0.0417, "tokens_salvaged": 4096,
    }
    bench.DETAIL["qos"] = {
        "tenant_b_itl_ratio": 0.0052, "shed_fraction": 0.8333,
        "critical_goodput": 0.9873, "baseline_goodput": 1.0,
        "tenant_b_on": {"itl_p99_ms": 3.432}, "tenant_b_off": {"itl_p99_ms": 654.4},
    }
    bench.DETAIL["platform"] = "tpu"
    bench.DETAIL["events"] = {
        "cpu_smoke": False, "decode_step_wall_ms": 5.0521, "emit_us": 8.271,
        "emits_per_request": 7, "emit_overhead_frac": 0.002803,
        "journal_events": 4096, "reconstruct_ms": 0.2905,
    }
    bench.DETAIL["step_anatomy"] = {
        "cpu_smoke": False,
        "decode": {"host_frac": 0.3124, "roofline_frac": 0.6981,
                   "dispatch_gap_ms_p50": 231.456,
                   "dispatches": {"decode_window": 240}},
        "spec_draft": {"host_frac": 0.4123},
        "multi_lora": {"host_frac": 0.3852},
    }
    bench.DETAIL["metering"] = {
        "cpu_smoke": False, "decode_step_wall_ms": 8.456,
        "on_phase_us": 1.395, "kv_acquire_us": 2.084,
        "kv_release_us": 1.586, "overhead_frac": 0.000423,
        "device_rel_err": 1.3e-09,
        "kv_rel_err": {"hbm": 2.7e-09, "host": 0.0, "disk": 0.0},
        "device_s_total": 123.456,
        "tenants_metered": ["acme", "umbrella"],
    }
    bench.DETAIL["prefill_anatomy"] = {
        "greedy_parity": "exact", "stall_delta": 7,
        "depth1": {"prefill_stalls": 7, "prefill_calls": 8,
                   "reconcile_waits": 248, "prefill_fixed_ms": 10.234,
                   "prefill_host_frac": 0.9741, "prefill_roofline_frac": 0.6312,
                   "ttft_p50_ms": 1509.7, "wall_s": 41.5214,
                   "output_tokens": 1200},
        "depth2": {"prefill_stalls": 0, "prefill_calls": 8,
                   "reconcile_waits": 241, "prefill_fixed_ms": 9.871,
                   "prefill_host_frac": 0.9702, "prefill_roofline_frac": 0.6518,
                   "ttft_p50_ms": 1287.3, "wall_s": 38.1042,
                   "output_tokens": 1200},
    }
    bench.DETAIL["replay"] = {
        "cpu_smoke": False,
        "scenarios": {
            sc: {"goodput": 0.9873, "ttft_p99_ms": 3965.343,
                 "itl_p99_ms": 552.341, "tok_s": 4123.45, "wall_s": 12.3}
            for sc in ("bursty_chat", "int8_kv", "long_context_sessions",
                       "lora_churn", "spec_draft", "fleet_prefix", "mm_vl")
        },
    }


def test_summary_line_fits_truncation_budget(bench_mod, tmp_path, monkeypatch):
    monkeypatch.setenv("DYNTPU_BENCH_DETAIL", str(tmp_path / "detail.json"))
    _fill_representative(bench_mod)
    bench_mod.ERRORS["parity_disagg"] = {
        "error": "TimeoutError: section exceeded 2400s budget on the tunnel",
        "elapsed_s": 2400.1, "traceback_tail": "x" * 1500,
    }
    result = bench_mod._result()
    # what __main__ actually prints: compact separators (the driver keeps
    # only the last 2000 chars of stdout — measured at exactly 2000 in every
    # BENCH_r02..r05 capture — and ", " formatting alone costs ~200 chars)
    line = json.dumps(result, separators=(",", ":"))
    assert len(line) < 1950, f"artifact line too long: {len(line)}"
    s = result["summary"]
    assert s["headline_tok_s"] == 6354.12
    assert s["platform"] == "tpu"
    # replay spine: one aliased array per scenario, columns per replay_cols
    assert s["replay_cols"] == "goodput,ttft_p99_ms,itl_p99_ms,tok_s"
    assert s["replay"]["bursty"] == [0.9873, 3965, 552, 4123]
    assert set(s["replay"]) == {
        "bursty", "int8", "lctx", "lora", "spec", "fleet", "mm",
    }
    assert result["value"] == 6354.12
    assert s["ref_workload_isl3k_osl150"]["tok_s"] == 731.55
    # the per-stage seconds moved to bench_detail.json in r19: the flat-TTFT
    # attribution now rides the gated prefill_anatomy keys instead
    assert "stages" not in s["ref_workload_isl3k_osl150"]
    # prefill anatomy acceptance keys (pipelined arm only; the depth-1
    # baseline arm and stall deltas stay in bench_detail.json — parity and
    # strictly-fewer-stalls are asserted inside the section itself)
    assert s["prefill_anatomy"] == {
        "fixed_ms": 9.871, "dispatches": 8, "ttft_p50_ms": 1287.3,
    }
    assert s["http_serving"]["http_over_engine_ratio"] == 0.96
    # step-anatomy acceptance keys ride the compact line (decode arm only;
    # the dispatch cadence and spec/LoRA arm breakdowns stay in
    # bench_detail.json)
    assert s["step_anatomy"] == {
        "host_frac": 0.3124, "roofline_frac": 0.6981,
    }
    # cost attribution: worst residual across both planes + hot-path price
    assert s["metering"] == {"err": 2.7e-09, "frac": 0.000423}
    assert s["mla_decode_tok_s"] == 4658.33
    assert s["moe_decode_tok_s"] == 5425.87
    # live-migration acceptance keys ride the compact line (salvage counters
    # and the kill-arm pause stay in bench_detail.json)
    assert s["migration"] == {
        "parity": 1.0, "pause_ms_p99": 1234.5, "goodput_delta": 0.0417,
    }
    # multi-tenant QoS acceptance keys ride the compact line (per-tenant
    # breakdowns and budget values stay in bench_detail.json)
    assert s["qos"] == {
        "tenant_b_itl_ratio": 0.0052, "shed_fraction": 0.8333,
        "critical_goodput": 0.9873,
    }
    # flight recorder: short keys on the line (full-named report in
    # bench_detail.json)
    assert s["events"] == {"emit_frac": 0.002803, "rec_ms": 0.2905}
    # ratio_derived moved to bench_detail.json (truncation budget)
    assert s["parity_kv_routing"] == {"ratio_measured": 2.79}
    assert s["parity_host_offload"]["ratio_projected"] == 8.82
    # third KV tier acceptance keys ride the compact line (restore counters
    # and the cap-under-churn proof stay in bench_detail.json)
    assert s["kv_tiers"] == {
        "resume_ttft_ratio": 0.231, "restore_parity": 1.0,
        "disk_resident_bytes": 452984832,
    }
    # errors land compactly (no tracebacks) in the summary itself
    assert "TimeoutError" in s["errors"]["parity_disagg"]
    assert "traceback" not in json.dumps(s)


def test_detail_lands_in_file_not_stdout(bench_mod, tmp_path, monkeypatch):
    monkeypatch.setenv("DYNTPU_BENCH_DETAIL", str(tmp_path / "detail.json"))
    _fill_representative(bench_mod)
    result = bench_mod._result()
    line = json.dumps(result)
    # full detail must NOT ride stdout (it is what got truncated in r4)
    assert "total_output_tokens" not in line
    path = result["detail_file"]
    assert path and os.path.exists(path)
    with open(path) as f:
        detail = json.load(f)
    assert detail["detail"]["headline_bs%d_ps%d" % bench_mod.HEADLINE][
        "total_output_tokens"] == 8192


def test_empty_sections_still_produce_parseable_line(bench_mod, tmp_path, monkeypatch):
    """A fatal crash before any section lands must still emit valid compact
    JSON with an errors map (the driver's `parsed` must never be null)."""
    monkeypatch.setenv("DYNTPU_BENCH_DETAIL", str(tmp_path / "detail.json"))
    result = bench_mod._result(extra_errors={"__run__": {"error": "boom"}})
    line = json.dumps(result)
    parsed = json.loads(line)
    assert parsed["value"] == 0.0
    assert parsed["summary"]["errors"]["__run__"] == "boom"
    assert len(line) < 1800
