"""tools/graftlint: the static-analysis half of the lint gate. Acceptance:
each of the six detectors catches its seeded positive fixture and stays
silent on its negative fixture (which includes reasoned suppressions, so the
allowlist machinery is exercised), the whole-repo scan comes back with zero
unsuppressed findings, the suppression/baseline plumbing behaves, exit codes
follow the bench_compare convention, and the metric-conformance detector's
static view of DECLARED_METRIC_FAMILIES matches the runtime declaration the
prometheus --check gate validates against the rendered surfaces.

Tier-1, CPU, fast: everything here is stdlib AST work except the one
exposition cross-validation test that renders the sample surfaces.
"""

import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from tools.graftlint.cli import DEFAULT_SCAN_ROOTS, main, run_scan  # noqa: E402
from tools.graftlint.core import load_baseline, write_baseline  # noqa: E402
from tools.graftlint.selfcheck import _HEADER_RE, FIXTURES_DIR, self_check  # noqa: E402

# ---------------- fixtures: one positive and one negative per detector ----


def _fixture_cases():
    cases = []
    for f in sorted(FIXTURES_DIR.glob("*.py")):
        m = _HEADER_RE.search(f.read_text().splitlines()[0])
        assert m, f"{f.name} missing its graftlint-fixture header"
        cases.append(pytest.param(f, m.group(1), int(m.group(2)), id=f.name))
    return cases


def test_fixture_inventory_covers_all_detectors():
    cases = [c.values for c in _fixture_cases()]
    rules = {rule for (_fixture, rule, _expect) in cases}
    assert rules == {
        "host-sync",
        "use-after-donation",
        "recompile-hazard",
        "async-blocking",
        "metric-conformance",
        "event-conformance",
    }
    # a positive AND a negative per rule
    by_rule = {}
    for _fixture, rule, expect in cases:
        by_rule.setdefault(rule, set()).add(expect > 0)
    assert all(v == {True, False} for v in by_rule.values()), by_rule


@pytest.mark.parametrize("fixture,rule,expect", _fixture_cases())
def test_detector_fixture(fixture, rule, expect):
    findings, errors = run_scan([fixture], root=FIXTURES_DIR, force_hot=True)
    assert not errors
    active = [f for f in findings if not f.suppressed]
    mine = [f for f in active if f.rule == rule]
    assert len(mine) == expect, [f.render() for f in active]
    # no detector bleeds findings into another detector's fixture
    assert [f for f in active if f.rule != rule] == []


def test_self_check_green():
    assert self_check() == []


# ---------------- whole-repo gate ----------------


def test_repo_scan_zero_unsuppressed_findings():
    """The acceptance criterion: the shipped tree is clean under all six
    detectors (modulo reasoned suppressions and the checked-in baseline)."""
    findings, errors = run_scan([ROOT / p for p in DEFAULT_SCAN_ROOTS], root=ROOT)
    assert not errors
    baseline = load_baseline(ROOT / "tools/graftlint/baseline.json")
    active = [
        f
        for f in findings
        if not f.suppressed and f.fingerprint not in baseline
    ]
    assert active == [], "\n" + "\n".join(f.render() for f in active)
    # every suppression in the tree carries a reason (reasonless ones are
    # converted into findings by make_finding, so active==[] implies this;
    # assert the stronger property directly for a readable failure)
    for f in findings:
        if f.suppressed:
            assert f.suppress_reason, f.render()


# ---------------- suppression + baseline machinery ----------------


def test_suppression_without_reason_is_a_finding(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n\n\ndef f(x):\n"
        "    jax.block_until_ready(x)  # graftlint: sync-ok\n"
    )
    findings, _ = run_scan([bad], root=tmp_path, force_hot=True)
    active = [f for f in findings if not f.suppressed]
    assert len(active) == 1
    assert "suppression without a reason" in active[0].message


def test_reasoned_suppression_suppresses(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text(
        "import jax\n\n\ndef f(x):\n"
        "    jax.block_until_ready(x)  # graftlint: sync-ok warmup only\n"
    )
    findings, _ = run_scan([ok], root=tmp_path, force_hot=True)
    assert [f for f in findings if not f.suppressed] == []
    assert [f.suppress_reason for f in findings if f.suppressed] == ["warmup only"]


def test_baseline_acknowledges_debt(tmp_path):
    src = tmp_path / "debt.py"
    src.write_text("import jax\n\n\ndef f(x):\n    jax.block_until_ready(x)\n")
    findings, _ = run_scan([src], root=tmp_path, force_hot=True)
    active = [f for f in findings if not f.suppressed]
    assert len(active) == 1
    bl = tmp_path / "baseline.json"
    write_baseline(bl, active)
    assert load_baseline(bl) == {active[0].fingerprint}
    # fingerprints survive line drift: prepend a comment line and re-scan
    src.write_text("# a new comment\n" + src.read_text())
    findings2, _ = run_scan([src], root=tmp_path, force_hot=True)
    fps = load_baseline(bl)
    assert [f for f in findings2 if not f.suppressed and f.fingerprint not in fps] == []


# ---------------- CLI exit codes (the bench_compare convention) ----------


def test_cli_exit_codes(tmp_path, capsys):
    dirty = tmp_path / "dirty"
    dirty.mkdir()
    (dirty / "x.py").write_text(
        "import time\n\n\nasync def tick():\n    time.sleep(1)\n"
    )
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "y.py").write_text("import asyncio\n\n\nasync def tick():\n    await asyncio.sleep(1)\n")
    assert main([str(dirty), "--root", str(tmp_path), "--no-baseline"]) == 1
    assert main([str(clean), "--root", str(tmp_path), "--no-baseline"]) == 0
    capsys.readouterr()


def test_module_entrypoint_self_check():
    """lint.sh invokes `python -m tools.graftlint --self-check`; pin the -m
    wiring from a clean interpreter."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--self-check"],
        cwd=ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "self-check passed" in proc.stdout


# ---------------- metric-conformance cross-validation ----------------


def test_static_declaration_matches_runtime_tuple():
    """The detector's AST view of DECLARED_METRIC_FAMILIES must equal the
    tuple Python sees at import time (same file, two readers)."""
    import ast

    from dynamo_tpu.utils.prometheus import DECLARED_METRIC_FAMILIES
    from tools.graftlint.detectors.metrics_conformance import (
        DECLARING_MODULE,
        _find_declaration,
    )

    tree = ast.parse((ROOT / DECLARING_MODULE).read_text())
    declared, _ = _find_declaration(tree)
    assert {name for name, _ in declared} == set(DECLARED_METRIC_FAMILIES)
    assert len(DECLARED_METRIC_FAMILIES) == len(set(DECLARED_METRIC_FAMILIES))


def test_declared_families_match_rendered_surfaces():
    """The runtime half of the contract: every declared family is rendered
    by the cluster-free sample surfaces and vice versa (what
    `python -m dynamo_tpu.utils.prometheus --check` gates in lint.sh)."""
    from dynamo_tpu.utils.prometheus import _declaration_problems, _sample_surfaces

    assert _declaration_problems(_sample_surfaces()) == []


def test_metric_typo_is_caught(tmp_path):
    """End-to-end: a typo'd emitting literal fails the gate even though the
    declaration itself is well-formed."""
    mod = tmp_path / "emitter.py"
    mod.write_text(
        "DECLARED_METRIC_FAMILIES = (\n"
        '    "dynamo_demo_requests_total",\n'
        ")\n\n\n"
        "def render():\n"
        '    return "dynamo_demo_reqeusts_total"\n'  # transposed letters
    )
    findings, _ = run_scan([mod], root=tmp_path)
    msgs = [f.message for f in findings if not f.suppressed]
    assert any("dynamo_demo_reqeusts_total" in m for m in msgs), msgs
    assert any("never referenced" in m for m in msgs), msgs
