"""Disk KV tier (engine/kv_store.py): on-disk record integrity, int8
compression arithmetic (a disk byte holds ~2x the bf16 context), byte-budget
LRU enforcement, and corrupt/truncated-file restores degrading to misses —
never wrong answers."""

import os

import numpy as np
import pytest

from dynamo_tpu.engine.kv_store import (
    DiskKvStore,
    _block_disk_nbytes,
    _decode_block,
    _encode_block,
    _quantize_block,
    disk_block_bytes,
    resolve_disk_capacity_blocks,
)
from dynamo_tpu.quant.kv import kv_page_bytes

#: wire block layout [L, 2, n, ps, hd] with page axis 2
SHAPE = (2, 2, 1, 4, 8)
PAGE_AXIS = 2


def _block(seed=0):
    return np.random.default_rng(seed).standard_normal(SHAPE).astype(np.float32)


# ---------------- on-disk record format ----------------


def test_encode_decode_roundtrip_float():
    x = _block(1)
    dec = _decode_block(_encode_block(77, x), 77)
    assert isinstance(dec, np.ndarray) and dec.shape == SHAPE
    # per-row symmetric int8: error bounded by half a quantization step
    q, s = _quantize_block(x)
    step = s.reshape(SHAPE[:4] + (1,))
    assert np.all(np.abs(dec.astype(np.float32) - x) <= step * 0.51)


def test_encode_decode_bit_exact_int8_wire():
    """An already-int8 wire block (kv_cache_dtype="int8") stores losslessly:
    the park/resume round trip is bit-exact, so greedy decoding stays
    token-identical across a demote/restore cycle."""
    rng = np.random.default_rng(2)
    wire = {
        "q": rng.integers(-127, 128, SHAPE, dtype=np.int8),
        "s": rng.standard_normal(SHAPE[:4]).astype(np.float32),
    }
    dec = _decode_block(_encode_block(5, wire), 5)
    assert set(dec) == {"q", "s"}
    np.testing.assert_array_equal(dec["q"], wire["q"])
    np.testing.assert_array_equal(dec["s"], wire["s"])


def test_decode_rejects_corruption():
    raw = _encode_block(9, _block(3))
    with pytest.raises(ValueError):
        _decode_block(b"XXXX" + raw[4:], 9)  # bad magic
    with pytest.raises(ValueError):
        _decode_block(raw[:-3], 9)  # truncated payload
    flipped = bytearray(raw)
    flipped[-1] ^= 0xFF
    with pytest.raises(ValueError):
        _decode_block(bytes(flipped), 9)  # checksum mismatch
    with pytest.raises(ValueError):
        _decode_block(raw, 10)  # identity mismatch


def test_quantize_zero_rows_clean():
    q, s = _quantize_block(np.zeros(SHAPE, np.float32))
    assert not q.any()
    assert np.isfinite(s).all()


# ---------------- capacity arithmetic ----------------


def test_disk_budget_resolves_at_int8_page_cost():
    """The disk sibling of resolve_host_capacity_blocks: the on-disk block
    cost is ALWAYS the int8 wire cost, so the same byte budget holds ~2x
    the blocks a bf16 host tier does (int8 row = hd + 4 scale bytes vs
    2*hd bf16 bytes)."""
    ps, heads, hd, layers = 64, 8, 128, 24
    blk_disk = disk_block_bytes(ps, heads, hd, layers)
    assert blk_disk == kv_page_bytes(ps, heads, hd, layers, "int8")
    blk_bf16 = kv_page_bytes(ps, heads, hd, layers, None)
    budget = 1 << 26
    n_disk = resolve_disk_capacity_blocks(budget, blk_disk)
    n_bf16 = budget // blk_bf16
    assert n_disk == budget // blk_disk
    assert n_disk > 1.8 * n_bf16  # ~2x at hd=128 (132 vs 256 bytes/row)
    assert resolve_disk_capacity_blocks(0, blk_disk) == 0
    assert resolve_disk_capacity_blocks(budget, 0) == 0


def test_block_disk_nbytes_matches_encoded_payload():
    x = _block(4)
    raw = _encode_block(1, x)
    q, s = _quantize_block(x)
    assert _block_disk_nbytes(x) == q.nbytes + s.nbytes
    # the header rides on top of the payload the budget accounts
    assert len(raw) > _block_disk_nbytes(x)


# ---------------- store: spill / restore / LRU budget ----------------


def test_store_spill_restore_roundtrip(tmp_path):
    store = DiskKvStore(directory=str(tmp_path), budget_bytes=1 << 20,
                        page_axis=PAGE_AXIS)
    try:
        blocks = {h: _block(h) for h in (101, 102, 103)}
        for h, b in blocks.items():
            assert store.spill(h, b) == []  # under budget: nothing evicted
        assert len(store) == 3 and all(h in store for h in blocks)
        assert store.leading_run([101, 102, 103, 999]) == [101, 102, 103]
        res = store.restore([101, 102, 103])
        assert res.status == "hit" and res.blocks == 3 and not res.failed
        (part,) = res.parts
        assert (part.block_from, part.block_to) == (0, 3)
        assert part.cat_axis == PAGE_AXIS
        # wire-concat along the page axis, per-block values within a quant step
        assert part.data.shape[PAGE_AXIS] == 3 * SHAPE[PAGE_AXIS]
        for i, h in enumerate((101, 102, 103)):
            got = np.take(part.data, [i], axis=PAGE_AXIS)
            assert np.allclose(got, blocks[h], atol=np.abs(blocks[h]).max() / 64)
    finally:
        store.close()


def test_store_lru_budget_and_discard(tmp_path):
    one = _block_disk_nbytes(_block(0))
    store = DiskKvStore(directory=str(tmp_path), budget_bytes=2 * one,
                        page_axis=PAGE_AXIS, block_bytes=one)
    try:
        evicted = []
        for h in range(1, 6):
            evicted += store.spill(h, _block(h))
        assert evicted == [1, 2, 3]  # LRU order; 4, 5 resident
        assert len(store) == 2 and store.bytes_resident <= 2 * one
        assert store.drops == 3 and store.spills == 5
        store.flush()
        assert not os.path.exists(store._path(1))
        assert os.path.exists(store._path(5))
        # revisit spill refreshes LRU position instead of re-writing
        assert store.spill(4, _block(4)) == []
        assert store.spill(6, _block(6)) == [5]  # 4 was refreshed; 5 is LRU
        # discard (promotion back up the ladder) unlinks and frees budget
        assert store.discard(4) and not store.discard(4)
        store.flush()
        assert not os.path.exists(store._path(4))
        assert store.bytes_resident == one
    finally:
        store.close()


def test_store_budget_zero_and_oversize_block():
    store = DiskKvStore(budget_bytes=0)
    try:
        # no budget: the block leaves its last tier immediately
        assert store.spill(7, _block(7)) == [7]
        assert len(store) == 0
    finally:
        store.close()
    small = DiskKvStore(budget_bytes=10)
    try:
        assert small.spill(8, _block(8)) == [8]  # budget can never hold it
        assert len(small) == 0
    finally:
        small.close()


def test_store_corrupt_file_restore_falls_back(tmp_path):
    """A corrupt/truncated block file is a MISS, never a wrong answer:
    restore stops at the first bad block (the tail recomputes) and reports
    the bad hashes so the engine emits their one truthful removed."""
    store = DiskKvStore(directory=str(tmp_path), budget_bytes=1 << 20,
                        page_axis=PAGE_AXIS)
    try:
        for h in (201, 202, 203):
            store.spill(h, _block(h))
        store.flush()
        with open(store._path(202), "r+b") as f:  # truncate the middle block
            f.truncate(16)
        res = store.restore([201, 202, 203])
        assert res.status == "hit" and res.blocks == 1
        assert res.failed == [202]
        assert store.io_errors >= 1
        # first block bad: the whole restore is a miss
        with open(store._path(201), "r+b") as f:
            f.seek(0)
            f.write(b"JUNK")
        res = store.restore([201, 203])
        assert res.status == "miss" and res.failed == [201]
    finally:
        store.close()


def test_restore_async_miss_is_immediate():
    store = DiskKvStore(budget_bytes=1 << 20)
    try:
        fut = store.restore_async([12345])
        assert fut.done() and fut.result().status == "miss"
    finally:
        store.close()


def test_env_dir_override_and_owned_cleanup(tmp_path, monkeypatch):
    env_dir = tmp_path / "kvdir"
    monkeypatch.setenv("DYNTPU_KV_DISK_DIR", str(env_dir))
    store = DiskKvStore(budget_bytes=1 << 20, page_axis=PAGE_AXIS)
    try:
        assert store.directory == str(env_dir)
        store.spill(42, _block(42))
        store.flush()
    finally:
        store.close()
    # an env-provided directory is the USER'S: close never deletes it
    assert env_dir.is_dir() and os.path.exists(os.path.join(str(env_dir), f"{42:016x}.kvb"))
    monkeypatch.delenv("DYNTPU_KV_DISK_DIR")
    owned = DiskKvStore(budget_bytes=1 << 20)
    d = owned.directory
    owned.spill(1, _block(1))
    owned.close()
    assert not os.path.exists(d)  # owned tempdir cleaned up


# ---------------- config validation ----------------


def test_disk_config_requires_host_tier():
    from dynamo_tpu.engine.config import EngineConfig

    common = dict(model_id="tiny", page_size=4, num_pages=16, max_seqs=2,
                  max_model_len=32)
    with pytest.raises(ValueError, match="requires a host cache tier"):
        EngineConfig(disk_cache_bytes=1 << 20, **common)
    with pytest.raises(ValueError):
        EngineConfig(disk_cache_bytes=-1, host_cache_blocks=4, **common)
    cfg = EngineConfig(disk_cache_bytes=1 << 20, host_cache_blocks=4, **common)
    assert cfg.disk_cache_bytes == 1 << 20
