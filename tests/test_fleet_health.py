"""Fleet health plane end-to-end: two-worker loopback kill test (acceptance),
engine health/resource integration on a tiny engine, and the /live vs /ready
probe split on the HTTP service."""

import asyncio
import time

import aiohttp

from dynamo_tpu.cplane.broker import Broker
from dynamo_tpu.components.frontend import FrontendService
from dynamo_tpu.components.metrics import MetricsService
from dynamo_tpu.components.planner import PlannerService
from dynamo_tpu.frontends.pipeline import card_for_model
from dynamo_tpu.llm.kv_router.router import KvRouter
from dynamo_tpu.llm.model_registry import ModelEntry, register_model
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.prometheus import check_exposition

NS = "fh"


async def _poll(predicate, timeout=8.0, interval=0.1, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = predicate()
        if asyncio.iscoroutine(result):
            result = await result
        if result:
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def _worker_stats(state="ready"):
    return {
        "kv_metrics": {
            "request_active_slots": 1, "request_total_slots": 100,
            "kv_active_blocks": 10, "kv_total_blocks": 1000,
            "num_requests_waiting": 0, "gpu_cache_usage_perc": 0.01,
            "gpu_prefix_cache_hit_rate": 0.0,
        },
        "health": {"state": state, "heartbeat_age_s": 0.01},
        "resources": {"kv_pages_used": 10, "kv_pages_total": 1000,
                      "xla_compiles": 2, "hbm_bytes_in_use": 0},
        "stage_seconds": {"prefill_s": 0.1},
    }


def test_two_worker_kill_health_plane():
    """Acceptance: kill one of two workers and assert its health goes
    stale/dead in /cluster/status, the router stops selecting it, the
    planner's observe() excludes it, and /ready on a frontend pointed only
    at the dead pool flips to 503 while /live stays 200."""

    async def body():
        broker = Broker()
        bport = await broker.start()
        addr = f"127.0.0.1:{bport}"

        async def handler(req):
            yield {"ok": True}

        # two mock decode workers on the "backend" component; worker 0 ALSO
        # exclusively serves the "deadpool" component the frontend points at
        rts = []
        for i in range(2):
            rt = DistributedRuntime(cplane_address=addr)
            await rt.connect()
            ep = rt.namespace(NS).component("backend").endpoint("generate")
            await ep.serve_endpoint(handler, metrics=_worker_stats)
            rts.append(rt)
        dead_ep = rts[0].namespace(NS).component("deadpool").endpoint("generate")
        await dead_ep.serve_endpoint(handler, metrics=_worker_stats)
        id0 = rts[0].primary_lease.lease_id
        id1 = rts[1].primary_lease.lease_id

        mon_rt = DistributedRuntime(cplane_address=addr)
        await mon_rt.connect()
        svc = MetricsService(
            mon_rt, NS, "backend", host="127.0.0.1", port=0,
            interval=0.15, max_missed_scrapes=2,
        )
        mport = await svc.start()

        router_rt = DistributedRuntime(cplane_address=addr)
        await router_rt.connect()
        router = KvRouter(router_rt, NS, "backend", kv_block_size=4,
                          metrics_interval=0.15)
        await router.start()

        planner_rt = DistributedRuntime(cplane_address=addr)
        await planner_rt.connect()
        planner = PlannerService(planner_rt, NS, decode_component="backend",
                                 interval=3600.0)
        planner.aggregator.max_missed_scrapes = 2

        # frontend pointed ONLY at the deadpool component (worker 0)
        front_rt = DistributedRuntime(cplane_address=addr)
        await front_rt.connect()
        card = card_for_model("tiny")
        await register_model(front_rt.cplane, ModelEntry(
            name="tiny", endpoint=f"dyn://{NS}.deadpool.generate",
            model_type="chat", card=card,
        ))
        frontend = FrontendService(front_rt, host="127.0.0.1", port=0)
        fport = await frontend.start()
        base = f"http://127.0.0.1:{fport}"

        try:
            async with aiohttp.ClientSession() as http:
                # ---- healthy fleet baseline ----
                await _poll(
                    lambda: len(router.aggregator.get_metrics()) == 2,
                    what="router sees both workers",
                )
                picked = {await router.schedule([1, 2, 3, 4]) for _ in range(6)}
                assert picked <= {id0, id1} and picked

                await planner.step()
                loads = planner.aggregator.get_metrics()
                assert {w.worker_id for w in loads} == {id0, id1}

                async with http.get(f"{base}/ready") as resp:
                    assert resp.status == 200
                    body = await resp.json()
                    assert body["status"] == "ready"

                async def status_doc():
                    async with http.get(
                        f"http://127.0.0.1:{mport}/cluster/status"
                    ) as resp:
                        assert resp.status == 200
                        return await resp.json()

                await _poll(
                    lambda: status_doc(), what="cluster status up",
                )
                doc = await status_doc()
                assert doc["summary"]["workers"] == 2
                assert all(w["servable"] for w in doc["workers"])
                assert all(w["health"]["state"] == "ready" for w in doc["workers"])

                # federated /metrics carries per-worker labeled families
                async with http.get(f"http://127.0.0.1:{mport}/metrics") as resp:
                    text = await resp.text()
                assert check_exposition(text) == []
                assert "llm_worker_health_state" in text
                assert f'worker_id="{id0:x}"' in text and f'worker_id="{id1:x}"' in text
                assert "llm_worker_resource_kv_pages_used" in text

                # ---- kill worker 0 (lease revoke + stats stop) ----
                await rts[0]._shutdown_hook()

                # /cluster/status: worker 0 goes stale, then ages out entirely
                async def dead_in_status():
                    doc = await status_doc()
                    entry = {w["worker_id"]: w for w in doc["workers"]}.get(f"{id0:x}")
                    return entry is None or (entry["stale"] and not entry["servable"])

                async def aged_out():
                    doc = await status_doc()
                    return f"{id0:x}" not in {w["worker_id"] for w in doc["workers"]}

                await _poll(dead_in_status, what="worker 0 stale/dead in status")
                await _poll(aged_out, what="worker 0 aged out of status")

                # router stops selecting the dead worker
                await _poll(
                    lambda: [w.worker_id for w in router.aggregator.get_metrics()] == [id1],
                    what="router fleet view drops worker 0",
                )
                for _ in range(8):
                    assert await router.schedule([1, 2, 3, 4]) == id1

                # planner observe() excludes it once its own aggregator ages
                # the silent worker out (max_missed_scrapes rounds)
                for _ in range(planner.aggregator.max_missed_scrapes + 1):
                    await planner.step()
                loads = planner.aggregator.get_metrics()
                assert {w.worker_id for w in loads} == {id1}
                # and the decode replica count reflects the surviving instance
                assert await planner._replica_count("backend") == 1

                # frontend pointed only at the dead pool: /ready 503, /live 200
                async def front_unready():
                    async with http.get(f"{base}/ready") as resp:
                        return resp.status == 503
                await _poll(front_unready, what="/ready flips to 503")
                async with http.get(f"{base}/ready") as resp:
                    body = await resp.json()
                    assert body["status"] == "unready"
                async with http.get(f"{base}/live") as resp:
                    assert resp.status == 200
                    assert (await resp.json())["status"] == "live"
        finally:
            await frontend.stop()
            await router.stop()
            await planner.stop()
            await svc.stop()
            for rt in (rts[1], mon_rt, router_rt, planner_rt, front_rt):
                await rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())


# ---------------- tiny-engine integration ----------------


def test_engine_health_resources_and_slo():
    """A real (tiny) engine reports ready after start, resource gauges and
    compile counts after serving, SLO observations, and dead after shutdown;
    its /metrics exposition stays conformant throughout."""
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.scheduler import EngineRequest

    from tests.test_engine import tiny_engine_config

    async def body():
        cfg = tiny_engine_config(slo_ttft_ms=60_000.0)
        engine = AsyncJaxEngine(cfg)
        assert engine.health.state == "starting"
        await engine.start()
        assert engine.health.state == "ready"

        outs = []
        async for out in engine.generate(
            EngineRequest(request_id="r1", token_ids=[1, 2, 3, 4, 5])
        ):
            outs.append(out)
        assert outs

        r = engine.resource_snapshot()
        assert r["kv_pages_total"] == cfg.num_pages - 1
        assert r["kv_pages_peak"] >= 1  # watermark moved during serving
        assert r["xla_compiles"] >= 1 and r["xla_compile_s"] > 0
        assert r["hbm_bytes_in_use"] == 0  # CPU: graceful zeros
        assert r["prefix_cache_miss_blocks"] >= 0

        # heartbeat is live while the loop runs
        await asyncio.sleep(0.05)
        assert engine.health.heartbeat_age() < 5.0

        slo = engine.slo_snapshot()
        assert slo["metrics"]["ttft"]["count"] >= 1
        assert slo["ok"]  # 60s target: comfortably met

        text = engine.render_stage_metrics()
        assert check_exposition(text) == []
        assert "dynamo_engine_kv_pages" in text
        assert "dynamo_engine_xla_compiles_total" in text
        assert 'dynamo_health_state{component="engine",state="ready"} 1' in text
        assert "dynamo_engine_slo_latency_seconds" in text

        await engine.shutdown()
        assert engine.health.state == "dead"

    asyncio.run(body())


def test_worker_stats_carry_health_plane():
    """WorkerService._stats: kv_metrics + health + resources + slo ride one
    stats broadcast (what the aggregator scrapes)."""
    from dynamo_tpu.components.worker import WorkerService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    from tests.test_engine import tiny_engine_config

    async def body():
        broker = Broker()
        bport = await broker.start()
        rt = DistributedRuntime(cplane_address=f"127.0.0.1:{bport}")
        await rt.connect()
        svc = WorkerService(
            rt, NS, "backend", ModelDeploymentCard.for_tiny("tiny"),
            tiny_engine_config(), register=False,
        )
        await svc.start()
        try:
            stats = svc._stats()
            assert stats["health"]["state"] == "ready"
            assert stats["resources"]["kv_pages_total"] > 0
            assert "slo" in stats and "kv_metrics" in stats
        finally:
            await svc.stop()
            await rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())


# ---------------- /live vs /ready probe split ----------------


def test_http_live_ready_split():
    from dynamo_tpu.llm.http.service import HttpService

    async def body():
        state = {"ok": True}
        svc = HttpService(
            host="127.0.0.1", port=0,
            readiness=lambda: (state["ok"], {"detail": "x"}),
        )
        port = await svc.start()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(f"{base}/ready") as resp:
                    assert resp.status == 200
                state["ok"] = False
                async with http.get(f"{base}/ready") as resp:
                    assert resp.status == 503
                    assert (await resp.json())["status"] == "unready"
                # /live is static: stays 200 regardless of readiness, and its
                # payload never touches the model manager
                async with http.get(f"{base}/live") as resp:
                    assert resp.status == 200
                    assert await resp.json() == {"status": "live"}
                # /health keeps the legacy model-listing behavior
                async with http.get(f"{base}/health") as resp:
                    assert resp.status == 200
                    assert "models" in await resp.json()
                # SLO families render on /metrics
                svc.slo.observe("ttft", 0.01)
                async with http.get(f"{base}/metrics") as resp:
                    text = await resp.text()
                assert check_exposition(text) == []
                assert "dynamo_slo_latency_seconds" in text
        finally:
            await svc.stop()

    asyncio.run(body())


def test_http_ready_defaults_to_200_without_provider():
    from dynamo_tpu.llm.http.service import HttpService

    async def body():
        svc = HttpService(host="127.0.0.1", port=0)
        port = await svc.start()
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(f"http://127.0.0.1:{port}/ready") as resp:
                    assert resp.status == 200
        finally:
            await svc.stop()

    asyncio.run(body())
