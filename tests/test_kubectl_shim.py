"""KubectlCluster exercised against a scripted fake `kubectl` binary.

The env has no kind/kubectl, so the achievable bar for the real-cluster path
is argv/stdin/JSON-output fidelity: a fake kubectl on PATH records every
invocation (argv + stdin) to a log and replays canned JSON, and the
controller's ClusterApi drives through it — covering the shim's flag
construction, server-side-apply stdin feed, label-selector listing, and
error propagation (reference: the operator's client-go usage in
deploy/dynamo/operator/internal/controller/dynamonimdeployment_controller.go,
here reduced to the kubectl CLI contract)."""

import asyncio
import json
import os
import stat

import pytest

from dynamo_tpu.deploy.controller import DeployController, KubectlCluster
from dynamo_tpu.deploy.reconciler import MANAGED_BY


FAKE_KUBECTL = r'''#!/usr/bin/env python3
import json, os, sys

log_path = os.environ["FAKE_KUBECTL_LOG"]
fixture_path = os.environ["FAKE_KUBECTL_FIXTURES"]
stdin = sys.stdin.read() if not sys.stdin.isatty() else ""
with open(log_path, "a") as f:
    f.write(json.dumps({"argv": sys.argv[1:], "stdin": stdin}) + "\n")

args = sys.argv[1:]
if args and args[0] == "get":
    with open(fixture_path) as f:
        fixtures = json.load(f)
    key = "all-namespaces" if "--all-namespaces" in args else "namespaced"
    print(json.dumps(fixtures.get(key, {"items": []})))
    sys.exit(0)
if args and args[0] == "apply":
    obj = json.loads(stdin)
    if obj.get("metadata", {}).get("name", "").startswith("reject-"):
        print("error: admission webhook denied", file=sys.stderr)
        sys.exit(1)
    print(json.dumps({"applied": obj["metadata"]["name"]}))
    sys.exit(0)
if args and args[0] == "delete":
    sys.exit(0)
sys.exit(2)
'''


@pytest.fixture()
def fake_kubectl(tmp_path):
    path = tmp_path / "kubectl"
    path.write_text(FAKE_KUBECTL)
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    log = tmp_path / "calls.jsonl"
    fixtures = tmp_path / "fixtures.json"
    fixtures.write_text(json.dumps({"namespaced": {"items": []},
                                    "all-namespaces": {"items": []}}))
    os.environ["FAKE_KUBECTL_LOG"] = str(log)
    os.environ["FAKE_KUBECTL_FIXTURES"] = str(fixtures)
    yield str(path), log, fixtures
    os.environ.pop("FAKE_KUBECTL_LOG", None)
    os.environ.pop("FAKE_KUBECTL_FIXTURES", None)


def calls(log):
    if not log.exists():
        return []
    return [json.loads(line) for line in log.read_text().splitlines()]


def test_apply_uses_server_side_apply_with_field_manager(fake_kubectl):
    kubectl, log, _ = fake_kubectl
    cluster = KubectlCluster(kubectl=kubectl)
    obj = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "web", "namespace": "prod", "labels": {}},
        "spec": {"replicas": 2},
    }
    asyncio.run(cluster.apply(obj))
    (call,) = calls(log)
    assert call["argv"][:3] == ["apply", "-f", "-"]
    assert "--server-side" in call["argv"]
    fm = call["argv"].index("--field-manager")
    assert call["argv"][fm + 1] == MANAGED_BY
    # the full object rode stdin, byte-exact JSON
    assert json.loads(call["stdin"]) == obj


def test_apply_error_propagates(fake_kubectl):
    kubectl, _, _ = fake_kubectl
    cluster = KubectlCluster(kubectl=kubectl)
    obj = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "reject-me", "namespace": "prod"},
    }
    with pytest.raises(RuntimeError, match="admission webhook"):
        asyncio.run(cluster.apply(obj))


def test_list_objects_selector_and_parse(fake_kubectl):
    kubectl, log, fixtures = fake_kubectl
    items = [{
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "web", "namespace": "prod",
                     "labels": {"app.kubernetes.io/managed-by": MANAGED_BY}},
    }]
    fixtures.write_text(json.dumps({
        "namespaced": {"items": items},
        "all-namespaces": {"items": items},
    }))
    cluster = KubectlCluster(kubectl=kubectl)
    got = asyncio.run(cluster.list_objects("prod"))
    assert got == items
    (call,) = calls(log)
    assert call["argv"][0] == "get"
    assert "-n" in call["argv"] and call["argv"][call["argv"].index("-n") + 1] == "prod"
    sel = call["argv"][call["argv"].index("-l") + 1]
    assert sel == f"app.kubernetes.io/managed-by={MANAGED_BY}"
    # kinds include everything the reconciler can own
    kinds = call["argv"][1]
    for k in ("deployments", "statefulsets", "services", "horizontalpodautoscalers", "jobs"):
        assert k in kinds
    # cluster-wide namespace discovery
    namespaces = asyncio.run(cluster.list_managed_namespaces())
    assert namespaces == {"prod"}
    assert "--all-namespaces" in calls(log)[-1]["argv"]


def test_delete_ignore_not_found(fake_kubectl):
    kubectl, log, _ = fake_kubectl
    cluster = KubectlCluster(kubectl=kubectl)
    asyncio.run(cluster.delete("Deployment", "prod", "web"))
    (call,) = calls(log)
    assert call["argv"][:3] == ["delete", "deployment", "web"]
    assert "--ignore-not-found" in call["argv"]


def test_controller_converges_through_kubectl_shim(fake_kubectl, tmp_path):
    """Full converge pass over the shim: renders manifests, applies each via
    kubectl with server-side apply, and the image-build Job path rides the
    same surface (the closest this env gets to a real cluster)."""
    import time

    from dynamo_tpu.deploy.api_server import DeploymentStore
    from dynamo_tpu.deploy.crd import DeploymentSpec, ServiceSpec

    async def run():
        store = DeploymentStore()
        spec = DeploymentSpec(
            name="shimtest", image="dynamo-tpu:v1",
            services=[ServiceSpec(name="frontend",
                                  command=["python", "-m", "dynamo_tpu.components.frontend"],
                                  port=8080)],
        )
        store.put(spec.name, spec.to_dict())
        job = {
            "apiVersion": "batch/v1", "kind": "Job",
            "metadata": {"name": "bshim-image-build", "namespace": "default",
                         "labels": {"app.kubernetes.io/managed-by": MANAGED_BY}},
            "spec": {"template": {"spec": {"containers": []}}},
        }
        store.put_build("bshim", {
            "name": "bshim", "image": "r/i:v1", "context": "dir:///x",
            "namespace": "default", "phase": "pending", "job": job,
            "created_at": time.time(),
        })
        kubectl, log, fixtures = fake_kubectl
        ctrl = DeployController(store, KubectlCluster(kubectl=kubectl), interval=3600)
        await ctrl.converge_once()
        all_calls = calls(log)
        applies = [c for c in all_calls if c["argv"][0] == "apply"]
        # build Job + deployment's rendered objects all reached kubectl
        applied_names = [json.loads(c["stdin"])["metadata"]["name"] for c in applies]
        assert "bshim-image-build" in applied_names
        assert any(n.startswith("shimtest") for n in applied_names)
        assert store.get_build("bshim")["phase"] == "building"
        # status writeback happened off the kubectl listing
        assert store.get_status("shimtest")["created"] >= 1

    asyncio.run(run())
