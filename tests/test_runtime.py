"""Distributed runtime: two-plane RPC end-to-end over a real broker + TCP.

Mirrors the reference's pipeline/network tests (reference: lib/runtime/tests/
pipeline.rs + lib/bindings/python/tests fixture pattern)."""

import asyncio

import pytest

from dynamo_tpu.cplane.broker import Broker
from dynamo_tpu.runtime.codec import TwoPartMessage, decode, encode, CodecError
from dynamo_tpu.runtime.client import NoInstancesError
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.service import collect_service_stats
from dynamo_tpu.runtime.tcp import ResponseStreamError


def run(coro):
    return asyncio.run(coro)


# ---------------- codec ----------------


def test_two_part_codec_roundtrip():
    msg = TwoPartMessage(header=b"hdr", body=b"payload" * 100)
    data = encode(msg)
    out, rest = decode(data + b"extra")
    assert out == msg and rest == b"extra"


def test_two_part_codec_checksum():
    data = bytearray(encode(TwoPartMessage(header=b"h", body=b"b")))
    data[-1] ^= 0xFF
    with pytest.raises(CodecError):
        decode(bytes(data))


# ---------------- RPC harness ----------------


async def with_cluster(fn):
    broker = Broker()
    port = await broker.start()
    drts = []

    async def drt():
        d = DistributedRuntime(cplane_address=f"127.0.0.1:{port}")
        await d.connect()
        drts.append(d)
        return d

    try:
        return await fn(drt)
    finally:
        for d in drts:
            await d._shutdown_hook()
        await broker.stop()


async def serve_doubler(worker: DistributedRuntime, ns="test", comp="worker", ep="generate"):
    async def handler(request):
        for x in request["values"]:
            yield {"doubled": x * 2, "worker": worker.primary_lease.lease_id}

    endpoint = worker.namespace(ns).component(comp).endpoint(ep)
    return await endpoint.serve_endpoint(handler, metrics=lambda: {"load": 0.5})


def test_rpc_stream_end_to_end():
    async def body(drt):
        worker, caller = await drt(), await drt()
        await serve_doubler(worker)
        client = await caller.client("test", "worker", "generate")
        await client.wait_for_instances(timeout=5)
        stream = await client.random({"values": [1, 2, 3]})
        results = [item async for item in stream]
        assert [r["doubled"] for r in results] == [2, 4, 6]

    run(with_cluster(body))


def test_rpc_handler_error_propagates():
    async def body(drt):
        worker, caller = await drt(), await drt()

        async def bad_handler(request):
            yield {"ok": 1}
            raise ValueError("boom")

        ep = worker.namespace("test").component("w2").endpoint("gen")
        await ep.serve_endpoint(bad_handler)
        client = await caller.client("test", "w2", "gen")
        await client.wait_for_instances(timeout=5)
        stream = await client.random({})
        with pytest.raises(ResponseStreamError, match="boom"):
            async for _ in stream:
                pass

    run(with_cluster(body))


def test_rpc_error_before_stream():
    async def body(drt):
        worker, caller = await drt(), await drt()

        async def fail_fast(request):
            raise RuntimeError("rejected")
            yield  # pragma: no cover

        ep = worker.namespace("test").component("w3").endpoint("gen")
        await ep.serve_endpoint(fail_fast)
        client = await caller.client("test", "w3", "gen")
        await client.wait_for_instances(timeout=5)
        with pytest.raises(ResponseStreamError, match="rejected"):
            await client.random({})

    run(with_cluster(body))


def test_direct_and_round_robin_routing():
    async def body(drt):
        w1, w2, caller = await drt(), await drt(), await drt()
        await serve_doubler(w1)
        await serve_doubler(w2)
        client = await caller.client("test", "worker", "generate")
        ids = await client.wait_for_instances(timeout=5)
        while len(client.instance_ids()) < 2:
            await asyncio.sleep(0.02)
        ids = client.instance_ids()
        assert len(ids) == 2

        # direct: always the chosen worker
        for target in ids:
            stream = await client.direct({"values": [5]}, target)
            results = [r async for r in stream]
            assert results[0]["worker"] == target

        # round robin alternates
        seen = []
        for _ in range(4):
            stream = await client.round_robin({"values": [1]})
            results = [r async for r in stream]
            seen.append(results[0]["worker"])
        assert seen == [ids[0], ids[1], ids[0], ids[1]]

    run(with_cluster(body))


def test_instance_vanishes_on_worker_death():
    async def body(drt):
        worker, caller = await drt(), await drt()
        await serve_doubler(worker)
        client = await caller.client("test", "worker", "generate")
        await client.wait_for_instances(timeout=5)
        assert len(client.instance_ids()) == 1

        await worker._shutdown_hook()  # lease revoked => instance key deleted
        for _ in range(100):
            if not client.instance_ids():
                break
            await asyncio.sleep(0.02)
        assert client.instance_ids() == []
        with pytest.raises(NoInstancesError):
            await client.random({"values": [1]})

    run(with_cluster(body))


def test_stats_scrape():
    async def body(drt):
        w1, w2, caller = await drt(), await drt(), await drt()
        await serve_doubler(w1)
        await serve_doubler(w2)
        stats = await collect_service_stats(caller.cplane, "test", "worker", timeout=0.3)
        assert len(stats.endpoints) == 2
        assert all(e.data == {"load": 0.5} for e in stats.endpoints)
        ids = {e.instance_id for e in stats.endpoints}
        assert ids == {w1.primary_lease.lease_id, w2.primary_lease.lease_id}

    run(with_cluster(body))


def test_dyn_endpoint_address():
    async def body(drt):
        worker, caller = await drt(), await drt()
        await serve_doubler(worker)
        client = await caller.endpoint_client("dyn://test.worker.generate")
        await client.wait_for_instances(timeout=5)
        stream = await client.random({"values": [7]})
        results = [r async for r in stream]
        assert results[0]["doubled"] == 14

    run(with_cluster(body))


def test_request_context_propagates_across_hops():
    """The metadata bag injected at the edge reaches the first-hop handler via
    the envelope, and flows AMBIENTLY into a second hop the handler makes
    without any explicit plumbing (reference: pipeline/context.rs — Context
    rides every network hop)."""
    from dynamo_tpu.runtime.context import current_context, new_context, use_context

    async def body(drt):
        backend, middle, caller = await drt(), await drt(), await drt()

        async def backend_handler(request):
            ctx = current_context()
            yield {
                "trace": ctx.metadata.get("trace") if ctx else None,
                "rid": ctx.request_id if ctx else None,
            }

        ep = backend.namespace("ctx").component("backend").endpoint("gen")
        await ep.serve_endpoint(backend_handler)

        async def middle_handler(request):
            # no explicit context arg: the ambient context must carry over
            client = await middle.client("ctx", "backend", "gen")
            await client.wait_for_instances(timeout=5)
            stream = await client.random({"hop": 2})
            async for item in stream:
                ctx = current_context()
                item["middle_saw"] = ctx.metadata.get("trace") if ctx else None
                yield item

        ep2 = middle.namespace("ctx").component("middle").endpoint("gen")
        await ep2.serve_endpoint(middle_handler)

        client = await caller.client("ctx", "middle", "gen")
        await client.wait_for_instances(timeout=5)
        ctx = new_context(request_id="req-42", metadata={"trace": "abc123"})
        with use_context(ctx):
            stream = await client.random({"hop": 1})
        results = [item async for item in stream]
        assert results == [
            {"trace": "abc123", "rid": "req-42", "middle_saw": "abc123"}
        ]

        # no ambient context -> handler sees None
        stream = await client.random({"hop": 1})
        results = [item async for item in stream]
        assert results[0]["trace"] is None

    run(with_cluster(body))
