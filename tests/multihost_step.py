"""Child script for test_multihost: one process of a 2-process jax.distributed
CPU mesh. Joins via init_multihost (DYNTPU_COORDINATOR / NUM_PROCESSES /
PROCESS_ID — the same env the helm worker template sets), builds a global
dp=2 x tp=4 mesh spanning both processes, places the tiny Llama model's
params/KV with place_global, and runs one sharded decode step under jit.
Prints CHECKSUM <value>; the parent asserts both processes print the same.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

flags = os.environ.get("XLA_FLAGS", "")
flags = " ".join(f for f in flags.split() if "host_platform_device_count" not in f)
os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=4").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from dynamo_tpu.models.llama import LlamaConfig, LlamaModel  # noqa: E402
from dynamo_tpu.parallel.mesh import (  # noqa: E402
    MeshConfig,
    build_mesh,
    init_multihost,
    place_global,
)


def main() -> None:
    init_multihost()
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, jax.devices()

    mesh = build_mesh(MeshConfig(dp=2, tp=4))
    cfg = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=8, num_kv_heads=4, head_dim=16,
    )
    model = LlamaModel(cfg)
    # same seed in both processes -> identical host values; place_global
    # contributes each process's addressable shards
    params = place_global(model.init_params(jax.random.key(0)), model.param_shardings(mesh))
    kv = place_global(model.init_kv_cache(8, 4), model.kv_cache_sharding(mesh))

    rep = NamedSharding(mesh, P())
    B = 2
    tokens = np.array([5, 9], np.int32)
    positions = np.array([3, 1], np.int32)
    page_tables = np.array([[1, 2, 0, 0], [3, 0, 0, 0]], np.int32)
    active = np.array([True, True])

    step = jax.jit(
        model.decode,
        in_shardings=(
            model.param_shardings(mesh),
            model.kv_cache_sharding(mesh),
            rep, rep, rep, rep,
        ),
        out_shardings=(rep, model.kv_cache_sharding(mesh)),
    )
    logits, kv = step(params, kv, tokens, positions, page_tables, active)
    jax.block_until_ready(logits)
    assert logits.shape == (B, cfg.vocab_size)
    # fully replicated: every process can read its local copy
    local = np.asarray(logits.addressable_shards[0].data, np.float32)
    print(f"CHECKSUM {float(np.sum(local)):.6f} ARGMAX {np.argmax(local, -1).tolist()}", flush=True)


if __name__ == "__main__":
    sys.exit(main())
