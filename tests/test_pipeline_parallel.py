"""Pipeline parallelism: GPipe stage rotation parity + engine e2e (pp mesh on
the virtual CPU devices)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from dynamo_tpu.models.llama import LlamaConfig, LlamaModel
from dynamo_tpu.parallel.pipeline import (
    decode_pipelined,
    prefill_pipelined,
    stage_kv_sharding,
    stage_param_shardings,
)

# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow

NUM_PAGES, PAGE_SIZE = 16, 4


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(num_layers=4)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.key(0))
    return cfg, model, params


@pytest.mark.parametrize("pp,microbatches", [(2, 2), (4, 4), (4, 2)])
def test_prefill_and_decode_parity(setup, pp, microbatches):
    cfg, model, params = setup
    mesh = Mesh(np.array(jax.devices()[:pp]), ("pp",))
    params_pp = jax.device_put(params, stage_param_shardings(model, mesh))
    kv_pp = jax.device_put(
        model.init_kv_cache(NUM_PAGES, PAGE_SIZE), stage_kv_sharding(mesh, folded=cfg.kv_folded)
    )

    T = 16
    prompt = np.array([5, 9, 2, 77, 31, 8, 100, 3, 44, 12, 7, 60, 2, 9, 1, 30], np.int32)
    pt = np.array([3, 5, 7, 9, 11, 0, 0, 0], np.int32)
    pos = np.arange(T, dtype=np.int32)
    valid = np.ones(T, bool)

    ref_logits, ref_kv = model.prefill(
        params, model.init_kv_cache(NUM_PAGES, PAGE_SIZE),
        jnp.asarray(prompt), jnp.asarray(pos), jnp.asarray(pt),
        jnp.asarray(valid), jnp.asarray(T - 1),
    )
    pp_logits, kv_pp = jax.jit(
        lambda p, kv: prefill_pipelined(
            model, p, kv, jnp.asarray(prompt), jnp.asarray(pos), jnp.asarray(pt),
            jnp.asarray(valid), jnp.asarray(T - 1), mesh,
            num_microbatches=microbatches,
        ),
        donate_argnums=(1,),
    )(params_pp, kv_pp)
    np.testing.assert_allclose(
        np.asarray(pp_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )

    B = 4
    toks = np.zeros(B, np.int32)
    toks[0] = 42
    dpos = np.zeros(B, np.int32)
    dpos[0] = T
    pts = np.zeros((B, 8), np.int32)
    pts[0] = pt
    act = np.zeros(B, bool)
    act[0] = True
    ref_dlog, _ = model.decode(
        params, ref_kv, jnp.asarray(toks), jnp.asarray(dpos), jnp.asarray(pts), jnp.asarray(act)
    )
    pp_dlog, _ = jax.jit(
        lambda p, kv: decode_pipelined(
            model, p, kv, jnp.asarray(toks), jnp.asarray(dpos), jnp.asarray(pts),
            jnp.asarray(act), mesh, num_microbatches=microbatches,
        ),
        donate_argnums=(1,),
    )(params_pp, kv_pp)
    np.testing.assert_allclose(
        np.asarray(pp_dlog)[0], np.asarray(ref_dlog)[0], rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("pp,tp,microbatches", [(2, 2, 2), (4, 2, 4)])
def test_prefill_and_decode_parity_composed_tp_pp(setup, pp, tp, microbatches):
    """Composed (pp, tp) mesh: stage sharding on the layer axis x Megatron
    head sharding with in-layer psums must match the single-device model."""
    cfg, model, params = setup
    if pp * tp > len(jax.devices()):
        pytest.skip("not enough virtual devices")
    mesh = Mesh(np.array(jax.devices()[: pp * tp]).reshape(pp, tp), ("pp", "tp"))
    params_pp = jax.device_put(params, stage_param_shardings(model, mesh))
    kv_pp = jax.device_put(
        model.init_kv_cache(NUM_PAGES, PAGE_SIZE),
        stage_kv_sharding(mesh, folded=cfg.kv_folded),
    )

    T = 16
    prompt = np.array([5, 9, 2, 77, 31, 8, 100, 3, 44, 12, 7, 60, 2, 9, 1, 30], np.int32)
    pt = np.array([3, 5, 7, 9, 11, 0, 0, 0], np.int32)
    pos = np.arange(T, dtype=np.int32)
    valid = np.ones(T, bool)

    ref_logits, ref_kv = model.prefill(
        params, model.init_kv_cache(NUM_PAGES, PAGE_SIZE),
        jnp.asarray(prompt), jnp.asarray(pos), jnp.asarray(pt),
        jnp.asarray(valid), jnp.asarray(T - 1),
    )
    pp_logits, kv_pp = jax.jit(
        lambda p, kv: prefill_pipelined(
            model, p, kv, jnp.asarray(prompt), jnp.asarray(pos), jnp.asarray(pt),
            jnp.asarray(valid), jnp.asarray(T - 1), mesh,
            num_microbatches=microbatches,
        ),
        donate_argnums=(1,),
    )(params_pp, kv_pp)
    np.testing.assert_allclose(
        np.asarray(pp_logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )

    B = 4
    toks = np.zeros(B, np.int32)
    toks[0] = 42
    dpos = np.zeros(B, np.int32)
    dpos[0] = T
    pts = np.zeros((B, 8), np.int32)
    pts[0] = pt
    act = np.zeros(B, bool)
    act[0] = True
    ref_dlog, _ = model.decode(
        params, ref_kv, jnp.asarray(toks), jnp.asarray(dpos), jnp.asarray(pts), jnp.asarray(act)
    )
    pp_dlog, _ = jax.jit(
        lambda p, kv: decode_pipelined(
            model, p, kv, jnp.asarray(toks), jnp.asarray(dpos), jnp.asarray(pts),
            jnp.asarray(act), mesh, num_microbatches=microbatches,
        ),
        donate_argnums=(1,),
    )(params_pp, kv_pp)
    np.testing.assert_allclose(
        np.asarray(pp_dlog)[0], np.asarray(ref_dlog)[0], rtol=2e-4, atol=2e-4
    )


# ---------------- engine e2e: pp=2 tokens match pp=1 ----------------


def _engine_config(pp, tp=1):
    from dynamo_tpu.engine.config import EngineConfig

    return EngineConfig(
        model_id="tiny",
        page_size=4,
        num_pages=64,
        max_seqs=4,
        max_model_len=64,
        prefill_buckets=(8, 16, 32),
        pp=pp,
        tp=tp,
    )


async def _greedy(engine, rid, prompt, n):
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    req = EngineRequest(
        request_id=rid,
        token_ids=list(prompt),
        sampling=SamplingParams(temperature=0.0, max_tokens=n, ignore_eos=True),
    )
    toks = []
    async for out in engine.generate(req):
        if out.token is not None:
            toks.append(out.token)
    return toks


def test_engine_pp_matches_single_device():
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    prompts = [
        [5, 9, 2, 77, 31, 8, 100],
        [44, 12, 7, 60, 2, 9, 1, 30, 17, 3],
    ]

    async def run(pp):
        engine = AsyncJaxEngine(_engine_config(pp))
        await engine.start()
        outs = [await _greedy(engine, f"r{i}", p, 8) for i, p in enumerate(prompts)]
        await engine.shutdown()
        return outs

    loop = asyncio.new_event_loop()
    try:
        ref = loop.run_until_complete(run(pp=1))
        got = loop.run_until_complete(run(pp=2))
    finally:
        loop.close()
    assert got == ref


def test_engine_composed_pp_tp_matches_single_device():
    """Full engine e2e on a composed pp=2 x tp=2 mesh: greedy tokens must
    match the single-device engine exactly."""
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    prompts = [
        [5, 9, 2, 77, 31, 8, 100],
        [44, 12, 7, 60, 2, 9, 1, 30, 17, 3],
    ]

    async def run(pp, tp):
        engine = AsyncJaxEngine(_engine_config(pp, tp))
        await engine.start()
        try:
            return [await _greedy(engine, f"r{i}", p, 8) for i, p in enumerate(prompts)]
        finally:
            await engine.shutdown()

    loop = asyncio.new_event_loop()
    try:
        ref = loop.run_until_complete(run(pp=1, tp=1))
        got = loop.run_until_complete(run(pp=2, tp=2))
    finally:
        loop.close()
    assert got == ref


def test_pp_config_validation():
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.model_runner import ModelRunner
    from dynamo_tpu.models.registry import load_model

    model, params = load_model("tiny")  # 2 layers
    with pytest.raises(ValueError, match="not divisible by pp"):
        ModelRunner(
            EngineConfig(model_id="tiny", pp=3, prefill_buckets=(9,), max_seqs=3),
            model, params,
        )
    with pytest.raises(ValueError, match="prefill bucket"):
        ModelRunner(
            EngineConfig(model_id="tiny", pp=2, prefill_buckets=(9,), max_seqs=2),
            model, params,
        )
