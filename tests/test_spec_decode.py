"""Speculative decoding subsystem: n-gram proposers, device-side acceptance
(greedy + distribution-exact rejection sampling), scheduler spec rounds, and
the multi-token stream path.

Fast units (proposer, parse, acceptance math, stop-string chunks, offload
load_many logic) run in the default tier; compile-heavy engine e2e parity
tests are marked slow like the rest of the engine suite.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.sampling import SamplingParams, accept_speculative, fold_seed
from dynamo_tpu.spec import NgramProposer, SpecConfig, make_proposer, parse_speculative


# ---------------- config parsing ----------------


def test_parse_speculative():
    assert parse_speculative(None) is None
    assert parse_speculative("") is None
    assert parse_speculative("off") is None
    cfg = parse_speculative("ngram:4")
    assert cfg == SpecConfig(kind="ngram", k=4)
    assert parse_speculative("ngram").k == 4
    assert parse_speculative("ngram:2").k == 2
    with pytest.raises(ValueError):
        parse_speculative("draft:4")  # a bare numeric segment is k, not a model
    with pytest.raises(ValueError):
        parse_speculative("ngram:0")
    with pytest.raises(ValueError):
        parse_speculative("ngram:99")


def test_parse_speculative_draft():
    cfg = parse_speculative("draft:tiny:3")
    assert (cfg.kind, cfg.model, cfg.k) == ("draft", "tiny", 3)
    assert parse_speculative("draft:tiny").k == 4  # default k
    # model ids may themselves contain colons (tiny-override JSON, abs
    # paths): only a purely-numeric LAST segment is k
    js = 'tiny:{"num_layers": 2, "hidden_size": 64}'
    cfg = parse_speculative(f"draft:{js}:2")
    assert (cfg.model, cfg.k) == (js, 2)
    assert parse_speculative(f"draft:{js}").model == js
    cfg = parse_speculative("draft:/ckpt/dir:8")
    assert (cfg.model, cfg.k) == ("/ckpt/dir", 8)
    with pytest.raises(ValueError):
        parse_speculative("draft")  # model id is mandatory
    with pytest.raises(ValueError):
        parse_speculative("draft:tiny:0")


def test_engine_config_validates_speculative():
    from dynamo_tpu.engine.config import EngineConfig

    cfg = EngineConfig(speculative="ngram:3")
    assert cfg.spec.k == 3
    assert EngineConfig().spec is None
    with pytest.raises(ValueError):
        EngineConfig(speculative="bogus:1")


# ---------------- n-gram proposer ----------------


def test_ngram_proposer_prompt_lookup():
    p = NgramProposer(max_ngram=3, min_ngram=1)
    # history repeats "1 2 3 4"; suffix [2, 3, 4]... last token 4 -> suffix
    # n-grams end in 4; earlier occurrence continues with 1, 2, ...
    hist = [1, 2, 3, 4, 1, 2, 3, 4, 1, 2, 3, 4]
    assert p.propose(hist, 3) == [1, 2, 3]
    # longest suffix wins over shorter matches
    hist2 = [9, 5, 6, 7, 1, 5, 6, 7]
    assert p.propose(hist2, 2) == [1, 5]  # trigram [5,6,7] matched at pos 1
    # no match at any n: nothing proposed
    assert p.propose([1, 2, 3, 4, 5], 4) == []
    # k caps the continuation
    assert p.propose(hist, 1) == [1]
    # short histories never crash
    assert p.propose([], 4) == []
    assert p.propose([1], 4) == []


def test_ngram_proposer_most_recent_match_wins():
    p = NgramProposer(max_ngram=2, min_ngram=1)
    # bigram [1, 2] occurs twice with different continuations; the LATER
    # occurrence (recency) supplies the draft
    hist = [1, 2, 7, 7, 1, 2, 9, 1, 2]
    assert p.propose(hist, 1) == [9]


def test_make_proposer_dispatch():
    assert isinstance(make_proposer(SpecConfig(kind="ngram")), NgramProposer)
    # draft proposals are a batched device dispatch (ModelRunner.dispatch_
    # draft), not a host-side Proposer — the scheduler gets None here
    assert make_proposer(SpecConfig(kind="draft", model="tiny")) is None
    with pytest.raises(ValueError):
        make_proposer(SpecConfig(kind="eagle"))


# ---------------- fold_seed regression (satellite) ----------------


def test_fold_seed_zero_is_a_real_seed():
    # seed=0 used to fall through `if not seed` and decay to the unseeded
    # engine stream; it must map to a nonzero deterministic device seed
    assert fold_seed(None) == 0
    assert fold_seed(0) != 0
    assert fold_seed(0) == fold_seed(0)
    assert fold_seed(0) != fold_seed(1)
    # folding stays total over weird inputs
    assert fold_seed(-1) != 0
    assert fold_seed(2**63) != 0


# ---------------- acceptance math ----------------


def _one_hot_logits(rows, V, hi=9.0, lo=-9.0):
    """[len(rows), V] logits with rows[i] dominant."""
    out = np.full((len(rows), V), lo, np.float32)
    for i, t in enumerate(rows):
        out[i, t] = hi
    return out


def _accept(logits, drafts, n_drafts, temps, key=0, seeds=None, positions=None,
            top_k=None, top_p=None, min_p=None, draft_probs=None):
    B = logits.shape[0]
    out, n_emit = accept_speculative(
        jnp.asarray(logits), jnp.asarray(drafts, jnp.int32),
        jnp.asarray(n_drafts, jnp.int32), jax.random.key(key),
        jnp.asarray(temps, jnp.float32),
        jnp.asarray(top_k if top_k is not None else np.zeros(B), jnp.int32),
        jnp.asarray(top_p if top_p is not None else np.ones(B), jnp.float32),
        min_p=jnp.asarray(min_p if min_p is not None else np.zeros(B), jnp.float32),
        seeds=jnp.asarray(seeds if seeds is not None else np.zeros(B), jnp.int32),
        positions=jnp.asarray(positions if positions is not None else np.zeros(B), jnp.int32),
        draft_probs=(
            jnp.asarray(draft_probs, jnp.float32) if draft_probs is not None else None
        ),
    )
    return np.asarray(out), np.asarray(n_emit)


def test_accept_greedy_prefix_rule():
    V = 16
    # target argmax chain per row: 3, 4, 5, 6 (row i predicts draft d_{i+1})
    logits = np.stack([_one_hot_logits([3, 4, 5, 6], V)] * 4)  # [4, 4, V]
    drafts = np.array(
        [[3, 4, 5], [3, 4, 0], [0, 4, 5], [3, 4, 5]], np.int32
    )
    n_drafts = np.array([3, 3, 3, 0], np.int32)
    out, n_emit = _accept(logits, drafts, n_drafts, temps=np.zeros(4))
    # row 0: all drafts match argmaxes -> 3 accepted + bonus
    # row 1: first two match -> 2 accepted + correction
    # row 2: first draft wrong -> correction only
    # row 3: no drafts -> plain one-token decode
    assert n_emit.tolist() == [4, 3, 1, 1]
    assert out[0, :4].tolist() == [3, 4, 5, 6]
    assert out[1, :3].tolist() == [3, 4, 5]
    assert out[2, :1].tolist() == [3]
    assert out[3, :1].tolist() == [3]


def test_accept_rejection_sampling_distribution_exact():
    """The emitted first token's marginal must equal the target distribution
    regardless of the (degenerate) proposal — the Leviathan et al. guarantee
    the engine's quality claim rests on."""
    V = 8
    B = 4000
    row = np.array([2.0, 1.0, 0.5, 0.0, -0.5, -1.0, -1.5, -2.0], np.float32)
    target = np.exp(row) / np.exp(row).sum()
    logits = np.tile(row, (B, 2, 1))  # K=1: one draft row + bonus row
    drafts = np.full((B, 1), 1, np.int32)  # always propose token 1 (p ~ 0.25)
    n_drafts = np.ones(B, np.int32)
    out, n_emit = _accept(logits, drafts, n_drafts, temps=np.ones(B))
    freq = np.bincount(out[:, 0], minlength=V) / B
    # 4-sigma binomial tolerance at B=4000 is ~0.03 on the largest p
    np.testing.assert_allclose(freq, target, atol=0.04)
    assert 1 <= n_emit.min() and n_emit.max() <= 2


def test_accept_rejection_sampling_respects_top_k():
    V = 8
    B = 4000
    row = np.array([2.0, 1.5, 1.0, 0.5, 0.0, -0.5, -1.0, -1.5], np.float32)
    logits = np.tile(row, (B, 2, 1))
    drafts = np.full((B, 1), 5, np.int32)  # outside top-2: p(d) = 0, always rejected
    out, _ = _accept(
        logits, drafts, np.ones(B, np.int32), temps=np.ones(B),
        top_k=np.full(B, 2, np.int32),
    )
    masked = np.full(V, -np.inf)
    masked[:2] = row[:2]
    target = np.exp(masked - masked.max())
    target /= target.sum()
    freq = np.bincount(out[:, 0], minlength=V) / B
    assert set(np.unique(out[:, 0])) <= {0, 1}
    np.testing.assert_allclose(freq, target, atol=0.04)


def test_accept_seeded_streams_deterministic():
    """Seeded slots must ignore the engine key entirely: identical (seed,
    position) inputs under different engine keys give identical outputs —
    and the seeded marginal still matches the target distribution."""
    V = 8
    B = 2000
    row = np.linspace(1.5, -1.5, V).astype(np.float32)
    target = np.exp(row) / np.exp(row).sum()
    logits = np.tile(row, (B, 2, 1))
    drafts = np.full((B, 1), 0, np.int32)
    seeds = np.arange(1, B + 1, dtype=np.int32)
    positions = np.arange(B, dtype=np.int32) % 97
    a_out, a_n = _accept(logits, drafts, np.ones(B, np.int32),
                         temps=np.ones(B), key=1, seeds=seeds, positions=positions)
    b_out, b_n = _accept(logits, drafts, np.ones(B, np.int32),
                         temps=np.ones(B), key=2, seeds=seeds, positions=positions)
    np.testing.assert_array_equal(a_out, b_out)
    np.testing.assert_array_equal(a_n, b_n)
    freq = np.bincount(a_out[:, 0], minlength=V) / B
    np.testing.assert_allclose(freq, target, atol=0.05)


# ---------------- real-draft-prob acceptance (draft-model tentpole) --------


#: chi-square critical value at alpha = 0.001 for df = 7 (V=8 bins - 1);
#: a seeded run sits far below it when the marginal is the target p
_CHI2_CRIT_DF7_P001 = 24.322


def test_accept_draft_probs_greedy_stays_argmax_prefix():
    """temperature == 0 must ignore draft_probs entirely: acceptance is the
    argmax-prefix rule, token-identical to the one-hot (n-gram) path."""
    V = 16
    logits = np.stack([_one_hot_logits([3, 4, 5, 6], V)] * 2)
    drafts = np.array([[3, 4, 5], [3, 0, 5]], np.int32)
    n_drafts = np.array([3, 3], np.int32)
    rng = np.random.default_rng(0)
    q = rng.random((2, 3, V)).astype(np.float32)
    q /= q.sum(-1, keepdims=True)
    out, n_emit = _accept(logits, drafts, n_drafts, temps=np.zeros(2),
                          draft_probs=q)
    ref_out, ref_n = _accept(logits, drafts, n_drafts, temps=np.zeros(2))
    np.testing.assert_array_equal(out, ref_out)
    np.testing.assert_array_equal(n_emit, ref_n)
    assert n_emit.tolist() == [4, 2]


def test_accept_draft_probs_q_equals_p_always_accepts():
    """When the draft distribution equals the target's, min(1, p/q) == 1:
    every draft sampled from q is accepted and the bonus row samples — the
    draft==target regime the greedy-parity e2e rides."""
    V = 8
    B = 512
    row = np.linspace(1.5, -1.5, V).astype(np.float32)
    p = np.exp(row) / np.exp(row).sum()
    logits = np.tile(row, (B, 2, 1))
    rng = np.random.default_rng(3)
    drafts = rng.choice(V, size=(B, 1), p=p).astype(np.int32)
    q = np.tile(p.astype(np.float32), (B, 1, 1))
    _, n_emit = _accept(logits, drafts, np.ones(B, np.int32),
                        temps=np.ones(B), draft_probs=q)
    assert n_emit.tolist() == [2] * B


def test_accept_draft_probs_distribution_exact_chi_square():
    """Satellite: the full Leviathan/Chen rule against a REAL (non-one-hot)
    draft distribution q must leave the emitted first token's marginal
    exactly the target p. Drafts are sampled from q (as the draft model
    does), acceptance divides by q, rejections resample the residual —
    chi-square over a tiny vocab, seeded end to end."""
    V = 8
    B = 4096
    row = np.array([2.0, 1.0, 0.5, 0.0, -0.5, -1.0, -1.5, -2.0], np.float32)
    p = np.exp(row) / np.exp(row).sum()
    # a deliberately mismatched draft: sharper AND shifted vs the target, so
    # both accept (p/q < 1 and > 1) and residual branches get real traffic
    q_row = np.roll(np.exp(2.0 * row), 2)
    q_row /= q_row.sum()
    rng = np.random.default_rng(11)
    drafts = rng.choice(V, size=(B, 1), p=q_row).astype(np.int32)
    logits = np.tile(row, (B, 2, 1))
    q = np.tile(q_row.astype(np.float32), (B, 1, 1))
    out, n_emit = _accept(logits, drafts, np.ones(B, np.int32),
                          temps=np.ones(B), key=5, draft_probs=q)
    counts = np.bincount(out[:, 0], minlength=V)
    chi2 = float((((counts - B * p) ** 2) / (B * p)).sum())
    assert chi2 < _CHI2_CRIT_DF7_P001, (
        f"chi2 {chi2:.1f} vs crit {_CHI2_CRIT_DF7_P001} — emitted marginal "
        f"deviates from the target distribution: {counts / B} vs {p}"
    )
    # both paths exercised: some drafts accepted, some rejected
    assert 0 < int((n_emit == 2).sum()) < B


def test_accept_draft_probs_residual_renormalizes():
    """On rejection the resample comes from max(0, p - q) renormalized: mass
    q covers is excluded, so a draft with q == p on its argmax never re-emits
    the rejected token from the residual branch."""
    V = 8
    B = 2048
    row = np.array([1.0, 1.0, -9.0, -9.0, -9.0, -9.0, -9.0, -9.0], np.float32)
    p = np.exp(row) / np.exp(row).sum()  # ~[.5, .5, ~0...]
    # q puts ALL its mass on token 0: p/q = .5 -> token-0 drafts accepted
    # half the time; the residual max(0, p - q) zeroes token 0 entirely, so
    # every rejection must emit token 1
    q_row = np.zeros(V, np.float32)
    q_row[0] = 1.0
    drafts = np.zeros((B, 1), np.int32)
    logits = np.tile(row, (B, 2, 1))
    q = np.tile(q_row, (B, 1, 1))
    out, n_emit = _accept(logits, drafts, np.ones(B, np.int32),
                          temps=np.ones(B), key=9, draft_probs=q)
    rejected = n_emit == 1
    assert rejected.any() and (~rejected).any()
    assert set(np.unique(out[rejected, 0]).tolist()) == {1}
    # accept rate ~ p(0)/q(0) = 0.5 (4-sigma band at B=2048: +-0.044)
    accept_rate = float((~rejected).mean())
    assert abs(accept_rate - 0.5) < 0.05


# ---------------- incremental n-gram index (satellite) ----------------


def test_ngram_index_matches_stateless_propose():
    """The incremental index must propose exactly what the stateless
    full-history scan proposes, at every prefix, for histories that loop,
    drift, and repeat with different continuations."""
    from dynamo_tpu.spec.proposer import NgramIndex

    rng = np.random.default_rng(42)
    hist = rng.integers(0, 6, 400).tolist()  # small vocab -> dense matches
    p = NgramProposer(max_ngram=3, min_ngram=1)
    idx = NgramIndex([], max_ngram=3, min_ngram=1)
    for i, t in enumerate(hist):
        idx.append(t)
        if i % 7 == 0:
            assert idx.propose(4) == p.propose(hist[: i + 1], 4), f"prefix {i+1}"


def test_ngram_index_seeded_matches_incremental():
    from dynamo_tpu.spec.proposer import NgramIndex

    hist = [1, 2, 3, 4, 1, 2, 3, 4, 1, 2]
    seeded = NgramIndex(hist, max_ngram=4, min_ngram=1)
    grown = NgramIndex(hist[:3], max_ngram=4, min_ngram=1)
    grown.extend(hist[3:])
    assert seeded.propose(5) == grown.propose(5) == NgramProposer().propose(hist, 5)


def test_ngram_index_propose_cost_o_new_tokens():
    """Satellite micro-benchmark: a spec round's propose cost must depend on
    the tokens ACCEPTED since the last round, not the history length. The
    index's ``work`` counter counts dict registrations + lookups — the round
    cost at 8000 tokens of history must equal the round cost at 80."""
    from dynamo_tpu.spec.proposer import NgramIndex

    def round_cost(history_len: int, new_tokens: int) -> int:
        rng = np.random.default_rng(history_len)
        idx = NgramIndex(rng.integers(0, 50, history_len).tolist(),
                         max_ngram=4, min_ngram=1)
        before = idx.work
        idx.extend(rng.integers(0, 50, new_tokens).tolist())  # accepted tokens
        idx.propose(4)
        return idx.work - before

    # the hard bound: max_ngram registrations per new token + max_ngram
    # propose lookups, INDEPENDENT of history length (the old stateless scan
    # cost ~history * max_ngram window comparisons per round)
    for hist_len in (80, 8000, 40000):
        for new in (1, 5, 10):
            cost = round_cost(hist_len, new)
            assert cost <= 4 * new + 4, (
                f"round cost {cost} at history={hist_len} new={new} exceeds "
                f"the O(new tokens) bound {4 * new + 4}"
            )
    # 100x the history, same round cost (up to the <=max_ngram propose
    # lookup variance from which n-gram length matches first)
    assert abs(round_cost(8000, 5) - round_cost(80, 5)) <= 4


# ---------------- stop strings over multi-token chunks (satellite) ----------


class _ChunkEngine:
    """Stub engine emitting pre-baked multi-token StepOutput windows (the
    shape a speculative engine produces)."""

    def __init__(self, chunks):
        self.chunks = chunks

    async def generate_batched(self, request):
        from dynamo_tpu.engine.scheduler import StepOutput

        for i, chunk in enumerate(self.chunks):
            last = i == len(self.chunks) - 1
            steps = [StepOutput(request.request_id, token=t) for t in chunk]
            if last and steps:
                steps[-1].finished = True
                steps[-1].finish_reason = "length"
            yield steps


def _run_backend(chunks, stop):
    from dynamo_tpu.llm.backend import Backend
    from dynamo_tpu.llm.protocols.common import PreprocessedRequest
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    backend = Backend(_ChunkEngine(chunks), ByteTokenizer())
    req = PreprocessedRequest(
        request_id="s1", token_ids=[65], stop_strings=stop,
        sampling=SamplingParams(max_tokens=64),
    )

    async def go():
        outs = []
        async for out in backend.generate(req):
            outs.append(out)
        return outs

    return asyncio.run(go())


def test_stop_string_completed_mid_chunk_truncates():
    # one engine window carries the whole "hello STOP world" byte stream; the
    # stop completes mid-chunk, so text must truncate before it, token_ids
    # must end AT the token completing the match, and the " world" tail must
    # never surface
    tokens = list(b"hello STOPworld")
    outs = _run_backend([tokens], stop=("STOP",))
    full = "".join(o.text for o in outs)
    assert full == "hello "
    assert outs[-1].finish_reason == "stop"
    emitted_ids = [t for o in outs for t in o.token_ids]
    assert emitted_ids == list(b"hello STOP")
    assert outs[-1].cumulative_tokens == len(b"hello STOP")


def test_stop_string_spanning_chunks_truncates():
    # stop string split across two windows: the jail must hold the partial
    # prefix from chunk 1 and complete the match in chunk 2
    outs = _run_backend([list(b"abc ST"), list(b"OP tail")], stop=("STOP",))
    assert "".join(o.text for o in outs) == "abc "
    assert outs[-1].finish_reason == "stop"
    emitted_ids = [t for o in outs for t in o.token_ids]
    assert emitted_ids == list(b"abc STOP")


def test_no_stop_emits_everything_batched():
    outs = _run_backend([list(b"abcd"), list(b"efgh")], stop=())
    assert "".join(o.text for o in outs) == "abcdefgh"
    assert outs[-1].finish_reason == "length"


def test_unfinished_stop_prefix_flushes_at_end():
    outs = _run_backend([list(b"abc ST")], stop=("STOP",))
    assert "".join(o.text for o in outs) == "abc ST"
    assert outs[-1].finish_reason == "length"


# ---------------- HostKvPool.load_many (satellite) ----------------


class _FakeRunner:
    """Records inject/extract calls; enough surface for HostKvPool."""

    class _Model:
        wire_n_axis = 2

    def __init__(self):
        self.model = self._Model()
        self.injected = []  # (ids, data) pairs

    def extract_pages(self, ids):
        # [L, 2, n, ps, H, D]-shaped stand-in keyed by page id
        return np.full((1, 2, len(ids), 4, 1, 1), float(ids[0]), np.float32)

    def inject_pages(self, ids, data):
        self.injected.append((np.asarray(ids).copy(), np.asarray(data).copy()))

    # the REAL pow2-padding path (shared with the streamed-disagg part
    # scatter), so these tests keep proving the actual bucketing logic
    from dynamo_tpu.engine.model_runner import ModelRunner as _MR

    inject_pages_bucketed = _MR.inject_pages_bucketed
    del _MR


def _pool_with_blocks(hashes):
    from dynamo_tpu.engine.offload import HostKvPool

    runner = _FakeRunner()
    pool = HostKvPool(runner, capacity_blocks=16)
    for h in hashes:
        pool.save(h, page_id=h)
    return pool, runner


def test_load_many_pads_batch_to_power_of_two():
    pool, runner = _pool_with_blocks([101, 102, 103])
    hits = pool.load_many([(101, 7), (102, 8), (103, 9)])
    assert hits == {101, 102, 103}
    assert len(runner.injected) == 1
    ids, data = runner.injected[0]
    # 3 blocks pad to a 4-bucket; pad ids are far out of range so the donated
    # scatter drops them instead of clobbering a live page
    assert len(ids) == 4
    assert ids[:3].tolist() == [7, 8, 9]
    assert ids[3] >= np.iinfo(np.int32).max // 2
    assert data.shape[pool.runner.model.wire_n_axis] == 4
    # the pad rows ride as zeros (dropped anyway)
    assert float(np.abs(data[:, :, 3]).max()) == 0.0
    assert pool.loads == 3


def test_load_many_stops_at_first_missing_block():
    # block 102 LRU-dropped between the caller's membership check and the
    # injection (e.g. a save() evicted it while destination pages were being
    # allocated): only the contiguous leading run may count as restored
    pool, runner = _pool_with_blocks([101, 102, 103])
    pool.discard(102)
    hits = pool.load_many([(101, 7), (102, 8), (103, 9)])
    assert hits == {101}
    ids, data = runner.injected[0]
    assert ids[0] == 7 and len(ids) == 1
    assert pool.loads == 1


def test_load_many_all_missing_injects_nothing():
    pool, runner = _pool_with_blocks([101])
    pool.discard(101)
    assert pool.load_many([(101, 7)]) == set()
    assert runner.injected == []


# ---------------- engine e2e (compile-heavy -> full matrix tier) ----------


def _tiny_cfg(model_id="tiny", **over):
    from dynamo_tpu.engine.config import EngineConfig

    defaults = dict(
        model_id=model_id, page_size=4, num_pages=64, max_seqs=4,
        max_model_len=64, prefill_buckets=(8, 16, 32), tp=1,
    )
    defaults.update(over)
    return EngineConfig(**defaults)


async def _collect(engine, req):
    toks, finish = [], None
    async for out in engine.generate(req):
        if out.token is not None:
            toks.append(out.token)
        if out.finished:
            finish = out.finish_reason
    return toks, finish


def _run_engine(cfg, requests):
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.scheduler import EngineRequest

    async def go():
        eng = AsyncJaxEngine(cfg)
        await eng.start()
        try:
            results = await asyncio.gather(*[
                _collect(eng, EngineRequest(request_id=f"r{i}", **kw))
                for i, kw in enumerate(requests)
            ])
            stage = eng.scheduler.stage
            metrics_text = eng.render_stage_metrics()
        finally:
            await eng.shutdown()
        return results, stage, metrics_text

    return asyncio.run(go())


REPETITIVE = [5, 9, 2, 7, 5, 9, 2, 7, 5, 9]


@pytest.mark.slow
@pytest.mark.parametrize("model_id", ["tiny", "tiny-moe", "tiny-mla"])
def test_spec_greedy_token_identical(model_id):
    greedy = dict(token_ids=list(REPETITIVE),
                  sampling=SamplingParams(temperature=0.0, max_tokens=16))
    base_results, _, _ = _run_engine(_tiny_cfg(model_id), [greedy])
    ref = base_results[0][0]
    results, stage, text = _run_engine(
        _tiny_cfg(model_id, speculative="ngram:4"), [greedy]
    )
    got, fin = results[0]
    assert got == ref, f"{model_id}: spec {got} != base {ref}"
    assert stage.spec_rounds > 0
    assert stage.spec_accepted > 0, "repetitive workload must accept drafts"
    assert "dynamo_spec_proposed_total" in text
    assert "dynamo_spec_accepted_total" in text
    assert "dynamo_spec_accepted_per_round_bucket" in text


@pytest.mark.slow
def test_spec_concurrent_requests_isolated():
    reqs = [
        dict(token_ids=[10 + i, 11, 12, 10 + i, 11, 12, 10 + i],
             sampling=SamplingParams(temperature=0.0, max_tokens=10))
        for i in range(3)
    ]
    base_results, _, _ = _run_engine(_tiny_cfg(), reqs)
    spec_results, _, _ = _run_engine(_tiny_cfg(speculative="ngram:4"), reqs)
    for (b, _), (s, _) in zip(base_results, spec_results):
        assert b == s


@pytest.mark.slow
def test_spec_eos_mid_chunk_stops_exactly():
    greedy = dict(token_ids=list(REPETITIVE),
                  sampling=SamplingParams(temperature=0.0, max_tokens=16))
    results, _, _ = _run_engine(_tiny_cfg(), [greedy])
    ref = results[0][0]
    eos = ref[5]  # force EOS at a token the greedy chain emits mid-stream
    stop_req = dict(
        token_ids=list(REPETITIVE), eos_token_ids=(eos,),
        sampling=SamplingParams(temperature=0.0, max_tokens=16),
    )
    results, _, _ = _run_engine(_tiny_cfg(speculative="ngram:4"), [stop_req])
    got, fin = results[0]
    assert fin == "stop"
    assert got == ref[: ref.index(eos) + 1], "tokens past the EOS must be dead"


@pytest.mark.slow
def test_spec_seeded_sampling_reproducible():
    req = dict(token_ids=list(REPETITIVE),
               sampling=SamplingParams(temperature=0.9, seed=7, max_tokens=12))
    a, _, _ = _run_engine(_tiny_cfg(speculative="ngram:4"), [req])
    b, _, _ = _run_engine(_tiny_cfg(speculative="ngram:4"), [req])
    assert a[0][0] == b[0][0]
    # seed=0 is a real seed now (the fold_seed regression): also reproducible
    req0 = dict(token_ids=list(REPETITIVE),
                sampling=SamplingParams(temperature=0.9, seed=0, max_tokens=12))
    c, _, _ = _run_engine(_tiny_cfg(speculative="ngram:4"), [req0])
    d, _, _ = _run_engine(_tiny_cfg(speculative="ngram:4"), [req0])
    assert c[0][0] == d[0][0]


@pytest.mark.slow
def test_spec_ineligible_requests_ride_classic_windows():
    # penalties force the classic path; output must match the classic engine
    req = dict(token_ids=list(REPETITIVE),
               sampling=SamplingParams(temperature=0.0, max_tokens=10,
                                       presence_penalty=0.5))
    base_results, _, _ = _run_engine(_tiny_cfg(), [req])
    spec_results, stage, _ = _run_engine(_tiny_cfg(speculative="ngram:4"), [req])
    assert spec_results[0][0] == base_results[0][0]
    assert stage.spec_rounds == 0  # never speculated


@pytest.mark.slow
def test_spec_max_tokens_exact():
    req = dict(token_ids=list(REPETITIVE),
               sampling=SamplingParams(temperature=0.0, max_tokens=5))
    results, _, _ = _run_engine(_tiny_cfg(speculative="ngram:4"), [req])
    toks, fin = results[0]
    assert len(toks) == 5
    assert fin == "length"


# ---------------- draft-model speculation e2e (tentpole) ----------------

#: NON-repetitive prompt: no token pair repeats, so prompt-lookup never
#: matches and n-gram speculation degenerates to 1 token/round — the regime
#: the draft-model proposer exists for
ARBITRARY = [5, 9, 2, 7, 13, 3, 11, 17, 6, 1]


def _run_engine_snap(cfg, requests):
    """_run_engine + a resource_snapshot taken while the engine is live."""
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.scheduler import EngineRequest

    async def go():
        eng = AsyncJaxEngine(cfg)
        await eng.start()
        try:
            results = await asyncio.gather(*[
                _collect(eng, EngineRequest(request_id=f"r{i}", **kw))
                for i, kw in enumerate(requests)
            ])
            stage = eng.scheduler.stage
            metrics_text = eng.render_stage_metrics()
            snap = eng.resource_snapshot()
        finally:
            await eng.shutdown()
        return results, stage, metrics_text, snap

    return asyncio.run(go())


@pytest.mark.slow
def test_spec_draft_greedy_token_identical_nonrepetitive():
    """draft == target model: every draft argmax equals the target argmax,
    so greedy output must be token-identical to the classic engine AND
    acceptance must be full — on a prompt where n-gram proposes nothing."""
    greedy = dict(token_ids=list(ARBITRARY),
                  sampling=SamplingParams(temperature=0.0, max_tokens=16))
    base_results, _, _ = _run_engine(_tiny_cfg(), [greedy])
    ref = base_results[0][0]
    results, stage, text, snap = _run_engine_snap(
        _tiny_cfg(speculative="draft:tiny:4"), [greedy]
    )
    got, _ = results[0]
    assert got == ref, f"draft spec {got} != base {ref}"
    assert stage.spec_rounds > 0 and stage.spec_draft_calls > 0
    # draft==target accepts everything the budget allows
    assert stage.spec_accepted == stage.spec_proposed > 0
    # the draft families ride the engine exposition
    assert 'dynamo_spec_draft_seconds_total{phase="dispatch"}' in text
    assert 'dynamo_spec_acceptance_ratio{proposer="draft"}' in text
    assert "dynamo_spec_draft_pages" in text
    # acceptance criterion: draft KV pages visible in resource_snapshot();
    # all sequences finished, so the pool drained back to empty
    assert snap["spec_draft_pages_total"] > 0
    assert snap["spec_draft_pages_used"] == 0
    assert snap["spec_proposer"] == "draft"
    assert snap["spec_acceptance_rate"] == 1.0


@pytest.mark.slow
def test_spec_draft_beats_ngram_acceptance_on_arbitrary_text():
    """On non-repetitive text the n-gram proposer finds no suffix match
    (zero proposals); the draft model keeps proposing and the verify pass
    keeps accepting — the tentpole's reason to exist, pinned as a test."""
    greedy = dict(token_ids=list(ARBITRARY),
                  sampling=SamplingParams(temperature=0.0, max_tokens=12))
    _, ngram_stage, _ = _run_engine(_tiny_cfg(speculative="ngram:4"), [greedy])
    _, draft_stage, _, _ = _run_engine_snap(
        _tiny_cfg(speculative="draft:tiny:4"), [greedy]
    )
    ngram_rate = ngram_stage.spec_accepted / max(1, ngram_stage.spec_proposed)
    draft_rate = draft_stage.spec_accepted / max(1, draft_stage.spec_proposed)
    assert draft_stage.spec_proposed > ngram_stage.spec_accepted
    assert draft_rate > ngram_rate
    assert draft_rate == 1.0  # draft == target


@pytest.mark.slow
def test_spec_draft_concurrent_and_seeded_reproducible():
    # concurrent greedy requests stay isolated and classic-identical
    reqs = [
        dict(token_ids=[10 + 3 * i, 11, 25 + i, 7, 13 + 2 * i, 3, 19 + i],
             sampling=SamplingParams(temperature=0.0, max_tokens=10))
        for i in range(3)
    ]
    base_results, _, _ = _run_engine(_tiny_cfg(), reqs)
    draft_results, _, _, _ = _run_engine_snap(
        _tiny_cfg(speculative="draft:tiny:4"), reqs
    )
    for (b, _), (s, _) in zip(base_results, draft_results):
        assert b == s
    # temperature>0 + seed: the full (draft sampling + acceptance) pipeline
    # must be deterministic end to end
    req = dict(token_ids=list(ARBITRARY),
               sampling=SamplingParams(temperature=0.9, seed=7, max_tokens=12))
    a, _, _, _ = _run_engine_snap(_tiny_cfg(speculative="draft:tiny:4"), [req])
    b, _, _, _ = _run_engine_snap(_tiny_cfg(speculative="draft:tiny:4"), [req])
    assert a[0][0] == b[0][0]


@pytest.mark.slow
def test_spec_draft_eos_and_max_tokens_exact():
    greedy = dict(token_ids=list(ARBITRARY),
                  sampling=SamplingParams(temperature=0.0, max_tokens=16))
    results, _, _ = _run_engine(_tiny_cfg(), [greedy])
    ref = results[0][0]
    eos = ref[5]
    stop_req = dict(
        token_ids=list(ARBITRARY), eos_token_ids=(eos,),
        sampling=SamplingParams(temperature=0.0, max_tokens=16),
    )
    results, _, _, _ = _run_engine_snap(
        _tiny_cfg(speculative="draft:tiny:4"), [stop_req]
    )
    got, fin = results[0]
    assert fin == "stop"
    assert got == ref[: ref.index(eos) + 1]
    short = dict(token_ids=list(ARBITRARY),
                 sampling=SamplingParams(temperature=0.0, max_tokens=5))
    results, _, _, _ = _run_engine_snap(
        _tiny_cfg(speculative="draft:tiny:4"), [short]
    )
    toks, fin = results[0]
    assert len(toks) == 5 and fin == "length"


@pytest.mark.slow
def test_spec_draft_composes_with_int8_kv():
    """The draft model loads with the engine's kv_cache_dtype: int8 KV on
    BOTH caches must stay token-identical to the classic engine at the same
    dtype (greedy, draft == target)."""
    greedy = dict(token_ids=list(ARBITRARY),
                  sampling=SamplingParams(temperature=0.0, max_tokens=12))
    base_results, _, _ = _run_engine(_tiny_cfg(kv_cache_dtype="int8"), [greedy])
    results, stage, _, _ = _run_engine_snap(
        _tiny_cfg(kv_cache_dtype="int8", speculative="draft:tiny:4"), [greedy]
    )
    assert results[0][0] == base_results[0][0]
    assert stage.spec_accepted == stage.spec_proposed > 0


def test_dynotop_spec_column():
    import importlib.util
    from pathlib import Path

    spec_mod = importlib.util.spec_from_file_location(
        "dynotop", Path(__file__).resolve().parent.parent / "tools" / "dynotop.py"
    )
    dynotop = importlib.util.module_from_spec(spec_mod)
    spec_mod.loader.exec_module(dynotop)

    doc = {
        "namespace": "ns", "component": "backend", "summary": {"workers": 1},
        "workers": [{
            "worker_id": "ab", "last_seen_s": 0.1, "missed_scrapes": 0,
            "health": {"state": "ready", "heartbeat_age_s": 0.01},
            "kv_metrics": {"request_active_slots": 1, "request_total_slots": 4,
                           "kv_active_blocks": 1, "kv_total_blocks": 10},
            "resources": {"spec_proposer": "draft",
                          "spec_acceptance_rate": 0.872},
        }],
    }
    text = dynotop.render_status(doc)
    assert "SPEC" in text
    assert "draft 87%" in text
    doc["workers"][0]["resources"]["spec_proposer"] = "ngram"
    assert "ngram 87%" in dynotop.render_status(doc)
    # non-spec workers render a dash, not a crash
    doc["workers"][0]["resources"] = {}
    text = dynotop.render_status(doc)
    assert "draft" not in text
