"""Long-context serving (ISSUE 8): the page-table width ladder, depth-aware
chunked prefill, and the pressure-driven host-offload path.

Fast tests cover the config-level planners; the slow tier runs the tiny
engine end-to-end — bucket promotion mid-decode, preempt/resume across
ladder widths, int8 KV at a 16K-capable geometry under interpret-mode Pallas
kernels, and exact token parity between the ladder and the dense-table path
on a deep prompt.
"""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest


# ---------------- config-level planners (fast) ----------------


def test_table_ladder_auto_resolution():
    # short context: a single rung — the pre-ladder behavior exactly
    c = EngineConfig(model_id="tiny", page_size=16, max_model_len=1024)
    assert c.table_buckets == (64,)
    # deep context: pow2 rungs from 128 up to the dense width
    c = EngineConfig(model_id="tiny", page_size=16, max_model_len=131072)
    assert c.table_buckets == (128, 256, 512, 1024, 2048, 4096, 8192)
    assert c.table_bucket_for(1) == 128
    assert c.table_bucket_for(129) == 256
    assert c.table_bucket_for(8192) == 8192
    with pytest.raises(ValueError):
        c.table_bucket_for(8193)


def test_table_ladder_explicit_clamps_to_dense_width():
    c = EngineConfig(
        model_id="tiny", page_size=4, max_model_len=64,
        page_table_buckets=(2, 4, 8, 999),
    )
    assert c.table_buckets == (2, 4, 8, 16)  # 999 clamps; dense width last
    assert c.table_bucket_for(3) == 4


def test_chunk_len_shrinks_with_depth():
    c = EngineConfig(
        model_id="tiny", page_size=16, max_model_len=131072,
        prefill_buckets=(256, 512, 1024, 2048), prefill_flat_depth=8192,
    )
    # shallow: full-size chunks (budget = 2048 * 8192)
    assert c.chunk_len_for(0) == 2048
    assert c.chunk_len_for(4096) == 2048
    # deep: the planner halves the chunk to keep chunk * depth roughly flat
    assert c.chunk_len_for(16384) < 2048
    assert c.chunk_len_for(65536) == 256  # floor: the smallest bucket
    # monotone non-increasing in depth
    lens = [c.chunk_len_for(d) for d in range(0, 131072, 4096)]
    assert all(a >= b for a, b in zip(lens, lens[1:]))
    # disabled: always the max bucket
    c2 = EngineConfig(
        model_id="tiny", page_size=16, max_model_len=131072,
        prefill_buckets=(256, 512, 1024, 2048), prefill_flat_depth=0,
    )
    assert c2.chunk_len_for(100000) == 2048


def test_short_context_chunking_unchanged():
    """The default config must chunk exactly as before the planner landed:
    every depth inside a 2K context keeps the max bucket."""
    c = EngineConfig(model_id="tiny")
    for d in range(0, c.max_model_len, 64):
        assert c.chunk_len_for(d) == c.max_prefill_chunk


# ---------------- engine e2e (slow tier) ----------------

pytestmark_slow = pytest.mark.slow


async def _collect(eng, req):
    toks, cached = [], 0
    async for out in eng.generate(req):
        if out.token is not None:
            toks.append(out.token)
        cached = max(cached, out.cached_tokens)
    return toks, cached


def _run(cfg, reqs):
    async def body():
        eng = AsyncJaxEngine(cfg)
        await eng.start()
        try:
            outs = []
            for req in reqs:
                outs.append(await _collect(eng, req))
            return outs, eng.resource_snapshot(), eng.scheduler
        finally:
            await eng.shutdown()

    return asyncio.run(body())


def _req(rid, prompt, n, **kw):
    return EngineRequest(
        request_id=rid, token_ids=list(prompt),
        sampling=SamplingParams(temperature=0.0, max_tokens=n, **kw),
    )


def _prompt(n, seed=0, vocab=200):
    rng = np.random.default_rng(seed)
    return [int(x) for x in rng.integers(1, vocab, n)]


@pytest.mark.slow
def test_bucket_promotion_mid_decode_token_parity():
    """A sequence that outgrows its table rung mid-decode promotes to the
    next width and stays token-identical to the dense-table engine."""

    def cfg(**over):
        return EngineConfig(
            model_id="tiny", page_size=4, num_pages=64, max_seqs=4,
            max_model_len=64, prefill_buckets=(8, 16, 32), **over,
        )

    reqs = [_req("r1", _prompt(20), 24, ignore_eos=True)]
    (ladder_out,), snap, sched = _run(
        cfg(page_table_buckets=(2, 4, 8)), reqs
    )
    (dense_out,), _, _ = _run(cfg(), reqs)
    assert ladder_out[0] == dense_out[0], "ladder broke token parity"
    assert snap["context_table_promotions"] >= 1
    # both the narrow and the promoted width dispatched
    widths = {int(w) for w in snap["context_table_dispatches"]}
    assert len(widths) >= 2, snap["context_table_dispatches"]


@pytest.mark.slow
def test_preempt_resume_across_bucket_widths():
    """Page pressure preempts the youngest sequence while tables sit at
    different ladder rungs; the resumed request (prompt grown by its own
    output, possibly a wider rung) must still finish with the right token
    count and exact greedy parity vs an uncontended engine."""

    def cfg(pages, **over):
        return EngineConfig(
            model_id="tiny", page_size=4, num_pages=pages, max_seqs=2,
            max_model_len=96, prefill_buckets=(8, 16, 32), watermark=0.0,
            page_table_buckets=(2, 4, 8), decode_steps=2, pipeline_depth=1,
            **over,
        )

    reqs = [
        _req("a", _prompt(24, seed=1), 20, ignore_eos=True),
        _req("b", _prompt(24, seed=2), 20, ignore_eos=True),
    ]

    async def contended():
        eng = AsyncJaxEngine(cfg(20))  # 19 usable pages: both can't fit fully
        await eng.start()
        try:
            outs = await asyncio.gather(
                *[_collect(eng, r) for r in reqs]
            )
            return outs, eng.scheduler.preempt_count
        finally:
            await eng.shutdown()

    outs, preempts = asyncio.run(contended())
    assert preempts >= 1, "the contended run never preempted"
    for r, (toks, _) in zip(reqs, outs):
        (ref, _), = _run(cfg(64), [r])[0]
        assert toks == ref, f"{r.request_id}: {toks} != {ref}"


@pytest.mark.slow
def test_int8_kv_at_16k_geometry_interpret(monkeypatch):
    """A 16K-capable engine (max_model_len=16384 -> 1024-page dense width,
    4-rung auto ladder) with kv_cache_dtype=int8 serving a deep prompt
    through the interpret-mode Pallas kernels: exact token parity between
    the ladder and the dense-table path."""
    monkeypatch.setenv("DYNTPU_PALLAS", "1")

    def cfg(**over):
        return EngineConfig(
            model_id="tiny", page_size=16, num_pages=192, max_seqs=2,
            max_model_len=16384, prefill_buckets=(256, 512),
            kv_cache_dtype="int8", decode_steps=4, pipeline_depth=2, **over,
        )

    assert cfg().table_buckets == (128, 256, 512, 1024)
    reqs = [_req("deep", _prompt(2100, seed=9), 8, ignore_eos=True)]
    (ladder_out,), snap, _ = _run(cfg(), reqs)
    (dense_out,), _, _ = _run(cfg(page_table_buckets=(1024,)), reqs)
    assert len(ladder_out[0]) == 8
    assert ladder_out[0] == dense_out[0], "int8 ladder broke parity at depth"
    assert snap["kv_cache_dtype"] == "int8"
    # a 2100-token prompt needs 132 pages -> the 256 rung, not the dense 1024
    assert "256" in snap["context_table_dispatches"]
    assert "1024" not in snap["context_table_dispatches"]


@pytest.mark.slow
def test_deep_prompt_ladder_vs_dense_exact_parity():
    """The acceptance-criteria parity: a deep prompt (multiple chunks, table
    above the first rung) generates byte-identical greedy tokens on the
    ladder and on a dense single-width table, and the depth-aware chunk
    planner's chunks reassemble the full prompt."""

    def cfg(**over):
        return EngineConfig(
            model_id="tiny", page_size=4, num_pages=192, max_seqs=2,
            max_model_len=640, prefill_buckets=(8, 16, 32, 64),
            prefill_flat_depth=128, **over,
        )

    prompt = _prompt(500, seed=3)
    reqs = [_req("deep", prompt, 16, ignore_eos=True)]
    (ladder_out,), snap, _ = _run(
        cfg(page_table_buckets=(16, 32, 64, 128)), reqs
    )
    (dense_out,), _, _ = _run(cfg(), reqs)
    assert ladder_out[0] == dense_out[0]
    # flat_depth=128 with a 500-token prompt: the planner must have shrunk
    # chunks at depth (multiple buckets dispatched, not just the max)
    lens = {int(b) for b in snap["context_chunk_dispatches"]}
    assert len(lens) >= 2, snap["context_chunk_dispatches"]
    assert min(lens) < 64


@pytest.mark.slow
def test_pressure_drain_offloads_cold_blocks_to_host():
    """Crossing the occupancy watermark drains cold refcount-0 blocks to the
    host tier in batches (offload_pressure_blocks climbs), and a revisit of
    the drained prefix restores from host — cached tokens, no recompute."""

    def cfg():
        return EngineConfig(
            model_id="tiny", page_size=4, num_pages=40, max_seqs=2,
            max_model_len=96, prefill_buckets=(8, 16, 32),
            host_cache_blocks=64, offload_watermark=0.3,
            offload_drain_batch=4, watermark=0.0,
        )

    async def body():
        eng = AsyncJaxEngine(cfg())
        await eng.start()
        try:
            p1 = _prompt(32, seed=5)
            t1, _ = await _collect(eng, _req("a", p1, 4))
            # fill more of the pool so occupancy crosses the 0.3 watermark
            # while a's blocks sit cold in the reusable pool
            t2, _ = await _collect(eng, _req("b", _prompt(32, seed=6), 4))
            t3, _ = await _collect(eng, _req("c", _prompt(32, seed=7), 4))
            snap = eng.resource_snapshot()
            assert snap["offload_pressure_blocks"] >= 1, snap
            assert snap["offload_saves"] >= 1
            # revisit the first prompt: its drained blocks restore from the
            # host tier as cached prefix (no recompute of those tokens)
            t1b, cached = await _collect(eng, _req("a2", p1, 4))
            assert t1b == t1
            assert cached > 0
            assert eng.resource_snapshot()["offload_loads"] >= 1
            return True
        finally:
            await eng.shutdown()

    assert asyncio.run(body())
