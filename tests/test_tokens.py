"""Block hashing tests (mirrors reference in-module tests, lib/llm/src/tokens.rs bottom)."""

import struct

import xxhash

from dynamo_tpu.llm.tokens import (
    TokenSequence,
    chain_hash,
    compute_block_hash,
    compute_block_hash_for_seq,
    compute_hash,
)


def test_hash_is_xxh3_seeded():
    data = b"hello world"
    assert compute_hash(data) == xxhash.xxh3_64_intdigest(data, seed=1337)


def test_block_hash_le_u32_bytes():
    tokens = [1, 2, 3, 4]
    assert compute_block_hash(tokens) == compute_hash(struct.pack("<4I", 1, 2, 3, 4))


def test_seq_hashes_unchained_complete_chunks_only():
    tokens = list(range(10))
    hashes = compute_block_hash_for_seq(tokens, 4)
    assert len(hashes) == 2  # trailing partial chunk of 2 ignored
    assert hashes[0] == compute_block_hash([0, 1, 2, 3])
    assert hashes[1] == compute_block_hash([4, 5, 6, 7])


def test_token_sequence_chaining():
    seq = TokenSequence(list(range(8)), block_size=4)
    assert len(seq.blocks) == 2
    b0, b1 = seq.blocks
    # First block: sequence hash == block hash.
    assert b0.sequence_hash == b0.block_hash
    assert b0.parent_sequence_hash is None
    # Second block chains: hash([parent_u64, block_u64]).
    assert b1.parent_sequence_hash == b0.sequence_hash
    assert b1.sequence_hash == chain_hash(b0.sequence_hash, b1.block_hash)


def test_incremental_matches_bulk():
    tokens = list(range(23))
    bulk = TokenSequence(tokens, block_size=4)
    inc = TokenSequence(block_size=4)
    for t in tokens:
        inc.push_token(t)
    assert [b.sequence_hash for b in bulk.blocks] == [b.sequence_hash for b in inc.blocks]
    assert bulk.current.tokens == inc.current.tokens == list(range(20, 23))
    assert bulk.tokens == tokens


def test_same_prefix_same_hashes():
    a = TokenSequence([5, 6, 7, 8, 9, 10, 11, 12], block_size=4)
    b = TokenSequence([5, 6, 7, 8, 100, 200, 300, 400], block_size=4)
    assert a.blocks[0].sequence_hash == b.blocks[0].sequence_hash
    assert a.blocks[1].sequence_hash != b.blocks[1].sequence_hash
