"""`dynamo-tpu build` artifact packaging + `deploy` CLI against a live API
server (reference: dynamo build/deploy against the cloud api-server)."""

import asyncio
import json
import threading

import yaml

from dynamo_tpu.deploy.api_server import DeployApiServer
from dynamo_tpu.deploy.crd import DeploymentSpec
from dynamo_tpu.sdk.build import build_artifact
from dynamo_tpu.sdk.deploy import DeployClient, load_spec


def test_build_artifact_from_example_graph(tmp_path):
    out = build_artifact(
        "examples.graphs.agg:Frontend",
        str(tmp_path / "art"),
        config_file="examples/configs/agg.yaml",
        name="agg-demo",
    )
    manifest = json.loads((out / "manifest.json").read_text())
    assert manifest["deployment"] == "agg-demo"
    classes = {s["class"].rsplit(":", 1)[1] for s in manifest["services"]}
    assert {"Frontend", "Processor", "TpuWorker"} <= classes

    spec = DeploymentSpec.from_yaml(str(out / "deployment.yaml"))
    assert spec.name == "agg-demo"
    by_name = {s.name: s for s in spec.services}
    assert by_name["tpuworker"].tpu_chips == 1  # resources={"tpu": 1} on the graph
    assert by_name["tpuworker"].command[-1].endswith(":TpuWorker")
    assert (out / "config.yaml").exists()


def test_build_config_overrides_workers(tmp_path):
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(yaml.safe_dump({"TpuWorker": {"workers": 3, "resources": {"tpu": 0}}}))
    out = build_artifact(
        "examples.graphs.agg:Frontend", str(tmp_path / "art"), config_file=str(cfg)
    )
    spec = DeploymentSpec.from_yaml(str(out / "deployment.yaml"))
    worker = next(s for s in spec.services if s.name == "tpuworker")
    assert worker.replicas == 3 and worker.tpu_chips == 0


def test_deploy_cli_roundtrip(tmp_path):
    """build -> create -> get/revisions -> update -> rollback -> delete against
    a live in-process API server."""
    art = build_artifact(
        "examples.graphs.agg:Frontend", str(tmp_path / "art"), name="roundtrip"
    )

    loop = asyncio.new_event_loop()
    server = DeployApiServer()
    port = loop.run_until_complete(server.start())
    runner = threading.Thread(target=loop.run_forever, daemon=True)
    runner.start()
    try:
        client = DeployClient(f"http://127.0.0.1:{port}")
        spec = load_spec(str(art))
        created = client.create(spec)
        assert created["name"] == "roundtrip"

        got = client.get("roundtrip")
        assert {s["name"] for s in got["spec"]["services"]} >= {"frontend", "tpuworker"}

        spec2 = dict(spec)
        spec2["services"] = [
            dict(s, replicas=2) if s["name"] == "tpuworker" else s
            for s in spec["services"]
        ]
        client.update("roundtrip", spec2)
        revs = client.revisions("roundtrip")
        assert len(revs) == 2

        client.rollback("roundtrip", 1)
        got = client.get("roundtrip")
        worker = next(s for s in got["spec"]["services"] if s["name"] == "tpuworker")
        assert worker["replicas"] == 1

        manifests = client.manifests("roundtrip")
        kinds = {m["kind"] for m in manifests["manifests"]}
        assert "Deployment" in kinds

        client.delete("roundtrip")
        assert client.list() == [] or "roundtrip" not in client.list()
    finally:
        loop.call_soon_threadsafe(loop.stop)
        runner.join(timeout=5)


def test_cli_dispatch(tmp_path, capsys):
    from dynamo_tpu.launch.run import main

    rc = main([
        "build", "examples.graphs.agg:Frontend", "-o", str(tmp_path / "a"),
        "--name", "cli-built",
    ])
    assert rc == 0
    assert (tmp_path / "a" / "deployment.yaml").exists()
