"""Full aggregated serving graph over the runtime: HTTP frontend (model
discovery) -> processor (KV-aware routing) -> worker (JAX engine), each on its
own DistributedRuntime, crossing the broker + TCP planes.

The distributed analogue of the reference's `dynamo serve graphs.agg:Frontend`
(reference: examples/llm/graphs/agg.py, SURVEY.md §3.2)."""

import asyncio
import json

import aiohttp
import pytest

from dynamo_tpu.cplane.broker import Broker
from dynamo_tpu.components.frontend import FrontendService
from dynamo_tpu.components.processor import ProcessorService
from dynamo_tpu.components.worker import WorkerService
from dynamo_tpu.frontends.pipeline import card_for_model
from dynamo_tpu.llm.model_registry import ModelEntry, register_model
from dynamo_tpu.runtime.distributed import DistributedRuntime

from tests.test_engine import tiny_engine_config

NS = "g"


@pytest.fixture(scope="module")
def graph():
    loop = asyncio.new_event_loop()

    async def boot():
        broker = Broker()
        bport = await broker.start()
        addr = f"127.0.0.1:{bport}"

        worker_rt = DistributedRuntime(cplane_address=addr)
        await worker_rt.connect()
        proc_rt = DistributedRuntime(cplane_address=addr)
        await proc_rt.connect()
        front_rt = DistributedRuntime(cplane_address=addr)
        await front_rt.connect()

        card = card_for_model("tiny")
        worker = WorkerService(
            worker_rt, NS, "backend", card, tiny_engine_config(),
            register=False,  # processor fronts the workers; register that below
        )
        await worker.start()

        processor = ProcessorService(
            proc_rt, NS, worker_component="backend", kv_block_size=4, routing="kv"
        )
        await processor.start()

        # register the model to point at the processor tier
        entry = ModelEntry(
            name="tiny",
            endpoint=f"dyn://{NS}.processor.generate",
            model_type="chat",
            card=card,
        )
        await register_model(front_rt.cplane, entry)

        frontend = FrontendService(front_rt, host="127.0.0.1", port=0)
        port = await frontend.start()

        return broker, (worker_rt, proc_rt, front_rt), (worker, processor, frontend), f"http://127.0.0.1:{port}"

    broker, rts, services, url = loop.run_until_complete(boot())
    yield loop, url, services
    worker, processor, frontend = services

    async def teardown():
        await frontend.stop()
        await processor.stop()
        await worker.stop()
        for rt in rts:
            await rt._shutdown_hook()
        await broker.stop()

    loop.run_until_complete(teardown())
    loop.close()


BODY = {
    "model": "tiny",
    "messages": [{"role": "user", "content": "distributed hello"}],
    "max_tokens": 6,
    "temperature": 0,
}


def test_graph_unary(graph):
    loop, url, _ = graph

    async def go():
        async with aiohttp.ClientSession() as s:
            async with s.post(url + "/v1/chat/completions", json=BODY) as resp:
                return resp.status, await resp.json()

    status, body = loop.run_until_complete(go())
    assert status == 200
    assert body["choices"][0]["message"]["content"] != ""
    assert body["usage"]["completion_tokens"] == 6


def test_graph_stream_and_kv_routing(graph):
    loop, url, services = graph
    _, processor, _ = services

    async def stream_once():
        texts = []
        async with aiohttp.ClientSession() as s:
            async with s.post(
                url + "/v1/chat/completions", json={**BODY, "stream": True}
            ) as resp:
                assert resp.status == 200
                async for line in resp.content:
                    line = line.decode().strip()
                    if line.startswith("data:"):
                        data = line[5:].strip()
                        if data == "[DONE]":
                            break
                        chunk = json.loads(data)
                        d = chunk["choices"][0]["delta"]
                        if d.get("content"):
                            texts.append(d["content"])
        return "".join(texts)

    t1 = loop.run_until_complete(stream_once())
    t2 = loop.run_until_complete(stream_once())
    assert t1 == t2 != ""

    async def check_router():
        # the worker's kv events flowed into the processor's radix index;
        # by the second identical request the router saw prefix overlap
        await asyncio.sleep(0.2)
        return processor.router.indexer.stats()

    nodes, workers = loop.run_until_complete(check_router())
    assert nodes > 0 and workers == 1


def test_graph_model_discovery_detach(graph):
    loop, url, _ = graph

    async def go():
        async with aiohttp.ClientSession() as s:
            async with s.get(url + "/v1/models") as resp:
                return await resp.json()

    models = loop.run_until_complete(go())
    assert [m["id"] for m in models["data"]] == ["tiny"]
