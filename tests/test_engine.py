"""End-to-end engine tests on the tiny model (virtual CPU devices)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest

from tests.test_llama_model import naive_forward


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow


def tiny_engine_config(**over) -> EngineConfig:
    defaults = dict(
        model_id="tiny",
        page_size=4,
        num_pages=64,
        max_seqs=4,
        max_model_len=64,
        prefill_buckets=(8, 16, 32),
        tp=1,
    )
    defaults.update(over)
    return EngineConfig(**defaults)


def greedy_reference(engine, prompt, n):
    """Greedy continuation using the naive dense forward on engine weights."""
    cfg = engine.model.config
    params = jax.device_get(engine.runner.params)
    toks = list(prompt)
    out = []
    for _ in range(n):
        logits = naive_forward(cfg, params, toks)
        nxt = int(jnp.argmax(logits[-1]))
        toks.append(nxt)
        out.append(nxt)
    return out


async def _collect(engine, req):
    toks = []
    finish = None
    cached = 0
    async for out in engine.generate(req):
        if out.token is not None:
            toks.append(out.token)
        cached = max(cached, out.cached_tokens)
        if out.finished:
            finish = out.finish_reason
    return toks, finish, cached


@pytest.fixture(scope="module")
def engine():
    eng = AsyncJaxEngine(tiny_engine_config())

    async def boot():
        await eng.start()

    asyncio.run(boot())
    yield eng
    asyncio.run(eng.shutdown())


def test_greedy_matches_naive(engine):
    prompt = [5, 9, 2, 77, 31]
    req = EngineRequest(
        request_id="r1",
        token_ids=prompt,
        sampling=SamplingParams(temperature=0.0, max_tokens=8),
    )

    async def run():
        return await _collect(engine, req)

    toks, finish, _ = asyncio.run(run())
    assert finish == "length"
    assert toks == greedy_reference(engine, prompt, 8)


def test_concurrent_requests_isolated(engine):
    prompts = [[5, 9, 2], [100, 101, 102, 103], [7, 7, 7, 7, 7, 7]]

    async def run():
        reqs = [
            EngineRequest(
                request_id=f"c{i}",
                token_ids=p,
                sampling=SamplingParams(temperature=0.0, max_tokens=6),
            )
            for i, p in enumerate(prompts)
        ]
        return await asyncio.gather(*[_collect(engine, r) for r in reqs])

    results = asyncio.run(run())
    for (toks, finish, _), prompt in zip(results, prompts):
        assert finish == "length"
        assert toks == greedy_reference(engine, prompt, 6), f"prompt {prompt}"


def test_prefix_cache_reuse_across_requests(engine):
    prompt = [11, 12, 13, 14, 15, 16, 17, 18, 19]

    async def run(rid):
        req = EngineRequest(
            request_id=rid,
            token_ids=list(prompt),
            sampling=SamplingParams(temperature=0.0, max_tokens=4),
        )
        return await _collect(engine, req)

    toks1, _, cached1 = asyncio.run(run("p1"))
    toks2, _, cached2 = asyncio.run(run("p2"))
    assert toks1 == toks2
    assert cached1 == 0
    assert cached2 >= 4  # second run reuses cached prefix blocks
    m = engine.metrics()
    assert m.gpu_prefix_cache_hit_rate > 0


def test_eos_stops(engine):
    prompt = [5, 9, 2, 77, 31]
    first = greedy_reference(engine, prompt, 1)[0]
    req = EngineRequest(
        request_id="eos1",
        token_ids=prompt,
        sampling=SamplingParams(temperature=0.0, max_tokens=50),
        eos_token_ids=(first,),
    )

    async def run():
        return await _collect(engine, req)

    toks, finish, _ = asyncio.run(run())
    assert finish == "stop"
    assert toks == [first]


def test_max_model_len_enforced(engine):
    req = EngineRequest(
        request_id="long1",
        token_ids=list(np.random.default_rng(0).integers(1, 200, 60)),
        sampling=SamplingParams(temperature=0.0, max_tokens=50),
    )

    async def run():
        return await _collect(engine, req)

    toks, finish, _ = asyncio.run(run())
    assert finish == "length"
    assert len(toks) <= 4  # 64 max_model_len - 60 prompt


def test_oversized_prompt_errors(engine):
    req = EngineRequest(request_id="big", token_ids=list(range(100)))

    async def run():
        return await _collect(engine, req)

    toks, finish, _ = asyncio.run(run())
    assert finish == "error"
    assert toks == []


def test_multi_step_matches_single_step():
    """The fused decode window (decode_steps>1) is token-identical to
    one-step-at-a-time decode: the sampled-token feedback loop on device must
    reproduce the host loop exactly (greedy)."""
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]

    def run_with(k):
        eng = AsyncJaxEngine(tiny_engine_config(decode_steps=k))

        async def go():
            await eng.start()
            req = EngineRequest(
                request_id=f"k{k}",
                token_ids=list(prompt),
                sampling=SamplingParams(temperature=0.0, max_tokens=9),
            )
            out = await _collect(eng, req)
            await eng.shutdown()
            return out

        return asyncio.run(go())

    toks1, fin1, _ = run_with(1)
    toks3, fin3, _ = run_with(3)
    assert fin1 == fin3 == "length"
    assert toks1 == toks3
    assert len(toks1) == 9  # 9 tokens through a K=3 window: 3 full windows


def test_multi_step_window_freezes_at_max_model_len():
    """A sequence whose window crosses max_model_len freezes on device (no
    out-of-capacity KV writes) and finishes with reason=length exactly at the
    boundary."""
    eng = AsyncJaxEngine(tiny_engine_config(decode_steps=8, max_model_len=16))

    async def go():
        await eng.start()
        req = EngineRequest(
            request_id="edge",
            token_ids=[2, 4, 6, 8, 10, 12, 14, 16, 18, 20, 22],  # 11 tokens
            sampling=SamplingParams(temperature=0.0, max_tokens=50),
        )
        out = await _collect(eng, req)
        await eng.shutdown()
        return out

    toks, finish, _ = asyncio.run(go())
    assert finish == "length"
    assert len(toks) == 16 - 11  # decode to the model-length boundary, not past


def test_capacity_freeze_no_phantom_tokens():
    """Under page exhaustion with no preemption victim the window shrinks and
    the device freezes the slot; emitted tokens must still match the K=1
    schedule exactly — no phantom tokens sampled from frozen state."""

    def run_with(k):
        # 1 slot, 16 usable pages * page_size 4 = 64 token capacity but
        # max_model_len 128: the sequence exhausts physical pages mid-decode
        # with no preemption victim, forcing the shrunk-window fallback and an
        # eventual OOM finish — both schedules must agree token-for-token.
        eng = AsyncJaxEngine(
            tiny_engine_config(
                decode_steps=k, max_seqs=1, num_pages=17, max_model_len=128, watermark=0.0
            )
        )

        async def go():
            await eng.start()
            req = EngineRequest(
                request_id=f"cap{k}",
                token_ids=[9, 8, 7, 6, 5, 4],
                sampling=SamplingParams(temperature=0.0, max_tokens=1000, ignore_eos=True),
            )
            out = await _collect(eng, req)
            await eng.shutdown()
            return out

        return asyncio.run(go())

    toks1, fin1, _ = run_with(1)
    toks8, fin8, _ = run_with(8)
    assert toks8 == toks1
    assert fin8 == fin1 == "error"  # true OOM, past the shrunk-window fallback
    # 58 fed decode tokens (KV positions 6..63) + the prefill-sampled first
    # token = 59: decoded exactly to physical capacity, never past it
    assert len(toks1) == 64 - 6 + 1


def test_step_failure_fails_waiting_requests():
    """A trace/step error during admission must fail the request (not leave
    its caller waiting forever) — the request may not have reached a slot yet
    when the step dies."""
    eng = AsyncJaxEngine(tiny_engine_config())

    async def go():
        await eng.start()

        def boom(*a, **k):
            raise RuntimeError("injected step failure")

        # both prefill entrypoints: lone chunks ride the packed trace now
        eng.runner.prefill_chunk = boom
        eng.runner.prefill_chunk_batch = boom
        req = EngineRequest(
            request_id="fail0",
            token_ids=[1, 2, 3],
            sampling=SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True),
        )
        finish = None
        try:
            async for out in eng.generate(req):
                if out.finished:
                    finish = out.finish_reason
        except RuntimeError as e:
            finish = f"exc:{e}"
        finally:
            await eng.shutdown()
        return finish

    finish = asyncio.run(asyncio.wait_for(go(), timeout=60))
    assert finish == "error"


def test_packed_prefill_matches_unpacked():
    """Cross-request packed prefill (prefill_lanes > 1) must produce exactly
    the tokens of the per-request path — including multi-chunk prompts whose
    chunks interleave across packed calls, and prefix-cache hits."""

    async def run(lanes: int):
        eng = AsyncJaxEngine(tiny_engine_config(
            prefill_lanes=lanes, max_model_len=96, num_pages=96,
        ))
        await eng.start()
        rng = np.random.default_rng(42)
        # mixed lengths: some single-chunk, some spanning 2-3 chunks of the
        # 32-token max bucket
        prompts = [rng.integers(1, 200, n).tolist() for n in (7, 30, 50, 70)]
        # a shared prefix pair (prefix-cache interaction with packing)
        prompts.append(prompts[3][:40] + [5, 6, 7])
        reqs = [
            EngineRequest(
                request_id=f"p{i}", token_ids=p,
                sampling=SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True),
            )
            for i, p in enumerate(prompts)
        ]
        # feature-bearing lanes: penalties, seeded stream, min_tokens EOS
        # suppression, logprobs — the want_* packed-trace variants
        reqs.append(EngineRequest(
            request_id="pen", token_ids=prompts[0],
            sampling=SamplingParams(
                temperature=0.0, max_tokens=6, ignore_eos=True,
                presence_penalty=0.4, frequency_penalty=0.2,
            ),
        ))
        reqs.append(EngineRequest(
            request_id="seeded", token_ids=prompts[1],
            sampling=SamplingParams(temperature=0.9, max_tokens=6, seed=7,
                                    ignore_eos=True),
        ))
        reqs.append(EngineRequest(
            request_id="mintok", token_ids=prompts[2],
            sampling=SamplingParams(temperature=0.0, max_tokens=6, min_tokens=3),
            eos_token_ids=(9,),
        ))
        reqs.append(EngineRequest(
            request_id="lp", token_ids=prompts[0][:20],
            sampling=SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True),
            logprobs=2,
        ))
        outs = await asyncio.gather(*[_collect(eng, r) for r in reqs])
        await eng.shutdown()
        return [toks for toks, _, _ in outs]

    packed = asyncio.run(run(4))
    unpacked = asyncio.run(run(1))
    assert packed == unpacked


def test_sp_tp_gate_requires_head_geometry():
    """ADVICE r4: a model config without num_heads/num_kv_heads must fail the
    composed sp x tp gate AT INIT (0-defaults made `0 % tp == 0` pass and the
    failure surfaced later inside a traced shard_map)."""
    import pytest

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.model_runner import ModelRunner

    class HeadlessConfig:
        num_layers = 2

    class HeadlessModel:
        config = HeadlessConfig()

    cfg = EngineConfig(sp=2, tp=2)
    with pytest.raises(ValueError, match="num_heads"):
        ModelRunner(cfg, HeadlessModel(), params={})


def test_background_warmup_serves_while_compiling():
    """warmup="background": readiness waits only for the core traces; the
    engine serves immediately and the feature variants (logprobs/penalties)
    compile between steps — after the task drains, a feature-bearing request
    works without error (VERDICT r4 weak-5: cold first deploy)."""
    import asyncio

    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    async def body():
        eng = AsyncJaxEngine(tiny_engine_config(warmup="background"))
        await eng.start()
        try:
            assert eng._warmup_task is not None

            async def collect(rid, sampling):
                req = EngineRequest(
                    request_id=rid, token_ids=[5, 9, 2, 7], sampling=sampling,
                    logprobs=0 if sampling.presence_penalty else None,
                )
                return [o.token async for o in eng.generate(req) if o.token is not None]

            # serves immediately, before the variants finish compiling
            toks = await collect("t1", SamplingParams(temperature=0.0, max_tokens=4))
            assert len(toks) == 4
            await eng._warmup_task  # drains between steps; must not raise
            assert eng._warmup_task.done()
            # feature-bearing request rides the precompiled variants
            toks = await collect("t2", SamplingParams(
                temperature=0.0, max_tokens=4, presence_penalty=0.2,
            ))
            assert len(toks) == 4
        finally:
            await eng.shutdown()

    asyncio.run(body())
