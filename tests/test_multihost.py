"""Multi-host bootstrap exercised for real: two OS processes join one
jax.distributed CPU mesh via init_multihost (the DYNTPU_COORDINATOR /
NUM_PROCESSES / PROCESS_ID contract the helm worker template sets) and run
one sharded decode step of the actual Llama model over a global dp x tp mesh
(reference analogue: lib/llm/src/engines/vllm/ray.rs leader/follower)."""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tests", "multihost_step.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_decode_step():
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            DYNTPU_COORDINATOR=f"127.0.0.1:{port}",
            DYNTPU_NUM_PROCESSES="2",
            DYNTPU_PROCESS_ID=str(pid),
            PYTHONUNBUFFERED="1",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, SCRIPT],
                env=env,
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"process failed:\n{out[-3000:]}"
    checks = [line for out in outs for line in out.splitlines() if line.startswith("CHECKSUM")]
    assert len(checks) == 2, outs
    # both processes computed the same replicated logits
    assert checks[0] == checks[1], checks
