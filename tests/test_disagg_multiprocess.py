"""Cross-PROCESS disaggregation: prefill worker and decode worker as separate
OS processes — the topology the helm chart deploys (prefill-worker.yaml +
worker.yaml) — with the broker between them and bulk KV riding the dedicated
data-plane socket (disagg/dataplane.py), not the control-plane result message.

Correctness bar: greedy generation through the 2-process disagg path is
token-exact vs a single local engine (reference property:
docs/disagg_serving.md — non-blocking block transfer + notification).
"""

import asyncio
import json
import os
import subprocess
import sys

import pytest

from dynamo_tpu.cplane.broker import Broker
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest
from dynamo_tpu.llm.disagg_router import config_key
from dynamo_tpu.runtime.distributed import DistributedRuntime

from tests.test_engine import _collect, tiny_engine_config

pytestmark = pytest.mark.slow

NS = "mp"
ENGINE_ARGS = [
    "--page-size", "4", "--num-pages", "128", "--max-seqs", "4",
    "--max-model-len", "64",
]


def _spawn(module: str, *args: str, log_path: str) -> subprocess.Popen:
    env = dict(os.environ)
    env.setdefault("DYNTPU_LOG", "info")
    env["PYTHONUNBUFFERED"] = "1"
    # log to a file, not a PIPE: an undrained pipe blocks the child once the
    # ~64KB buffer fills, which presents as an unrelated-looking test timeout
    logf = open(log_path, "w")
    p = subprocess.Popen(
        [sys.executable, "-m", module, *args],
        env=env,
        stdout=logf,
        stderr=subprocess.STDOUT,
        text=True,
    )
    p._log_path = log_path
    return p


async def _wait_queue_consumer(cplane, queue: str, timeout: float = 90.0) -> None:
    """The prefill worker is ready once it holds a parked pull on the queue."""
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        try:
            info = await cplane.queue_info(queue)
            if info.get("waiters", 0) > 0:
                return
        except Exception:
            pass
        await asyncio.sleep(0.25)
    raise TimeoutError(f"no consumer on {queue}")


def test_two_process_disagg_token_exact_and_cancel(tmp_path):
    loop = asyncio.new_event_loop()
    procs: list[subprocess.Popen] = []

    async def body():
        broker = Broker()
        bport = await broker.start()
        addr = f"127.0.0.1:{bport}"

        drt = DistributedRuntime(cplane_address=addr)
        await drt.connect()
        # force every prompt longer than one block down the remote path
        await drt.cplane.kv_put(
            config_key("tiny"),
            json.dumps({"max_local_prefill_length": 4, "max_prefill_queue_size": 64}).encode(),
        )

        procs.append(_spawn(
            "dynamo_tpu.components.worker", "tiny", "--disagg",
            "--namespace", NS, "--component", "backend", "--cplane", addr,
            *ENGINE_ARGS, log_path=str(tmp_path / "worker.log"),
        ))
        procs.append(_spawn(
            "dynamo_tpu.components.prefill_worker", "tiny",
            "--namespace", NS, "--cplane", addr, *ENGINE_ARGS,
            log_path=str(tmp_path / "prefill.log"),
        ))

        print("STAGE: workers spawned", flush=True)
        client = await drt.endpoint_client(f"dyn://{NS}.backend.generate")
        await client.wait_for_instances(timeout=120)
        print("STAGE: instances up", flush=True)
        await _wait_queue_consumer(drt.cplane, f"{NS}.prefill_queue.tiny")
        print("STAGE: queue consumer up", flush=True)

        # ---- token-exact vs a local engine ----
        prompt = [7, 3, 9, 11, 2, 5, 8, 13, 21, 34, 6, 17, 25, 1, 4, 19]
        pre = {
            "request_id": "mp-1",
            "token_ids": prompt,
            "sampling": {"temperature": 0.0, "max_tokens": 8, "ignore_eos": True},
            "model": "tiny",
        }
        got = []
        print("STAGE: sending request", flush=True)
        async for out in await client.random(pre):
            got.extend(out.get("token_ids") or [])

        from dynamo_tpu.engine.engine import AsyncJaxEngine

        print("STAGE: got tokens", got, flush=True)
        local = AsyncJaxEngine(tiny_engine_config())
        await local.start()
        expected, _, _ = await _collect(local, EngineRequest(
            request_id="local-1", token_ids=list(prompt),
            sampling=SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True),
        ))
        await local.shutdown()
        assert got == expected, f"2-process disagg {got} != local {expected}"
        print("STAGE: token-exact ok", flush=True)

        # ---- the remote path (and the socket data plane) actually ran ----
        from dynamo_tpu.runtime.service import collect_service_stats

        stats = await collect_service_stats(drt.cplane, NS, "backend", timeout=2.0)
        disagg = next(
            (e.data.get("disagg") for e in stats.endpoints if e.data.get("disagg")), None
        )
        assert disagg is not None, "worker did not report disagg stats"
        assert disagg["remote_prefills"] >= 1, disagg
        print("STAGE: stats ok", flush=True)

        # ---- cancellation does not leak (a later request still works) ----
        pre2 = dict(pre, request_id="mp-cancel", sampling={
            "temperature": 0.0, "max_tokens": 64, "ignore_eos": True,
        })
        stream = await client.random(dict(pre2, token_ids=prompt[:12]))
        agen = stream.__aiter__()
        await agen.__anext__()  # first payload arrived; now abandon mid-stream
        await agen.aclose()
        print("STAGE: cancel ok", flush=True)

        got3 = []
        async for out in await client.random(dict(pre, request_id="mp-3")):
            got3.extend(out.get("token_ids") or [])
        assert got3 == expected
        print("STAGE: post-cancel ok", flush=True)

        # children first: broker.stop() waits on live connections
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)
        await drt._shutdown_hook()
        await broker.stop()

    try:
        loop.run_until_complete(asyncio.wait_for(body(), 300))
    except Exception:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
            try:
                with open(p._log_path) as f:
                    print(f"--- {p._log_path} ---\n{f.read()[-4000:]}")
            except Exception:
                pass
        raise
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        loop.close()
