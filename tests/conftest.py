"""Test config: force an 8-device virtual CPU mesh so multi-chip sharding paths
(tp/dp/sp) compile and execute without TPU hardware."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("DYNTPU_LOG", "warning")
