"""Test config: force an 8-device virtual CPU mesh so multi-chip sharding paths
(tp/dp/sp) compile and execute without TPU hardware.

Environment quirk (see .claude/skills/verify/SKILL.md): sitecustomize
(/root/.axon_site) imports jax at interpreter startup and registers the axon TPU
PJRT plugin, so JAX_PLATFORMS env mutations after startup are no-ops — jax read
the env already. ``jax.config.update("jax_platforms", ...)`` is the only
reliable way to pin the backend, and keeping the axon backend un-initialized
also avoids flaky hangs in the TPU relay.
"""

import os

# XLA_FLAGS is read lazily when the CPU client is created, so setting it here
# (before any jax operation) still works even though jax is already imported.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("DYNTPU_LOG", "warning")
# Subprocesses spawned by tests (sdk serve supervisor etc.) must not register
# the axon TPU plugin (hangs when the relay is down) and must run on CPU.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
