"""Weight-only int8 quantization: container semantics, model parity across
all three families, tp/pp sharding parity, loader/registry integration.

Numeric expectations are for the f32 tiny configs (random weights): per-layer
symmetric per-output-channel int8 carries ~1/127 relative weight error, which
lands well under 0.25 max-abs-logit-delta at 2 layers (CPU-measured ~0.08)."""

import asyncio
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.registry import load_model
from dynamo_tpu.quant import (
    QuantizedLinear,
    dequantize_int8,
    qlinear,
    quantize_int8,
)

# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow

PROMPT = np.array([5, 9, 2, 77, 31, 8, 100], dtype=np.int32)
PAGE_TABLE = np.array([3, 5, 7, 0, 0, 0, 0, 0], dtype=np.int32)
NUM_PAGES, PAGE_SIZE = 16, 4


def _prefill_logits(model, params):
    Tn, T_pad = len(PROMPT), 8
    tokens = np.zeros(T_pad, np.int32)
    tokens[:Tn] = PROMPT
    positions = np.arange(T_pad, dtype=np.int32)
    kv = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits, kv = model.prefill(
        params, kv, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )
    return np.asarray(logits), kv


# ---------------- container / math unit behavior ----------------

def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(2, 64, 48)).astype(np.float32)  # [L, in, out]
    q = quantize_int8(w)
    assert q.q.shape == w.shape and q.q.dtype == jnp.int8
    assert q.s.shape == (2, 48)
    back = np.asarray(dequantize_int8(q))
    # symmetric 127-step grid: |err| <= scale/2 = absmax/254 per channel
    absmax = np.abs(w).max(axis=1)  # [L, out]
    assert np.all(np.abs(back - w) <= absmax[:, None, :] / 254 + 1e-7)


def test_qlinear_matches_dequantized_matmul():
    rng = np.random.default_rng(1)
    w = rng.normal(size=(32, 24)).astype(np.float32)
    h = rng.normal(size=(5, 32)).astype(np.float32)
    q = quantize_int8(w)
    ref = h @ np.asarray(dequantize_int8(q))
    out = np.asarray(qlinear(jnp.asarray(h), q))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_quantized_linear_is_scan_sliceable():
    w = quantize_int8(np.ones((3, 8, 4), np.float32))

    def body(c, lp):
        return c, qlinear(jnp.ones((2, 8)), lp).sum()

    _, ys = jax.lax.scan(body, 0.0, w)
    assert ys.shape == (3,)


# ---------------- model parity (all three families) ----------------

@pytest.mark.parametrize("family", ["tiny", "tiny-moe", "tiny-mla"])
def test_int8_logits_close_to_full_precision(family):
    model_fp, params_fp = load_model(family, seed=0)
    logits_fp, _ = _prefill_logits(model_fp, params_fp)
    model_q, params_q = load_model(family, seed=0, quantize="int8_wo")
    logits_q, _ = _prefill_logits(model_q, params_q)
    delta = np.abs(logits_fp - logits_q).max()
    # CPU-measured ~0.05-0.09 at tiny scale; 0.25 leaves seed headroom
    assert delta < 0.25, f"{family}: max|dlogit| {delta}"
    # and the quantization actually happened (container leaves, int8 payload)
    layers = params_q.get("layers") or params_q.get("moe_layers")
    wo = layers["wo"]
    assert isinstance(wo, QuantizedLinear) and wo.q.dtype == jnp.int8


def test_int8_keeps_embeddings_and_norms_full_precision():
    model, params = load_model("tiny", seed=0, quantize="int8_wo")
    assert not isinstance(params["embed"], QuantizedLinear)
    assert not isinstance(params["layers"]["input_norm"], QuantizedLinear)
    assert params["layers"]["input_norm"].dtype == model.config.dtype


def test_int8_greedy_decode_chain_matches_itself_under_jit():
    """The int8 path is deterministic: eager vs jitted prefill+decode agree."""
    model, params = load_model("tiny", seed=0, quantize="int8_wo")
    logits_eager, kv = _prefill_logits(model, params)
    logits_jit, _ = jax.jit(model.prefill)(
        params, model.init_kv_cache(NUM_PAGES, PAGE_SIZE),
        jnp.array(np.pad(PROMPT, (0, 1))), jnp.arange(8, dtype=jnp.int32),
        jnp.array(PAGE_TABLE), jnp.arange(8) < len(PROMPT),
        jnp.array(len(PROMPT) - 1),
    )
    np.testing.assert_allclose(logits_eager, np.asarray(logits_jit), atol=1e-4)


# ---------------- sharding parity (acceptance: tp>1) ----------------

def test_tp2_int8_logits_match_tp1_int8():
    """int8 under tp=2 (sharded int8 weights + channel-sharded/replicated
    scales) must reproduce the tp=1 int8 logits."""
    from jax.sharding import Mesh

    model, params = load_model("tiny", seed=0, quantize="int8_wo")
    logits_tp1, _ = _prefill_logits(model, params)

    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("tp",))
    shardings = model.param_shardings(mesh)
    # the sharding tree mirrors the quantized structure
    assert isinstance(shardings["layers"]["wq"], QuantizedLinear)
    params_sh = jax.device_put(params, shardings)
    kv = jax.device_put(
        model.init_kv_cache(NUM_PAGES, PAGE_SIZE), model.kv_cache_sharding(mesh)
    )
    Tn, T_pad = len(PROMPT), 8
    tokens = np.zeros(T_pad, np.int32)
    tokens[:Tn] = PROMPT
    positions = np.arange(T_pad, dtype=np.int32)
    logits_tp2, _ = jax.jit(model.prefill)(
        params_sh, kv, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )
    np.testing.assert_allclose(np.asarray(logits_tp2), logits_tp1, atol=1e-4)


def test_engine_int8_tokens_identical_across_tp_pp_sp():
    """One greedy request through the full engine on tp=2 / pp=2 / sp=2
    meshes: every mesh must emit the tp=1 int8 token stream exactly."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    prompt = list(np.random.default_rng(0).integers(1, 200, 20))
    base = dict(
        model_id="tiny", page_size=4, num_pages=64, max_seqs=2,
        max_model_len=128, prefill_buckets=(16, 32), quantize="int8_wo",
    )

    async def collect(cfg):
        eng = AsyncJaxEngine(cfg)
        await eng.start()
        try:
            req = EngineRequest(
                "r1", list(prompt),
                sampling=SamplingParams(temperature=0.0, max_tokens=10, ignore_eos=True),
            )
            toks = []
            async for out in eng.generate(req):
                if out.token is not None:
                    toks.append(out.token)
            return toks
        finally:
            await eng.shutdown()

    async def run():
        ref = await collect(EngineConfig(**base))
        assert len(ref) == 10
        for mesh_kw in ({"tp": 2}, {"pp": 2}, {"sp": 2}):
            got = await collect(EngineConfig(**base, **mesh_kw))
            assert got == ref, f"{mesh_kw}: {got} != {ref}"

    asyncio.run(run())


# ---------------- load-time integration ----------------

def test_registry_cache_keys_on_quantize():
    _, p_fp = load_model("tiny", seed=0)
    _, p_q = load_model("tiny", seed=0, quantize="int8_wo")
    assert not isinstance(p_fp["layers"]["wq"], QuantizedLinear)
    assert isinstance(p_q["layers"]["wq"], QuantizedLinear)


def test_engine_config_rejects_unknown_quantize_mode():
    from dynamo_tpu.engine.config import EngineConfig

    with pytest.raises(ValueError, match="quantize"):
        EngineConfig(model_id="tiny", quantize="fp8")


def test_hf_checkpoint_loads_quantized(tmp_path):
    """An HF-format checkpoint loaded with quantize="int8_wo" quantizes at
    load time and stays logit-close to the full-precision load."""
    from safetensors.numpy import save_file

    from dynamo_tpu.models.llama import LlamaConfig, LlamaModel

    hf_cfg = {
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8,
        "rope_theta": 10000.0, "rms_norm_eps": 1e-5,
    }
    (tmp_path / "config.json").write_text(json.dumps(hf_cfg))
    cfg = LlamaConfig.from_hf_config(hf_cfg)
    src = LlamaModel(cfg)
    params = src.init_params(jax.random.key(7))

    def _np(x):
        return np.asarray(x, np.float32)

    def _T(x):
        return np.ascontiguousarray(_np(x).T)

    tensors = {
        "model.embed_tokens.weight": _np(params["embed"]),
        "model.norm.weight": _np(params["final_norm"]),
        "lm_head.weight": _np(params["lm_head"]),
    }
    lw = params["layers"]
    for l in range(cfg.num_layers):
        pre = f"model.layers.{l}."
        tensors[pre + "input_layernorm.weight"] = _np(lw["input_norm"][l])
        tensors[pre + "self_attn.q_proj.weight"] = _T(lw["wq"][l])
        tensors[pre + "self_attn.k_proj.weight"] = _T(lw["wk"][l])
        tensors[pre + "self_attn.v_proj.weight"] = _T(lw["wv"][l])
        tensors[pre + "self_attn.o_proj.weight"] = _T(lw["wo"][l])
        tensors[pre + "post_attention_layernorm.weight"] = _np(lw["post_norm"][l])
        tensors[pre + "mlp.gate_proj.weight"] = _T(lw["gate"][l])
        tensors[pre + "mlp.up_proj.weight"] = _T(lw["up"][l])
        tensors[pre + "mlp.down_proj.weight"] = _T(lw["down"][l])
    save_file(tensors, str(tmp_path / "model.safetensors"))

    model_fp, params_fp = load_model(str(tmp_path))
    model_q, params_q = load_model(str(tmp_path), quantize="int8_wo")
    assert isinstance(params_q["layers"]["gate"], QuantizedLinear)
    lf, _ = _prefill_logits(model_fp, params_fp)
    lq, _ = _prefill_logits(model_q, params_q)
    assert np.abs(lf - lq).max() < 0.25
