"""OpenAI logprobs: engine-level correctness + HTTP rendering (chat and
completions, streaming and aggregated)."""

import asyncio
import json

import aiohttp
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest

from tests.test_llama_model import naive_forward


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def engine():
    cfg = EngineConfig(
        model_id="tiny",
        page_size=4,
        num_pages=64,
        max_seqs=4,
        max_model_len=64,
        prefill_buckets=(8, 16, 32),
    )
    e = AsyncJaxEngine(cfg)
    loop = asyncio.new_event_loop()
    loop.run_until_complete(e.start())
    yield e, loop
    loop.run_until_complete(e.shutdown())
    loop.close()


async def _collect(engine, req):
    outs = []
    async for out in engine.generate(req):
        if out.token is not None:
            outs.append(out)
    return outs


def test_engine_logprobs_match_reference(engine):
    """Greedy: chosen logprob equals the naive forward's log-softmax max, and
    top-1 alternative is the chosen token itself."""
    e, loop = engine
    prompt = [5, 9, 2, 77, 31, 8, 100]
    req = EngineRequest(
        request_id="lp1",
        token_ids=list(prompt),
        sampling=SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True),
        logprobs=3,
    )
    outs = loop.run_until_complete(_collect(e, req))
    assert len(outs) == 4

    cfg = e.model.config
    params = jax.device_get(e.runner.params)
    toks = list(prompt)
    for out in outs:
        logits = naive_forward(cfg, params, toks)[-1]
        ref_lp = jax.nn.log_softmax(logits.astype(jnp.float32))
        assert out.logprob is not None
        assert out.token == int(jnp.argmax(logits))
        np.testing.assert_allclose(out.logprob, float(ref_lp[out.token]), rtol=1e-3, atol=1e-3)
        # top alternatives: 3 requested, sorted descending, top-1 == chosen
        assert len(out.top_logprobs) == 3
        ids = [t for t, _ in out.top_logprobs]
        lps = [l for _, l in out.top_logprobs]
        assert ids[0] == out.token
        assert lps == sorted(lps, reverse=True)
        toks.append(out.token)


def test_engine_no_logprobs_by_default(engine):
    e, loop = engine
    req = EngineRequest(
        request_id="lp0",
        token_ids=[3, 1, 4, 1, 5],
        sampling=SamplingParams(temperature=0.0, max_tokens=2, ignore_eos=True),
    )
    outs = loop.run_until_complete(_collect(e, req))
    assert all(o.logprob is None and o.top_logprobs is None for o in outs)


# ---------------- HTTP rendering ----------------


@pytest.fixture(scope="module")
def http_server():
    from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model
    from dynamo_tpu.llm.http.service import HttpService

    async def setup():
        cfg = EngineConfig(
            model_id="tiny",
            page_size=4,
            num_pages=64,
            max_seqs=4,
            max_model_len=64,
            prefill_buckets=(8, 16, 32),
        )
        e = AsyncJaxEngine(cfg)
        await e.start()
        card = card_for_model("tiny")
        svc = HttpService(host="127.0.0.1", port=0)
        svc.manager.add(build_pipeline(e, card))
        port = await svc.start()
        return e, svc, port

    loop = asyncio.new_event_loop()
    e, svc, port = loop.run_until_complete(setup())
    yield port, loop
    loop.run_until_complete(svc.stop())
    loop.run_until_complete(e.shutdown())
    loop.close()


def _post(loop, port, path, body):
    async def go():
        async with aiohttp.ClientSession() as s:
            async with s.post(f"http://127.0.0.1:{port}{path}", json=body) as resp:
                return resp.status, await resp.text()

    return loop.run_until_complete(go())


def test_http_completions_logprobs(http_server):
    port, loop = http_server
    status, text = _post(
        loop, port, "/v1/completions",
        {"model": "tiny", "prompt": "hi", "max_tokens": 3, "temperature": 0.0,
         "logprobs": 2, "ext": {"ignore_eos": True}},
    )
    assert status == 200
    lp = json.loads(text)["choices"][0]["logprobs"]
    assert lp is not None
    assert len(lp["tokens"]) == 3
    assert len(lp["token_logprobs"]) == 3
    assert all(isinstance(x, float) for x in lp["token_logprobs"])
    assert all(len(d) == 2 for d in lp["top_logprobs"])
    assert lp["text_offset"] == sorted(lp["text_offset"])


def test_http_chat_logprobs_stream_and_unary(http_server):
    port, loop = http_server
    body = {
        "model": "tiny",
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 3, "temperature": 0.0,
        "logprobs": True, "top_logprobs": 2,
        "ext": {"ignore_eos": True},
    }
    status, text = _post(loop, port, "/v1/chat/completions", body)
    assert status == 200
    lp = json.loads(text)["choices"][0]["logprobs"]
    assert lp is not None and len(lp["content"]) == 3
    entry = lp["content"][0]
    assert {"token", "logprob", "bytes", "top_logprobs"} <= set(entry)
    assert len(entry["top_logprobs"]) == 2

    status, text = _post(loop, port, "/v1/chat/completions", dict(body, stream=True))
    assert status == 200
    frames = [json.loads(l[6:]) for l in text.splitlines() if l.startswith("data: {")]
    lp_frames = [
        f for f in frames
        if f["choices"] and (f["choices"][0].get("logprobs") or {}).get("content")
    ]
    assert sum(len(f["choices"][0]["logprobs"]["content"]) for f in lp_frames) == 3


def test_http_chat_no_logprobs_field_absent(http_server):
    port, loop = http_server
    status, text = _post(
        loop, port, "/v1/chat/completions",
        {"model": "tiny", "messages": [{"role": "user", "content": "hello"}],
         "max_tokens": 2, "temperature": 0.0, "ext": {"ignore_eos": True}},
    )
    assert status == 200
    assert "logprobs" not in json.loads(text)["choices"][0]
