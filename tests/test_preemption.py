"""Scheduler preemption under page pressure + request cancellation."""

import asyncio

import pytest

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest

from tests.test_engine import tiny_engine_config, greedy_reference, _collect


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow


def test_preemption_under_page_pressure():
    """Two long-running sequences in a pool that cannot hold both: the younger
    gets preempted and resumes later, and BOTH finish with correct greedy
    output (prefix cache recovers the preempted work)."""

    async def body():
        # 8 usable pages; each seq: 8-token prompt + 16 decode = 24 tokens = 6 pages
        eng = AsyncJaxEngine(
            tiny_engine_config(num_pages=9, max_seqs=2, max_model_len=32, watermark=0.0)
        )
        await eng.start()
        try:
            prompts = [[10 + i for i in range(8)], [50 + i for i in range(8)]]
            reqs = [
                EngineRequest(
                    request_id=f"p{i}",
                    token_ids=list(p),
                    sampling=SamplingParams(temperature=0.0, max_tokens=16),
                )
                for i, p in enumerate(prompts)
            ]
            results = await asyncio.gather(*[_collect(eng, r) for r in reqs])
            for (toks, finish, _), prompt in zip(results, prompts):
                assert finish == "length"
                assert toks == greedy_reference(eng, prompt, 16), f"prompt {prompt}"
        finally:
            await eng.shutdown()

    asyncio.run(body())


def test_cancellation_frees_resources():
    async def body():
        eng = AsyncJaxEngine(tiny_engine_config())
        await eng.start()
        try:
            req = EngineRequest(
                request_id="c1",
                token_ids=[1, 2, 3],
                sampling=SamplingParams(temperature=0.0, max_tokens=10_000, ignore_eos=True),
            )
            got = 0
            async for out in eng.generate(req):
                got += 1
                if got >= 3:
                    break  # client walks away mid-stream
            # the cancel box drains on the next loop iteration
            for _ in range(200):
                if eng.scheduler.num_running == 0:
                    break
                await asyncio.sleep(0.02)
            assert eng.scheduler.num_running == 0
            assert eng.allocator.active_pages == 0
        finally:
            await eng.shutdown()

    asyncio.run(body())


def test_prefill_burst_interleaves_with_running_decode():
    """Admission fairness (VERDICT r4 item 3): with a decode stream running,
    a burst of new prompts must NOT serialize all its prefill passes ahead of
    the decode windows — at most config.prefill_batches_per_step packed
    prefill calls dispatch per scheduler step, with decode windows between
    them (protects running streams' ITL and intra-burst TTFT spread)."""
    import asyncio

    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    from tests.test_engine import tiny_engine_config

    async def body():
        eng = AsyncJaxEngine(tiny_engine_config(
            max_seqs=8, num_pages=96, prefill_lanes=2,
            prefill_batches_per_step=1, prefill_buckets=(8, 16, 32),
        ))
        await eng.start()
        tags = []
        try:
            # record the dispatch ORDER at the runner boundary
            runner = eng.runner
            orig_batch = runner.prefill_chunk_batch
            orig_window = runner.dispatch_decode_window

            def spy_batch(*a, **k):
                tags.append("prefill")
                return orig_batch(*a, **k)

            def spy_window(*a, **k):
                tags.append("window")
                return orig_window(*a, **k)

            runner.prefill_chunk_batch = spy_batch
            runner.dispatch_decode_window = spy_window

            async def run_req(rid, prompt, n):
                req = EngineRequest(
                    request_id=rid, token_ids=prompt,
                    sampling=SamplingParams(temperature=0.0, max_tokens=n,
                                            ignore_eos=True),
                )
                toks = []
                async for out in eng.generate(req):
                    if out.token is not None:
                        toks.append(out.token)
                return toks

            # a long-running decode stream...
            long_task = asyncio.create_task(run_req("long", [5, 9, 2, 7], 48))
            while not tags or tags[-1] != "window":
                await asyncio.sleep(0.01)
            burst_from = len(tags)
            # ...then a 6-request burst (3 packed prefill calls at 2 lanes)
            rng_prompts = [[i + 1, 50 + i, 60 + i, 70 + i, 80 + i, 90 + i,
                            30 + i, 40 + i, 20 + i, 10 + i, 3, 4] for i in range(6)]
            burst = await asyncio.gather(*[
                run_req(f"b{i}", rng_prompts[i], 4) for i in range(6)
            ])
            await long_task
            assert all(len(t) == 4 for t in burst)
            seq = tags[burst_from:]
            prefill_idx = [i for i, t in enumerate(seq) if t == "prefill"]
            assert len(prefill_idx) >= 3, seq  # the burst really packed
            # windows interleave: with cap=1 a run of 2 can appear across two
            # steps whose windows were already pipeline-full (decode saturated,
            # not starved); cap=0 would dispatch all 3 packed calls back-to-
            # back in ONE step (run of 3+)
            runs, cur = [], 0
            for t in seq:
                cur = cur + 1 if t == "prefill" else 0
                runs.append(cur)
            assert max(runs) <= 2, seq
            # and decode windows actually ran BETWEEN the burst's prefills
            assert any(t == "window" for t in seq[prefill_idx[0]:prefill_idx[-1]]), seq
        finally:
            await eng.shutdown()

    asyncio.run(body())
