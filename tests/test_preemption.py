"""Scheduler preemption under page pressure + request cancellation."""

import asyncio

import pytest

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest

from tests.test_engine import tiny_engine_config, greedy_reference, _collect


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow


def test_preemption_under_page_pressure():
    """Two long-running sequences in a pool that cannot hold both: the younger
    gets preempted and resumes later, and BOTH finish with correct greedy
    output (prefix cache recovers the preempted work)."""

    async def body():
        # 8 usable pages; each seq: 8-token prompt + 16 decode = 24 tokens = 6 pages
        eng = AsyncJaxEngine(
            tiny_engine_config(num_pages=9, max_seqs=2, max_model_len=32, watermark=0.0)
        )
        await eng.start()
        try:
            prompts = [[10 + i for i in range(8)], [50 + i for i in range(8)]]
            reqs = [
                EngineRequest(
                    request_id=f"p{i}",
                    token_ids=list(p),
                    sampling=SamplingParams(temperature=0.0, max_tokens=16),
                )
                for i, p in enumerate(prompts)
            ]
            results = await asyncio.gather(*[_collect(eng, r) for r in reqs])
            for (toks, finish, _), prompt in zip(results, prompts):
                assert finish == "length"
                assert toks == greedy_reference(eng, prompt, 16), f"prompt {prompt}"
        finally:
            await eng.shutdown()

    asyncio.run(body())


def test_cancellation_frees_resources():
    async def body():
        eng = AsyncJaxEngine(tiny_engine_config())
        await eng.start()
        try:
            req = EngineRequest(
                request_id="c1",
                token_ids=[1, 2, 3],
                sampling=SamplingParams(temperature=0.0, max_tokens=10_000, ignore_eos=True),
            )
            got = 0
            async for out in eng.generate(req):
                got += 1
                if got >= 3:
                    break  # client walks away mid-stream
            # the cancel box drains on the next loop iteration
            for _ in range(200):
                if eng.scheduler.num_running == 0:
                    break
                await asyncio.sleep(0.02)
            assert eng.scheduler.num_running == 0
            assert eng.allocator.active_pages == 0
        finally:
            await eng.shutdown()

    asyncio.run(body())
