"""C-ABI embedding library: a foreign engine publishes KV events through
libdynamo_tpu_llm.so and a KvRouter (subscribed over the broker) indexes them.

Mirrors the reference C FFI path (reference: lib/bindings/c/src/lib.rs ->
NATS kv_events -> indexer, SURVEY.md §3.4)."""

import asyncio
import ctypes
import sys
from pathlib import Path

import pytest

from dynamo_tpu.cplane.broker import Broker
from dynamo_tpu.llm.kv_router.router import KvRouter
from dynamo_tpu.llm.tokens import TokenSequence
from dynamo_tpu.runtime.distributed import DistributedRuntime

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def capi():
    sys.path.insert(0, str(REPO / "native"))
    try:
        import build as native_build
    finally:
        sys.path.pop(0)
    try:
        path = native_build.build_llm_capi()
    except Exception as e:
        pytest.skip(f"native toolchain unavailable: {e}")
    lib = ctypes.CDLL(str(path))
    lib.dynamo_tpu_llm_init.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32,
    ]
    lib.dynamo_tpu_llm_kv_event_publish_stored.argtypes = [
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.dynamo_tpu_llm_kv_event_publish_removed.argtypes = [
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64,
    ]
    return lib


def test_capi_events_reach_router(capi):
    async def body():
        broker = Broker()
        port = await broker.start()
        rt = DistributedRuntime(cplane_address=f"127.0.0.1:{port}")
        await rt.connect()
        router = KvRouter(rt, "cns", "cworker", kv_block_size=4)
        await router.start()
        try:
            worker_id = 0x77
            rc = capi.dynamo_tpu_llm_init(
                f"127.0.0.1:{port}".encode(), b"cns", b"cworker", worker_id, 4
            )
            assert rc == 0

            # blocks for tokens [0..8) with the canonical hash scheme
            prompt = list(range(8))
            ts = TokenSequence(prompt, 4)
            b = ts.blocks
            arr = lambda vals: (ctypes.c_uint64 * len(vals))(*vals)
            loop = asyncio.get_running_loop()
            rc = await loop.run_in_executor(
                None,
                lambda: capi.dynamo_tpu_llm_kv_event_publish_stored(
                    1, 0, 0, 2,
                    arr([blk.sequence_hash for blk in b]),
                    arr([blk.block_hash for blk in b]),
                ),
            )
            assert rc == 0
            await asyncio.sleep(0.2)

            scores = router.indexer.find_matches_for_request(prompt)
            assert scores.scores == {worker_id: 2}

            rc = await loop.run_in_executor(
                None,
                lambda: capi.dynamo_tpu_llm_kv_event_publish_removed(
                    2, arr([b[1].sequence_hash]), 1
                ),
            )
            assert rc == 0
            await asyncio.sleep(0.2)
            scores = router.indexer.find_matches_for_request(prompt)
            assert scores.scores == {worker_id: 1}

            assert capi.dynamo_tpu_llm_shutdown() == 0
        finally:
            await router.stop()
            await rt._shutdown_hook()
            await broker.stop()

    asyncio.run(body())
