"""run CLI: dyn:// worker/frontend split + batch mode.

Mirrors the reference dynamo-run matrix (reference: launch/dynamo-run in=/out=
combinations)."""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_run_cli_dyn_split(tmp_path):
    """worker: run --in dyn://d.worker.gen --out jax
    frontend: run --in http --out dyn://d.worker.gen"""
    cplane_port = _free_port()
    http_port = _free_port()
    env = dict(os.environ)
    env["DYNTPU_CPLANE"] = f"127.0.0.1:{cplane_port}"

    broker = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.cplane.broker", "--port", str(cplane_port)],
        env=env, cwd="/root/repo",
    )
    worker = frontend = None
    try:
        time.sleep(1.0)
        worker = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.launch.run", "run", "tiny",
             "--in", "dyn://d.worker.gen", "--out", "jax"],
            env=env, cwd="/root/repo",
        )
        frontend = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.launch.run", "run", "tiny",
             "--in", "http", "--out", "dyn://d.worker.gen",
             "--http-port", str(http_port)],
            env=env, cwd="/root/repo",
        )
        body = json.dumps({
            "model": "tiny",
            "messages": [{"role": "user", "content": "over the wire"}],
            "max_tokens": 5,
            "temperature": 0,
        }).encode()
        deadline = time.time() + 120
        last = None
        while time.time() < deadline:
            for proc, name in ((broker, "broker"), (worker, "worker"), (frontend, "frontend")):
                if proc.poll() is not None:
                    pytest.fail(f"{name} died rc={proc.returncode}")
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{http_port}/v1/chat/completions",
                    data=body, headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    result = json.loads(resp.read())
                assert result["usage"]["completion_tokens"] == 5
                assert isinstance(result["choices"][0]["message"]["content"], str)
                return
            except Exception as e:
                last = e
                time.sleep(1.0)
        pytest.fail(f"never became ready: {last}")
    finally:
        for proc in (frontend, worker, broker):
            if proc is not None and proc.poll() is None:
                proc.terminate()
        for proc in (frontend, worker, broker):
            if proc is not None:
                try:
                    proc.wait(10)
                except subprocess.TimeoutExpired:
                    proc.kill()


@pytest.mark.slow
def test_run_cli_batch_mode(tmp_path):
    batch_file = tmp_path / "prompts.jsonl"
    batch_file.write_text(
        "\n".join(json.dumps({"text": f"prompt {i}", "max_tokens": 4}) for i in range(3))
    )
    proc = subprocess.run(
        [sys.executable, "-m", "dynamo_tpu.launch.run", "run", "tiny",
         "--in", f"batch:{batch_file}", "--out", "jax"],
        capture_output=True, text=True, timeout=180, cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["requests"] == 3
    assert summary["output_tokens"] == 12
    out_file = Path(summary["output_file"])
    assert out_file.exists()
    lines = [json.loads(l) for l in out_file.read_text().splitlines()]
    assert all(r["tokens_out"] == 4 for r in lines)
