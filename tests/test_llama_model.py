"""Model correctness: paged prefill/decode vs a naive dense transformer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.llama import LlamaConfig, LlamaModel
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.rotary import apply_rope


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow

PAGE_SIZE = 4
NUM_PAGES = 16


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.key(0))
    return cfg, model, params


def naive_forward(cfg, params, tokens):
    """Plain dense causal transformer — the semantic reference."""
    T = len(tokens)
    pos = jnp.arange(T)
    h = params["embed"][jnp.array(tokens)].astype(cfg.dtype)
    for l in range(cfg.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])
        x = rms_norm(h, lp["input_norm"], cfg.rms_norm_eps)
        q = apply_rope((x @ lp["wq"]).reshape(T, cfg.num_heads, cfg.head_dim), pos, cfg.rope_theta)
        k = apply_rope((x @ lp["wk"]).reshape(T, cfg.num_kv_heads, cfg.head_dim), pos, cfg.rope_theta)
        v = (x @ lp["wv"]).reshape(T, cfg.num_kv_heads, cfg.head_dim)
        g = cfg.num_heads // cfg.num_kv_heads
        kr = jnp.repeat(k, g, axis=1)
        vr = jnp.repeat(v, g, axis=1)
        s = jnp.einsum("thd,shd->hts", q.astype(jnp.float32), kr.astype(jnp.float32))
        s = s / np.sqrt(cfg.head_dim)
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None], s, -1e30)
        a = jnp.einsum("hts,shd->thd", jax.nn.softmax(s, -1), vr.astype(jnp.float32)).astype(cfg.dtype)
        h = h + a.reshape(T, -1) @ lp["wo"]
        x = rms_norm(h, lp["post_norm"], cfg.rms_norm_eps)
        h = h + (jax.nn.silu(x @ lp["gate"]) * (x @ lp["up"])) @ lp["down"]
    x = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"] if cfg.tie_word_embeddings else params["lm_head"]
    return jnp.einsum("td,vd->tv", x.astype(jnp.float32), head.astype(jnp.float32))


PROMPT = np.array([5, 9, 2, 77, 31, 8, 100], dtype=np.int32)
PAGE_TABLE = np.array([3, 5, 7, 0, 0, 0, 0, 0], dtype=np.int32)


def test_prefill_matches_naive(setup):
    cfg, model, params = setup
    ref = naive_forward(cfg, params, PROMPT)[-1]
    Tn, T_pad = len(PROMPT), 8
    tokens = np.zeros(T_pad, np.int32)
    tokens[:Tn] = PROMPT
    positions = np.arange(T_pad, dtype=np.int32)
    kv = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits, _ = model.prefill(
        params, kv, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref), atol=1e-4)


def test_prefill_then_decode_matches_full_prefill(setup):
    cfg, model, params = setup
    Tn, T_pad = len(PROMPT), 8
    tokens = np.zeros(T_pad, np.int32)
    tokens[:Tn] = PROMPT
    positions = np.arange(T_pad, dtype=np.int32)

    kv1 = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits_a, kv1 = model.prefill(
        params, kv1, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )

    # Prefill only the first 3 tokens, then decode the rest one-by-one in a
    # 2-slot batch where slot 1 is inactive throughout.
    kv2 = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits_b, kv2 = model.prefill(
        params, kv2, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < 3), jnp.array(2),
    )
    pts = np.zeros((2, 8), np.int32)
    pts[0] = PAGE_TABLE
    for i in range(3, Tn):
        logits_dec, kv2 = model.decode(
            params, kv2,
            jnp.array([PROMPT[i], 0], jnp.int32),
            jnp.array([i, 0], jnp.int32),
            jnp.array(pts),
            jnp.array([True, False]),
        )
        logits_b = logits_dec[0]

    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b), atol=1e-4)
    # compare only pages owned by the sequence (trash pages accumulate garbage
    # from masked rows by design)
    owned = np.asarray(PAGE_TABLE[:2])  # pages covering the 7-token prompt
    L = cfg.num_layers
    flat = (owned[None, :] + np.arange(L)[:, None] * NUM_PAGES).ravel()
    for leaf in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(kv1[leaf][flat]), np.asarray(kv2[leaf][flat]), atol=1e-4
        )


def test_inactive_slot_does_not_corrupt_pages(setup):
    cfg, model, params = setup
    kv = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    # sentinel data in page 3 of every layer — owned by nobody here
    flat = np.arange(cfg.num_layers) * NUM_PAGES + 3
    kv = {leaf: kv[leaf].at[flat].set(7.0) for leaf in kv}
    sentinel = {leaf: np.asarray(kv[leaf][flat]) for leaf in kv}
    pts = np.zeros((2, 8), np.int32)
    _, kv2 = model.decode(
        params, kv,
        jnp.array([1, 2], jnp.int32),
        jnp.array([0, 0], jnp.int32),
        jnp.array(pts),
        jnp.array([False, False]),
    )
    for leaf in kv2:
        np.testing.assert_array_equal(np.asarray(kv2[leaf][flat]), sentinel[leaf])


def test_tp_sharded_prefill_matches(setup):
    """Same prefill under a tp=2 mesh sharding must produce identical logits."""
    from jax.sharding import Mesh

    cfg, model, params = setup
    devices = np.array(jax.devices()[:2]).reshape(2)
    mesh = Mesh(devices, ("tp",))
    shardings = model.param_shardings(mesh)
    params_sh = jax.device_put(params, shardings)
    kv = jax.device_put(
        model.init_kv_cache(NUM_PAGES, PAGE_SIZE), model.kv_cache_sharding(mesh)
    )
    Tn, T_pad = len(PROMPT), 8
    tokens = np.zeros(T_pad, np.int32)
    tokens[:Tn] = PROMPT
    positions = np.arange(T_pad, dtype=np.int32)
    logits_sh, _ = jax.jit(model.prefill)(
        params_sh, kv, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )
    ref = naive_forward(cfg, params, PROMPT)[-1]
    np.testing.assert_allclose(np.asarray(logits_sh), np.asarray(ref), atol=1e-4)


def test_qwen2_style_bias_model():
    """attention_bias=True (Qwen2 family): paged prefill matches a naive
    dense forward with biases."""
    from dataclasses import replace

    cfg = replace(LlamaConfig.tiny(), attention_bias=True)
    model = LlamaModel(cfg)
    params = model.init_params(jax.random.key(3))

    def naive_bias(tokens):
        T = len(tokens)
        pos = jnp.arange(T)
        h = params["embed"][jnp.array(tokens)].astype(cfg.dtype)
        for l in range(cfg.num_layers):
            lp = jax.tree.map(lambda x: x[l], params["layers"])
            x = rms_norm(h, lp["input_norm"], cfg.rms_norm_eps)
            q = apply_rope(((x @ lp["wq"]) + lp["bq"]).reshape(T, cfg.num_heads, cfg.head_dim), pos, cfg.rope_theta)
            k = apply_rope(((x @ lp["wk"]) + lp["bk"]).reshape(T, cfg.num_kv_heads, cfg.head_dim), pos, cfg.rope_theta)
            v = ((x @ lp["wv"]) + lp["bv"]).reshape(T, cfg.num_kv_heads, cfg.head_dim)
            g = cfg.num_heads // cfg.num_kv_heads
            kr = jnp.repeat(k, g, axis=1)
            vr = jnp.repeat(v, g, axis=1)
            s = jnp.einsum("thd,shd->hts", q.astype(jnp.float32), kr.astype(jnp.float32)) / np.sqrt(cfg.head_dim)
            s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None], s, -1e30)
            a = jnp.einsum("hts,shd->thd", jax.nn.softmax(s, -1), vr.astype(jnp.float32)).astype(cfg.dtype)
            h = h + a.reshape(T, -1) @ lp["wo"]
            x = rms_norm(h, lp["post_norm"], cfg.rms_norm_eps)
            h = h + (jax.nn.silu(x @ lp["gate"]) * (x @ lp["up"])) @ lp["down"]
        x = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
        return jnp.einsum("td,vd->tv", x.astype(jnp.float32), params["lm_head"].astype(jnp.float32))

    ref = naive_bias(PROMPT)[-1]
    Tn, T_pad = len(PROMPT), 8
    tokens = np.zeros(T_pad, np.int32)
    tokens[:Tn] = PROMPT
    positions = np.arange(T_pad, dtype=np.int32)
    kv = model.init_kv_cache(NUM_PAGES, PAGE_SIZE)
    logits, _ = model.prefill(
        params, kv, jnp.array(tokens), jnp.array(positions),
        jnp.array(PAGE_TABLE), jnp.array(positions < Tn), jnp.array(Tn - 1),
    )
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref), atol=1e-4)
