"""Pallas paged decode attention (interpret mode on CPU) vs the pure-JAX
reference implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.ops.attention import paged_decode_attention
from dynamo_tpu.ops.pallas.paged_attention import paged_decode_attention_pallas


def make_case(B=3, Hq=4, Hkv=2, D=16, P=16, ps=4, max_pages=6, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), jnp.float32)
    # distinct pages per sequence, lengths straddling page boundaries
    pt = np.zeros((B, max_pages), np.int32)
    for b in range(B):
        pt[b] = rng.choice(np.arange(1, P), size=max_pages, replace=False)
    positions = jnp.asarray([3, 9, 14], jnp.int32)[:B]  # lengths 4, 10, 15
    return q, k, v, jnp.asarray(pt), positions


def test_pallas_matches_reference():
    q, k, v, pt, pos = make_case()
    ref = paged_decode_attention(q, k, v, pt, pos)
    got = paged_decode_attention_pallas(q, k, v, pt, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_pallas_single_token_context():
    q, k, v, pt, _ = make_case(B=1)
    pos = jnp.asarray([0], jnp.int32)
    ref = paged_decode_attention(q, k, v, pt, pos)
    got = paged_decode_attention_pallas(q, k, v, pt, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_pallas_gqa_and_mha():
    for Hq, Hkv in [(8, 8), (8, 2), (4, 1)]:
        q, k, v, pt, pos = make_case(Hq=Hq, Hkv=Hkv, seed=Hq * 10 + Hkv)
        ref = paged_decode_attention(q, k, v, pt, pos)
        got = paged_decode_attention_pallas(q, k, v, pt, pos, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=2e-5, err_msg=f"Hq={Hq} Hkv={Hkv}"
        )
