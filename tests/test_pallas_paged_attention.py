"""Pallas paged decode attention (interpret mode on CPU) vs the pure-JAX
reference implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.ops.attention import paged_decode_attention
from dynamo_tpu.ops.pallas.paged_attention import paged_decode_attention_pallas


# compile-heavy JAX e2e: runs in the full matrix, not the <2-min default tier
pytestmark = pytest.mark.slow


def make_case(B=3, Hq=4, Hkv=2, D=16, P=16, ps=4, max_pages=6, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), jnp.float32)
    # distinct pages per sequence, lengths straddling page boundaries
    pt = np.zeros((B, max_pages), np.int32)
    for b in range(B):
        pt[b] = rng.choice(np.arange(1, P), size=max_pages, replace=False)
    positions = jnp.asarray([3, 9, 14], jnp.int32)[:B]  # lengths 4, 10, 15
    return q, k, v, jnp.asarray(pt), positions


def test_pallas_matches_reference():
    q, k, v, pt, pos = make_case()
    ref = paged_decode_attention(q, k, v, pt, pos)
    got = paged_decode_attention_pallas(q, k, v, pt, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_pallas_single_token_context():
    q, k, v, pt, _ = make_case(B=1)
    pos = jnp.asarray([0], jnp.int32)
    ref = paged_decode_attention(q, k, v, pt, pos)
    got = paged_decode_attention_pallas(q, k, v, pt, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_pallas_gqa_and_mha():
    for Hq, Hkv in [(8, 8), (8, 2), (4, 1)]:
        q, k, v, pt, pos = make_case(Hq=Hq, Hkv=Hkv, seed=Hq * 10 + Hkv)
        ref = paged_decode_attention(q, k, v, pt, pos)
        got = paged_decode_attention_pallas(q, k, v, pt, pos, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=2e-5, err_msg=f"Hq={Hq} Hkv={Hkv}"
        )


def test_pallas_tp_shard_map():
    """dispatch under a tp=2 mesh runs the kernel via shard_map (heads split
    across devices, no collectives) and matches the unsharded reference."""
    from jax.sharding import Mesh

    from dynamo_tpu.ops.attention import dispatch_paged_decode_attention

    q, k, v, pt, pos = make_case(Hq=8, Hkv=2)
    ref = paged_decode_attention(q, k, v, pt, pos)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    got = jax.jit(
        lambda *a: dispatch_paged_decode_attention(*a, mesh=mesh)
    )(q, k, v, pt, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_engine_tp2_uses_pallas_under_shard_map(monkeypatch):
    """A tp=2 engine with the Pallas kernel forced on generates the same
    greedy tokens as tp=1 (kernel correctness through the whole stack)."""
    import asyncio

    from tests.test_engine import tiny_engine_config

    monkeypatch.setenv("DYNTPU_PALLAS", "1")
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    async def body():
        eng = AsyncJaxEngine(tiny_engine_config(tp=2))
        await eng.start()
        req = EngineRequest(
            request_id="tp2",
            token_ids=[5, 9, 2, 77, 31],
            sampling=SamplingParams(temperature=0.0, max_tokens=6),
        )
        toks = []
        async for out in eng.generate(req):
            if out.token is not None:
                toks.append(out.token)
        await eng.shutdown()
        return toks

    got = asyncio.run(body())

    monkeypatch.setenv("DYNTPU_PALLAS", "0")

    async def ref_body():
        eng = AsyncJaxEngine(tiny_engine_config(tp=1))
        await eng.start()
        req = EngineRequest(
            request_id="ref",
            token_ids=[5, 9, 2, 77, 31],
            sampling=SamplingParams(temperature=0.0, max_tokens=6),
        )
        toks = []
        async for out in eng.generate(req):
            if out.token is not None:
                toks.append(out.token)
        await eng.shutdown()
        return toks

    ref = asyncio.run(ref_body())
    assert got == ref, f"tp2 pallas {got} != tp1 reference {ref}"


# ---------------- chunked-prefill flash kernel ----------------

from dynamo_tpu.ops.attention import paged_prefill_attention
from dynamo_tpu.ops.pallas.prefill_attention import paged_prefill_attention_pallas


def make_prefill_case(T=128, Hq=4, Hkv=2, D=16, P=48, ps=4, max_pages=40, start=0, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((T, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), jnp.float32)
    pt = jnp.asarray(rng.choice(np.arange(1, P), size=max_pages, replace=False), jnp.int32)
    positions = jnp.asarray(start + np.arange(T), jnp.int32)
    return q, k, v, pt, positions


def test_prefill_pallas_matches_reference():
    q, k, v, pt, pos = make_prefill_case()
    ref = paged_prefill_attention(q, k, v, pt, pos)
    got = paged_prefill_attention_pallas(q, k, v, pt, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_prefill_pallas_cached_prefix_chunk():
    """Chunk starting mid-sequence (cached prefix skipped): attends over all
    earlier pages + its own rows."""
    q, k, v, pt, pos = make_prefill_case(T=128, start=57, seed=3)
    ref = paged_prefill_attention(q, k, v, pt, pos)
    got = paged_prefill_attention_pallas(q, k, v, pt, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_prefill_pallas_multi_block_and_gqa():
    for T, Hq, Hkv in [(256, 8, 2), (128, 4, 4), (384, 8, 1)]:
        q, k, v, pt, pos = make_prefill_case(
            T=T, Hq=Hq, Hkv=Hkv, P=128, max_pages=100, seed=T + Hq
        )
        ref = paged_prefill_attention(q, k, v, pt, pos)
        got = paged_prefill_attention_pallas(q, k, v, pt, pos, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_prefill_dispatch_gates_on_block_divisibility():
    from dynamo_tpu.ops.attention import use_pallas_prefill

    assert not use_pallas_prefill(128, 96)  # not block-divisible: XLA path


def test_prefill_dispatch_tp2_shard_map(monkeypatch):
    """dispatch_paged_prefill_attention under a tp=2 mesh (kernel forced on,
    interpret mode) matches the unsharded XLA reference."""
    from jax.sharding import Mesh

    from dynamo_tpu.ops.attention import dispatch_paged_prefill_attention

    monkeypatch.setenv("DYNTPU_PALLAS", "1")
    q, k, v, pt, pos = make_prefill_case(T=128, Hq=8, Hkv=2, P=64, max_pages=40, seed=11)
    mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    ref = paged_prefill_attention(q, k, v, pt, pos)
    got = jax.jit(
        lambda *a: dispatch_paged_prefill_attention(*a, mesh=mesh)
    )(q, k, v, pt, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


def test_pallas_chunked_matches_reference():
    from dynamo_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas_chunked,
    )

    for B, Hq, Hkv, seed in [(3, 4, 2, 0), (8, 16, 8, 1), (2, 8, 8, 5)]:
        q, k, v, pt, pos = make_case(B=B, Hq=Hq, Hkv=Hkv, seed=seed)
        pos = jnp.asarray(np.random.default_rng(seed).integers(0, 15, B), jnp.int32)
        ref = paged_decode_attention(q, k, v, pt, pos)
        got = paged_decode_attention_pallas_chunked(q, k, v, pt, pos, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=2e-5, err_msg=f"B={B} Hq={Hq}"
        )


def test_pallas_folded_matches_reference():
    """head_dim < 128 variant: heads folded into lanes, zero-placed Q."""
    from dynamo_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas_folded,
    )

    for B, Hq, Hkv, D, seed in [(3, 8, 2, 16, 0), (4, 32, 4, 16, 1), (2, 4, 4, 8, 2)]:
        q, k, v, pt, pos = make_case(B=B, Hq=Hq, Hkv=Hkv, D=D, seed=seed)
        pos = jnp.asarray(np.random.default_rng(seed).integers(0, 15, B), jnp.int32)
        ref = paged_decode_attention(q, k, v, pt, pos)
        got = paged_decode_attention_pallas_folded(q, k, v, pt, pos, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=2e-5, err_msg=f"B={B} Hq={Hq} D={D}"
        )


def test_pallas_grouped_matches_reference():
    from dynamo_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas_grouped,
    )

    for B, Hq, Hkv, seed in [(8, 16, 8, 1), (4, 8, 8, 2), (3, 4, 2, 0), (6, 4, 2, 5)]:
        q, k, v, pt, pos = make_case(B=B, Hq=Hq, Hkv=Hkv, seed=seed)
        pos = jnp.asarray(np.random.default_rng(seed).integers(0, 15, B), jnp.int32)
        ref = paged_decode_attention(q, k, v, pt, pos)
        got = paged_decode_attention_pallas_grouped(q, k, v, pt, pos, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=2e-5, err_msg=f"B={B} Hq={Hq}"
        )


def test_prefill_pallas_folded_matches_reference():
    """Folded-lane flash prefill (head_dim < 128 layouts)."""
    from dynamo_tpu.ops.pallas.prefill_attention import (
        paged_prefill_attention_pallas_folded,
    )

    for T, Hq, Hkv, start, seed in [
        (128, 4, 2, 0, 0), (256, 8, 2, 0, 1), (128, 4, 4, 57, 3), (128, 8, 4, 9, 4),
    ]:
        q, k, v, pt, pos = make_prefill_case(
            T=T, Hq=Hq, Hkv=Hkv, P=128, max_pages=100, start=start, seed=seed
        )
        ref = paged_prefill_attention(q, k, v, pt, pos)
        got = paged_prefill_attention_pallas_folded(q, k, v, pt, pos, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=2e-5,
            err_msg=f"T={T} Hq={Hq} Hkv={Hkv} start={start}",
        )


def test_pallas_lookahead_matches_reference():
    """Cross-program-prefetch kernel (r5 default): ragged lengths straddling
    the prefetch window W — some sequences fully inside it, some spilling
    into the tail double-buffer path — must match the XLA reference."""
    from dynamo_tpu.ops.pallas.paged_attention import (
        lookahead_window,
        paged_decode_attention_pallas_lookahead,
    )

    q, k, v, pt, pos = make_case()
    assert lookahead_window(4, 2, 16, 4) >= 1
    got = paged_decode_attention_pallas_lookahead(q, k, v, pt, pos, interpret=True)
    want = paged_decode_attention(q, k, v, pt, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_pallas_lookahead_ragged_and_long_tails():
    """Lengths from 1 token to many pages past the prefetch window, odd B
    (parity alternation), duplicated shapes across calls."""
    from dynamo_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas_lookahead,
    )

    rng = np.random.default_rng(7)
    B, Hq, Hkv, D, P, ps, max_pages = 5, 4, 2, 16, 64, 4, 12
    q = jnp.asarray(rng.standard_normal((B, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((P, ps, Hkv, D)), jnp.float32)
    pt = np.zeros((B, max_pages), np.int32)
    used = set([0])
    for b in range(B):
        for j in range(max_pages):
            p = int(rng.integers(1, P))
            while p in used:
                p = int(rng.integers(1, P))
            used.add(p)
            pt[b, j] = p
    # lengths: 1 token; exactly W pages; W pages + 1 token; deep tail; page-1
    positions = jnp.asarray([0, 2 * ps - 1, 2 * ps, 11 * ps - 1, ps - 1], jnp.int32)
    got = paged_decode_attention_pallas_lookahead(
        q, k, v, jnp.asarray(pt), positions, interpret=True
    )
    want = paged_decode_attention(q, k, v, jnp.asarray(pt), positions)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_pallas_lookahead_vmem_fallback():
    """A geometry whose prefetch window would blow the VMEM budget must fall
    back to perseq (same contract) rather than compile an oversized scratch."""
    from dynamo_tpu.ops.pallas import paged_attention as pa

    assert pa.lookahead_window(512, 32, 128, 2) == 0
    # budget-fitting case picks at least 1, capped at 4
    assert 1 <= pa.lookahead_window(128, 8, 128, 2) <= 4
