"""Prefill dispatch-ahead (EngineConfig.prefill_pipeline_depth): config
validation, backlog-aware chunk-bucket promotion, the prefill roofline floor
arithmetic, the StepAnatomy prefill plane, and token-identical parity of the
pipelined scheduler vs the strict reconcile-per-call baseline (greedy,
seeded, and int8-KV arms) plus cancel-mid-pipeline safety."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.utils.step_anatomy import (
    DEFAULT_MXU_TFLOPS,
    RooflineModel,
    StepAnatomy,
)


# ---------------- config ----------------


def test_pipeline_depth_validation():
    assert EngineConfig(model_id="tiny").prefill_pipeline_depth == 2
    assert EngineConfig(model_id="tiny", prefill_pipeline_depth=1) is not None
    with pytest.raises(ValueError):
        EngineConfig(model_id="tiny", prefill_pipeline_depth=0)


def test_chunk_len_for_backlog_promotion():
    cfg = EngineConfig(
        model_id="tiny", page_size=4, num_pages=256, max_model_len=1024,
        prefill_buckets=(16, 32, 64), prefill_flat_depth=128,
    )
    # flat-depth budget = 64*128 = 8192: at context depth 256 only the
    # 16-row bucket fits (16*272 <= 8192 < 32*288)
    assert cfg.chunk_len_for(256) == 16
    # a deep backlog (>= 2*top rows pending) doubles the budget: 32*288
    # now fits, 64*320 still doesn't — fewer, larger dispatches
    assert cfg.chunk_len_for(256, backlog_rows=128) == 32
    assert cfg.chunk_len_for(256, backlog_rows=127) == 16
    # no promotion past what the doubled budget allows
    assert cfg.chunk_len_for(256, backlog_rows=10_000) == 32


# ---------------- prefill floor arithmetic ----------------


def test_prefill_floor_hand_computed(monkeypatch):
    monkeypatch.delenv("DYNTPU_MXU_TFLOPS", raising=False)
    roof = RooflineModel(
        param_bytes=1_000_000, page_bytes=2048, page_size=16,
        hbm_bw=1e9, param_count=500_000,
    )
    # bytes bound: params + ceil(48/16)=3 pages; FLOP bound: 2*N*rows/MXU
    rows = 48
    bytes_floor = (1_000_000 + 3 * 2048) / 1e9
    flop_floor = 2.0 * 500_000 * rows / (DEFAULT_MXU_TFLOPS * 1e12)
    assert roof.prefill_floor_bytes(rows) == 1_000_000 + 3 * 2048
    assert roof.prefill_floor_seconds(rows) == pytest.approx(
        max(bytes_floor, flop_floor)
    )
    # a big enough model goes FLOP-bound; the env knob moves the bound
    big = RooflineModel(
        param_bytes=10, page_bytes=1, page_size=16,
        hbm_bw=1e15, param_count=10**12,
    )
    assert big.prefill_floor_seconds(512) == pytest.approx(
        2.0 * 10**12 * 512 / (DEFAULT_MXU_TFLOPS * 1e12)
    )
    monkeypatch.setenv("DYNTPU_MXU_TFLOPS", "100")
    big2 = RooflineModel(
        param_bytes=10, page_bytes=1, page_size=16,
        hbm_bw=1e15, param_count=10**12,
    )
    assert big2.prefill_floor_seconds(512) == pytest.approx(
        2.0 * 10**12 * 512 / 100e12
    )


def test_prefill_plane_accumulation_and_gauge():
    roof = RooflineModel(param_bytes=1000, page_bytes=10, page_size=4,
                         hbm_bw=1000.0, param_count=100)
    a = StepAnatomy(roofline=roof)
    assert a.prefill_roofline_fraction() is None  # no priced prefill yet
    assert a.prefill_fixed_ms() is None
    assert "dynamo_engine_prefill_roofline_fraction" not in a.render_metrics()
    rec = a.begin("prefill_packed")
    a.add_phase(rec, "host_prep", 0.001)
    a.add_phase(rec, "dispatch", 0.009)
    a.note_steps(rec, tokens=8, participants=2)
    a.note_prefill_floor(rec, 8)
    # floor = (1000 + 2*10) / 1000 B/s = 1.02 s over 0.010 s measured
    assert rec.floor_s == pytest.approx(1.02)
    assert a.prefill_roofline_fraction() == pytest.approx(1.02 / 0.010)
    assert a.prefill_fixed_ms() == pytest.approx(10.0)
    snap = a.snapshot()
    assert snap["prefill_roofline_frac"] == pytest.approx(102.0)
    assert snap["prefill_fixed_ms"] == pytest.approx(10.0)
    assert snap["prefill_host_frac"] == 1.0
    # the prefill floor must NOT pollute the decode roofline fraction
    assert a.roofline_fraction() is None
    text = a.render_metrics()
    assert "dynamo_engine_prefill_roofline_fraction" in text
    # /debug/steps record carries the per-dispatch floor
    assert rec.to_dict()["floor_ms"] == pytest.approx(1020.0)


# ---------------- scheduler parity: pipelined vs reconcile-per-call ----------


def _cfg(depth, **over):
    base = dict(
        model_id="tiny", page_size=4, num_pages=256, max_seqs=8,
        max_model_len=96, prefill_buckets=(8, 16, 32), prefill_lanes=2,
        decode_steps=4, pipeline_depth=2, prefill_pipeline_depth=depth,
    )
    base.update(over)
    return EngineConfig(**base)


async def _serve_tokens(cfg, prompts, sampling_kw):
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    eng = AsyncJaxEngine(cfg)
    await eng.start()
    try:
        toks = {i: [] for i in range(len(prompts))}

        async def one(i):
            req = EngineRequest(
                request_id=f"p-{i}", token_ids=list(prompts[i]),
                sampling=SamplingParams(max_tokens=8, ignore_eos=True,
                                        **sampling_kw),
            )
            async for out in eng.generate(req):
                if out.token is not None:
                    toks[i].append(out.token)

        await asyncio.gather(*[one(i) for i in range(len(prompts))])
        stalls = eng.scheduler.stage.prefill_stalls
        calls = eng.scheduler.stage.prefill_calls
        return toks, stalls, calls
    finally:
        await eng.shutdown()


@pytest.mark.parametrize(
    "sampling_kw,over",
    [
        ({"temperature": 0.0}, {}),  # greedy
        ({"temperature": 0.8, "seed": 7}, {}),  # seeded stochastic
        ({"temperature": 0.0}, {"kv_cache_dtype": "int8"}),  # int8 KV
    ],
    ids=["greedy", "seeded", "int8_kv"],
)
def test_pipelined_token_parity(sampling_kw, over):
    """Dispatch-ahead is a scheduling change only: depth=2 must produce the
    exact token streams of the strict depth=1 baseline — greedy, seeded
    (per-request deterministic stream), and quantized-KV arms alike."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, 200, 24).tolist() for _ in range(6)]

    async def both():
        t1, s1, c1 = await _serve_tokens(_cfg(1, **over), prompts, sampling_kw)
        t2, s2, c2 = await _serve_tokens(_cfg(2, **over), prompts, sampling_kw)
        return t1, s1, c1, t2, s2, c2

    t1, s1, c1, t2, s2, c2 = asyncio.run(both())
    for i in range(len(prompts)):
        assert t1[i], f"request {i} produced no tokens"
        assert t1[i] == t2[i], f"request {i}: {t1[i]} != {t2[i]}"
    # the burst packs multiple calls (2 lanes over 6 prompts), so the strict
    # arm must have paid forced stalls the pipelined arm avoids
    assert c1 >= 2 and c2 >= 2
    assert s1 > s2, f"depth=1 stalls {s1} not above depth=2 stalls {s2}"


def test_cancel_mid_pipeline():
    """Cancelling requests while packed prefills ride unreconciled must not
    wedge the gate or corrupt survivors: stale in-flight entries skip
    finished sequences, remaining requests complete, and the engine serves
    fresh traffic afterwards."""
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 200, 24).tolist() for _ in range(6)]

    async def run():
        eng = AsyncJaxEngine(_cfg(2))
        await eng.start()
        try:
            done = {}

            async def one(i):
                req = EngineRequest(
                    request_id=f"c-{i}", token_ids=list(prompts[i % len(prompts)]),
                    sampling=SamplingParams(temperature=0.0, max_tokens=8,
                                            ignore_eos=True),
                )
                toks = []
                async for out in eng.generate(req):
                    if out.token is not None:
                        toks.append(out.token)
                done[i] = toks

            tasks = [asyncio.create_task(one(i)) for i in range(6)]
            # let the burst enter the scheduler, then kill half the clients
            # while their prefills are (or were just) in flight
            await asyncio.sleep(0)
            for t in tasks[::2]:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            # survivors completed with output
            for i in (1, 3, 5):
                assert done.get(i), f"survivor {i} produced no tokens"
            # the engine still serves fresh traffic (slots/pages released)
            await one(99)
            assert done[99]
        finally:
            await eng.shutdown()

    asyncio.run(run())
