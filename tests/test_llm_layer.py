"""LLM protocol layer: backend detokenization + stop jailing, SSE codec,
aggregators, preprocessor validation."""

import asyncio

import pytest

from dynamo_tpu.engine.scheduler import EngineRequest, StepOutput
from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.protocols.aggregator import aggregate_chat_stream
from dynamo_tpu.llm.protocols.common import PreprocessedRequest
from dynamo_tpu.llm.protocols.openai import (
    ChatCompletionRequest,
    CompletionRequest,
    ProtocolError,
)
from dynamo_tpu.llm.protocols.sse import SseDecoder, encode_data, encode_done
from dynamo_tpu.llm.tokenizer import ByteTokenizer, DecodeStream


class ScriptedEngine:
    """Emits a fixed token list, one StepOutput per token."""

    def __init__(self, tokens, finish_reason="length"):
        self.tokens = tokens
        self.finish_reason = finish_reason

    async def generate(self, request: EngineRequest):
        for i, tok in enumerate(self.tokens):
            last = i == len(self.tokens) - 1
            yield StepOutput(
                request_id=request.request_id,
                token=tok,
                finished=last,
                finish_reason=self.finish_reason if last else None,
            )


def run_backend(tokens, stop=(), finish="length"):
    tok = ByteTokenizer()
    backend = Backend(ScriptedEngine(tokens, finish), tok)
    req = PreprocessedRequest(
        request_id="t1", token_ids=tok.encode("hi"), stop_strings=tuple(stop)
    )

    async def go():
        outs = []
        async for o in backend.generate(req):
            outs.append(o)
        return outs

    return asyncio.run(go())


def test_backend_detokenizes_text():
    outs = run_backend(list(b"hello"))
    assert "".join(o.text for o in outs) == "hello"
    assert outs[-1].finish_reason == "length"
    assert outs[-1].cumulative_tokens == 5


def test_backend_stop_string_truncates():
    outs = run_backend(list(b"hello world and more"), stop=["world"])
    assert "".join(o.text for o in outs) == "hello "
    assert outs[-1].finish_reason == "stop"


def test_backend_stop_prefix_jail_released_at_eos():
    # 'wor' is a prefix of the stop string but never completes -> must be emitted
    outs = run_backend(list(b"hello wor"), stop=["world"])
    assert "".join(o.text for o in outs) == "hello wor"
    assert outs[-1].finish_reason == "length"


def test_backend_multibyte_utf8_boundary():
    # é = 0xC3 0xA9 split across steps must not emit replacement chars
    outs = run_backend(list("café".encode("utf-8")))
    text = "".join(o.text for o in outs)
    assert text == "café"
    assert "�" not in text


def test_decode_stream_waits_for_codepoint():
    tok = ByteTokenizer()
    ds = DecodeStream(tok)
    assert ds.step(0xC3) is None
    assert ds.step(0xA9) == "é"


def test_sse_roundtrip():
    dec = SseDecoder()
    frames = encode_data({"a": 1}) + encode_data("x") + encode_done()
    msgs = list(dec.feed(frames))
    assert msgs[0].json() == {"a": 1}
    assert msgs[1].data == "x"
    assert msgs[2].is_done


def test_sse_incremental_feed():
    dec = SseDecoder()
    frames = encode_data({"k": "v"})
    out = []
    for i in range(len(frames)):
        out.extend(dec.feed(frames[i : i + 1]))
    assert len(out) == 1 and out[0].json() == {"k": "v"}


def test_aggregator_chat():
    async def chunks():
        yield {"id": "c1", "created": 1, "model": "m",
               "choices": [{"index": 0, "delta": {"role": "assistant", "content": "he"}}]}
        yield {"id": "c1", "created": 1, "model": "m",
               "choices": [{"index": 0, "delta": {"content": "llo"}}]}
        yield {"id": "c1", "created": 1, "model": "m",
               "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}],
               "usage": {"prompt_tokens": 2, "completion_tokens": 2, "total_tokens": 4}}

    out = asyncio.run(aggregate_chat_stream(chunks()))
    assert out["object"] == "chat.completion"
    assert out["choices"][0]["message"]["content"] == "hello"
    assert out["choices"][0]["finish_reason"] == "stop"
    assert out["usage"]["total_tokens"] == 4


def test_aggregator_chat_merges_fragmented_tool_calls():
    """Spec-conformant streams split one tool call across chunks (id/name once,
    arguments in pieces, all under the same index) — they must merge."""

    async def chunks():
        yield {"id": "c1", "created": 1, "model": "m",
               "choices": [{"index": 0, "delta": {"role": "assistant", "tool_calls": [
                   {"index": 0, "id": "call_1", "type": "function",
                    "function": {"name": "get_weather", "arguments": "{\"ci"}}]}}]}
        yield {"id": "c1", "created": 1, "model": "m",
               "choices": [{"index": 0, "delta": {"tool_calls": [
                   {"index": 0, "function": {"arguments": "ty\": \"SF\"}"}}]}}]}
        yield {"id": "c1", "created": 1, "model": "m",
               "choices": [{"index": 0, "delta": {"tool_calls": [
                   {"index": 1, "id": "call_2", "type": "function",
                    "function": {"name": "get_time", "arguments": "{}"}}]}}]}
        yield {"id": "c1", "created": 1, "model": "m",
               "choices": [{"index": 0, "delta": {}, "finish_reason": "tool_calls"}]}

    out = asyncio.run(aggregate_chat_stream(chunks()))
    calls = out["choices"][0]["message"]["tool_calls"]
    assert len(calls) == 2
    assert calls[0] == {"id": "call_1", "type": "function",
                        "function": {"name": "get_weather", "arguments": "{\"city\": \"SF\"}"}}
    assert calls[1]["function"]["name"] == "get_time"
    assert out["choices"][0]["message"]["content"] is None
    assert out["choices"][0]["finish_reason"] == "tool_calls"


def test_protocol_validation():
    with pytest.raises(ProtocolError):
        ChatCompletionRequest.from_dict({"messages": []})
    with pytest.raises(ProtocolError):
        ChatCompletionRequest.from_dict({"messages": [{"role": "user", "content": "x"}], "n": 2})
    with pytest.raises(ProtocolError):
        ChatCompletionRequest.from_dict(
            {"messages": [{"role": "user", "content": "x"}], "temperature": -1}
        )
    with pytest.raises(ProtocolError):
        CompletionRequest.from_dict({})
    r = ChatCompletionRequest.from_dict(
        {"messages": [{"role": "user", "content": "x"}], "stop": "end",
         "nvext": {"ignore_eos": True, "top_k": 5}}
    )
    assert r.stop == ["end"] and r.ext.ignore_eos and r.ext.top_k == 5


def test_preprocessor_chat_and_limits():
    tok = ByteTokenizer()
    pre = OpenAIPreprocessor(tok, "m", max_model_len=64)
    req = ChatCompletionRequest.from_dict(
        {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 1000,
         "temperature": 0}
    )
    p, ann = pre.preprocess_chat(req)
    assert p.sampling.max_tokens + len(p.token_ids) <= 64
    assert p.sampling.temperature == 0.0
    assert p.eos_token_ids == (ByteTokenizer.EOS,)

    long_req = ChatCompletionRequest.from_dict(
        {"messages": [{"role": "user", "content": "x" * 100}]}
    )
    with pytest.raises(ProtocolError):
        pre.preprocess_chat(long_req)


def test_model_card_survives_owning_worker_death():
    """Two workers serve one model; the card is lease-tied to worker A. When
    A dies (lease revoked), the card disappears — and worker B's refresh loop
    restores it within one interval (the reference's TTL-bucket semantics)."""
    import asyncio

    from dynamo_tpu.cplane.broker import Broker
    from dynamo_tpu.cplane.client import CplaneClient
    from dynamo_tpu.llm.model_registry import (
        ModelEntry,
        ModelRegistration,
        list_models,
    )

    async def body():
        broker = Broker()
        port = await broker.start()
        a = await CplaneClient(f"127.0.0.1:{port}").connect()
        b = await CplaneClient(f"127.0.0.1:{port}").connect()
        lease_a = await a.lease_create(ttl=5.0)
        lease_b = await b.lease_create(ttl=5.0)
        entry = ModelEntry(name="m", endpoint="dyn://ns.c.generate")
        reg_a = await ModelRegistration(a, entry, lease_a.lease_id, interval=0.2).start()
        reg_b = await ModelRegistration(b, entry, lease_b.lease_id, interval=0.2).start()
        assert [m.name for m in await list_models(b)] == ["m"]

        # make B the current owner deterministically (last re-put wins), then
        # watch for a blip: A's death must NOT delete the key B owns
        from dynamo_tpu.llm.model_registry import register_model

        await register_model(b, entry, lease_id=lease_b.lease_id)
        deletes = []
        watcher = await b.kv_get_and_watch_prefix("models/")

        async def record():
            async for ev in watcher.events():
                if ev.kind == "delete":
                    deletes.append(ev.key)

        rec = asyncio.get_running_loop().create_task(record())
        await reg_a.stop(unregister=False)
        await a.close()
        await asyncio.sleep(0.8)  # A's lease reaped on conn close
        models = await list_models(b)
        assert [m.name for m in models] == ["m"], "card lost after co-worker death"
        assert deletes == [], f"shared card blipped: {deletes}"
        rec.cancel()
        await watcher.stop()

        # last worker gone (clean stop unregisters): the card must not be a
        # permanent ghost in the durable KV
        await reg_b.stop()
        await b.close()
        c = await CplaneClient(f"127.0.0.1:{port}").connect()
        assert await list_models(c) == []
        await c.close()
        await broker.stop()

    asyncio.new_event_loop().run_until_complete(asyncio.wait_for(body(), 30))
