"""Multi-LoRA multiplexing: gathered adapter kernels serve M fine-tunes in
one batch.

Correctness bars:
  - a mixed-adapter batch must be token-identical to each adapter served
    alone (same engine geometry => identical trace, so this is exact), and —
    at full precision — token-identical to merged-weight serving
    ``W' = W + scale * A @ B`` (the algebra claim; bf16 merges round
    W+delta differently by construction, so that arm asserts teacher-forced
    argmax agreement instead)
  - LRU eviction/hot-swap under churn never perturbs an in-flight sequence
    (pinned slots) and reloads reproduce identical outputs
  - lora-salted block identity never cross-hits between adapters or the
    base model — locally (radix/allocator) and over the fleet pull path
"""

import asyncio
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest
from dynamo_tpu.lora import init_lora_pool, lora_uid, merge_adapter_into_params, module_dims, parse_adapter_specs, synth_adapter

from tests.test_engine import tiny_engine_config


def _req(rid, prompt, n=8, lora="", temperature=0.0, holder="", blocks=0):
    return EngineRequest(
        request_id=rid,
        token_ids=list(prompt),
        sampling=SamplingParams(temperature=temperature, max_tokens=n, ignore_eos=True),
        lora_name=lora,
        kv_holder_addr=holder,
        kv_holder_blocks=blocks,
    )


async def _collect(engine, req):
    toks, cached = [], 0
    async for out in engine.generate(req):
        if out.token is not None:
            toks.append(out.token)
        cached = max(cached, out.cached_tokens)
    return toks, cached


def _lora_engine(**over):
    from dynamo_tpu.engine.engine import AsyncJaxEngine

    defaults = dict(
        lora_adapters=("a1=random:7", "a2=random:8"), max_loras=2, lora_rank=4
    )
    defaults.update(over)
    return AsyncJaxEngine(tiny_engine_config(**defaults))


PROMPT = [3, 1, 4, 1, 5, 9, 2]


# ---------------- spec parsing / identity salts ----------------


def test_parse_adapter_specs():
    specs = parse_adapter_specs(("a1", "b=/tmp/x", "c=random:3"))
    assert list(specs) == ["a1", "b", "c"]
    assert specs["b"] == "/tmp/x"
    assert specs["c"] == "random:3"
    assert specs["a1"].startswith("random:")  # bare name = deterministic synth
    with pytest.raises(ValueError):
        parse_adapter_specs(("dup", "dup"))
    with pytest.raises(ValueError):
        parse_adapter_specs(("bad name",))


def test_lora_uid_stable_and_nonzero():
    assert lora_uid("a1") == lora_uid("a1")
    assert lora_uid("a1") != lora_uid("a2")
    assert lora_uid("a1") != 0


def test_salted_token_sequence_isolates_chains():
    from dynamo_tpu.llm.tokens import TokenSequence, compute_block_hash_for_seq

    toks = list(range(16))
    base = TokenSequence(toks, 4)
    s1 = TokenSequence(toks, 4, salt=lora_uid("a1"))
    s2 = TokenSequence(toks, 4, salt=lora_uid("a2"))
    same = TokenSequence(toks, 4, salt=lora_uid("a1"))
    # every chained hash diverges between salts, and the salted chain is
    # reproducible (the fleet pull path keys on these)
    for a, b in ((base, s1), (s1, s2)):
        assert all(
            x.sequence_hash != y.sequence_hash for x, y in zip(a.blocks, b.blocks)
        )
    assert [b.sequence_hash for b in s1.blocks] == [b.sequence_hash for b in same.blocks]
    # first block keeps parent None (chain structure unchanged)
    assert s1.blocks[0].parent_sequence_hash is None
    # router identity: only the FIRST chunk hash salts (deeper chunks are
    # only reachable through it in the radix tree)
    h0 = compute_block_hash_for_seq(toks, 4)
    h1 = compute_block_hash_for_seq(toks, 4, lora_uid("a1"))
    assert h0[0] != h1[0] and h0[1:] == h1[1:]


def test_allocator_salted_prefix_never_cross_hits():
    from dynamo_tpu.engine.page_table import PageAllocator

    alloc = PageAllocator(32, 4)
    toks = list(range(12))
    salt = lora_uid("a1")
    cached, _ = alloc.allocate_sequence("s1", toks, salt=salt)
    assert cached == 0
    alloc.commit_prefilled("s1", len(toks))
    alloc.free_sequence("s1")
    # base identity misses the adapter's cached blocks entirely
    assert alloc.lookup_prefix(toks) == 0
    cached_base, _ = alloc.allocate_sequence("s2", toks)
    assert cached_base == 0
    alloc.free_sequence("s2")
    # same adapter hits (last block held back so the final token prefills)
    assert alloc.lookup_prefix(toks, salt=salt) == 12
    cached_same, _ = alloc.allocate_sequence("s3", toks, salt=salt)
    assert cached_same == 8


def test_radix_salt_isolation():
    from dynamo_tpu.llm.kv_events import KvCacheEvent, StoredBlock
    from dynamo_tpu.llm.kv_router.indexer import KvIndexer, RouterEvent
    from dynamo_tpu.llm.tokens import TokenSequence

    idx = KvIndexer(4, use_native=False)
    toks = list(range(12))
    salt = lora_uid("a1")
    ts = TokenSequence(toks, 4, salt=salt)
    parent = None
    for b in ts.blocks:
        idx.apply_event(RouterEvent(worker_id=1, event=KvCacheEvent.stored(
            parent_hash=parent,
            blocks=[StoredBlock(block_hash=b.sequence_hash, tokens_hash=b.block_hash)],
        )))
        parent = b.sequence_hash
    # adapter-salted query matches all 3 blocks; base and other-adapter
    # queries match none (the chains diverge at the radix root)
    assert idx.find_matches_for_request(toks, salt=salt).scores == {1: 3}
    assert idx.find_matches_for_request(toks).scores == {}
    assert idx.find_matches_for_request(toks, salt=lora_uid("a2")).scores == {}


# ---------------- gathered kernel algebra (model level) ----------------


def _manual_chain(model, params, prompt, steps, lora=None, lora_id=0):
    """Greedy chain through model.prefill + model.decode with manual pages
    (B=1 at slot 0 of the decode batch)."""
    ps, num_pages = 4, 32
    kv = jax.tree.map(jnp.asarray, model.init_kv_cache(num_pages, ps))
    mp = 16
    table = np.zeros(mp, np.int32)
    need = -(-(len(prompt) + steps) // ps)
    table[:need] = np.arange(1, need + 1)
    T = 16
    toks = np.zeros(T, np.int32)
    toks[: len(prompt)] = prompt
    lkw = {}
    if lora is not None:
        lkw = dict(lora=lora, lora_id=jnp.int32(lora_id))
    logits, kv = model.prefill(
        params, kv, jnp.asarray(toks), jnp.arange(T, dtype=jnp.int32),
        jnp.asarray(table), jnp.arange(T) < len(prompt),
        jnp.int32(len(prompt) - 1), **lkw,
    )
    out = [int(jnp.argmax(logits))]
    B = 2  # lane 1 idle, to mirror a real (partially inactive) batch
    tables = np.zeros((B, mp), np.int32)
    tables[0] = table
    for i in range(steps - 1):
        pos = len(prompt) + i
        dkw = {}
        if lora is not None:
            dkw = dict(lora=lora, lora_ids=jnp.asarray([lora_id, 0], jnp.int32))
        logits, kv = model.decode(
            params, kv,
            jnp.asarray([out[-1], 0], jnp.int32),
            jnp.asarray([pos, 0], jnp.int32),
            jnp.asarray(tables),
            jnp.asarray([True, False]),
            **dkw,
        )
        out.append(int(jnp.argmax(logits[0])))
    return out


def _pool_with(model, adapters, rank=4):
    """Device pool with the given {slot: (seed)} synthetic adapters loaded."""
    pool = jax.tree.map(jnp.asarray, init_lora_pool(model, max_loras=len(adapters), rank=rank))
    for slot, seed in adapters.items():
        tree, scale = synth_adapter(model.config, rank, seed)
        mods = {
            m: {
                "a": pool["mods"][m]["a"].at[:, slot].set(tree[m]["a"]),
                "b": pool["mods"][m]["b"].at[:, slot].set(tree[m]["b"]),
            }
            for m in pool["mods"]
        }
        pool = {"scales": pool["scales"].at[slot].set(scale), "mods": mods}
    return pool


def test_merged_weight_parity_full_precision():
    """f32: the gathered adapter pass is token-identical to merged-weight
    serving, per adapter, over a greedy chain — the exact-algebra claim."""
    from dynamo_tpu.models.registry import load_model

    model, params = load_model("tiny")
    pool = _pool_with(model, {1: 7, 2: 8})
    for slot, seed in ((1, 7), (2, 8)):
        tree, scale = synth_adapter(model.config, 4, seed)
        merged = jax.tree.map(jnp.asarray, merge_adapter_into_params(model, params, tree, scale))
        want = _manual_chain(model, merged, PROMPT, 12)
        got = _manual_chain(model, params, PROMPT, 12, lora=pool, lora_id=slot)
        assert got == want, f"adapter slot {slot}: {got} != merged {want}"
    # slot 0 (zero adapter) == base exactly
    base = _manual_chain(model, params, PROMPT, 12)
    via_pool = _manual_chain(model, params, PROMPT, 12, lora=pool, lora_id=0)
    assert via_pool == base


def test_merged_weight_agreement_bf16():
    """bf16: merging rounds W+delta once while the gathered pass rounds W
    and delta separately, so exact token identity is not the claim —
    teacher-forced argmax agreement is."""
    from dynamo_tpu.models.registry import load_model

    model, params = load_model('tiny:{"dtype": "bf16"}')
    pool = _pool_with(model, {1: 7})
    tree, scale = synth_adapter(model.config, 4, 7)
    merged = jax.tree.map(jnp.asarray, merge_adapter_into_params(model, params, tree, scale))
    forced = _manual_chain(model, merged, PROMPT, 24)
    # teacher-forced: feed the merged arm's tokens into the lora arm and
    # compare each step's argmax
    ps, num_pages, mp, T = 4, 32, 16, 32
    kv = jax.tree.map(jnp.asarray, model.init_kv_cache(num_pages, ps))
    table = np.zeros(mp, np.int32)
    need = -(-(len(PROMPT) + 24) // ps)
    table[:need] = np.arange(1, need + 1)
    toks = np.zeros(T, np.int32)
    toks[: len(PROMPT)] = PROMPT
    logits, kv = model.prefill(
        params, kv, jnp.asarray(toks), jnp.arange(T, dtype=jnp.int32),
        jnp.asarray(table), jnp.arange(T) < len(PROMPT),
        jnp.int32(len(PROMPT) - 1), lora=pool, lora_id=jnp.int32(1),
    )
    agree = [int(jnp.argmax(logits)) == forced[0]]
    tables = np.zeros((1, mp), np.int32)
    tables[0] = table
    for i in range(23):
        pos = len(PROMPT) + i
        logits, kv = model.decode(
            params, kv, jnp.asarray([forced[i]], jnp.int32),
            jnp.asarray([pos], jnp.int32), jnp.asarray(tables),
            jnp.asarray([True]), lora=pool, lora_ids=jnp.asarray([1], jnp.int32),
        )
        agree.append(int(jnp.argmax(logits[0])) == forced[i + 1])
    assert sum(agree) / len(agree) >= 0.9, f"agreement {sum(agree)}/{len(agree)}"


# ---------------- engine e2e: mixed batch == each adapter alone ----------------


@pytest.mark.slow
@pytest.mark.parametrize("quantize", [None, "int8_wo"], ids=["fp", "int8"])
def test_engine_mixed_batch_token_identical_to_alone(quantize):
    """A mixed-adapter concurrent batch (base + a1 + a2 + a1) must emit
    exactly what each request gets served ALONE on a fresh identical engine
    — the same decode-window trace runs in both cases, so any divergence
    means the gathered kernel leaked across lanes. int8 base weights ride
    the same gate (the delta sits on top of qlinear unchanged)."""
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 250, 9).tolist() for _ in range(4)]
    loras = ["", "a1", "a2", "a1"]

    async def body():
        mixed_eng = _lora_engine(quantize=quantize)
        await mixed_eng.start()
        try:
            mixed = await asyncio.gather(*[
                _collect(mixed_eng, _req(f"m{i}", prompts[i], n=10, lora=loras[i]))
                for i in range(4)
            ])
        finally:
            await mixed_eng.shutdown()
        alone_eng = _lora_engine(quantize=quantize)
        await alone_eng.start()
        try:
            alone = []
            for i in range(4):
                alone.append(await _collect(
                    alone_eng, _req(f"s{i}", prompts[i], n=10, lora=loras[i])
                ))
        finally:
            await alone_eng.shutdown()
        for i in range(4):
            assert mixed[i][0] == alone[i][0], (
                f"lane {i} (lora={loras[i]!r}): mixed {mixed[i][0]} != "
                f"alone {alone[i][0]}"
            )
        # different adapters actually produce different text (the deltas are
        # live, not zero)
        assert len({tuple(mixed[i][0]) for i in (0, 1, 2)}) >= 2

    asyncio.run(body())


@pytest.mark.slow
def test_engine_mixed_equals_merged_full_precision():
    """End-to-end: the ENGINE's mixed-adapter greedy output equals the
    model-level merged-weight chain (f32) — ties the serving stack to the
    algebra claim, not just lane isolation."""
    from dynamo_tpu.models.registry import load_model

    async def body():
        eng = _lora_engine()
        await eng.start()
        try:
            outs = await asyncio.gather(
                _collect(eng, _req("a", PROMPT, n=12, lora="a1")),
                _collect(eng, _req("b", PROMPT, n=12, lora="a2")),
            )
        finally:
            await eng.shutdown()
        model, params = load_model("tiny")
        for (toks, _), seed in zip(outs, (7, 8)):
            tree, scale = synth_adapter(model.config, 4, seed)
            merged = jax.tree.map(
                jnp.asarray, merge_adapter_into_params(model, params, tree, scale)
            )
            want = _manual_chain(model, merged, PROMPT, 12)
            assert toks == want, f"engine {toks} != merged chain {want}"

    asyncio.run(body())


# ---------------- LRU eviction / hot swap under churn ----------------


@pytest.mark.slow
def test_lru_eviction_hot_swap_coherent():
    """4 adapters through 2 device slots: serving cycles evict/reload via
    LRU; a reloaded adapter reproduces its exact earlier output, and the
    eviction counter proves slots actually churned."""

    async def body():
        eng = _lora_engine(
            lora_adapters=("a1=random:1", "a2=random:2", "a3=random:3", "a4=random:4"),
            max_loras=2,
        )
        await eng.start()
        try:
            first = {}
            for name in ("a1", "a2", "a3", "a4"):
                toks, _ = await _collect(eng, _req(f"f-{name}", PROMPT, lora=name))
                first[name] = toks
            store = eng.runner.lora_store
            assert store.evictions >= 2  # a3/a4 displaced a1/a2
            assert store.resident_count == 2
            # reload round: every adapter reproduces its first output after
            # being hot-swapped back in (host copies cached; KV prefix for
            # evicted adapters may or may not survive — either way greedy
            # output is identical)
            for name in ("a1", "a2", "a3", "a4"):
                toks, _ = await _collect(eng, _req(f"r-{name}", PROMPT, lora=name))
                assert toks == first[name], f"{name} changed after hot-swap"
            snap = store.metrics_snapshot()
            assert snap["evictions"] >= 4
            assert snap["loads"] == 4  # host loads happen once per adapter
        finally:
            await eng.shutdown()

    asyncio.run(body())


@pytest.mark.slow
def test_inflight_sequence_pins_its_slot():
    """An in-flight sequence's adapter slot is never hot-swapped under it:
    with ONE device slot, a long a1 stream runs while a2/a3 requests queue —
    they wait for the pin to release (no eviction mid-flight), then serve,
    and a1's output matches an uncontended run."""

    async def body():
        eng = _lora_engine(
            lora_adapters=("a1=random:1", "a2=random:2", "a3=random:3"),
            max_loras=1,
        )
        await eng.start()
        try:
            results = await asyncio.gather(
                _collect(eng, _req("long-a1", PROMPT, n=24, lora="a1")),
                _collect(eng, _req("q-a2", PROMPT, n=6, lora="a2")),
                _collect(eng, _req("q-a3", PROMPT, n=6, lora="a3")),
            )
            assert all(len(t) for t, _ in results)
        finally:
            await eng.shutdown()
        ref = _lora_engine(lora_adapters=("a1=random:1",), max_loras=1)
        await ref.start()
        try:
            want, _ = await _collect(ref, _req("ref-a1", PROMPT, n=24, lora="a1"))
        finally:
            await ref.shutdown()
        assert results[0][0] == want, "pinned slot was disturbed mid-flight"

    asyncio.run(body())


@pytest.mark.slow
def test_spec_verify_mixed_adapters_token_identical():
    """Speculative (n-gram) verify rounds carry each slot's adapter id into
    the shared multi-query pass: a mixed-adapter spec engine must emit
    exactly what the classic mixed-adapter engine emits (greedy), with
    drafts actually accepted (the repetitive prompt guarantees proposals)."""
    prompt = [7, 8, 9, 7, 8, 9, 7, 8]

    async def run_all(**over):
        eng = _lora_engine(**over)
        await eng.start()
        try:
            outs = await asyncio.gather(
                _collect(eng, _req("r1", prompt, n=16, lora="a1")),
                _collect(eng, _req("r2", prompt, n=16, lora="a2")),
                _collect(eng, _req("r0", prompt, n=16)),
            )
            accepted = eng.scheduler.stage.spec_accepted
        finally:
            await eng.shutdown()
        return [t for t, _ in outs], accepted

    async def body():
        spec, accepted = await run_all(speculative="ngram:3")
        classic, _ = await run_all()
        assert spec == classic, f"spec {spec} != classic {classic}"
        assert accepted > 0, "no drafts accepted — the spec path never engaged"

    asyncio.run(body())


def test_unknown_adapter_fails_request_not_engine():
    async def body():
        eng = _lora_engine()
        await eng.start()
        try:
            req = _req("bad", PROMPT, lora="nope")
            outs = []
            async for out in eng.generate(req):
                outs.append(out)
            assert outs[-1].finish_reason == "error"
            # the engine keeps serving
            toks, _ = await _collect(eng, _req("ok", PROMPT, lora="a1"))
            assert len(toks) == 8
        finally:
            await eng.shutdown()

    asyncio.run(body())


# ---------------- salted prefix: engine + fleet pull ----------------


@pytest.mark.slow
def test_engine_prefix_cache_no_cross_adapter_hit():
    """Same token prefix, different adapter => cached_tokens 0; same adapter
    repeat => real prefix hit. The salted chained hash is what keeps an
    adapter's KV (delta-bearing k/v) from serving another adapter."""
    prompt = list(range(1, 25))  # 6 full blocks at page_size 4

    async def body():
        eng = _lora_engine()
        await eng.start()
        try:
            _, cached0 = await _collect(eng, _req("b0", prompt, n=2))
            assert cached0 == 0
            _, c_a1 = await _collect(eng, _req("a1-first", prompt, n=2, lora="a1"))
            assert c_a1 == 0  # base prefix must NOT serve the adapter
            _, c_a1b = await _collect(eng, _req("a1-again", prompt, n=2, lora="a1"))
            assert c_a1b > 0  # same adapter: genuine hit
            _, c_a2 = await _collect(eng, _req("a2-first", prompt, n=2, lora="a2"))
            assert c_a2 == 0  # sibling adapter: no cross-hit
            _, c_base = await _collect(eng, _req("b1", prompt, n=2))
            assert c_base > 0  # base still hits base
        finally:
            await eng.shutdown()

    asyncio.run(body())


@pytest.mark.slow
def test_fleet_fetch_salted_no_cross_hit():
    """Fleet pull path: a holder that cached an ADAPTER's prefix serves a
    peer's request for the SAME adapter (hit, token-identical), while a
    BASE request for the same tokens gets a clean fallback (the salted
    hashes simply don't exist on the holder)."""
    from dynamo_tpu.disagg.prefix_fetch import KvPullServer, PrefixFetchClient

    prompt = list(range(1, 25))

    async def body():
        holder = _lora_engine()
        await holder.start()
        puller = _lora_engine()
        await puller.start()
        srv = None
        try:
            expected, _ = await _collect(holder, _req("seed", prompt, lora="a1"))
            srv = await KvPullServer(holder, host="127.0.0.1").start()
            puller.attach_prefix_fetch(
                PrefixFetchClient(asyncio.get_running_loop(), timeout_s=30.0)
            )
            got, cached = await _collect(puller, _req(
                "pull", prompt, lora="a1", holder=srv.address, blocks=6
            ))
            assert got == expected
            assert cached > 0
            assert puller.scheduler.prefix_fetch_hits == 1
            # base request, same tokens: the holder has no UNSALTED blocks
            # for this prompt -> gone -> recompute fallback, correct output
            base_got, base_cached = await _collect(puller, _req(
                "pull-base", prompt, holder=srv.address, blocks=6
            ))
            assert base_cached == 0
            assert puller.scheduler.prefix_fetch_fallbacks == 1
            assert base_got != expected  # adapter delta is live
        finally:
            if srv is not None:
                await srv.stop()
            await holder.shutdown()
            await puller.shutdown()

    asyncio.run(body())


# ---------------- satellite: prefill-worker fleet pull ----------------


@pytest.mark.slow
def test_prefill_worker_pulls_prefix_before_recompute():
    """disagg prefill path: sync_remote_prefill with a router-attached
    holder pulls the prefix over the dataplane instead of recomputing it —
    same first token, fewer locally prefilled rows, counters bumped; a dead
    holder degrades to recompute."""
    from dynamo_tpu.disagg.prefix_fetch import KvPullServer, PrefixFetchClient
    from dynamo_tpu.llm.remote_prefill import RemotePrefillRequest

    prompt = list(range(1, 25))

    def _engine():
        from dynamo_tpu.engine.engine import AsyncJaxEngine

        return AsyncJaxEngine(tiny_engine_config())

    async def body():
        holder = _engine()
        await holder.start()
        pre_a = _engine()
        await pre_a.start()
        pre_b = _engine()
        await pre_b.start()
        srv = None
        try:
            await _collect(holder, _req("seed", prompt, n=2))
            srv = await KvPullServer(holder, host="127.0.0.1").start()
            loop = asyncio.get_running_loop()
            pre_a.attach_prefix_fetch(PrefixFetchClient(loop, timeout_s=30.0))
            pre_b.attach_prefix_fetch(PrefixFetchClient(loop, timeout_s=2.0))

            def rp(holder_addr, blocks):
                return RemotePrefillRequest(
                    request_id="rp1", token_ids=list(prompt),
                    kv_holder_addr=holder_addr, kv_holder_blocks=blocks,
                )

            # pull arm
            result_a, _ = await pre_a.run_on_engine(
                lambda: pre_a.sync_remote_prefill(rp(srv.address, 6))
            )
            assert pre_a.scheduler.prefix_fetch_hits == 1
            assert pre_a.scheduler.prefix_fetch_blocks == 5  # (24-1)//4
            rows_a = pre_a.scheduler.stage.prefill_rows
            # recompute arm (no holder)
            result_b, _ = await pre_b.run_on_engine(
                lambda: pre_b.sync_remote_prefill(rp("", 0))
            )
            rows_b = pre_b.scheduler.stage.prefill_rows
            assert result_a.first_token == result_b.first_token
            assert rows_a < rows_b  # the pulled prefix skipped recompute
            # dead holder: timeout -> recompute, never an error
            pre_b.scheduler.allocator = pre_b.allocator  # no-op, clarity
            result_c, _ = await pre_b.run_on_engine(
                lambda: pre_b.sync_remote_prefill(
                    RemotePrefillRequest(
                        request_id="rp2",
                        token_ids=[t + 1 for t in prompt],
                        kv_holder_addr="127.0.0.1:1",  # nothing listens
                        kv_holder_blocks=6,
                    )
                )
            )
            assert result_c.first_token >= 0
            assert pre_b.scheduler.prefix_fetch_fallbacks >= 1
        finally:
            if srv is not None:
                await srv.stop()
            await holder.shutdown()
            await pre_a.shutdown()
            await pre_b.shutdown()

    asyncio.run(body())


# ---------------- HTTP edge: adapter names + model_not_found ----------------


@pytest.fixture(scope="module")
def lora_server():
    """Colocated HTTP service with a LoRA-enabled tiny engine: base pipeline
    plus one ModelPipeline per adapter (the run_http wiring)."""
    import aiohttp  # noqa: F401 — fail fast if missing

    from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model, lora_pipelines
    from dynamo_tpu.llm.http.service import HttpService

    loop = asyncio.new_event_loop()

    async def boot():
        eng = _lora_engine()
        await eng.start()
        card = card_for_model("tiny")
        service = HttpService(host="127.0.0.1", port=0)
        base = build_pipeline(eng, card)
        service.manager.add(base)
        for lp in lora_pipelines(base, eng.config.lora_adapters):
            service.manager.add(lp)
        port = await service.start()
        return eng, service, f"http://127.0.0.1:{port}"

    eng, service, url = loop.run_until_complete(boot())
    yield loop, url
    loop.run_until_complete(service.stop())
    loop.run_until_complete(eng.shutdown())
    loop.close()


def _post(loop, url, path, body):
    import aiohttp

    async def go():
        async with aiohttp.ClientSession() as s:
            async with s.post(url + path, json=body) as resp:
                ctype = resp.headers.get("Content-Type", "")
                return resp.status, ctype, await resp.text()

    return loop.run_until_complete(go())


def _chat(model, stream=False):
    return {
        "model": model,
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 6,
        "temperature": 0,
        "stream": stream,
    }


@pytest.mark.slow
def test_http_models_lists_adapters(lora_server):
    import aiohttp

    loop, url = lora_server

    async def go():
        async with aiohttp.ClientSession() as s:
            async with s.get(url + "/v1/models") as resp:
                return await resp.json()

    body = loop.run_until_complete(go())
    ids = {m["id"] for m in body["data"]}
    assert {"tiny", "tiny:a1", "tiny:a2"} <= ids


@pytest.mark.slow
def test_http_adapter_serves_and_differs(lora_server):
    loop, url = lora_server
    st0, _, base = _post(loop, url, "/v1/chat/completions", _chat("tiny"))
    st1, _, a1 = _post(loop, url, "/v1/chat/completions", _chat("tiny:a1"))
    assert st0 == 200 and st1 == 200
    base_text = json.loads(base)["choices"][0]["message"]["content"]
    a1_text = json.loads(a1)["choices"][0]["message"]["content"]
    # deterministic per model name
    _, _, a1_again = _post(loop, url, "/v1/chat/completions", _chat("tiny:a1"))
    assert json.loads(a1_again)["choices"][0]["message"]["content"] == a1_text
    assert base_text != a1_text


@pytest.mark.slow
def test_http_unknown_adapter_404_unary(lora_server):
    loop, url = lora_server
    status, ctype, text = _post(
        loop, url, "/v1/chat/completions", _chat("tiny:nope")
    )
    assert status == 404
    body = json.loads(text)
    assert body["error"]["code"] == "model_not_found"
    assert "tiny:nope" in body["error"]["message"]


@pytest.mark.slow
def test_http_unknown_adapter_404_stream_before_sse(lora_server):
    """stream=true with an unknown adapter must be a plain JSON 404 — no SSE
    bytes, no 200-then-error-event (mirrors the context_length_exceeded
    contract)."""
    loop, url = lora_server
    for path, body in (
        ("/v1/chat/completions", _chat("tiny:nope", stream=True)),
        ("/v1/completions", {"model": "tiny:nope", "prompt": "hi",
                             "max_tokens": 4, "stream": True}),
    ):
        status, ctype, text = _post(loop, url, path, body)
        assert status == 404, path
        assert "text/event-stream" not in ctype
        assert not text.startswith("data:")
        assert json.loads(text)["error"]["code"] == "model_not_found"


# ---------------- config / CLI / telemetry surfaces ----------------


def test_config_validation():
    from dynamo_tpu.engine.config import EngineConfig

    cfg = EngineConfig(model_id="tiny", lora_adapters="a1, a2=random:3")
    assert cfg.lora_adapters == ("a1", "a2=random:3")
    assert cfg.lora_enabled
    with pytest.raises(ValueError):
        EngineConfig(model_id="tiny", lora_adapters=("a1",), pp=2)
    with pytest.raises(ValueError):
        EngineConfig(model_id="tiny", lora_adapters=("dup", "dup"))
    with pytest.raises(ValueError):
        EngineConfig(model_id="tiny", lora_adapters=("a1",), max_loras=0)
    assert not EngineConfig(model_id="tiny").lora_enabled


def test_run_cli_and_yaml_passthrough():
    from argparse import Namespace

    from dynamo_tpu.launch._run_impl import engine_config_for
    from dynamo_tpu.launch.run import build_parser

    args = build_parser().parse_args([
        "run", "tiny", "--lora-adapters", "a1,a2=random:9",
        "--max-loras", "3", "--lora-rank", "16",
    ])
    cfg = engine_config_for(args)
    assert cfg.lora_adapters == ("a1", "a2=random:9")
    assert cfg.max_loras == 3 and cfg.lora_rank == 16
    # graph-yaml form: list value instead of a comma string
    ns = Namespace(model="tiny", lora_adapters=["a1", "a2"], max_loras=None,
                   lora_rank=None)
    cfg = engine_config_for(ns)
    assert cfg.lora_adapters == ("a1", "a2")


def test_adapter_dir_roundtrip(tmp_path):
    """The canonical npz adapter format loads, pads to the pool rank, and
    carries alpha/r as the scale."""
    from dynamo_tpu.lora.adapter import load_adapter
    from dynamo_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.tiny()
    dims = module_dims(cfg)
    L, r = cfg.num_layers, 2
    rng = np.random.default_rng(0)
    arrays = {}
    for m in ("wq", "down"):
        din, dout = dims[m]
        arrays[f"{m}.a"] = rng.standard_normal((L, din, r)).astype(np.float32)
        arrays[f"{m}.b"] = rng.standard_normal((L, r, dout)).astype(np.float32)
    np.savez(tmp_path / "adapter_model.npz", **arrays)
    (tmp_path / "adapter_config.json").write_text(json.dumps(
        {"r": r, "lora_alpha": 8, "target_modules": ["wq", "down"]}
    ))
    tree, scale = load_adapter(str(tmp_path), cfg, rank=4)
    assert scale == 4.0  # alpha/r = 8/2
    assert tree["wq"]["a"].shape == (L, dims["wq"][0], 4)  # padded to pool rank
    np.testing.assert_array_equal(tree["wq"]["a"][..., :r], arrays["wq.a"])
    assert not tree["wq"]["a"][..., r:].any()  # zero pad => exact product
    assert not tree["wk"]["a"].any()  # untargeted module stays zero
    with pytest.raises(ValueError):
        load_adapter(str(tmp_path), cfg, rank=1)  # pool rank below adapter r


def test_lora_exposition_families():
    from dynamo_tpu.utils.prometheus import _sample_surfaces

    text = dict(_sample_surfaces())["engine.render_stage_metrics"]
    assert "# TYPE dynamo_lora_slots gauge" in text
    assert 'dynamo_lora_slots{state="resident"}' in text
    assert 'dynamo_lora_slots{state="capacity"}' in text
    assert "# TYPE dynamo_lora_evictions_total counter" in text
    assert "# TYPE dynamo_lora_loads_total counter" in text
    assert "# TYPE dynamo_lora_load_seconds_total counter" in text
    assert '# TYPE dynamo_lora_requests_total counter' in text
    assert 'dynamo_lora_requests_total{adapter="a1"}' in text


def test_dynotop_lora_column():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "dynotop", Path(__file__).resolve().parent.parent / "tools" / "dynotop.py"
    )
    dynotop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(dynotop)
    doc = {
        "namespace": "ns", "component": "backend", "summary": {"workers": 1},
        "workers": [{
            "worker_id": "ab", "last_seen_s": 0.1, "missed_scrapes": 0,
            "health": {"state": "ready", "heartbeat_age_s": 0.05},
            "kv_metrics": {}, "slo": None,
            "resources": {"lora_resident": 2, "lora_capacity": 4,
                          "lora_hot": "a1-long-name"},
        }, {
            "worker_id": "cd", "last_seen_s": 0.1, "missed_scrapes": 0,
            "health": {"state": "ready"}, "kv_metrics": {}, "resources": {},
        }],
    }
    text = dynotop.render_status(doc)
    assert "LORA" in text
    assert "2/4 a1-lon" in text  # resident/capacity + truncated hot adapter
    cd_line = next(line for line in text.splitlines() if line.startswith("cd"))
    assert " - " in cd_line  # base-only worker renders the dash
